#!/usr/bin/env python
"""Benchmark: prompts/sec/chip on the yes/no scoring sweep (BASELINE.json).

Workload: the north-star op — batched, jit'd relative-probability extraction
(forward to the last real position, softmax over the two target-token logits)
over Falcon-7B geometry with ~430-token right-padded prompts (few-shot prefix
+ question, bucketed at 512).  This is the TPU replacement for the reference's
serial per-prompt ``model.generate`` loop (run_base_vs_instruct_100q.py:464-472).

Weights are randomly initialized on-device in bf16 (zero-egress image: no 7B
download) — throughput is architecture-bound, not value-bound.

Baseline: the reference path on an A100 is a serial 50-token fp16/int8
generate per prompt; public A100 7B decode rates (~30-40 tok/s at batch 1 with
HF transformers + int8) put it at ≈0.7 prompts/sec.  We use 1.0 prompts/sec as
a conservative A100 baseline, so vs_baseline = prompts_per_sec / 1.0.

Default configuration (measured on TPU v5e, 2026-07): w8a8 int8 projections
(the reference's own path is bitsandbytes int8; ours keeps 0.9997 logit
correlation vs bf16, and <=0.0043 relative-prob drift across all 8 decoder
families — ops/quant.py, tests/test_quant_audit.py, PARITY.md) at batch 192
with the engine's 432-token length bucket (430-token prompts pad to 432 —
runtime/batching.DEFAULT_BUCKETS), where the v5e int8 MXU path runs ~2.3x
the bf16 ceiling.

The DEFAULT metric is ``--mode parity`` — the TWO-PHASE sweep (one prefill
settles every row whose position-0 top-k contains a target, exactly the rows
for which the reference reads position 0 and stops,
run_base_vs_instruct_100q.py:349-364; only the undecided slice continues
into the scored MAX_LOOK_AHEAD=10 decode, reusing the prefill KV cache).
Measured on v5e (2026-07, round 3):

    mode / --decided-frac          prompts/sec   decode slice
    single forward (ceiling)          38.1           —
    parity 1.0                        36.5           8 rows
    parity 0.9 (default)              36.2          32 rows
    parity 0.6                        35.2         128 rows
    decode, all rows (floor)          35.9         192 rows

Why parity cannot reach the single-forward ceiling: the scored decode is 10
SEQUENTIAL single-token steps, and each step must stream the full ~7 GB of
int8 weights from HBM regardless of how few rows decode — ≈8.5 ms/step at
819 GB/s, so ≥85 ms/batch (-0.6 p/s) even at perfect efficiency; measured
step cost is ~13-20 ms (attention + per-step fixed overheads), i.e. the
two-phase ceiling is ≈37.4 and the slice size barely matters.  The round-3
decode-path work that got it this close is in models/decoder.py: a
read-only prompt cache + small per-chunk tail with a two-block joint
softmax (grouped_attention_two_block) replaced the scatter-updated cache,
whose XLA layout mismatch cost a 150-310 ms full-cache relayout loop every
batch (found via jax.profiler trace, 2026-07).

``--decided-frac`` defaults to 0.9: in the reference's own committed sweep
outputs, ~60% of completions BEGIN with Yes/No (top-1 at position 0, the
floor for top-5 membership — data/instruct_model_comparison_results_combined
.csv), and the prompts instruct a Yes/No answer, so top-5 decisiveness is
higher still.  In real sweeps the engine additionally stops the scored
decode early once every undecided row has hit (rows resolve at positions
1-3 in practice; runtime/engine._scan_decode_chunked) — the synthetic bench
cannot show that win because random-weight rows never hit.

Single-forward history: 38.2 r01/r02, 37.7 at the 448 bucket; 31.5 int8 /
16.5 bf16 at the old batch-128/512 config (``--batch 128 --seq 512
[--quant none]``).  Batch 224+ OOMs 16 GB HBM.

Where the single-forward time goes (jax.profiler device trace): the two
projection-matmul fusions take 92.6 ms/layer vs 87 ms theoretical at the
v5e's 394 TOPS int8 — ~94% of MXU peak — so the matmul side is essentially
optimal.  The remaining ~40% of the step is VPU-bound elementwise that XLA
already fuses (attention softmax ~14%, activation quantization ~3%, rotary
~2%, layernorm/residual/dequant the rest).  The round-2 attempts to claw
that back are all measured in ops/attention.py's outcome table: the causal
block-skipping Pallas kernel beats XLA dense standalone by 25% (16.2 vs
21.6 ms) but loses ~12% in situ because a custom call is an opaque fusion
boundary (``--attn flash`` = 33.6 p/s), and XLA-level microbatch
interleaving loses MXU efficiency (``--microbatch 2`` = 31.6 p/s) — so
XLA dense stays the sweep default and the fused-block-kernel item is closed
as measured-infeasible on this evidence.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline",
"secondary": [single-forward, all-rows-decode]} — both companion modes ride
along so round-over-round trends separate metric changes from contention on
the shared chip.
"""

import argparse
import json
import sys
import time

import numpy as np

A100_BASELINE_PROMPTS_PER_SEC = 1.0

FALCON_7B = dict(
    vocab_size=65024, hidden_size=4544, num_layers=32, num_heads=71,
    num_kv_heads=1, intermediate_size=18176, parallel_residual=True,
    shared_layernorm=True, qkv_bias=False, out_bias=False, mlp_bias=False,
    position_embedding="rotary", tie_word_embeddings=True,
    max_position_embeddings=2048,
)

SMALL_1B = dict(
    vocab_size=50304, hidden_size=2048, num_layers=16, num_heads=16,
    intermediate_size=8192, parallel_residual=True, qkv_bias=True,
    out_bias=True, mlp_bias=True, position_embedding="rotary", rotary_pct=0.25,
    max_position_embeddings=2048,
)


def init_params(cfg, key, dtype, quant=False):
    """Random bf16 (or w8a8-int8-quantized) params directly on device.

    The per-layer tensors are generated inside a jitted ``lax.scan`` so the
    only transient workspace is ONE layer's uniform-bits buffer (~330 MB for
    Falcon-7B's MLP), not a stacked fp32 copy (10.6 GB) — a 7B model then
    initializes inside 16 GB HBM.  With ``quant=True`` each projection is
    quantized inside the same scan body (per-output-channel int8 + fp32
    scale), so the full bf16 weight set never exists on device — matching a
    production loader that quantizes per tensor while streaming from disk.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from llm_interpretation_replication_tpu.ops.quant import quantize_weight

    h, nd = cfg.hidden_size, cfg.num_heads * cfg.head_dim
    kvd = cfg.num_kv_heads * cfg.head_dim
    L, F, V = cfg.num_layers, cfg.intermediate_size, cfg.vocab_size

    def rnd(kk, shape, scale=0.02):
        return jax.random.normal(kk, shape, dtype) * jnp.asarray(scale, dtype)

    def proj(kk, shape):
        w = rnd(kk, shape)
        if not quant:
            return {"w": w}
        q, s = quantize_weight(w, contract_axis=-2)
        return {"w": q, "s": s}

    @jax.jit
    def build(key):
        key, ek = jax.random.split(key)

        def layer(carry, lk):
            ks = jax.random.split(lk, 6)
            out = {
                "wq": proj(ks[0], (h, nd)),
                "wk": proj(ks[1], (h, kvd)),
                "wv": proj(ks[2], (h, kvd)),
                "wo": proj(ks[3], (nd, h)),
                "wi": proj(ks[4], (h, F)),
                "wo2": proj(ks[5], (F, h)),
            }
            return carry, out

        _, stacked = lax.scan(layer, 0, jax.random.split(key, L))
        return rnd(ek, (V, h)), stacked

    embed, stacked = build(key)

    def unpack(names):
        out = {}
        for name, k2 in names.items():
            out[name] = stacked[k2]["w"]
            if quant:
                out[name + "_qscale"] = stacked[k2]["s"]
        return out

    layers = {
        "ln1": {"scale": jnp.ones((L, h), dtype), "bias": jnp.zeros((L, h), dtype)},
        "attn": unpack({"wq": "wq", "wk": "wk", "wv": "wv", "wo": "wo"}),
        "mlp": unpack({"wi": "wi", "wo": "wo2"}),
    }
    if not cfg.shared_layernorm:
        layers["ln2"] = {"scale": jnp.ones((L, h), dtype), "bias": jnp.zeros((L, h), dtype)}
    if cfg.qkv_bias:
        layers["attn"].update(
            bq=jnp.zeros((L, nd), dtype), bk=jnp.zeros((L, kvd), dtype),
            bv=jnp.zeros((L, kvd), dtype), bo=jnp.zeros((L, h), dtype),
        )
        layers["mlp"].update(bi=jnp.zeros((L, F), dtype), bo=jnp.zeros((L, h), dtype))
    params = {
        "embed": {"tokens": embed},
        "layers": layers,
        "final_ln": {"scale": jnp.ones(h, dtype), "bias": jnp.zeros(h, dtype)},
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = rnd(jax.random.fold_in(key, 99), (h, V))
    return params


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", choices=["falcon-7b", "small-1b"], default="falcon-7b")
    parser.add_argument("--batch", type=int, default=192)
    parser.add_argument("--seq", type=int, default=432)
    parser.add_argument("--iters", type=int, default=16)
    parser.add_argument("--prompt-tokens", type=int, default=430)
    parser.add_argument("--quant", choices=["none", "int8"], default="int8",
                        help="w8a8 int8 projections (the reference path is "
                             "bitsandbytes int8, so int8-vs-int8 is the fair "
                             "comparison; ~0.9997 logit correlation vs bf16)")
    parser.add_argument("--attn", choices=["xla", "flash"], default="xla",
                        help="attention impl: XLA dense (the DecoderConfig "
                             "'xla' value) or the Pallas kernels "
                             "(ops/attention.py)")
    parser.add_argument("--mode", choices=["parity", "single", "decode"],
                        default=None,  # resolved to parity after --decode 0 compat
                        help="parity (default): the two-phase sweep — one "
                             "prefill settles every row whose position-0 "
                             "top-k contains a target (the reference reads "
                             "position 0 for those rows, "
                             "run_base_vs_instruct_100q.py:349-364) and only "
                             "the undecided slice continues into the scored "
                             "MAX_LOOK_AHEAD decode, reusing the prefill KV "
                             "cache; single: one forward, no decode (the "
                             "perturbation-sweep fast path); decode: every "
                             "row takes the full scored decode (worst case / "
                             "the r02 headline metric)")
    parser.add_argument("--decided-frac", type=float, default=0.9,
                        metavar="F",
                        help="parity mode: fraction of rows decided at "
                             "position 0.  Random weights never place the "
                             "target tokens in the top-5 of a 65k vocab, so "
                             "the bench fixes the undecided slice explicitly "
                             "— throughput is architecture-bound, not "
                             "value-bound.  0.9 is conservative for the real "
                             "sweep, where prompts end \"Answer either 'Yes' "
                             "or 'No'\" and instruct models put a target in "
                             "the top-5 almost always; --decided-frac 0 "
                             "reproduces the worst case (== --mode decode)")
    parser.add_argument("--decode", type=int, default=10, metavar="N",
                        help="scored look-ahead steps (MAX_LOOK_AHEAD) for "
                             "the parity/decode modes")
    parser.add_argument("--no-secondary", action="store_true",
                        help="skip the secondary single/decode measurements "
                             "(parity mode attaches both to the JSON line so "
                             "round-over-round trends separate metric "
                             "changes from chip contention)")
    parser.add_argument("--repeats", type=int, default=3, metavar="N",
                        help="timing repetitions; the best (minimum-time) "
                             "run is reported to reject chip-contention "
                             "noise on shared/tunneled devices")
    parser.add_argument("--microbatch", type=int, default=1, metavar="N",
                        help="split the batch into N independent chunks "
                             "inside the jit so XLA can overlap one chunk's "
                             "VPU-bound attention softmax with another's "
                             "MXU-bound projections")
    args = parser.parse_args()

    if args.decode == 0:
        # old CLI: --decode 0 was the single-forward fast path
        if args.mode not in (None, "single"):
            parser.error(f"--decode 0 selects the single-forward path and "
                         f"contradicts --mode {args.mode}; drop one")
        args.mode = "single"
        args.decode = 10
    if args.mode is None:
        args.mode = "parity"
    if not 0.0 <= args.decided_frac <= 1.0:
        parser.error("--decided-frac must be within [0, 1]")
    if args.mode == "parity" and args.microbatch > 1:
        parser.error("--microbatch applies to the single/decode modes; the "
                     "parity mode's decode slice is sized from the full batch")

    if args.quant == "none" and args.model == "falcon-7b":
        # bf16 7B weights (~13 GB) leave no HBM for the dense S×T attention
        # scores at ANY batch size on a 16 GB chip — the Pallas flash kernel
        # streams them in blocks and is the only path that fits, and batch
        # must drop to 64 for the activations (measured 2026-07: dense OOMs
        # at batch 64-192; flash 21.2 p/s at batch 64, OOM above).
        if args.attn == "xla":
            print("# --quant none on falcon-7b: dense attention cannot fit "
                  "beside bf16 weights; switching to --attn flash",
                  file=sys.stderr)
            args.attn = "flash"
        if args.batch > 64:
            print(f"# --quant none on falcon-7b: clamping --batch "
                  f"{args.batch} -> 64 (bf16 activation headroom)",
                  file=sys.stderr)
            args.batch = 64

    import jax
    import jax.numpy as jnp

    from llm_interpretation_replication_tpu.models.config import DecoderConfig
    from llm_interpretation_replication_tpu.models.decoder import (
        forward_last_logits,
        greedy_decode,
    )
    from llm_interpretation_replication_tpu.scoring.yes_no import relative_prob_first_token

    geometry = FALCON_7B if args.model == "falcon-7b" else SMALL_1B
    cfg = DecoderConfig(**geometry, attention_impl=args.attn)
    dtype = jnp.bfloat16

    use_quant = args.quant == "int8"
    try:
        params = init_params(cfg, jax.random.PRNGKey(0), dtype, quant=use_quant)
        np.asarray(params["final_ln"]["scale"][0])  # sync (see NOTE below)
    except Exception as err:  # HBM too small for 7B on this chip: drop down
        if args.model == "falcon-7b":
            print(f"# falcon-7b init failed ({err}); falling back to small-1b", file=sys.stderr)
            args.model = "small-1b"
            cfg = DecoderConfig(**SMALL_1B, attention_impl=args.attn)
            params = init_params(cfg, jax.random.PRNGKey(0), dtype, quant=use_quant)
            np.asarray(params["final_ln"]["scale"][0])
        else:
            raise

    rng = np.random.default_rng(0)
    ids = rng.integers(10, cfg.vocab_size - 10, size=(args.batch, args.seq)).astype(np.int32)
    mask = np.zeros((args.batch, args.seq), np.int32)
    mask[:, : args.prompt_tokens] = 1
    ids = jnp.asarray(ids)
    mask = jnp.asarray(mask)
    yes_id, no_id = 5, 9
    look = max(1, args.decode)

    from llm_interpretation_replication_tpu.models.decoder import (
        KVCache,
        decode_steps,
        prefill,
    )
    from llm_interpretation_replication_tpu.runtime.engine import _pad_pow2
    from llm_interpretation_replication_tpu.scoring.yes_no import (
        first_token_scan,
        yes_no_from_scores,
    )

    # Undecided slice for the two-phase parity mode, padded to the engine's
    # power-of-two menu so the decode shape is one the engine also compiles.
    n_undec = max(1, round(args.batch * (1.0 - args.decided_frac)))
    sub = _pad_pow2(n_undec, args.batch)

    def score_parity(params, ids, mask):
        # Phase 1: one prompt forward; position-0 top-k settles decided rows.
        last, cache = prefill(params, cfg, ids, mask,
                              cache_len=ids.shape[1])
        _, _, rel0, _, _ = first_token_scan(last, yes_id, no_id)
        # Phase 2: only the undecided slice decodes, from the kept KV cache.
        lengths = jnp.sum(mask, axis=-1)
        sub_cache = KVCache(k=cache.k[:, :sub], v=cache.v[:, :sub],
                            positions=cache.positions[:sub],
                            valid=cache.valid[:sub], length=cache.length)
        _, sc, _, _, _ = decode_steps(params, cfg, sub_cache, last[:sub],
                                      lengths[:sub], jnp.int32(0), look,
                                      None, None, with_scores=True)
        res = yes_no_from_scores(sc, yes_id, no_id)
        return rel0, res.relative_prob

    def score_decode(params, ids, mask):
        # worst case: every row takes the scored MAX_LOOK_AHEAD decode
        _, logits = greedy_decode(params, cfg, ids, mask, look)
        return relative_prob_first_token(logits[:, 0, :], yes_id, no_id)

    def score_single(params, ids, mask):
        logits = forward_last_logits(params, cfg, ids, mask)
        return relative_prob_first_token(logits, yes_id, no_id)

    base_fns = {"parity": score_parity, "decode": score_decode,
                "single": score_single}

    def with_microbatch(score_one):
        if args.microbatch <= 1:
            return score_one
        if args.batch % args.microbatch:
            parser.error(f"--batch {args.batch} not divisible by "
                         f"--microbatch {args.microbatch}")
        chunk = args.batch // args.microbatch

        def score(params, ids, mask):
            outs = [
                score_one(params, ids[i * chunk:(i + 1) * chunk],
                          mask[i * chunk:(i + 1) * chunk])
                for i in range(args.microbatch)
            ]
            return tuple(jnp.concatenate(parts) for parts in zip(*outs))
        return score

    def measure(mode, iters, repeats):
        """Best-of-N repeats: the tunneled chip is occasionally contended
        (same code measured 13-36 p/s across runs); the minimum per-step time
        is the uncontended hardware number the sweep actually achieves."""
        score_jit = jax.jit(with_microbatch(base_fns[mode]))
        # NOTE: on the axon-tunneled chip, block_until_ready does NOT
        # actually block; a host fetch does.  Sync via np.asarray of a
        # scalar slice.
        out = score_jit(params, ids, mask)
        np.asarray(jax.tree_util.tree_leaves(out)[0][0])  # compile + sync
        dt = float("inf")
        for _ in range(max(1, repeats)):
            t0 = time.perf_counter()
            for _ in range(iters):
                out = score_jit(params, ids, mask)
            np.asarray(jax.tree_util.tree_leaves(out)[0][0])  # drain queue
            dt = min(dt, (time.perf_counter() - t0) / iters)
        return args.batch / dt

    def describe(mode):
        tags = {
            "parity": (f"two-phase {args.decode}-step look-ahead, "
                       f"{int(round(args.decided_frac * 100))}% rows decided "
                       f"at position 0, {sub}-row decode slice"),
            "decode": f"{args.decode}-token look-ahead decode, all rows",
            "single": "single forward",
        }
        return (f"prompts/sec/chip (yes-no scoring sweep, {args.model} geometry, "
                f"{'w8a8 int8' if args.quant == 'int8' else 'bf16'}, "
                f"batch {args.batch}, {args.prompt_tokens}-token prompts, "
                + tags[mode]
                + (f", attn={args.attn}" if args.attn != "xla" else "")
                + (f", microbatch={args.microbatch}" if args.microbatch > 1 else "")
                + ")")

    primary = measure(args.mode, args.iters, args.repeats)
    record = {
        "metric": describe(args.mode),
        "value": round(primary, 2),
        "unit": "prompts/sec",
        "vs_baseline": round(primary / A100_BASELINE_PROMPTS_PER_SEC, 2),
    }
    if args.mode == "parity" and not args.no_secondary:
        # Same run, same chip: the single-forward ceiling and the all-rows
        # decode floor, so BENCH_r{N}.json trends separate metric changes
        # from chip contention.
        record["secondary"] = [
            {"metric": describe(m), "value": round(v, 2), "unit": "prompts/sec"}
            for m, v in (
                ("single", measure("single", max(4, args.iters // 2), 2)),
                ("decode", measure("decode", max(4, args.iters // 2), 2)),
            )
        ]
    print(json.dumps(record))


if __name__ == "__main__":
    main()
