#!/usr/bin/env python
"""Benchmark: prompts/sec/chip on the yes/no scoring sweep (BASELINE.json).

Workload: the north-star op — batched, jit'd relative-probability extraction
(forward to the last real position, softmax over the two target-token logits)
over Falcon-7B geometry with ~430-token right-padded prompts (few-shot prefix
+ question, bucketed at 512).  This is the TPU replacement for the reference's
serial per-prompt ``model.generate`` loop (run_base_vs_instruct_100q.py:464-472).

Weights are randomly initialized on-device in bf16 (zero-egress image: no 7B
download) — throughput is architecture-bound, not value-bound.

Baseline: the reference path on an A100 is a serial 50-token fp16/int8
generate per prompt; public A100 7B decode rates (~30-40 tok/s at batch 1 with
HF transformers + int8) put it at ≈0.7 prompts/sec.  We use 1.0 prompts/sec as
a conservative A100 baseline, so vs_baseline = prompts_per_sec / 1.0.

Default configuration (measured on TPU v5e, 2026-07): w8a8 int8 projections
(the reference's own path is bitsandbytes int8; ours keeps 0.9997 logit
correlation vs bf16, and <=0.0017 relative-prob drift across all 7 decoder
families — ops/quant.py, tests/test_quant_audit.py, PARITY.md) at batch 192
with the engine's 432-token length bucket (430-token prompts pad to 432 —
runtime/batching.DEFAULT_BUCKETS), where the v5e int8 MXU path runs ~2.3x
the bf16 ceiling.

The DEFAULT metric is ``--decode 10`` — the reference's full
MAX_LOOK_AHEAD=10 generate semantics (prompt forward + 10 cached greedy
steps in one device program, run_base_vs_instruct_100q.py:337-358) —
measuring 34.4 prompts/sec, 34x the serial-A100 baseline.  The
single-forward fast path (``--decode 0``, the perturbation-sweep hot op)
measures 38.2 (37.7 at the 448 bucket; 31.5 int8 / 16.5 bf16 at the old
batch-128/512 config — ``--batch 128 --seq 512 [--quant none]``).  Batch
224+ OOMs 16 GB HBM.

Where the time goes (jax.profiler device trace, single-forward config): the
two projection-matmul fusions take 92.6 ms/layer vs 87 ms theoretical at the
v5e's 394 TOPS int8 — ~94% of MXU peak — so the matmul side is essentially
optimal.  The remaining ~40% of the step is VPU-bound elementwise that XLA
already fuses (attention softmax ~14%, activation quantization ~3%, rotary
~2%, layernorm/residual/dequant the rest).  The round-2 attempts to claw
that back are all measured in ops/attention.py's outcome table: the causal
block-skipping Pallas kernel beats XLA dense standalone by 25% (16.2 vs
21.6 ms) but loses ~12% in situ because a custom call is an opaque fusion
boundary (``--attn flash`` = 33.6 p/s), and XLA-level microbatch
interleaving loses MXU efficiency (``--microbatch 2`` = 31.6 p/s) — so
XLA dense stays the sweep default and the fused-block-kernel item is closed
as measured-infeasible on this evidence.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import argparse
import json
import sys
import time

import numpy as np

A100_BASELINE_PROMPTS_PER_SEC = 1.0

FALCON_7B = dict(
    vocab_size=65024, hidden_size=4544, num_layers=32, num_heads=71,
    num_kv_heads=1, intermediate_size=18176, parallel_residual=True,
    shared_layernorm=True, qkv_bias=False, out_bias=False, mlp_bias=False,
    position_embedding="rotary", tie_word_embeddings=True,
    max_position_embeddings=2048,
)

SMALL_1B = dict(
    vocab_size=50304, hidden_size=2048, num_layers=16, num_heads=16,
    intermediate_size=8192, parallel_residual=True, qkv_bias=True,
    out_bias=True, mlp_bias=True, position_embedding="rotary", rotary_pct=0.25,
    max_position_embeddings=2048,
)


def init_params(cfg, key, dtype, quant=False):
    """Random bf16 (or w8a8-int8-quantized) params directly on device.

    The per-layer tensors are generated inside a jitted ``lax.scan`` so the
    only transient workspace is ONE layer's uniform-bits buffer (~330 MB for
    Falcon-7B's MLP), not a stacked fp32 copy (10.6 GB) — a 7B model then
    initializes inside 16 GB HBM.  With ``quant=True`` each projection is
    quantized inside the same scan body (per-output-channel int8 + fp32
    scale), so the full bf16 weight set never exists on device — matching a
    production loader that quantizes per tensor while streaming from disk.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from llm_interpretation_replication_tpu.ops.quant import quantize_weight

    h, nd = cfg.hidden_size, cfg.num_heads * cfg.head_dim
    kvd = cfg.num_kv_heads * cfg.head_dim
    L, F, V = cfg.num_layers, cfg.intermediate_size, cfg.vocab_size

    def rnd(kk, shape, scale=0.02):
        return jax.random.normal(kk, shape, dtype) * jnp.asarray(scale, dtype)

    def proj(kk, shape):
        w = rnd(kk, shape)
        if not quant:
            return {"w": w}
        q, s = quantize_weight(w, contract_axis=-2)
        return {"w": q, "s": s}

    @jax.jit
    def build(key):
        key, ek = jax.random.split(key)

        def layer(carry, lk):
            ks = jax.random.split(lk, 6)
            out = {
                "wq": proj(ks[0], (h, nd)),
                "wk": proj(ks[1], (h, kvd)),
                "wv": proj(ks[2], (h, kvd)),
                "wo": proj(ks[3], (nd, h)),
                "wi": proj(ks[4], (h, F)),
                "wo2": proj(ks[5], (F, h)),
            }
            return carry, out

        _, stacked = lax.scan(layer, 0, jax.random.split(key, L))
        return rnd(ek, (V, h)), stacked

    embed, stacked = build(key)

    def unpack(names):
        out = {}
        for name, k2 in names.items():
            out[name] = stacked[k2]["w"]
            if quant:
                out[name + "_qscale"] = stacked[k2]["s"]
        return out

    layers = {
        "ln1": {"scale": jnp.ones((L, h), dtype), "bias": jnp.zeros((L, h), dtype)},
        "attn": unpack({"wq": "wq", "wk": "wk", "wv": "wv", "wo": "wo"}),
        "mlp": unpack({"wi": "wi", "wo": "wo2"}),
    }
    if not cfg.shared_layernorm:
        layers["ln2"] = {"scale": jnp.ones((L, h), dtype), "bias": jnp.zeros((L, h), dtype)}
    if cfg.qkv_bias:
        layers["attn"].update(
            bq=jnp.zeros((L, nd), dtype), bk=jnp.zeros((L, kvd), dtype),
            bv=jnp.zeros((L, kvd), dtype), bo=jnp.zeros((L, h), dtype),
        )
        layers["mlp"].update(bi=jnp.zeros((L, F), dtype), bo=jnp.zeros((L, h), dtype))
    params = {
        "embed": {"tokens": embed},
        "layers": layers,
        "final_ln": {"scale": jnp.ones(h, dtype), "bias": jnp.zeros(h, dtype)},
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = rnd(jax.random.fold_in(key, 99), (h, V))
    return params


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", choices=["falcon-7b", "small-1b"], default="falcon-7b")
    parser.add_argument("--batch", type=int, default=192)
    parser.add_argument("--seq", type=int, default=432)
    parser.add_argument("--iters", type=int, default=16)
    parser.add_argument("--prompt-tokens", type=int, default=430)
    parser.add_argument("--quant", choices=["none", "int8"], default="int8",
                        help="w8a8 int8 projections (the reference path is "
                             "bitsandbytes int8, so int8-vs-int8 is the fair "
                             "comparison; ~0.9997 logit correlation vs bf16)")
    parser.add_argument("--attn", choices=["xla", "flash"], default="xla",
                        help="attention impl: XLA dense (the DecoderConfig "
                             "'xla' value) or the Pallas kernels "
                             "(ops/attention.py)")
    parser.add_argument("--decode", type=int, default=10, metavar="N",
                        help="greedy-decode N tokens per prompt (default 10 — "
                             "the reference's full MAX_LOOK_AHEAD generate "
                             "semantics, so the headline number is "
                             "parity-true; 0 = single-forward fast path)")
    parser.add_argument("--repeats", type=int, default=3, metavar="N",
                        help="timing repetitions; the best (minimum-time) "
                             "run is reported to reject chip-contention "
                             "noise on shared/tunneled devices")
    parser.add_argument("--microbatch", type=int, default=1, metavar="N",
                        help="split the batch into N independent chunks "
                             "inside the jit so XLA can overlap one chunk's "
                             "VPU-bound attention softmax with another's "
                             "MXU-bound projections")
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp

    from llm_interpretation_replication_tpu.models.config import DecoderConfig
    from llm_interpretation_replication_tpu.models.decoder import (
        forward_last_logits,
        greedy_decode,
    )
    from llm_interpretation_replication_tpu.scoring.yes_no import relative_prob_first_token

    geometry = FALCON_7B if args.model == "falcon-7b" else SMALL_1B
    cfg = DecoderConfig(**geometry, attention_impl=args.attn)
    dtype = jnp.bfloat16

    use_quant = args.quant == "int8"
    try:
        params = init_params(cfg, jax.random.PRNGKey(0), dtype, quant=use_quant)
        np.asarray(params["final_ln"]["scale"][0])  # sync (see NOTE below)
    except Exception as err:  # HBM too small for 7B on this chip: drop down
        if args.model == "falcon-7b":
            print(f"# falcon-7b init failed ({err}); falling back to small-1b", file=sys.stderr)
            args.model = "small-1b"
            cfg = DecoderConfig(**SMALL_1B, attention_impl=args.attn)
            params = init_params(cfg, jax.random.PRNGKey(0), dtype, quant=use_quant)
            np.asarray(params["final_ln"]["scale"][0])
        else:
            raise

    rng = np.random.default_rng(0)
    ids = rng.integers(10, cfg.vocab_size - 10, size=(args.batch, args.seq)).astype(np.int32)
    mask = np.zeros((args.batch, args.seq), np.int32)
    mask[:, : args.prompt_tokens] = 1
    ids = jnp.asarray(ids)
    mask = jnp.asarray(mask)
    yes_id, no_id = 5, 9

    if args.decode:
        def score_one(params, ids, mask):
            # parity mode: the reference's generate + MAX_LOOK_AHEAD scan —
            # prompt forward + N cached single-token steps in one program
            _, logits = greedy_decode(params, cfg, ids, mask, args.decode)
            return relative_prob_first_token(logits[:, 0, :], yes_id, no_id)
    else:
        def score_one(params, ids, mask):
            logits = forward_last_logits(params, cfg, ids, mask)
            return relative_prob_first_token(logits, yes_id, no_id)

    if args.microbatch > 1:
        if args.batch % args.microbatch:
            parser.error(f"--batch {args.batch} not divisible by "
                         f"--microbatch {args.microbatch}")
        chunk = args.batch // args.microbatch

        def score(params, ids, mask):
            outs = [
                score_one(params, ids[i * chunk:(i + 1) * chunk],
                          mask[i * chunk:(i + 1) * chunk])
                for i in range(args.microbatch)
            ]
            return tuple(jnp.concatenate(parts) for parts in zip(*outs))
    else:
        score = score_one

    score_jit = jax.jit(score)
    # NOTE: on the axon-tunneled chip, block_until_ready does NOT actually
    # block; a host fetch does.  Sync via np.asarray of a scalar slice.
    out = score_jit(params, ids, mask)
    np.asarray(out[2][0])  # compile + sync

    # Best-of-N repeats: the tunneled chip is occasionally contended (same
    # code measured 13-36 p/s across runs); the minimum per-step time is the
    # uncontended hardware number the sweep actually achieves.
    dt = float("inf")
    for _ in range(max(1, args.repeats)):
        t0 = time.perf_counter()
        for _ in range(args.iters):
            out = score_jit(params, ids, mask)
        np.asarray(out[2][0])  # drain the queue
        dt = min(dt, (time.perf_counter() - t0) / args.iters)

    prompts_per_sec = args.batch / dt
    print(
        json.dumps(
            {
                "metric": f"prompts/sec/chip (yes-no scoring sweep, {args.model} geometry, "
                          f"{'w8a8 int8' if args.quant == 'int8' else 'bf16'}, "
                          f"batch {args.batch}, {args.prompt_tokens}-token prompts"
                          + (f", {args.decode}-token look-ahead decode" if args.decode else "")
                          + (f", attn={args.attn}" if args.attn != "xla" else "")
                          + (f", microbatch={args.microbatch}" if args.microbatch > 1 else "")
                          + ")",
                "value": round(prompts_per_sec, 2),
                "unit": "prompts/sec",
                "vs_baseline": round(prompts_per_sec / A100_BASELINE_PROMPTS_PER_SEC, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
