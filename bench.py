#!/usr/bin/env python
"""Benchmark: prompts/sec/chip on the yes/no scoring sweep (BASELINE.json).

The DEFAULT metric is ``--mode sweep`` — the END-TO-END 10k-perturbation
scoring sweep exactly as a user runs it: the REAL
``data/perturbations.json`` rephrasing texts (real length histogram /
bucket mix), host tokenization, length bucketing, ONE cross-scenario
two-phase ScoringEngine call with per-prompt target pairs, cross-batch
pooled phase-2 decodes, row building, and checkpointed xlsx writes, all
inside the wall clock (best of ``--sweep-repeats``, so first-compile time
is excluded from the reported number but visible in repeat 1).  This
replaces the reference's serial per-prompt ``model.generate`` loop
(run_base_vs_instruct_100q.py:464-472) and supersedes the r01-r03
synthetic steady-state headline.

The synthetic steady-state modes remain for device-rate comparison and
round-over-round continuity at the 430-token operating point:

- ``--mode parity``: two-phase with the engine's POOLED phase-2 — each
  batch prefills, and one pooled ``sub``-row scored decode runs every
  ~batch/undecided rows prefills (runtime/engine._Phase2Pool).
- ``--mode single``: one forward, no decode (the fast-path ceiling).
- ``--mode decode``: every row takes the scored 10-step decode (floor).

Weights are randomly initialized on-device (zero-egress image: no 7B
download) — throughput is architecture-bound, not value-bound.  For the
sweep mode, the position-0 hit rate that drives phase 2 is CALIBRATED into
the synthetic weights (boost target-token unembedding rows along the mean
normalized-hidden direction until the rate measured through the engine's
own scan is ~--decided-frac), so which rows are decided, pool sizes, and
early-exit behavior all emerge per-row instead of being dialed.

Baseline: the reference path on an A100 is a serial 50-token fp16/int8
generate per prompt; public A100 7B decode rates (~30-40 tok/s at batch 1
with HF transformers + int8) put it at ≈0.7 prompts/sec.  We use 1.0
prompts/sec as a conservative A100 baseline, so vs_baseline =
prompts_per_sec / 1.0.

Default configuration (measured on TPU v5e, 2026-07): w8a8 int8 projections
(the reference's own path is bitsandbytes int8; ours keeps 0.9997 logit
correlation vs bf16, and <=0.0043 relative-prob drift across the 9 audited
decoder families — ops/quant.py, tests/test_quant_audit.py, PARITY.md).
Sweep mode: batch 256 over the real ~107-token prompts (384 OOMs at the
256-token worst bucket).  Parity/single/decode modes: batch 192 at the
432-token bucket, where the v5e int8 MXU path runs ~2.3x the bf16 ceiling.

Two-phase economics: the scored decode is 10 SEQUENTIAL single-token
steps, each streaming the full ~7 GB of int8 weights from HBM regardless
of how few rows decode (≈8.5 ms/step at 819 GB/s; measured 13-20 ms with
attention + fixed overheads).  Paying that once per batch capped r03's
parity mode at 36.1 vs the 38.1 single-forward ceiling; POOLING the
undecided rows across ~10 batches (decode cost is nearly flat in rows)
amortizes it to ~1/10 per batch.  The r03 decode-path work that made steps
cheap at all is in models/decoder.py: a read-only prompt cache + small
per-chunk tail with a two-block joint softmax replaced the scatter-updated
cache, whose XLA layout mismatch cost a 150-310 ms full-cache relayout
loop every batch (found via jax.profiler trace, 2026-07).

``--decided-frac`` defaults to 0.9: in the reference's own committed sweep
outputs, ~60% of completions BEGIN with Yes/No (top-1 at position 0, the
floor for top-5 membership — data/instruct_model_comparison_results_combined
.csv), and the prompts instruct a Yes/No answer, so top-5 decisiveness is
higher still.

History: e2e sweep 111.8-112.1 r05 (async pool flushes; 105.8 with
length-sorted batches + step-16 menu but blocking flushes); 93.2 r04
final at pipeline depth 4 (91.5-92.2 at depth 2, 67.6 at depth 1 — the
async-dispatch overlap measured; 87.7 before the 96/112/144 hot-zone
buckets; 68.2 with per-scenario calls).  Steady state at the 430-token
operating point: single forward 38.1-38.2 r01-r04; parity 36.8-36.9 r04
pooled+selected (36.07 r03 per-batch 32-row slice; the measured ceiling
for any cache-carrying two-phase design is 37.3 — the layer scan's K/V
stacking, see PARITY.md); decode-all 35.8-35.9; 31.5 int8 / 16.5 bf16 at
the old batch-128/512 config.  Batch 224+ OOMs 16 GB HBM at seq 432;
sweep batches 320+ OOM (retried under the r5 menu-capped
pool: batch 320 survives one 10k repeat then ResourceExhausts on the
next — fragmentation-level, so 256 stays the ceiling).  NEVER run the e2e sweep
beside other CPU-heavy processes: a concurrent pytest run measured 24 p/s
on identical code (the steady-state modes are device-bound and immune).

Where the single-forward time goes (jax.profiler device trace): the two
projection-matmul fusions take 92.6 ms/layer vs 87 ms theoretical at the
v5e's 394 TOPS int8 — ~94% of MXU peak — so the matmul side is essentially
optimal.  (At the SWEEP's short 104-token operating point the same
fusions run at 54-91% of peak because the fused quant-scale epilogue
amortizes over fewer rows — whole-step MFU ~58%; trace-backed table in
PARITY.md "Where the 104-token sweep step's time goes".)  The remaining ~40% of the step is VPU-bound elementwise that XLA
already fuses (attention softmax ~14%, activation quantization ~3%, rotary
~2%, layernorm/residual/dequant the rest).  The round-2 attempts to claw
that back are all measured in ops/attention.py's outcome table: the causal
block-skipping Pallas kernel beats XLA dense standalone by 25% (16.2 vs
21.6 ms) but loses ~12% in situ because a custom call is an opaque fusion
boundary (``--attn flash`` = 33.6 p/s), and XLA-level microbatch
interleaving loses MXU efficiency (``--microbatch 2`` = 31.6 p/s) — so
XLA dense stays the sweep default and the fused-block-kernel item is closed
as measured-infeasible on this evidence.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline",
"secondary": [single-forward, all-rows-decode]} — both companion modes ride
along so round-over-round trends separate metric changes from contention on
the shared chip.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

A100_BASELINE_PROMPTS_PER_SEC = 1.0

# One spelling of the bench geometries, shared with the auto-parallel plan
# search (models/config.py BENCH_GEOMETRIES).
from llm_interpretation_replication_tpu.models.config import (  # noqa: E402
    FALCON_7B_GEOMETRY as FALCON_7B,
    SMALL_1B_GEOMETRY as SMALL_1B,
)


def init_params(cfg, key, dtype, quant=False):
    """Random bf16 (or w8a8-int8-quantized) params directly on device.

    The per-layer tensors are generated inside a jitted ``lax.scan`` so the
    only transient workspace is ONE layer's uniform-bits buffer (~330 MB for
    Falcon-7B's MLP), not a stacked fp32 copy (10.6 GB) — a 7B model then
    initializes inside 16 GB HBM.  With ``quant=True`` each projection is
    quantized inside the same scan body (per-output-channel int8 + fp32
    scale), so the full bf16 weight set never exists on device — matching a
    production loader that quantizes per tensor while streaming from disk.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from llm_interpretation_replication_tpu.ops.quant import quantize_weight

    h, nd = cfg.hidden_size, cfg.num_heads * cfg.head_dim
    kvd = cfg.num_kv_heads * cfg.head_dim
    L, F, V = cfg.num_layers, cfg.intermediate_size, cfg.vocab_size

    def rnd(kk, shape, scale=0.02):
        return jax.random.normal(kk, shape, dtype) * jnp.asarray(scale, dtype)

    def proj(kk, shape):
        w = rnd(kk, shape)
        if not quant:
            return {"w": w}
        q, s = quantize_weight(w, contract_axis=-2)
        return {"w": q, "s": s}

    @jax.jit
    def build(key):
        key, ek = jax.random.split(key)

        def layer(carry, lk):
            ks = jax.random.split(lk, 6)
            out = {
                "wq": proj(ks[0], (h, nd)),
                "wk": proj(ks[1], (h, kvd)),
                "wv": proj(ks[2], (h, kvd)),
                "wo": proj(ks[3], (nd, h)),
                "wi": proj(ks[4], (h, F)),
                "wo2": proj(ks[5], (F, h)),
            }
            return carry, out

        _, stacked = lax.scan(layer, 0, jax.random.split(key, L))
        return rnd(ek, (V, h)), stacked

    embed, stacked = build(key)

    def unpack(names):
        out = {}
        for name, k2 in names.items():
            out[name] = stacked[k2]["w"]
            if quant:
                out[name + "_qscale"] = stacked[k2]["s"]
        return out

    layers = {
        "ln1": {"scale": jnp.ones((L, h), dtype), "bias": jnp.zeros((L, h), dtype)},
        "attn": unpack({"wq": "wq", "wk": "wk", "wv": "wv", "wo": "wo"}),
        "mlp": unpack({"wi": "wi", "wo": "wo2"}),
    }
    if not cfg.shared_layernorm:
        layers["ln2"] = {"scale": jnp.ones((L, h), dtype), "bias": jnp.zeros((L, h), dtype)}
    if cfg.qkv_bias:
        layers["attn"].update(
            bq=jnp.zeros((L, nd), dtype), bk=jnp.zeros((L, kvd), dtype),
            bv=jnp.zeros((L, kvd), dtype), bo=jnp.zeros((L, h), dtype),
        )
        layers["mlp"].update(bi=jnp.zeros((L, F), dtype), bo=jnp.zeros((L, h), dtype))
    params = {
        "embed": {"tokens": embed},
        "layers": layers,
        "final_ln": {"scale": jnp.ones(h, dtype), "bias": jnp.zeros(h, dtype)},
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = rnd(jax.random.fold_in(key, 99), (h, V))
    return params


def _train_sweep_tokenizer(texts, vocab_size=900):
    """Byte-level BPE trained on the sweep's own prompt texts (zero-egress
    image: no hub tokenizer).  vocab_size=900 is calibrated so compression
    matches a production English BPE: 4.13 chars/token measured on the
    perturbation corpus (falcon/GPT-2-class tokenizers run ~4.0-4.3 on
    English prose); larger vocabs overfit the 2.5 MB corpus (saturating at
    5.1 chars/token by vocab 4k) and would undercount tokens, inflating
    prompts/sec."""
    from tokenizers import ByteLevelBPETokenizer
    from transformers import PreTrainedTokenizerFast

    tok = ByteLevelBPETokenizer()
    tok.train_from_iterator(texts, vocab_size=vocab_size, min_frequency=2)
    inner = tok._tokenizer if hasattr(tok, "_tokenizer") else tok
    fast = PreTrainedTokenizerFast(tokenizer_object=inner)
    fast.pad_token = fast.decode([0])
    fast.pad_token_id = 0
    return fast


def _calibrate_decided_rate(params, cfg, engine, scenarios, prompts_by_scenario,
                            target_rate, sample_rows=64):
    """Boost the target tokens' unembedding rows until the measured
    position-0 top-5 hit rate over a stratified sample is ~``target_rate``.

    Random weights never place a target token in the top-5 of a 65k vocab,
    so an unmodified synthetic model would send EVERY row into phase 2 — the
    worst case, not the real sweep (real prompts end "Answer only 'X' or
    'Y'" and instruct models put a target in the top-5 nearly always).
    Rather than dialing the undecided slice directly (the r03 bench's
    --decided-frac), this boosts each target row e_t by α·ĥ along the mean
    normalized-hidden direction and bisects α until the rate measured
    THROUGH the engine's own scan matches; which rows are decided, how many
    per batch, and where undecided rows later hit all emerge per-row, so
    pool sizes fluctuate and the chunked early exit operates like a real
    sweep.  ĥ is recovered from mean logits: logits = LN(h)·Eᵀ with
    E ~ iid N(0, 0.02²) ⇒ mean_rows LN(h) ≈ μ_logits·E / (V·0.02²).

    Returns (params, measured_rate)."""
    import jax.numpy as jnp

    from llm_interpretation_replication_tpu.models.decoder import forward_last_logits
    from llm_interpretation_replication_tpu.runtime import batching
    from llm_interpretation_replication_tpu.scoring import yes_no as yn

    tok = engine.tokenizer
    samples = []  # (ids, mask, yes_id, no_id) per scenario
    mean_logits = None
    for scenario, prompts in zip(scenarios, prompts_by_scenario):
        yes_id, no_id = engine.target_ids(list(scenario["target_tokens"]))[:2]
        batch = next(batching.batches_for_prompts(
            batching.encode_prompts(tok, prompts[:sample_rows]),
            sample_rows, engine.ecfg.buckets, pad_id=tok.pad_token_id or 0,
        ))
        ids, mask = jnp.asarray(batch.token_ids), jnp.asarray(batch.attention_mask)
        samples.append((ids, mask, yes_id, no_id,
                        int((batch.indices >= 0).sum())))
        logits = forward_last_logits(params, cfg, ids, mask)
        s = jnp.mean(logits, axis=0)
        mean_logits = s if mean_logits is None else mean_logits + s
    # The unembedding actually producing logits: the tied token embedding
    # ([V, h] rows) or the separate lm_head ([h, V] columns).
    tied = bool(getattr(cfg, "tie_word_embeddings", False))
    unembed = (params["embed"]["tokens"] if tied
               else jnp.transpose(params["lm_head"]))           # [V, h]
    h_dir = jnp.matmul(mean_logits[None, :].astype(jnp.float32),
                       unembed.astype(jnp.float32))[0]
    h_dir = h_dir / jnp.linalg.norm(h_dir)
    tids = sorted({t for _, _, y, n, _ in samples for t in (int(y), int(n))})
    base_rows = unembed[jnp.asarray(tids)].astype(jnp.float32)

    def rate_at(alpha):
        rows = (base_rows + alpha * h_dir[None, :]).astype(unembed.dtype)
        p = dict(params)
        if tied:
            p["embed"] = dict(params["embed"])
            p["embed"]["tokens"] = unembed.at[jnp.asarray(tids)].set(rows)
        else:
            p["lm_head"] = params["lm_head"].at[:, jnp.asarray(tids)].set(
                jnp.transpose(rows))
        hits = total = 0
        for ids, mask, yes_id, no_id, n_real in samples:
            last = forward_last_logits(p, cfg, ids, mask)
            hit = np.asarray(yn.first_token_scan(
                last, yes_id, no_id, top_k=engine.ecfg.top_k)[4])
            hits += int(hit[:n_real].sum())   # pad rows duplicate row 0 and
            total += n_real                   # must not weight the rate
        return p, hits / total

    lo, hi = 0.0, 1.0
    while hi < 4096:
        _, r = rate_at(hi)
        if r >= target_rate:
            break
        lo, hi = hi, hi * 2
    for _ in range(8):
        mid = (lo + hi) / 2
        _, r = rate_at(mid)
        if r < target_rate:
            lo = mid
        else:
            hi = mid
    # The decided/undecided threshold can be SHARP across alphas when rows'
    # projections onto the mean-hidden direction cluster; return whichever
    # bracket end measures closer to the target, and report the measured
    # rate rather than pretending the dial was hit.
    lo_p, lo_r = rate_at(lo)
    hi_p, hi_r = rate_at(hi)
    boosted, measured = ((lo_p, lo_r)
                         if abs(lo_r - target_rate) < abs(hi_r - target_rate)
                         else (hi_p, hi_r))
    if abs(measured - target_rate) > 0.15:
        print(f"# WARNING: calibrated hit rate {measured:.2f} far from "
              f"target {target_rate}; sweep runs at the measured rate",
              file=sys.stderr)
    return boosted, measured


#: Calibration-target bracket for the synthetic decided-rate / EOS-rate
#: shaping, validated against the reference's own recorded workbooks
#: (data_assets/decided_rate_calibration.json — mined position-0
#: answer-start rates; the ROADMAP item-4 validation clause).  The default
#: --decided-frac 0.9 and the EOS-typical bracket's decided-rate target
#: both sit inside this bracket; pinned in tests/test_packed.py.
DECIDED_RATE_TARGETS = (0.87, 0.92)


def _arm_eos_token(tok, cfg) -> int:
    """Give the bench tokenizer an EOS id the engine will honor.

    The sweep tokenizer is trained on the corpus text alone (no special
    tokens), so ``eos_token_id`` is None and every decode runs to its cap
    — the no-EOS bracket.  The EOS-typical bracket registers a dedicated
    ``<|eos|>`` special token (its id lands just past the text vocab —
    ~900 ids against the model's 65k rows, so prompts can never contain
    it and the model's unembedding covers it) and the engine reads the id
    per scoring call, so arming between brackets needs no engine rebuild.
    (Assigning a bare out-of-vocab int to ``eos_token_id`` would NOT
    survive: the HF setter round-trips through convert_ids_to_tokens and
    silently resets to None for unknown ids.)"""
    if getattr(tok, "eos_token_id", None) is None:
        tok.add_special_tokens({"eos_token": "<|eos|>"})
    eos_id = int(tok.eos_token_id)
    if eos_id >= int(cfg.vocab_size):
        raise ValueError(
            f"eos id {eos_id} outside the model vocab {cfg.vocab_size}; "
            f"the synthetic geometry must cover the tokenizer vocab")
    return eos_id


def _calibrate_eos_rate(params, cfg, engine, scenarios, prompts_by_scenario,
                        target_rate, eos_id, sample_rows=64):
    """Boost the EOS token's unembedding row until the measured fraction
    of rows emitting EOS within the first TWO generated positions is
    ~``target_rate`` — the EOS-typical decode bracket (ROADMAP item 4):
    real instruct models answer at position 0 and stop right after, so
    the synthetic weights should too, at the same calibrated decided
    rate the position-0 shaping targets (DECIDED_RATE_TARGETS).

    The boost direction is the mean hidden direction at generated
    position 1 (recovered from mean position-1 logits the way
    _calibrate_decided_rate recovers position 0's), ORTHOGONALIZED
    against the position-0 direction: the component along position 0
    would race the decided-rate calibration's target-token boost for the
    answer slot, and zeroing it keeps the position-0 logits of the yes/no
    tokens untouched — decided rows' relative_prob/odds_ratio stay
    bit-identical across brackets (the tests/test_packed.py parity pin;
    only the EOS row of the unembedding changes, and ratios of unchanged
    logits are normalization-free).

    Runs AFTER _calibrate_decided_rate, on its boosted params.  Returns
    (params, measured_rate)."""
    import jax.numpy as jnp

    from llm_interpretation_replication_tpu.models.decoder import (
        decode_steps,
        prefill,
    )
    from llm_interpretation_replication_tpu.runtime import batching

    tok = engine.tokenizer
    samples = []
    for scenario, prompts in zip(scenarios, prompts_by_scenario):
        batch = next(batching.batches_for_prompts(
            batching.encode_prompts(tok, prompts[:sample_rows]),
            sample_rows, engine.ecfg.buckets, pad_id=tok.pad_token_id or 0,
        ))
        ids = jnp.asarray(batch.token_ids)
        mask = jnp.asarray(batch.attention_mask)
        samples.append((ids, mask, int((batch.indices >= 0).sum())))

    # mean logits at generated positions 0 and 1 (scores[:, 0] is exactly
    # the prefill logits; scores[:, 1] follows the greedy position-0 token)
    mean0 = mean1 = None
    for ids, mask, _ in samples:
        last, cache = prefill(params, cfg, ids, mask,
                              cache_len=int(ids.shape[1]))
        lengths = jnp.sum(mask, axis=-1)
        _, sc, _, _, _ = decode_steps(params, cfg, cache, last, lengths,
                                      np.int32(0), 2, None, None,
                                      with_scores=True)
        s0 = jnp.mean(sc[:, 0].astype(jnp.float32), axis=0)
        s1 = jnp.mean(sc[:, 1].astype(jnp.float32), axis=0)
        mean0 = s0 if mean0 is None else mean0 + s0
        mean1 = s1 if mean1 is None else mean1 + s1
    tied = bool(getattr(cfg, "tie_word_embeddings", False))
    unembed = (params["embed"]["tokens"] if tied
               else jnp.transpose(params["lm_head"]))           # [V, h]
    ue32 = unembed.astype(jnp.float32)

    def h_dir(mean_logits):
        d = jnp.matmul(mean_logits[None, :], ue32)[0]
        return d / jnp.linalg.norm(d)

    h0, h1 = h_dir(mean0), h_dir(mean1)
    he = h1 - jnp.dot(h1, h0) * h0      # orthogonal to the position-0 dir
    norm = jnp.linalg.norm(he)
    he = jnp.where(norm > 1e-6, he / jnp.where(norm > 0, norm, 1.0), h1)
    base_row = unembed[eos_id].astype(jnp.float32)

    rates = {}   # alpha -> measured rate.  RATES ONLY: caching the built
                 # params would pin one full modified unembedding
                 # (~0.55 GiB at falcon-7b) per evaluated alpha — ~20
                 # alphas would OOM the 16 GiB device mid-calibration.
                 # Rebuilding params is one transient device copy; the
                 # expensive part (prefill + decode over every sample) is
                 # what the memo skips when the bisection re-reads its
                 # endpoints at the end.

    def rate_at(alpha):
        row = (base_row + alpha * he).astype(unembed.dtype)
        p = dict(params)
        if tied:
            p["embed"] = dict(params["embed"])
            p["embed"]["tokens"] = unembed.at[eos_id].set(row)
        else:
            p["lm_head"] = params["lm_head"].at[:, eos_id].set(row)
        if alpha not in rates:
            hits = total = 0
            for ids, mask, n_real in samples:
                last, cache = prefill(p, cfg, ids, mask,
                                      cache_len=int(ids.shape[1]))
                lengths = jnp.sum(mask, axis=-1)
                toks, _, _, _, _ = decode_steps(
                    p, cfg, cache, last, lengths, np.int32(0), 2, eos_id,
                    None, with_scores=False)
                t = np.asarray(toks)[:n_real]
                hits += int((t == eos_id).any(axis=1).sum())
                total += n_real
            rates[alpha] = hits / total
        return p, rates[alpha]

    lo, hi = 0.0, 1.0
    while hi < 4096:
        _, r = rate_at(hi)
        if r >= target_rate:
            break
        lo, hi = hi, hi * 2
    for _ in range(8):
        mid = (lo + hi) / 2
        _, r = rate_at(mid)
        if r < target_rate:
            lo = mid
        else:
            hi = mid
    lo_p, lo_r = rate_at(lo)
    hi_p, hi_r = rate_at(hi)
    boosted, measured = ((lo_p, lo_r)
                         if abs(lo_r - target_rate) < abs(hi_r - target_rate)
                         else (hi_p, hi_r))
    if abs(measured - target_rate) > 0.15:
        print(f"# WARNING: calibrated EOS-within-2 rate {measured:.2f} far "
              f"from target {target_rate}; bracket runs at the measured "
              f"rate", file=sys.stderr)
    return boosted, measured


def _is_oom(err) -> bool:
    """Device out-of-memory — delegates to the shared fault-tolerance layer
    (runtime/faults.is_oom), which this bench's r5 private copy grew into."""
    from llm_interpretation_replication_tpu.runtime.faults import is_oom

    return is_oom(err)


def _sweep_oom_action(err, args, engine, rep, had_success, floor,
                      fallback, label):
    """Skip-or-step-down policy for a mid-repeat device OOM — the shared
    policy in runtime/faults.sweep_oom_action (pure over the batch size),
    with this bench's state application: on "retry" the stepped-down batch
    lands in ``args.sweep_batch`` and the engine's batch_size.  Returns
    "skip" (an earlier repeat succeeded: keep best-of) or "retry";
    re-raises non-OOM errors and OOM at ``floor``.  Messages carry the
    truncated error text so a misclassified RESOURCE_EXHAUSTED (RPC/quota
    vs HBM) leaves a diagnostic trail."""
    import dataclasses as dc

    from llm_interpretation_replication_tpu.runtime.faults import (
        sweep_oom_action,
    )

    action, new_batch = sweep_oom_action(err, args.sweep_batch, rep,
                                         had_success, floor, fallback, label)
    if action == "retry":
        predicted = getattr(args, "predicted_batch", None)
        if predicted is not None:
            # the budget model (runtime/plan.py) predicted a fit the chip
            # refused — make the prediction error auditable next to the
            # ladder step so the anchors can be re-calibrated from logs
            print(f"# {label}: planner predicted batch {predicted} fits "
                  f"({getattr(args, 'fit_decision', '')}); hardware OOM'd "
                  f"at {args.sweep_batch}, ladder steps to {new_batch}",
                  file=sys.stderr)
        args.sweep_batch = new_batch
        engine.ecfg = dc.replace(engine.ecfg, batch_size=new_batch)
    return action


def _sweep_corpus(args):
    """Shared sweep-mode preamble: load the perturbation corpus, apply
    the --sweep-rows cap, and build the binary-leg prompt texts — ONE
    spelling across the sweep / sweep-full / sweep-packed modes (the
    third near-verbatim copy of this block is where drift bugs start).
    Returns (scenarios, prompts_by_scenario, n_total)."""
    import json as jsonlib

    with open(args.perturbations) as f:
        scenarios = jsonlib.load(f)
    if getattr(args, "sweep_rows", 0):
        per = max(1, args.sweep_rows // len(scenarios))
        scenarios = [dict(s, rephrasings=s["rephrasings"][:per])
                     for s in scenarios]
    prompts_by_scenario = [
        [f"{r} {s['response_format']}" for r in s["rephrasings"]]
        for s in scenarios
    ]
    return scenarios, prompts_by_scenario, sum(
        len(p) for p in prompts_by_scenario)


def run_sweep_mode(args, cfg, params):
    """End-to-end 10k-row perturbation scoring sweep — the BASELINE.json
    north-star workload as the USER runs it: real perturbations.json prompt
    texts (real length histogram / bucket mix), host tokenization, length
    bucketing, the two-phase ScoringEngine (prefill + pooled phase-2 decode
    + chunked early exit + pipeline_depth overlap), row building, and
    checkpointed xlsx writes, all inside the wall clock.  Replaces the
    reference's serial per-prompt generate loop
    (run_base_vs_instruct_100q.py:464-472) and the r03 bench's synthetic
    steady-state bucket."""
    import os
    import tempfile
    import time as timemod

    import pandas as pd

    from llm_interpretation_replication_tpu.runtime.engine import (
        EngineConfig,
        ScoringEngine,
    )
    from llm_interpretation_replication_tpu.sweeps.writers import (
        PERTURBATION_COLUMNS,
        perturbation_row,
    )
    from llm_interpretation_replication_tpu.utils.xlsx import write_xlsx

    scenarios, prompts_by_scenario, n_total = _sweep_corpus(args)
    tok = _train_sweep_tokenizer([p for ps in prompts_by_scenario for p in ps])

    pool_kw = {}
    if getattr(args, "pool_max_bytes", 0):
        pool_kw["phase2_pool_max_bytes"] = args.pool_max_bytes
    engine = ScoringEngine(
        "falcon", cfg, params, tok,
        engine_config=EngineConfig(
            batch_size=args.sweep_batch, decode_completions=False,
            phase2_pool_target=args.pool_target,
            pooled_confidence=getattr(args, "pooled_confidence", True),
            slot_repack=getattr(args, "slot_repack", True),
            pipeline_depth=args.pipeline_depth,
            kv_dtype=getattr(args, "kv_dtype", "bf16") or "bf16",
            prefill_chunk=getattr(args, "prefill_chunk", 0) or 0,
            # the bench MEASURES an operating point: a mid-repeat OOM must
            # step the whole repeat down the ladder visibly (below), never
            # degrade single batches silently inside the engine
            oom_backoff=False,
            **pool_kw,
        ),
    )
    lens = [len(ids) for ids in tok([p for ps in prompts_by_scenario for p in ps])["input_ids"]]
    params, measured_rate = _calibrate_decided_rate(
        params, cfg, engine, scenarios, prompts_by_scenario, args.decided_frac,
    )
    engine.params = params
    args.measured_rate = measured_rate
    args.eos_rate = None
    if getattr(args, "eos_mode", "none") == "typical":
        # EOS-typical bracket for the binary sweep: only the ~10%
        # undecided rows decode here (decode_completions=False), so the
        # bracket moves the scan-decode early exit, not a completions
        # span — the full-study mode is where the 4x span lives
        eos_id = _arm_eos_token(tok, cfg)
        params, eos_rate = _calibrate_eos_rate(
            params, cfg, engine, scenarios, prompts_by_scenario,
            args.decided_frac, eos_id)
        engine.params = params
        args.eos_rate = eos_rate
        print(f"# sweep: EOS-typical bracket — calibrated EOS-within-2 "
              f"rate {eos_rate:.2f}", file=sys.stderr)
    print(f"# sweep: {n_total} prompts, token lengths mean "
          f"{sum(lens)/len(lens):.0f} min {min(lens)} max {max(lens)}, "
          f"calibrated position-0 hit rate {measured_rate:.2f} "
          f"(target {args.decided_frac})", file=sys.stderr)

    from llm_interpretation_replication_tpu.sweeps.perturbation import (
        _sidelog_path,
    )

    out_path = args.sweep_out or os.path.join(
        tempfile.mkdtemp(prefix="bench_sweep_"), "results.xlsx")
    sidelog = _sidelog_path(out_path)
    # flight recorder (obs/flight.py) armed at the workbook dir: a
    # mid-repeat OOM-ladder step or retry exhaustion leaves a
    # flightrec-*.json next to the bench artifacts
    from llm_interpretation_replication_tpu.obs import flight as obs_flight

    obs_flight.enable(os.path.dirname(os.path.abspath(out_path)))
    all_rows, pending = [], []

    def flush(final=False):
        # The sweep shells' append-only checkpoint (sweeps/perturbation.py):
        # each flush APPENDS its rows to the side-log in O(new rows),
        # fsync'd for crash consistency like the real sweep shell; the
        # xlsx renders once, at end of sweep.  The r04 rewrite-the-workbook
        # flush cost a measured 3.7-4.6 s tail over the 10k sweep.
        nonlocal pending
        if pending:
            from llm_interpretation_replication_tpu.utils.checkpoint import (
                append_jsonl,
            )

            append_jsonl(sidelog, pending)
            all_rows.extend(pending)
            pending = []
        if final:
            write_xlsx(pd.DataFrame(all_rows, columns=PERTURBATION_COLUMNS),
                       out_path)
            if os.path.exists(sidelog):
                os.remove(sidelog)

    # ONE cross-scenario scoring call with per-prompt target pairs — the
    # sweep shell's own batching (sweeps/perturbation.py): per-scenario
    # calls paid a partial tail batch per (scenario, bucket), ~40% of all
    # prefill rows on this corpus.
    items = [(s, r) for s in scenarios for r in s["rephrasings"]]
    all_prompts = [p for ps in prompts_by_scenario for p in ps]
    all_targets = [list(s["target_tokens"]) for s, _ in items]
    from llm_interpretation_replication_tpu.utils.telemetry import counters

    # scope the record's context-block counters to the measured repeats
    # (calibration above must not inflate them) — _operating_context
    args.counters_snap = counters()
    engine.occupancy_report()      # drop calibration/warmup ring stats —
    #                                the occupancy block scopes to the
    #                                measured repeats like the counters
    _obs_phase_snap(args)
    best_dt = float("inf")
    best_score_s = float("inf")
    last_ok_rows = 0
    last_rows = None
    repeat_times = []
    rep = 0
    while rep < max(1, args.sweep_repeats):
        all_rows, pending = [], []
        if os.path.exists(sidelog):
            os.remove(sidelog)  # each repeat checkpoints from scratch
        t0 = timemod.perf_counter()
        try:
            with _profile_window(args, rep):
                rows = engine.score_prompts(all_prompts,
                                            targets=all_targets)
        except Exception as err:
            # step through the MEASURED ladder (384/352 -> 320 -> 256,
            # runtime/faults.MEASURED_SWEEP_LADDER): 320 is a fully-
            # measured operating point (120.5-120.9 p/s warm), so a
            # user-requested 352/384 that OOMs lands there before
            # falling to 256 (111.8-112.1 p/s)
            from llm_interpretation_replication_tpu.runtime.faults import (
                MEASURED_SWEEP_LADDER,
                next_batch_down,
            )

            action = _sweep_oom_action(
                err, args, engine, rep, best_dt < float("inf"),
                floor=256,
                fallback=lambda b: next_batch_down(
                    b, ladder=MEASURED_SWEEP_LADDER, floor=256) or 256,
                label="sweep")
            if action == "skip":
                rep += 1
            continue
        t_score = timemod.perf_counter() - t0
        best_score_s = min(best_score_s, t_score)
        last_rows = rows
        for (scenario, reph), row in zip(items, rows):
            pending.append(perturbation_row(
                args.model, scenario, reph,
                response_text=row["completion"],
                confidence_text="",
                logprobs_repr="bench:two-phase",
                token_1_prob=row["yes_prob"],
                token_2_prob=row["no_prob"],
                odds_ratio=row["odds_ratio"],
                confidence_value=None, weighted_confidence=None,
            ))
            if len(pending) >= args.checkpoint_every:
                flush()
        flush(final=True)
        dt = timemod.perf_counter() - t0
        # e2e-vs-steady-state gap decomposition, measured per repeat: the
        # scoring call (device + overlapped host consume, incl. tokenize)
        # vs the serial row-building + workbook-rewrite tail
        print(f"# sweep repeat {rep}: total {dt:.1f}s = scoring "
              f"{t_score:.1f}s + rows/writes {dt - t_score:.1f}s",
              file=sys.stderr)
        best_dt = min(best_dt, dt)
        repeat_times.append(dt)
        last_ok_rows = len(all_rows)
        rep += 1
        _metrics_repeat_sample(args)
    assert last_ok_rows == n_total, (last_ok_rows, n_total)
    args.repeat_times = repeat_times  # warm-vs-cold report (main())
    # measurement scope ends with the measured repeats: the serve replay
    # / packed secondary below must inflate neither the record's context
    # counters (_operating_context prefers this snapshot) nor its phases
    # block (the span totals are read HERE, before the companion legs'
    # spans accumulate — their work is not the headline's)
    from llm_interpretation_replication_tpu.utils.telemetry import (
        counters_since,
    )

    args.context_counters = counters_since(args.counters_snap)
    args.phases_report = _phases_report(
        args, sum(repeat_times), n_total * max(1, len(repeat_times)))
    # slot-occupancy block (ROADMAP item 3): idle fraction before/after
    # repack, refills, repack stalls — drained from the engine's rings
    args.occupancy_report = engine.occupancy_report()

    if getattr(args, "serve_replay", False):
        # Route the SAME workload through the serve/ continuous-batching
        # scheduler and verify row-level parity against the offline rows
        # the repeats above already produced — the coalescing win (or
        # cost) becomes a measured number next to the offline headline.
        from llm_interpretation_replication_tpu.serve import SchedulerConfig
        from llm_interpretation_replication_tpu.serve.replay import replay

        rep_report = replay(
            engine, all_prompts, targets=all_targets,
            config=SchedulerConfig(max_batch=args.sweep_batch,
                                   queue_capacity=max(4096, n_total),
                                   slot_admission=not getattr(
                                       args, "no_slot_admission", False)),
            # compare scoring against scoring: the serve pass has no
            # row-building/xlsx tail, so the offline side is the best
            # repeat's SCORING time, not its e2e wall clock
            offline_rows=last_rows, offline_s=best_score_s,
            require_parity=False,
        )
        rep_report.pop("serve_rows", None)
        args.serve_report = rep_report
        print(f"# serve replay: {rep_report['serve_rows_per_s']} rows/s "
              f"through the scheduler vs {rep_report['offline_rows_per_s']} "
              f"offline best, {rep_report['serve_batches']} micro-batches, "
              f"{rep_report['mismatched_rows']} mismatched row(s)",
              file=sys.stderr)

    if getattr(args, "serve_load", False):
        # Open-loop load companion (ISSUE 11): drive the scheduler with
        # seeded Poisson traffic drawn from the SAME corpus at >= 3
        # offered rates bracketing the measured offline ceiling, and
        # attach the latency-anatomy block.  The headline rows double as
        # the parity reference — load must change WHEN a row is
        # computed, never WHAT.
        from llm_interpretation_replication_tpu.serve import SchedulerConfig
        from llm_interpretation_replication_tpu.serve import (
            load as serve_load_mod,
        )

        offline_rate = n_total / best_score_s
        rates_arg = getattr(args, "serve_load_rates", "auto")
        if rates_arg and rates_arg != "auto":
            rates = [float(r) for r in rates_arg.split(",") if r.strip()]
        else:
            # bracket the knee: below, at, and above the offline
            # scoring-only ceiling the repeats above just measured
            rates = [round(offline_rate * f, 2) for f in (0.5, 1.0, 1.5)]
        load_block = serve_load_mod.rate_sweep(
            engine, all_prompts, targets=all_targets, rates=rates,
            duration_s=args.serve_load_duration,
            seed=args.serve_load_seed,
            config=SchedulerConfig(
                max_batch=args.sweep_batch,
                queue_capacity=max(
                    4096, int(max(rates) * args.serve_load_duration * 2)),
                slot_admission=not getattr(
                    args, "no_slot_admission", False)),
            offline_rows=last_rows, closed_comparator=True)
        args.serve_load_report = load_block
        print(serve_load_mod.format_rate_table(load_block),
              file=sys.stderr)
        if not load_block.get("parity_ok"):
            # loud, like the replay contract: a load run that changed a
            # row is a correctness failure, not a perf data point
            print("# serve load: PARITY FAILED — served rows differ "
                  "from the offline sweep rows", file=sys.stderr)
        if getattr(args, "serve_load_replicas", 0) > 1:
            # EnginePool companion (ISSUE 12): the SAME open-loop
            # harness over the replica fleet — one multi-replica
            # single-model pool and one multi-model roster pool, a
            # serve_load block per configuration, so replica count
            # becomes an axis of the latency-anatomy curve.
            # Best-effort like the packed secondary: a pool failure
            # must never sink the headline record.
            try:
                args.serve_load_pool_report = _serve_load_pool_secondary(
                    args, engine, all_prompts, all_targets, last_rows,
                    rates)
            except Exception as err:
                print(f"# serve-load pool secondary failed ({err}); "
                      f"headline record unaffected", file=sys.stderr)

    if getattr(args, "packed", 0) and last_rows is not None:
        # Packed-mode companion (ISSUE 10): rescore the SAME corpus with
        # --packed questions per prefill row and report questions/s + the
        # measured drift block vs the headline rows the repeats above
        # already produced — the isolated leg comes free, and its answers
        # feed back as the Auto-Demo demonstrations.  Best-effort: a
        # packed failure must never sink the headline record.
        try:
            args.packed_report = _packed_secondary(args, engine, all_prompts,
                                                   all_targets, last_rows)
        except Exception as err:
            print(f"# packed secondary failed ({err}); headline record "
                  f"unaffected", file=sys.stderr)

    # Verified teardown (ISSUE 12): release everything this mode's engine
    # pinned — audit pools, plan/token caches, its calibrated param copy's
    # unique leaves (release_params=False keeps the leaves shared with the
    # caller's tree alive for the full-study leg) — so the in-process
    # full-study secondary starts from the torn-down allocator the old
    # subprocess workaround provided.
    engine.close(release_params=False)
    return n_total / best_dt, measured_rate, out_path


def _serve_load_pool_secondary(args, engine, prompts, targets,
                               offline_rows, rates) -> dict:
    """Two EnginePool configurations through the SAME ``--serve-load``
    harness (serve/load.rate_sweep via ``pool.client()``):

    - ``single-model-xN``: N replicas of the sweep snapshot behind one
      front door — replica count as a latency-anatomy axis;
    - ``multi-model``: the primary plus a second resident model (the
      instruct-roster shape; same snapshot under a second name, so the
      routing/queueing layer is measured, not a second weight load)
      with the measured traffic pinned to the primary.

    Replicas are SIBLING engines over the primary's param tree (same
    device buffers — no extra weight HBM), each with a plan-search-
    audited operating point note (runtime/plan_search.replica_plan at
    the replica's mesh slice).  ``offline_rows`` stays the parity
    reference: pool routing must change WHEN a row is computed, never
    WHAT."""
    from llm_interpretation_replication_tpu.runtime.engine import (
        ScoringEngine,
    )
    from llm_interpretation_replication_tpu.runtime.plan_search import (
        replica_plan,
    )
    from llm_interpretation_replication_tpu.serve import SchedulerConfig
    from llm_interpretation_replication_tpu.serve import (
        load as serve_load_mod,
    )
    from llm_interpretation_replication_tpu.serve.pool import (
        EnginePool,
        PoolConfig,
    )

    n = int(args.serve_load_replicas)
    sched_cfg = SchedulerConfig(
        max_batch=args.sweep_batch,
        queue_capacity=max(4096,
                           int(max(rates) * args.serve_load_duration * 2)),
        slot_admission=not getattr(args, "no_slot_admission", False))
    try:
        plan = replica_plan(engine.cfg, args.quant, 1, workload="binary",
                            batches=(args.sweep_batch,),
                            attention_impl=getattr(args, "attn", "xla"))
        plan_note = plan.reason if plan is not None else None
    except (ValueError, AttributeError, TypeError):
        plan_note = None  # synthetic geometry the budget model can't price

    def sibling():
        # sibling replicas share the primary's param tree: same device
        # buffers, separate schedulers/plan caches; owns_engine=False so
        # pool teardown never deletes the shared leaves
        return ScoringEngine(engine.family, engine.cfg, engine.params,
                             engine.tokenizer, mesh=engine.mesh,
                             engine_config=engine.ecfg)

    def measure(pool, name):
        block = serve_load_mod.rate_sweep(
            engine, prompts, targets=targets, rates=rates,
            duration_s=args.serve_load_duration,
            seed=args.serve_load_seed, config=sched_cfg,
            offline_rows=offline_rows,
            scheduler_factory=lambda cfg: pool.client(args.model))
        entry = {"name": name,
                 "replicas": [r.health(0) for r in pool.replicas()],
                 "serve_load": block}
        print(f"# serve load pool [{name}]:", file=sys.stderr)
        print(serve_load_mod.format_rate_table(block), file=sys.stderr)
        if not block.get("parity_ok"):
            print(f"# serve load pool [{name}]: PARITY FAILED — pool-"
                  f"served rows differ from the offline sweep rows",
                  file=sys.stderr)
        return entry

    configurations = []
    pool = EnginePool(PoolConfig(scheduler=sched_cfg))
    try:
        for _ in range(n):
            pool.load(args.model, sibling(), owns_engine=False,
                      plan_note=plan_note)
        configurations.append(measure(pool, f"single-model-x{n}"))
    finally:
        pool.close()
    pool = EnginePool(PoolConfig(scheduler=sched_cfg))
    try:
        pool.load(args.model, sibling(), owns_engine=False,
                  plan_note=plan_note)
        pool.load(f"{args.model}-roster-b", sibling(), owns_engine=False,
                  plan_note=plan_note)
        configurations.append(measure(pool, "multi-model"))
    finally:
        pool.close()
    roles_spec = getattr(args, "serve_load_roles", "") or ""
    if roles_spec:
        # Disaggregated roster (ISSUE 20): prefill:N,decode:M specialist
        # replicas over REAL mesh slices (parallel/mesh.carve_slices —
        # degenerate shared placement on the CPU harness, and the
        # replica health docs say which), measured through the SAME rate
        # sweep so its knee lands next to the symmetric roster at equal
        # replica count.  Offline rows stay the parity reference: the
        # cross-replica KV handoff moves WHERE decode runs, never WHAT.
        from llm_interpretation_replication_tpu.parallel import (
            mesh as mesh_mod,
        )

        roster = _parse_roles_spec(roles_spec)
        total = sum(roster.values())
        slices = mesh_mod.carve_slices(total)
        pool = EnginePool(PoolConfig(scheduler=sched_cfg))
        try:
            idx = 0
            for role, count in roster.items():
                for _ in range(count):
                    try:
                        rplan = replica_plan(
                            engine.cfg, args.quant, len(slices[idx]),
                            workload="binary",
                            batches=(args.sweep_batch,),
                            attention_impl=getattr(args, "attn", "xla"),
                            role=role)
                        note = rplan.reason if rplan is not None else None
                    except (ValueError, AttributeError, TypeError):
                        note = None
                    pool.load(args.model, sibling(), owns_engine=False,
                              plan_note=note, role=role,
                              devices=slices[idx])
                    idx += 1
            tag = ",".join(f"{r}:{c}" for r, c in roster.items())
            entry = measure(pool, f"roles-{tag}")
            entry["roles"] = dict(roster)
            configurations.append(entry)
        finally:
            pool.close()
    out = {"replicas": n, "configurations": configurations}
    if getattr(args, "serve_load_faults", ""):
        # fleet self-healing under injected faults (ISSUE 16): a THIRD,
        # supervised configuration — same harness, same parity
        # reference, with replicas killed/wedged (and a vendor outage
        # burst) on the --serve-load-faults schedule.  The resulting
        # 'recovery' block is the round-over-round yardstick: detection
        # and restart latency, requests failed-over, requests lost
        # (structurally zero or the self-healing layer failed).
        entry = _serve_load_recovery_leg(
            args, engine, prompts, targets, offline_rows, rates,
            sibling, sched_cfg)
        configurations.append(entry)
        out["recovery"] = entry["recovery"]
    return out


def _parse_roles_spec(spec):
    """``'prefill:2,decode:2'`` -> ``{"prefill": 2, "decode": 2}``; both
    roles required with counts >= 1 (a fleet missing either role is not
    disaggregated — the symmetric roster already measures that)."""
    out = {}
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        role, _, count = part.partition(":")
        role = role.strip().lower()
        if role not in ("prefill", "decode"):
            raise ValueError(
                f"unknown role {role!r} in --serve-load-roles "
                f"(expected prefill|decode)")
        out[role] = int(count or 0)
    if out.get("prefill", 0) < 1 or out.get("decode", 0) < 1:
        raise ValueError(
            "--serve-load-roles needs both roles with counts >= 1, "
            "e.g. 'prefill:1,decode:1'")
    return out


def _parse_fault_schedule(spec):
    """``'kill@1.0,wedge@2.5,vendor@0'`` -> ``[(kind, offset_s), ...]``
    sorted by offset.  Kinds: kill | wedge | vendor."""
    out = []
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        kind, _, at = part.partition("@")
        kind = kind.strip().lower()
        if kind not in ("kill", "wedge", "vendor"):
            raise ValueError(
                f"unknown fault kind {kind!r} in --serve-load-faults "
                f"(expected kill|wedge|vendor)")
        out.append((kind, float(at or 0.0)))
    return sorted(out, key=lambda f: f[1])


def _serve_load_recovery_leg(args, engine, prompts, targets,
                             offline_rows, rates, sibling,
                             sched_cfg) -> dict:
    """One open-loop run at the TOP swept rate over a SUPERVISED pool
    (serve/supervisor.py) while the --serve-load-faults schedule kills /
    wedges replicas mid-traffic; a ``vendor`` entry adds a flaky
    RemoteBackend outage burst after the measured run.  Every local
    replica is a :class:`BreakableEngine`-wrapped sibling of the sweep
    snapshot, so failed-over rows stay bit-identical to the offline
    reference — the recovery block proves the fleet healed without
    changing WHAT was computed."""
    import threading

    from llm_interpretation_replication_tpu.serve import (
        ScoreRequest,
        SupervisorConfig,
    )
    from llm_interpretation_replication_tpu.serve import (
        load as serve_load_mod,
    )
    from llm_interpretation_replication_tpu.serve.pool import (
        EnginePool,
        PoolConfig,
        RemoteBackend,
    )
    from llm_interpretation_replication_tpu.utils.testing import (
        BreakableEngine,
        FlakyVendor,
    )

    faults = _parse_fault_schedule(args.serve_load_faults)
    n = max(2, int(args.serve_load_replicas))
    duration = args.serve_load_duration
    breakables = []

    def breakable():
        b = BreakableEngine(sibling())
        breakables.append(b)
        return b

    sup_cfg = SupervisorConfig(
        wedge_timeout_s=max(1.5, 0.25 * duration),
        rebuild_backoff_initial_s=0.1, rebuild_backoff_max_s=1.0,
        breaker_failure_threshold=3, breaker_cooldown_s=1.0,
        poll_s=0.02)
    pool = EnginePool(PoolConfig(scheduler=sched_cfg,
                                 supervision=sup_cfg))
    vendor = None
    vendor_model = f"{args.model}-vendor"
    fired = []
    try:
        for _ in range(n):
            pool.load(args.model, breakable(), owns_engine=False)
        pool.supervisor.register_rebuild(args.model, breakable)
        if any(k == "vendor" for k, _ in faults):
            # the vendor leg gets its OWN model name plus one local
            # sibling under that name: the breaker sheds outage traffic
            # to the sibling without ever mixing vendor-shaped rows into
            # the parity-checked measured run
            vendor = FlakyVendor()
            pool.load_remote(RemoteBackend(vendor_model, vendor),
                             model=vendor_model)
            pool.load(vendor_model, breakable(), owns_engine=False)
            pool.supervisor.register_rebuild(vendor_model, breakable)

        def pick_victim():
            """A live, healthy local replica of the measured model —
            only while a sibling survives to fail over to."""
            live = [r for r in pool.replicas(args.model)
                    if r.state == "live"
                    and isinstance(r.engine, BreakableEngine)
                    and r.engine.mode == "ok"]
            return live[0].engine if len(live) >= 2 else None

        stop = threading.Event()

        def inject():
            t0 = time.monotonic()
            for kind, at in faults:
                delay = t0 + at - time.monotonic()
                if delay > 0 and stop.wait(delay):
                    return
                if kind == "vendor":
                    continue        # the post-run burst leg below
                victim = pick_victim()
                if victim is None:
                    fired.append({"kind": kind, "at_s": at,
                                  "skipped": "no healthy sibling pair"})
                    continue
                (victim.kill if kind == "kill" else victim.wedge)()
                fired.append({"kind": kind, "at_s": at})

        injector = threading.Thread(target=inject, daemon=True,
                                    name="bench-fault-injector")
        injector.start()
        report = serve_load_mod.run_load(
            engine, prompts, targets=targets, rate=max(rates),
            duration_s=duration, seed=args.serve_load_seed,
            config=sched_cfg, offline_rows=offline_rows,
            scheduler_factory=lambda cfg: pool.client(args.model))
        stop.set()
        injector.join(timeout=5.0)

        vendor_block = None
        if vendor is not None:
            vendor.down = True
            burst = [pool.submit(
                ScoreRequest(prompt=prompts[i % len(prompts)],
                             targets=("Yes", "No"), timeout_s=120.0),
                model=vendor_model) for i in range(24)]
            answered = 0
            for f in burst:
                try:
                    f.result(timeout=120.0)
                    answered += 1
                except Exception as err:  # graftlint: disable=G05 outage burst audit: any per-request failure type counts against 'answered' below; the burst must drain fully to read the breaker verdict
                    print(f"# recovery vendor burst: "
                          f"{type(err).__name__}: {err}", file=sys.stderr)
            opened = pool.supervisor.breaker_states()
            vendor.down = False
            deadline = time.monotonic() + 30.0
            reclosed = False
            while time.monotonic() < deadline:
                states = pool.supervisor.breaker_states()
                if all(s == "closed" for s in states.values()):
                    reclosed = True
                    break
                # half-open probes need traffic to re-close the breaker
                try:
                    pool.submit(ScoreRequest(
                        prompt=prompts[0], targets=("Yes", "No"),
                        timeout_s=30.0),
                        model=vendor_model).result(timeout=30.0)
                except Exception:  # graftlint: disable=G05 probe traffic: a probe bounced by a still-open breaker is expected; the loop keeps probing until the cooldown admits one
                    pass
                time.sleep(0.1)
            vendor_block = {
                "requests": len(burst),
                "answered": answered,
                "breaker_opened": "open" in opened.values(),
                "breaker_reclosed": reclosed,
                "vendor_calls": vendor.calls,
                "vendor_failures": vendor.failures,
            }
            fired.extend({"kind": kind, "at_s": at, "post_run": True}
                         for kind, at in faults if kind == "vendor")

        sup_report = pool.supervisor.report()
    finally:
        for b in breakables:
            b.heal()            # unblock wedged coalescer threads
        pool.close()

    lost = int(report.get("errors_by_type", {}).get("TimeoutError", 0))
    recovery = dict(sup_report)
    recovery["requests_lost"] = lost
    recovery["faults_injected"] = fired
    recovery["load"] = {
        k: report.get(k) for k in (
            "offered_rate", "requests", "completed", "errors",
            "errors_by_type", "shed", "parity")}
    if vendor_block is not None:
        recovery["vendor_outage"] = vendor_block
    det = recovery.get("detection_ms") or {}
    rst = recovery.get("restart_ms") or {}
    print(f"# serve load pool [self-healing]: "
          f"{recovery['incidents']} incident(s) "
          f"({recovery['crashes']} crash, {recovery['wedges']} wedge), "
          f"{recovery['restarts']} restart(s), "
          f"{recovery['requests_failed_over']} failed over, "
          f"{lost} lost; detection mean "
          f"{det.get('mean', 'n/a')} ms, restart mean "
          f"{rst.get('mean', 'n/a')} ms", file=sys.stderr)
    if lost:
        print("# serve load pool [self-healing]: REQUESTS LOST — the "
              "always-answered contract broke under injected faults",
              file=sys.stderr)
    return {"name": "self-healing", "faults": fired,
            "serve_load_point": report, "recovery": recovery}


def _packed_secondary(args, engine, prompts, targets, isolated_rows) -> dict:
    """One packed scoring pass over the sweep corpus: questions/s at the
    packed operating point + the drift block vs the isolated headline
    rows' first-token fields (the API top-20 comparator both modes
    emit).  The packed row batch steps down by the packing factor (rows
    are ~Q× longer; dense attention is quadratic in row length)."""
    import time as timemod

    from llm_interpretation_replication_tpu.scoring import (
        packed as packed_mod,
    )

    packing = max(1, int(args.packed))
    iso_rel = np.asarray([row.get("first_token_relative_prob", float("nan"))
                          for row in isolated_rows], dtype=float)
    demos = packed_mod.demos_from_relative_probs(iso_rel, targets)
    packs = packed_mod.build_packs(prompts, packing, demos)
    packed_batch = max(32, (args.sweep_batch // packing // 32) * 32)
    with engine.config_overrides(batch_size=packed_batch):
        t0 = timemod.perf_counter()
        rows = engine.score_packed(packs, targets=targets)
        dt = timemod.perf_counter() - t0
    packed_rel = np.asarray([row.get("first_token_relative_prob",
                                     float("nan")) for row in rows],
                            dtype=float)
    drift = packed_mod.drift_report(packed_rel, iso_rel, packing)
    report = {
        "metric": (f"questions/sec/chip (packed batch prompting secondary, "
                   f"Q={packing} questions per prefill row, batch "
                   f"{packed_batch} packed rows, anchor-gathered binary "
                   f"leg)"),
        "value": round(len(prompts) / dt, 2),
        "unit": "questions/sec",
        "drift": drift,
    }
    print(f"# packed secondary: {report['value']} questions/s at Q="
          f"{packing} (batch {packed_batch} rows), drift |Δrel_prob| "
          f"mean {drift['mean_abs_delta']} p90 {drift['p90_abs_delta']} "
          f"flip rate {drift['flip_rate']}", file=sys.stderr)
    return report


def _distill_bench_k_head(args, engine, scenarios, prompts_by_scenario,
                          label="sweep-full"):
    """Self-distill the engine's K-head on the sweep's own texts (both
    legs' formats) when ``--decode-k`` > 1 — AFTER calibration swapped in
    the final params (a head distilled on stale weights still verifies
    safely, it just rejects) and BEFORE warmup, so the verify programs
    compile untimed with everything else.  Re-run after any later param
    swap (the EOS-typical bracket leg)."""
    import time as timemod

    if (getattr(args, "decode_k", 1) or 1) <= 1:
        return
    sample = [p for ps in prompts_by_scenario for p in ps][:24]
    sample += [f"{r} {s['confidence_format']}" for s in scenarios
               for r in s["rephrasings"][:2]][:8]
    t0 = timemod.perf_counter()
    engine.distill_k_head_on(sample)
    print(f"# {label}: K-head distilled for decode_k={args.decode_k} on "
          f"{min(len(sample), 32)} sample prompts "
          f"({timemod.perf_counter() - t0:.1f}s)", file=sys.stderr)


def _k_decode_block(args) -> "dict | None":
    """The ``k_decode`` block for sweep-full records (ISSUE 13): the
    configured vs plan-search-predicted K, the measured accepted-K
    distribution (telemetry ``accepted_k`` histogram, scoped to the
    measured repeats like the context counters), per-leg steps saved,
    and the block reject rate — everything the next driver run needs to
    measure the multiplier per leg and recalibrate K_ACCEPT_PRIOR."""
    k = int(getattr(args, "decode_k", 1) or 1)
    predicted = getattr(args, "predicted_k", None)
    if k <= 1 and predicted is None:
        return None
    from llm_interpretation_replication_tpu.utils.telemetry import (
        HIST_GROWTH,
        hist_bucket_le,
    )

    c = getattr(args, "context_counters", None) or {}
    hist = getattr(args, "k_hist", None) or {}
    proposed = int(c.get("k_blocks_proposed", 0))
    rejected = int(c.get("k_blocks_rejected", 0))
    # recover the INTEGER accepted-K each log bucket holds: accepted
    # lengths are small ints and the 2^(1/8) growth keeps consecutive
    # ints in distinct buckets through K ~ 11, so rounding the bucket's
    # geometric midpoint (le / sqrt(growth) — the upper bound itself can
    # round UP past the content, e.g. le(8) = 8.72) is exact for every
    # K the engine can record — the driver's K_ACCEPT_PRIOR
    # recalibration reads these labels as K values
    mid = HIST_GROWTH ** 0.5
    return {
        "decode_k": k,
        "predicted_k": predicted,
        "accepted_k_hist": {
            str(int(round(hist_bucket_le(idx) / mid))): int(n)
            for idx, n in sorted(hist.get("counts", {}).items())
        },
        "accepted_k_mean": (round(hist["sum"] / hist["count"], 3)
                            if hist.get("count") else None),
        "k_steps_saved": {
            "total": int(c.get("k_steps_saved", 0)),
            "confidence": int(c.get("k_steps_saved|leg=confidence", 0)),
            "completion": int(c.get("k_steps_saved|leg=completion", 0)),
        },
        "k_blocks_proposed": proposed,
        "k_blocks_rejected": rejected,
        "k_reject_rate": (round(rejected / proposed, 4)
                          if proposed else None),
        "head_missing": bool(c.get("k_decode_head_missing")),
    }


def run_sweep_full_mode(args, cfg, params):
    """Full-study row contract, end to end, through the REAL sweep shell
    (sweeps/perturbation.run_model_perturbation_sweep): per rephrasing, the
    binary leg with ``decode_completions=True`` — the 50-token ``Model
    Response`` text the reference's generate records
    (run_base_vs_instruct_100q.py:337-346,379) — plus the confidence leg
    (decode + digit-reconstruction weighted confidence), writing all 15
    workbook columns (perturb_prompts.py:966-969).  One workbook row therefore
    costs TWO engine passes, both decoding; the completions path also runs
    at pipeline depth 2 by default (a full KV cache is pinned per in-flight
    batch — EngineConfig docstring), so this number is NOT predictable from
    the binary-leg headline; it is measured here.

    Random weights never emit EOS, so every completion runs the full 50
    tokens — the honest WORST case; real instruct models EOS after the
    answer and land between this and the binary-leg rate."""
    import os
    import tempfile
    import time as timemod

    from llm_interpretation_replication_tpu.runtime.engine import (
        EngineConfig,
        ScoringEngine,
    )
    from llm_interpretation_replication_tpu.sweeps import (
        run_model_perturbation_sweep,
    )

    scenarios, prompts_by_scenario, n_total = _sweep_corpus(args)
    # the tokenizer must cover BOTH legs' texts
    tok = _train_sweep_tokenizer(
        [p for ps in prompts_by_scenario for p in ps]
        + [f"{r} {s['confidence_format']}" for s in scenarios
           for r in s["rephrasings"]])

    pool_kw = {}
    if getattr(args, "pool_max_bytes", 0):
        pool_kw["phase2_pool_max_bytes"] = args.pool_max_bytes
    engine = ScoringEngine(
        "falcon", cfg, params, tok,
        engine_config=EngineConfig(
            batch_size=args.sweep_batch, decode_completions=True,
            phase2_pool_target=args.pool_target,
            pooled_confidence=getattr(args, "pooled_confidence", True),
            slot_repack=getattr(args, "slot_repack", True),
            pipeline_depth=args.pipeline_depth,
            kv_dtype=getattr(args, "kv_dtype", "bf16") or "bf16",
            prefill_chunk=getattr(args, "prefill_chunk", 0) or 0,
            decode_k=getattr(args, "decode_k", 1) or 1,
            # measured operating point: repeat-level step-down only (the
            # engine's silent per-batch degradation would skew the record)
            oom_backoff=False,
            **pool_kw,
        ),
    )
    params, measured_rate = _calibrate_decided_rate(
        params, cfg, engine, scenarios, prompts_by_scenario, args.decided_frac,
    )
    engine.params = params
    args.measured_rate = measured_rate
    args.eos_rate = None
    if getattr(args, "eos_mode", "none") == "typical":
        # the WHOLE run measures the EOS-typical bracket: synthetic weights
        # emit EOS right after the answer at the calibrated decided rate,
        # so completion decodes early-stop like a real instruct model's
        eos_id = _arm_eos_token(tok, cfg)
        params, eos_rate = _calibrate_eos_rate(
            params, cfg, engine, scenarios, prompts_by_scenario,
            args.decided_frac, eos_id)
        engine.params = params
        args.eos_rate = eos_rate
        print(f"# sweep-full: EOS-typical bracket — calibrated "
              f"EOS-within-2 rate {eos_rate:.2f} (eos id {eos_id})",
              file=sys.stderr)
    fuse = bool(getattr(args, "fuse_prefix", True))
    print(f"# sweep-full: {n_total} rows x 2 legs (binary+completions, "
          f"confidence), calibrated position-0 hit rate {measured_rate:.2f}, "
          f"prefix reuse {'ON (fused legs)' if fuse else 'OFF'}",
          file=sys.stderr)
    _distill_bench_k_head(args, engine, scenarios, prompts_by_scenario)

    if getattr(args, "warmup", True):
        # Explicit bucket warmup (engine.warmup): compile — or deserialize
        # from the persistent cache — every program the sweep needs BEFORE
        # repeat 0's clock starts, so cold and warm repeats measure the
        # same code path and the repeat-0 compile penalty (~150 s in
        # BENCH_r05) moves into this untimed pass.
        from llm_interpretation_replication_tpu.runtime.engine import LegSpec

        try:
            t0 = timemod.perf_counter()
            if fuse:
                # full-corpus tokenization here is deliberate (and
                # untimed): sampling lengths could miss an occupied
                # bucket, re-introducing a timed repeat-0 compile — the
                # exact penalty warmup exists to remove.  ~1-2 s of host
                # work against minutes of sweep.
                reph_lens = [
                    len(ids) for s in scenarios
                    for ids in tok(s["rephrasings"])["input_ids"]]
                # per-leg suffix maxima: the binary and confidence format
                # strings can land in different SUFFIX_BUCKETS, and each
                # (prefix bucket, suffix bucket) pair is its own program
                suffix_lens = [
                    max(len(ids) for s in scenarios for ids in
                        tok([" " + s[key]],
                            add_special_tokens=False)["input_ids"])
                    for key in ("response_format", "confidence_format")]
                report = engine.warmup(
                    prompt_lengths=reph_lens, suffix_length=suffix_lens,
                    legs=[LegSpec("binary"),
                          LegSpec("confidence", with_confidence=True,
                                  max_new_tokens=10)])
            else:
                lens = [len(ids) for ps in prompts_by_scenario
                        for ids in tok(ps)["input_ids"]]
                report = engine.warmup(
                    prompt_lengths=lens,
                    legs=[LegSpec("binary"),
                          LegSpec("confidence", with_confidence=True,
                                  max_new_tokens=10)])
            hits = sum(1 for r in report if r["cache_hit"])
            print(f"# warmup: {len(report)} buckets in "
                  f"{timemod.perf_counter() - t0:.1f}s "
                  f"({hits} compile-cache hits)", file=sys.stderr)
        except Exception as err:  # warmup is best-effort; the sweep still
            print(f"# warmup failed ({err}); repeat 0 compiles inline",
                  file=sys.stderr)

    from llm_interpretation_replication_tpu.utils.telemetry import (
        counters,
        hist_snapshot,
    )

    # context-block counters scope to the measured repeats: the warmup
    # pass above also runs _prefill and must not inflate the record
    # (the accepted_k histogram follows the same discipline)
    args.counters_snap = counters()
    args.k_hist_snap = hist_snapshot(["accepted_k"])
    engine.occupancy_report()      # scope the occupancy block to the
    #                                measured repeats (counters discipline)
    _obs_phase_snap(args)
    best_dt = float("inf")
    last_ok_path = None
    repeat_times = []
    rep = 0
    while rep < max(1, args.sweep_repeats):
        out_path = args.sweep_out or os.path.join(
            tempfile.mkdtemp(prefix="bench_sweep_full_"), "results.xlsx")
        # each repeat sweeps from scratch: a leftover workbook/side-log
        # would resume-skip every row and time nothing.  (With a fixed
        # --sweep-out this necessarily deletes the previous repeat's
        # workbook before re-measuring; without it each repeat gets its
        # own tmpdir and earlier successes stay on disk — last_ok_path
        # below returns the last SUCCESSFUL repeat's workbook either way.)
        from llm_interpretation_replication_tpu.sweeps.perturbation import (
            _sidelog_path,
        )

        for stale in (out_path, _sidelog_path(out_path)):
            if os.path.exists(stale):
                os.remove(stale)
        t0 = timemod.perf_counter()
        try:
            with _profile_window(args, rep):
                df = run_model_perturbation_sweep(
                    engine, args.model, scenarios, out_path,
                    checkpoint_every=args.checkpoint_every,
                    confidence=True, log=lambda *a, **k: None,
                    fuse_prefix=fuse,
                )
        except Exception as err:
            action = _sweep_oom_action(
                err, args, engine, rep, best_dt < float("inf"),
                floor=192, fallback=lambda b: b - 32, label="sweep-full")
            if action == "skip":
                rep += 1
            continue
        dt = timemod.perf_counter() - t0
        assert len(df) == n_total, (len(df), n_total)
        print(f"# sweep-full repeat {rep}: total {dt:.1f}s "
              f"({n_total / dt:.2f} rows/s, 2 engine legs each)",
              file=sys.stderr)
        best_dt = min(best_dt, dt)
        repeat_times.append(dt)
        last_ok_path = out_path
        rep += 1
        _metrics_repeat_sample(args)
    from llm_interpretation_replication_tpu.utils.telemetry import counters

    c = counters()
    print(f"# sweep-full telemetry: prefix_hit={c.get('prefix_hit', 0):.0f} "
          f"prefix_miss={c.get('prefix_miss', 0):.0f} "
          f"host_overlap_idle_ms={c.get('host_overlap_idle_ms', 0):.0f} "
          f"prefill_chunks={c.get('prefill_chunks', 0):.0f} "
          f"kv_cache_bytes_saved={c.get('kv_cache_bytes_saved', 0):.0f}",
          file=sys.stderr)
    print(f"# sweep-full pooled confidence: "
          f"pooled_conf_rows={c.get('pooled_conf_rows', 0):.0f} "
          f"retired={c.get('pooled_conf_retired_rows', 0):.0f} "
          f"conf_steps_saved={c.get('conf_steps_saved', 0):.0f} "
          f"completion_cache_bytes_freed="
          f"{c.get('completion_cache_bytes_freed', 0):.0f}",
          file=sys.stderr)
    args.repeat_times = repeat_times
    args.phases_report = _phases_report(
        args, sum(repeat_times), n_total * max(1, len(repeat_times)))
    # slot-occupancy block (ROADMAP item 3): measured-repeat ring stats
    args.occupancy_report = engine.occupancy_report()

    # {no-EOS, EOS-typical} bracket rows (ROADMAP item 4): the measured
    # repeats above are one bracket; when they ran no-EOS (the r01-r06
    # headline continuity bracket), one extra measured repeat runs the
    # EOS-typical bracket so decode early-stop savings
    # (decode_steps_saved, completion-cache frees) land in a recorded
    # number instead of staying an unmeasured ~4x span.
    from llm_interpretation_replication_tpu.utils.telemetry import (
        counters_since as _counters_since,
    )

    c_main = _counters_since(getattr(args, "counters_snap", None) or {})
    # freeze the context block's counter scope HERE: the bracket leg below
    # runs after the measured repeats, and its decode_steps_saved /
    # cache frees must not leak into a record whose context names the
    # no-EOS bracket (_operating_context prefers this snapshot)
    args.context_counters = dict(c_main)
    from llm_interpretation_replication_tpu.utils.telemetry import (
        hist_since as _hist_since,
    )

    args.k_hist = _hist_since(
        getattr(args, "k_hist_snap", None) or {}).get("accepted_k")
    main_mode = ("eos-typical" if getattr(args, "eos_mode", "none")
                 == "typical" else "no-eos")
    brackets = [_bracket_row(main_mode, n_total / best_dt, args.eos_rate,
                             measured_rate, c_main,
                             n_repeats=len(repeat_times))]
    # default False at getattr level: direct run_sweep_full_mode callers
    # (tests drive it with minimal Namespaces) opt in; the CLI arms the
    # bracket leg by default via the --eos-brackets parser default
    if (main_mode == "no-eos" and getattr(args, "eos_brackets", False)
            and best_dt < float("inf")):
        try:
            eos_id = _arm_eos_token(engine.tokenizer, cfg)
            eparams, eos_rate = _calibrate_eos_rate(
                params, cfg, engine, scenarios, prompts_by_scenario,
                args.decided_frac, eos_id)
            engine.params = eparams
            # the bracket swaps params, so the K-head re-distills on the
            # EOS-boosted weights (its continuations now end in EOS —
            # exactly what the heads must learn to propose)
            _distill_bench_k_head(args, engine, scenarios,
                                  prompts_by_scenario,
                                  label="sweep-full eos-bracket")
            snap = counters()
            out_b = os.path.join(
                tempfile.mkdtemp(prefix="bench_sweep_full_eos_"),
                "results.xlsx")
            t0 = timemod.perf_counter()
            df = run_model_perturbation_sweep(
                engine, args.model, scenarios, out_b,
                checkpoint_every=args.checkpoint_every,
                confidence=True, log=lambda *a, **k: None,
                fuse_prefix=fuse,
            )
            dt = timemod.perf_counter() - t0
            assert len(df) == n_total, (len(df), n_total)
            delta = _counters_since(snap)
            row = _bracket_row("eos-typical", n_total / dt, eos_rate,
                               measured_rate, delta)
            brackets.append(row)
            print(f"# sweep-full EOS-typical bracket: "
                  f"{row['value']} rows/s (vs {brackets[0]['value']} "
                  f"no-EOS), decode_steps_saved="
                  f"{row['decode_steps_saved']}, eos rate "
                  f"{eos_rate:.2f}", file=sys.stderr)
        except Exception as err:  # bracket is best-effort: the headline
            # bracket is already measured; a bracket-leg OOM or
            # calibration failure must not sink the record
            print(f"# EOS-typical bracket failed ({err}); record keeps "
                  f"the no-EOS row only", file=sys.stderr)
        finally:
            engine.params = params
            engine.tokenizer.eos_token_id = None
    args.brackets_report = brackets

    if last_ok_path and not os.path.exists(last_ok_path):
        # with a fixed --sweep-out, a later failed repeat deleted the
        # successful repeat's workbook at loop start — never hand the
        # caller a path that no longer exists
        print(f"# note: workbook of the successful repeat was removed by a "
              f"later failed repeat (fixed --sweep-out); no workbook to "
              f"report", file=sys.stderr)
        last_ok_path = None
    # verified teardown (ISSUE 12): same discipline as run_sweep_mode —
    # nothing this mode's engine pinned outlives the mode
    engine.close(release_params=False)
    return n_total / best_dt, measured_rate, last_ok_path


def _bracket_row(eos_mode: str, rows_per_s: float, eos_rate, decided_rate,
                 counter_delta: dict, n_repeats: int = 1) -> dict:
    """One {no-EOS, EOS-typical} bracket row for the sweep-full record:
    the bracket's measured rate plus the decode early-stop savings its
    counters actually recorded (decode_steps_saved is structurally 0 on
    the no-EOS bracket — nothing ever emits EOS — and must be > 0 on the
    EOS-typical bracket for the bracket to mean anything).

    Counter deltas normalize PER MEASURED REPEAT (``n_repeats``): the
    main bracket's delta spans every measured repeat while the extra
    EOS-typical leg runs exactly one, and the block exists to compare
    the two rows — mismatched scopes would understate one side by the
    repeat count."""
    n = max(1, int(n_repeats))
    row = {
        "eos_mode": eos_mode,
        "metric": (f"full-study rows/sec/chip ({eos_mode} decode bracket, "
                   f"binary leg with completions + confidence leg)"),
        "value": round(rows_per_s, 2),
        "unit": "rows/sec",
        "decided_rate": round(float(decided_rate), 3),
        "repeats": n,
        "decode_steps_saved": int(
            counter_delta.get("decode_steps_saved", 0) / n),
        "conf_steps_saved": int(
            counter_delta.get("conf_steps_saved", 0) / n),
    }
    if eos_rate is not None:
        row["eos_rate"] = round(float(eos_rate), 3)
    if counter_delta.get("completion_cache_bytes_freed"):
        row["completion_cache_gib_freed"] = round(
            counter_delta["completion_cache_bytes_freed"] / n / 2**30, 3)
    if counter_delta.get("k_steps_saved"):
        # joint K-decode savings per bracket (ISSUE 13): the EOS-typical
        # bracket is where accepted blocks cover whole completions
        row["k_steps_saved"] = int(counter_delta["k_steps_saved"] / n)
    return row


def _full_study_record(a, rps: float, rate: float) -> dict:
    """The sweep-full JSON record body from one measured run's namespace
    — ONE spelling shared by the ``--mode sweep-full`` headline and the
    sweep mode's in-process full-study secondary (``a`` is then the
    secondary's own namespace: its operating point, context counters,
    phases and brackets, never the parent's)."""
    fused_tag = ("fused prefix-KV two-leg scoring"
                 if getattr(a, "fuse_prefix", True)
                 else "unfused two-call legs")
    bracket_tag = ("EOS-typical decode bracket"
                   if getattr(a, "eos_mode", "none") == "typical"
                   else "no-EOS worst case")
    # the K tag folds into the metric text so bench-diff's alignment key
    # (obs/benchdiff._shape_tags) never cross-compares a joint-K-decode
    # run with the sequential workload shape; K=1 stays untagged so
    # legacy records keep aligning
    k_tag = (f", joint decode-k {a.decode_k}"
             if (getattr(a, "decode_k", 1) or 1) > 1 else "")
    record = {
        "metric": (
            f"full-study rows/sec/chip (END-TO-END perturbation "
            f"sweep, FULL row contract: binary leg with 50-token "
            f"completions + confidence leg, all 15 workbook "
            f"columns via the real sweep shell, {fused_tag}; "
            f"{a.model} geometry, "
            f"{'w8a8 int8' if a.quant == 'int8' else 'bf16'}, "
            f"batch {a.sweep_batch}, measured position-0 hit "
            f"rate {rate:.2f}, {bracket_tag}{k_tag})"
        ),
        "value": round(rps, 2),
        "unit": "rows/sec",
        # the reference's serial full row is TWO ~50-token
        # generates (binary + confidence) per rephrasing: ~0.5
        # rows/sec on the A100 baseline assumptions
        "vs_baseline": round(rps / (A100_BASELINE_PROMPTS_PER_SEC / 2), 2),
    }
    if getattr(a, "brackets_report", None):
        # {no-EOS, EOS-typical} bracket rows (ROADMAP item 4):
        # the decode early-stop span is a recorded number, with
        # decode_steps_saved/cache frees per bracket
        record["brackets"] = a.brackets_report
    k_block = _k_decode_block(a)
    if k_block:
        # joint K-decode telemetry (ISSUE 13): accepted-K distribution,
        # per-leg steps saved, reject rate, predicted-vs-configured K
        record["k_decode"] = k_block
    if getattr(a, "occupancy_report", None):
        # slot-occupancy block (ROADMAP item 3): slot-idle fraction
        # before/after decode-then-repack, refills, repack stalls — the
        # number the next driver record measures the occupancy gain by
        record["occupancy"] = a.occupancy_report
    record.update(_repeat_report(a))
    record.update(_operating_context(a))
    if getattr(a, "plan_search_report", None):
        record["plan_search"] = a.plan_search_report
    record.update(getattr(a, "phases_report", None) or {})
    return record


#: The child-namespace contract for the in-process sweep-full companion
#: (cross-checked by ``lint contracts``): exactly these attributes may be
#: re-pointed on the shallow-copied namespace inside
#: ``_full_study_secondary`` — everything else INHERITS from the parent
#: run (the ISSUE-10 bracket flags, --trace/--metrics instrumentation,
#: corpus paths).  Adding a ``child.x = ...`` without declaring it here
#: (or declaring one and dropping the assignment) fails the contracts
#: gate, which is the machine-checked successor of the hand-written
#: child-forwarding source pins.
FULL_STUDY_CHILD_OVERRIDES = (
    "mode", "sweep_repeats", "kv_dtype", "prefill_chunk", "attn",
    "pooled_confidence", "slot_repack", "sweep_out", "plan_search_report",
    "profile",
    # plan-search / fixed-plan resolve outputs for the child's own
    # full-workload operating point:
    "sweep_batch", "pool_target", "fit_decision", "predicted_batch",
    "decode_k", "predicted_k",
)


def _full_study_secondary(args, cfg, geometry, params) -> dict:
    """The sweep mode's full-study companion row, IN-PROCESS (ISSUE 12).

    The r05-era subprocess isolation is DELETED: its measured reason —
    5.5 vs 31.4 rows/s on identical code, the earlier modes' live param
    copies and allocator state thrashing a path that runs within a
    quarter-GiB of the HBM edge — is exactly what
    ``ScoringEngine.close()`` now tears down.  ``run_sweep_mode`` closes
    its engine (audit pools swept, caches cleared, its calibrated param
    copy's unique leaves released) before this leg builds a fresh one,
    so the full-study engine starts from the torn-down allocator the
    child process used to provide — without re-paying process spawn,
    JAX init, or a second weight materialization.  The next
    driver-produced record is the measured confirmation: this
    secondary's value should land within noise of a standalone
    ``--mode sweep-full`` run (PARITY.md "Full-study secondary").

    Runs on a SHALLOW COPY of the parent namespace: one repeat at the
    documented full-study operating point (``--full-kv-dtype`` /
    ``--full-prefill-chunk``), its own counter/phase snapshots, a fresh
    workbook tempdir, and — under ``--plan-search`` — its OWN
    full-workload search (the parent's binary-workload choice does not
    transfer across workloads)."""
    import copy

    from llm_interpretation_replication_tpu.models.config import (
        DecoderConfig,
    )
    from llm_interpretation_replication_tpu.runtime.engine import (
        EngineConfig,
    )
    from llm_interpretation_replication_tpu.runtime.plan import (
        resolve_full_sweep_plan,
    )

    child = copy.copy(args)
    child.mode = "sweep-full"
    # ONE full-study repeat: SKILL.md/PARITY.md document the secondary as
    # a single repeat — a second warm repeat costs ~5 minutes for no
    # extra information (best-of noise rejection matters for the
    # headline, not the companion row)
    child.sweep_repeats = 1
    # the full-study OPERATING POINT, not the parent sweep's bf16
    # default: the secondary measures the same int8 + chunk-128 point a
    # direct --mode sweep-full run would
    child.kv_dtype = getattr(args, "full_kv_dtype", "int8")
    child.prefill_chunk = getattr(args, "full_prefill_chunk", 128)
    child.attn = getattr(args, "attn", "xla")
    child.pooled_confidence = getattr(args, "pooled_confidence", True)
    child.slot_repack = getattr(args, "slot_repack", True)
    child.sweep_out = None          # fresh tempdir workbook — never the
    #                                 parent sweep's artifact
    child.plan_search_report = None
    if getattr(args, "profile", None):
        # own capture dir, the old child-process discipline: a profiled
        # parent must not clobber its repeat-0 capture with this leg's
        child.profile = os.path.join(args.profile, "sweep-full")
    searched = False
    if getattr(args, "plan_search", False):
        # the secondary searches its OWN (full-study) operating point:
        # the parent's binary-workload choice does not transfer
        from llm_interpretation_replication_tpu.runtime.plan_search import (
            chosen_plan,
            format_candidate_table,
            plan_search_record,
            search_plans,
        )

        ranked = search_plans(
            cfg, args.quant, n_devices=1, seq=256, workload="full",
            batches=tuple(range(32, max(512, args.sweep_batch) + 1, 32)),
            pipeline_depth=args.pipeline_depth, attention_impl=child.attn,
            # price the pool the way the engine will actually run it: the
            # refill model when decode-then-repack is on (the default)
            slot_repack=getattr(child, "slot_repack", True))
        best = chosen_plan(ranked)
        print(format_candidate_table(ranked,
                                     title="plan search (full-study)"),
              file=sys.stderr)
        if best is not None:
            searched = True
            child.plan_search_report = plan_search_record(ranked)
            child.sweep_batch = best.batch
            child.kv_dtype = best.kv_dtype
            child.prefill_chunk = best.prefill_chunk
            child.pool_target = best.pool_target
            child.fit_decision = best.reason
            child.predicted_batch = best.batch
            # the priced K axis rides the secondary's own full-workload
            # search, like batch/kv/chunk/pool (ISSUE 13)
            child.decode_k = best.decode_k
            child.predicted_k = best.decode_k
        else:
            # same fallback a direct --mode sweep-full run takes: no
            # fitting full-workload candidate means the fixed-plan
            # resolve below picks the batch — never the parent's
            # binary-workload point (which also leaves stale
            # fit_decision/predicted_batch on the copied namespace)
            print("# full-study secondary plan search: no candidate "
                  "fits; falling back to the fixed-plan resolve",
                  file=sys.stderr)
    if not searched:
        sweep_plan = resolve_full_sweep_plan(
            cfg, child.quant, child.sweep_batch, 256,
            pipeline_depth=child.pipeline_depth,
            requested_impl="flash" if child.attn == "flash" else None,
            top_k=EngineConfig().top_k,
            kv_dtype=child.kv_dtype, prefill_chunk=child.prefill_chunk,
            pooled_confidence=child.pooled_confidence,
            pool_target=child.pool_target or None,
            slot_repack=getattr(child, "slot_repack", True),
        )
        child.fit_decision = sweep_plan.reason
        child.predicted_batch = sweep_plan.batch
        if (sweep_plan.batch != child.sweep_batch
                or sweep_plan.attention_impl != child.attn):
            print(f"# full-study secondary plan: {sweep_plan.reason}; "
                  f"batch {child.sweep_batch} -> {sweep_plan.batch}, "
                  f"attn {child.attn} -> {sweep_plan.attention_impl}",
                  file=sys.stderr)
            child.sweep_batch = sweep_plan.batch
            if sweep_plan.attention_impl != child.attn:
                child.attn = sweep_plan.attention_impl
                cfg = DecoderConfig(**geometry,
                                    attention_impl=child.attn)
    rps, rate, out_path = run_sweep_full_mode(child, cfg, params)
    print(f"# full-study secondary workbook: "
          f"{out_path or 'unavailable'}", file=sys.stderr)
    return _full_study_record(child, rps, rate)


def run_sweep_packed_mode(args, cfg, params):
    """Packed multi-question batching as the headline (ISSUE 10): the
    perturbation corpus scored ``--packed`` questions per prefill through
    the REAL packed sweep shell (sweeps/perturbation.
    run_packed_perturbation_sweep — resume keys, side-log checkpoints,
    heartbeats), with the drift-parity leg on by default: the same rows
    score isolated first (supplying the Auto-Demo demonstrations), and
    the record carries the per-question |Δ relative_prob| distribution +
    flip rate as a first-class block."""
    import os
    import tempfile
    import time as timemod

    from llm_interpretation_replication_tpu.obs import flight as obs_flight
    from llm_interpretation_replication_tpu.runtime.engine import (
        EngineConfig,
        ScoringEngine,
    )
    from llm_interpretation_replication_tpu.sweeps import (
        run_packed_perturbation_sweep,
    )
    from llm_interpretation_replication_tpu.utils.telemetry import counters

    scenarios, prompts_by_scenario, n_total = _sweep_corpus(args)
    tok = _train_sweep_tokenizer(
        [p for ps in prompts_by_scenario for p in ps])
    packing = max(1, int(getattr(args, "packed", 4) or 4))
    engine = ScoringEngine(
        "falcon", cfg, params, tok,
        engine_config=EngineConfig(
            batch_size=args.sweep_batch, decode_completions=False,
            pipeline_depth=args.pipeline_depth,
            oom_backoff=False,
        ),
    )
    params, measured_rate = _calibrate_decided_rate(
        params, cfg, engine, scenarios, prompts_by_scenario,
        args.decided_frac,
    )
    engine.params = params
    args.measured_rate = measured_rate
    print(f"# sweep-packed: {n_total} questions at Q={packing} per row, "
          f"batch {args.sweep_batch} packed rows, calibrated position-0 "
          f"hit rate {measured_rate:.2f}, drift parity "
          f"{'ON' if getattr(args, 'packed_parity', True) else 'OFF'}",
          file=sys.stderr)

    args.counters_snap = counters()
    _obs_phase_snap(args)
    out_base = args.sweep_out or os.path.join(
        tempfile.mkdtemp(prefix="bench_sweep_packed_"), "results.xlsx")
    obs_flight.enable(os.path.dirname(os.path.abspath(out_base)))
    best_dt = float("inf")
    last_report = None
    repeat_times = []
    rep = 0
    while rep < max(1, args.sweep_repeats):
        from llm_interpretation_replication_tpu.sweeps.perturbation import (
            _sidelog_path,
        )

        for stale in (out_base, _sidelog_path(out_base)):
            if os.path.exists(stale):
                os.remove(stale)  # each repeat sweeps from scratch
        t0 = timemod.perf_counter()
        try:
            with _profile_window(args, rep):
                df, report = run_packed_perturbation_sweep(
                    engine, args.model, scenarios, out_base,
                    packing=packing,
                    drift_parity=getattr(args, "packed_parity", True),
                    checkpoint_every=args.checkpoint_every,
                    log=lambda *a, **k: None,
                )
        except Exception as err:
            action = _sweep_oom_action(
                err, args, engine, rep, best_dt < float("inf"),
                floor=32, fallback=lambda b: max(32, b - 32),
                label="sweep-packed")
            if action == "skip":
                rep += 1
            continue
        dt = timemod.perf_counter() - t0
        assert len(df) == n_total, (len(df), n_total)
        print(f"# sweep-packed repeat {rep}: total {dt:.1f}s "
              f"({n_total / dt:.2f} questions/s incl. "
              f"{'the isolated parity leg' if getattr(args, 'packed_parity', True) else 'no parity leg'})",
              file=sys.stderr)
        best_dt = min(best_dt, dt)
        repeat_times.append(dt)
        if report is not None:
            last_report = report
        rep += 1
        _metrics_repeat_sample(args)
    args.repeat_times = repeat_times
    args.packed_drift = last_report
    args.phases_report = _phases_report(
        args, sum(repeat_times), n_total * max(1, len(repeat_times)))
    return n_total / best_dt, measured_rate, out_base


def _metrics_repeat_sample(args):
    """One metrics-registry sample per finished repeat (``--metrics``):
    the binary sweep mode has no per-chunk heartbeat (one engine call
    covers the corpus), so the repeat boundary is its sampling point."""
    if not getattr(args, "metrics", None):
        return
    from llm_interpretation_replication_tpu.obs import metrics as obs_metrics

    obs_metrics.get_registry().sample()


def _obs_phase_snap(args):
    """Snapshot the span tracer's phase totals so the ``phases`` block
    scopes to the measured repeats (the ``counters_snap`` pattern —
    calibration/warmup spans must not inflate the decomposition)."""
    from llm_interpretation_replication_tpu import obs

    args.phase_snap = obs.phase_snapshot()


def _phases_report(args, wall_s: float, rows: int) -> dict:
    """The ``phases`` block for the sweep JSON records (obs/report.py):
    per-phase (and per-leg) self-time seconds since :func:`_obs_phase_snap`
    with coverage against the measured wall-clock — ISSUE-6's missing
    decomposition of where the full-study row's time goes.  Also renders
    the stderr table.  {} when tracing is off."""
    from llm_interpretation_replication_tpu import obs
    from llm_interpretation_replication_tpu.obs.report import (
        format_phase_table,
        phases_block,
    )

    if not obs.enabled():
        return {}
    totals = obs.phase_totals_since(getattr(args, "phase_snap", {}),
                                    by_leg=True)
    block = phases_block(totals, wall_s=wall_s or None, rows=rows or None)
    print(format_phase_table(block, title="phase attribution "
                                          "(measured repeats)"),
          file=sys.stderr)
    return {"phases": block}


def _profile_window(args, rep: int):
    """Windowed jax.profiler capture of repeat 0 (``--profile DIR``)."""
    from llm_interpretation_replication_tpu.obs.profiler import (
        profile_window,
    )

    return profile_window(getattr(args, "profile", None), enabled=rep == 0)


def _repeat_report(args) -> dict:
    """Warm-vs-cold repeat decomposition for the sweep modes' JSON record:
    repeat 0 runs first in the process (cold — it pays whatever compilation
    the warmup pass and persistent cache did NOT absorb), later repeats are
    warm.  With the compile cache + warmup on, cold_s ≈ warm_s; the r5
    record's 468.5 s repeat-0 vs 316.1 s repeat-1 gap is exactly what this
    field exists to track."""
    times = getattr(args, "repeat_times", None)
    if not times:
        return {}
    report = {"cold_s": round(times[0], 1)}
    if len(times) > 1:
        report["warm_s"] = round(min(times[1:]), 1)
    return {"repeats": report}


def _operating_context(args) -> dict:
    """Auditable operating-point context for the sweep JSON records: the
    KV-cache dtype, the chunked-prefill setting, and the budget planner's
    fit-decision string (runtime/plan.py reason) — so every recorded
    number names the configuration AND the prediction that chose it.

    Counters report the delta since the sweep's own measured repeats began
    (``args.counters_snap``, set after calibration/warmup) — the counters
    are process-global monotones and warmup's throwaway prefills must not
    inflate the recorded operating point."""
    from llm_interpretation_replication_tpu.utils.telemetry import (
        counters,
        counters_since,
    )

    snap = getattr(args, "counters_snap", None)
    # the run modes freeze this snapshot right after their measured
    # repeats, BEFORE any trailing companion leg (the EOS bracket's extra
    # repeat, the packed secondary, serve replay) can inflate it
    c = getattr(args, "context_counters", None)
    if c is None:
        c = counters() if snap is None else counters_since(snap)
    ctx = {
        "kv_dtype": getattr(args, "kv_dtype", "bf16"),
        "prefill_chunk": getattr(args, "prefill_chunk", 0),
        "planner": getattr(args, "fit_decision", ""),
        # pool settings ride along so the record is self-describing: a
        # BENCH_r06 number names the pooled-confidence configuration that
        # produced it, not just the kv/chunk knobs
        "phase2_pool_target": getattr(args, "pool_target", 0),
        "pooled_confidence": bool(getattr(args, "pooled_confidence", True)),
        "slot_repack": bool(getattr(args, "slot_repack", True)),
        # the decode bracket + packing settings (ISSUE 10): a record's
        # number names which {no-EOS, EOS-typical} bracket produced it
        # and whether rows were packed, so bench-diff can refuse to
        # cross-compare rows from different workload shapes
        "eos_mode": ("eos-typical"
                     if getattr(args, "eos_mode", "none") == "typical"
                     else "no-eos"),
    }
    if getattr(args, "measured_rate", None) is not None:
        ctx["decided_rate"] = round(float(args.measured_rate), 3)
    if getattr(args, "eos_rate", None) is not None:
        ctx["eos_rate"] = round(float(args.eos_rate), 3)
    if getattr(args, "mode", "") == "sweep-packed":
        ctx["packed"] = int(getattr(args, "packed", 0) or 0)
    if (getattr(args, "decode_k", 1) or 1) > 1 and \
            getattr(args, "mode", "") == "sweep-full":
        # the joint-K operating point is part of the record's identity
        # (bench-diff keys on it); K=1 stays absent like the other
        # default-off knobs, and only the full-study mode actually runs
        # the decode legs the knob touches (the sweep mode's secondary
        # carries its own sweep-full child namespace)
        ctx["decode_k"] = int(args.decode_k)
    for name in ("decode_steps_saved", "packed_rows", "packed_questions",
                 "k_steps_saved", "k_blocks_proposed", "k_blocks_rejected"):
        if c.get(name):
            ctx[name] = int(c[name])
    if getattr(args, "pool_max_bytes", 0):
        ctx["phase2_pool_max_bytes"] = int(args.pool_max_bytes)
    if c.get("prefill_chunks"):
        ctx["prefill_chunks"] = int(c["prefill_chunks"])
    if c.get("kv_cache_bytes_saved"):
        ctx["kv_cache_gib_saved"] = round(
            c["kv_cache_bytes_saved"] / 2**30, 2)
    for name in ("pooled_conf_rows", "pooled_conf_retired_rows",
                 "conf_steps_saved", "slot_rows", "slot_refills",
                 "slot_retired", "slot_repacks", "slot_repack_stalls",
                 "slot_compactions"):
        if c.get(name):
            ctx[name] = int(c[name])
    if c.get("completion_cache_bytes_freed"):
        ctx["completion_cache_gib_freed"] = round(
            c["completion_cache_bytes_freed"] / 2**30, 3)
    return {"context": ctx}


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", choices=["falcon-7b", "small-1b"], default="falcon-7b")
    parser.add_argument("--batch", type=int, default=192)
    parser.add_argument("--seq", type=int, default=432)
    parser.add_argument("--iters", type=int, default=16)
    parser.add_argument("--prompt-tokens", type=int, default=430)
    parser.add_argument("--quant", choices=["none", "int8"], default="int8",
                        help="w8a8 int8 projections (the reference path is "
                             "bitsandbytes int8, so int8-vs-int8 is the fair "
                             "comparison; ~0.9997 logit correlation vs bf16)")
    parser.add_argument("--kv-dtype", choices=["bf16", "int8"],
                        default=None,
                        help="decode-time KV cache storage dtype: bf16 "
                             "keeps every bit-parity contract; int8 "
                             "(per-head scales, quantize-on-append — "
                             "ops/quant.quantize_kv) nearly halves the "
                             "cache HBM the full-study contract pins, "
                             "lifting the sweep batch off the 224 cliff "
                             "(tolerance documented in PARITY.md).  "
                             "Default: bf16, EXCEPT --mode sweep-full "
                             "(and the sweep mode's full-study child), "
                             "which measures the documented int8 + "
                             "prefill-chunk-128 operating point — the "
                             "PR-5 planner prediction BENCH_r06 exists "
                             "to confirm")
    parser.add_argument("--prefill-chunk", type=int, default=None,
                        metavar="N",
                        help="> 0: prompts above N tokens prefill in "
                             "N-token chunks through the suffix-extension "
                             "path (models/decoder.chunked_prefill), "
                             "bounding the [B,S,T] attention transients "
                             "the long buckets pay; the budget planner "
                             "(runtime/plan.py) budgets the chunked "
                             "bound.  Default: 0, except --mode "
                             "sweep-full / the full-study child: 128 "
                             "(see --kv-dtype)")
    parser.add_argument("--attn", choices=["xla", "flash"], default="xla",
                        help="attention impl: XLA dense (the DecoderConfig "
                             "'xla' value) or the Pallas kernels "
                             "(ops/attention.py)")
    parser.add_argument("--mode", choices=["sweep", "sweep-full",
                                           "sweep-packed", "parity",
                                           "single", "decode"],
                        default=None,  # resolved after --decode 0 compat:
                                       # sweep when perturbations.json exists,
                                       # else parity
                        help="sweep (default): END-TO-END 10k-perturbation "
                             "scoring sweep on the real perturbations.json "
                             "texts — tokenize + bucketing + two-phase "
                             "engine + row building + xlsx checkpoints all "
                             "inside the wall clock (the BASELINE.json "
                             "north-star workload); "
                             "sweep-full: the FULL-STUDY row contract "
                             "through the real sweep shell — binary leg "
                             "with 50-token completions PLUS confidence "
                             "leg, all 15 workbook columns "
                             "(perturb_prompts.py:966-969); "
                             "sweep-packed: packed multi-question batching "
                             "(--packed questions per prefill, anchor-"
                             "gathered binary leg, measured-drift parity "
                             "block — scoring/packed.py); "
                             "parity: the two-phase sweep — one "
                             "prefill settles every row whose position-0 "
                             "top-k contains a target (the reference reads "
                             "position 0 for those rows, "
                             "run_base_vs_instruct_100q.py:349-364) and only "
                             "the undecided slice continues into the scored "
                             "MAX_LOOK_AHEAD decode, reusing the prefill KV "
                             "cache; single: one forward, no decode (the "
                             "perturbation-sweep fast path); decode: every "
                             "row takes the full scored decode (worst case / "
                             "the r02 headline metric)")
    parser.add_argument("--eos-mode", choices=["none", "typical"],
                        default="none",
                        help="decode bracket for the sweep modes: 'none' "
                             "(default) keeps the synthetic weights' "
                             "no-EOS ceiling-decode bound — the r01-r06 "
                             "headline continuity bracket; 'typical' "
                             "calibrates an EOS boost into the weights "
                             "(_calibrate_eos_rate: EOS emitted right "
                             "after the answer at the decided-rate "
                             "target) so completion decodes early-stop "
                             "like a real instruct model's and "
                             "decode_steps_saved / completion-cache frees "
                             "become measured numbers")
    parser.add_argument("--eos-brackets",
                        action=argparse.BooleanOptionalAction, default=True,
                        help="sweep-full mode with --eos-mode none: after "
                             "the measured repeats, run ONE extra repeat "
                             "at the EOS-typical bracket and attach both "
                             "{no-EOS, EOS-typical} rows to the record's "
                             "'brackets' block (--no-eos-brackets skips "
                             "the extra repeat)")
    parser.add_argument("--packed", type=int, default=4, metavar="Q",
                        help="packed multi-question batching (Auto-Demo, "
                             "scoring/packed.py): Q questions + their "
                             "demonstration answers concatenate into one "
                             "row and the binary leg reads anchor-gathered "
                             "logits from ONE prefill — no decode path.  "
                             "--mode sweep attaches a packed secondary "
                             "(questions/sec + the measured drift block vs "
                             "the isolated headline rows); --mode "
                             "sweep-packed measures it as the headline "
                             "through the real packed sweep shell.  0 "
                             "disables the packed secondary")
    parser.add_argument("--packed-parity",
                        action=argparse.BooleanOptionalAction, default=True,
                        help="sweep-packed mode: score the same rows "
                             "isolated too and report per-question "
                             "|Δ relative_prob| + flip rate as the drift "
                             "block (measured-drift contract, PARITY.md); "
                             "the isolated answers double as the Auto-Demo "
                             "demonstrations")
    parser.add_argument("--decided-frac", type=float, default=0.9,
                        metavar="F",
                        help="parity mode: fraction of rows decided at "
                             "position 0.  Random weights never place the "
                             "target tokens in the top-5 of a 65k vocab, so "
                             "the bench fixes the undecided slice explicitly "
                             "— throughput is architecture-bound, not "
                             "value-bound.  0.9 is conservative for the real "
                             "sweep, where prompts end \"Answer either 'Yes' "
                             "or 'No'\" and instruct models put a target in "
                             "the top-5 almost always; --decided-frac 0 "
                             "reproduces the worst case (== --mode decode)")
    parser.add_argument("--decode", type=int, default=10, metavar="N",
                        help="scored look-ahead steps (MAX_LOOK_AHEAD) for "
                             "the parity/decode modes")
    parser.add_argument("--no-secondary", action="store_true",
                        help="skip the secondary single/decode measurements "
                             "(parity mode attaches both to the JSON line so "
                             "round-over-round trends separate metric "
                             "changes from chip contention)")
    parser.add_argument("--repeats", type=int, default=3, metavar="N",
                        help="timing repetitions; the best (minimum-time) "
                             "run is reported to reject chip-contention "
                             "noise on shared/tunneled devices")
    parser.add_argument("--perturbations", metavar="PATH",
                        default="/root/reference/data/perturbations.json",
                        help="sweep mode: the real 5x2000-rephrasing corpus "
                             "(real length histogram / bucket mix)")
    parser.add_argument("--sweep-batch", type=int, default=320, metavar="N",
                        help="sweep mode engine batch size (real prompts "
                             "are ~107 tokens so a larger batch than the "
                             "430-token parity mode fits; measured 2026-07 "
                             "r5: 320 runs at 120.5-120.9 p/s warm — the "
                             "pooled decode's ReducedScores statistics "
                             "replaced the [batch, 10, V] fp32 score "
                             "buffer that used to OOM 320 — while 352 and "
                             "384 still OOM)")
    parser.add_argument("--sweep-rows", type=int, default=0, metavar="N",
                        help="sweep mode: cap total rows (0 = full 10k)")
    parser.add_argument("--sweep-repeats", type=int, default=2, metavar="N",
                        help="sweep mode: full-sweep repetitions, best "
                             "wall-clock reported (chip contention)")
    parser.add_argument("--sweep-out", metavar="PATH", default=None,
                        help="sweep mode: output workbook (default: temp dir)")
    parser.add_argument("--pool-target", type=int, default=0, metavar="N",
                        help="sweep modes: phase-2 cross-batch pool size "
                             "(0 = engine default, one pooled decode per "
                             "batch-size rows) — shared by the binary "
                             "undecided-row pool and the confidence-leg "
                             "pool")
    parser.add_argument("--pool-max-bytes", type=int, default=0,
                        metavar="BYTES",
                        help="sweep modes: HBM cap on K/V held by the "
                             "cross-batch pools (0 = engine default, "
                             "512 MiB; EngineConfig.phase2_pool_max_bytes)")
    parser.add_argument("--pooled-confidence",
                        action=argparse.BooleanOptionalAction, default=True,
                        help="sweep-full mode: route the confidence leg's "
                             "digit decode through the leg-parameterized "
                             "cross-batch pool (early-exit row retirement "
                             "+ per-chunk completion-cache streaming — "
                             "runtime/engine._Phase2Pool).  "
                             "--no-pooled-confidence measures the r5 "
                             "per-batch decode")
    parser.add_argument("--slot-repack",
                        action=argparse.BooleanOptionalAction, default=True,
                        help="sweep modes: decode-then-repack slot-level "
                             "continuous batching (runtime/slots.py) — "
                             "retired pool lanes refill from the pending "
                             "queue mid-decode and the record gains an "
                             "'occupancy' block (slot-idle fraction "
                             "before/after, refills, repack stalls).  "
                             "--no-slot-repack measures the legacy "
                             "whole-flush schedule")
    parser.add_argument("--decode-k", type=int, default=1, metavar="K",
                        help="sweep-full mode (and the sweep mode's "
                             "full-study secondary): joint next-K-token "
                             "decode with verify-and-accept on both decode "
                             "legs (models/decoder.k_verify_block) — a "
                             "K-head self-distilled on the calibrated "
                             "weights proposes K tokens per pass, one "
                             "joint program verifies them against the "
                             "single-step argmax path, accepted blocks "
                             "are bit-identical to the sequential decode "
                             "and rejections fall back to it.  The record "
                             "gains a k_decode block (accepted-K "
                             "distribution, per-leg steps saved, reject "
                             "rate).  1 = sequential (default); "
                             "--plan-search may override with the priced "
                             "K axis")
    parser.add_argument("--pipeline-depth", type=int, default=None,
                        metavar="N",
                        help="sweep modes: in-flight device batches (host "
                             "post-processing of batch k overlaps device "
                             "compute of batch k+1).  Measured warm 10k "
                             "sweeps (v5e 2026-07): depth 1 = 67.6 p/s, "
                             "2 = 91.5, 4 = 93.2.  Default: 4 for --mode "
                             "sweep (the pooled+selected path holds only "
                             "small cache slices per in-flight batch) and "
                             "2 for --mode sweep-full (the completions "
                             "path pins a full KV cache per in-flight "
                             "batch)")
    parser.add_argument("--fuse-prefix", action=argparse.BooleanOptionalAction,
                        default=True,
                        help="sweep-full mode: fused two-leg scoring — the "
                             "rephrasing prefix prefills ONCE per row into "
                             "a KV cache and the binary/confidence legs run "
                             "as short format-suffix extensions against it "
                             "(engine.score_prefixed).  --no-fuse-prefix "
                             "measures the r5 unfused two-call contract")
    parser.add_argument("--warmup", action=argparse.BooleanOptionalAction,
                        default=True,
                        help="sweep-full mode: explicit bucket-warmup pass "
                             "(engine.warmup) before repeat 0, so compiles "
                             "— or persistent-cache deserializations — "
                             "happen outside the timed repeats")
    parser.add_argument("--checkpoint-every", type=int, default=2000,
                        metavar="N",
                        help="sweep mode: append a checkpoint to the "
                             "side-log every N rows (the sweep shells' "
                             "resume checkpoint; the xlsx renders once at "
                             "end of sweep)")
    parser.add_argument("--plan-search", action="store_true",
                        help="sweep modes: replace the fixed operating "
                             "point with the auto-parallel plan search "
                             "(runtime/plan_search.py) — enumerate batch x "
                             "kv-dtype x prefill-chunk x pool-target "
                             "candidates against the HBM budget model, "
                             "rank by predicted rows/s, run the chosen "
                             "plan, and attach a 'plan_search' block "
                             "(chosen plan + ranked runner-up table with "
                             "per-candidate fit/reject reasons) to the "
                             "JSON record.  The PR-1 OOM ladder stays "
                             "armed as the safety net when the prediction "
                             "misses on hardware")
    parser.add_argument("--serve-replay", action="store_true",
                        help="sweep mode: after the offline repeats, push "
                             "the same workload through the serve/ "
                             "continuous-batching scheduler, verify "
                             "row-level parity against the offline rows, "
                             "and attach a 'serve' block (scheduler vs "
                             "offline rows/sec, micro-batch count, queue "
                             "latency percentiles) to the JSON record")
    parser.add_argument("--serve-load", action="store_true",
                        help="sweep mode: after the offline repeats, "
                             "drive the serve/ scheduler with the "
                             "open-loop load harness (serve/load.py: "
                             "seeded Poisson arrivals over the real "
                             "corpus prompt mix) at >= 3 offered rates, "
                             "and attach a 'serve_load' block (per-rate "
                             "p50/p90/p99/p99.9 end-to-end latency + "
                             "queue_wait/coalesce/serve_engine/respond "
                             "phase decomposition from exact-count "
                             "histograms, achieved-vs-offered rate, "
                             "queue-depth trajectory, saturation "
                             "throughput, row parity vs the offline "
                             "rows) to the JSON record")
    parser.add_argument("--serve-load-rates", metavar="R1,R2,R3[,...]",
                        default="auto",
                        help="offered rates (rows/s) for --serve-load; "
                             "'auto' (default) brackets the measured "
                             "offline scoring rate at 0.5x/1.0x/1.5x so "
                             "the sweep crosses the knee")
    parser.add_argument("--serve-load-duration", type=float, default=8.0,
                        metavar="S",
                        help="--serve-load: seconds of offered traffic "
                             "per rate point")
    parser.add_argument("--serve-load-seed", type=int, default=0,
                        metavar="N",
                        help="--serve-load: seed for the Poisson "
                             "schedule + prompt mix (same seed = "
                             "identical replayable traffic)")
    parser.add_argument("--serve-load-replicas", type=int, default=2,
                        metavar="N",
                        help="--serve-load: after the single-engine "
                             "sweep, run the EnginePool companion "
                             "(serve/pool.py) — N sibling replicas of "
                             "the sweep snapshot (shared param tree) in "
                             "a single-model pool, plus a two-model "
                             "roster pool, each measured through the "
                             "SAME rate sweep into a 'serve_load_pool' "
                             "block with one serve_load block per "
                             "configuration (0/1 = skip the pool "
                             "companion)")
    parser.add_argument("--serve-load-faults", metavar="K@T[,K@T...]",
                        default="",
                        help="--serve-load pool companion: fault-"
                             "injection schedule for a third, SUPERVISED "
                             "pool configuration (serve/supervisor.py "
                             "self-healing) — comma list of kind@offset_s "
                             "entries fired against the fleet during one "
                             "open-loop run at the top swept rate.  "
                             "Kinds: 'kill' (replica engine crashes: "
                             "quarantine + rebuild + in-flight failover), "
                             "'wedge' (replica hangs: watchdog detection "
                             "+ reclaim), 'vendor' (a flaky RemoteBackend "
                             "outage burst: circuit breaker opens, "
                             "traffic sheds to a local sibling, half-"
                             "open probe re-closes).  The record gains a "
                             "'recovery' block: detection/restart "
                             "latency, requests failed-over vs lost "
                             "(lost must be 0).  Example: "
                             "'kill@1.0,wedge@2.5,vendor@0'")
    parser.add_argument("--serve-load-roles", metavar="prefill:N,decode:M",
                        default="",
                        help="--serve-load pool companion: also measure a "
                             "DISAGGREGATED roster — N prefill-specialist "
                             "replicas (chunked prefill + position-0 "
                             "scan, finished KV slabs handed off) and M "
                             "decode-specialist replicas (slot rings fed "
                             "by imported slabs) of the sweep snapshot, "
                             "through the SAME rate sweep, as an extra "
                             "'serve_load_pool' configuration tagged with "
                             "its role composition.  Compare its knee "
                             "against the symmetric single-model-x(N+M) "
                             "roster at equal replica count (obs "
                             "bench-diff aligns rosters by role tag).  "
                             "Empty = symmetric rosters only")
    parser.add_argument("--no-slot-admission", action="store_true",
                        help="serve legs: disable slot-level mid-decode "
                             "admission (SchedulerConfig.slot_admission, "
                             "default ON since replay bit-parity was "
                             "pinned) and launch only at coalescer "
                             "boundaries — the A/B escape hatch")
    parser.add_argument("--strict", action="store_true",
                        help="arm strict mode (runtime/strict.py, same as "
                             "LLM_INTERP_STRICT=1): transfer-guard the "
                             "scoring pipeline and count XLA recompiles; "
                             "the record gains a 'strict' block with the "
                             "recompile_events / blocked_transfers "
                             "telemetry counters so the measured operating "
                             "point is auditable")
    parser.add_argument("--trace", nargs="?", const="bench_trace.json",
                        default=None, metavar="PATH",
                        help="span tracing (obs/): record the hot path's "
                             "phase spans (tokenize, prefill, "
                             "extend_prefill, decode, pooled decode, d2h "
                             "fetch — tagged by leg/bucket/batch), export "
                             "a Perfetto-loadable Chrome trace to PATH "
                             "(default bench_trace.json) plus a JSONL span "
                             "log at PATH.spans.jsonl, and attach a "
                             "'phases' block decomposing the measured "
                             "wall-clock per phase (and per leg in "
                             "sweep-full) to the JSON record; "
                             "measurement-only, strict-safe")
    parser.add_argument("--trace-sync", action="store_true",
                        help="with --trace: opt-in block_until_ready at "
                             "phase-span close for per-phase DEVICE time "
                             "attribution — deliberately serializes the "
                             "async-dispatch overlap, so throughput "
                             "numbers from a sync-traced run are NOT "
                             "operating-point measurements")
    parser.add_argument("--profile", metavar="DIR", default=None,
                        help="windowed jax.profiler capture (obs/"
                             "profiler.py): capture repeat 0 of the sweep "
                             "modes into DIR (TensorBoard/Perfetto "
                             "viewable; headless analysis via "
                             "utils/profiling.top_device_ops)")
    parser.add_argument("--metrics", nargs="?", const="bench_metrics.jsonl",
                        default=None, metavar="PATH",
                        help="streaming JSONL metrics log (obs/"
                             "metrics.py): one sample per sweep heartbeat "
                             "+ one per finished repeat — telemetry "
                             "counters (raw + since-start delta), "
                             "sample-ring percentiles, progress gauges — "
                             "to PATH (default bench_metrics.jsonl); "
                             "forwarded to the sweep-full child with a "
                             "child-specific path like --trace")
    parser.add_argument("--microbatch", type=int, default=1, metavar="N",
                        help="split the batch into N independent chunks "
                             "inside the jit so XLA can overlap one chunk's "
                             "VPU-bound attention softmax with another's "
                             "MXU-bound projections")
    args = parser.parse_args()

    if args.decode == 0:
        # old CLI: --decode 0 was the single-forward fast path
        if args.mode not in (None, "single"):
            parser.error(f"--decode 0 selects the single-forward path and "
                         f"contradicts --mode {args.mode}; drop one")
        args.mode = "single"
        args.decode = 10
    if args.mode is None:
        if os.path.exists(args.perturbations):
            args.mode = "sweep"
        else:
            # same `python bench.py` reports a DIFFERENT metric when the
            # corpus is absent — say so, like the other auto-switches
            print(f"# perturbation corpus {args.perturbations} not found; "
                  f"falling back to --mode parity (synthetic steady-state "
                  f"metric, not the e2e sweep)", file=sys.stderr)
            args.mode = "parity"
    if not 0.0 <= args.decided_frac <= 1.0:
        parser.error("--decided-frac must be within [0, 1]")
    if args.pipeline_depth is None:
        args.pipeline_depth = 2 if args.mode == "sweep-full" else 4
    # The full-study mode measures the documented PR-5 operating point by
    # default (int8 KV + 128-token chunked prefill — the planner's
    # batch >= 320 fit prediction BENCH_r06 exists to confirm); every
    # other mode keeps the bf16 bit-parity default.  full_* carry the
    # full-study resolution for the sweep mode's child re-exec, so a
    # plain `python bench.py` measures its full-study secondary at the
    # same operating point a direct --mode sweep-full run would.
    args.full_kv_dtype = args.kv_dtype if args.kv_dtype is not None else "int8"
    args.full_prefill_chunk = (args.prefill_chunk
                               if args.prefill_chunk is not None else 128)
    if args.mode == "sweep-full":
        if args.kv_dtype is None or args.prefill_chunk is None:
            print(f"# sweep-full operating point: kv-dtype "
                  f"{args.full_kv_dtype}, prefill-chunk "
                  f"{args.full_prefill_chunk} (pass --kv-dtype/"
                  f"--prefill-chunk to override)", file=sys.stderr)
        args.kv_dtype = args.full_kv_dtype
        args.prefill_chunk = args.full_prefill_chunk
    else:
        args.kv_dtype = args.kv_dtype or "bf16"
        args.prefill_chunk = args.prefill_chunk or 0
    if args.mode in ("parity", "sweep", "sweep-packed") and args.microbatch > 1:
        parser.error("--microbatch applies to the single/decode modes; the "
                     "parity/sweep decode slice is sized from the full batch")
    if args.mode == "sweep-packed" and not (getattr(args, "packed", 0) or 0):
        parser.error("--mode sweep-packed needs --packed >= 1 (questions "
                     "per packed row)")
    if args.mode == "sweep-packed" and args.eos_mode == "typical":
        parser.error("--eos-mode typical does not apply to --mode "
                     "sweep-packed: the packed path has no decode at all "
                     "(anchor gather inside one prefill program), so "
                     "there is no early stop to bracket")
    if args.serve_replay and args.mode != "sweep":
        parser.error("--serve-replay rides the sweep mode's offline rows "
                     "(row-parity needs them); use --mode sweep")
    if args.serve_load and args.mode != "sweep":
        parser.error("--serve-load rides the sweep mode's offline rows "
                     "(the parity reference and the auto-rate anchor); "
                     "use --mode sweep")
    if args.serve_load and args.serve_load_rates != "auto":
        rates = [r for r in args.serve_load_rates.split(",") if r.strip()]
        if len(rates) < 3:
            parser.error("--serve-load-rates needs >= 3 offered rates "
                         "to bracket a knee (or 'auto')")
    if getattr(args, "serve_load_roles", ""):
        if not args.serve_load:
            parser.error("--serve-load-roles is a --serve-load pool "
                         "configuration; add --serve-load")
        if getattr(args, "serve_load_replicas", 0) <= 1:
            parser.error("--serve-load-roles rides the pool companion; "
                         "--serve-load-replicas must be >= 2 so the "
                         "symmetric roster exists to compare against")
        try:
            _parse_roles_spec(args.serve_load_roles)  # fail fast, not
        except ValueError as err:                     # after the sweep
            parser.error(str(err))

    import jax
    import jax.numpy as jnp

    from llm_interpretation_replication_tpu.runtime import strict as strict_mod

    if args.strict:
        strict_mod.activate()
    else:
        strict_mod.activate_from_env()

    if args.metrics:
        # streaming metrics log (obs/metrics.py): one JSON sample per
        # sweep heartbeat / finished repeat; a crashed run keeps every
        # line already flushed, like the span log
        from llm_interpretation_replication_tpu.obs import (
            metrics as metrics_mod,
        )

        metrics_mod.enable_jsonl(args.metrics)
        print(f"# obs: metrics log streaming to {args.metrics}",
              file=sys.stderr)

    if args.trace:
        # span tracing (obs/): armed for the whole run; the Chrome trace
        # exports at interpreter exit so every return path below is
        # covered, and the JSONL span log streams as spans close (a
        # crashed run still leaves its spans on disk)
        import atexit

        from llm_interpretation_replication_tpu import obs as obs_mod

        obs_mod.enable(jsonl_path=args.trace + ".spans.jsonl",
                       sync=args.trace_sync, memory=True)

        def _export_trace():
            path = obs_mod.export_chrome(args.trace)
            print(f"# obs: trace written to {path} (span log "
                  f"{args.trace}.spans.jsonl; view in Perfetto or "
                  f"'obs report --trace {args.trace}.spans.jsonl')",
                  file=sys.stderr)

        atexit.register(_export_trace)
    elif args.mode in ("sweep", "sweep-full", "sweep-packed"):
        # phases-by-default: the sweep records' `phases` decomposition
        # (ISSUE-7 acceptance: BENCH_r06 ships with the block attached)
        # must not depend on remembering --trace — arm the in-memory span
        # tracer alone: no JSONL stream, no Chrome export, no per-span
        # memory snapshots, so the overhead is the no-op-span epsilon the
        # obs overhead smoke test already bounds
        from llm_interpretation_replication_tpu import obs as obs_mod

        obs_mod.enable()

    def _attach_strict(record):
        """Append the strict-mode audit block (recompile_events /
        blocked_transfers) to a bench JSON record when armed."""
        if strict_mod.strict_enabled():
            record["strict"] = strict_mod.strict_report()
        return record

    # Persistent compilation cache: programs at sweep shapes take 1.5-4 min
    # EACH to compile through the remote-compile helper and are recompiled
    # per process otherwise — across bench invocations on the same machine
    # the cache turns a ~25-minute warmup into seconds.  Env-gated via
    # LLM_INTERP_COMPILE_CACHE (a path relocates it, 0/off disables); the
    # repo-local .jax_cache is the default.
    from llm_interpretation_replication_tpu.runtime.loader import (
        enable_compile_cache,
    )

    cache_dir = enable_compile_cache(
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache"))
    if cache_dir is None:
        print("# compilation cache disabled/unavailable; repeat 0 pays "
              "full compiles", file=sys.stderr)

    from llm_interpretation_replication_tpu.models.config import DecoderConfig
    from llm_interpretation_replication_tpu.models.decoder import (
        forward_last_logits,
        greedy_decode,
    )
    from llm_interpretation_replication_tpu.runtime.plan import resolve_scoring_plan
    from llm_interpretation_replication_tpu.scoring.yes_no import relative_prob_first_token

    geometry = FALCON_7B if args.model == "falcon-7b" else SMALL_1B
    cfg = DecoderConfig(**geometry, attention_impl=args.attn)

    # bf16 7B weights (~13 GB) leave no HBM for the dense S×T attention
    # scores at sweep batches on a 16 GB chip — the Pallas flash kernel
    # streams them in blocks and is the only path that fits, with the batch
    # clamped for activation headroom (measured 2026-07: dense OOMs at batch
    # 64-192; flash 21.2 p/s at batch 64, OOM above).  The routing decision
    # is the shared library one (runtime/plan.py), regression-pinned in
    # tests/test_runtime.py.
    plan = resolve_scoring_plan(
        cfg, args.quant, args.batch, args.seq,
        requested_impl="flash" if args.attn == "flash" else None,
    )
    if plan.attention_impl != args.attn:
        print(f"# --quant {args.quant} on {args.model}: {plan.reason}; "
              f"switching to --attn {plan.attention_impl}", file=sys.stderr)
        args.attn = plan.attention_impl
        cfg = DecoderConfig(**geometry, attention_impl=args.attn)
    if plan.batch != args.batch:
        print(f"# clamping --batch {args.batch} -> {plan.batch} "
              f"({plan.reason})", file=sys.stderr)
        args.batch = plan.batch

    dtype = jnp.bfloat16

    use_quant = args.quant == "int8"
    try:
        params = init_params(cfg, jax.random.PRNGKey(0), dtype, quant=use_quant)
        np.asarray(params["final_ln"]["scale"][0])  # sync (see NOTE below)
    except Exception as err:  # HBM too small for 7B on this chip: drop down
        if args.model == "falcon-7b":
            print(f"# falcon-7b init failed ({err}); falling back to small-1b", file=sys.stderr)
            args.model = "small-1b"
            cfg = DecoderConfig(**SMALL_1B, attention_impl=args.attn)
            params = init_params(cfg, jax.random.PRNGKey(0), dtype, quant=use_quant)
            np.asarray(params["final_ln"]["scale"][0])
        else:
            raise

    from llm_interpretation_replication_tpu.models.decoder import (
        cache_kv_map,
        decode_steps,
    )
    from llm_interpretation_replication_tpu.runtime.engine import (
        _pad_slice,
        _prefill_select,
    )
    from llm_interpretation_replication_tpu.scoring.yes_no import (
        first_token_scan,
        yes_no_from_scores,
    )

    yes_id, no_id = 5, 9
    look = max(1, args.decode)

    def phase2_geometry(batch, decided_frac):
        """(n_undec, pool_every, sub): undecided rows per batch, prefills
        per pooled decode, and the menu-padded pooled slice size."""
        n_undec = max(1, round(batch * (1.0 - decided_frac)))
        pool_every = max(1, int(round(batch / n_undec)))
        sub = _pad_slice(min(pool_every * n_undec, batch), batch)
        return n_undec, pool_every, sub

    def steady_setup(batch, seq, prompt_tokens, decided_frac):
        """Inputs + score fns for the synthetic steady-state modes at a
        given operating point (batch, bucket length, real-token count)."""
        rng = np.random.default_rng(0)
        ids = jnp.asarray(rng.integers(
            10, cfg.vocab_size - 10, size=(batch, seq)).astype(np.int32))
        m = np.zeros((batch, seq), np.int32)
        m[:, :prompt_tokens] = 1
        mask = jnp.asarray(m)
        # Two-phase parity mode, exactly the engine's pooled+selected path
        # (runtime/engine._score_decoder_pooled): each batch runs
        # _prefill_select — prefill + position-0 scan + IN-PROGRAM selection
        # of a ``sel_m``-row undecided-first cache slice, so the full KV
        # cache never materializes (measured 106 ms/batch just to emit it) —
        # and ONE pooled ``sub``-row scored decode runs every ``pool_every``
        # prefills (decode is weight-streaming-bound; amortize it).
        _, pool_every, sub = phase2_geometry(batch, decided_frac)
        sel_m = _pad_slice(max(8, batch // 4), batch)
        valid_rows = jnp.ones((batch,), bool)
        yes_arr = jnp.full((batch,), yes_id, jnp.int32)
        no_arr = jnp.full((batch,), no_id, jnp.int32)

        def score_prefill(params, ids, mask):
            scan0, _first3, _sel, sub_cache, last_s, len_s = _prefill_select(
                params, cfg, ids, mask, valid_rows, yes_arr, no_arr,
                cache_len=ids.shape[1], slice_m=sel_m, top_k=5,
            )
            return scan0[2], sub_cache, last_s, len_s

        def score_pooled_decode(params, sub_cache, last_s, len_s):
            # Pool flush: concatenate accumulated slices up to ``sub`` rows
            # (modeled by tiling the latest slice — identical shapes/bytes
            # to the engine's cross-batch concat) and run ONE scored decode.
            # Tiling routes through cache_kv_map so an int8 slice's scales
            # would tile with the codes (G07 — the scale-awareness rule).
            reps = -(-sub // sel_m)
            cache = cache_kv_map(
                sub_cache,
                lambda x: jnp.concatenate([x] * reps, axis=1)[:, :sub],
                positions=jnp.concatenate(
                    [sub_cache.positions] * reps, axis=0)[:sub],
                valid=jnp.concatenate([sub_cache.valid] * reps, axis=0)[:sub],
            )
            last = jnp.concatenate([last_s] * reps, axis=0)[:sub]
            lens = jnp.concatenate([len_s] * reps, axis=0)[:sub]
            _, sc, _, _, _ = decode_steps(params, cfg, cache, last,
                                          lens, jnp.int32(0), look,
                                          None, None, with_scores=True)
            res = yes_no_from_scores(sc, yes_id, no_id)
            return res.relative_prob

        score_parity = (score_prefill, score_pooled_decode, pool_every)

        def score_decode(params, ids, mask):
            # worst case: every row takes the scored MAX_LOOK_AHEAD decode
            _, logits = greedy_decode(params, cfg, ids, mask, look)
            return relative_prob_first_token(logits[:, 0, :], yes_id, no_id)

        def score_single(params, ids, mask):
            logits = forward_last_logits(params, cfg, ids, mask)
            return relative_prob_first_token(logits, yes_id, no_id)

        return ids, mask, sub, {"parity": score_parity,
                                "decode": score_decode,
                                "single": score_single}

    def with_microbatch(score_one, batch):
        if args.microbatch <= 1:
            return score_one
        if batch % args.microbatch:
            parser.error(f"--batch {batch} not divisible by "
                         f"--microbatch {args.microbatch}")
        chunk = batch // args.microbatch

        def score(params, ids, mask):
            outs = [
                score_one(params, ids[i * chunk:(i + 1) * chunk],
                          mask[i * chunk:(i + 1) * chunk])
                for i in range(args.microbatch)
            ]
            return tuple(jnp.concatenate(parts) for parts in zip(*outs))
        return score

    def measure(mode, iters, repeats, batch=None, seq=None, prompt_tokens=None,
                decided_frac=None):
        """Best-of-N repeats: the tunneled chip is occasionally contended
        (same code measured 13-36 p/s across runs); the minimum per-step time
        is the uncontended hardware number the sweep actually achieves."""
        batch = batch or args.batch
        ids, mask, _, fns = steady_setup(
            batch, seq or args.seq, prompt_tokens or args.prompt_tokens,
            args.decided_frac if decided_frac is None else decided_frac)
        # NOTE: on the axon-tunneled chip, block_until_ready does NOT
        # actually block; a host fetch does.  Sync via np.asarray of a
        # scalar slice.
        if mode == "parity":
            f_prefill, f_decode, pool_every = fns[mode]
            f_prefill = jax.jit(f_prefill)
            f_decode = jax.jit(f_decode)
            # round iterations UP to whole pool windows so the timing
            # carries exactly iters/pool_every pooled decodes
            iters = max(pool_every, ((iters + pool_every - 1)
                                     // pool_every) * pool_every)
            out = f_prefill(params, ids, mask)
            dec = f_decode(params, *out[1:])
            np.asarray(out[0][0]), np.asarray(dec[0])  # compile + sync
            dt = float("inf")
            for _ in range(max(1, repeats)):
                t0 = time.perf_counter()
                for i in range(iters):
                    out = f_prefill(params, ids, mask)
                    if (i + 1) % pool_every == 0:
                        dec = f_decode(params, *out[1:])
                np.asarray(out[0][0]), np.asarray(dec[0])  # drain queue
                dt = min(dt, (time.perf_counter() - t0) / iters)
            return batch / dt
        score_jit = jax.jit(with_microbatch(fns[mode], batch))
        out = score_jit(params, ids, mask)
        np.asarray(jax.tree_util.tree_leaves(out)[0][0])  # compile + sync
        dt = float("inf")
        for _ in range(max(1, repeats)):
            t0 = time.perf_counter()
            for _ in range(iters):
                out = score_jit(params, ids, mask)
            np.asarray(jax.tree_util.tree_leaves(out)[0][0])  # drain queue
            dt = min(dt, (time.perf_counter() - t0) / iters)
        return batch / dt

    def describe(mode, batch=None, seq=None, prompt_tokens=None,
                 decided_frac=None, extra=""):
        batch = batch or args.batch
        frac = args.decided_frac if decided_frac is None else decided_frac
        _, pool_every, sub = phase2_geometry(batch, frac)
        tags = {
            "parity": (f"two-phase {args.decode}-step look-ahead, "
                       f"{int(round(frac * 100))}% rows decided "
                       f"at position 0, pooled {sub}-row decode every "
                       f"{pool_every} batches"),
            "decode": f"{args.decode}-token look-ahead decode, all rows",
            "single": "single forward",
        }
        return (f"prompts/sec/chip (yes-no scoring sweep, {args.model} geometry, "
                f"{'w8a8 int8' if args.quant == 'int8' else 'bf16'}, "
                f"batch {batch}, {prompt_tokens or args.prompt_tokens}-token prompts, "
                + tags.get(mode, mode) + extra
                + (f", attn={args.attn}" if args.attn != "xla" else "")
                + (f", microbatch={args.microbatch}" if args.microbatch > 1 else "")
                + ")")

    if args.mode in ("sweep", "sweep-full", "sweep-packed"):
        # The sweep runs at --sweep-batch on the real ~107-token prompts
        # (256-token worst bucket: the longest rephrasing is 203 tokens) —
        # plan THAT operating point, not the parity mode's 432-token one.
        # The full-study mode plans with the completion path's pinned
        # caches/score buffers included (measured: batch 256 OOMs there).
        # The packed mode plans at the PACKED row length (Q questions +
        # demonstrations per row — runtime/plan_search.packed_seq_tokens).
        if args.plan_search:
            # the auto-parallel search replaces the fixed operating point:
            # the CHOSEN candidate's batch/kv-dtype/chunk/pool override the
            # flags, the ranked runner-up table lands in the record, and a
            # prediction miss on hardware falls down the PR-1 OOM ladder
            # like any other wrong prediction (_sweep_oom_action)
            from llm_interpretation_replication_tpu.runtime.plan_search import (
                chosen_plan,
                format_candidate_table,
                plan_search_record,
                search_plans,
            )

            workload = {"sweep-full": "full",
                        "sweep-packed": "packed"}.get(args.mode, "binary")
            ranked = search_plans(
                cfg, args.quant, n_devices=1, seq=256, workload=workload,
                batches=tuple(range(32, max(512, args.sweep_batch) + 1,
                                    32)),
                pipeline_depth=args.pipeline_depth,
                # a --attn flash run must be priced as flash (the fp32
                # output workspace), not as the dense score tensor the
                # flash kernel never materializes
                attention_impl=args.attn,
                slot_repack=getattr(args, "slot_repack", True))
            best = chosen_plan(ranked)
            print(format_candidate_table(ranked), file=sys.stderr)
            if best is None:
                print("# plan search: no candidate fits; falling back to "
                      "the fixed operating point", file=sys.stderr)
            else:
                args.plan_search_report = plan_search_record(ranked)
                args.sweep_batch = best.batch
                args.kv_dtype = best.kv_dtype
                args.prefill_chunk = best.prefill_chunk
                # unconditional: pool_target 0 IS part of the chosen plan
                # (pool at batch size) — letting a user flag survive here
                # would run a different pool than the record names
                args.pool_target = best.pool_target
                args.fit_decision = best.reason
                args.predicted_batch = best.batch
                if workload == "packed":
                    # the packing factor is part of the chosen plan too
                    args.packed = best.packing
                if workload == "full":
                    # the priced K axis (ISSUE 13): the chosen block size
                    # overrides --decode-k like every other plan knob, and
                    # predicted_k rides into the k_decode block so the
                    # record names prediction vs configuration
                    args.decode_k = best.decode_k
                    args.predicted_k = best.decode_k
                print(f"# plan search: running chosen plan batch "
                      f"{best.batch} kv {best.kv_dtype} chunk "
                      f"{best.prefill_chunk} pool "
                      f"{best.pool_target or 'batch'} "
                      + (f"packing {best.packing} "
                         if workload == "packed" else "")
                      + (f"decode-k {best.decode_k} "
                         if best.decode_k > 1 else "")
                      + f"({best.predicted_rows_per_s:.1f} predicted "
                      f"rows/s)", file=sys.stderr)
        sweep_plan = None
        if getattr(args, "plan_search_report", None):
            pass  # operating point chosen above; skip the fixed resolve
        elif args.mode == "sweep-full":
            from llm_interpretation_replication_tpu.runtime.engine import (
                EngineConfig,
            )
            from llm_interpretation_replication_tpu.runtime.plan import (
                resolve_full_sweep_plan,
            )
            sweep_plan = resolve_full_sweep_plan(
                cfg, args.quant, args.sweep_batch, 256,
                pipeline_depth=args.pipeline_depth,
                requested_impl="flash" if args.attn == "flash" else None,
                # the engine run_sweep_full_mode builds uses EngineConfig's
                # default scan top-k; a custom top_k beyond ReducedScores'
                # kept candidates makes the engine stack full fp32 score
                # tensors, which the plan must budget (plan.py)
                top_k=EngineConfig().top_k,
                # kv-dtype-aware cache terms + the chunked-prefill
                # activation bound — the planner PREDICTS the int8-KV
                # operating point instead of discovering it by OOM
                kv_dtype=args.kv_dtype, prefill_chunk=args.prefill_chunk,
                # the pooled-confidence cache term (ISSUE 7): the fit
                # decision carries the pool's no-retirement worst-case
                # peak, so the prediction names the configuration the
                # engine actually runs.  pool_target=None lets the
                # planner price the pool at whatever batch it FITS —
                # with no explicit --pool-target the engine pools at its
                # own (clamped) batch_size, not the requested one
                pooled_confidence=args.pooled_confidence,
                pool_target=args.pool_target or None,
                slot_repack=getattr(args, "slot_repack", True),
            )
        elif args.mode == "sweep-packed":
            from llm_interpretation_replication_tpu.runtime.plan_search import (
                packed_seq_tokens,
            )

            # packed rows are Q questions long: budget the REAL row
            # length, not the isolated 256-token worst bucket (dense
            # attention is quadratic in it)
            sweep_plan = resolve_scoring_plan(
                cfg, args.quant, args.sweep_batch,
                packed_seq_tokens(max(1, args.packed or 1)),
                requested_impl="flash" if args.attn == "flash" else None,
                prefill_chunk=0,
            )
        else:
            sweep_plan = resolve_scoring_plan(
                cfg, args.quant, args.sweep_batch, 256,
                requested_impl="flash" if args.attn == "flash" else None,
                # NO chunk discount here: the binary sweep runs the pooled
                # phase-2 path, whose _prefill_select program keeps
                # monolithic prefill by design (EngineConfig.prefill_chunk
                # docstring) — budgeting the chunked bound would predict a
                # fit the actual program cannot run
                prefill_chunk=0,
            )
        # auditable fit decision: the planner's reason string and predicted
        # batch land in the JSON record's context block, and the OOM
        # ladder prints predicted-vs-actual when the prediction was wrong
        # on hardware (_sweep_oom_action)
        if sweep_plan is not None:
            args.fit_decision = sweep_plan.reason
            args.predicted_batch = sweep_plan.batch
            if sweep_plan.batch != args.sweep_batch or (
                    sweep_plan.attention_impl != args.attn):
                print(f"# sweep plan: {sweep_plan.reason}; batch "
                      f"{args.sweep_batch} -> {sweep_plan.batch}, attn "
                      f"{args.attn} -> {sweep_plan.attention_impl}",
                      file=sys.stderr)
                args.sweep_batch = sweep_plan.batch
                if sweep_plan.attention_impl != args.attn:
                    args.attn = sweep_plan.attention_impl
                    cfg = DecoderConfig(**geometry, attention_impl=args.attn)
        if args.mode == "sweep-packed":
            qps, rate, out_path = run_sweep_packed_mode(args, cfg, params)
            print(f"# sweep-packed workbook: {out_path}", file=sys.stderr)
            record = {
                "metric": (
                    f"questions/sec/chip (packed batch prompting, "
                    f"Q={args.packed} questions per prefill row with "
                    f"Auto-Demo demonstrations, anchor-gathered binary "
                    f"leg via the real packed sweep shell; {args.model} "
                    f"geometry, "
                    f"{'w8a8 int8' if args.quant == 'int8' else 'bf16'}, "
                    f"batch {args.sweep_batch} packed rows, measured "
                    f"position-0 hit rate {rate:.2f})"
                ),
                "value": round(qps, 2),
                "unit": "questions/sec",
                "vs_baseline": round(qps / A100_BASELINE_PROMPTS_PER_SEC, 2),
            }
            if getattr(args, "packed_drift", None):
                # the drift-parity block is a first-class result (ISSUE
                # 10): |Δ relative_prob| distribution + flip rate of
                # packed vs isolated judgments
                record["packed_drift"] = args.packed_drift
            record.update(_repeat_report(args))
            record.update(_operating_context(args))
            if getattr(args, "plan_search_report", None):
                record["plan_search"] = args.plan_search_report
            record.update(getattr(args, "phases_report", None) or {})
            print(json.dumps(_attach_strict(record)))
            return
        if args.mode == "sweep-full":
            rps, rate, out_path = run_sweep_full_mode(args, cfg, params)
            print(f"# sweep-full workbook: "
                  f"{out_path or 'unavailable (removed by a failed repeat)'}",
                  file=sys.stderr)
            record = _full_study_record(args, rps, rate)
            print(json.dumps(_attach_strict(record)))
            return
        pps, rate, out_path = run_sweep_mode(args, cfg, params)
        print(f"# sweep workbook: {out_path}", file=sys.stderr)
        # the bracket tag folds into the metric text so bench-diff's
        # alignment key (obs/benchdiff._shape_tags) can never
        # cross-compare an EOS-typical sweep with a no-EOS one
        sweep_bracket = (", EOS-typical decode bracket"
                         if args.eos_mode == "typical" else "")
        record = {
            "metric": (
                f"prompts/sec/chip (END-TO-END 10k-perturbation scoring "
                f"sweep on real perturbations.json texts: tokenize + "
                f"bucketing + two-phase engine + row building + xlsx "
                f"checkpoints; {args.model} geometry, "
                f"{'w8a8 int8' if args.quant == 'int8' else 'bf16'}, "
                f"batch {args.sweep_batch}, measured position-0 hit rate "
                f"{rate:.2f}{sweep_bracket})"
            ),
            "value": round(pps, 2),
            "unit": "prompts/sec",
            "vs_baseline": round(pps / A100_BASELINE_PROMPTS_PER_SEC, 2),
        }
        record.update(_repeat_report(args))
        record.update(_operating_context(args))
        if getattr(args, "plan_search_report", None):
            record["plan_search"] = args.plan_search_report
        record.update(getattr(args, "phases_report", None) or {})
        if getattr(args, "occupancy_report", None):
            # slot-occupancy block (ROADMAP item 3) for the binary
            # sweep's pooled rings — same shape as the sweep-full one
            record["occupancy"] = args.occupancy_report
        if getattr(args, "serve_report", None):
            record["serve"] = args.serve_report
        if getattr(args, "serve_load_report", None):
            # the open-loop latency/throughput curve (ISSUE 11): per-rate
            # tail latency + phase anatomy + saturation estimate — the
            # yardstick the EnginePool fleet PR will be judged against
            record["serve_load"] = args.serve_load_report
        if getattr(args, "serve_load_pool_report", None):
            # the EnginePool fleet through the SAME harness (ISSUE 12):
            # one serve_load block per pool configuration
            # (single-model-xN replicas + the multi-model roster), with
            # per-replica health/plan notes
            record["serve_load_pool"] = args.serve_load_pool_report
            if args.serve_load_pool_report.get("recovery"):
                # fleet self-healing under --serve-load-faults (ISSUE
                # 16): detection/restart latency + failed-over vs lost —
                # top-level so bench-diff aligns it round over round
                record["recovery"] = (
                    args.serve_load_pool_report["recovery"])
        if getattr(args, "packed_report", None):
            # the packed-mode companion record (ISSUE 10): questions/s at
            # the packed operating point + the measured drift block
            # (|Δ relative_prob| distribution, flip rate) vs the isolated
            # headline rows
            record["packed"] = args.packed_report
        if not args.no_secondary:
            # (a) the steady-state device rate at the sweep's own dominant
            # operating point — the e2e number should be >=90% of this, the
            # rest is host-side cost the pipeline failed to overlap; (b) the
            # r01-r03 430-token parity + single headlines for
            # round-over-round continuity on the shared chip.
            sweep_kw = dict(batch=args.sweep_batch, seq=128, prompt_tokens=104,
                            decided_frac=rate)
            record["secondary"] = [
                {"metric": describe("parity", extra=", sweep operating point",
                                    **sweep_kw),
                 "value": round(measure("parity", max(4, args.iters // 2), 2,
                                        **sweep_kw), 2),
                 "unit": "prompts/sec"},
                {"metric": describe("parity"),
                 "value": round(measure("parity", max(4, args.iters // 2), 2), 2),
                 "unit": "prompts/sec"},
                {"metric": describe("single"),
                 "value": round(measure("single", max(4, args.iters // 2), 2), 2),
                 "unit": "prompts/sec"},
            ]
            # (c) the FULL-STUDY row contract (binary leg with 50-token
            # completions + confidence leg, all 15 columns via the real
            # sweep shell) — IN-PROCESS (ISSUE 12).  The r05-era fresh-
            # subprocess isolation is DELETED: run_sweep_mode now tears
            # its engine down (ScoringEngine.close — the verified-
            # teardown fix the workaround stood in for, VERDICT Missing
            # #3), so this leg's fresh engine starts from the torn-down
            # allocator the child process used to provide.  The 6x
            # in-process thrash (5.5 vs 31.4 rows/s on identical code)
            # is therefore expected GONE; the next driver-produced
            # record is the measured confirmation (PARITY.md
            # "Full-study secondary").  The --serve-load*/--serve-replay
            # harness flags still measure on the PARENT sweep's offline
            # rows only — the full-study leg measures the row contract,
            # not the serving harness (tests/test_bench.py pins this
            # decision).  Guarded so a full-study failure can never sink
            # the headline record.
            try:
                record["secondary"].append(
                    _full_study_secondary(args, cfg, geometry, params))
            except Exception as err:
                print(f"# full-study secondary failed ({err}); headline "
                      f"record unaffected", file=sys.stderr)
        print(json.dumps(_attach_strict(record)))
        return

    primary = measure(args.mode, args.iters, args.repeats)
    record = {
        "metric": describe(args.mode),
        "value": round(primary, 2),
        "unit": "prompts/sec",
        "vs_baseline": round(primary / A100_BASELINE_PROMPTS_PER_SEC, 2),
    }
    if args.mode == "parity" and not args.no_secondary:
        # Same run, same chip: the single-forward ceiling and the all-rows
        # decode floor, so BENCH_r{N}.json trends separate metric changes
        # from chip contention.
        record["secondary"] = [
            {"metric": describe(m), "value": round(v, 2), "unit": "prompts/sec"}
            for m, v in (
                ("single", measure("single", max(4, args.iters // 2), 2)),
                ("decode", measure("decode", max(4, args.iters // 2), 2)),
            )
        ]
    print(json.dumps(_attach_strict(record)))


if __name__ == "__main__":
    main()
