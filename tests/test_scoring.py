"""Behavioral tests of the scoring core against in-test oracles that follow
the reference semantics (run_base_vs_instruct_100q.py:279-392,
evaluate_closed_source_models.py:327-456, perturb_prompts_gpt.py:47-85,
evaluate_irrelevant_perturbations.py:190-265)."""

import math

import numpy as np
import pytest

import jax.numpy as jnp

from llm_interpretation_replication_tpu.scoring import (
    extract_final_number,
    extract_first_int,
    format_base_prompt,
    format_instruct_prompt,
    target_token_ids,
    top_candidates_from_scores,
    weighted_confidence_digits,
    weighted_confidence_single_tokens,
    yes_no_from_scores,
)


def oracle_yes_no(scores, yes_id, no_id, max_look_ahead=10, top_k=5):
    """Reference semantics, straightforward python."""
    def softmax(x):
        e = np.exp(x - x.max())
        return e / e.sum()

    for pos in range(min(max_look_ahead, scores.shape[0])):
        probs = softmax(scores[pos])
        top = np.argsort(-probs)[:top_k]
        if yes_id in top or no_id in top:
            return probs[yes_id], probs[no_id], pos, True
    probs = softmax(scores[0])
    return probs[yes_id], probs[no_id], 0, False


class TestYesNoScan:
    def test_matches_oracle_random(self):
        rng = np.random.default_rng(0)
        B, P, V = 16, 12, 50
        scores = rng.standard_normal((B, P, V)).astype(np.float32) * 3
        yes_id, no_id = 7, 11
        res = yes_no_from_scores(jnp.asarray(scores), yes_id, no_id)
        for b in range(B):
            ey, en, epos, efound = oracle_yes_no(scores[b], yes_id, no_id)
            assert res.found[b] == efound, b
            assert res.position[b] == epos, b
            np.testing.assert_allclose(res.yes_prob[b], ey, rtol=1e-5)
            np.testing.assert_allclose(res.no_prob[b], en, rtol=1e-5)
            expected_rel = ey / (ey + en) if ey + en > 0 else 0.5
            np.testing.assert_allclose(res.relative_prob[b], expected_rel, rtol=1e-5)

    def test_fallback_position_zero(self):
        # Yes/No never in top-5 anywhere -> fall back to position 0 probs
        V = 40
        scores = np.full((1, 12, V), -10.0, np.float32)
        scores[:, :, :6] = 5.0  # top-5 always tokens 0..5
        res = yes_no_from_scores(jnp.asarray(scores), 20, 21)
        assert not bool(res.found[0])
        assert int(res.position[0]) == 0

    def test_top_k_2(self):
        rng = np.random.default_rng(1)
        scores = rng.standard_normal((8, 10, 30)).astype(np.float32) * 2
        res = yes_no_from_scores(jnp.asarray(scores), 3, 4, top_k=2)
        for b in range(8):
            ey, en, epos, efound = oracle_yes_no(scores[b], 3, 4, top_k=2)
            assert res.found[b] == efound
            assert res.position[b] == epos

    def test_odds_ratio_inf_when_no_zero(self):
        scores = np.full((1, 1, 10), -100.0, np.float32)
        scores[0, 0, 2] = 50.0  # yes gets everything
        res = yes_no_from_scores(jnp.asarray(scores), 2, 3, max_look_ahead=1)
        assert np.isinf(float(res.odds_ratio[0]))

    def test_reduced_statistics_match_full_scores(self):
        """yes_no_from_reduced on decoder._reduce_step_scores statistics must
        reproduce yes_no_from_scores on the full [B, P, V] tensor — same
        found/position bits exactly, same probabilities to float tolerance —
        including per-row target ids and the EOS valid-steps cutoff."""
        import jax
        from llm_interpretation_replication_tpu.models import decoder as dmod
        from llm_interpretation_replication_tpu.scoring import (
            steps_until_eos, yes_no_from_reduced)

        rng = np.random.default_rng(7)
        B, P, V = 16, 10, 80
        scores = rng.standard_normal((B, P, V)).astype(np.float32) * 4
        yes_ids = rng.integers(0, V, B).astype(np.int32)
        no_ids = rng.integers(0, V, B).astype(np.int32)
        tokens = rng.integers(0, V, (B, P)).astype(np.int32)
        vs = steps_until_eos(jnp.asarray(tokens), eos_id=3)

        tgt = np.stack([yes_ids, no_ids], axis=1)
        red = jax.vmap(dmod._reduce_step_scores, in_axes=(1, None),
                       out_axes=(1, 1, 1, 1))(jnp.asarray(scores),
                                              jnp.asarray(tgt))
        vals, ids, logz, tlog = red
        for top_k in (2, 5):
            full = yes_no_from_scores(
                jnp.asarray(scores), yes_ids, no_ids, top_k=top_k,
                valid_steps=vs)
            reduced = yes_no_from_reduced(
                vals, logz, tlog, top_k=top_k, valid_steps=vs)
            np.testing.assert_array_equal(np.asarray(full.found),
                                          np.asarray(reduced.found))
            np.testing.assert_array_equal(np.asarray(full.position),
                                          np.asarray(reduced.position))
            for f in ("yes_prob", "no_prob", "relative_prob"):
                np.testing.assert_allclose(
                    np.asarray(getattr(full, f)),
                    np.asarray(getattr(reduced, f)), rtol=1e-5)
        # the kept candidates also ARE the confidence leg's top-19 contract
        from llm_interpretation_replication_tpu.runtime.engine import (
            _confidence_topk)
        clp, cidx = _confidence_topk(jnp.asarray(scores))
        np.testing.assert_array_equal(np.asarray(cidx),
                                      np.asarray(ids[:, :3, :]))
        np.testing.assert_allclose(
            np.asarray(clp),
            np.asarray(vals[:, :3, :] - logz[:, :3, None]), rtol=1e-5,
            atol=1e-6)

    def test_eos_truncates_scan_like_hf_generate(self):
        """HF generate stops at EOS, so the reference's scores list ends at
        the eos-emitting position; batched decode keeps forced-EOS positions
        that must be invisible to the scan (valid_steps)."""
        from llm_interpretation_replication_tpu.scoring import steps_until_eos

        V, eos = 30, 7
        # row 0: emits eos at step 1 -> 2 visible positions; a fat "yes" at
        # position 3 must NOT be seen (reference would have fallen back to 0)
        scores = np.full((2, 6, V), -10.0, np.float32)
        scores[:, :, :6] = 3.0             # top-5 = tokens 0..5, no yes/no
        scores[0, 3, 20] = 50.0            # invisible: after row-0's eos
        scores[1, 3, 20] = 50.0            # visible: row 1 never hits eos
        tokens = np.full((2, 6), 4, np.int32)
        tokens[0, 1] = eos
        tokens[0, 2:] = eos                # forced eos after done
        vs = steps_until_eos(jnp.asarray(tokens), eos)
        np.testing.assert_array_equal(np.asarray(vs), [2, 6])
        res = yes_no_from_scores(jnp.asarray(scores), 20, 21,
                                 valid_steps=vs)
        assert not bool(res.found[0]) and int(res.position[0]) == 0
        assert bool(res.found[1]) and int(res.position[1]) == 3
        # without the cutoff the phantom position would (wrongly) hit
        res_raw = yes_no_from_scores(jnp.asarray(scores), 20, 21)
        assert bool(res_raw.found[0])


class TestEndToEndAgainstTorchReference:
    """Tiny NeoX model: reference-style HF generate + python scan vs our
    one-program greedy decode + vectorized scan."""

    def test_pipeline_parity(self):
        torch = pytest.importorskip("torch")
        from transformers import GPTNeoXConfig, GPTNeoXForCausalLM

        from llm_interpretation_replication_tpu.models import config as mcfg
        from llm_interpretation_replication_tpu.models import convert as mconvert
        from llm_interpretation_replication_tpu.models import decoder

        hf_config = GPTNeoXConfig(
            vocab_size=128, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, intermediate_size=64, rotary_pct=0.25,
            max_position_embeddings=128,
        )
        torch.manual_seed(21)
        model = GPTNeoXForCausalLM(hf_config).eval()
        fam, cfg = mcfg.from_hf_config(hf_config)
        params = mconvert.convert(
            fam, mconvert.getter_from_torch_state_dict(model.state_dict()), cfg,
            dtype=jnp.float32,
        )
        rng = np.random.default_rng(2)
        yes_id, no_id = 5, 9
        prompts = [rng.integers(3, 128, size=n).astype(np.int32) for n in (9, 6, 12)]
        seq = max(len(p) for p in prompts)
        ids = np.zeros((len(prompts), seq), np.int32)
        mask = np.zeros_like(ids)
        for r, p in enumerate(prompts):
            ids[r, : len(p)] = p
            mask[r, : len(p)] = 1

        # ours: batched decode + vectorized scan
        _, batch_scores = decoder.greedy_decode(
            params, cfg, jnp.asarray(ids), jnp.asarray(mask), num_steps=10
        )
        ours = yes_no_from_scores(batch_scores, yes_id, no_id)

        # reference style: per-prompt HF generate + oracle scan
        for r, p in enumerate(prompts):
            with torch.no_grad():
                out = model.generate(
                    torch.tensor(p[None, :].astype(np.int64)), max_new_tokens=10,
                    do_sample=False, output_scores=True,
                    return_dict_in_generate=True, pad_token_id=0,
                )
            ref_scores = np.stack([s[0].float().numpy() for s in out.scores])
            ey, en, epos, efound = oracle_yes_no(ref_scores, yes_id, no_id)
            assert bool(ours.found[r]) == efound
            assert int(ours.position[r]) == epos
            np.testing.assert_allclose(float(ours.yes_prob[r]), ey, atol=1e-4)
            np.testing.assert_allclose(float(ours.no_prob[r]), en, atol=1e-4)


class TestWeightedConfidence:
    def test_single_tokens_simple(self):
        positions = [[("85", math.log(0.9)), ("90", math.log(0.1))]]
        got = weighted_confidence_single_tokens(positions)
        np.testing.assert_allclose(got, 85 * 0.9 + 90 * 0.1, rtol=1e-9)

    def test_single_tokens_filters_out_of_range(self):
        positions = [[("850", math.log(0.5)), ("42", math.log(0.5))]]
        got = weighted_confidence_single_tokens(positions)
        np.testing.assert_allclose(got, 42.0, rtol=1e-9)

    def test_digits_complete_tokens(self):
        positions = [[("85", math.log(0.6)), ("100", math.log(0.4))]]
        got = weighted_confidence_digits(positions)
        np.testing.assert_allclose(got, 85 * 0.6 + 100 * 0.4, rtol=1e-6)

    def test_digits_two_token_reconstruction(self):
        # first "5" (p=.5), "8" (p=.5); second "0" (p=.4, only digit)
        positions = [
            [("5", math.log(0.5)), ("8", math.log(0.5))],
            [("0", math.log(0.4)), ("x", math.log(0.6))],
        ]
        got = weighted_confidence_digits(positions)
        # 50:.2, 80:.2, 5:.3, 8:.3 -> weighted = 29.9
        np.testing.assert_allclose(got, 29.9, rtol=1e-6)

    def test_digits_100_chain(self):
        # "1"(p=.8) -> "0"(p=.9) -> "0"(p=.7): 100 with .504,
        # 10 with .8*.9*.3=.216, 1 alone with .8*.1=.08
        positions = [
            [("1", math.log(0.8)), ("y", math.log(0.2))],
            [("0", math.log(0.9)), ("z", math.log(0.1))],
            [("0", math.log(0.7)), ("w", math.log(0.3))],
        ]
        got = weighted_confidence_digits(positions)
        total = 0.504 + 0.216 + 0.08
        expected = (100 * 0.504 + 10 * 0.216 + 1 * 0.08) / total
        np.testing.assert_allclose(got, expected, rtol=1e-6)

    def test_digits_none_when_no_numbers(self):
        assert weighted_confidence_digits([[("a", -1.0)]]) is None
        assert weighted_confidence_digits([]) is None

    def test_from_model_scores(self):
        from helpers import build_test_tokenizer

        tok = build_test_tokenizer()
        v = tok.vocab_size if hasattr(tok, "vocab_size") else 300
        ids_85 = tok("85", add_special_tokens=False).input_ids
        scores = np.full((3, max(v, 300)), -20.0, np.float32)
        scores[0, ids_85[0]] = 5.0
        positions = top_candidates_from_scores(scores, tok, num_positions=3, top_k=19)
        got = weighted_confidence_digits(positions)
        assert got is not None

    def test_extract_first_int(self):
        assert extract_first_int("Confidence: 85 out of 100") == 85
        assert extract_first_int("no numbers") is None
        assert extract_first_int("") is None


class TestExtractFinalNumber:
    def test_marker_sandwich(self):
        assert extract_final_number("thinking...\n***\n20\n***") == 20.0

    def test_after_marker(self):
        assert extract_final_number("blah\n###\n42") == 42.0

    def test_standalone_line(self):
        assert extract_final_number("I reason a lot 123 times.\n77\n") == 77.0

    def test_last_number(self):
        assert extract_final_number("maybe 10 or rather 65 overall") == 65.0

    def test_digit_concat_fallback(self):
        assert extract_final_number("9 9") == 9.0  # last number wins over concat

    def test_empty(self):
        assert extract_final_number("") is None
        assert extract_final_number("none here") is None


class TestPromptsAndTargets:
    def test_prompt_formats(self):
        q = 'Is a "screenshot" a "photograph"?'
        base = format_base_prompt(q)
        assert base.startswith('Question: Is "soup" a "beverage"?')
        assert base.endswith(f"Question: {q} Answer either 'Yes' or 'No', without any other text.\nAnswer:")
        inst = format_instruct_prompt(q)
        assert inst == f"{q} Answer either 'Yes' or 'No', without any other text."
        bai = format_instruct_prompt(q, "baichuan-inc/Baichuan2-7B-Chat")
        assert bai.startswith("<human>: ") and bai.endswith("\n<bot>:")

    def test_target_token_ids_leading_space(self):
        from helpers import build_test_tokenizer

        tok = build_test_tokenizer()
        yes_id, no_id = target_token_ids(tok, ["Yes", "No"])
        # decoder-only convention: the id is for " Yes" (with space)
        assert yes_id == tok(" Yes", add_special_tokens=False).input_ids[0]
        assert no_id == tok(" No", add_special_tokens=False).input_ids[0]
        assert yes_id != tok("Yes", add_special_tokens=False).input_ids[0]
