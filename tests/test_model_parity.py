"""Logit-parity tests: our JAX decoder vs HF torch reference implementations.

For each family the reference sweeps (SURVEY.md §2.2), build a tiny random HF
model on CPU, convert its weights with models/convert.py, and require logits to
match to fp32 tolerance on ragged (right-padded) batches.  This is the
correctness gate that lets real 7B checkpoints load with confidence.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax.numpy as jnp  # noqa: E402

from llm_interpretation_replication_tpu.models import config as mcfg  # noqa: E402
from llm_interpretation_replication_tpu.models import convert as mconvert  # noqa: E402
from llm_interpretation_replication_tpu.models import decoder  # noqa: E402

VOCAB = 128


def _hf_logits(model, token_ids, attention_mask):
    with torch.no_grad():
        out = model(
            input_ids=torch.tensor(token_ids),
            attention_mask=torch.tensor(attention_mask),
        )
    return out.logits.float().numpy()


def _ours_logits(family, hf_config, state_dict, token_ids, attention_mask):
    fam, cfg = mcfg.from_hf_config(hf_config)
    assert fam == family
    get = mconvert.getter_from_torch_state_dict(state_dict)
    params = mconvert.convert(family, get, cfg, dtype=jnp.float32)
    logits = decoder.forward(
        params, cfg, jnp.asarray(token_ids), jnp.asarray(attention_mask)
    )
    return np.asarray(logits)


def _batch(rng, batch=3, seq=12):
    token_ids = rng.integers(3, VOCAB, size=(batch, seq)).astype(np.int32)
    attention_mask = np.ones((batch, seq), np.int32)
    # ragged right padding
    attention_mask[1, seq - 3 :] = 0
    token_ids[1, seq - 3 :] = 0
    attention_mask[2, seq - 5 :] = 0
    token_ids[2, seq - 5 :] = 0
    return token_ids, attention_mask


def _assert_close(ours, theirs, attention_mask, atol=2e-3):
    # compare only real positions; padded positions are unconstrained
    mask = attention_mask.astype(bool)
    np.testing.assert_allclose(ours[mask], theirs[mask], atol=atol, rtol=1e-3)


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(0)


def test_neox_parity(rng):
    from transformers import GPTNeoXConfig, GPTNeoXForCausalLM

    hf_config = GPTNeoXConfig(
        vocab_size=VOCAB, hidden_size=32, num_hidden_layers=3,
        num_attention_heads=4, intermediate_size=64, rotary_pct=0.25,
        max_position_embeddings=64, use_parallel_residual=True,
    )
    torch.manual_seed(0)
    model = GPTNeoXForCausalLM(hf_config).eval()
    ids, mask = _batch(rng)
    _assert_close(
        _ours_logits("neox", hf_config, model.state_dict(), ids, mask),
        _hf_logits(model, ids, mask),
        mask,
    )


def test_neox_nonparallel_residual(rng):
    from transformers import GPTNeoXConfig, GPTNeoXForCausalLM

    hf_config = GPTNeoXConfig(
        vocab_size=VOCAB, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64, rotary_pct=1.0,
        max_position_embeddings=64, use_parallel_residual=False,
    )
    torch.manual_seed(1)
    model = GPTNeoXForCausalLM(hf_config).eval()
    ids, mask = _batch(rng)
    _assert_close(
        _ours_logits("neox", hf_config, model.state_dict(), ids, mask),
        _hf_logits(model, ids, mask),
        mask,
    )


def test_falcon_mqa_parity(rng):
    from transformers import FalconConfig, FalconForCausalLM

    # falcon-7b geometry: multi_query=True, parallel_attn=True, no biases
    hf_config = FalconConfig(
        vocab_size=VOCAB, hidden_size=32, num_hidden_layers=3,
        num_attention_heads=4, multi_query=True, parallel_attn=True,
        new_decoder_architecture=False, bias=False, alibi=False,
    )
    torch.manual_seed(2)
    model = FalconForCausalLM(hf_config).eval()
    ids, mask = _batch(rng)
    _assert_close(
        _ours_logits("falcon", hf_config, model.state_dict(), ids, mask),
        _hf_logits(model, ids, mask),
        mask,
    )


def test_bloom_alibi_parity(rng):
    from transformers import BloomConfig, BloomForCausalLM

    hf_config = BloomConfig(
        vocab_size=VOCAB, hidden_size=32, n_layer=3, n_head=4,
    )
    torch.manual_seed(3)
    model = BloomForCausalLM(hf_config).eval()
    ids, mask = _batch(rng)
    _assert_close(
        _ours_logits("bloom", hf_config, model.state_dict(), ids, mask),
        _hf_logits(model, ids, mask),
        mask,
    )


def test_mistral_gqa_sliding_window_parity(rng):
    from transformers import MistralConfig, MistralForCausalLM

    hf_config = MistralConfig(
        vocab_size=VOCAB, hidden_size=32, num_hidden_layers=3,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=64,
        sliding_window=6, max_position_embeddings=64,
    )
    torch.manual_seed(4)
    model = MistralForCausalLM(hf_config).eval()
    ids, mask = _batch(rng, seq=16)
    _assert_close(
        _ours_logits("llama", hf_config, model.state_dict(), ids, mask),
        _hf_logits(model, ids, mask),
        mask,
    )


def test_llama_parity(rng):
    from transformers import LlamaConfig, LlamaForCausalLM

    hf_config = LlamaConfig(
        vocab_size=VOCAB, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=4, intermediate_size=64,
        max_position_embeddings=64, tie_word_embeddings=False,
    )
    torch.manual_seed(5)
    model = LlamaForCausalLM(hf_config).eval()
    ids, mask = _batch(rng)
    _assert_close(
        _ours_logits("llama", hf_config, model.state_dict(), ids, mask),
        _hf_logits(model, ids, mask),
        mask,
    )


def test_opt_parity(rng):
    from transformers import OPTConfig, OPTForCausalLM

    hf_config = OPTConfig(
        vocab_size=VOCAB, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, ffn_dim=64, max_position_embeddings=64,
        do_layer_norm_before=True, word_embed_proj_dim=32,
    )
    torch.manual_seed(6)
    model = OPTForCausalLM(hf_config).eval()
    ids, mask = _batch(rng)
    _assert_close(
        _ours_logits("opt", hf_config, model.state_dict(), ids, mask),
        _hf_logits(model, ids, mask),
        mask,
    )


def test_greedy_decode_matches_hf_generate(rng):
    """Our one-program greedy decode must reproduce HF ``generate`` token-for-
    token with per-step scores (the reference's MAX_LOOK_AHEAD scan input —
    run_base_vs_instruct_100q.py:337-358)."""
    from transformers import GPTNeoXConfig, GPTNeoXForCausalLM

    hf_config = GPTNeoXConfig(
        vocab_size=VOCAB, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64, rotary_pct=0.25,
        max_position_embeddings=64,
    )
    torch.manual_seed(7)
    model = GPTNeoXForCausalLM(hf_config).eval()
    ids = rng.integers(3, VOCAB, size=(1, 8)).astype(np.int32)
    mask = np.ones_like(ids)
    steps = 6

    with torch.no_grad():
        out = model.generate(
            torch.tensor(ids), max_new_tokens=steps, do_sample=False,
            output_scores=True, return_dict_in_generate=True,
            pad_token_id=0,
        )
    hf_tokens = out.sequences[0, ids.shape[1] :].numpy()
    hf_scores = np.stack([s[0].float().numpy() for s in out.scores])

    fam, cfg = mcfg.from_hf_config(hf_config)
    params = mconvert.convert(
        fam, mconvert.getter_from_torch_state_dict(model.state_dict()), cfg,
        dtype=jnp.float32,
    )
    tokens, scores = decoder.greedy_decode(
        params, cfg, jnp.asarray(ids), jnp.asarray(mask), num_steps=steps
    )
    np.testing.assert_array_equal(np.asarray(tokens)[0], hf_tokens)
    np.testing.assert_allclose(np.asarray(scores)[0], hf_scores, atol=2e-3, rtol=1e-3)


def test_greedy_decode_ragged_batch_matches_unpadded(rng):
    """Padding must not change a row's continuation: decode each row alone vs
    in a ragged batch."""
    from transformers import MistralConfig, MistralForCausalLM

    hf_config = MistralConfig(
        vocab_size=VOCAB, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=64,
        sliding_window=None, max_position_embeddings=64,
    )
    torch.manual_seed(8)
    model = MistralForCausalLM(hf_config).eval()
    fam, cfg = mcfg.from_hf_config(hf_config)
    params = mconvert.convert(
        fam, mconvert.getter_from_torch_state_dict(model.state_dict()), cfg,
        dtype=jnp.float32,
    )
    lens = [10, 7, 4]
    seq = max(lens)
    ids = np.zeros((3, seq), np.int32)
    mask = np.zeros((3, seq), np.int32)
    rows = []
    for r, ln in enumerate(lens):
        row = rng.integers(3, VOCAB, size=ln).astype(np.int32)
        rows.append(row)
        ids[r, :ln] = row
        mask[r, :ln] = 1
    btoks, _ = decoder.greedy_decode(params, cfg, jnp.asarray(ids), jnp.asarray(mask), num_steps=5)
    for r, row in enumerate(rows):
        stoks, _ = decoder.greedy_decode(
            params, cfg, jnp.asarray(row[None, :]), jnp.ones((1, len(row)), jnp.int32), num_steps=5
        )
        np.testing.assert_array_equal(np.asarray(btoks)[r], np.asarray(stoks)[0])
