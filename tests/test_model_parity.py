"""Logit-parity tests: our JAX decoder vs HF torch reference implementations.

For each family the reference sweeps (SURVEY.md §2.2), build a tiny random HF
model on CPU, convert its weights with models/convert.py, and require logits to
match to fp32 tolerance on ragged (right-padded) batches.  This is the
correctness gate that lets real 7B checkpoints load with confidence.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax.numpy as jnp  # noqa: E402

from llm_interpretation_replication_tpu.models import config as mcfg  # noqa: E402
from llm_interpretation_replication_tpu.models import convert as mconvert  # noqa: E402
from llm_interpretation_replication_tpu.models import decoder  # noqa: E402

VOCAB = 128


def _hf_logits(model, token_ids, attention_mask):
    with torch.no_grad():
        out = model(
            input_ids=torch.tensor(token_ids),
            attention_mask=torch.tensor(attention_mask),
        )
    return out.logits.float().numpy()


def _ours_logits(family, hf_config, state_dict, token_ids, attention_mask):
    fam, cfg = mcfg.from_hf_config(hf_config)
    assert fam == family
    get = mconvert.getter_from_torch_state_dict(state_dict)
    params = mconvert.convert(family, get, cfg, dtype=jnp.float32)
    logits = decoder.forward(
        params, cfg, jnp.asarray(token_ids), jnp.asarray(attention_mask)
    )
    return np.asarray(logits)


def _batch(rng, batch=3, seq=12):
    token_ids = rng.integers(3, VOCAB, size=(batch, seq)).astype(np.int32)
    attention_mask = np.ones((batch, seq), np.int32)
    # ragged right padding
    attention_mask[1, seq - 3 :] = 0
    token_ids[1, seq - 3 :] = 0
    attention_mask[2, seq - 5 :] = 0
    token_ids[2, seq - 5 :] = 0
    return token_ids, attention_mask


def _assert_close(ours, theirs, attention_mask, atol=2e-3):
    # compare only real positions; padded positions are unconstrained
    mask = attention_mask.astype(bool)
    np.testing.assert_allclose(ours[mask], theirs[mask], atol=atol, rtol=1e-3)


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(0)


def test_neox_parity(rng):
    from transformers import GPTNeoXConfig, GPTNeoXForCausalLM

    hf_config = GPTNeoXConfig(
        vocab_size=VOCAB, hidden_size=32, num_hidden_layers=3,
        num_attention_heads=4, intermediate_size=64, rotary_pct=0.25,
        max_position_embeddings=64, use_parallel_residual=True,
    )
    torch.manual_seed(0)
    model = GPTNeoXForCausalLM(hf_config).eval()
    ids, mask = _batch(rng)
    _assert_close(
        _ours_logits("neox", hf_config, model.state_dict(), ids, mask),
        _hf_logits(model, ids, mask),
        mask,
    )


def test_neox_nonparallel_residual(rng):
    from transformers import GPTNeoXConfig, GPTNeoXForCausalLM

    hf_config = GPTNeoXConfig(
        vocab_size=VOCAB, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64, rotary_pct=1.0,
        max_position_embeddings=64, use_parallel_residual=False,
    )
    torch.manual_seed(1)
    model = GPTNeoXForCausalLM(hf_config).eval()
    ids, mask = _batch(rng)
    _assert_close(
        _ours_logits("neox", hf_config, model.state_dict(), ids, mask),
        _hf_logits(model, ids, mask),
        mask,
    )


def test_falcon_mqa_parity(rng):
    from transformers import FalconConfig, FalconForCausalLM

    # falcon-7b geometry: multi_query=True, parallel_attn=True, no biases
    hf_config = FalconConfig(
        vocab_size=VOCAB, hidden_size=32, num_hidden_layers=3,
        num_attention_heads=4, multi_query=True, parallel_attn=True,
        new_decoder_architecture=False, bias=False, alibi=False,
    )
    torch.manual_seed(2)
    model = FalconForCausalLM(hf_config).eval()
    ids, mask = _batch(rng)
    _assert_close(
        _ours_logits("falcon", hf_config, model.state_dict(), ids, mask),
        _hf_logits(model, ids, mask),
        mask,
    )


def test_bloom_alibi_parity(rng):
    from transformers import BloomConfig, BloomForCausalLM

    hf_config = BloomConfig(
        vocab_size=VOCAB, hidden_size=32, n_layer=3, n_head=4,
    )
    torch.manual_seed(3)
    model = BloomForCausalLM(hf_config).eval()
    ids, mask = _batch(rng)
    _assert_close(
        _ours_logits("bloom", hf_config, model.state_dict(), ids, mask),
        _hf_logits(model, ids, mask),
        mask,
    )


def test_mistral_gqa_sliding_window_parity(rng):
    from transformers import MistralConfig, MistralForCausalLM

    hf_config = MistralConfig(
        vocab_size=VOCAB, hidden_size=32, num_hidden_layers=3,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=64,
        sliding_window=6, max_position_embeddings=64,
    )
    torch.manual_seed(4)
    model = MistralForCausalLM(hf_config).eval()
    ids, mask = _batch(rng, seq=16)
    _assert_close(
        _ours_logits("llama", hf_config, model.state_dict(), ids, mask),
        _hf_logits(model, ids, mask),
        mask,
    )


def test_llama_parity(rng):
    from transformers import LlamaConfig, LlamaForCausalLM

    hf_config = LlamaConfig(
        vocab_size=VOCAB, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=4, intermediate_size=64,
        max_position_embeddings=64, tie_word_embeddings=False,
    )
    torch.manual_seed(5)
    model = LlamaForCausalLM(hf_config).eval()
    ids, mask = _batch(rng)
    _assert_close(
        _ours_logits("llama", hf_config, model.state_dict(), ids, mask),
        _hf_logits(model, ids, mask),
        mask,
    )


def test_opt_parity(rng):
    from transformers import OPTConfig, OPTForCausalLM

    hf_config = OPTConfig(
        vocab_size=VOCAB, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, ffn_dim=64, max_position_embeddings=64,
        do_layer_norm_before=True, word_embed_proj_dim=32,
    )
    torch.manual_seed(6)
    model = OPTForCausalLM(hf_config).eval()
    ids, mask = _batch(rng)
    _assert_close(
        _ours_logits("opt", hf_config, model.state_dict(), ids, mask),
        _hf_logits(model, ids, mask),
        mask,
    )


def test_greedy_decode_matches_hf_generate(rng):
    """Our one-program greedy decode must reproduce HF ``generate`` token-for-
    token with per-step scores (the reference's MAX_LOOK_AHEAD scan input —
    run_base_vs_instruct_100q.py:337-358)."""
    from transformers import GPTNeoXConfig, GPTNeoXForCausalLM

    hf_config = GPTNeoXConfig(
        vocab_size=VOCAB, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64, rotary_pct=0.25,
        max_position_embeddings=64,
    )
    torch.manual_seed(7)
    model = GPTNeoXForCausalLM(hf_config).eval()
    ids = rng.integers(3, VOCAB, size=(1, 8)).astype(np.int32)
    mask = np.ones_like(ids)
    steps = 6

    with torch.no_grad():
        out = model.generate(
            torch.tensor(ids), max_new_tokens=steps, do_sample=False,
            output_scores=True, return_dict_in_generate=True,
            pad_token_id=0,
        )
    hf_tokens = out.sequences[0, ids.shape[1] :].numpy()
    hf_scores = np.stack([s[0].float().numpy() for s in out.scores])

    fam, cfg = mcfg.from_hf_config(hf_config)
    params = mconvert.convert(
        fam, mconvert.getter_from_torch_state_dict(model.state_dict()), cfg,
        dtype=jnp.float32,
    )
    tokens, scores = decoder.greedy_decode(
        params, cfg, jnp.asarray(ids), jnp.asarray(mask), num_steps=steps
    )
    np.testing.assert_array_equal(np.asarray(tokens)[0], hf_tokens)
    np.testing.assert_allclose(np.asarray(scores)[0], hf_scores, atol=2e-3, rtol=1e-3)


def test_greedy_decode_alibi_and_learned_positions_match_hf(rng):
    """Decode-path position machinery beyond rotary: the two-block decode
    attention rebuilds ALiBi distances (BLOOM) and learned-position lookups
    (OPT, +2 offset) from the cache's explicit positions — both must
    reproduce HF generate token-for-token, not just the prompt forward."""
    from transformers import (
        BloomConfig,
        BloomForCausalLM,
        GPTJConfig,
        GPTJForCausalLM,
        OPTConfig,
        OPTForCausalLM,
    )

    cases = [
        ("bloom", BloomForCausalLM, BloomConfig(
            vocab_size=VOCAB, hidden_size=32, n_layer=3, n_head=4), 3),
        ("opt", OPTForCausalLM, OPTConfig(
            vocab_size=VOCAB, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, ffn_dim=64, max_position_embeddings=64,
            do_layer_norm_before=True, word_embed_proj_dim=32), 6),
        # interleaved partial rotary + shared-LN parallel block + lm_head bias
        ("gptj", GPTJForCausalLM, GPTJConfig(
            vocab_size=VOCAB, n_embd=32, n_layer=3, n_head=4, rotary_dim=4,
            n_positions=64, activation_function="gelu_new"), 21),
    ]
    steps = 6
    for fam_expect, cls, hf_config, seed in cases:
        torch.manual_seed(seed)
        model = cls(hf_config).eval()
        ids = rng.integers(3, VOCAB, size=(1, 9)).astype(np.int32)
        mask = np.ones_like(ids)
        with torch.no_grad():
            out = model.generate(
                torch.tensor(ids), max_new_tokens=steps, do_sample=False,
                output_scores=True, return_dict_in_generate=True,
                pad_token_id=0,
            )
        hf_tokens = out.sequences[0, ids.shape[1]:].numpy()
        hf_scores = np.stack([s[0].float().numpy() for s in out.scores])
        fam, cfg = mcfg.from_hf_config(hf_config)
        assert fam == fam_expect
        params = mconvert.convert(
            fam, mconvert.getter_from_torch_state_dict(model.state_dict()),
            cfg, dtype=jnp.float32,
        )
        tokens, scores = decoder.greedy_decode(
            params, cfg, jnp.asarray(ids), jnp.asarray(mask), num_steps=steps
        )
        np.testing.assert_array_equal(np.asarray(tokens)[0], hf_tokens,
                                      err_msg=fam)
        np.testing.assert_allclose(np.asarray(scores)[0], hf_scores,
                                   atol=2e-3, rtol=1e-3, err_msg=fam)


def test_greedy_decode_eos_stop_matches_hf():
    """EOS semantics: HF generate stops after emitting eos_token_id; our
    batched decode force-pads with EOS past that point.  Designating a token
    the model ACTUALLY generates mid-continuation as EOS makes the stop
    deterministic: tokens up to and including it must match HF, and
    everything after must be the forced EOS pad.  Uses a private rng (not
    the module fixture) and picks a step whose token has no earlier
    occurrence, so the test is order-independent and cannot stop early."""
    from transformers import GPTNeoXConfig, GPTNeoXForCausalLM

    local_rng = np.random.default_rng(42)
    hf_config = GPTNeoXConfig(
        vocab_size=VOCAB, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64, rotary_pct=0.25,
        max_position_embeddings=64,
    )
    torch.manual_seed(7)
    model = GPTNeoXForCausalLM(hf_config).eval()
    ids = local_rng.integers(3, VOCAB, size=(1, 8)).astype(np.int32)
    mask = np.ones_like(ids)
    fam, cfg = mcfg.from_hf_config(hf_config)
    params = mconvert.convert(
        fam, mconvert.getter_from_torch_state_dict(model.state_dict()), cfg,
        dtype=jnp.float32,
    )
    free_toks, _ = decoder.greedy_decode(
        params, cfg, jnp.asarray(ids), jnp.asarray(mask), num_steps=8
    )
    free = [int(t) for t in np.asarray(free_toks)[0]]
    # first step >= 1 whose token never occurred earlier: HF must stop THERE
    stop = next(j for j in range(1, len(free)) if free[j] not in free[:j])
    eos = free[stop]

    with torch.no_grad():
        out = model.generate(
            torch.tensor(ids), max_new_tokens=8, do_sample=False,
            pad_token_id=0, eos_token_id=eos,
        )
    hf_tokens = out[0, ids.shape[1]:].numpy()
    assert hf_tokens[-1] == eos and len(hf_tokens) == stop + 1

    toks, _ = decoder.greedy_decode(
        params, cfg, jnp.asarray(ids), jnp.asarray(mask), num_steps=8,
        eos_token_id=eos,
    )
    toks = np.asarray(toks)[0]
    np.testing.assert_array_equal(toks[: stop + 1], hf_tokens)
    np.testing.assert_array_equal(toks[stop + 1:],
                                  np.full(8 - stop - 1, eos))  # forced pad


def test_greedy_decode_ragged_batch_matches_unpadded(rng):
    """Padding must not change a row's continuation: decode each row alone vs
    in a ragged batch."""
    from transformers import MistralConfig, MistralForCausalLM

    hf_config = MistralConfig(
        vocab_size=VOCAB, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=64,
        sliding_window=None, max_position_embeddings=64,
    )
    torch.manual_seed(8)
    model = MistralForCausalLM(hf_config).eval()
    fam, cfg = mcfg.from_hf_config(hf_config)
    params = mconvert.convert(
        fam, mconvert.getter_from_torch_state_dict(model.state_dict()), cfg,
        dtype=jnp.float32,
    )
    lens = [10, 7, 4]
    seq = max(lens)
    ids = np.zeros((3, seq), np.int32)
    mask = np.zeros((3, seq), np.int32)
    rows = []
    for r, ln in enumerate(lens):
        row = rng.integers(3, VOCAB, size=ln).astype(np.int32)
        rows.append(row)
        ids[r, :ln] = row
        mask[r, :ln] = 1
    btoks, _ = decoder.greedy_decode(params, cfg, jnp.asarray(ids), jnp.asarray(mask), num_steps=5)
    for r, row in enumerate(rows):
        stoks, _ = decoder.greedy_decode(
            params, cfg, jnp.asarray(row[None, :]), jnp.ones((1, len(row)), jnp.int32), num_steps=5
        )
        np.testing.assert_array_equal(np.asarray(btoks)[r], np.asarray(stoks)[0])


def _qwen2_tiny(seed, tie=False):
    from transformers import Qwen2Config, Qwen2ForCausalLM

    hf_config = Qwen2Config(
        vocab_size=VOCAB, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=4, intermediate_size=64,
        max_position_embeddings=64, use_sliding_window=False,
        sliding_window=None, tie_word_embeddings=tie,
    )
    torch.manual_seed(seed)
    return hf_config, Qwen2ForCausalLM(hf_config).eval()


def test_qwen2_sliding_window_ignored_when_disabled():
    """Qwen2 checkpoints ship sliding_window alongside use_sliding_window:
    false — the window must not leak into our config."""
    from transformers import Qwen2Config

    hf_config = Qwen2Config(
        vocab_size=VOCAB, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=4, intermediate_size=64,
        use_sliding_window=False, sliding_window=32768,
    )
    _, cfg = mcfg.from_hf_config(hf_config)
    assert cfg.sliding_window is None and cfg.qkv_bias


def test_qwen2_parity(rng):
    """Qwen2/Qwen1.5 (the reference's Qwen-7B-Chat leg on modern checkpoints):
    llama-shaped with hardwired QKV bias."""
    hf_config, model = _qwen2_tiny(9)
    ids, mask = _batch(rng)
    _assert_close(
        _ours_logits("llama", hf_config, model.state_dict(), ids, mask),
        _hf_logits(model, ids, mask),
        mask,
    )


def test_qwen1_parity(rng):
    """Qwen-7B-Chat first generation (model_type "qwen", trust_remote_code —
    compare_instruct_models.py:159).  Its arch is computationally identical to
    Qwen2 at MHA/full-rotary settings, so a tiny Qwen2 is the torch oracle:
    we re-key its state dict into the Qwen1 layout (fused c_attn; the w1/w2
    MLP pair where SiLU acts on w2) and require identical logits through our
    "qwen" converter."""
    import types

    hf_config, model = _qwen2_tiny(10)
    sd = model.state_dict()
    qwen1_sd = {
        "transformer.wte.weight": sd["model.embed_tokens.weight"],
        "transformer.ln_f.weight": sd["model.norm.weight"],
        "lm_head.weight": sd["lm_head.weight"],
    }
    for i in range(hf_config.num_hidden_layers):
        src = f"model.layers.{i}"
        dst = f"transformer.h.{i}"
        qwen1_sd[f"{dst}.ln_1.weight"] = sd[f"{src}.input_layernorm.weight"]
        qwen1_sd[f"{dst}.ln_2.weight"] = sd[f"{src}.post_attention_layernorm.weight"]
        qwen1_sd[f"{dst}.attn.c_attn.weight"] = torch.cat(
            [sd[f"{src}.self_attn.{p}.weight"] for p in ("q_proj", "k_proj", "v_proj")]
        )
        qwen1_sd[f"{dst}.attn.c_attn.bias"] = torch.cat(
            [sd[f"{src}.self_attn.{p}.bias"] for p in ("q_proj", "k_proj", "v_proj")]
        )
        qwen1_sd[f"{dst}.attn.c_proj.weight"] = sd[f"{src}.self_attn.o_proj.weight"]
        qwen1_sd[f"{dst}.mlp.w2.weight"] = sd[f"{src}.mlp.gate_proj.weight"]
        qwen1_sd[f"{dst}.mlp.w1.weight"] = sd[f"{src}.mlp.up_proj.weight"]
        qwen1_sd[f"{dst}.mlp.c_proj.weight"] = sd[f"{src}.mlp.down_proj.weight"]

    qwen1_config = types.SimpleNamespace(
        model_type="qwen", vocab_size=VOCAB, hidden_size=32,
        num_hidden_layers=2, num_attention_heads=4, kv_channels=8,
        intermediate_size=2 * 64,  # Qwen1 configs store DOUBLE the MLP width
        rotary_emb_base=getattr(hf_config, "rope_theta", 10000.0),
        rotary_pct=1.0, seq_length=64, layer_norm_epsilon=hf_config.rms_norm_eps,
        tie_word_embeddings=False,
    )
    ids, mask = _batch(rng)
    _assert_close(
        _ours_logits("qwen", qwen1_config, qwen1_sd, ids, mask),
        _hf_logits(model, ids, mask),
        mask,
    )


def _baichuan_from_llama(seed, norm_head):
    """Tiny llama oracle re-keyed into the Baichuan layout (fused W_pack)."""
    from transformers import LlamaConfig, LlamaForCausalLM

    hf_config = LlamaConfig(
        vocab_size=VOCAB, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=4, intermediate_size=64,
        max_position_embeddings=64, tie_word_embeddings=False,
        rms_norm_eps=1e-6,
    )
    torch.manual_seed(seed)
    model = LlamaForCausalLM(hf_config).eval()
    if norm_head:
        # bake the NormHead into the ORACLE: Baichuan2 normalizes lm_head rows
        # every forward, so an oracle with pre-normalized rows is the target
        with torch.no_grad():
            w = model.lm_head.weight
            model.lm_head.weight.copy_(torch.nn.functional.normalize(w))
    sd = model.state_dict()
    bc_sd = {
        "model.embed_tokens.weight": sd["model.embed_tokens.weight"],
        "model.norm.weight": sd["model.norm.weight"],
    }
    if norm_head:
        # our converter receives UN-normalized rows (scaled arbitrarily) and
        # must normalize them itself
        torch.manual_seed(seed + 100)
        scale = 0.5 + torch.rand(VOCAB, 1)
        bc_sd["lm_head.weight"] = sd["lm_head.weight"] * scale
    else:
        bc_sd["lm_head.weight"] = sd["lm_head.weight"]
    for i in range(hf_config.num_hidden_layers):
        pre = f"model.layers.{i}"
        bc_sd[f"{pre}.input_layernorm.weight"] = sd[f"{pre}.input_layernorm.weight"]
        bc_sd[f"{pre}.post_attention_layernorm.weight"] = sd[f"{pre}.post_attention_layernorm.weight"]
        bc_sd[f"{pre}.self_attn.W_pack.weight"] = torch.cat(
            [sd[f"{pre}.self_attn.{p}.weight"] for p in ("q_proj", "k_proj", "v_proj")]
        )
        bc_sd[f"{pre}.self_attn.o_proj.weight"] = sd[f"{pre}.self_attn.o_proj.weight"]
        for p in ("gate_proj", "up_proj", "down_proj"):
            bc_sd[f"{pre}.mlp.{p}.weight"] = sd[f"{pre}.mlp.{p}.weight"]
    return hf_config, model, bc_sd


def test_baichuan_7b_parity(rng):
    """Baichuan-7B layout (W_pack fused QKV, rotary, no NormHead)."""
    import types

    hf_config, model, bc_sd = _baichuan_from_llama(11, norm_head=False)
    bc_config = types.SimpleNamespace(
        model_type="baichuan", vocab_size=VOCAB, hidden_size=32,
        num_hidden_layers=2, num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, rms_norm_eps=1e-6,
        tie_word_embeddings=False,
    )
    fam, cfg = mcfg.from_hf_config(bc_config)
    assert fam == "baichuan"
    assert cfg.position_embedding == "rotary" and not cfg.norm_head
    ids, mask = _batch(rng)
    _assert_close(
        _ours_logits("baichuan", bc_config, bc_sd, ids, mask),
        _hf_logits(model, ids, mask),
        mask,
    )


def test_baichuan2_norm_head_parity(rng):
    """Baichuan2 NormHead: the converter L2-normalizes lm_head rows, so
    arbitrary row scaling of the stored head must not change logits."""
    import dataclasses

    hf_config, model, bc_sd = _baichuan_from_llama(12, norm_head=True)
    fam_cfg = mcfg.llama_config(hf_config)
    cfg = dataclasses.replace(fam_cfg, fused_qkv=True, norm_head=True)
    get = mconvert.getter_from_torch_state_dict(bc_sd)
    params = mconvert.convert("baichuan", get, cfg, dtype=jnp.float32)
    ids, mask = _batch(rng)
    ours = np.asarray(decoder.forward(
        params, cfg, jnp.asarray(ids), jnp.asarray(mask)
    ))
    _assert_close(ours, _hf_logits(model, ids, mask), mask)


def test_baichuan_13b_config_translation():
    """13B geometry (40 layers) -> ALiBi; Baichuan2 vocab (125,696) -> NormHead."""
    import types

    b2_13b = types.SimpleNamespace(
        model_type="baichuan", vocab_size=125_696, hidden_size=5120,
        num_hidden_layers=40, num_attention_heads=40, intermediate_size=13696,
        model_max_length=4096, rms_norm_eps=1e-6, tie_word_embeddings=False,
    )
    fam, cfg = mcfg.from_hf_config(b2_13b)
    assert fam == "baichuan"
    assert cfg.position_embedding == "alibi"
    assert cfg.norm_head and cfg.max_position_embeddings == 4096


def test_gptj_parity(rng):
    """GPT-J/GPT-JT (interleaved RoPE, shared-LN parallel block, lm_head
    bias) — togethercomputer/GPT-JT in the reference's word-meaning roster
    (compare_instruct_models.py:162)."""
    from transformers import GPTJConfig, GPTJForCausalLM

    hf_config = GPTJConfig(
        vocab_size=VOCAB, n_embd=32, n_layer=3, n_head=4, rotary_dim=4,
        n_positions=64, activation_function="gelu_new",
    )
    torch.manual_seed(21)
    model = GPTJForCausalLM(hf_config).eval()
    ids, mask = _batch(rng)
    _assert_close(
        _ours_logits("gptj", hf_config, model.state_dict(), ids, mask),
        _hf_logits(model, ids, mask),
        mask,
    )


def test_mpt_parity(rng):
    """MPT (ALiBi, fused Wqkv, bias-free incl. LayerNorm) —
    mosaicml/mpt-7b-instruct in the reference's roster
    (compare_instruct_models.py:157)."""
    from transformers import MptConfig, MptForCausalLM

    hf_config = MptConfig(
        vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=3,
        expansion_ratio=2, max_seq_len=64,
    )
    torch.manual_seed(22)
    model = MptForCausalLM(hf_config).eval()
    ids, mask = _batch(rng)
    _assert_close(
        _ours_logits("mpt", hf_config, model.state_dict(), ids, mask),
        _hf_logits(model, ids, mask),
        mask,
    )


def test_glm_parity(rng):
    """HF GLM-4 (GQA, partial GLM-convention RoPE, fused gate_up_proj) — the
    in-process oracle for the ChatGLM lineage the reference special-cases
    (compare_instruct_models.py:416-421)."""
    from transformers import GlmConfig, GlmForCausalLM

    hf_config = GlmConfig(
        vocab_size=VOCAB, hidden_size=32, intermediate_size=64,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        head_dim=8, partial_rotary_factor=0.5, pad_token_id=0,
        max_position_embeddings=64,
    )
    torch.manual_seed(23)
    model = GlmForCausalLM(hf_config).eval()
    ids, mask = _batch(rng)
    _assert_close(
        _ours_logits("glm", hf_config, model.state_dict(), ids, mask),
        _hf_logits(model, ids, mask),
        mask,
    )


def test_chatglm_conversion_structure():
    """ChatGLM2-6B geometry (remote-code family; no offline HF oracle):
    config translation + weight conversion from the shared synthetic state
    dict + jit forward must produce finite logits with the right shapes."""
    from helpers import chatglm_test_setup

    hf, sd = chatglm_test_setup(VOCAB)
    fam, cfg = mcfg.from_hf_config(hf)
    assert fam == "chatglm"
    assert cfg.num_kv_heads == 2 and cfg.rotary_style == "interleaved"
    assert cfg.rotary_pct == 0.5 and cfg.intermediate_size == 48

    L, h, nd, kvd, f = hf.num_layers, 32, 32, 16, 48
    params = mconvert.convert(
        "chatglm", mconvert.getter_from_torch_state_dict(sd), cfg,
        dtype=jnp.float32,
    )
    assert params["layers"]["attn"]["wq"].shape == (L, h, nd)
    assert params["layers"]["attn"]["wk"].shape == (L, h, kvd)
    assert params["layers"]["mlp"]["wg"].shape == (L, h, f)
    ids = np.random.default_rng(8).integers(3, VOCAB, size=(2, 10)).astype(np.int32)
    mask = np.ones_like(ids)
    mask[1, 7:] = 0
    logits = np.asarray(decoder.forward(params, cfg, jnp.asarray(ids), jnp.asarray(mask)))
    assert logits.shape == (2, 10, VOCAB)
    assert np.isfinite(logits[mask.astype(bool)]).all()


def test_chatglm_numeric_parity_hf_glm_oracle():
    """SECOND, independent ChatGLM2 oracle: HuggingFace's own
    ``GlmForCausalLM`` (transformers' GLM-4 implementation — written by the
    THUDM/HF teams, not by this repo) configured to the ChatGLM2 geometry.
    The GLM-4 decoder block is the ChatGLM2 block: RMSNorm, biased QKV,
    multi-query groups, INTERLEAVED rotary over the first half of each head
    (partial_rotary_factor=0.5 with repeat_interleave'd cos/sin — its
    ``apply_rotary_pos_emb`` rotates pairs (x[2i], x[2i+1]) by
    theta_i = 10000^(-2i/rot), exactly RotaryEmbedding(kv_channels//2)),
    fused-chunked swiglu MLP, sequential residuals, untied output layer.

    The handcrafted numpy oracle below re-derives those equations by hand —
    if this repo misread the published modeling_chatglm.py, the numpy oracle
    could share the misreading.  HF's executable cannot: it is a separate
    codebase whose GLM-4 checkpoints depend on these exact semantics.  Both
    oracles agreeing with models/decoder.py (<=1e-4) closes that gap
    (round-3 verdict item 5).  Reference load site:
    compare_instruct_models.py:409-421."""
    torch = pytest.importorskip("torch")
    try:
        from transformers import GlmConfig, GlmForCausalLM
    except ImportError:
        pytest.skip("transformers build without Glm")
    from helpers import chatglm_test_setup

    hf, sd = chatglm_test_setup(VOCAB)
    n, d, g = 4, 8, 2
    nd, kvd = n * d, g * d
    glm_cfg = GlmConfig(
        vocab_size=VOCAB, hidden_size=32, intermediate_size=48,
        num_hidden_layers=hf.num_layers, num_attention_heads=n,
        num_key_value_heads=g, head_dim=d, partial_rotary_factor=0.5,
        attention_bias=True, rms_norm_eps=hf.layernorm_epsilon,
        rope_theta=10000.0, tie_word_embeddings=False,
        attention_dropout=0.0, max_position_embeddings=hf.seq_length,
        pad_token_id=0,
    )
    model = GlmForCausalLM(glm_cfg).eval()
    mapped = {}
    for i in range(hf.num_layers):
        src, dst = f"transformer.encoder.layers.{i}", f"model.layers.{i}"
        qkv_w = sd[f"{src}.self_attention.query_key_value.weight"]
        qkv_b = sd[f"{src}.self_attention.query_key_value.bias"]
        mapped[f"{dst}.self_attn.q_proj.weight"] = qkv_w[:nd]
        mapped[f"{dst}.self_attn.q_proj.bias"] = qkv_b[:nd]
        mapped[f"{dst}.self_attn.k_proj.weight"] = qkv_w[nd:nd + kvd]
        mapped[f"{dst}.self_attn.k_proj.bias"] = qkv_b[nd:nd + kvd]
        mapped[f"{dst}.self_attn.v_proj.weight"] = qkv_w[nd + kvd:]
        mapped[f"{dst}.self_attn.v_proj.bias"] = qkv_b[nd + kvd:]
        mapped[f"{dst}.self_attn.o_proj.weight"] = sd[f"{src}.self_attention.dense.weight"]
        mapped[f"{dst}.mlp.gate_up_proj.weight"] = sd[f"{src}.mlp.dense_h_to_4h.weight"]
        mapped[f"{dst}.mlp.down_proj.weight"] = sd[f"{src}.mlp.dense_4h_to_h.weight"]
        mapped[f"{dst}.input_layernorm.weight"] = sd[f"{src}.input_layernorm.weight"]
        mapped[f"{dst}.post_attention_layernorm.weight"] = sd[f"{src}.post_attention_layernorm.weight"]
    mapped["model.embed_tokens.weight"] = sd["transformer.embedding.word_embeddings.weight"]
    mapped["model.norm.weight"] = sd["transformer.encoder.final_layernorm.weight"]
    mapped["lm_head.weight"] = sd["transformer.output_layer.weight"]
    missing, unexpected = model.load_state_dict(
        {k: v.float() for k, v in mapped.items()}, strict=False)
    assert not missing and not unexpected, (missing, unexpected)

    rng = np.random.default_rng(11)
    ids, mask = _batch(rng)
    with torch.no_grad():
        oracle = model(
            torch.tensor(ids.astype(np.int64)),
            attention_mask=torch.tensor(mask.astype(np.int64)),
        ).logits.numpy()

    fam, cfg = mcfg.from_hf_config(hf)
    assert fam == "chatglm"
    params = mconvert.convert(
        fam, mconvert.getter_from_torch_state_dict(sd), cfg, dtype=jnp.float32)
    ours = np.asarray(decoder.forward(
        params, cfg, jnp.asarray(ids), jnp.asarray(mask)))
    _assert_close(ours, oracle, mask, atol=1e-4)


def test_chatglm_numeric_parity_handcrafted_oracle():
    """ChatGLM2 numeric pin WITHOUT remote code: a handcrafted numpy oracle of
    the ChatGLM2 block — RMSNorm, fused QKV with bias, multi-query groups,
    interleaved RoPE over the first half of each head (RotaryEmbedding(dim =
    kv_channels // 2) with inv_freq over arange(0, rot, 2)/rot, pairs
    (x[2i], x[2i+1])), swiglu MLP chunked [gate; up], sequential residuals —
    per the published THUDM modeling_chatglm.py equations that the reference
    loads via trust_remote_code (compare_instruct_models.py:409-421).  Every
    other family pins against an executable HF oracle; this closes the one
    structural-only gap at the same <=1e-4 tolerance."""
    from helpers import chatglm_test_setup

    hf, sd_torch = chatglm_test_setup(VOCAB)
    fam, cfg = mcfg.from_hf_config(hf)
    assert fam == "chatglm"
    L, h, n, d, g, f = hf.num_layers, 32, 4, 8, 2, 48
    nd, kvd = n * d, g * d
    sd = {k: v.numpy() for k, v in sd_torch.items()}

    rng = np.random.default_rng(11)
    ids, mask = _batch(rng)
    eps = 1e-5

    # ---- the oracle: modeling_chatglm.py equations in plain numpy ---------
    def rms(x, w):
        return x / np.sqrt((x ** 2).mean(-1, keepdims=True) + eps) * w

    def softmax(x):
        x = x - x.max(-1, keepdims=True)
        e = np.exp(x)
        return e / e.sum(-1, keepdims=True)

    b, s = ids.shape
    rot = d // 2                               # RotaryEmbedding(kv_channels // 2)
    inv_freq = 1.0 / (10000.0 ** (np.arange(0, rot, 2) / rot))
    ang = np.outer(np.arange(s), inv_freq)     # [s, rot/2]
    cos, sin = np.cos(ang), np.sin(ang)

    def rope(t):                               # t: [b, s, heads, d]
        tr, tp = t[..., :rot], t[..., rot:]
        x0, x1 = tr[..., 0::2], tr[..., 1::2]
        c, sn = cos[None, :, None, :], sin[None, :, None, :]
        out = np.stack([x0 * c - x1 * sn, x1 * c + x0 * sn], axis=-1)
        return np.concatenate([out.reshape(tr.shape), tp], axis=-1)

    valid = mask.astype(bool)
    causal = np.tril(np.ones((s, s), bool))
    attend = causal[None] & valid[:, None, :]  # [b, s_q, s_k]

    x = sd["transformer.embedding.word_embeddings.weight"][ids]
    for i in range(L):
        pre = f"transformer.encoder.layers.{i}"
        hln = rms(x, sd[f"{pre}.input_layernorm.weight"])
        qkv = hln @ sd[f"{pre}.self_attention.query_key_value.weight"].T \
            + sd[f"{pre}.self_attention.query_key_value.bias"]
        q = rope(qkv[..., :nd].reshape(b, s, n, d))
        k = rope(qkv[..., nd:nd + kvd].reshape(b, s, g, d))
        v = qkv[..., nd + kvd:].reshape(b, s, g, d)
        k = np.repeat(k, n // g, axis=2)       # head j reads group j // (n/g)
        v = np.repeat(v, n // g, axis=2)
        scores = np.einsum("bsnd,btnd->bnst", q, k) / np.sqrt(d)
        scores = np.where(attend[:, None], scores, -1e30)
        attn = np.einsum("bnst,btnd->bsnd", softmax(scores), v).reshape(b, s, nd)
        x = x + attn @ sd[f"{pre}.self_attention.dense.weight"].T
        h2 = rms(x, sd[f"{pre}.post_attention_layernorm.weight"])
        a = h2 @ sd[f"{pre}.mlp.dense_h_to_4h.weight"].T
        gate, up = np.split(a, 2, axis=-1)     # swiglu chunks in half
        x = x + (gate / (1.0 + np.exp(-gate)) * up) @ sd[f"{pre}.mlp.dense_4h_to_h.weight"].T
    x = rms(x, sd["transformer.encoder.final_layernorm.weight"])
    oracle = x @ sd["transformer.output_layer.weight"].T

    get = mconvert.getter_from_torch_state_dict(sd_torch)
    params = mconvert.convert("chatglm", get, cfg, dtype=jnp.float32)
    ours = np.asarray(decoder.forward(params, cfg, jnp.asarray(ids), jnp.asarray(mask)))
    _assert_close(ours, oracle, mask, atol=1e-4)


def test_mpt_biased_variant_and_unsupported_configs():
    """Original-Mosaic MPT checkpoints with ``no_bias: false`` carry bias
    tensors (HF's port drops them, so this leg is structurally tested against
    a synthetic state dict); non-ALiBi and GQA variants are rejected loudly
    instead of converting to silently-wrong weights."""
    import types

    base = dict(model_type="mpt", vocab_size=VOCAB, d_model=32, n_heads=4,
                n_layers=2, expansion_ratio=2, max_seq_len=64)
    with pytest.raises(ValueError, match="ALiBi"):
        mcfg.from_hf_config(types.SimpleNamespace(
            **base, attn_config={"alibi": False}))
    with pytest.raises(ValueError, match="kv_n_heads"):
        mcfg.from_hf_config(types.SimpleNamespace(
            **base, attn_config={"alibi": True, "kv_n_heads": 2}))

    fam, cfg = mcfg.from_hf_config(types.SimpleNamespace(**base, no_bias=False))
    assert fam == "mpt" and cfg.qkv_bias and cfg.mlp_bias
    rng2 = np.random.default_rng(9)
    h, f = 32, 64
    sd = {}
    for i in range(2):
        pre = f"transformer.blocks.{i}"
        sd[f"{pre}.attn.Wqkv.weight"] = rng2.standard_normal((3 * h, h)) * 0.05
        sd[f"{pre}.attn.Wqkv.bias"] = rng2.standard_normal(3 * h) * 0.01
        sd[f"{pre}.attn.out_proj.weight"] = rng2.standard_normal((h, h)) * 0.05
        sd[f"{pre}.attn.out_proj.bias"] = rng2.standard_normal(h) * 0.01
        sd[f"{pre}.ffn.up_proj.weight"] = rng2.standard_normal((f, h)) * 0.05
        sd[f"{pre}.ffn.up_proj.bias"] = rng2.standard_normal(f) * 0.01
        sd[f"{pre}.ffn.down_proj.weight"] = rng2.standard_normal((h, f)) * 0.05
        sd[f"{pre}.ffn.down_proj.bias"] = rng2.standard_normal(h) * 0.01
        for ln in ("norm_1", "norm_2"):
            sd[f"{pre}.{ln}.weight"] = np.ones(h)
            sd[f"{pre}.{ln}.bias"] = np.zeros(h)
    sd["transformer.wte.weight"] = rng2.standard_normal((VOCAB, h)) * 0.05
    sd["transformer.norm_f.weight"] = np.ones(h)
    sd["transformer.norm_f.bias"] = np.zeros(h)
    params = mconvert.convert("mpt", lambda n: sd[n], cfg, dtype=jnp.float32)
    assert "bq" in params["layers"]["attn"] and "bi" in params["layers"]["mlp"]
    ids = np.random.default_rng(10).integers(3, VOCAB, size=(2, 8)).astype(np.int32)
    mask = np.ones_like(ids)
    logits = np.asarray(decoder.forward(params, cfg, jnp.asarray(ids), jnp.asarray(mask)))
    assert np.isfinite(logits).all()
