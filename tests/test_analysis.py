"""Orchestrator + presentation-layer tests with synthetic sweep data."""

import math
import os

import numpy as np
import pandas as pd
import pytest

from llm_interpretation_replication_tpu.analysis import (
    ModelConfidenceAnalyzer,
    analyze_model,
    analyze_workbook,
    base_vs_instruct_figures,
    compare_with_human_data,
    consistency_statistics,
    cross_experiment_kappa,
    evaluate_all_models,
    model_comparison_report,
    process_scenario_perturbations,
    run_combined_analysis,
    similarity_report,
    write_outputs,
    write_report,
    calculate_correlations,
)
from llm_interpretation_replication_tpu.api_backends import (
    AnthropicClient,
    FakeTransport,
    GeminiClient,
    OpenAIClient,
    ResponseCache,
)
from llm_interpretation_replication_tpu.api_backends.transport import TransportError
from llm_interpretation_replication_tpu.utils.retry import RetryPolicy


def _scenarios(n=2):
    return [
        {
            "original_main": f"Scenario {i} main text. Second sentence here.",
            "scenario_name": f"Scenario {i}",
            "response_format": "Answer only 'Covered' or 'Not Covered'.",
            "target_tokens": ["Covered", "Not"],
            "confidence_format": "How confident are you, 0-100?",
        }
        for i in range(1, n + 1)
    ]


def _workbook(rng, scenarios, model="gpt-test", rows_per_scenario=80):
    records = []
    for s in scenarios:
        center = rng.uniform(0.2, 0.8)
        for j in range(rows_per_scenario):
            t1 = float(np.clip(rng.normal(center, 0.15), 0.001, 0.999))
            records.append(
                {
                    "Model": model,
                    "Original Main Part": s["original_main"],
                    "Response Format": s["response_format"],
                    "Confidence Format": s["confidence_format"],
                    "Rephrased Main Part": f"{s['original_main']} v{j}",
                    "Full Rephrased Prompt": "x",
                    "Full Confidence Prompt": "y",
                    "Model Response": "Covered" if t1 > 0.5 else "Not Covered",
                    "Model Confidence Response": str(int(100 * t1)),
                    "Log Probabilities": "",
                    "Token_1_Prob": t1,
                    "Token_2_Prob": 1 - t1,
                    "Odds_Ratio": t1 / (1 - t1),
                    "Confidence Value": int(100 * t1),
                    "Weighted Confidence": 100 * t1,
                }
            )
    return pd.DataFrame(records)


class TestPerturbationReport:
    def test_analyze_model_full_report(self, tmp_path):
        rng = np.random.default_rng(0)
        scenarios = _scenarios(2)
        df = _workbook(rng, scenarios)
        report = analyze_model(
            df, "gpt-test", scenarios, str(tmp_path), n_simulations=20_000
        )
        assert len(report["scenarios"]) == 2
        rec = report["scenarios"][0]
        assert rec["n"] == 80
        assert "summary" in rec and 0 <= rec["summary"]["mean"] <= 1
        assert "ks_stat" in rec["normality"]
        assert rec["truncated_normal"]["fit"] == "ok"
        assert report["scenario_pair_kappa"]
        assert len(report["compliance"]) == 2
        assert os.path.exists(tmp_path / "tables.tex")
        assert os.path.exists(tmp_path / "scenario_1_prob_hist.png")
        assert os.path.exists(tmp_path / "combined_probability.png")

    def test_analyze_workbook_splits_models(self, tmp_path):
        rng = np.random.default_rng(1)
        scenarios = _scenarios(1)
        df = pd.concat(
            [_workbook(rng, scenarios, model=m, rows_per_scenario=30) for m in ("a", "b")],
            ignore_index=True,
        )
        out = analyze_workbook(df, scenarios, str(tmp_path),
                               n_simulations=5_000, make_figures=False)
        assert set(out) == {"a", "b"}


def fast_retry():
    return RetryPolicy(retry_on=(TransportError,), max_retries=2,
                       initial_delay=0.0, sleep=lambda s: None)


class TestClosedSourceEval:
    def _clients(self):
        ft = FakeTransport()
        top = [{"token": "Yes", "logprob": math.log(0.8)},
               {"token": "No", "logprob": math.log(0.1)}]
        # content "85" parses as a confidence; binary probs come from logprobs
        ft.add("POST", "/chat/completions", lambda c: (200, {
            "choices": [{"message": {"content": "85"},
                         "logprobs": {"content": [{"token": "Yes", "top_logprobs": top}]}}],
            "usage": {"prompt_tokens": 10, "completion_tokens": 1},
        }))
        gt = FakeTransport()
        gt.add("POST", ":generateContent", lambda c: (200, {
            "candidates": [{
                "content": {"parts": [{"text": "80"}]},
                "logprobsResult": {"topCandidates": [
                    {"candidates": [{"token": "Yes", "logProbability": math.log(0.7)},
                                    {"token": "No", "logProbability": math.log(0.2)}]},
                ]},
            }]
        }))
        at = FakeTransport()
        at.add("POST", "/messages", lambda c: (200, {
            "content": [{"type": "text", "text": "75"}]
        }))
        return (
            OpenAIClient("k", transport=ft, retry_policy=fast_retry()),
            GeminiClient("k", transport=gt, retry_policy=fast_retry()),
            AnthropicClient("k", transport=at, retry_policy=fast_retry()),
        )

    def test_run_orchestrator_confirm_and_short_circuit(self, tmp_path):
        """The main()-shell behaviors (reference :1902-2110): interactive
        confirm gate on fresh API runs, cache-mode banner skips the gate,
        saved-results CSV short-circuits evaluation entirely."""
        from llm_interpretation_replication_tpu.analysis.closed_source_eval import (
            run_closed_source_evaluation,
        )

        questions = [f'Is a "x{i}" a "y{i}"?' for i in range(3)]
        logs = []
        # 1. declined confirm: no evaluation, no report.  The gate only fires
        # when paid vendors are configured (3 q x 2 calls x 3 vendors = 18).
        gpt, gem, claude = self._clients()
        out = run_closed_source_evaluation(
            questions, str(tmp_path / "o1"), confirm_fn=lambda _p: False,
            log=logs.append, gpt_client=gpt, gemini_client=gem,
            claude_client=claude,
        )
        assert out is None
        assert not os.path.exists(tmp_path / "o1")
        assert len(gpt.transport.calls) == 0    # declined before any API call
        assert any("Total API calls: 18" in line for line in logs)
        assert any("Estimated processing time: 0.8 minutes" in line for line in logs)

        # 2. accepted confirm with live clients: full run + report files
        gpt, gem, claude = self._clients()
        human_means = {q: 0.5 for q in questions}
        df = run_closed_source_evaluation(
            questions, str(tmp_path / "o2"), human_means=human_means,
            human_std=0.1, confirm_fn=lambda _p: True, log=logs.append,
            gpt_client=gpt, gemini_client=gem, claude_client=claude,
            rng=np.random.default_rng(42),
        )
        assert len(df) == 3
        assert os.path.exists(tmp_path / "o2" / "closed_source_evaluation_results.csv")
        assert os.path.exists(tmp_path / "o2" / "mae_results_tables.tex")

        # 3. rerun: saved CSV short-circuits — confirm never fires, no clients
        df2 = run_closed_source_evaluation(
            questions, str(tmp_path / "o2"),
            confirm_fn=lambda _p: (_ for _ in ()).throw(AssertionError("asked")),
            log=logs.append,
        )
        assert len(df2) == 3
        assert any("Loading existing results" in line for line in logs)

        # 4. warm cache file: banner instead of confirm gate
        cache_path = str(tmp_path / "cache.json")
        gpt, gem, claude = self._clients()
        ResponseCache(cache_path)  # empty; fill via a normal run first
        run_closed_source_evaluation(
            questions, str(tmp_path / "o3"), cache_file=cache_path,
            confirm_fn=lambda _p: True, log=logs.append,
            gpt_client=gpt, gemini_client=gem, claude_client=claude,
            rng=np.random.default_rng(42),
        )
        logs.clear()
        run_closed_source_evaluation(
            questions, str(tmp_path / "o4"), cache_file=cache_path,
            confirm_fn=lambda _p: (_ for _ in ()).throw(AssertionError("asked")),
            log=logs.append, rng=np.random.default_rng(42),
        )
        assert any("Cache mode: ENABLED" in line for line in logs)

    def test_full_loop_with_cache_and_report(self, tmp_path):
        gpt, gem, claude = self._clients()
        cache = ResponseCache(str(tmp_path / "cache.json"))
        questions = [f'Is a "thing{i}" a "stuff{i}"?' for i in range(6)]
        df = evaluate_all_models(
            questions, gpt_client=gpt, gemini_client=gem, claude_client=claude,
            cache=cache, rng=np.random.default_rng(42),
        )
        assert len(df) == 6
        assert df["gpt_relative_prob"].iloc[0] == pytest.approx(0.8 / 0.9)
        assert cache.is_complete(questions[0])
        # second run hits the cache only: no new transport calls for evaluators
        gpt2, gem2, claude2 = self._clients()
        df2 = evaluate_all_models(
            questions, gpt_client=gpt2, gemini_client=gem2, claude_client=claude2,
            cache=cache, rng=np.random.default_rng(42),
        )
        assert len(gpt2.transport.calls) == 0

        human_means = {q: 0.4 + 0.05 * i for i, q in enumerate(questions)}
        comparisons = compare_with_human_data(df, human_means, human_std=0.167,
                                              n_bootstrap=500, seed=42)
        assert set(comparisons["mae"]) >= {"GPT", "Claude", "Gemini", "Equanimity", "Random", "Normal"}
        # reference semantics: predictions are verbalized confidences / 100
        assert comparisons["mae"]["GPT"]["mae"] == pytest.approx(
            np.mean([abs(0.85 - h) for h in human_means.values()]))
        assert comparisons["mae"]["Normal"]["human_std"] == pytest.approx(0.167)
        # constant predictions here -> no correlation recorded for GPT; the
        # random evaluator varies, so its correlation fields are present
        assert {"correlation", "p_value", "n_matched"} <= set(comparisons["mae"]["Random"])
        corr = calculate_correlations(df)
        paths = write_report(df, comparisons, corr, str(tmp_path / "out"))
        assert os.path.exists(paths["csv"])
        assert os.path.exists(paths["latex"])
        assert os.path.exists(paths["error_strip"])
        assert os.path.exists(paths["dashboard"])
        assert os.path.exists(paths["mae_comparison"])


class TestStatementsSample:
    def test_escaping_and_structure(self):
        from llm_interpretation_replication_tpu.viz.latex import (
            escape_statement,
            irrelevant_statements_sample,
        )

        assert escape_statement("5% of $2 & #3_x") == "5\\% of \\$2 \\& \\#3\\_x"
        assert escape_statement("90° × 10⁻¹⁹ π") == (
            "90$^\\circ$ $\\times$ 10$^{-19}$ $\\pi$"
        )
        statements = [f"Fact number {i}." for i in range(100)]
        tex = irrelevant_statements_sample(statements, k=10, seed=42)
        lines = tex.splitlines()
        assert lines[0] == "\\begin{enumerate}"
        assert lines[-1] == "\\end{enumerate}"
        assert sum(1 for l in lines if l.startswith("    \\item ")) == 10
        # seeded: deterministic across calls
        assert tex == irrelevant_statements_sample(statements, k=10, seed=42)

    @pytest.mark.skipif(
        not os.path.exists("/root/reference/data/irrelevant_statements_sample.tex"),
        reason="reference mount not available",
    )
    def test_golden_vs_reference_sample(self):
        from llm_interpretation_replication_tpu.config import irrelevant_statements
        from llm_interpretation_replication_tpu.viz.latex import (
            irrelevant_statements_sample,
        )

        with open("/root/reference/data/irrelevant_statements_sample.tex") as f:
            golden = f.read()
        ours = irrelevant_statements_sample(irrelevant_statements(), k=50, seed=42)
        assert ours.strip() == golden.strip()


class TestIrrelevantEval:
    def test_process_and_stats(self, tmp_path):
        from llm_interpretation_replication_tpu.gen.irrelevant import generate_perturbations

        scenarios = generate_perturbations(
            [dict(s, main=s["original_main"], name=s["scenario_name"]) for s in _scenarios(2)],
            [f"Fact {i}." for i in range(3)],
        )
        calls = {"n": 0}

        def evaluator(prompt):
            calls["n"] += 1
            return f"Thinking...\n***\n{40 + calls['n'] % 20}\n***"

        df = process_scenario_perturbations(
            {"model-x": evaluator}, scenarios, str(tmp_path),
        )
        n_pert = sum(len(s["perturbations_with_irrelevant"]) for s in scenarios)
        assert len(df) == n_pert + len(scenarios)  # + originals
        assert df["confidence"].notna().all()
        stats = consistency_statistics(df)
        assert set(stats["model"]) == {"model-x"}
        assert (stats["ci_width"] >= 0).all()
        paths = write_outputs(df, stats, str(tmp_path), make_figures=True)
        assert os.path.exists(paths["xlsx"])
        # resume: nothing re-evaluated
        before = calls["n"]
        process_scenario_perturbations({"model-x": evaluator}, scenarios, str(tmp_path))
        assert calls["n"] == before

    def test_resume_after_lost_processed_set_does_not_duplicate(self, tmp_path):
        """Kill window between the rows-CSV rename and the processed-set
        flush: the triple set is stale/absent but the CSV has the rows.  The
        CSV must seed the processed-set on resume — without it every loaded
        triple would be re-evaluated AND re-appended (duplicated rows,
        double-counted stats)."""
        from llm_interpretation_replication_tpu.gen.irrelevant import (
            generate_perturbations,
        )

        scenarios = generate_perturbations(
            [dict(s, main=s["original_main"], name=s["scenario_name"])
             for s in _scenarios(1)],
            ["Fact A.", "Fact B."],
        )
        calls = {"n": 0}

        def evaluator(prompt):
            calls["n"] += 1
            return "Covered\n85"

        df1 = process_scenario_perturbations(
            {"model-x": evaluator}, scenarios, str(tmp_path),
        )
        os.remove(os.path.join(tmp_path, "processed_triples.json"))
        before = calls["n"]
        df2 = process_scenario_perturbations(
            {"model-x": evaluator}, scenarios, str(tmp_path),
        )
        assert calls["n"] == before          # nothing re-evaluated
        assert len(df2) == len(df1)          # and nothing duplicated
        assert not df2.duplicated(
            subset=["model", "scenario_name", "perturbation_id"]
        ).any()


class TestIrrelevantAnalyzeResults:
    def _df(self):
        rows = [
            {"model": "gpt", "scenario_name": "S1", "perturbation_id": "original",
             "irrelevant_statement": "", "position_index": -1,
             "position_description": "original", "response": "Covered",
             "confidence": 80.0, "confidence_raw_response": "80",
             "is_original": True, "response_prompt": "P-orig-r",
             "confidence_prompt": "P-orig-c"},
        ]
        for pid, (pos, resp, conf) in enumerate(
            [(0, "Covered", 70.0), (0, "Covered", 90.0),
             (1, "Not Covered", 60.0), (1, "Covered", 85.0)], start=1
        ):
            rows.append({
                "model": "gpt", "scenario_name": "S1", "perturbation_id": pid,
                "irrelevant_statement": f"Fact {pid}.", "position_index": pos,
                "position_description": f"pos{pos}", "response": resp,
                "confidence": conf, "confidence_raw_response": str(conf),
                "is_original": False, "response_prompt": f"P{pid}-r",
                "confidence_prompt": f"P{pid}-c",
            })
        import pandas as pd

        return pd.DataFrame(rows)

    def test_nested_analysis_matches_reference_shape(self, tmp_path):
        from llm_interpretation_replication_tpu.analysis.irrelevant_eval import (
            analyze_results, save_results, summary_frame,
        )

        df = self._df()
        analysis = analyze_results(df)
        a = analysis["S1"]["gpt"]
        assert a["consistency"] == pytest.approx(0.75)     # 3 of 4 match
        cs = a["confidence_stats"]
        assert cs["original_confidence"] == 80.0
        assert cs["mean_all_confidence"] == pytest.approx(77.0)
        assert cs["n_samples"] == 5
        assert cs["min_confidence"] == 60.0 and cs["max_confidence"] == 90.0
        assert cs["mean_perturbed_confidence"] == pytest.approx(76.25)
        # per-position consistency: pos0 2/2, pos1 1/2
        assert a["position_consistency"] == {"0_pos0": 1.0, "1_pos1": 0.5}
        assert a["original_response_prompt"] == "P-orig-r"
        assert len(a["confidence_values"]) == 5

        paths = save_results(df, analysis, str(tmp_path))
        from llm_interpretation_replication_tpu.utils.xlsx import read_xlsx

        assert len(read_xlsx(paths["xlsx"], sheet=0)) == len(df)   # Raw Results
        assert read_xlsx(paths["xlsx"], sheet=1)["consistency"].iloc[0] == 0.75
        pos_sheet = read_xlsx(paths["xlsx"], sheet=2)              # Position
        assert "0_pos0" in pos_sheet.columns
        report = open(paths["report"]).read()
        assert "Consistency: 75.00%" in report
        prompts = open(paths["prompts"]).read()
        assert "P-orig-r" in prompts and "CONFIDENCE PROMPT" in prompts
        assert summary_frame(analysis)["n_samples"].iloc[0] == 5

    def test_missing_original_falls_back_to_mode(self):
        from llm_interpretation_replication_tpu.analysis.irrelevant_eval import (
            analyze_results,
        )

        df = self._df()
        df = df[df["perturbation_id"] != "original"]
        a = analyze_results(df)["S1"]["gpt"]
        assert a["original_response"] == "Covered"          # modal perturbed
        assert a["confidence_stats"]["original_confidence"] == pytest.approx(76.25)
        assert a["original_response_prompt"] == "N/A - Original missing"


class TestCombinedConfidence:
    def test_combiner_and_figure(self, tmp_path):
        rng = np.random.default_rng(2)
        scenarios = _scenarios(2)
        frames = {
            m: _workbook(rng, scenarios, model=m, rows_per_scenario=40)
            for m in ("GPT-4.1", "Claude", "Gemini")
        }
        out = run_combined_analysis(frames, str(tmp_path))
        assert len(out["stats"]) == 6  # 2 scenarios x 3 models
        assert len(out["correlations"]) == 3
        assert os.path.exists(out["figure"])
        analyzer = ModelConfidenceAnalyzer(frames)
        assert set(analyzer.models) == set(frames)


class TestModelComparison:
    def _frame(self, rng):
        rows = []
        for i in range(40):
            base = rng.uniform(0, 1)
            for model, noise in (("org/a-7b", 0.02), ("org/b-7b", 0.02), ("org/c-7b", 1.0)):
                v = rng.uniform(0, 1) if noise > 0.5 else np.clip(base + rng.normal(0, noise), 0, 1)
                rows.append({"prompt": f"q{i}", "model": model, "relative_prob": float(v)})
        return pd.DataFrame(rows)

    def test_report(self, tmp_path):
        rng = np.random.default_rng(3)
        report = model_comparison_report(
            self._frame(rng), str(tmp_path), n_bootstrap=100,
            reference_model="org/c-7b",
        )
        assert len(report["pairwise"]) == 3
        ab = report["pairwise"][
            (report["pairwise"].model_1 == "org/a-7b")
            & (report["pairwise"].model_2 == "org/b-7b")
        ].iloc[0]
        assert ab["pearson_r"] > 0.9
        assert os.path.exists(report["heatmap"])
        assert os.path.exists(report["difference_strip"])

    def test_cross_experiment_kappa(self):
        rng = np.random.default_rng(4)
        k = cross_experiment_kappa([self._frame(rng), self._frame(rng)], n_bootstrap=50)
        assert len(k["pairs"]) == 3


class TestBaseVsInstructFigs:
    def test_figures_written(self, tmp_path):
        rng = np.random.default_rng(5)
        rows = []
        for fam, (b, i) in {"falcon": ("org/falcon-7b", "org/falcon-7b-instruct"),
                            "bloom": ("org/bloom-7b", "org/bloomz-7b")}.items():
            for q in range(20):
                for model, role in ((b, "base"), (i, "instruct")):
                    rows.append({
                        "prompt": f"q{q}", "model": model, "model_family": fam,
                        "base_or_instruct": role,
                        "yes_prob": rng.uniform(0.1, 0.9),
                        "no_prob": rng.uniform(0.1, 0.9),
                        "relative_prob": rng.uniform(0, 1),
                    })
        paths = base_vs_instruct_figures(pd.DataFrame(rows), str(tmp_path))
        assert os.path.exists(paths["difference_strips"])
        assert os.path.exists(paths["heatmap"])


class TestSimilarityReport:
    def test_report_workbook(self, tmp_path):
        records = [{
            "original_main": "Is a screenshot a photograph?",
            "rephrasings": [
                "Would a screenshot count as a photograph?",
                "Can a screenshot be considered a photograph?",
                "Do bananas grow on trees in cold climates?",
            ],
        }]
        summary = similarity_report(records, str(tmp_path))
        assert set(summary["metric"]) == {
            "tfidf_cosine_similarity", "bm25_similarity", "levenshtein_similarity",
        }
        assert os.path.exists(tmp_path / "original_vs_rephrasings_similarity.xlsx")
        assert os.path.exists(tmp_path / "scenario_1_original_vs_rephrasings.csv")
