"""Statistics-layer tests: seeded regression values, statistical sanity on
known distributions, and behavioral checks against reference semantics."""

import numpy as np
import pandas as pd
import pytest

from llm_interpretation_replication_tpu.stats import (
    BM25Okapi,
    base_vs_instruct_analysis,
    bootstrap_mae,
    bootstrap_mae_difference,
    calculate_all_similarities,
    check_confidence_compliance,
    check_first_and_full,
    check_output_compliance,
    classify_confidence_response,
    cohens_kappa,
    correlation_summary_bootstrap,
    fisher_z_pvalue,
    fit_clipped_normal,
    normality_tests,
    paired_mean_diff_bootstrap,
    pairwise_correlations,
    pairwise_kappa,
    pivot_model_values,
    required_sample_size,
    simulated_power,
)


class TestNormality:
    def test_normal_data_accepted(self):
        rng = np.random.default_rng(0)
        res = normality_tests(rng.normal(0.5, 0.1, 2000))
        assert res["ks_normal"] and res["ad_normal"]
        assert res["ad_p"] == 0.15

    def test_bimodal_rejected(self):
        rng = np.random.default_rng(1)
        data = np.concatenate([rng.normal(0, 0.05, 1000), rng.normal(1, 0.05, 1000)])
        res = normality_tests(data)
        assert not res["ks_normal"] and not res["ad_normal"]
        assert res["ad_p"] == 0.0001  # large statistic band

    def test_insufficient_data(self):
        res = normality_tests([0.5, 0.6])
        assert np.isnan(res["ks_stat"]) and not res["ks_normal"]

    def test_nonfinite_filtered(self):
        rng = np.random.default_rng(2)
        data = np.concatenate([rng.normal(0, 1, 500), [np.nan, np.inf, -np.inf]])
        res = normality_tests(data)
        assert res["n"] == 500


class TestTruncatedNormal:
    def test_fit_recovers_clipped_normal(self):
        rng = np.random.default_rng(3)
        data = np.clip(rng.normal(0.7, 0.3, 3000), 0, 1)
        res, sim = fit_clipped_normal(data, n_simulations=50_000, seed=42)
        assert res["fit"] == "ok"
        assert res["mean_relative_error"] < 0.01
        assert res["std_relative_error"] < 0.02
        # a clipped normal should be judged adequate against itself
        assert res["adequate_ks"]
        assert abs(res["underlying_mean"] - 0.7) < 0.05
        assert res["zero_proportion"] > 0.0 and res["one_proportion"] > 0.1

    def test_uniform_data_rejected(self):
        rng = np.random.default_rng(4)
        data = rng.uniform(0, 1, 3000)
        res, _ = fit_clipped_normal(data, n_simulations=50_000, seed=42)
        assert res["fit"] == "ok"
        assert not res["adequate"]  # uniform is not a clipped normal

    def test_all_boundary_fails_cleanly(self):
        res, sim = fit_clipped_normal(np.array([0.0] * 5 + [1.0] * 5))
        assert res["fit"] == "failed-all-boundary"
        assert sim.size == 0

    def test_reproducible_with_seed(self):
        data = np.clip(np.random.default_rng(5).normal(0.4, 0.2, 500), 0, 1)
        r1, s1 = fit_clipped_normal(data, n_simulations=10_000, seed=7)
        r2, s2 = fit_clipped_normal(data, n_simulations=10_000, seed=7)
        assert r1["ks_p"] == r2["ks_p"]
        np.testing.assert_array_equal(s1, s2)


class TestBootstrap:
    def test_mae_ci_contains_mean_seeded(self):
        rng = np.random.default_rng(6)
        errors = np.abs(rng.normal(0.2, 0.05, 100))
        mean, lo, hi = bootstrap_mae(errors, seed=42)
        assert lo < mean < hi
        # seeded regression: repeatable
        mean2, lo2, hi2 = bootstrap_mae(errors, seed=42)
        assert (mean, lo, hi) == (mean2, lo2, hi2)

    def test_mae_empty(self):
        assert bootstrap_mae([]) == (None, None, None)

    def test_mae_difference_detects_real_gap(self):
        rng = np.random.default_rng(7)
        model = np.abs(rng.normal(0.30, 0.05, 200))
        baseline = np.abs(rng.normal(0.20, 0.05, 200))
        diff, lo, hi, p = bootstrap_mae_difference(model, baseline, seed=42)
        assert diff > 0.05
        assert p < 0.01
        assert lo < diff < hi

    def test_mae_difference_null_not_significant(self):
        rng = np.random.default_rng(8)
        a = np.abs(rng.normal(0.2, 0.05, 100))
        b = np.abs(rng.normal(0.2, 0.05, 100))
        _, _, _, p = bootstrap_mae_difference(a, b, seed=42)
        assert p > 0.05

    def test_mae_difference_scalar_baseline(self):
        rng = np.random.default_rng(9)
        model = np.abs(rng.normal(0.3, 0.05, 100))
        diff, lo, hi, p = bootstrap_mae_difference(model, 0.2, seed=42)
        assert abs(diff - (np.mean(model) - 0.2)) < 1e-12

    def test_paired_diff(self):
        rng = np.random.default_rng(10)
        diffs = rng.normal(0.1, 0.2, 100)
        res = paired_mean_diff_bootstrap(diffs, seed=42)
        assert res["n"] == 100
        assert res["ci_lower"] < res["mean_diff"] < res["ci_upper"]

    def test_base_vs_instruct_frame_analysis(self):
        rng = np.random.default_rng(11)
        rows = []
        for i in range(40):
            rows.append({"model_family": "Fam", "base_or_instruct": "base",
                         "prompt": f"q{i}", "relative_prob": rng.uniform(0.2, 0.4)})
            rows.append({"model_family": "Fam", "base_or_instruct": "instruct",
                         "prompt": f"q{i}", "relative_prob": rng.uniform(0.5, 0.8)})
        out = base_vs_instruct_analysis(pd.DataFrame(rows), seed=42)
        assert out["Fam"]["mean_diff"] > 0.2
        assert out["Fam"]["p_value"] < 0.01


class TestCorrelations:
    def _frame(self):
        rng = np.random.default_rng(12)
        base = rng.uniform(0, 1, 50)
        rows = []
        for i, v in enumerate(base):
            rows.append({"prompt": f"q{i}", "model": "a", "relative_prob": v})
            rows.append({"prompt": f"q{i}", "model": "b",
                         "relative_prob": np.clip(v + rng.normal(0, 0.05), 0, 1)})
            rows.append({"prompt": f"q{i}", "model": "c", "relative_prob": rng.uniform(0, 1)})
        return pd.DataFrame(rows)

    def test_pairwise_correlations(self):
        pivot = pivot_model_values(self._frame())
        corr = pairwise_correlations(pivot)
        assert len(corr) == 3
        ab = corr[(corr.model_1 == "a") & (corr.model_2 == "b")].iloc[0]
        assert ab["pearson_r"] > 0.9
        ac = corr[(corr.model_1 == "a") & (corr.model_2 == "c")].iloc[0]
        assert abs(ac["pearson_r"]) < 0.5

    def test_summary_bootstrap(self):
        pivot = pivot_model_values(self._frame())
        summary = correlation_summary_bootstrap(pivot, n_bootstrap=200, seed=42)
        assert summary["n_pairs"] == 3
        assert summary["mean_ci"][0] <= summary["mean"] <= summary["mean_ci"][1]

    def test_cohens_kappa_known_values(self):
        assert cohens_kappa([1, 1, 0, 0], [1, 1, 0, 0]) == pytest.approx(1.0)
        assert cohens_kappa([1, 1, 0, 0], [0, 0, 1, 1]) == pytest.approx(-1.0)
        # independent raters with balanced marginals -> kappa near 0
        rng = np.random.default_rng(13)
        a = rng.integers(0, 2, 2000)
        b = rng.integers(0, 2, 2000)
        assert abs(cohens_kappa(a, b)) < 0.1

    def test_pairwise_kappa(self):
        pivot = pivot_model_values(self._frame())
        res = pairwise_kappa(pivot, n_bootstrap=100, seed=42)
        assert len(res["pairs"]) == 3
        ab = [p for p in res["pairs"] if {p["model_1"], p["model_2"]} == {"a", "b"}][0]
        assert ab["kappa"] > 0.6

    def test_fisher_z(self):
        p = fisher_z_pvalue(0.5, 100)
        assert p < 0.001
        assert fisher_z_pvalue(0.0, 100) == pytest.approx(1.0)


class TestCompareCorrelationDistributions:
    """The 57th coverage row (VERDICT Missing #2): the reference's
    compare_distributions (calculate_correlation_pvalues.py:138-205) —
    Mann-Whitney/KS/t-test/Cohen's d over two correlation samples plus the
    proportion of significant correlations."""

    def _samples(self):
        rng = np.random.default_rng(7)
        within = np.clip(rng.normal(0.75, 0.08, 60), -1, 1)
        between = np.clip(rng.normal(0.45, 0.12, 80), -1, 1)
        return within, between

    def test_separated_distributions_all_tests_agree(self):
        from llm_interpretation_replication_tpu.stats import (
            compare_correlation_distributions,
        )

        within, between = self._samples()
        out = compare_correlation_distributions(
            within, between, labels=("within", "between"))
        assert out["mannwhitney_p"] < 1e-6
        assert out["ks_p"] < 1e-6
        assert out["t_p"] < 1e-6
        assert out["cohens_d"] > 1.0  # large standardized effect
        assert out["within"]["n"] == 60 and out["between"]["n"] == 80
        assert out["within"]["mean"] > out["between"]["mean"]

    def test_identical_distributions_null_holds(self):
        from llm_interpretation_replication_tpu.stats import (
            compare_correlation_distributions,
        )

        rng = np.random.default_rng(8)
        a = rng.normal(0.5, 0.1, 200)
        b = rng.normal(0.5, 0.1, 200)
        out = compare_correlation_distributions(a, b)
        assert out["mannwhitney_p"] > 0.01
        assert out["ks_p"] > 0.01
        assert abs(out["cohens_d"]) < 0.25

    def test_cohens_d_known_value(self):
        """Two point-mass-ish samples with unit pooled std: d = mean gap."""
        from llm_interpretation_replication_tpu.stats import (
            compare_correlation_distributions,
        )

        a = np.array([0.0, 2.0] * 50)   # mean 1, var ~1.01
        b = np.array([1.0, 3.0] * 50)   # mean 2, same spread
        out = compare_correlation_distributions(a, b)
        assert out["cohens_d"] == pytest.approx(-1.0, abs=0.01)

    def test_proportion_significant_and_nan_policy(self):
        from llm_interpretation_replication_tpu.stats import (
            compare_correlation_distributions,
        )

        within, between = self._samples()
        out = compare_correlation_distributions(
            np.concatenate([within, [np.nan]]), between,
            labels=("w", "b"),
            p_values_a=[0.01] * 45 + [0.5] * 15,
            p_values_b=[0.2] * 80,
            alpha=0.05,
        )
        assert out["w"]["n"] == 60  # the NaN correlation dropped
        assert out["w"]["proportion_significant"] == pytest.approx(0.75)
        assert out["b"]["proportion_significant"] == 0.0

    def test_too_few_finite_values_raises(self):
        from llm_interpretation_replication_tpu.stats import (
            compare_correlation_distributions,
        )

        with pytest.raises(ValueError):
            compare_correlation_distributions([0.5], [0.1, 0.2, 0.3])


class TestCompliance:
    def test_first_and_full(self):
        exp = {
            "first_tokens": ["Covered", "Not"],
            "full_responses": {"Covered": ["Covered"], "Not": ["Not Covered"]},
        }
        assert check_first_and_full("Covered", "Covered", exp) == (True, True)
        assert check_first_and_full("Not", "Not Covered", exp) == (True, True)
        assert check_first_and_full("Not", "Not covered at all", exp) == (True, False)
        assert check_first_and_full("The", "The policy covers", exp) == (False, None)

    def test_confidence_classification(self):
        assert classify_confidence_response("85") == "compliant"
        assert classify_confidence_response(" 100 ") == "compliant"
        assert classify_confidence_response("150") == "out_of_range"
        assert classify_confidence_response("85.5") == "float"
        assert classify_confidence_response("I think 85") == "text"

    def test_workbook_compliance_rates(self):
        df = pd.DataFrame(
            [
                {"Original Main Part": "s1", "Model Response": "Covered",
                 "Model Confidence Response": "85", "Log Probabilities": "", "Relative_Prob": 0.8},
                {"Original Main Part": "s1", "Model Response": "Not Covered",
                 "Model Confidence Response": "90.5", "Log Probabilities": "", "Relative_Prob": 0.2},
                {"Original Main Part": "s1", "Model Response": "It depends on the policy",
                 "Model Confidence Response": "maybe 50", "Log Probabilities": "", "Relative_Prob": 0.5},
            ]
        )
        out = check_output_compliance(df)
        row = out.iloc[0]
        assert row["Total_Samples"] == 3
        assert row["First_Token_Compliant"] == 2
        conf = check_confidence_compliance(df)
        assert conf.iloc[0]["Confidence_Compliant"] == 1
        assert conf.iloc[0]["Float_Errors"] == 1
        assert conf.iloc[0]["Text_Errors"] == 1

    def test_api_logprobs_path(self):
        lp = str({"content": [{"token": "Not"}, {"token": " Covered"}]})
        df = pd.DataFrame(
            [{"Original Main Part": "s1", "Model Response": "",
              "Model Confidence Response": "10", "Log Probabilities": lp,
              "Relative_Prob": 0.1}]
        )
        out = check_output_compliance(df)
        assert out.iloc[0]["First_Token_Compliant"] == 1
        assert out.iloc[0]["Conditional_Subsequent_Compliant"] == 1


class TestSimilarity:
    def test_all_metrics_rank_similar_higher(self):
        original = "Is a screenshot a photograph for copyright purposes?"
        close = "For copyright purposes, is a screenshot considered a photograph?"
        far = "Bananas grow in tropical climates around the equator."
        res = calculate_all_similarities(original, [close, far])
        ov = res["original_vs_rephrasings"]
        for metric in ("tfidf_cosine_similarity", "bm25_similarity", "levenshtein_similarity"):
            assert ov[0][metric] > ov[1][metric], metric
        assert set(res["summary_stats"]) == {
            "tfidf_cosine_similarity", "bm25_similarity", "levenshtein_similarity",
        }

    def test_bm25_scores_self_highest(self):
        corpus = [["a", "b", "c"], ["a", "b"], ["x", "y", "z"]]
        bm = BM25Okapi(corpus)
        scores = bm.get_scores(["x", "y", "z"])
        assert np.argmax(scores) == 2


class TestNativeLevenshtein:
    def test_native_matches_python(self):
        from llm_interpretation_replication_tpu.native import (
            _levenshtein_py,
            levenshtein,
            using_native,
        )

        assert using_native()
        rng = np.random.default_rng(14)
        import string

        for _ in range(50):
            a = "".join(rng.choice(list(string.ascii_lowercase + " é漢")) for _ in range(rng.integers(0, 30)))
            b = "".join(rng.choice(list(string.ascii_lowercase + " é漢")) for _ in range(rng.integers(0, 30)))
            assert levenshtein(a, b) == _levenshtein_py(a, b), (a, b)


class TestPower:
    def test_sample_size_formula(self):
        res = required_sample_size(0.05, 0.1)  # effect size 0.5
        # classic n ≈ 31.5 for d=0.5, power .80 -> ~32 with t-correction
        assert 30 <= res["sample_sizes"]["power_80"]["raw"] <= 35
        assert res["sample_sizes"]["power_80"]["with_margin"] >= res["sample_sizes"]["power_80"]["raw"]

    def test_zero_effect_infinite(self):
        res = required_sample_size(0.0, 0.1)
        assert res["sample_sizes"]["power_80"]["raw"] == np.inf

    def test_simulated_power_matches_analytic(self):
        # d=0.5 at n=32 should give ~80% power
        p = simulated_power(0.05, 0.1, 32, n_simulations=4000, seed=42)
        assert 0.74 <= p <= 0.86

    def test_power_report(self, tmp_path):
        from llm_interpretation_replication_tpu.stats import power_report

        # the reference's pilot numbers (power_analysis.py:103-132)
        models = {
            "GPT": {"mae": 0.205, "mae_std": 0.126, "mae_diff": 0.032,
                    "ci_lower": -0.017, "ci_upper": 0.082},
            "Claude": {"mae": 0.232, "mae_std": 0.129, "mae_diff": 0.059,
                       "ci_lower": 0.008, "ci_upper": 0.109},
        }
        tex = tmp_path / "power_analysis_report.tex"
        report = power_report(models, baseline_mae=0.180, sample_size=50,
                              n_simulations=2000, output_tex=str(tex))
        # GPT has the smaller effect -> it is the limiting model
        assert report["recommendation"]["power_80"]["limiting_model"] == "GPT"
        assert (report["models"]["GPT"]["sample_sizes"]["power_80"]["raw"]
                > report["models"]["Claude"]["sample_sizes"]["power_80"]["raw"])
        # Claude's CI excludes zero, GPT's doesn't
        assert report["models"]["Claude"]["significant"]
        assert not report["models"]["GPT"]["significant"]
        # achieved power at N=50 is low for GPT (underpowered pilot)
        assert report["models"]["GPT"]["achieved_power"] < 0.6
        content = tex.read_text()
        assert "\\begin{tabular}" in content and "GPT" in content

    def test_power_report_zero_effect_limits(self, tmp_path):
        from llm_interpretation_replication_tpu.stats import power_report

        models = {
            "Flat": {"mae": 0.2, "mae_std": 0.1, "mae_diff": 0.0,
                     "ci_lower": -0.05, "ci_upper": 0.05},
            "Real": {"mae": 0.25, "mae_std": 0.1, "mae_diff": 0.05,
                     "ci_lower": 0.01, "ci_upper": 0.09},
        }
        tex = tmp_path / "report.tex"
        report = power_report(models, baseline_mae=0.18, sample_size=50,
                              n_simulations=500, output_tex=str(tex))
        # the unpowerable model must surface as the limiting factor, not be
        # silently dropped
        rec = report["recommendation"]["power_80"]
        assert rec["raw"] == np.inf and rec["limiting_model"] == "Flat"
        assert "No finite $N$" in tex.read_text()
