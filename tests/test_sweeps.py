"""Sweep orchestration tests with a deterministic fake engine: schema-exact
outputs, checkpoint/resume, error-row behavior."""

import hashlib

import numpy as np
import pandas as pd
import pytest

from llm_interpretation_replication_tpu.sweeps import (
    BASE_VS_INSTRUCT_100Q_COLUMNS,
    INSTRUCT_COMPARISON_COLUMNS,
    MODEL_COMPARISON_COLUMNS,
    PERTURBATION_COLUMNS,
    run_base_vs_instruct_word_meaning,
    run_instruct_sweep,
    run_model_perturbation_sweep,
    run_sweep,
)
from llm_interpretation_replication_tpu.utils.xlsx import read_xlsx


class FakeEngine:
    """Deterministic scoring from a hash of (model, prompt)."""

    def __init__(self, model_name, fail=False):
        self.model_name = model_name
        self.fail = fail
        self.calls = 0

    def _p(self, prompt):
        h = hashlib.sha256(f"{self.model_name}|{prompt}".encode()).digest()
        return h[0] / 255.0, h[1] / 255.0

    def score_prompts(self, prompts, targets=("Yes", "No"),
                      with_confidence=False, max_new_tokens=None):
        if self.fail:
            raise RuntimeError("simulated OOM")
        self.calls += 1
        rows = []
        for p in prompts:
            a, b = self._p(p)
            total = a + b
            row = {
                "yes_prob": a,
                "no_prob": b,
                "relative_prob": a / total if total else 0.5,
                "odds_ratio": a / b if b else float("inf"),
                "scan_found": True,
                "completion": "Yes" if a > b else "No",
                "success": True,
            }
            if with_confidence:
                row["weighted_confidence"] = round(100 * a, 2)
                row["completion"] = str(int(100 * a))
            rows.append(row)
        return rows

    def first_token_relative_prob(self, prompts, targets=("Yes", "No"), top_filter=0):
        out = np.zeros((len(prompts), 3))
        for i, p in enumerate(prompts):
            a, b = self._p(p)
            out[i] = (a, b, a / (a + b))
        return out

    def target_ids(self, targets):
        return [1, 2]


PAIRS = [
    {"base": "fake/alpha-7b", "instruct": "fake/alpha-7b-instruct", "family": "Alpha"},
    {"base": "fake/beta-7b", "instruct": "fake/beta-7b-instruct", "family": "Beta"},
]
QUESTIONS = [f'Is a "thing{i}" a "stuff{i}"?' for i in range(5)]


class TestBaseVsInstruct100q:
    def test_schema_and_rows(self, tmp_path):
        made = []

        def factory(name):
            made.append(name)
            return FakeEngine(name)

        df = run_sweep(
            factory, model_pairs=PAIRS, prompts=QUESTIONS,
            checkpoint_path=str(tmp_path / "ck.json"),
            results_csv=str(tmp_path / "out.csv"),
        )
        assert list(df.columns) == BASE_VS_INSTRUCT_100Q_COLUMNS
        assert len(df) == 4 * len(QUESTIONS)
        assert set(df["base_or_instruct"]) == {"base", "instruct"}
        assert set(df["model_family"]) == {"Alpha", "Beta"}
        assert df["success"].all()
        saved = pd.read_csv(tmp_path / "out.csv")
        assert len(saved) == len(df)

    def test_resume_skips_completed(self, tmp_path):
        factory_calls = []

        def factory(name):
            factory_calls.append(name)
            return FakeEngine(name)

        ck = str(tmp_path / "ck.json")
        csv = str(tmp_path / "out.csv")
        run_sweep(factory, model_pairs=PAIRS[:1], prompts=QUESTIONS,
                  checkpoint_path=ck, results_csv=csv)
        n_first = len(factory_calls)
        # second run with both pairs: only the new pair's models load
        run_sweep(factory, model_pairs=PAIRS, prompts=QUESTIONS,
                  checkpoint_path=ck, results_csv=csv)
        assert n_first == 2
        assert factory_calls[n_first:] == ["fake/beta-7b", "fake/beta-7b-instruct"]

    def test_error_rows_keep_sweep_alive(self, tmp_path):
        def factory(name):
            return FakeEngine(name, fail="beta" in name)

        df = run_sweep(
            factory, model_pairs=PAIRS, prompts=QUESTIONS,
            checkpoint_path=str(tmp_path / "ck.json"),
            results_csv=str(tmp_path / "out.csv"),
        )
        beta = df[df["model"].str.contains("beta")]
        assert (~beta["success"].astype(bool)).all()
        assert beta["completion"].str.startswith("MODEL_ERROR").all()
        alpha = df[df["model"].str.contains("alpha")]
        assert alpha["success"].all()


class TestInstructSweep:
    def test_schema(self, tmp_path):
        df = run_instruct_sweep(
            lambda name: FakeEngine(name),
            prompts=QUESTIONS,
            models=["fake/gamma-7b-instruct", "fake/delta-7b-chat"],
            checkpoint_path=str(tmp_path / "ck.json"),
            results_csv=str(tmp_path / "out.csv"),
        )
        assert list(df.columns) == INSTRUCT_COMPARISON_COLUMNS
        assert set(df["model_family"]) == {"gamma", "delta"}

    def test_checkpoint_rejects_different_prompt_set(self, tmp_path):
        """The checkpoint is keyed by model name; a checkpoint from a
        DIFFERENT question list (e.g. the 50q sweep's, reused by a survey-2
        run) must be discarded, not silently replayed as the new sweep."""
        ck = str(tmp_path / "ck.json")
        models = ["fake/gamma-7b-instruct"]
        df1 = run_instruct_sweep(
            lambda name: FakeEngine(name), prompts=QUESTIONS, models=models,
            checkpoint_path=ck, results_csv=str(tmp_path / "a.csv"),
        )
        # same prompts -> checkpoint honored (no rescoring)
        factory_calls = []

        def factory(name):
            factory_calls.append(name)
            return FakeEngine(name)

        run_instruct_sweep(
            factory, prompts=QUESTIONS, models=models,
            checkpoint_path=ck, results_csv=str(tmp_path / "b.csv"),
        )
        assert factory_calls == []
        # different prompts -> stale checkpoint discarded, models rescored
        other = [q + " (survey 2)" for q in QUESTIONS]
        df2 = run_instruct_sweep(
            factory, prompts=other, models=models,
            checkpoint_path=ck, results_csv=str(tmp_path / "c.csv"),
        )
        assert factory_calls == models
        assert set(df2["prompt"]) == set(other)
        assert set(df2["prompt"]) != set(df1["prompt"])

    def test_word_meaning_pairs_schema(self, tmp_path):
        df = run_base_vs_instruct_word_meaning(
            lambda name: FakeEngine(name),
            prompts=QUESTIONS,
            model_pairs=[{"base": "fake/eps-7b", "instruct": "fake/eps-7b-instruct"}],
            checkpoint_path=str(tmp_path / "ck.json"),
            results_csv=str(tmp_path / "out.csv"),
        )
        assert list(df.columns) == MODEL_COMPARISON_COLUMNS
        assert set(df["base_or_instruct"]) == {"base", "instruct"}


class TestPerturbationSweep:
    SCENARIOS = [
        {
            "original_main": "Scenario one text.",
            "response_format": "Answer only 'Covered' or 'Not Covered'.",
            "target_tokens": ["Covered", "Not"],
            "confidence_format": "How confident are you, 0 to 100?",
            "rephrasings": [f"Rephrasing {i} of one." for i in range(6)],
        },
        {
            "original_main": "Scenario two text.",
            "response_format": "Answer only 'First' or 'Ultimate'.",
            "target_tokens": ["Ultimate", "First"],
            "confidence_format": "How confident, 0-100?",
            "rephrasings": [f"Rephrasing {i} of two." for i in range(4)],
        },
    ]

    def test_workbook_schema_and_content(self, tmp_path):
        out = str(tmp_path / "results.xlsx")
        df = run_model_perturbation_sweep(
            FakeEngine("fake/model-7b"), "fake/model-7b", self.SCENARIOS, out,
            checkpoint_every=3,
        )
        assert list(df.columns) == PERTURBATION_COLUMNS
        assert len(df) == 10
        back = read_xlsx(out)
        assert list(back.columns) == PERTURBATION_COLUMNS
        assert len(back) == 10
        row = back.iloc[0]
        assert row["Full Rephrased Prompt"] == (
            f"{row['Rephrased Main Part']} {row['Response Format']}"
        )
        assert row["Token_1_Prob"] > 0 or row["Token_2_Prob"] > 0

    def test_resume_skips_done_rows(self, tmp_path):
        out = str(tmp_path / "results.xlsx")
        run_model_perturbation_sweep(
            FakeEngine("fake/model-7b"), "fake/model-7b",
            [dict(self.SCENARIOS[0], rephrasings=self.SCENARIOS[0]["rephrasings"][:3])],
            out,
        )
        eng = FakeEngine("fake/model-7b")
        df = run_model_perturbation_sweep(
            eng, "fake/model-7b", self.SCENARIOS, out
        )
        assert len(df) == 10
        # no duplicated rows after resume
        keys = df["Rephrased Main Part"].tolist()
        assert len(keys) == len(set(keys))

    def test_foreign_engine_old_signature_still_works(self, tmp_path):
        """Duck-typed engines predating the per-call max_new_tokens kwarg
        (score_prompts(prompts, targets, with_confidence)) keep working —
        the confidence cap is passed only to engines that accept it."""

        class OldEngine(FakeEngine):
            def score_prompts(self, prompts, targets=("Yes", "No"),
                              with_confidence=False):
                return FakeEngine.score_prompts(
                    self, prompts, targets, with_confidence)

        out = str(tmp_path / "results.xlsx")
        df = run_model_perturbation_sweep(
            OldEngine("fake/old-7b"), "fake/old-7b",
            [self.SCENARIOS[0]], out,
        )
        assert len(df) == 6
        assert df["Confidence Value"].notna().all()

    def test_sidelog_crash_resume(self, tmp_path):
        """Checkpoint flushes append to the O(new-rows) side-log; a crash
        before the final xlsx render loses nothing — resume reads the
        side-log, skips its rows, and the final workbook folds them in
        (then deletes the side-log)."""
        import json as jsonlib
        import os

        from llm_interpretation_replication_tpu.sweeps.perturbation import (
            _sidelog_path,
        )

        out = str(tmp_path / "results.xlsx")
        run_model_perturbation_sweep(
            FakeEngine("fake/model-7b"), "fake/model-7b",
            [dict(self.SCENARIOS[0],
                  rephrasings=self.SCENARIOS[0]["rephrasings"][:3])],
            out,
        )
        assert not os.path.exists(_sidelog_path(out))  # clean finish
        # simulate a crash mid-run: the 3 finished rows live ONLY in the
        # side-log (no rendered workbook yet)
        done = read_xlsx(out).to_dict("records")
        os.remove(out)
        with open(_sidelog_path(out), "w") as f:
            for row in done:
                f.write(jsonlib.dumps(row) + "\n")
        eng = FakeEngine("fake/model-7b")
        df = run_model_perturbation_sweep(
            eng, "fake/model-7b", self.SCENARIOS, out
        )
        assert len(df) == 10
        keys = df["Rephrased Main Part"].tolist()
        assert len(keys) == len(set(keys))           # crash rows not redone
        back = read_xlsx(out)
        assert len(back) == 10                       # final render has all
        assert not os.path.exists(_sidelog_path(out))  # consumed


class TestPerturbationSweepRealEngine:
    def test_end_to_end_with_real_engine_and_mixed_targets(self, tmp_path):
        """The full local sweep against a REAL tiny ScoringEngine: two
        scenarios with different (and swapped) target pairs score in one
        cross-scenario pass, the binary leg's Token_i_Prob comes from the
        FUSED first-token fields (verified equal to a standalone
        first_token_relative_prob call), and the confidence leg fills
        Confidence Value / Weighted Confidence from real decodes."""
        from test_runtime import _tiny_engine

        eng, _, _ = _tiny_engine(batch_size=8)
        scenarios = [
            {
                "original_main": "Scenario one text.",
                "response_format": "Answer only 'Yes' or 'No'.",
                "target_tokens": ["Yes", "No"],
                "confidence_format": "How confident, 0-100?",
                "rephrasings": [f"Is thing {i} a stuff?" for i in range(3)],
            },
            {
                "original_main": "Scenario two text.",
                "response_format": "Answer only 'No' or 'Yes'.",
                "target_tokens": ["No", "Yes"],
                "confidence_format": "Confidence from 0 to 100?",
                "rephrasings": [f"Does item {i} count?" for i in range(3)],
            },
        ]
        out = str(tmp_path / "results.xlsx")
        df = run_model_perturbation_sweep(
            eng, "tiny/real-engine", scenarios, out, checkpoint_every=2,
        )
        assert list(df.columns) == PERTURBATION_COLUMNS
        assert len(df) == 6
        # fused binary leg == the standalone fast path, per scenario targets
        for scenario in scenarios:
            prompts = [f"{r} {scenario['response_format']}"
                       for r in scenario["rephrasings"]]
            fast = eng.first_token_relative_prob(
                prompts, targets=list(scenario["target_tokens"]),
                top_filter=20)
            sub = df[df["Original Main Part"] == scenario["original_main"]]
            np.testing.assert_allclose(
                sub["Token_1_Prob"].to_numpy(dtype=float), fast[:, 0],
                rtol=1e-6)
            np.testing.assert_allclose(
                sub["Token_2_Prob"].to_numpy(dtype=float), fast[:, 1],
                rtol=1e-6)
        # confidence leg ran real decodes
        assert (df["Model Confidence Response"].astype(str).str.len() > 0).any()
