"""Tier-1 ``-m perf`` smoke test: a 2-chunk FUSED perturbation sweep on the
in-process harness must engage the prefix-reuse path (nonzero prefix-hit
counter), keep the prefix pool consistent, and emit rows matching the
15-column workbook contract — the fast canary that the perf layer did not
silently fall back to unfused scoring."""

import numpy as np
import pytest

from test_runtime import _tiny_engine

from llm_interpretation_replication_tpu.sweeps import (
    run_model_perturbation_sweep,
)
from llm_interpretation_replication_tpu.sweeps.writers import (
    PERTURBATION_COLUMNS,
)
from llm_interpretation_replication_tpu.utils import telemetry

SCENARIOS = [
    {
        "original_main": "Scenario one text.",
        "response_format": "Answer only 'Yes' or 'No'.",
        "target_tokens": ["Yes", "No"],
        "confidence_format": "How confident, 0-100?",
        "rephrasings": [f"Is thing {i} a stuff?" for i in range(4)],
    },
    {
        "original_main": "Scenario two text.",
        "response_format": "Answer only 'No' or 'Yes'.",
        "target_tokens": ["No", "Yes"],
        "confidence_format": "Confidence from 0 to 100?",
        "rephrasings": [f"Does item {i} count?" for i in range(4)],
    },
]


@pytest.mark.perf
def test_two_chunk_fused_sweep_smoke(tmp_path):
    eng, _, _ = _tiny_engine(batch_size=4)
    telemetry.clear_counters()
    out = str(tmp_path / "results.xlsx")
    df = run_model_perturbation_sweep(
        eng, "tiny/perf-smoke", SCENARIOS, out,
        checkpoint_every=3, score_chunk=4,  # 8 rows -> exactly 2 chunks
    )
    # 15-column workbook contract, one row per rephrasing
    assert list(df.columns) == PERTURBATION_COLUMNS
    assert len(df) == 8
    assert df["Token_1_Prob"].astype(float).notna().all()
    assert (df["Model Confidence Response"].astype(str).str.len() > 0).any()
    # the fused path actually engaged: each row's confidence leg rode the
    # binary leg's prefix cache...
    assert telemetry.counter("prefix_hit") == 8
    assert telemetry.counter("prefix_miss") == 8
    # ...the 2-chunk host pipeline served both chunks...
    assert telemetry.counter("host_overlap_chunks") == 2
    # ...and every prefix cache entry was released exactly once
    assert eng.last_prefix_pool.consistent
