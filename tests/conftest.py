"""Test harness configuration.

All tests run on a virtual 8-device CPU mesh (the standard JAX substitute for
multi-chip hardware — SURVEY.md §4): JAX_PLATFORMS=cpu with
``--xla_force_host_platform_device_count=8``.  These env vars must be set
before jax initializes, hence the module-level assignments here.
"""

import os
import sys

# Force CPU: the session env pre-sets JAX_PLATFORMS=axon (the real TPU chip);
# tests always run on the virtual 8-device CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Keep HF offline: zero-egress image, tests build tiny local models only.
os.environ.setdefault("HF_HUB_OFFLINE", "1")
os.environ.setdefault("TRANSFORMERS_OFFLINE", "1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402

import jax  # noqa: E402

# The axon TPU plugin (sitecustomize) force-sets jax_platforms="axon,cpu";
# override at the config level so tests run on the virtual 8-device CPU mesh.
jax.config.update("jax_platforms", "cpu")
# XLA CPU's default matmul precision is bf16-like (~7e-2 error on unit-scale
# 64-dim dots); parity tests against torch fp32 need true fp32 matmuls.
jax.config.update("jax_default_matmul_precision", "highest")


@pytest.fixture(scope="session")
def eight_cpu_devices():
    import jax

    devices = jax.devices()
    assert len(devices) == 8, f"expected 8 virtual cpu devices, got {devices}"
    return devices
