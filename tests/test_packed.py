"""Packed multi-question batching + EOS-realistic decode brackets
(ISSUE 10, ``-m packed``, tier-1).

Pins the four contracts of the new workload shape:

- **anchor-gather correctness**: a packed row's FIRST question carries no
  packed context, so its anchor logits — and every probability field —
  are bit-identical to isolated scoring; single-question packs reproduce
  the isolated sweep everywhere.
- **measured-drift determinism**: the drift-parity block is a pure
  function of the two scoring passes — identical inputs emit identical
  blocks (distribution fields + flip rate populated).
- **EOS-typical bracket parity**: modifying ONLY the EOS unembedding row
  leaves every position-0-decided row's relative_prob/odds_ratio
  bit-identical (ratios of unchanged logits), while the completion
  decode early-stops and records ``decode_steps_saved`` — the bracket
  changes throughput, never decided judgments.
- **strict mode**: the packed sweep runs end-to-end under the d2h
  transfer guard with ``blocked_transfers == 0``.

Plus the ISSUE-10 satellites: the mined decided-rate calibration asset
validates the 0.87-0.92 targets (ROADMAP item 4's validation clause),
and the bench forwards the new bracket flags to its sweep-full child.
"""

import json
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402
from helpers import build_test_tokenizer, random_decoder_params  # noqa: E402
from llm_interpretation_replication_tpu.models.config import (  # noqa: E402
    DecoderConfig,
)
from llm_interpretation_replication_tpu.runtime.engine import (  # noqa: E402
    EngineConfig,
    ScoringEngine,
)
from llm_interpretation_replication_tpu.scoring import packed as pk  # noqa: E402
from llm_interpretation_replication_tpu.utils.telemetry import (  # noqa: E402
    counters,
)

pytestmark = pytest.mark.packed

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY = dict(
    vocab_size=300, hidden_size=32, num_layers=2, num_heads=4,
    intermediate_size=64, position_embedding="rotary", rotary_pct=0.25,
    max_position_embeddings=512,
)


@pytest.fixture(scope="module")
def engine():
    cfg = DecoderConfig(**TINY)
    tok = build_test_tokenizer()
    return ScoringEngine(
        "falcon", cfg, random_decoder_params(cfg), tok,
        engine_config=EngineConfig(batch_size=4, decode_completions=False,
                                   buckets=(32, 64, 96, 128, 192, 256)))


def _prompts(n=6):
    return [f"Is item number {i} a beverage? Answer only 'Yes' or 'No'."
            for i in range(n)]


# ---------------------------------------------------------------------------
# Formatter / encoding
# ---------------------------------------------------------------------------

class TestPackedEncoding:
    def test_anchors_point_at_last_prompt_token(self, engine):
        tok = engine.tokenizer
        prompts = _prompts(4)
        packs = pk.build_packs(prompts, 2, demos=["Yes"] * 4)
        rows, anchors = pk.encode_packs(tok, packs)
        assert len(rows) == 2 and [len(a) for a in anchors] == [2, 2]
        # question 0's segment IS the isolated tokenization, and its
        # anchor is its last token
        iso = tok(prompts[0])["input_ids"]
        assert rows[0][: len(iso)] == list(iso)
        assert anchors[0][0] == len(iso) - 1
        # the last question of a pack carries NO demo continuation:
        # tokens after the final anchor are causally dead
        assert anchors[0][-1] == len(rows[0]) - 1

    def test_demo_continuation_between_questions(self, engine):
        packs = pk.build_packs(_prompts(2), 2, demos=["Yes", "No"])
        (p0, d0), (p1, d1) = packs[0]
        assert d0 == " Yes.\n\n"     # question 0's OWN answer demonstrates
        assert d1 is None            # nothing follows the last anchor

    def test_build_packs_rejects_bad_packing(self):
        with pytest.raises(ValueError):
            pk.build_packs(_prompts(2), 0)

    def test_demos_from_relative_probs(self):
        demos = pk.demos_from_relative_probs(
            [0.9, 0.1, float("nan")],
            [["Yes", "No"]] * 3)
        assert demos == ["Yes", "No", "Yes"]   # NaN falls back to yes


# ---------------------------------------------------------------------------
# Anchor-gather position correctness on a tiny model
# ---------------------------------------------------------------------------

class TestAnchorCorrectness:
    def test_single_question_packs_reproduce_isolated_bitwise(self, engine):
        prompts = _prompts(6)
        targets = [["Yes", "No"]] * 6
        iso = engine.first_token_relative_prob(prompts, targets=targets,
                                               top_filter=0)
        rows = engine.score_packed(pk.build_packs(prompts, 1),
                                   targets=targets, top_filter=0)
        got = np.asarray([r["first_token_relative_prob"] for r in rows])
        np.testing.assert_array_equal(got, iso[:, 2])

    def test_first_question_of_each_pack_is_bit_identical(self, engine):
        """Question 0 has no packed context: its token stream equals the
        isolated prompt's, so the anchor logits are the same numbers even
        though the packed row pads to a LONGER bucket (masked softmax
        positions contribute exact zeros)."""
        prompts = _prompts(6)
        targets = [["Yes", "No"]] * 6
        iso = engine.first_token_relative_prob(prompts, targets=targets,
                                               top_filter=0)
        rows = engine.score_packed(pk.build_packs(prompts, 3),
                                   targets=targets, top_filter=0)
        rel = np.asarray([r["first_token_relative_prob"] for r in rows])
        assert rel[0] == iso[0, 2]
        assert rel[3] == iso[3, 2]
        # later questions see packed context and legitimately move
        assert not np.allclose(rel[1:3], iso[1:3, 2])

    def test_packed_rows_carry_the_result_contract(self, engine):
        prompts = _prompts(4)
        rows = engine.score_packed(pk.build_packs(prompts, 2),
                                   targets=[["Yes", "No"]] * 4)
        assert len(rows) == 4
        for row in rows:
            assert row["success"] and row["completion"] == ""
            for key in ("yes_prob", "no_prob", "relative_prob",
                        "odds_ratio", "first_token_yes_prob",
                        "first_token_no_prob",
                        "first_token_relative_prob"):
                assert key in row
        c = counters()
        assert c.get("packed_rows", 0) >= 2
        assert c.get("packed_questions", 0) >= 4

    def test_per_question_targets_route_to_the_right_anchor(self, engine):
        """Mixed-scenario packing: each question's (yes, no) pair scores
        at ITS anchor — swapping one question's pair must flip only that
        question's relative probability (to 1 - rel)."""
        prompts = _prompts(4)
        base = [["Yes", "No"]] * 4
        swapped = [["Yes", "No"], ["No", "Yes"],
                   ["Yes", "No"], ["Yes", "No"]]
        packs = pk.build_packs(prompts, 2)
        a = engine.score_packed(packs, targets=base, top_filter=0)
        b = engine.score_packed(packs, targets=swapped, top_filter=0)
        ra = np.asarray([r["first_token_relative_prob"] for r in a])
        rb = np.asarray([r["first_token_relative_prob"] for r in b])
        np.testing.assert_allclose(rb[1], 1.0 - ra[1], rtol=1e-6)
        np.testing.assert_array_equal(rb[[0, 2, 3]], ra[[0, 2, 3]])

    def test_t5_rejects_packed_scoring(self):
        eng = ScoringEngine("t5", None, None, None)
        with pytest.raises(ValueError, match="decoder-only"):
            eng.score_packed([[("q", None)]], targets=("Yes", "No"))


# ---------------------------------------------------------------------------
# Drift-parity determinism
# ---------------------------------------------------------------------------

class TestDriftReport:
    def test_report_is_deterministic_and_populated(self, engine):
        prompts = _prompts(6)
        targets = [["Yes", "No"]] * 6
        iso = engine.first_token_relative_prob(prompts, targets=targets,
                                               top_filter=0)
        packs = pk.build_packs(prompts, 3,
                               pk.demos_from_relative_probs(
                                   iso[:, 2], targets))

        def one():
            rows = engine.score_packed(packs, targets=targets,
                                       top_filter=0)
            rel = [r["first_token_relative_prob"] for r in rows]
            return pk.drift_report(rel, iso[:, 2], 3)

        a, b = one(), one()
        assert a == b                         # bit-deterministic block
        assert a["packing"] == 3 and a["n_questions"] == 6
        for key in ("mean_abs_delta", "p50_abs_delta", "p90_abs_delta",
                    "max_abs_delta", "flip_rate"):
            assert a[key] is not None
        assert a["max_abs_delta"] > 0         # real packed-context drift

    def test_nan_rows_are_skipped_not_counted(self):
        rep = pk.drift_report([0.6, float("nan")], [0.4, 0.5], 2)
        assert rep["n_questions"] == 1 and rep["n_skipped"] == 1
        assert rep["flip_rate"] == 1.0        # 0.6 vs 0.4 flips at 0.5

    def test_length_mismatch_raises(self):
        with pytest.raises(ValueError):
            pk.drift_report([0.5], [0.5, 0.5], 2)

    def test_packed_sweep_emits_the_drift_block(self, engine, tmp_path):
        from llm_interpretation_replication_tpu.sweeps import (
            run_packed_perturbation_sweep,
        )

        scen = [{"original_main": "Is soup a beverage?",
                 "response_format": "Answer only 'Yes' or 'No'.",
                 "confidence_format": "How confident (0-100)?",
                 "target_tokens": ["Yes", "No"],
                 "rephrasings": [f"Is soup nr {i} a beverage?"
                                 for i in range(5)]}]
        out = str(tmp_path / "packed.xlsx")
        df, rep = run_packed_perturbation_sweep(
            engine, "tiny", scen, out, packing=2,
            log=lambda *a, **k: None)
        assert len(df) == 5 and os.path.exists(out)
        assert rep["packing"] == 2 and rep["n_questions"] == 5
        assert df["Log Probabilities"].iloc[0] == \
            "local:packed2:first_token_top20"
        # resume skips every row; the drift block covers only new rows
        df2, rep2 = run_packed_perturbation_sweep(
            engine, "tiny", scen, out, packing=2,
            log=lambda *a, **k: None)
        assert len(df2) == 5 and rep2["n_questions"] == 0


# ---------------------------------------------------------------------------
# EOS-typical bracket: bit-parity for decided rows + decode_steps_saved
# ---------------------------------------------------------------------------

def _eos_boosted(engine, cfg, params, prompts, targets, eos_id):
    """Deterministic EOS-typical twin of ``params``: the EOS unembedding
    row boosted along the mean position-1 hidden direction orthogonalized
    against position 0 — the _calibrate_eos_rate construction without the
    bisection, so tiny-model tests stay fast and exact."""
    from llm_interpretation_replication_tpu.models.decoder import (
        decode_steps,
        prefill,
    )
    from llm_interpretation_replication_tpu.runtime import batching

    enc = batching.encode_prompts(engine.tokenizer, prompts)
    batch = next(batching.batches_for_prompts(
        enc, len(prompts), engine.ecfg.buckets, pad_id=0))
    ids, mask = jnp.asarray(batch.token_ids), jnp.asarray(
        batch.attention_mask)
    last, cache = prefill(params, cfg, ids, mask,
                          cache_len=int(ids.shape[1]))
    lengths = jnp.sum(mask, axis=-1)
    _, sc, _, _, _ = decode_steps(params, cfg, cache, last, lengths,
                                  np.int32(0), 2, None, None,
                                  with_scores=True)
    unembed = jnp.transpose(params["lm_head"]).astype(jnp.float32)

    def hdir(m):
        d = jnp.matmul(m[None, :], unembed)[0]
        return d / jnp.linalg.norm(d)

    h0 = hdir(jnp.mean(sc[:, 0].astype(jnp.float32), axis=0))
    h1 = hdir(jnp.mean(sc[:, 1].astype(jnp.float32), axis=0))
    he = h1 - jnp.dot(h1, h0) * h0
    he = he / jnp.linalg.norm(he)
    row = (unembed[eos_id] + 64.0 * he).astype(params["lm_head"].dtype)
    p = dict(params)
    p["lm_head"] = params["lm_head"].at[:, eos_id].set(row)
    return p


class TestEosBracket:
    def _setup(self):
        # vocab headroom over the 300-token test tokenizer: the armed
        # <|eos|> special token lands at id 300 and the model's
        # unembedding must cover it (bench._arm_eos_token's own check)
        cfg = DecoderConfig(**dict(TINY, vocab_size=384))
        tok = build_test_tokenizer()
        params = random_decoder_params(cfg)
        eng = ScoringEngine(
            "falcon", cfg, params, tok,
            engine_config=EngineConfig(batch_size=8,
                                       decode_completions=True,
                                       buckets=(32, 64, 128)))
        return cfg, tok, params, eng

    def test_decided_rows_judgment_parity_across_brackets(self):
        """The EOS boost touches ONLY the EOS unembedding row, so a
        position-0-decided row's yes/no LOGITS are bit-identical between
        the no-EOS and EOS-typical brackets — the brackets change decode
        length, never decided judgments.  The recorded probabilities pass
        through a softmax whose normalizer sums EVERY logit (including
        the boosted EOS one), so raw bit-equality of the floats is
        physically impossible; the contract PARITY.md pins is the
        strongest true invariant: identical scan verdicts (hit mask,
        scan_found, >= 0.5 judgments — zero flips) and probabilities
        equal at the fp32 normalization rounding floor (the PARITY.md
        tolerance, |Δ| <= 2e-6 vs the ~0.05 int8-KV class)."""
        from llm_interpretation_replication_tpu.models.decoder import (
            forward_last_logits,
        )
        from llm_interpretation_replication_tpu.runtime import batching
        from llm_interpretation_replication_tpu.scoring import yes_no as yn

        cfg, tok, params, eng = self._setup()
        prompts = _prompts(6)
        targets = [["Yes", "No"]] * 6
        scen = [{"original_main": "x",
                 "response_format": "Answer only 'Yes' or 'No'.",
                 "confidence_format": "c", "target_tokens": ["Yes", "No"],
                 "rephrasings": [p.rsplit(" Answer", 1)[0]
                                 for p in prompts]}]
        # decided-calibrated weights: most rows hit at position 0, the
        # population the bracket-parity contract covers
        params, rate = bench._calibrate_decided_rate(
            params, cfg, eng, scen, [prompts], 0.9, sample_rows=8)
        eng.params = params
        base = eng.score_prompts(prompts, targets=targets)
        # the position-0 hit mask, straight from the prefill logits
        yes_id, no_id = eng.target_ids(["Yes", "No"])[:2]
        batch = next(batching.batches_for_prompts(
            batching.encode_prompts(tok, prompts), 8, eng.ecfg.buckets,
            pad_id=0))
        hit0 = np.asarray(yn.first_token_scan(
            forward_last_logits(params, cfg,
                                jnp.asarray(batch.token_ids),
                                jnp.asarray(batch.attention_mask)),
            yes_id, no_id, top_k=eng.ecfg.top_k)[4])
        hit_by_orig = {int(orig): bool(hit0[r])
                       for r, orig in enumerate(batch.indices) if orig >= 0}
        decided = [i for i in range(len(prompts)) if hit_by_orig[i]]
        assert decided, "calibration produced no position-0-decided rows"
        eos_id = bench._arm_eos_token(tok, cfg)
        assert tok.eos_token_id == eos_id and eos_id < cfg.vocab_size
        eng.params = _eos_boosted(eng, cfg, params, prompts, targets,
                                  eos_id)
        try:
            bracket = eng.score_prompts(prompts, targets=targets)
        finally:
            eng.params = params
            tok.eos_token_id = None
        for i in decided:
            b, e = base[i], bracket[i]
            # zero judgment flips, exact verdict-mask equality
            assert e["scan_found"] == b["scan_found"]
            assert (e["relative_prob"] >= 0.5) == (b["relative_prob"] >= 0.5)
            assert (e["first_token_relative_prob"] >= 0.5) == \
                (b["first_token_relative_prob"] >= 0.5)
            # probabilities at the normalization rounding floor
            assert e["relative_prob"] == pytest.approx(
                b["relative_prob"], abs=2e-6)
            assert e["first_token_relative_prob"] == pytest.approx(
                b["first_token_relative_prob"], abs=2e-6)
            assert e["odds_ratio"] == pytest.approx(
                b["odds_ratio"], rel=1e-5)

    def test_eos_bracket_records_decode_steps_saved(self):
        """With the EOS-boosted weights + armed eos id, the completion
        chunks early-stop and the saved steps land in the
        decode_steps_saved counter; the no-EOS bracket records none."""
        cfg, tok, params, eng = self._setup()
        prompts = _prompts(6)
        targets = [["Yes", "No"]] * 6
        snap = dict(counters())
        eng.score_prompts(prompts, targets=targets)
        c = counters()
        assert c.get("decode_steps_saved", 0) == \
            snap.get("decode_steps_saved", 0)    # no-EOS: nothing saved
        eos_id = bench._arm_eos_token(tok, cfg)
        eng.params = _eos_boosted(eng, cfg, params, prompts, targets,
                                  eos_id)
        try:
            snap = dict(counters())
            rows = eng.score_prompts(prompts, targets=targets)
        finally:
            eng.params = params
            tok.eos_token_id = None
        saved = counters().get("decode_steps_saved", 0) - snap.get(
            "decode_steps_saved", 0)
        assert saved > 0
        # completions cut at the first EOS: far shorter than the cap
        assert all(len(r["completion"]) < 100 for r in rows)

    def test_calibrate_eos_rate_converges_on_a_tiny_model(self):
        """_calibrate_eos_rate's bisection lands near the target on a
        model whose decided calibration holds (the real bench's regime),
        and reports the measured rate, not the dial."""
        cfg, tok, params, eng = self._setup()
        scen = [{"original_main": "x",
                 "response_format": "Answer only 'Yes' or 'No'.",
                 "confidence_format": "c", "target_tokens": ["Yes", "No"],
                 "rephrasings": [f"Is item {i} a beverage?"
                                 for i in range(6)]}]
        prompts_by = [[f"{r} {s['response_format']}"
                       for r in s["rephrasings"]] for s in scen]
        eos_id = bench._arm_eos_token(tok, cfg)
        try:
            boosted, rate = bench._calibrate_eos_rate(
                params, cfg, eng, scen, prompts_by, 0.9, eos_id,
                sample_rows=8)
        finally:
            tok.eos_token_id = None
        assert 0.0 <= rate <= 1.0
        assert boosted["lm_head"] is not params["lm_head"]

    def test_bracket_targets_pinned_to_the_mined_asset(self):
        """ISSUE-10 satellite (ROADMAP item 4's validation clause): the
        bench's calibration targets are the mined bracket — the reference
        workbooks' position-0 answer-start floor below it, the checked-in
        rounds' measured calibrated rates spanning it, and the default
        --decided-frac inside it."""
        from llm_interpretation_replication_tpu.config import (
            decided_rate_calibration,
        )

        asset = decided_rate_calibration()
        lo, hi = asset["calibration_targets"]["bracket"]
        assert (lo, hi) == bench.DECIDED_RATE_TARGETS == (0.87, 0.92)
        assert lo <= asset["calibration_targets"]["default_decided_frac"] <= hi
        # the reference floor sits strictly below the bracket (top-1 is
        # the floor for top-5 membership)
        floor = asset["reference_workbooks"][
            "instruct_model_comparison_results_combined.csv"]["rate"]
        assert floor < lo
        # every measured calibrated rate from the checked-in rounds lands
        # inside the bracket — the empirical validation of the targets
        measured = [v for rec in asset["measured_calibrated_rates"].values()
                    if isinstance(rec, dict)
                    for v in rec.values() if isinstance(v, (int, float))]
        assert measured and all(lo <= v <= hi for v in measured)
        # and the bench records the asset mined really say so
        r5 = json.load(open(os.path.join(REPO_ROOT, "BENCH_r05.json")))
        assert "hit rate 0.92" in r5["parsed"]["metric"]

    def test_arm_eos_rejects_vocab_overflow(self):
        cfg = DecoderConfig(**dict(TINY, vocab_size=16))
        tok = build_test_tokenizer()
        with pytest.raises(ValueError, match="outside the model vocab"):
            bench._arm_eos_token(tok, cfg)
        tok.eos_token_id = None


# ---------------------------------------------------------------------------
# Strict mode + bench plumbing pins
# ---------------------------------------------------------------------------

class TestStrictAndPlumbing:
    def test_strict_packed_sweep_blocked_transfers_zero(self, engine,
                                                        tmp_path):
        from llm_interpretation_replication_tpu.runtime import strict
        from llm_interpretation_replication_tpu.sweeps import (
            run_packed_perturbation_sweep,
        )

        scen = [{"original_main": "strict packed",
                 "response_format": "Answer only 'Yes' or 'No'.",
                 "confidence_format": "c", "target_tokens": ["Yes", "No"],
                 "rephrasings": [f"Is strict item {i} a beverage?"
                                 for i in range(5)]}]
        snap = dict(counters())
        strict.activate()
        try:
            df, rep = run_packed_perturbation_sweep(
                engine, "tiny", scen, str(tmp_path / "strict.xlsx"),
                packing=2, log=lambda *a, **k: None)
        finally:
            strict.deactivate()
        assert len(df) == 5
        assert counters().get("blocked_transfers", 0) == \
            snap.get("blocked_transfers", 0)

    def test_bracket_flags_reach_the_full_study_secondary(self):
        """ISSUE-10 satellite, ISSUE-12 shape: the full-study companion
        is IN-PROCESS now (subprocess deleted), so --eos-mode /
        --eos-brackets reach it by namespace inheritance — the shallow
        copy must NOT override them, and the brackets block must ride
        the shared record builder into the secondary entry."""
        bench_src = open(os.path.join(REPO_ROOT, "bench.py")).read()
        secondary = bench_src[bench_src.index("def _full_study_secondary"):]
        secondary = secondary[:secondary.index("\ndef ")]
        assert "copy.copy(args)" in secondary
        # inherited, never overridden: a parent bracket run measures its
        # bracket in the secondary too
        assert "child.eos_mode" not in secondary
        assert "child.eos_brackets" not in secondary
        assert "_full_study_record(child" in secondary
        builder = bench_src[bench_src.index("def _full_study_record"):]
        builder = builder[:builder.index("\ndef ")]
        assert 'record["brackets"] = a.brackets_report' in builder

    def test_context_block_carries_bracket_and_packing_fields(self):
        """The record's context block names the bracket/packing settings
        (source pin): eos_mode always, decided/eos rates when measured,
        the packing factor in sweep-packed mode, and the
        decode_steps_saved counter when nonzero."""
        bench_src = open(os.path.join(REPO_ROOT, "bench.py")).read()
        ctx = bench_src[bench_src.index("def _operating_context"):]
        ctx = ctx[:ctx.index("def main")]
        for needle in ('"eos_mode"', '"decided_rate"', '"eos_rate"',
                       '"packed"', '"decode_steps_saved"'):
            assert needle in ctx, needle

    def test_run_perturbation_cli_exposes_packed_flags(self):
        src = open(os.path.join(
            REPO_ROOT, "llm_interpretation_replication_tpu",
            "__main__.py")).read()
        assert '"--packed"' in src and '"--packed-parity"' in src
        assert "run_packed_perturbation_sweep" in src
