"""Int8 accuracy audit: per-family w8a8-vs-bf16 relative-prob deltas.

The sweeps default to w8a8 int8 projections (ops/quant.py) because the
reference's own numbers came from bitsandbytes int8 and the v5e int8 MXU path
is ~2.3x bf16.  This audit backs that default with per-family evidence beyond
the single logit-correlation figure: for every decoder family in the roster,
build a tiny random HF checkpoint, convert it, and measure how much int8
quantization moves the scoring sweep's actual decision quantity —
``relative_prob = p_yes / (p_yes + p_no)`` at the last prompt position — over
a 100-prompt ragged batch.

Measured deltas are recorded in PARITY.md ("Int8 accuracy audit"); families
exceeding the bounds here must ship ``quant='none'`` roster overrides.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
pytest.importorskip("transformers")  # _families() runs at collection time

import jax.numpy as jnp  # noqa: E402

from llm_interpretation_replication_tpu.models import config as mcfg  # noqa: E402
from llm_interpretation_replication_tpu.models import convert as mconvert  # noqa: E402
from llm_interpretation_replication_tpu.models import decoder  # noqa: E402
from llm_interpretation_replication_tpu.ops.quant import (  # noqa: E402
    quantize_decoder_params,
)
from llm_interpretation_replication_tpu.scoring.yes_no import (  # noqa: E402
    relative_prob_first_token,
)

VOCAB = 128
N_PROMPTS = 100
YES_ID, NO_ID = 5, 9

# Mean/max |Δ relative_prob| bounds.  Tiny random models are a NOISIER int8
# target than real 7B checkpoints (outlier-free weights, logit scale ~1 where
# quantization noise is proportionally larger), so these are loose ceilings —
# the recorded means sit well under them (see PARITY.md).
MEAN_BOUND = 0.02
MAX_BOUND = 0.10


def _families():
    from transformers import (
        BloomConfig,
        FalconConfig,
        GPTNeoXConfig,
        LlamaConfig,
        MistralConfig,
        OPTConfig,
        Qwen2Config,
    )

    return {
        "falcon-mqa": FalconConfig(
            vocab_size=VOCAB, hidden_size=32, num_hidden_layers=3,
            num_attention_heads=4, new_decoder_architecture=False,
            multi_query=True, parallel_attn=True, bias=False, alibi=False,
        ),
        "neox": GPTNeoXConfig(
            vocab_size=VOCAB, hidden_size=32, num_hidden_layers=3,
            num_attention_heads=4, intermediate_size=64, rotary_pct=0.25,
            max_position_embeddings=64, use_parallel_residual=True,
        ),
        "bloom-alibi": BloomConfig(
            vocab_size=VOCAB, hidden_size=32, n_head=4, n_layer=3,
        ),
        "mistral-gqa": MistralConfig(
            vocab_size=VOCAB, hidden_size=32, num_hidden_layers=3,
            num_attention_heads=4, num_key_value_heads=2,
            intermediate_size=64, max_position_embeddings=64,
            sliding_window=None,
        ),
        "llama": LlamaConfig(
            vocab_size=VOCAB, hidden_size=32, num_hidden_layers=3,
            num_attention_heads=4, intermediate_size=64,
            max_position_embeddings=64,
        ),
        "opt": OPTConfig(
            vocab_size=VOCAB, hidden_size=32, num_hidden_layers=3,
            num_attention_heads=4, ffn_dim=64, max_position_embeddings=64,
            word_embed_proj_dim=32,
        ),
        "qwen2": Qwen2Config(
            vocab_size=VOCAB, hidden_size=32, num_hidden_layers=3,
            num_attention_heads=4, num_key_value_heads=2,
            intermediate_size=64, max_position_embeddings=64,
        ),
        # remote-code family: weights come from the shared synthetic state
        # dict (helpers.chatglm_test_setup), not AutoModel
        "chatglm2-mqa": "chatglm",
    }


def _weights_for(hf_config, seed):
    """(hf_config, state_dict) — AutoModel for HF families, the synthetic
    ChatGLM2 setup for the remote-code one."""
    if hf_config == "chatglm":
        from helpers import chatglm_test_setup

        return chatglm_test_setup(VOCAB, seed=seed + 11)
    from transformers import AutoModelForCausalLM

    torch.manual_seed(seed)
    return hf_config, AutoModelForCausalLM.from_config(hf_config).eval().state_dict()


def _prompt_batch(rng, n=N_PROMPTS, seq=24):
    ids = rng.integers(12, VOCAB, size=(n, seq)).astype(np.int32)
    mask = np.ones((n, seq), np.int32)
    lengths = rng.integers(10, seq + 1, size=n)
    for r, ln in enumerate(lengths):
        mask[r, ln:] = 0
        ids[r, ln:] = 0
    return ids, mask


def _relative_probs(params, cfg, ids, mask):
    logits = decoder.forward_last_logits(
        params, cfg, jnp.asarray(ids), jnp.asarray(mask)
    )
    _, _, rel = relative_prob_first_token(logits, YES_ID, NO_ID)
    return np.asarray(rel, np.float64)


def _audit_family(name, hf_config, seed=0):
    hf_config, state_dict = _weights_for(hf_config, seed)
    fam, cfg = mcfg.from_hf_config(hf_config)
    get = mconvert.getter_from_torch_state_dict(state_dict)
    params = mconvert.convert(fam, get, cfg, dtype=jnp.bfloat16)
    qparams = quantize_decoder_params(params)
    rng = np.random.default_rng(seed + 1)
    ids, mask = _prompt_batch(rng)
    rel_bf16 = _relative_probs(params, cfg, ids, mask)
    rel_int8 = _relative_probs(qparams, cfg, ids, mask)
    delta = np.abs(rel_int8 - rel_bf16)
    corr = np.corrcoef(rel_bf16, rel_int8)[0, 1]
    return {
        "family": name,
        "mean_delta": float(delta.mean()),
        "max_delta": float(delta.max()),
        "correlation": float(corr),
    }


@pytest.mark.parametrize("name", sorted(_families()))
def test_int8_relative_prob_delta(name):
    rec = _audit_family(name, _families()[name])
    print(
        f"\n{name}: mean|Δ|={rec['mean_delta']:.4f} "
        f"max|Δ|={rec['max_delta']:.4f} r={rec['correlation']:.4f}"
    )
    assert rec["mean_delta"] < MEAN_BOUND, rec
    assert rec["max_delta"] < MAX_BOUND, rec
    assert rec["correlation"] > 0.99, rec
