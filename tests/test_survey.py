"""Survey pipeline tests against the REAL reference survey data (read-only
fixtures) — regression-checks the paper's published exclusion counts
(main.tex:341-349: 1,003 recruited; 115 attention, 9 identical excluded) —
plus synthetic behavioral tests for the MAE Table-5 machinery."""

import os

import numpy as np
import pandas as pd
import pytest

from llm_interpretation_replication_tpu.survey import (
    analyze_families,
    apply_exclusion_criteria,
    cross_prompt_difference_ci,
    extract_question_text,
    human_cross_prompt_correlations,
    human_llm_correlation,
    human_responses_by_question,
    llm_cross_prompt_correlations,
    llm_responses_by_question,
    load_and_clean_survey_data,
    match_survey_to_llm_questions,
    paired_bootstrap_mae_difference,
    per_item_agreement_humans,
    per_item_agreement_llms,
    validate_model_data,
)

REF = "/root/reference/data"
SURVEYS = [
    f"{REF}/word_meaning_survey_results.csv",
    f"{REF}/word_meaning_survey_results_part_2.csv",
]
LLM_CSV = f"{REF}/instruct_model_comparison_results_combined.csv"

needs_ref = pytest.mark.skipif(
    not os.path.exists(SURVEYS[0]), reason="reference data not mounted"
)


@pytest.fixture(scope="module")
def survey_data():
    df, cols = load_and_clean_survey_data(SURVEYS)
    return df, cols


@pytest.fixture(scope="module")
def clean_survey(survey_data):
    df, cols = survey_data
    return apply_exclusion_criteria(df, cols) + (cols,)


@needs_ref
class TestRealSurveyData:
    def test_recruited_count(self, survey_data):
        df, cols = survey_data
        # Qualtrics exports hold 1,008 data rows (paper recruited 1,003 via
        # Prolific; the extra rows are survey-side partials)
        assert len(df) == 1008
        assert len(cols) == 110  # 2 surveys x 5 groups x 11 questions

    def test_exclusion_counts_match_paper(self, clean_survey):
        df, stats, cols = clean_survey
        # paper (main.tex:341-349): 115 attention-check failures, 9 identical-
        # slider exclusions; final n falls in the appendix's 879-884 range
        assert stats["attention_failed"] == 115
        assert stats["identical_excluded"] == 9
        assert stats["final_count"] == 884

    def test_question_text_extraction(self):
        mapping = extract_question_text(SURVEYS)
        assert 'Is a "screenshot" a "photograph"?' in mapping.values()
        assert any(k.startswith("S2_") for k in mapping)

    def test_llm_matching_covers_most_questions(self, clean_survey):
        df, _, cols = clean_survey
        llm_df = pd.read_csv(LLM_CSV)
        matches, mapping = match_survey_to_llm_questions(llm_df, SURVEYS)
        # the combined instruct CSV covers both surveys' questions
        assert len(matches) >= 90

    def test_human_llm_correlation_runs(self, clean_survey):
        df, _, cols = clean_survey
        llm_df = pd.read_csv(LLM_CSV)
        matches, _ = match_survey_to_llm_questions(llm_df, SURVEYS)
        h = human_responses_by_question(df, cols)
        m = llm_responses_by_question(llm_df)
        res = human_llm_correlation(h, m, matches, seed=42)
        assert res is not None
        assert res["n_questions"] >= 90
        assert -1 <= res["correlation"] <= 1
        assert res["ci_lower"] <= res["correlation"] <= res["ci_upper"]

    def test_cross_prompt_human_vs_llm_gap(self, clean_survey):
        """Appendix result: humans correlate cross-prompt (~0.285) far more
        than LLMs (~0.05) — main_online_appendix.tex:582-621.  Point
        estimates reproduce the published 0.285 / 0.052 exactly (to paper
        rounding); the bootstrap runs small for speed, so the difference CI
        is checked qualitatively."""
        df, _, cols = clean_survey
        llm_df = pd.read_csv(LLM_CSV)
        _, mapping = match_survey_to_llm_questions(llm_df, SURVEYS)
        hum = human_cross_prompt_correlations(df, cols, n_bootstrap=5, seed=42)
        llm = llm_cross_prompt_correlations(llm_df, mapping, n_bootstrap=5, seed=42)
        # point estimates are deterministic: they hit the published values
        assert round(hum["mean_correlation"], 3) == 0.285
        assert round(llm["mean_correlation"], 3) == 0.052
        diff = cross_prompt_difference_ci(hum, llm, n_bootstrap=500, seed=42)
        assert diff["difference"] > 0.1
        assert diff["p_value"] < 0.05

    def test_per_item_agreement_scales(self, clean_survey):
        df, _, cols = clean_survey
        hum = per_item_agreement_humans(df, cols, n_bootstrap=50, seed=42)
        assert 0.5 <= hum["overall_mean"] <= 1.0
        assert hum["n_items"] == 100


class TestMae100q:
    def _synthetic(self):
        rng = np.random.default_rng(0)
        questions = [f"q{i}" for i in range(30)]
        human_avgs = {f"S1_Q1_{i}": float(rng.uniform(0.3, 0.8)) for i in range(30)}
        matches = {f"q{i}": f"S1_Q1_{i}" for i in range(30)}
        rows = []
        for model, offset, noise in [
            ("tiiuae/falcon-7b", 0.05, 0.05),
            ("tiiuae/falcon-7b-instruct", 0.25, 0.05),
        ]:
            for q in questions:
                h = human_avgs[matches[q]]
                rows.append({
                    "prompt": q, "model": model,
                    "relative_prob": float(np.clip(h + offset + rng.normal(0, noise), 0, 1)),
                })
        # a degenerate model that must be excluded
        for q in questions:
            rows.append({"prompt": q, "model": "stabilityai/stablelm-base-alpha-7b",
                         "relative_prob": 0.5})
            rows.append({"prompt": q, "model": "stabilityai/stablelm-tuned-alpha-7b",
                         "relative_prob": float(rng.uniform(0, 1))})
        return pd.DataFrame(rows), human_avgs, matches

    def test_validate_model_data_gates(self):
        df, _, _ = self._synthetic()
        ok, _ = validate_model_data(df, "tiiuae/falcon-7b")
        assert ok
        ok, reason = validate_model_data(df, "stabilityai/stablelm-base-alpha-7b")
        assert not ok and "Constant" in reason
        ok, reason = validate_model_data(df, "missing/model")
        assert not ok

    def test_family_analysis_detects_direction(self):
        df, human_avgs, matches = self._synthetic()
        res = analyze_families(
            df, human_avgs, matches,
            families={"Falcon": {"base": "tiiuae/falcon-7b",
                                 "instruct": "tiiuae/falcon-7b-instruct"}},
            n_bootstrap=2000, seed=42,
        )
        falcon = res["Falcon"]
        assert not falcon["excluded"]
        assert falcon["instruct_mae"] > falcon["base_mae"]
        assert falcon["observed_diff"] > 0.1
        assert falcon["p_value"] < 0.05
        assert "_overall" in res

    def test_excluded_family_reported(self):
        df, human_avgs, matches = self._synthetic()
        res = analyze_families(
            df, human_avgs, matches,
            families={"StableLM": {"base": "stabilityai/stablelm-base-alpha-7b",
                                   "instruct": "stabilityai/stablelm-tuned-alpha-7b"}},
            n_bootstrap=100, seed=42,
        )
        assert res["StableLM"]["excluded"]

    def test_paired_bootstrap_seeded_repeatable(self):
        rng = np.random.default_rng(1)
        base = np.abs(rng.normal(0.3, 0.1, 50))
        inst = np.abs(rng.normal(0.45, 0.1, 50))
        a = paired_bootstrap_mae_difference(base, inst, n_bootstrap=2000, seed=42)
        b = paired_bootstrap_mae_difference(base, inst, n_bootstrap=2000, seed=42)
        assert a == b
        assert a["observed_diff"] > 0


@needs_ref
class TestAgreementReports:
    """The two condensed agreement scripts' report shapes on REAL data:
    analyze_llm_human_agreement.py (point estimates) and
    analyze_llm_agreement_simple_bootstrap.py (question-level bootstrap)."""

    @staticmethod
    def _inputs():
        import pandas as pd

        from llm_interpretation_replication_tpu.survey.variants import (
            human_agreement_means,
        )

        instruct_df = pd.read_csv(f"{REF}/instruct_model_comparison_results.csv")
        base_df = pd.read_csv(f"{REF}/model_comparison_results.csv")
        means = human_agreement_means(
            [f"{REF}/word_meaning_survey_results.csv"], instruct_df
        )
        return instruct_df, base_df, means

    def test_human_means_cover_the_50_mapped_questions(self):
        _, _, means = self._inputs()
        assert len(means) == 50
        assert all(0.0 <= v <= 1.0 for v in means.values())

    def test_point_estimates_match_independent_oracle(self):
        """Per-model MAE/RMSE/Pearson recomputed in-test straight from the
        CSVs + cleaned means (scipy, no shared code path) must agree to
        1e-12; spot values pinned for regression."""
        import pandas as pd
        from scipy.stats import pearsonr

        from llm_interpretation_replication_tpu.survey.variants import (
            human_agreement_report,
        )

        instruct_df, base_df, means = self._inputs()
        rep = human_agreement_report(instruct_df, base_df, means)
        by_key = {(r["model"], r["model_type"]): r for r in rep["model_results"]}

        sub = base_df[base_df["model"] == "tiiuae/falcon-7b"]
        pairs = []
        for _, row in sub.iterrows():
            if row["prompt"] not in means:
                continue
            total = row["yes_prob"] + row["no_prob"]
            if pd.isna(total):
                continue
            p = row["yes_prob"] / total if total > 0 else 0.5
            pairs.append((means[row["prompt"]], p))
        h = np.array([a for a, _ in pairs])
        p = np.array([b for _, b in pairs])
        rec = by_key[("tiiuae/falcon-7b", "base")]
        np.testing.assert_allclose(rec["mae"], np.mean(np.abs(h - p)), rtol=1e-12)
        np.testing.assert_allclose(
            rec["rmse"], np.sqrt(np.mean((h - p) ** 2)), rtol=1e-12
        )
        np.testing.assert_allclose(rec["pearson_r"], pearsonr(h, p)[0], rtol=1e-10)
        assert rec["n_questions"] == len(pairs) == 49

        # regression pins (real-data values, round 3)
        np.testing.assert_allclose(rec["mae"], 0.21272931615254154, rtol=1e-9)
        inst = by_key[("tiiuae/falcon-7b-instruct", "instruct")]
        np.testing.assert_allclose(inst["mae"], 0.20193314582237168, rtol=1e-9)
        np.testing.assert_allclose(inst["pearson_r"], -0.045745630685306925,
                                   rtol=1e-9)
        assert inst["n_questions"] == 50

        # ranked by MAE; question variance covers all 50 questions
        maes = [r["mae"] for r in rep["model_results"]]
        assert maes == sorted(maes)
        assert len(rep["question_variance"]) == 50
        qv = rep["question_variance"]['Is a "screenshot" a "photograph"?']
        assert qv["n_models"] == len(rep["model_results"]) == 28

    def test_bootstrap_mape_keeps_tiny_but_nonzero_means(self):
        """The respondent bootstrap's MAPE mirrors the reference's
        finite-filter (analyze_llm_human_agreement_bootstrap.py:179-182):
        a question with a TINY but nonzero human mean (0 < h <= 0.01)
        contributes its huge-but-finite |err|/h term; only h == 0 (inf)
        drops.  The r04 code silently NaN'd the tiny-mean term, diverging
        from the reference on exactly this input."""
        import pandas as pd

        from llm_interpretation_replication_tpu.survey.variants import (
            agreement_bootstrap,
        )

        # 4 identical respondents -> every bootstrap resample has the same
        # means, so the expected MAPE is exact: Q_tiny mean = 0.5% = 0.005,
        # Q_mid = 0.5, Q_zero = 0 (inf term, dropped)
        survey_df = pd.DataFrame({
            "Q_tiny": [0.5] * 4, "Q_mid": [50.0] * 4, "Q_zero": [0.0] * 4,
        })
        mapping = {"Q_tiny": "p_tiny", "Q_mid": "p_mid", "Q_zero": "p_zero"}
        llm_df = pd.DataFrame({
            "model": ["m"] * 3,
            "prompt": ["p_tiny", "p_mid", "p_zero"],
            "relative_prob": [0.105, 0.25, 0.4],
        })
        rep = agreement_bootstrap(
            llm_df, survey_df, list(mapping), mapping,
            n_bootstrap=8, seed=0, min_questions=1,
        )
        (rec,) = rep["model_results"]
        ape_tiny = abs(0.005 - 0.105) / 0.005   # kept: finite (= 20.0)
        ape_mid = abs(0.5 - 0.25) / 0.5         # kept (= 0.5)
        expected = (ape_tiny + ape_mid) / 2 * 100   # inf term dropped
        np.testing.assert_allclose(rec["mape_mean"], expected, rtol=1e-12)
        np.testing.assert_allclose(rec["mape_std"], 0.0, atol=1e-12)

    def test_question_bootstrap_schema_and_group_comparison(self):
        from llm_interpretation_replication_tpu.survey.variants import (
            agreement_question_bootstrap,
        )

        instruct_df, base_df, means = self._inputs()
        boot = agreement_question_bootstrap(
            instruct_df, base_df, means, n_bootstrap=150, seed=7,
            n_diff_bootstrap=2000,
        )
        assert boot["analysis_type"] == "llm_human_agreement_bootstrap_questions"
        assert boot["bootstrap_parameters"]["bootstrap_method"] == (
            "questions_with_replacement"
        )
        assert boot["overall_comparison"]["base_models_count"] == 18
        assert boot["overall_comparison"]["instruct_models_count"] == 10
        for rec in boot["model_results"]:
            for metric in ("mae", "mse", "mape"):
                assert (rec[f"{metric}_ci_lower"] <= rec[f"{metric}_mean"]
                        <= rec[f"{metric}_ci_upper"]), rec["model"]
        maes = [r["mae_mean"] for r in boot["model_results"]]
        assert maes == sorted(maes)
        for metric in ("mae", "mse", "mape"):
            rec = boot["overall_comparison"]["metrics"][metric]
            assert 0.0 <= rec["p_value"] <= 1.0
            assert rec["difference_ci"][0] <= rec["difference_ci"][1]
        # seeded determinism (json text: NaN == NaN under repr, not ==)
        import json

        boot2 = agreement_question_bootstrap(
            instruct_df, base_df, means, n_bootstrap=150, seed=7,
            n_diff_bootstrap=2000,
        )
        assert json.dumps(boot, default=float) == json.dumps(boot2, default=float)
