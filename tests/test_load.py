"""Open-loop load harness + per-request latency anatomy (ISSUE 11):
seeded Poisson schedule determinism, streaming-histogram exactness and
percentile accuracy vs exact quantiles, scheduler latency stamps summing
to e2e, replay parity under load (strict-mode clean), saturation
shedding accounting, watchdog/flight-recorder non-interference, the
/healthz oldest-queued-age degraded condition, bench --serve-load record
structure, and obs bench-diff / obs report alignment of serve_load
blocks."""

import argparse
import io
import json
import math
import threading
import time

import numpy as np
import pytest

from test_runtime import _tiny_engine
from test_sweeps import FakeEngine

from llm_interpretation_replication_tpu.obs import flight as obs_flight
from llm_interpretation_replication_tpu.obs import metrics as obs_metrics
from llm_interpretation_replication_tpu.obs.benchdiff import (
    diff_records,
    format_diff_table,
)
from llm_interpretation_replication_tpu.obs.report import (
    format_serve_load_table,
)
from llm_interpretation_replication_tpu.obs.report import main as obs_main
from llm_interpretation_replication_tpu.serve import (
    Scheduler,
    SchedulerConfig,
    ScoreRequest,
)
from llm_interpretation_replication_tpu.serve import cli as serve_cli
from llm_interpretation_replication_tpu.serve import load as load_mod
from llm_interpretation_replication_tpu.serve.scheduler import (
    HIST_E2E,
    HIST_PHASES,
)
from llm_interpretation_replication_tpu.utils import telemetry

pytestmark = pytest.mark.serveload

FAST = dict(max_wait_s=0.005)


# ---------------------------------------------------------------------------
# Seeded Poisson schedule
# ---------------------------------------------------------------------------

class TestPoissonSchedule:
    def test_same_seed_same_arrival_times(self):
        """Satellite: deterministic traffic — a latency comparison across
        two builds replays bit-identical arrivals."""
        a = load_mod.poisson_schedule(80.0, 2.0, seed=7)
        b = load_mod.poisson_schedule(80.0, 2.0, seed=7)
        assert a == b and len(a) > 50
        assert load_mod.poisson_schedule(80.0, 2.0, seed=8) != a

    def test_schedule_is_sorted_within_duration(self):
        s = load_mod.poisson_schedule(50.0, 1.5, seed=0)
        assert s == sorted(s)
        assert all(0.0 < t < 1.5 for t in s)

    def test_mean_interarrival_matches_rate(self):
        s = load_mod.poisson_schedule(200.0, 30.0, seed=1)
        # ~6000 arrivals: the mean inter-arrival converges on 1/rate
        assert len(s) > 4000
        gaps = np.diff([0.0] + s)
        assert abs(float(np.mean(gaps)) - 1 / 200.0) < 0.1 / 200.0

    def test_nonpositive_rate_rejected(self):
        with pytest.raises(ValueError, match="rate"):
            load_mod.poisson_schedule(0.0, 1.0)


# ---------------------------------------------------------------------------
# Streaming histograms (telemetry.record_hist)
# ---------------------------------------------------------------------------

class TestStreamingHistograms:
    def test_bucket_bounds_contain_value(self):
        for v in (0.0004, 0.0011, 0.5, 1.0, 7.3, 1234.5, 9e6):
            idx = telemetry.hist_bucket_index(v)
            assert telemetry.hist_bucket_le(idx) >= v * (1 - 1e-12)
            if idx > 0:
                assert telemetry.hist_bucket_le(idx - 1) < v

    def test_exact_counts_no_tail_truncation(self):
        """The point of the structure: a ring caps at 4096 retained
        samples (the p99.9 history), a histogram never evicts."""
        telemetry.clear_hists()
        telemetry.clear_samples()
        for i in range(10000):
            telemetry.record_hist("load_test_hist", float(i + 1))
            telemetry.record_sample("load_test_ring", float(i + 1))
        assert telemetry.hist_count("load_test_hist") == 10000
        assert telemetry.sample_count("load_test_ring") == 4096  # truncated
        # the ring lost the slow head; the histogram still sees it
        assert telemetry.hist_percentiles("load_test_hist")["p50"] < 6000
        assert telemetry.sample_percentiles("load_test_ring")["p50"] > 6000

    def test_percentiles_vs_exact_quantiles_small_samples(self):
        """Satellite: any histogram quantile brackets the exact
        nearest-rank quantile within one bucket (< HIST_GROWTH rel)."""
        rng = np.random.default_rng(5)
        values = np.exp(rng.normal(2.0, 1.5, size=137)) + 0.05
        telemetry.clear_hists()
        for v in values:
            telemetry.record_hist("load_acc_hist", float(v))
        got = telemetry.hist_percentiles("load_acc_hist",
                                         (50.0, 90.0, 99.0, 99.9))
        s = np.sort(values)
        for p in (50.0, 90.0, 99.0, 99.9):
            exact = float(s[max(0, math.ceil(p / 100.0 * len(s)) - 1)])
            key = f"p{p:g}"
            assert exact * (1 - 1e-9) <= got[key], (p, exact, got[key])
            assert got[key] <= exact * telemetry.HIST_GROWTH * (1 + 1e-9), \
                (p, exact, got[key])

    def test_snapshot_since_scopes_a_phase(self):
        telemetry.clear_hists()
        for _ in range(10):
            telemetry.record_hist("load_scope_hist", 1.0)
        snap = telemetry.hist_snapshot(["load_scope_hist"])
        for _ in range(5):
            telemetry.record_hist("load_scope_hist", 1000.0)
        delta = telemetry.hist_since(snap)["load_scope_hist"]
        assert delta["count"] == 5
        pct = telemetry.hist_percentiles_from(delta["counts"])
        assert pct["p50"] >= 1000.0          # only the new phase
        assert telemetry.hist_percentiles("load_scope_hist")["p50"] < 2.0

    def test_since_never_negative_after_midwindow_clear(self):
        telemetry.clear_hists()
        for _ in range(20):
            telemetry.record_hist("load_clear_hist", 3.0)
        snap = telemetry.hist_snapshot(["load_clear_hist"])
        telemetry.clear_hists()
        for _ in range(4):
            telemetry.record_hist("load_clear_hist", 3.0)
        delta = telemetry.hist_since(snap).get("load_clear_hist")
        assert delta is not None and delta["count"] == 4
        assert all(n > 0 for n in delta["counts"].values())

    def test_prometheus_histogram_exposition(self):
        """Exported as a Prometheus ``histogram`` family: cumulative
        _bucket series, +Inf == _count, _sum; an empty histogram emits
        NO series (the empty-ring discipline)."""
        telemetry.clear_hists()
        for v in (1.0, 1.0, 10.0):
            telemetry.record_hist("load_expo_ms", v)
        text = obs_metrics.MetricsRegistry().prometheus_text()
        assert "# TYPE llm_interp_load_expo_ms histogram" in text
        lines = [l for l in text.splitlines()
                 if l.startswith("llm_interp_load_expo_ms_bucket")]
        assert lines[-1] == 'llm_interp_load_expo_ms_bucket{le="+Inf"} 3'
        counts = [int(l.rsplit(" ", 1)[1]) for l in lines]
        assert counts == sorted(counts)          # cumulative
        assert "llm_interp_load_expo_ms_count 3" in text
        assert "llm_interp_load_expo_ms_sum 12" in text
        telemetry.clear_hists()
        assert "load_expo_ms" not in obs_metrics.MetricsRegistry(
            ).prometheus_text()

    def test_registry_sample_carries_hists(self):
        telemetry.clear_hists()
        telemetry.record_hist("load_doc_ms", 2.5)
        doc = obs_metrics.MetricsRegistry().sample()
        assert doc["hists"]["load_doc_ms"]["count"] == 1
        assert "p99.9" in doc["hists"]["load_doc_ms"]


# ---------------------------------------------------------------------------
# Scheduler latency stamps
# ---------------------------------------------------------------------------

class TestLatencyAnatomy:
    def test_phases_are_disjoint_and_sum_to_e2e(self):
        eng = FakeEngine("anatomy/model")
        h0 = telemetry.hist_count(HIST_E2E)
        with Scheduler(eng, SchedulerConfig(**FAST)) as sched:
            futs = [sched.submit(ScoreRequest(prompt=f"q{i}"))
                    for i in range(5)]
            rows = [f.result(timeout=30) for f in futs]
        for f in futs:
            t = f.timing
            assert t is not None
            assert set(t) == {"e2e_ms", "queue_wait_ms", "coalesce_ms",
                              "serve_engine_ms", "respond_ms"}
            assert all(v >= 0.0 for v in t.values())
            parts = (t["queue_wait_ms"] + t["coalesce_ms"]
                     + t["serve_engine_ms"] + t["respond_ms"])
            assert abs(parts - t["e2e_ms"]) < 1e-6, t
        # the anatomy rides the FUTURE, never the result row (bit-parity)
        assert all("e2e_ms" not in r and "timing" not in r for r in rows)
        assert telemetry.hist_count(HIST_E2E) == h0 + 5
        for name in HIST_PHASES.values():
            assert telemetry.hist_count(name) >= 5


# ---------------------------------------------------------------------------
# run_load: parity under load, shedding, determinism, closed comparator
# ---------------------------------------------------------------------------

class TestRunLoad:
    def test_replay_parity_under_load_tiny_engine(self):
        """Acceptance: rows served under open-loop load are bit-identical
        to the offline score_prompts path."""
        eng, _, _ = _tiny_engine(batch_size=4)
        prompts = [f"Is thing {i} a stuff?" for i in range(6)]
        report = load_mod.run_load(
            eng, prompts, rate=20.0, duration_s=1.0, seed=3,
            config=SchedulerConfig(**FAST))
        assert report["requests"] > 5
        assert report["completed"] == report["requests"]
        assert report["errors"] == 0 and report["shed"] == 0
        assert report["parity"]["mismatched_rows"] == 0
        assert report["parity"]["checked_rows"] == report["completed"]
        # every request of the run is in the histogram window
        assert report["hist_requests"] == report["completed"]
        assert report["drain_s"] >= 0.0
        assert set(report["phases_ms"]) == {"queue_wait", "coalesce",
                                            "serve_engine", "respond"}
        for q in ("p50", "p90", "p99", "p99.9"):
            assert q in report["latency_ms"]

    def test_strict_mode_load_stays_clean(self):
        """Acceptance: blocked_transfers == 0 for a load run under
        strict mode."""
        from llm_interpretation_replication_tpu.runtime import strict

        eng, _, _ = _tiny_engine(batch_size=4)
        prompts = [f"Is item {i} a thing?" for i in range(4)]
        offline = eng.score_prompts(prompts)   # warm + parity reference
        strict.activate(sentry=False)
        try:
            report = load_mod.run_load(
                eng, prompts, rate=15.0, duration_s=0.8, seed=0,
                config=SchedulerConfig(**FAST), offline_rows=offline)
        finally:
            strict.deactivate()
        assert report["parity"]["mismatched_rows"] == 0
        assert report["blocked_transfers"] == 0

    def test_same_seed_same_traffic(self, tmp_path):
        """Seed determinism end to end: schedule AND prompt picks."""
        runs = []
        for k in range(2):
            path = tmp_path / f"load{k}.jsonl"
            load_mod.run_load(FakeEngine("det/model"),
                              [f"q{i}" for i in range(7)],
                              rate=60.0, duration_s=0.5, seed=11,
                              config=SchedulerConfig(**FAST),
                              parity=False, jsonl=str(path))
            lines = [json.loads(l) for l in
                     path.read_text().splitlines()]
            runs.append([(r["i"], r["scheduled_s"], r["prompt_idx"])
                         for r in lines])
        assert runs[0] == runs[1] and len(runs[0]) > 10

    def test_open_loop_sheds_on_backpressure(self):
        """At saturation the generator keeps its schedule and sheds into
        the typed QueueFull path — it never silently turns closed-loop."""
        gate = threading.Event()

        class SlowEngine(FakeEngine):
            def score_prompts(self, prompts, targets=("Yes", "No"),
                              with_confidence=False, max_new_tokens=None):
                gate.wait(timeout=10)
                return super().score_prompts(prompts, targets,
                                             with_confidence,
                                             max_new_tokens)

        threading.Timer(0.6, gate.set).start()
        report = load_mod.run_load(
            SlowEngine("slow/model"), ["a", "b"], rate=100.0,
            duration_s=0.5, seed=0, parity=False,
            config=SchedulerConfig(queue_capacity=2, max_batch=1, **FAST))
        assert report["shed"] > 0
        assert report["completed"] + report["errors"] + report["shed"] \
            == report["requests"]
        assert report["queue_depth"]["max"] >= 1

    def test_closed_loop_comparator(self):
        report = load_mod.run_load(
            FakeEngine("closed/model"), [f"q{i}" for i in range(5)],
            mode="closed", concurrency=3, duration_s=0.4, seed=0,
            parity=False, config=SchedulerConfig(**FAST))
        assert report["mode"] == "closed"
        assert report["offered_rate"] is None
        assert report["concurrency"] == 3
        assert report["completed"] > 0
        assert report["achieved_rows_per_s"] > 0

    def test_ring_truncation_visibility_rides_the_report(self):
        """Satellite: per-ring caps + truncation visibility — a ring
        capped below the run's volume reports total > retained in the
        load report while the histogram keeps every request."""
        telemetry.clear_samples()
        telemetry.set_sample_cap(8, "serve_latency_ms")   # per-ring cap
        try:
            report = load_mod.run_load(
                FakeEngine("trunc/model"), [f"q{i}" for i in range(4)],
                rate=80.0, duration_s=0.6, seed=2, parity=False,
                config=SchedulerConfig(**FAST))
            ring = report["samples"]["serve_latency_ms"]
            assert ring["cap"] == 8
            assert ring["retained"] <= 8 < ring["total"]
            assert report["rings_truncated"] is True
            assert report["hist_requests"] == report["completed"] > 8
        finally:
            telemetry.set_sample_cap(telemetry._SAMPLES_CAP_DEFAULT,
                                     "serve_latency_ms")


# ---------------------------------------------------------------------------
# rate_sweep: the knee finder / serve_load block
# ---------------------------------------------------------------------------

class TestRateSweep:
    def test_block_structure_and_parity(self):
        eng, _, _ = _tiny_engine(batch_size=4)
        prompts = [f"Is thing {i} a stuff?" for i in range(5)]
        block = load_mod.rate_sweep(
            eng, prompts, rates=(8.0, 16.0, 32.0), duration_s=0.6,
            seed=1, config=SchedulerConfig(**FAST),
            closed_comparator=True)
        assert len(block["rates"]) >= 3
        offered = [p["offered_rate"] for p in block["rates"]]
        assert offered == sorted(offered)
        for p in block["rates"]:
            assert {"p50", "p90", "p99", "p99.9"} <= set(p["latency_ms"])
            assert set(p["phases_ms"]) == {"queue_wait", "coalesce",
                                           "serve_engine", "respond"}
            assert p["parity"]["mismatched_rows"] == 0
        assert block["parity_ok"] is True
        assert block["saturation_rows_per_s"] > 0
        assert "knee_offered_rate" in block and "knee_beyond_sweep" in block
        assert block["closed_loop"]["mode"] == "closed"
        # renderers accept the block
        assert "saturation" in load_mod.format_rate_table(block)
        assert "queue_wait" in format_serve_load_table(block)

    def test_fewer_than_three_rates_rejected(self):
        with pytest.raises(ValueError, match=">= 3"):
            load_mod.rate_sweep(FakeEngine("x/y"), ["a"], rates=(1.0, 2.0))

    def test_knee_detects_saturation_by_drain_not_makespan_ratio(self):
        """Review regression: the knee criterion must survive per-request
        latency that is non-trivial vs the arrival window.  A fixed-delay
        engine at ~50 req/s capacity keeps up at 10 and 20 offered (drain
        ~ one service latency) and saturates at 200 (drain grows with the
        backlog) — an achieved/makespan ratio would have misclassified
        the sub-saturation points."""

        class DelayEngine(FakeEngine):
            def score_prompts(self, prompts, targets=("Yes", "No"),
                              with_confidence=False, max_new_tokens=None):
                time.sleep(0.02)
                return super().score_prompts(prompts, targets,
                                             with_confidence,
                                             max_new_tokens)

        block = load_mod.rate_sweep(
            DelayEngine("knee/model"), [f"q{i}" for i in range(4)],
            rates=(10.0, 20.0, 200.0), duration_s=0.4, seed=0,
            parity=False,
            config=SchedulerConfig(max_batch=1, max_wait_s=0.001))
        drains = [p["drain_s"] for p in block["rates"]]
        assert drains[2] > drains[0] + 0.5          # backlog at 200/s
        assert block["knee_offered_rate"] == 20.0
        assert block["knee_beyond_sweep"] is False
        assert block["knee_floor_saturated"] is False

    def test_all_saturated_sweep_reports_unknown_knee(self):
        """Review regression: the drain floor is relative, so a sweep
        where EVERY rate is above saturation must report the knee as
        unknown (None + knee_floor_saturated) — never confidently name
        the least-saturated point as 'keeping up'."""

        class DelayEngine(FakeEngine):
            def score_prompts(self, prompts, targets=("Yes", "No"),
                              with_confidence=False, max_new_tokens=None):
                time.sleep(0.02)
                return super().score_prompts(prompts, targets,
                                             with_confidence,
                                             max_new_tokens)

        block = load_mod.rate_sweep(
            DelayEngine("sat/model"), [f"q{i}" for i in range(4)],
            rates=(150.0, 200.0, 250.0), duration_s=0.2, seed=0,
            parity=False,
            config=SchedulerConfig(max_batch=1, max_wait_s=0.001))
        assert block["knee_floor_saturated"] is True
        assert block["knee_offered_rate"] is None
        assert block["knee_beyond_sweep"] is False
        assert "unknown" in load_mod.format_rate_table(block)

    def test_wedged_scheduler_costs_one_timeout_not_n(self):
        """Review regression: a wedged engine must cost ONE
        result_timeout_s for the whole collection phase, never one per
        outstanding future."""
        block_forever = threading.Event()   # never set

        class WedgedEngine(FakeEngine):
            def score_prompts(self, prompts, targets=("Yes", "No"),
                              with_confidence=False, max_new_tokens=None):
                block_forever.wait(timeout=30)
                return super().score_prompts(prompts, targets,
                                             with_confidence,
                                             max_new_tokens)

        t0 = time.monotonic()
        report = load_mod.run_load(
            WedgedEngine("wedge/model"), ["a", "b"], rate=40.0,
            duration_s=0.3, seed=0, parity=False,
            config=SchedulerConfig(max_batch=1, drain_timeout_s=0.2,
                                   **FAST),
            result_timeout_s=1.0)
        elapsed = time.monotonic() - t0
        assert report["requests"] >= 5
        assert report["errors"] + report["shed"] == report["requests"]
        assert elapsed < 6.0, elapsed   # one budget, not N x 1s
        block_forever.set()             # release the stuck thread


# ---------------------------------------------------------------------------
# Watchdog / flight-recorder non-interference at saturation (satellite)
# ---------------------------------------------------------------------------

class TestObsNonInterference:
    def test_saturated_load_trips_neither_watchdog_nor_flight(self, tmp_path):
        """A saturated load run under an armed flight recorder and a
        healthy sweep's watchdog must neither dump a flight record nor
        trip the watchdog — the harness is measurement, not a fault."""
        telemetry.clear_fault_events()
        obs_flight.enable(str(tmp_path))
        wd = obs_flight.StallWatchdog(label="load-test", floor_s=5.0,
                                      poll_s=0.05).start()
        stop = threading.Event()

        def beats():   # a healthy co-resident sweep keeps beating
            while not stop.wait(0.05):
                wd.beat()

        beater = threading.Thread(target=beats, daemon=True)
        beater.start()
        try:
            report = load_mod.run_load(
                FakeEngine("sat/model"), [f"q{i}" for i in range(6)],
                rate=300.0, duration_s=0.6, seed=4, parity=False,
                config=SchedulerConfig(queue_capacity=16, **FAST))
        finally:
            stop.set()
            beater.join(timeout=2)
            wd.stop()
            obs_flight.disable()
        assert report["requests"] > 50
        assert wd.trips == 0
        assert telemetry.fault_events("watchdog_stall") == []
        assert not list(tmp_path.glob("flightrec-*.json"))


# ---------------------------------------------------------------------------
# /healthz degraded condition (satellite)
# ---------------------------------------------------------------------------

class TestHealthzQueueAge:
    def test_wedged_short_queue_reads_degraded(self):
        """A never-started scheduler with ONE queued request (short
        queue!) degrades once the head request's age crosses the
        threshold — depth alone would have read healthy."""
        sched = Scheduler(FakeEngine("h/m"), SchedulerConfig(
            health_max_queue_age_s=0.05, **FAST))
        doc = serve_cli.scheduler_health(sched)
        assert "status" not in doc and doc["queue_depth"] == 0
        sched.submit(ScoreRequest(prompt="stuck"))
        time.sleep(0.12)
        doc = serve_cli.scheduler_health(sched)
        assert doc["queue_depth"] == 1
        assert doc["status"] == "degraded"
        assert doc["oldest_queued_age_s"] >= 0.05
        assert "waited" in doc["degraded_reason"]
        sched.close(drain=False)

    def test_threshold_zero_disables_and_fresh_queue_healthy(self):
        sched = Scheduler(FakeEngine("h/m"), SchedulerConfig(
            health_max_queue_age_s=0.0, **FAST))
        sched.submit(ScoreRequest(prompt="young"))
        time.sleep(0.02)
        doc = serve_cli.scheduler_health(sched)
        assert "status" not in doc
        assert doc["oldest_queued_age_s"] >= 0.0
        sched.close(drain=False)

    def test_degraded_age_served_through_endpoint(self):
        sched = Scheduler(FakeEngine("h/m"), SchedulerConfig(
            health_max_queue_age_s=0.05, **FAST))
        sched.submit(ScoreRequest(prompt="stuck"))
        time.sleep(0.12)
        import urllib.request

        server = obs_metrics.MetricsServer(
            obs_metrics.MetricsRegistry(), 0,
            healthz_fn=lambda: serve_cli.scheduler_health(sched)).start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/healthz") as resp:
                doc = json.loads(resp.read())
        finally:
            server.close()
            sched.close(drain=False)
        assert doc["status"] == "degraded"
        assert doc["oldest_queued_age_s"] >= 0.05


# ---------------------------------------------------------------------------
# serve CLI load mode + corpus workload
# ---------------------------------------------------------------------------

class TestServeCliLoadMode:
    def _corpus(self, tmp_path):
        scenarios = [
            {"original_main": f"Is thing {s} a stuff?",
             "response_format": "Answer only 'Yes' or 'No'.",
             "target_tokens": ["Yes", "No"] if s == 0 else ["No", "Yes"],
             "rephrasings": [f"Is thing {s} variant {i} a stuff?"
                             for i in range(3)]}
            for s in range(2)
        ]
        path = tmp_path / "perturbations.json"
        path.write_text(json.dumps(scenarios))
        return str(path)

    def test_corpus_workload_matches_sweep_spelling(self, tmp_path):
        prompts, targets = load_mod.corpus_workload(self._corpus(tmp_path),
                                                    max_rephrasings=2)
        assert len(prompts) == 4
        assert prompts[0] == ("Is thing 0 variant 0 a stuff? "
                              "Answer only 'Yes' or 'No'.")
        assert targets[2] == ("No", "Yes")

    def test_load_cli_single_rate_over_corpus(self, tmp_path, capsys):
        args = argparse.Namespace(
            load_rate="40", load_duration=0.5, load_seed=0,
            load_jsonl=None, replay=self._corpus(tmp_path),
            max_rephrasings=None, input="-")
        rc = serve_cli.run_load_cli(FakeEngine("cli/model"), args,
                                    SchedulerConfig(**FAST))
        assert rc == 0
        report = json.loads(capsys.readouterr().out)
        assert report["mode"] == "open"
        assert report["parity"]["mismatched_rows"] == 0

    def test_load_cli_rate_list_runs_sweep(self, tmp_path, capsys):
        args = argparse.Namespace(
            load_rate="20,40,80", load_duration=0.3, load_seed=0,
            load_jsonl=None, replay=self._corpus(tmp_path),
            max_rephrasings=None, input="-")
        rc = serve_cli.run_load_cli(FakeEngine("cli/model"), args,
                                    SchedulerConfig(**FAST))
        assert rc == 0
        block = json.loads(capsys.readouterr().out)
        assert len(block["rates"]) == 3
        assert block["parity_ok"] is True
        assert "closed_loop" in block

    def test_load_cli_two_rates_rejected_not_dropped(self, tmp_path,
                                                     capsys):
        """Review regression: two comma-separated rates must be rejected
        loudly — silently running only the first would report a
        single-point curve as if it covered the request."""
        args = argparse.Namespace(
            load_rate="20,40", load_duration=0.2, load_seed=0,
            load_jsonl=None, replay=self._corpus(tmp_path),
            max_rephrasings=None, input="-")
        rc = serve_cli.run_load_cli(FakeEngine("cli/model"), args,
                                    SchedulerConfig(**FAST))
        assert rc == 2
        assert "needs >= 3" in capsys.readouterr().err

    def test_load_cli_empty_rate_list_is_a_clean_error(self, tmp_path,
                                                       capsys):
        """Review regression: '--load-rate ,' must exit 2 with the
        '# serve load:' diagnostic, not IndexError."""
        args = argparse.Namespace(
            load_rate=",", load_duration=0.2, load_seed=0,
            load_jsonl=None, replay=self._corpus(tmp_path),
            max_rephrasings=None, input="-")
        rc = serve_cli.run_load_cli(FakeEngine("cli/model"), args,
                                    SchedulerConfig(**FAST))
        assert rc == 2
        assert "no rates" in capsys.readouterr().err

    def test_jsonl_lines_name_their_rate_point(self, tmp_path):
        """Review regression: a sweep streams every point (and the
        closed comparator) into ONE jsonl — each line must name its
        mode + offered rate or the anatomy is unattributable."""
        path = tmp_path / "anatomy.jsonl"
        load_mod.rate_sweep(
            FakeEngine("jl/model"), [f"q{i}" for i in range(4)],
            rates=(20.0, 40.0, 80.0), duration_s=0.3, seed=0,
            parity=False, closed_comparator=True,
            config=SchedulerConfig(**FAST), jsonl=str(path))
        lines = [json.loads(l) for l in path.read_text().splitlines()]
        rates_seen = {(l["mode"], l["offered_rate"]) for l in lines}
        assert ("open", 20.0) in rates_seen
        assert ("open", 40.0) in rates_seen
        assert ("open", 80.0) in rates_seen
        assert ("closed", None) in rates_seen

    def test_load_cli_pools_input_lines(self, tmp_path, capsys):
        path = tmp_path / "reqs.jsonl"
        path.write_text("".join(json.dumps({"prompt": f"q{i}"}) + "\n"
                                for i in range(4)))
        args = argparse.Namespace(
            load_rate="30", load_duration=0.4, load_seed=1,
            load_jsonl=str(tmp_path / "anatomy.jsonl"), replay=None,
            max_rephrasings=None, input=str(path))
        rc = serve_cli.run_load_cli(FakeEngine("cli/model"), args,
                                    SchedulerConfig(**FAST))
        assert rc == 0
        lines = (tmp_path / "anatomy.jsonl").read_text().splitlines()
        report = json.loads(capsys.readouterr().out)
        assert len(lines) == report["requests"]
        ok = [json.loads(l) for l in lines if json.loads(l).get("ok")]
        assert ok and all("serve_engine_ms" in r for r in ok)

    def test_load_cli_hosts_metrics_port_during_run(self, tmp_path,
                                                    capsys, monkeypatch):
        """Review regression: --metrics-port must not be silently
        ignored in load mode — the histogram families exist exactly for
        a scraper watching a load run.  The server wiring is asserted
        with a recording fake (the real endpoint's behavior is covered
        by the healthz/endpoint tests above and test_obs_metrics.py);
        the start must precede the load run and the close must follow
        it."""
        events = []

        class RecordingServer:
            def __init__(self, registry, port, host="127.0.0.1",
                         healthz_fn=None):
                self.port = port

            def start(self):
                events.append(("start", self.port))
                return self

            def close(self):
                events.append(("close", self.port))

        monkeypatch.setattr(obs_metrics, "MetricsServer", RecordingServer)
        args = argparse.Namespace(
            load_rate="40", load_duration=0.3, load_seed=0,
            load_jsonl=None, replay=self._corpus(tmp_path),
            max_rephrasings=None, input="-", metrics_port=9617)
        rc = serve_cli.run_load_cli(FakeEngine("cli/model"), args,
                                    SchedulerConfig(**FAST))
        assert rc == 0
        assert events == [("start", 9617), ("close", 9617)]
        err = capsys.readouterr().err
        assert ":9617/metrics" in err           # operator told where

    def test_main_cli_registers_load_flags(self):
        import os

        src = open(os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))),
            "llm_interpretation_replication_tpu", "__main__.py")).read()
        for flag in ("--load-rate", "--load-duration", "--load-seed",
                     "--load-jsonl"):
            assert flag in src, flag


# ---------------------------------------------------------------------------
# bench --serve-load (acceptance) + bench-diff / obs report alignment
# ---------------------------------------------------------------------------

def _serve_load_block(p99s=(6.0, 8.0, 40.0), achieved=(10.0, 20.0, 24.0),
                      offered=(10.0, 20.0, 30.0)):
    rates = []
    for o, a, p in zip(offered, achieved, p99s):
        rates.append({
            "mode": "open", "offered_rate": o, "achieved_rows_per_s": a,
            "requests": 10, "completed": 10, "errors": 0, "shed": 0,
            "duration_s": 1.0, "makespan_s": 1.0, "drain_s": 0.05,
            "hist_requests": 10,
            "latency_ms": {"p50": p / 2, "p90": p * 0.8, "p99": p,
                           "p99.9": p * 1.2},
            "phases_ms": {k: {"p50": 1.0, "p90": 2.0, "p99": 3.0,
                              "p99.9": 4.0, "mean": 1.5}
                          for k in ("queue_wait", "coalesce",
                                    "serve_engine", "respond")},
            "queue_depth": {"max": 3, "mean": 1.0, "trajectory": []},
            "parity": {"checked_rows": 10, "mismatched_rows": 0,
                       "mismatched_indices": []},
        })
    return {"mode": "open-loop poisson", "seed": 0, "duration_s": 1.0,
            "rates": rates, "saturation_rows_per_s": max(achieved),
            "knee_offered_rate": 20.0, "knee_beyond_sweep": False,
            "parity_ok": True}


class TestBenchServeLoad:
    def test_sweep_mode_emits_serve_load_block(self, tmp_path):
        """Acceptance: bench --serve-load attaches a serve_load block
        with >= 3 offered-rate points, p50/p90/p99/p99.9 + per-phase
        decomposition from histograms, a saturation estimate, and the
        row-parity assertion vs the offline rows."""
        import os
        import sys as _sys

        _sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        import bench
        import jax
        import jax.numpy as jnp
        from test_bench import TINY, _args
        from llm_interpretation_replication_tpu.models.decoder import (
            DecoderConfig,
        )

        cfg = DecoderConfig(**TINY)
        params = bench.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
        args = _args(tmp_path, batch=8)
        args.serve_load = True
        args.serve_load_rates = "auto"
        args.serve_load_duration = 0.5
        args.serve_load_seed = 0
        pps, rate, out = bench.run_sweep_mode(args, cfg, params)
        block = args.serve_load_report
        assert len(block["rates"]) >= 3
        for point in block["rates"]:
            assert {"p50", "p90", "p99", "p99.9"} <= set(point["latency_ms"])
            assert set(point["phases_ms"]) == {
                "queue_wait", "coalesce", "serve_engine", "respond"}
            assert point["parity"]["mismatched_rows"] == 0
            assert "trajectory" in point["queue_depth"]
        assert block["saturation_rows_per_s"] > 0
        assert block["parity_ok"] is True
        assert block["closed_loop"]["completed"] > 0

    def test_bench_registers_and_gates_serve_load_flags(self):
        import os

        src = open(os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py")).read()
        for flag in ("--serve-load", "--serve-load-rates",
                     "--serve-load-duration", "--serve-load-seed"):
            assert f'"{flag}"' in src, flag
        assert "--serve-load rides the sweep mode" in src


class TestBenchDiffServeLoad:
    def test_aligns_blocks_and_flags_latency_regression(self):
        """Acceptance: bench-diff aligns serve_load blocks across two
        records — per-point achieved (higher-better) and p99 latency
        (LOWER-better) — and a p99 that grew is the regression.  Points
        align by SWEEP POSITION: the records deliberately carry
        DIFFERENT offered rates (the default 'auto' derives them from
        each record's own measured ceiling, so the floats never repeat
        across rounds — review regression)."""
        old = {"metric": "prompts/sec/chip (END-TO-END ...)", "value": 100.0,
               "unit": "prompts/sec", "label": "r06",
               "serve_load": _serve_load_block(
                   p99s=(6.0, 8.0, 40.0), offered=(10.0, 20.0, 30.0))}
        new = {"metric": "prompts/sec/chip (END-TO-END ...)", "value": 101.0,
               "unit": "prompts/sec", "label": "r07",
               "serve_load": _serve_load_block(
                   p99s=(6.0, 30.0, 40.0), offered=(10.4, 20.8, 31.2))}
        diff = diff_records([old, new], threshold_pct=5.0)
        rows = {r["key"]: r for r in diff["metrics"]}
        assert rows["serve-load[1] p99 [ms]"]["verdict"] == "REGRESSION"
        assert rows["serve-load[0] p99 [ms]"]["verdict"] == "ok"
        assert rows["serve-load[0] achieved [rows/sec]"]["verdict"] == "ok"
        assert rows["serve-load saturation [rows/sec]"]["verdict"] == "ok"
        # the bracket's offered rate rides along informationally — its
        # drift must not read as a verdict
        assert rows["serve-load[1] offered"]["values"] == [20.0, 20.8]
        assert rows["serve-load[1] offered"]["verdict"] == "ok"
        assert any(r["key"] == "serve-load[1] p99 [ms]"
                   for r in diff["regressions"])
        assert "serve-load[1] p99 [ms]" in format_diff_table(diff)

    def test_latency_drop_is_improvement_and_throughput_drop_regresses(self):
        old = {"metric": "m", "value": 1.0, "unit": "prompts/sec",
               "label": "a", "serve_load": _serve_load_block(
                   p99s=(40.0, 40.0, 40.0), achieved=(10.0, 20.0, 24.0))}
        new = {"metric": "m", "value": 1.0, "unit": "prompts/sec",
               "label": "b", "serve_load": _serve_load_block(
                   p99s=(6.0, 6.0, 6.0), achieved=(10.0, 20.0, 12.0))}
        diff = diff_records([old, new], threshold_pct=5.0)
        rows = {r["key"]: r for r in diff["metrics"]}
        assert rows["serve-load[0] p99 [ms]"]["verdict"] == "improved"
        assert rows["serve-load[2] achieved [rows/sec]"]["verdict"] \
            == "REGRESSION"
        assert rows["serve-load saturation [rows/sec]"]["verdict"] \
            == "REGRESSION"

    def test_obs_report_renders_serve_load_table(self, tmp_path, capsys):
        rec = {"metric": "m", "value": 1.0, "unit": "prompts/sec",
               "serve_load": _serve_load_block()}
        path = tmp_path / "BENCH_r99.json"
        path.write_text(json.dumps(rec))
        rc = obs_main(["report", "--serve-load", str(path)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "serve-load latency anatomy" in out
        for phase in ("queue_wait", "coalesce", "serve_engine", "respond"):
            assert phase in out
        assert "saturation" in out

    def test_obs_report_without_block_is_a_clean_error(self, tmp_path,
                                                       capsys):
        path = tmp_path / "BENCH_r98.json"
        path.write_text(json.dumps({"metric": "m", "value": 1.0,
                                    "unit": "prompts/sec"}))
        assert obs_main(["report", "--serve-load", str(path)]) == 2
        assert "no serve_load block" in capsys.readouterr().err
