import math
import random

import numpy as np
import pandas as pd
import pytest

from llm_interpretation_replication_tpu.utils import (
    CheckpointFile,
    ProcessedSet,
    RateLimiter,
    RetryPolicy,
    append_xlsx,
    read_xlsx,
    retry_with_exponential_backoff,
    write_xlsx,
)


class TestXlsx:
    def test_roundtrip_mixed_types(self, tmp_path):
        df = pd.DataFrame(
            {
                "Model": ["gpt-4.1", "claude", "gémini ü"],
                "Token_1_Prob": [0.123456789, 0.0, 1.0],
                "Confidence Value": [85, 0, 100],
                "Odds_Ratio": [1.5, float("inf"), float("nan")],
                "Model Response": ["Yes", "No <tag> & 'quote'", ""],
            }
        )
        path = tmp_path / "out.xlsx"
        write_xlsx(df, path)
        back = read_xlsx(path)
        assert list(back.columns) == list(df.columns)
        assert back["Model"].tolist() == df["Model"].tolist()
        np.testing.assert_allclose(
            back["Token_1_Prob"].astype(float), df["Token_1_Prob"], rtol=1e-12
        )
        assert back["Confidence Value"].tolist() == [85, 0, 100]
        assert back.loc[1, "Odds_Ratio"] == "inf"
        assert back.loc[2, "Odds_Ratio"] is None or (
            isinstance(back.loc[2, "Odds_Ratio"], float)
            and math.isnan(back.loc[2, "Odds_Ratio"])
        )
        assert back.loc[1, "Model Response"] == "No <tag> & 'quote'"

    def test_append(self, tmp_path):
        path = tmp_path / "acc.xlsx"
        append_xlsx(pd.DataFrame({"a": [1, 2]}), path)
        combined = append_xlsx(pd.DataFrame({"a": [3]}), path)
        assert combined["a"].tolist() == [1, 2, 3]
        assert read_xlsx(path)["a"].tolist() == [1, 2, 3]

    def test_readable_by_pandas_schema_columns(self, tmp_path):
        # The reference's perturbation workbook schema (SURVEY.md §2.8 /
        # perturb_prompts.py:966-969) must survive a write/read cycle verbatim.
        cols = [
            "Model", "Original Main Part", "Response Format", "Confidence Format",
            "Rephrased Main Part", "Full Rephrased Prompt", "Full Confidence Prompt",
            "Model Response", "Model Confidence Response", "Log Probabilities",
            "Token_1_Prob", "Token_2_Prob", "Odds_Ratio", "Confidence Value",
            "Weighted Confidence",
        ]
        df = pd.DataFrame([{c: f"v_{i}" for i, c in enumerate(cols)}])
        path = tmp_path / "schema.xlsx"
        write_xlsx(df, path)
        assert list(read_xlsx(path).columns) == cols


class TestRetry:
    def test_retries_then_succeeds(self):
        sleeps = []
        policy = RetryPolicy(
            max_retries=5,
            initial_delay=60.0,
            sleep=sleeps.append,
            rng=random.Random(0),
        )
        calls = {"n": 0}

        @retry_with_exponential_backoff(policy)
        def flaky():
            calls["n"] += 1
            if calls["n"] < 4:
                raise RuntimeError("rate limit")
            return "ok"

        assert flaky() == "ok"
        assert calls["n"] == 4
        assert len(sleeps) == 3
        # Reference behavior: 60 s doubling, capped at 300 s, jitter 0.8-1.2.
        assert 48 <= sleeps[0] <= 72
        assert 96 <= sleeps[1] <= 144
        assert 192 <= sleeps[2] <= 288

    def test_exhaustion_reraises(self):
        policy = RetryPolicy(max_retries=2, sleep=lambda s: None)

        @retry_with_exponential_backoff(policy)
        def always_fails():
            raise ValueError("boom")

        with pytest.raises(ValueError):
            always_fails()

    def test_delay_cap(self):
        policy = RetryPolicy(rng=random.Random(1), sleep=lambda s: None)
        assert policy.delay_for_attempt(10) <= 300 * 1.2

    def test_rate_limiter_spacing(self):
        t = {"now": 0.0}
        waits = []

        def clock():
            return t["now"]

        def sleep(s):
            waits.append(s)
            t["now"] += s

        rl = RateLimiter(2.0, clock=clock, sleep=sleep)  # 0.5 s interval
        for _ in range(3):
            rl.acquire()
        assert waits == pytest.approx([0.5, 0.5], abs=1e-9) or sum(waits) == pytest.approx(1.0)


class TestCheckpoint:
    def test_checkpoint_file_roundtrip(self, tmp_path):
        ck = CheckpointFile(str(tmp_path / "ck.json"), default={"completed_models": [], "results": []})
        state = ck.load()
        assert state == {"completed_models": [], "results": []}
        state["completed_models"].append("falcon-7b")
        ck.save(state)
        assert ck.load()["completed_models"] == ["falcon-7b"]
        ck.clear()
        assert ck.load() == {"completed_models": [], "results": []}

    def test_processed_set_persistence(self, tmp_path):
        path = str(tmp_path / "keys.json")
        ps = ProcessedSet(path)
        ps.add(("gpt-4.1", "scenario_1", 17))
        ps.update([("claude", "scenario_2", 3), ("claude", "scenario_2", 4)])
        reloaded = ProcessedSet(path)
        assert ("gpt-4.1", "scenario_1", 17) in reloaded
        assert ("claude", "scenario_2", 4) in reloaded
        assert ("claude", "scenario_2", 5) not in reloaded
        assert len(reloaded) == 3
