import math
import random

import numpy as np
import pandas as pd
import pytest

from llm_interpretation_replication_tpu.utils import (
    CheckpointFile,
    ProcessedSet,
    RateLimiter,
    RetryPolicy,
    append_xlsx,
    read_xlsx,
    retry_with_exponential_backoff,
    write_xlsx,
)


class TestXlsx:
    def test_roundtrip_mixed_types(self, tmp_path):
        df = pd.DataFrame(
            {
                "Model": ["gpt-4.1", "claude", "gémini ü"],
                "Token_1_Prob": [0.123456789, 0.0, 1.0],
                "Confidence Value": [85, 0, 100],
                "Odds_Ratio": [1.5, float("inf"), float("nan")],
                "Model Response": ["Yes", "No <tag> & 'quote'", ""],
            }
        )
        path = tmp_path / "out.xlsx"
        write_xlsx(df, path)
        back = read_xlsx(path)
        assert list(back.columns) == list(df.columns)
        assert back["Model"].tolist() == df["Model"].tolist()
        np.testing.assert_allclose(
            back["Token_1_Prob"].astype(float), df["Token_1_Prob"], rtol=1e-12
        )
        assert back["Confidence Value"].tolist() == [85, 0, 100]
        assert back.loc[1, "Odds_Ratio"] == "inf"
        assert back.loc[2, "Odds_Ratio"] is None or (
            isinstance(back.loc[2, "Odds_Ratio"], float)
            and math.isnan(back.loc[2, "Odds_Ratio"])
        )
        assert back.loc[1, "Model Response"] == "No <tag> & 'quote'"

    def test_append(self, tmp_path):
        path = tmp_path / "acc.xlsx"
        append_xlsx(pd.DataFrame({"a": [1, 2]}), path)
        combined = append_xlsx(pd.DataFrame({"a": [3]}), path)
        assert combined["a"].tolist() == [1, 2, 3]
        assert read_xlsx(path)["a"].tolist() == [1, 2, 3]

    def test_readable_by_pandas_schema_columns(self, tmp_path):
        # The reference's perturbation workbook schema (SURVEY.md §2.8 /
        # perturb_prompts.py:966-969) must survive a write/read cycle verbatim.
        cols = [
            "Model", "Original Main Part", "Response Format", "Confidence Format",
            "Rephrased Main Part", "Full Rephrased Prompt", "Full Confidence Prompt",
            "Model Response", "Model Confidence Response", "Log Probabilities",
            "Token_1_Prob", "Token_2_Prob", "Odds_Ratio", "Confidence Value",
            "Weighted Confidence",
        ]
        df = pd.DataFrame([{c: f"v_{i}" for i, c in enumerate(cols)}])
        path = tmp_path / "schema.xlsx"
        write_xlsx(df, path)
        assert list(read_xlsx(path).columns) == cols


class TestRetry:
    def test_retries_then_succeeds(self):
        sleeps = []
        policy = RetryPolicy(
            max_retries=5,
            initial_delay=60.0,
            sleep=sleeps.append,
            rng=random.Random(0),
        )
        calls = {"n": 0}

        @retry_with_exponential_backoff(policy)
        def flaky():
            calls["n"] += 1
            if calls["n"] < 4:
                raise RuntimeError("rate limit")
            return "ok"

        assert flaky() == "ok"
        assert calls["n"] == 4
        assert len(sleeps) == 3
        # Reference behavior: 60 s doubling, capped at 300 s, jitter 0.8-1.2.
        assert 48 <= sleeps[0] <= 72
        assert 96 <= sleeps[1] <= 144
        assert 192 <= sleeps[2] <= 288

    def test_exhaustion_reraises(self):
        policy = RetryPolicy(max_retries=2, sleep=lambda s: None)

        @retry_with_exponential_backoff(policy)
        def always_fails():
            raise ValueError("boom")

        with pytest.raises(ValueError):
            always_fails()

    def test_delay_cap(self):
        policy = RetryPolicy(rng=random.Random(1), sleep=lambda s: None)
        assert policy.delay_for_attempt(10) <= 300 * 1.2

    def test_rate_limiter_spacing(self):
        t = {"now": 0.0}
        waits = []

        def clock():
            return t["now"]

        def sleep(s):
            waits.append(s)
            t["now"] += s

        rl = RateLimiter(2.0, clock=clock, sleep=sleep)  # 0.5 s interval
        for _ in range(3):
            rl.acquire()
        assert waits == pytest.approx([0.5, 0.5], abs=1e-9) or sum(waits) == pytest.approx(1.0)


class TestCheckpoint:
    def test_checkpoint_file_roundtrip(self, tmp_path):
        ck = CheckpointFile(str(tmp_path / "ck.json"), default={"completed_models": [], "results": []})
        state = ck.load()
        assert state == {"completed_models": [], "results": []}
        state["completed_models"].append("falcon-7b")
        ck.save(state)
        assert ck.load()["completed_models"] == ["falcon-7b"]
        ck.clear()
        assert ck.load() == {"completed_models": [], "results": []}

    def test_processed_set_persistence(self, tmp_path):
        path = str(tmp_path / "keys.json")
        ps = ProcessedSet(path)
        ps.add(("gpt-4.1", "scenario_1", 17))
        ps.update([("claude", "scenario_2", 3), ("claude", "scenario_2", 4)])
        reloaded = ProcessedSet(path)
        assert ("gpt-4.1", "scenario_1", 17) in reloaded
        assert ("claude", "scenario_2", 4) in reloaded
        assert ("claude", "scenario_2", 5) not in reloaded
        assert len(reloaded) == 3


class TestTelemetryCounters:
    """The counters API (utils/telemetry.py) that the prefix-reuse,
    host-pipeline, and strict-mode layers all report through."""

    @pytest.fixture(autouse=True)
    def _fresh(self):
        from llm_interpretation_replication_tpu.utils import telemetry

        telemetry.clear_counters()
        yield
        telemetry.clear_counters()

    def test_record_read_and_reset_semantics(self):
        from llm_interpretation_replication_tpu.utils import telemetry

        assert telemetry.counter("never_recorded") == 0
        telemetry.record_counter("hits")            # default increment 1
        telemetry.record_counter("hits", 2.5)       # float increments sum
        assert telemetry.counter("hits") == 3.5
        snap = telemetry.counters()
        snap["hits"] = -1                            # snapshot is a COPY
        assert telemetry.counter("hits") == 3.5
        telemetry.clear_counters()
        assert telemetry.counter("hits") == 0
        assert telemetry.counters() == {}

    def test_counters_since_deltas(self):
        from llm_interpretation_replication_tpu.utils import telemetry

        telemetry.record_counter("a", 2)
        snap = telemetry.counters()
        telemetry.record_counter("a", 3)
        telemetry.record_counter("b")
        delta = telemetry.counters_since(snap)
        assert delta == {"a": 3, "b": 1}
        # unchanged counters are omitted; a fresh snapshot yields {}
        assert telemetry.counters_since(telemetry.counters()) == {}

    def test_counters_since_robust_to_clear_mid_snapshot(self):
        """Regression (ISSUE-6 satellite): a clear_counters() between
        snapshot and read used to yield NEGATIVE deltas (value below the
        snapshot).  A cleared-and-restarted counter now reports
        everything recorded since the clear — never a negative."""
        from llm_interpretation_replication_tpu.utils import telemetry

        telemetry.record_counter("a", 5)
        snap = telemetry.counters()
        telemetry.clear_counters()
        telemetry.record_counter("a", 2)
        delta = telemetry.counters_since(snap)
        assert delta == {"a": 2}
        assert all(v >= 0 for v in delta.values())
        # counters untouched since the clear simply vanish from the delta
        telemetry.clear_counters()
        telemetry.record_counter("b", 1)
        snap2 = telemetry.counters()
        telemetry.clear_counters()
        assert telemetry.counters_since(snap2) == {}

    def test_thread_safety_under_concurrent_recording(self):
        import threading

        from llm_interpretation_replication_tpu.utils import telemetry

        n_threads, n_each = 8, 500
        barrier = threading.Barrier(n_threads)

        def hammer():
            barrier.wait()
            for _ in range(n_each):
                telemetry.record_counter("contended")

        threads = [threading.Thread(target=hammer) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # without the lock, lost read-modify-write updates would land
        # below the exact total
        assert telemetry.counter("contended") == n_threads * n_each

    def test_host_prefetcher_background_thread_records(self):
        from llm_interpretation_replication_tpu.runtime.batching import (
            HostPrefetcher,
        )
        from llm_interpretation_replication_tpu.utils import telemetry

        out = list(HostPrefetcher(range(5), lambda i: i * i))
        assert out == [0, 1, 4, 9, 16]
        # the worker thread and the consumer both recorded through the
        # shared lock: one chunk count per item, idle time accumulated
        assert telemetry.counter("host_overlap_chunks") == 5
        assert telemetry.counter("host_overlap_idle_ms") >= 0

    def test_sample_ring_cap_configurable_and_truncation_visible(self):
        """Regression (ISSUE-6 satellite, the silent-window footgun): a
        bounded ring drops history, so percentiles over a long run are
        TAIL statistics — the cap is now configurable per ring and the
        total-vs-retained report makes the truncation visible."""
        from llm_interpretation_replication_tpu.utils import telemetry

        telemetry.clear_samples()
        try:
            telemetry.set_sample_cap(8, "ring")
            assert telemetry.sample_cap("ring") == 8
            for v in range(20):
                telemetry.record_sample("ring", float(v))
            # the ring retains only the tail; the total keeps counting
            assert telemetry.sample_count("ring") == 8
            assert telemetry.sample_total("ring") == 20
            # and the percentile provably reflects ONLY the tail window
            assert telemetry.sample_percentiles("ring")["p50"] >= 12.0
            report = telemetry.sample_ring_report(["ring"])
            assert report["ring"] == {"total": 20, "retained": 8, "cap": 8}
            # lowering a cap trims immediately; strict_report embeds the
            # same visibility block for bench JSON / operator audit
            telemetry.set_sample_cap(4, "ring")
            assert telemetry.sample_count("ring") == 4
            from llm_interpretation_replication_tpu.runtime import strict

            assert strict.strict_report()["samples"]["ring"][
                "retained"] == 4
        finally:
            telemetry.clear_samples()
            telemetry.set_sample_cap(4096, "ring")

    def test_strict_mode_counters_flow_through_this_api(self):
        """recompile_events / blocked_transfers are ordinary counters:
        strict mode records them, benches diff them via counters_since."""
        import numpy as np
        import jax.numpy as jnp

        from llm_interpretation_replication_tpu.runtime import strict
        from llm_interpretation_replication_tpu.utils import telemetry

        strict.activate(sentry=False)
        try:
            snap = telemetry.counters()
            with pytest.raises(Exception, match="[Dd]isallowed"):
                with strict.device_region("utils-test"):
                    jnp.cos(np.ones((3,)))
            assert telemetry.counters_since(snap) == {
                strict.BLOCKED_COUNTER: 1}
            assert strict.strict_report()[strict.BLOCKED_COUNTER] == 1
        finally:
            strict.deactivate()
