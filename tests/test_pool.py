"""serve/pool.py EnginePool + runtime engine teardown (ISSUE 12).

Tier-1 suite (``-m enginepool``): verified ScoringEngine.close()
teardown (device-buffer census back to baseline, double-close
idempotent, typed EngineClosed), routing fairness across per-model
queues and least-loaded replicas, hot unload/load mid-traffic with zero
dropped requests, bit-identical row parity vs single-engine
score_prompts for every local replica, the pool under the --serve-load
open-loop harness with strict-mode ``blocked_transfers == 0``,
cost/latency-aware remote-backend selection over a fake transport, and
the per-replica /healthz + replica-labeled Prometheus export."""

import gc
import threading
import time

import pytest

from test_runtime import _tiny_engine
from test_sweeps import FakeEngine

import jax

from llm_interpretation_replication_tpu.api_backends.openai_client import (
    OpenAIClient,
)
from llm_interpretation_replication_tpu.api_backends.transport import (
    FakeTransport,
)
from llm_interpretation_replication_tpu.runtime import (
    EngineClosed,
    live_buffer_count,
)
from llm_interpretation_replication_tpu.obs import flight
from llm_interpretation_replication_tpu.serve import (
    EnginePool,
    PoisonousRequest,
    PoolClosed,
    PoolConfig,
    RemoteBackend,
    SchedulerConfig,
    ScoreRequest,
    SupervisorConfig,
    UnknownModel,
    rows_equal,
)
from llm_interpretation_replication_tpu.serve import load as load_mod
from llm_interpretation_replication_tpu.serve.pool import (
    LocalReplica,
    RemoteReplica,
)
from llm_interpretation_replication_tpu.utils import telemetry
from llm_interpretation_replication_tpu.utils.testing import (
    BreakableEngine,
    FlakyVendor,
)

pytestmark = pytest.mark.enginepool

#: fast admission for CPU-test traffic
FAST = SchedulerConfig(max_batch=4, max_wait_s=0.005)


def fast_pool(**kw):
    return EnginePool(PoolConfig(scheduler=FAST, **kw))


class SlowEngine(FakeEngine):
    """FakeEngine with a per-call service time, so queues actually form
    and least-loaded routing has load to balance."""

    def __init__(self, model_name, delay_s=0.01):
        super().__init__(model_name)
        self.delay_s = delay_s

    def score_prompts(self, prompts, targets=("Yes", "No"),
                      with_confidence=False, max_new_tokens=None):
        time.sleep(self.delay_s)
        return super().score_prompts(prompts, targets, with_confidence,
                                     max_new_tokens)


# ---------------------------------------------------------------------------
# ScoringEngine.close(): verified teardown (satellite)
# ---------------------------------------------------------------------------

class TestEngineTeardown:
    def test_buffer_census_returns_to_baseline(self):
        """Construct -> score -> close: live device-buffer counts return
        to the pre-construction baseline, param leaves are deleted
        DETERMINISTICALLY (not GC-timing), the prefix-pool audit state
        is swept, and the engine_closed telemetry counter records the
        teardown exactly once."""
        gc.collect()
        base = live_buffer_count()
        snap = telemetry.counters()
        eng, _, _ = _tiny_engine(batch_size=4)
        assert live_buffer_count() > base       # params resident
        rows = eng.score_prompts(
            ["Is a tweet a publication?", "Is soup a beverage?"])
        assert len(rows) == 2 and all(r["success"] for r in rows)
        leaf = jax.tree_util.tree_leaves(eng.params)[0]
        eng.close()
        assert leaf.is_deleted()                # deterministic release
        assert eng.params is None
        pool = eng.last_prefix_pool
        assert pool is None or pool.closed
        del rows, leaf
        gc.collect()
        assert live_buffer_count() <= base
        delta = telemetry.counters_since(snap)
        assert delta.get("engine_closed") == 1

    def test_double_close_idempotent_and_typed_raise(self):
        snap = telemetry.counters()
        eng, _, _ = _tiny_engine(batch_size=4)
        eng.close()
        eng.close()                             # idempotent: no raise
        assert telemetry.counters_since(snap).get("engine_closed") == 1
        with pytest.raises(EngineClosed):
            eng.score_prompts(["x"])
        with pytest.raises(EngineClosed):
            eng.first_token_relative_prob(["x"])
        with pytest.raises(EngineClosed):
            eng.score_prefixed([("a", ("b",))])

    def test_close_release_params_false_keeps_shared_leaves(self):
        """Sibling replicas over ONE param tree (the bench fleet shape):
        closing one with release_params=False must not delete the
        buffers the survivor still scores through."""
        from llm_interpretation_replication_tpu.runtime.engine import (
            ScoringEngine,
        )

        eng, _, _ = _tiny_engine(batch_size=4)
        sibling = ScoringEngine(eng.family, eng.cfg, eng.params,
                                eng.tokenizer, engine_config=eng.ecfg)
        ref = eng.score_prompts(["Is soup a beverage?"])
        sibling.close(release_params=False)
        again = eng.score_prompts(["Is soup a beverage?"])   # still alive
        assert rows_equal(ref[0], again[0])
        eng.close()

    def test_unload_then_load_a_different_model_in_process(self):
        """The capability the teardown exists for: model A's buffers go,
        model B loads into the same process, the census never
        accumulates."""
        gc.collect()
        base = live_buffer_count()
        eng_a, _, _ = _tiny_engine(batch_size=4)
        eng_a.score_prompts(["Is a tweet a publication?"])
        eng_a.close()
        gc.collect()
        assert live_buffer_count() <= base
        eng_b, _, _ = _tiny_engine(batch_size=4)   # the "different" model
        rows = eng_b.score_prompts(["Is soup a beverage?"])
        assert rows[0]["success"]
        eng_b.close()
        gc.collect()
        assert live_buffer_count() <= base


# ---------------------------------------------------------------------------
# Routing: per-model queues, least-loaded replicas
# ---------------------------------------------------------------------------

class TestRouting:
    def test_per_model_queues_route_to_their_own_engines(self):
        """Two models behind one front door: every request resolves
        through ITS model's engine (FakeEngine rows hash the model name,
        so cross-model leakage would show as a row mismatch)."""
        alpha, beta = FakeEngine("fake/alpha-7b"), FakeEngine("fake/beta-7b")
        ref_a = alpha.score_prompts(["q0", "q1"])
        ref_b = beta.score_prompts(["q0", "q1"])
        with fast_pool() as pool:
            pool.load("alpha", alpha)
            pool.load("beta", beta)
            futs_a = [pool.submit(ScoreRequest(prompt=f"q{i}"),
                                  model="alpha") for i in range(2)]
            futs_b = [pool.submit(ScoreRequest(prompt=f"q{i}",
                                               model="beta"))
                      for i in range(2)]
            rows_a = [f.result(timeout=30) for f in futs_a]
            rows_b = [f.result(timeout=30) for f in futs_b]
        for got, want in zip(rows_a, ref_a):
            assert rows_equal(got, want)
        for got, want in zip(rows_b, ref_b):
            assert rows_equal(got, want)

    def test_least_loaded_spreads_across_replicas(self):
        """With real service time, a 2-replica model serves from BOTH
        replicas — the router balances on outstanding work instead of
        pinning one."""
        ea = SlowEngine("fake/alpha-7b", delay_s=0.02)
        eb = SlowEngine("fake/alpha-7b", delay_s=0.02)
        with fast_pool() as pool:
            pool.load("alpha", ea)
            pool.load("alpha", eb)
            futs = [pool.submit(ScoreRequest(prompt=f"q{i}"),
                                model="alpha") for i in range(24)]
            for f in futs:
                f.result(timeout=60)
        assert ea.calls > 0 and eb.calls > 0

    def test_unknown_model_is_typed(self):
        with fast_pool() as pool:
            pool.load("alpha", FakeEngine("fake/alpha-7b"))
            with pytest.raises(UnknownModel):
                pool.submit(ScoreRequest(prompt="x"), model="nope")

    def test_single_model_pool_resolves_model_omitted(self):
        with fast_pool() as pool:
            pool.load("alpha", FakeEngine("fake/alpha-7b"))
            row = pool.submit(ScoreRequest(prompt="q0")).result(timeout=30)
        assert row["success"]

    def test_submit_after_close_is_typed(self):
        pool = fast_pool()
        pool.load("alpha", FakeEngine("fake/alpha-7b"))
        pool.close()
        with pytest.raises(PoolClosed):
            pool.submit(ScoreRequest(prompt="x"), model="alpha")

    def test_pool_queue_honors_deadlines(self):
        """A deadline covers POOL queue time (the scheduler convention):
        a bounded-time request parked behind a hot swap with no live
        replica rejects TYPED instead of hanging, and the queue never
        silently grants the pool wait on top of the replica wait."""
        from llm_interpretation_replication_tpu.serve import (
            DeadlineExceeded,
        )

        with fast_pool() as pool:
            r0 = pool.load("alpha", FakeEngine("fake/alpha-7b"))
            pool.unload(r0.rid)            # swap window: no live replica
            fut = pool.submit(ScoreRequest(prompt="x", timeout_s=0.05),
                              model="alpha")
            err = fut.exception(timeout=10)
            assert isinstance(err, DeadlineExceeded)

    def test_pool_queue_backpressure_is_typed(self):
        """The per-model front queue is bounded by the scheduler
        template's queue_capacity — a submit past it sheds with the
        typed QueueFull, never silent unbounded admission."""
        from llm_interpretation_replication_tpu.serve import QueueFull

        cfg = SchedulerConfig(max_batch=4, max_wait_s=0.005,
                              queue_capacity=3)
        pool = EnginePool(PoolConfig(scheduler=cfg))
        try:
            r0 = pool.load("alpha", FakeEngine("fake/alpha-7b"))
            pool.unload(r0.rid)            # nothing drains the queue
            for i in range(3):
                pool.submit(ScoreRequest(prompt=f"q{i}"), model="alpha")
            with pytest.raises(QueueFull):
                pool.submit(ScoreRequest(prompt="q3"), model="alpha")
        finally:
            pool.close(drain=False)

    def test_pool_queue_priority_ordering(self):
        """Higher priority dispatches first from the pool queue (FIFO
        within a level) — measured at the replica engine's call log."""
        order = []

        class LoggingEngine(FakeEngine):
            def score_prompts(self, prompts, targets=("Yes", "No"),
                              with_confidence=False, max_new_tokens=None):
                order.extend(prompts)
                return super().score_prompts(prompts, targets,
                                             with_confidence,
                                             max_new_tokens)

        cfg = SchedulerConfig(max_batch=1, max_wait_s=0.005)
        with EnginePool(PoolConfig(scheduler=cfg)) as pool:
            # queue during a swap window (no live replica), so dispatch
            # order is the router's choice, not submission timing
            r0 = pool.load("alpha", FakeEngine("fake/alpha-7b"))
            pool.unload(r0.rid)
            futs = [
                pool.submit(ScoreRequest(prompt="low", priority=0),
                            model="alpha"),
                pool.submit(ScoreRequest(prompt="high", priority=5),
                            model="alpha"),
            ]
            pool.load("alpha", LoggingEngine("fake/alpha-7b"))
            for f in futs:
                f.result(timeout=30)
        assert order[0] == "high"


# ---------------------------------------------------------------------------
# Hot unload / load under live traffic: zero dropped
# ---------------------------------------------------------------------------

class TestHotSwap:
    def test_unload_mid_traffic_zero_dropped(self):
        """Unloading one of two replicas under continuous traffic drops
        NOTHING: every submitted request resolves with a real row (the
        always-answered contract), and the survivor keeps serving."""
        ea = SlowEngine("fake/alpha-7b", delay_s=0.005)
        eb = SlowEngine("fake/alpha-7b", delay_s=0.005)
        with fast_pool() as pool:
            ra = pool.load("alpha", ea)
            pool.load("alpha", eb)
            futs, stop = [], threading.Event()

            def traffic():
                i = 0
                while not stop.is_set() and i < 200:
                    futs.append(pool.submit(
                        ScoreRequest(prompt=f"w{i}"), model="alpha"))
                    i += 1
                    time.sleep(0.002)

            t = threading.Thread(target=traffic, daemon=True)
            t.start()
            time.sleep(0.05)
            pool.unload(ra.rid)          # hot: eb keeps serving
            time.sleep(0.05)
            stop.set()
            t.join(timeout=5)
            rows = [f.result(timeout=60) for f in futs]
        assert futs and all(r["success"] for r in rows)
        assert len(pool.replicas()) == 0   # closed pool
        assert eb.calls > 0

    def test_unload_all_then_load_keeps_queued_traffic(self):
        """The swap window: with NO replica live, submits for a known
        model queue (never fail) and drain onto the replica loaded
        next — hot model replacement without a dropped request."""
        with fast_pool() as pool:
            r0 = pool.load("alpha", FakeEngine("fake/alpha-7b"))
            pool.unload(r0.rid)
            fut = pool.submit(ScoreRequest(prompt="held"), model="alpha")
            assert not fut.done()
            health = pool.health()
            assert health["status"] == "degraded"        # queued, no replica
            assert "no live replica" in health["degraded_reason"]
            pool.load("alpha", FakeEngine("fake/alpha-7b"))
            assert fut.result(timeout=30)["success"]

    def test_shared_group_releases_only_on_last_unload_any_order(self):
        """build_shared_pool ownership is REFCOUNTED: hot-unloading the
        siblings in ANY order never deletes buffers a survivor still
        scores through; only the last unload releases the shared tree."""
        import json

        from llm_interpretation_replication_tpu.serve import cli as serve_cli

        eng, _, _ = _tiny_engine(batch_size=4)
        leaf = jax.tree_util.tree_leaves(eng.params)[0]
        prompts = ["Is soup a beverage?"]
        offline = eng.score_prompts(prompts)
        pool = serve_cli.build_shared_pool(
            eng, "tiny", 2, SchedulerConfig(max_batch=4, max_wait_s=0.005))
        try:
            rids = [r.rid for r in pool.replicas()]
            pool.unload(rids[0])           # the PRIMARY's replica first
            assert not leaf.is_deleted()   # sibling still serves the tree
            row = pool.submit(ScoreRequest(prompt=prompts[0]),
                              model="tiny").result(timeout=120)
            assert rows_equal(row, offline[0])
            pool.unload(rids[1])           # last sibling out releases
            assert leaf.is_deleted()
        finally:
            pool.close()

    def test_unload_closes_engine_verified(self):
        """Pool unload runs the engine's verified teardown: buffers
        deleted, EngineClosed afterwards."""
        eng, _, _ = _tiny_engine(batch_size=4)
        leaf = jax.tree_util.tree_leaves(eng.params)[0]
        with fast_pool() as pool:
            rep = pool.load("tiny", eng)
            row = pool.submit(ScoreRequest(prompt="Is soup a beverage?"),
                              model="tiny").result(timeout=120)
            assert row["success"]
            pool.unload(rep.rid)
            assert leaf.is_deleted()
            with pytest.raises(EngineClosed):
                eng.score_prompts(["x"])


# ---------------------------------------------------------------------------
# Parity: pool-served rows are bit-identical to single-engine scoring
# ---------------------------------------------------------------------------

class TestPoolParity:
    def test_rows_bit_identical_for_every_local_replica(self):
        """Two tiny-engine replicas (same seed => same weights): every
        pool-served row equals the single-engine offline row bit for
        bit, regardless of which replica answered — routing is
        measurement-only."""
        eng_ref, _, _ = _tiny_engine(batch_size=4)
        eng_a, _, _ = _tiny_engine(batch_size=4)
        eng_b, _, _ = _tiny_engine(batch_size=4)
        prompts = [f"Is thing {i} a stuff?" for i in range(8)]
        offline = eng_ref.score_prompts(prompts)
        with fast_pool() as pool:
            pool.load("tiny", eng_a)
            pool.load("tiny", eng_b)
            futs = [pool.submit(ScoreRequest(prompt=p), model="tiny")
                    for p in prompts]
            rows = [f.result(timeout=300) for f in futs]
        for got, want in zip(rows, offline):
            assert rows_equal(got, want)
        eng_ref.close()


# ---------------------------------------------------------------------------
# The pool under the --serve-load harness (strict mode)
# ---------------------------------------------------------------------------

class TestPoolUnderLoad:
    def test_serve_load_smoke_strict_clean(self):
        """The SAME open-loop harness that measures the single-engine
        scheduler drives the pool (scheduler_factory=pool.client): rows
        stay parity-clean under offered load and the strict-mode
        transfer guard records blocked_transfers == 0."""
        from llm_interpretation_replication_tpu.runtime import strict

        eng_ref, _, _ = _tiny_engine(batch_size=4)
        eng_a, _, _ = _tiny_engine(batch_size=4)
        eng_b, _, _ = _tiny_engine(batch_size=4)
        prompts = [f"Is thing {i} a stuff?" for i in range(6)]
        offline = eng_ref.score_prompts(prompts)   # warm + parity reference
        with fast_pool() as pool:
            pool.load("tiny", eng_a)
            pool.load("tiny", eng_b)
            pool.submit(ScoreRequest(prompt=prompts[0]),
                        model="tiny").result(timeout=300)  # warm replicas
            strict.activate(sentry=False)
            try:
                report = load_mod.run_load(
                    eng_ref, prompts, rate=30.0, duration_s=0.5,
                    offline_rows=offline,
                    scheduler_factory=lambda cfg: pool.client("tiny"))
            finally:
                strict.deactivate()
        assert report["errors"] == 0
        assert report["parity"]["mismatched_rows"] == 0
        assert report["blocked_transfers"] == 0
        eng_ref.close()


# ---------------------------------------------------------------------------
# Fleet self-healing (ISSUE 16): supervised failover, poison ceiling,
# wedge watchdog, hedging, vendor circuit breakers
# ---------------------------------------------------------------------------

def _wait_for(cond, timeout_s=8.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return cond()


class TestSupervision:
    def _sup_pool(self, **kw):
        sup = SupervisorConfig(rebuild_backoff_initial_s=0.05,
                               rebuild_backoff_max_s=0.2, poll_s=0.01, **kw)
        return EnginePool(PoolConfig(scheduler=FAST, supervision=sup))

    def test_failover_matrix_strict_bit_identical(self, tmp_path):
        """The strict failover matrix: a replica killed under the
        --serve-load open-loop harness.  Every request is answered, the
        answered rows are bit-identical to the no-fault offline run
        (failover re-enters the queue — provenance rides on ``timing``,
        never the row), the strict transfer guard stays at
        ``blocked_transfers == 0``, the crashed lineage rebuilds, and
        the injected kill leaves a flight-recorder dump."""
        from llm_interpretation_replication_tpu.runtime import strict

        eng_ref, _, _ = _tiny_engine(batch_size=4)
        victim = BreakableEngine(_tiny_engine(batch_size=4)[0])
        sibling = BreakableEngine(_tiny_engine(batch_size=4)[0])
        prompts = [f"Is thing {i} a stuff?" for i in range(6)]
        offline = eng_ref.score_prompts(prompts)   # warm + parity reference
        flight.enable(str(tmp_path))
        pool = self._sup_pool()
        try:
            pool.load("tiny", victim)
            pool.load("tiny", sibling)
            pool.supervisor.register_rebuild(
                "tiny",
                lambda: BreakableEngine(_tiny_engine(batch_size=4)[0]))
            pool.submit(ScoreRequest(prompt=prompts[0]),
                        model="tiny").result(timeout=300)  # warm replicas
            # dead, but still "live" to the router: the next request
            # dispatched to it crashes mid-traffic and must fail over
            victim.kill()
            strict.activate(sentry=False)
            try:
                report = load_mod.run_load(
                    eng_ref, prompts, rate=30.0, duration_s=0.5,
                    offline_rows=offline,
                    scheduler_factory=lambda cfg: pool.client("tiny"))
            finally:
                strict.deactivate()
            rep = pool.supervisor.report()
            assert report["errors"] == 0                       # all answered
            assert report["errors_by_type"].get("TimeoutError", 0) == 0
            assert report["parity"]["mismatched_rows"] == 0    # bit-identical
            assert report["blocked_transfers"] == 0
            assert rep["incidents"] >= 1 and rep["crashes"] >= 1
            assert rep["requests_failed_over"] >= 1
            assert rep["requests_lost"] == 0
            assert _wait_for(
                lambda: pool.supervisor.report()["restarts"] >= 1)
            flight.get_recorder().wait()
            assert sorted(tmp_path.glob(
                "flightrec-pool_replica_crash-*.json"))
        finally:
            victim.heal()
            sibling.heal()
            pool.close()
            flight.get_recorder().wait()
            flight.disable()
            eng_ref.close()

    def test_poison_row_ceiling_typed_rejection(self):
        """The same request killing ``poison_kill_limit`` replicas is
        poisoned: the caller gets a typed :class:`PoisonousRequest`, a
        third replica never sees the row, and clean traffic keeps
        flowing through the survivors."""
        engines = [BreakableEngine(FakeEngine("tiny"),
                                   poison_marker="POISONROW")
                   for _ in range(3)]
        pool = self._sup_pool()
        try:
            for eng in engines:
                pool.load("tiny", eng)
            fut = pool.submit(
                ScoreRequest(prompt="this one is a POISONROW record"),
                model="tiny")
            with pytest.raises(PoisonousRequest):
                fut.result(timeout=60)
            rep = pool.supervisor.report()
            assert rep["poison_rejects"] == 1
            # the ceiling held: exactly two replicas crashed on the row
            assert sum(1 for eng in engines if eng.crashes > 0) == 2
            row = pool.submit(ScoreRequest(prompt="a clean row"),
                              model="tiny").result(timeout=60)
            assert row is not None
        finally:
            pool.close()

    def test_wedge_detection_reclaims_and_rebuilds(self):
        """A wedged replica (hung device: busy, no progress beats) is
        detected by the supervisor's watchdog within the wedge timeout,
        its in-flight legs are reclaimed and answered by the sibling,
        and the lineage rebuilds."""
        wedged = BreakableEngine(SlowEngine("tiny", delay_s=0.01))
        healthy = BreakableEngine(SlowEngine("tiny", delay_s=0.01))
        pool = self._sup_pool(wedge_timeout_s=0.3)
        try:
            pool.load("tiny", wedged)
            pool.load("tiny", healthy)
            pool.supervisor.register_rebuild(
                "tiny",
                lambda: BreakableEngine(SlowEngine("tiny", delay_s=0.01)))
            wedged.wedge()
            futs = [pool.submit(ScoreRequest(prompt=f"q{i}"), model="tiny")
                    for i in range(8)]
            assert _wait_for(
                lambda: pool.supervisor.report()["wedges"] >= 1)
            # unblock the hung coalescer so the quarantined corpse's
            # bounded teardown (and leg reclaim) can complete
            wedged.heal()
            rows = [f.result(timeout=120) for f in futs]
            assert all(r is not None for r in rows)
            rep = pool.supervisor.report()
            assert rep["wedges"] == 1          # one incident, many legs
            assert rep["detection_ms"] is not None
            assert rep["requests_lost"] == 0
            assert _wait_for(
                lambda: pool.supervisor.report()["restarts"] >= 1)
        finally:
            wedged.heal()
            healthy.heal()
            pool.close()

    def test_hedge_rescues_silent_straggler(self):
        """Opt-in hedging: with wedge detection OFF, a silently-stuck
        replica's requests exceed hedge_k x p99 and a second leg
        launches on the sibling — every request answered, hedges won
        counted, nothing lost."""
        straggler = BreakableEngine(FakeEngine("tiny"))
        rescuer = BreakableEngine(FakeEngine("tiny"))
        pool = self._sup_pool(hedge=True, hedge_k=2.0, hedge_min_samples=4)
        try:
            pool.load("tiny", straggler)
            pool.load("tiny", rescuer)
            # establish the per-model p99 the hedge threshold needs
            warm = [pool.submit(ScoreRequest(prompt=f"w{i}"), model="tiny")
                    for i in range(8)]
            for f in warm:
                f.result(timeout=60)
            straggler.wedge()
            futs = [pool.submit(ScoreRequest(prompt=f"q{i}"), model="tiny")
                    for i in range(6)]
            rows = [f.result(timeout=120) for f in futs]
            assert all(r is not None for r in rows)
            rep = pool.supervisor.report()
            assert rep["hedges_launched"] >= 1
            assert rep["hedges_won"] >= 1
            assert rep["requests_lost"] == 0
        finally:
            straggler.heal()
            rescuer.heal()
            pool.close()

    def test_vendor_breaker_open_shed_halfopen_reclose(self):
        """A hard vendor outage opens the circuit breaker after the
        failure threshold; traffic sheds to the local replica with
        every request still answered; after the cooldown a half-open
        probe against the healed vendor re-closes the breaker."""
        vendor = FlakyVendor()
        local = BreakableEngine(FakeEngine("m"))
        pool = self._sup_pool(breaker_failure_threshold=3,
                              breaker_cooldown_s=0.2)
        try:
            pool.load("m", local)
            pool.load_remote(RemoteBackend("m", vendor), model="m")
            vendor.down = True
            futs = [pool.submit(ScoreRequest(prompt=f"q{i}"), model="m")
                    for i in range(20)]
            rows = [f.result(timeout=120) for f in futs]
            assert all(r is not None for r in rows)   # shed, not lost
            assert _wait_for(
                lambda: "open" in pool.supervisor.breaker_states().values())
            assert vendor.failures >= 3
            # heal the vendor; keep trickling requests until a half-open
            # probe succeeds and the breaker re-closes
            vendor.down = False
            assert _wait_for(lambda: (
                pool.submit(ScoreRequest(prompt="probe"),
                            model="m").result(timeout=60) is not None
                and all(s == "closed"
                        for s in pool.supervisor.breaker_states().values())
            ), timeout_s=15.0)
        finally:
            pool.close()


# ---------------------------------------------------------------------------
# Remote backends: cost/latency-aware selection over a fake transport
# ---------------------------------------------------------------------------

def _openai_backend(model, pricing, calls_log=None):
    ft = FakeTransport()

    def responder(call):
        if calls_log is not None:
            calls_log.append(call["json"]["model"])
        return (200, {
            "choices": [{
                "message": {"content": "Yes"},
                "logprobs": {"content": [{"top_logprobs": [
                    {"token": "Yes", "logprob": -0.2},
                    {"token": "No", "logprob": -1.8}]}]},
            }],
            "usage": {"prompt_tokens": 10, "completion_tokens": 2},
        })

    ft.add("POST", "chat/completions", responder)
    client = OpenAIClient(api_key="test-key", transport=ft)
    return RemoteBackend.openai(client, model, pricing=pricing)


class TestRemoteBackends:
    def test_vendor_row_matches_result_contract(self):
        backend = _openai_backend("gpt-cheap",
                                  {"gpt-cheap": {"input": 1, "output": 2}})
        with fast_pool() as pool:
            pool.load_remote(backend)
            row = pool.submit(ScoreRequest(prompt="Is soup a beverage?"),
                              model="gpt-cheap").result(timeout=30)
        assert set(row) >= {"yes_prob", "no_prob", "relative_prob",
                            "odds_ratio", "completion", "success"}
        assert row["success"] and row["completion"] == "Yes"
        assert 0.0 <= row["relative_prob"] <= 1.0
        usage = backend.tracker.summary()["gpt-cheap"]
        assert usage["requests"] == 1
        assert backend.tracker.cost("gpt-cheap") > 0

    def test_cost_weight_prefers_cheaper_backend(self):
        """cost_weight=1/latency_weight=0: every request lands on the
        cheaper vendor — selection reads the pre-dispatch USD estimate
        from the cost.py pricing table."""
        log = []
        cheap = _openai_backend(
            "gpt-cheap", {"gpt-cheap": {"input": 1.0, "output": 1.0}}, log)
        dear = _openai_backend(
            "gpt-dear", {"gpt-dear": {"input": 500.0, "output": 500.0}}, log)
        with fast_pool(cost_weight=1.0, latency_weight=0.0) as pool:
            pool.load_remote(cheap, model="gpt")
            pool.load_remote(dear, model="gpt")
            futs = [pool.submit(ScoreRequest(prompt="Is soup a beverage?"),
                                model="gpt") for _ in range(6)]
            for f in futs:
                f.result(timeout=30)
        assert log.count("gpt-cheap") == 6 and "gpt-dear" not in log

    def test_latency_weight_prefers_faster_backend(self):
        """latency_weight=1/cost_weight=0 with seeded observations: the
        router picks the replica whose observed-latency EWMA predicts
        the smaller wait."""
        fast = _openai_backend("gpt-fast", {})
        slow = _openai_backend("gpt-slow", {})
        pool = fast_pool(cost_weight=0.0, latency_weight=1.0)
        try:
            r_fast = pool.load_remote(fast, model="gpt")
            r_slow = pool.load_remote(slow, model="gpt")
            r_fast.note_latency(0.01)
            r_slow.note_latency(2.0)
            with pool._lock:
                chosen = pool._select_replica(
                    "gpt", ScoreRequest(prompt="q"))
            assert chosen is r_fast
            # flip the observations: selection follows the evidence
            r_fast.note_latency(10.0)
            for _ in range(64):
                r_slow.note_latency(0.01)
            with pool._lock:
                chosen = pool._select_replica(
                    "gpt", ScoreRequest(prompt="q"))
            assert chosen is r_slow
        finally:
            pool.close()

    def test_remote_leg_honors_deadlines_without_spending(self):
        """An expired request never reaches the vendor (no dollars
        spent) — it rejects with the typed DeadlineExceeded, same as
        the local scheduler's queue sweep."""
        from llm_interpretation_replication_tpu.serve import (
            DeadlineExceeded,
        )

        calls = []
        ft = FakeTransport()

        def responder(call):
            calls.append(1)
            time.sleep(0.15)
            return (200, {"choices": [{"message": {"content": "Yes"},
                                       "logprobs": {"content": []}}]})

        ft.add("POST", "chat/completions", responder)
        client = OpenAIClient(api_key="k", transport=ft)
        backend = RemoteBackend.openai(client, "gpt-x")
        with fast_pool() as pool:
            pool.load_remote(backend, model="gpt")
            f1 = pool.submit(ScoreRequest(prompt="a"), model="gpt")
            f2 = pool.submit(ScoreRequest(prompt="b", timeout_s=0.05),
                             model="gpt")
            err = f2.exception(timeout=30)
            assert isinstance(err, DeadlineExceeded)
            assert f1.result(timeout=30)["success"]
        assert len(calls) == 1     # the expired request spent nothing

    def test_remote_failure_is_this_requests_typed_error(self):
        """A vendor transport error fails ITS request's future and the
        replica keeps draining — never wedges the pool."""
        ft = FakeTransport()   # no handler registered: every call 404s
        client = OpenAIClient(api_key="k", transport=ft)
        backend = RemoteBackend.openai(client, "gpt-x")
        ok = _openai_backend("gpt-x", {})
        with fast_pool(cost_weight=0.0, latency_weight=1.0) as pool:
            bad = pool.load_remote(backend, model="gpt")
            fut = pool.submit(ScoreRequest(prompt="q"), model="gpt")
            err = fut.exception(timeout=30)
            assert err is not None
            # hot-swap the failing vendor for a healthy one — traffic heals
            pool.unload(bad.rid)
            pool.load_remote(ok, model="gpt")
            row = pool.submit(ScoreRequest(prompt="q"),
                              model="gpt").result(timeout=30)
            assert row["success"]


# ---------------------------------------------------------------------------
# /healthz per-replica + replica-labeled Prometheus export (satellite)
# ---------------------------------------------------------------------------

class TestObservability:
    def test_health_reports_per_replica_and_degrades_on_wedge(self):
        """One wedged replica reads degraded while the pool stays up:
        the per-replica document carries id/model/queue-depth/oldest-
        wait, and the pool-level status only degrades where the
        evidence is."""
        release, entered = threading.Event(), threading.Event()

        class WedgedEngine(FakeEngine):
            def score_prompts(self, prompts, targets=("Yes", "No"),
                              with_confidence=False, max_new_tokens=None):
                entered.set()
                release.wait(timeout=30)
                return super().score_prompts(prompts, targets,
                                             with_confidence,
                                             max_new_tokens)

        pool = EnginePool(PoolConfig(scheduler=FAST,
                                     health_max_queue_age_s=0.03))
        try:
            pool.load("wedged", WedgedEngine("fake/wedged-7b"),
                      replica_id="rw")
            pool.load("fine", FakeEngine("fake/fine-7b"), replica_id="rf")
            # one request wedges the engine IN FLIGHT; only then queue a
            # second behind it (submitting both at once would coalesce
            # them into one micro-batch, leaving the queue empty and
            # nothing to age)
            f1 = pool.submit(ScoreRequest(prompt="a"), model="wedged")
            assert entered.wait(timeout=10)
            f2 = pool.submit(ScoreRequest(prompt="b"), model="wedged")
            deadline = time.monotonic() + 10
            doc = pool.health()
            while time.monotonic() < deadline:
                doc = pool.health()
                wedged = [r for r in doc["replicas"]
                          if r["replica"] == "rw"][0]
                if wedged.get("status") == "degraded":
                    break
                time.sleep(0.01)
            assert wedged["status"] == "degraded"
            assert "oldest_wait_s" in wedged
            assert doc["status"] == "degraded"
            assert doc["pool"] == "running"            # pool stays up
            fine = [r for r in doc["replicas"] if r["replica"] == "rf"][0]
            assert fine.get("status") != "degraded"
            assert {"replica", "model", "queue_depth", "outstanding"} <= \
                set(fine)
            # the healthy model still serves while the wedge persists
            row = pool.submit(ScoreRequest(prompt="c"),
                              model="fine").result(timeout=30)
            assert row["success"]
            release.set()
            assert f1.result(timeout=30)["success"]
            assert f2.result(timeout=30)["success"]
        finally:
            release.set()
            pool.close()

    def test_prometheus_export_labels_serve_metrics_by_replica(self):
        """serve_* counters and the latency-anatomy histograms export as
        ``{replica=...,model=...}`` series of the SAME family (the
        ``name|k=v`` labeled-telemetry convention), next to the
        unlabeled fleet aggregate."""
        from llm_interpretation_replication_tpu.obs import (
            metrics as obs_metrics,
        )

        with fast_pool() as pool:
            pool.load("alpha", FakeEngine("fake/alpha-7b"),
                      replica_id="ra")
            pool.load("alpha", FakeEngine("fake/alpha-7b"),
                      replica_id="rb")
            futs = [pool.submit(ScoreRequest(prompt=f"q{i}"),
                                model="alpha") for i in range(8)]
            for f in futs:
                f.result(timeout=30)
        text = obs_metrics.prometheus_text()
        labeled = [l for l in text.splitlines() if 'replica="r' in l]
        assert any(l.startswith("llm_interp_serve_completed{")
                   for l in labeled)
        assert any("llm_interp_serve_req_e2e_ms_bucket{" in l
                   for l in labeled)
        assert any('model="alpha"' in l for l in labeled)
        # one TYPE line per family: labeled series extend the base
        # family instead of minting llm_interp_serve_completed_replica_*
        assert text.count("# TYPE llm_interp_serve_completed counter") == 1

    def test_scheduler_config_labels_are_additive(self):
        """labeled_metric spelling round-trips through the exporter's
        split (unlabeled name unchanged; labels parse back)."""
        from llm_interpretation_replication_tpu.obs.metrics import (
            split_labeled_name,
        )
        from llm_interpretation_replication_tpu.serve import labeled_metric

        name = labeled_metric("serve_batches",
                              {"replica": "r0", "model": "m"})
        assert name == "serve_batches|model=m,replica=r0"
        base, labels = split_labeled_name(name)
        assert base == "serve_batches"
        assert labels == {"replica": "r0", "model": "m"}
        assert split_labeled_name("serve_batches") == ("serve_batches",
                                                       None)


# ---------------------------------------------------------------------------
# Plan-search per-replica operating points
# ---------------------------------------------------------------------------

class TestReplicaPlans:
    def test_replica_plan_prices_the_slice(self):
        """replica_plan searches ONE replica's mesh slice and the chosen
        point maps onto a replica EngineConfig via
        replica_engine_config."""
        from llm_interpretation_replication_tpu.models.config import (
            BENCH_GEOMETRIES,
            DecoderConfig,
        )
        from llm_interpretation_replication_tpu.runtime.engine import (
            EngineConfig,
        )
        from llm_interpretation_replication_tpu.runtime.plan_search import (
            replica_plan,
        )
        from llm_interpretation_replication_tpu.serve.pool import (
            replica_engine_config,
        )

        cfg = DecoderConfig(**BENCH_GEOMETRIES["falcon-7b"])
        plan = replica_plan(cfg, "int8", 1, workload="binary")
        assert plan is not None and plan.fits
        assert plan.data * plan.pipe * plan.model == 1
        ecfg = replica_engine_config(EngineConfig(), plan)
        assert ecfg.batch_size == plan.batch
        assert ecfg.kv_dtype == plan.kv_dtype
        # None plan = keep the hand-configured point
        base = EngineConfig(batch_size=7)
        assert replica_engine_config(base, None) is base

    def test_load_applies_plan_to_the_replica_engine_config(self):
        """EnginePool.load(plan=...) is the production wiring: the
        searched candidate rewrites THIS replica's EngineConfig and
        becomes its health-doc plan note."""
        from llm_interpretation_replication_tpu.models.config import (
            BENCH_GEOMETRIES,
            DecoderConfig,
        )
        from llm_interpretation_replication_tpu.runtime.engine import (
            EngineConfig,
        )
        from llm_interpretation_replication_tpu.runtime.plan_search import (
            replica_plan,
        )

        cfg = DecoderConfig(**BENCH_GEOMETRIES["falcon-7b"])
        plan = replica_plan(cfg, "int8", 1, workload="binary")
        eng = FakeEngine("fake/alpha-7b")
        eng.ecfg = EngineConfig(batch_size=4)
        with fast_pool() as pool:
            rep = pool.load("alpha", eng, plan=plan)
            assert eng.ecfg.batch_size == plan.batch
            assert eng.ecfg.kv_dtype == plan.kv_dtype
            assert rep.plan_note == plan.reason
            doc = pool.health()
        assert doc["replicas"][0]["plan"] == plan.reason

    def test_pool_records_plan_note_in_health(self):
        with fast_pool() as pool:
            pool.load("alpha", FakeEngine("fake/alpha-7b"),
                      plan_note="fits: 1.0 GiB headroom at dp1")
            doc = pool.health()
        assert doc["replicas"][0]["plan"].startswith("fits:")


# ---------------------------------------------------------------------------
# serve CLI: --pool-replicas
# ---------------------------------------------------------------------------

class TestServeCliPool:
    def test_jsonl_driver_over_shared_pool(self):
        """serve --pool-replicas: the JSONL driver answers every line in
        input order through the pool front door; siblings share one
        param tree and the LAST unload releases it (verified teardown
        at pool close)."""
        import io
        import json

        from llm_interpretation_replication_tpu.serve import cli as serve_cli

        eng, _, _ = _tiny_engine(batch_size=4)
        leaf = jax.tree_util.tree_leaves(eng.params)[0]
        pool = serve_cli.build_shared_pool(
            eng, "tiny", 2, SchedulerConfig(max_batch=4, max_wait_s=0.005))
        try:
            groups = {id(r.share_group) for r in pool.replicas()}
            assert len(groups) == 1        # one refcounted owner group
            lines = "\n".join(json.dumps({"prompt": f"Is thing {i} a stuff?"})
                              for i in range(4))
            out = io.StringIO()
            summary = serve_cli.run_jsonl_driver(
                eng, io.StringIO(lines), out,
                SchedulerConfig(max_batch=4), pool=pool)
        finally:
            pool.close()
        assert summary == {"requests": 4, "errors": 0}
        rows = [json.loads(l) for l in out.getvalue().splitlines()]
        assert [r["id"] for r in rows] == [0, 1, 2, 3]
        assert all(r["success"] for r in rows)
        # pool close tore the shared snapshot down through the owning
        # sibling — the census contract, not GC luck
        assert leaf.is_deleted()

    def test_request_lines_accept_model_key(self):
        from llm_interpretation_replication_tpu.serve import cli as serve_cli

        req = serve_cli.parse_request_line({"prompt": "q", "model": "m"})
        assert req.model == "m"


# ---------------------------------------------------------------------------
# bench --serve-load over the pool (acceptance)
# ---------------------------------------------------------------------------

class TestBenchPoolServeLoad:
    def test_bench_emits_serve_load_block_per_pool_configuration(
            self, tmp_path):
        """Acceptance (ISSUE 12): the pool runs through the SAME bench
        --serve-load harness — one serve_load block per configuration
        (single-model-x2 replicas AND a multi-model roster), each with
        >= 3 rate points, per-replica health/plan notes, and the
        row-parity contract intact."""
        import bench
        import jax as _jax
        import jax.numpy as jnp
        from test_bench import TINY, _args
        from llm_interpretation_replication_tpu.models.decoder import (
            DecoderConfig,
        )

        cfg = DecoderConfig(**TINY)
        params = bench.init_params(cfg, _jax.random.PRNGKey(0),
                                   jnp.float32)
        args = _args(tmp_path, batch=8)
        args.sweep_repeats = 1
        args.serve_load = True
        args.serve_load_rates = "auto"
        args.serve_load_duration = 0.4
        args.serve_load_seed = 0
        args.serve_load_replicas = 2
        bench.run_sweep_mode(args, cfg, params)
        block = args.serve_load_pool_report
        assert block["replicas"] == 2
        names = [c["name"] for c in block["configurations"]]
        assert names == ["single-model-x2", "multi-model"]
        for conf in block["configurations"]:
            assert len(conf["replicas"]) == 2
            sl = conf["serve_load"]
            assert len(sl["rates"]) >= 3
            assert sl["parity_ok"] is True
            for point in sl["rates"]:
                assert {"p50", "p90", "p99", "p99.9"} <= set(
                    point["latency_ms"])
        # multi-model configuration really hosts two models
        multi = block["configurations"][1]
        assert len({r["model"] for r in multi["replicas"]}) == 2

    def test_bench_fault_schedule_emits_recovery_block(self, tmp_path):
        """Acceptance (ISSUE 16): an injected replica kill plus a
        vendor outage under the SAME bench harness — zero lost
        requests, a populated `recovery` block (detection latency,
        requests failed-over vs lost), and the vendor breaker opening
        then re-closing after the outage heals."""
        import bench
        import jax as _jax
        import jax.numpy as jnp
        from test_bench import TINY, _args
        from llm_interpretation_replication_tpu.models.decoder import (
            DecoderConfig,
        )

        cfg = DecoderConfig(**TINY)
        params = bench.init_params(cfg, _jax.random.PRNGKey(0),
                                   jnp.float32)
        args = _args(tmp_path, batch=8)
        args.sweep_repeats = 1
        args.serve_load = True
        args.serve_load_rates = "auto"
        args.serve_load_duration = 0.4
        args.serve_load_seed = 0
        args.serve_load_replicas = 2
        args.serve_load_faults = "kill@0.05,vendor@0"
        bench.run_sweep_mode(args, cfg, params)
        block = args.serve_load_pool_report
        names = [c["name"] for c in block["configurations"]]
        assert names[-1] == "self-healing"
        rec = block["recovery"]
        assert rec["requests_lost"] == 0          # the contract
        assert rec["incidents"] >= 1 and rec["crashes"] >= 1
        assert rec["detection_ms"] is not None
        assert rec["load"]["errors_by_type"].get("TimeoutError", 0) == 0
        kinds = [f["kind"] for f in rec["faults_injected"]]
        assert "kill" in kinds and "vendor" in kinds
        vend = rec["vendor_outage"]
        assert vend["answered"] == vend["requests"]   # shed, not lost
        assert vend["breaker_opened"] is True
        assert vend["breaker_reclosed"] is True
        assert vend["vendor_failures"] >= 1


# ---------------------------------------------------------------------------
# Disaggregated prefill/decode fleet (ISSUE 20)
# ---------------------------------------------------------------------------

def _role_clone(eng, tok, **kw):
    """Sibling ScoringEngine over the fixture's param tree, slotted-
    eligible (decode_completions=False, the serve slot-admission
    contract)."""
    import dataclasses

    from llm_interpretation_replication_tpu.runtime.engine import (
        ScoringEngine,
    )

    return ScoringEngine(eng.family, eng.cfg, eng.params, tok,
                         engine_config=dataclasses.replace(
                             eng.ecfg, decode_completions=False, **kw))


class TestDisaggregatedFleet:
    """Role-split replicas over one pool: prefill specialists export KV
    slabs through the scheduler handoff hook, decode specialists import
    them into near-full slot rings; the router learns role affinity on
    top of least-loaded scoring (ISSUE 20 tentpole)."""

    PROMPTS = [f"Is item {i} a vehicle? Answer Yes or No."
               for i in range(10)]

    @pytest.fixture(scope="class")
    def tiny(self):
        eng, _, tok = _tiny_engine(batch_size=8)
        return eng, tok

    def test_two_role_pool_handoff_flows_and_rows_bit_identical(
            self, tiny):
        """Acceptance: a prefill + decode roster answers every request;
        the undecided rows' caches really cross replicas (handoff
        counters balance, requests never route to the decode replica)
        and every row is BIT-identical to offline score_prompts
        (PARITY.md "Cross-replica KV handoff")."""
        eng, tok = tiny
        telemetry.clear_counters()
        pool = EnginePool(PoolConfig(scheduler=SchedulerConfig(
            max_batch=4, max_wait_s=0.02, slot_admission=True)))
        try:
            pool.load("tiny", _role_clone(eng, tok), owns_engine=False,
                      role="prefill")
            pool.load("tiny", _role_clone(eng, tok), owns_engine=False,
                      role="decode")
            futs = [pool.submit(ScoreRequest(prompt=p), model="tiny")
                    for p in self.PROMPTS]
            rows = [f.result(timeout=300) for f in futs]
            docs = {d["role"]: d for d in
                    (r.health(0) for r in pool.replicas())}
        finally:
            pool.close()
        assert all(r["success"] for r in rows)
        c = telemetry.counters()
        assert c.get("pool_slab_handoffs", 0) >= 1
        assert c.get("serve_handoff_rows", 0) >= 1
        assert c.get("slot_slab_import_rows", 0) == \
            c.get("serve_handoff_rows")
        assert c.get("slot_slab_export_rows", 0) == \
            c.get("serve_handoff_rows")
        # role affinity: every request ARRIVED at the prefill replica
        # (e2e latency attributes to the leg the client submitted to)
        assert docs["prefill"]["completed"] == len(self.PROMPTS)
        assert docs["decode"]["completed"] == 0
        offline = _role_clone(eng, tok).score_prompts(self.PROMPTS)
        for a, b in zip(rows, offline):
            assert a["scan_found"] == b["scan_found"]
            for f in ("yes_prob", "no_prob", "relative_prob",
                      "first_token_relative_prob"):
                assert a[f] == b[f], f

    def test_decode_only_pool_still_answers_with_fallback(self):
        """Always-answered beats role purity: with only decode replicas
        live, fresh prompts fall back to them and the
        ``pool_decode_fallback`` counter says the roster is degenerate."""
        telemetry.clear_counters()
        with fast_pool() as pool:
            pool.load("alpha", FakeEngine("fake/alpha-7b"), role="decode")
            row = pool.submit(ScoreRequest(prompt="Is a kayak a boat?"),
                              model="alpha").result(timeout=60)
        assert row["success"]
        assert telemetry.counter("pool_decode_fallback") >= 1

    def test_router_prefers_non_decode_replicas(self):
        """Fresh prompts land on the prefill/unroled replica whenever one
        is live — the decode specialist's queue stays for handoffs."""
        telemetry.clear_counters()
        with fast_pool() as pool:
            pool.load("alpha", FakeEngine("fake/alpha-7b"), role="prefill")
            pool.load("alpha", FakeEngine("fake/alpha-7b"), role="decode")
            futs = [pool.submit(ScoreRequest(prompt=f"q{i}"),
                                model="alpha") for i in range(6)]
            rows = [f.result(timeout=60) for f in futs]
            docs = {d["role"]: d for d in
                    (r.health(0) for r in pool.replicas())}
        assert all(r["success"] for r in rows)
        assert docs["prefill"]["completed"] == 6
        assert docs["decode"]["completed"] == 0
        assert telemetry.counter("pool_decode_fallback") == 0

    def test_load_rejects_unknown_role(self):
        with fast_pool() as pool:
            with pytest.raises(ValueError):
                pool.load("alpha", FakeEngine("fake/alpha-7b"),
                          role="draft")

    def test_mesh_slice_binding_and_placement_health(self, tiny,
                                                     eight_cpu_devices):
        """Real mesh-slice placement: a replica loaded with a 4-device
        slice of the 8-device harness binds its engine to THAT mesh
        (``replica_mesh_bound`` fires), scores through it, and the
        health doc says ``sliced`` — vs ``shared`` for a full-pod
        slice (the CPU degenerate placement)."""
        from llm_interpretation_replication_tpu.parallel import (
            mesh as mesh_mod,
        )

        eng, tok = tiny
        slices = mesh_mod.carve_slices(2, devices=eight_cpu_devices)
        assert [len(s) for s in slices] == [4, 4]
        telemetry.clear_counters()
        with fast_pool() as pool:
            rep = pool.load("tiny", _role_clone(eng, tok),
                            owns_engine=False, role="prefill",
                            devices=slices[0])
            assert telemetry.counter("replica_mesh_bound") == 1
            doc = rep.health(0)
            assert doc["role"] == "prefill"
            assert doc["devices"] == 4
            assert doc["placement"] == "sliced"
            futs = [pool.submit(ScoreRequest(prompt=p), model="tiny")
                    for p in ["Is a kayak a boat?", "Is tea a soup?"]]
            rows = [f.result(timeout=300) for f in futs]
        assert all(r["success"] for r in rows)
        shared = mesh_mod.carve_slices(1, devices=eight_cpu_devices)
        with fast_pool() as pool:
            rep = pool.load("tiny", _role_clone(eng, tok),
                            owns_engine=False, devices=shared[0])
            assert rep.health(0)["placement"] == "shared"

    def test_supervisor_threads_role_and_slice_through_rebuild(self):
        """Source pins (the child-forwarding style): a supervised
        rebuild reloads the replica with ITS role and device slice, and
        failover prefers non-decode siblings with decode as the
        always-answered fallback."""
        import os

        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        src = open(os.path.join(
            repo_root, "llm_interpretation_replication_tpu", "serve",
            "supervisor.py"), encoding="utf-8").read()
        assert 'role=getattr(replica, "role", None)' in src
        assert 'devices=getattr(replica, "devices"' in src
        assert src.count('getattr(replica, "role", None) == "decode"') \
            >= 1

    def test_bench_roles_leg_emits_roster_block(self, tmp_path):
        """Acceptance: ``bench --serve-load --serve-load-roles
        prefill:1,decode:1`` measures the disaggregated roster through
        the SAME rate sweep as the symmetric roster — one
        ``serve_load_pool`` configuration tagged by role composition,
        replicas carrying role/placement health docs, parity intact."""
        import bench
        import jax as _jax
        import jax.numpy as jnp
        from test_bench import TINY, _args
        from llm_interpretation_replication_tpu.models.decoder import (
            DecoderConfig,
        )

        cfg = DecoderConfig(**TINY)
        params = bench.init_params(cfg, _jax.random.PRNGKey(0),
                                   jnp.float32)
        args = _args(tmp_path, batch=8)
        args.sweep_repeats = 1
        args.serve_load = True
        args.serve_load_rates = "auto"
        args.serve_load_duration = 0.4
        args.serve_load_seed = 0
        args.serve_load_replicas = 2
        args.serve_load_roles = "prefill:1,decode:1"
        bench.run_sweep_mode(args, cfg, params)
        block = args.serve_load_pool_report
        names = [c["name"] for c in block["configurations"]]
        assert "roles-prefill:1,decode:1" in names
        entry = next(c for c in block["configurations"]
                     if c.get("roles"))
        assert entry["roles"] == {"prefill": 1, "decode": 1}
        roles = sorted(r.get("role") for r in entry["replicas"])
        assert roles == ["decode", "prefill"]
        for r in entry["replicas"]:
            assert r.get("placement") in ("shared", "sliced")
        sl = entry["serve_load"]
        assert len(sl["rates"]) >= 3
        assert sl["parity_ok"] is True
        # knee-vs-knee: the symmetric roster at the same replica count
        # is in the same report for bench-diff to align against
        assert f"single-model-x{len(entry['replicas'])}" in names
