"""Shared test utilities: offline tokenizers and tiny models."""

from __future__ import annotations


def random_decoder_params(cfg, seed: int = 0):
    """Random fp32 param pytree matching ``models.decoder``'s stacked layout
    for a bias-free rotary decoder config (ln1/ln2, attn, mlp)."""
    import numpy as np
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    h, nd = cfg.hidden_size, cfg.num_heads * cfg.head_dim
    kvd = cfg.num_kv_heads * cfg.head_dim
    L, F, V = cfg.num_layers, cfg.intermediate_size, cfg.vocab_size

    def init(*shape):
        return jnp.asarray(rng.standard_normal(shape) * 0.02, jnp.float32)

    layers = {
        "ln1": {"scale": jnp.ones((L, h)), "bias": jnp.zeros((L, h))},
        "ln2": {"scale": jnp.ones((L, h)), "bias": jnp.zeros((L, h))},
        "attn": {
            "wq": init(L, h, nd), "wk": init(L, h, kvd),
            "wv": init(L, h, kvd), "wo": init(L, nd, h),
        },
        "mlp": {"wi": init(L, h, F), "wo": init(L, F, h)},
    }
    params = {
        "embed": {"tokens": init(V, h)},
        "layers": layers,
        "final_ln": {"scale": jnp.ones(h), "bias": jnp.zeros(h)},
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = init(h, V)
    return params


def build_test_tokenizer(vocab_size: int = 300):
    """Byte-level BPE tokenizer trained in-process (zero-egress image: no hub
    downloads).  Distinguishes " Yes" from "Yes" like real GPT-style vocabs."""
    from llm_interpretation_replication_tpu.utils.testing import (
        build_inprocess_tokenizer,
    )

    return build_inprocess_tokenizer(vocab_size)


def chatglm_test_setup(vocab_size: int = 128, seed: int = 11):
    """(hf_config_namespace, torch_state_dict) for the ChatGLM2 tiny geometry
    — the remote-code family with no offline HF oracle; shared by the
    handcrafted-oracle parity test and the int8 quantization audit."""
    import types

    import numpy as np
    import torch

    hf = types.SimpleNamespace(
        model_type="chatglm", padded_vocab_size=vocab_size, hidden_size=32,
        num_layers=3, num_attention_heads=4, kv_channels=8,
        multi_query_attention=True, multi_query_group_num=2,
        ffn_hidden_size=48, seq_length=64, layernorm_epsilon=1e-5,
        rmsnorm=True, add_qkv_bias=True, add_bias_linear=False,
    )
    n, d, g, h, f = 4, 8, 2, 32, 48
    nd, kvd = n * d, g * d
    rng = np.random.default_rng(seed)
    sd = {}
    for i in range(hf.num_layers):
        pre = f"transformer.encoder.layers.{i}"
        sd[f"{pre}.self_attention.query_key_value.weight"] = rng.standard_normal((nd + 2 * kvd, h)) * 0.05
        sd[f"{pre}.self_attention.query_key_value.bias"] = rng.standard_normal(nd + 2 * kvd) * 0.02
        sd[f"{pre}.self_attention.dense.weight"] = rng.standard_normal((h, nd)) * 0.05
        sd[f"{pre}.mlp.dense_h_to_4h.weight"] = rng.standard_normal((2 * f, h)) * 0.05
        sd[f"{pre}.mlp.dense_4h_to_h.weight"] = rng.standard_normal((h, f)) * 0.05
        sd[f"{pre}.input_layernorm.weight"] = 1.0 + rng.standard_normal(h) * 0.05
        sd[f"{pre}.post_attention_layernorm.weight"] = 1.0 + rng.standard_normal(h) * 0.05
    sd["transformer.embedding.word_embeddings.weight"] = rng.standard_normal((vocab_size, h)) * 0.05
    sd["transformer.encoder.final_layernorm.weight"] = 1.0 + rng.standard_normal(h) * 0.05
    sd["transformer.output_layer.weight"] = rng.standard_normal((vocab_size, h)) * 0.05
    return hf, {k: torch.tensor(v) for k, v in sd.items()}
