"""Shared test utilities: offline tokenizers and tiny models."""

from __future__ import annotations


def random_decoder_params(cfg, seed: int = 0):
    """Random fp32 param pytree matching ``models.decoder``'s stacked layout
    for a bias-free rotary decoder config (ln1/ln2, attn, mlp)."""
    import numpy as np
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    h, nd = cfg.hidden_size, cfg.num_heads * cfg.head_dim
    kvd = cfg.num_kv_heads * cfg.head_dim
    L, F, V = cfg.num_layers, cfg.intermediate_size, cfg.vocab_size

    def init(*shape):
        return jnp.asarray(rng.standard_normal(shape) * 0.02, jnp.float32)

    layers = {
        "ln1": {"scale": jnp.ones((L, h)), "bias": jnp.zeros((L, h))},
        "ln2": {"scale": jnp.ones((L, h)), "bias": jnp.zeros((L, h))},
        "attn": {
            "wq": init(L, h, nd), "wk": init(L, h, kvd),
            "wv": init(L, h, kvd), "wo": init(L, nd, h),
        },
        "mlp": {"wi": init(L, h, F), "wo": init(L, F, h)},
    }
    params = {
        "embed": {"tokens": init(V, h)},
        "layers": layers,
        "final_ln": {"scale": jnp.ones(h), "bias": jnp.zeros(h)},
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = init(h, V)
    return params


def build_test_tokenizer(vocab_size: int = 300):
    """Byte-level BPE tokenizer trained in-process (zero-egress image: no hub
    downloads).  Distinguishes " Yes" from "Yes" like real GPT-style vocabs."""
    from llm_interpretation_replication_tpu.utils.testing import (
        build_inprocess_tokenizer,
    )

    return build_inprocess_tokenizer(vocab_size)
