"""Shared test utilities: offline tokenizers and tiny models."""

from __future__ import annotations


def build_test_tokenizer(vocab_size: int = 300):
    """Byte-level BPE tokenizer trained in-process (zero-egress image: no hub
    downloads).  Distinguishes " Yes" from "Yes" like real GPT-style vocabs."""
    from tokenizers import ByteLevelBPETokenizer
    from transformers import PreTrainedTokenizerFast

    tok = ByteLevelBPETokenizer()
    corpus = [
        "Yes No Answer: Yes.",
        "Answer: No.",
        "Is a tweet a publication? Yes",
        "Is soup a beverage? No",
        "confidence 0 1 2 3 4 5 6 7 8 9 10 42 85 90 100",
        "The quick brown fox jumps over the lazy dog.",
    ] * 50
    tok.train_from_iterator(corpus, vocab_size=vocab_size, min_frequency=1)
    inner = tok._tokenizer if hasattr(tok, "_tokenizer") else tok
    fast = PreTrainedTokenizerFast(tokenizer_object=inner)
    fast.pad_token = fast.decode([0])
    fast.pad_token_id = 0
    return fast
