"""Observability layer (obs/): span tracer semantics, export formats,
engine phase coverage, serve trace-id parity, strict-mode tracing, and
the overhead contract.

Tier-1 (``-m obs``).  The tracer is a process-global singleton, so every
test runs against a reset tracer (autouse fixture) and leaves it
disabled."""

import json
import threading
import time

import pytest

from llm_interpretation_replication_tpu import obs
from llm_interpretation_replication_tpu.obs.report import (
    aggregate_spans,
    format_phase_table,
    load_spans,
    phases_block,
)
from llm_interpretation_replication_tpu.obs.report import main as obs_report_main
from llm_interpretation_replication_tpu.utils import telemetry

from test_runtime import _tiny_engine

pytestmark = pytest.mark.obs


@pytest.fixture(autouse=True)
def _fresh_tracer():
    obs.disable()
    obs.get_tracer().reset()
    yield
    obs.disable()
    obs.get_tracer().reset()


class TestSpanTracer:
    def test_nested_phase_self_time_never_double_counts(self):
        """A phase span nested inside another phase span subtracts from
        the parent's SELF time; a structural (phase=None) span is
        transparent — its phase-covered time passes through to the
        nearest phase-tagged ancestor."""
        obs.enable()
        with obs.span("consume", phase="d2h_fetch"):
            time.sleep(0.02)
            with obs.span("leg", leg="binary"):       # structural
                with obs.span("dec", phase="decode"):
                    time.sleep(0.03)
        totals = obs.phase_totals(by_leg=True)
        assert 0.025 <= totals["decode"]["binary"] <= 0.09
        # the fetch span's self time excludes the nested decode
        assert 0.015 <= totals["d2h_fetch"][""] <= 0.05
        flat = obs.phase_totals()
        assert set(flat) == {"decode", "d2h_fetch"}
        # the partition property: phases sum to the outer span's duration
        outer = [s for s in obs.get_tracer().spans()
                 if s["name"] == "consume"][0]
        assert abs(sum(flat.values()) - outer["dur"]) < 0.01

    def test_leg_and_trace_id_inherit_from_enclosing_span(self):
        obs.enable()
        with obs.span("outer", leg="confidence", trace_id="sv-7"):
            with obs.span("inner", phase="decode"):
                pass
        inner = [s for s in obs.get_tracer().spans()
                 if s["name"] == "inner"][0]
        assert inner["leg"] == "confidence"
        assert inner["trace_id"] == "sv-7"
        assert obs.phase_totals(by_leg=True)["decode"].keys() == {
            "confidence"}

    def test_thread_safety_and_per_thread_nesting(self):
        obs.enable()
        n_threads, n_each = 8, 50
        barrier = threading.Barrier(n_threads)

        def hammer():
            barrier.wait()
            for _ in range(n_each):
                with obs.span("outer", phase="a"):
                    with obs.span("inner", phase="b"):
                        pass

        threads = [threading.Thread(target=hammer) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        spans = obs.get_tracer().spans()
        assert len(spans) == n_threads * n_each * 2
        ids = [s["id"] for s in spans]
        assert len(set(ids)) == len(ids)          # allocation is atomic
        # nesting never crossed threads: every inner's parent is an outer
        by_id = {s["id"]: s for s in spans}
        for s in spans:
            if s["name"] == "inner":
                parent = by_id[s["parent"]]
                assert parent["name"] == "outer"
                assert parent["tid"] == s["tid"]

    def test_phase_totals_since_scopes_to_a_window(self):
        obs.enable()
        with obs.span("warmup", phase="prefill"):
            time.sleep(0.01)
        snap = obs.phase_snapshot()
        with obs.span("measured", phase="prefill"):
            time.sleep(0.02)
        delta = obs.phase_totals_since(snap)
        assert 0.015 <= delta["prefill"] <= 0.06
        assert obs.phase_totals()["prefill"] > delta["prefill"]

    def test_chrome_export_is_perfetto_loadable_json(self, tmp_path):
        obs.enable()
        with obs.span("work", phase="decode", leg="binary", bucket=64):
            time.sleep(0.005)
        path = obs.export_chrome(str(tmp_path / "trace.json"))
        with open(path) as f:
            doc = json.load(f)
        events = doc["traceEvents"]
        assert len(events) == 1
        ev = events[0]
        assert ev["ph"] == "X" and ev["name"] == "work"
        assert ev["cat"] == "decode"
        assert ev["dur"] >= 4000          # microseconds
        assert ev["args"]["leg"] == "binary"
        assert ev["args"]["bucket"] == 64
        assert isinstance(ev["ts"], (int, float))
        assert isinstance(ev["pid"], int) and isinstance(ev["tid"], int)

    def test_jsonl_span_log_streams_valid_lines(self, tmp_path):
        log = tmp_path / "spans.jsonl"
        obs.enable(jsonl_path=str(log))
        with obs.span("a", phase="prefill"):
            with obs.span("b", phase="decode"):
                pass
        obs.disable()
        lines = [json.loads(line) for line in
                 log.read_text().strip().splitlines()]
        assert [s["name"] for s in lines] == ["b", "a"]  # close order
        for s in lines:
            assert {"name", "phase", "t0", "t1", "dur", "self",
                    "tid", "id"} <= set(s)

    def test_jsonl_log_truncates_per_session_and_survives_torn_tail(
            self, tmp_path, capsys):
        """Review fixes: (a) a second session on the same path must not
        append onto the first's spans (doubled totals in obs report);
        (b) a torn trailing line (hard-killed run) is skipped with a
        note, not a fatal parse error."""
        log = tmp_path / "s.jsonl"
        obs.enable(jsonl_path=str(log))
        with obs.span("first", phase="prefill"):
            pass
        obs.disable()
        obs.get_tracer().reset()
        obs.enable(jsonl_path=str(log))          # fresh session, same path
        with obs.span("second", phase="prefill"):
            pass
        obs.disable()
        spans = load_spans(str(log))
        assert [s["name"] for s in spans] == ["second"]
        with open(log, "a") as f:
            f.write('{"name": "torn", "pha')     # killed mid-write
        assert [s["name"] for s in load_spans(str(log))] == ["second"]
        assert "malformed" in capsys.readouterr().err

    def test_spans_share_one_clock_epoch(self):
        """add_span (time.monotonic timestamps from the serve layer) and
        context-managed spans must land on one timeline."""
        obs.enable()
        t0 = time.monotonic()
        with obs.span("ctx", phase="prefill"):
            time.sleep(0.005)
        obs.add_span("manual", t0, time.monotonic(), phase="decode")
        ctx, manual = obs.get_tracer().spans()
        assert abs(ctx["t0"] - manual["t0"]) < 0.5
        assert manual["t1"] >= ctx["t1"]

    def test_disabled_tracer_is_a_cheap_no_op(self):
        assert not obs.enabled()
        t0 = time.perf_counter()
        for _ in range(20_000):
            with obs.span("hot", phase="decode", bucket=64) as rec:
                assert rec is None
        # generous bound: ~20k no-op spans must stay far under a second
        assert time.perf_counter() - t0 < 2.0
        assert obs.phase_totals() == {}
        assert obs.get_tracer().spans() == []


class TestReportRoundtrip:
    def _record(self, log_path=None):
        obs.enable(jsonl_path=log_path)
        with obs.span("consume", phase="d2h_fetch"):
            time.sleep(0.01)
            with obs.span("dec", phase="decode", leg="binary"):
                time.sleep(0.01)
        obs.disable()

    def test_jsonl_and_chrome_aggregate_to_the_live_totals(self, tmp_path):
        log = str(tmp_path / "s.jsonl")
        self._record(log)
        live = obs.phase_totals(by_leg=True)
        for path in (log, obs.export_chrome(str(tmp_path / "t.json"))):
            agg = aggregate_spans(load_spans(path))
            assert set(agg) == set(live)
            for phase in live:
                for leg in live[phase]:
                    assert agg[phase][leg] == pytest.approx(
                        live[phase][leg], abs=2e-4)

    def test_phases_block_and_table(self):
        self._record()
        block = phases_block(obs.phase_totals(by_leg=True),
                             wall_s=0.025, rows=10)
        assert block["coverage"] >= 0.7
        assert block["per_phase"]["decode"]["legs"]["binary"] > 0
        assert block["per_phase"]["decode"]["ms_per_row"] > 0
        table = format_phase_table(block)
        assert "decode" in table and "d2h_fetch" in table
        assert "% attributed" in table

    def test_obs_report_cli_over_saved_trace(self, tmp_path, capsys):
        log = str(tmp_path / "s.jsonl")
        self._record(log)
        assert obs_report_main(["report", "--trace", log]) == 0
        out = capsys.readouterr().out
        assert "decode" in out and "d2h_fetch" in out
        assert obs_report_main(
            ["report", "--trace", log, "--format", "json",
             "--wall-s", "0.05", "--rows", "4"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["per_phase"]["decode"]["seconds"] > 0
        assert obs_report_main(
            ["report", "--trace", str(tmp_path / "missing.jsonl")]) == 2

    def test_cli_obs_routes_before_argparse(self, tmp_path, capsys):
        log = str(tmp_path / "s.jsonl")
        self._record(log)
        from llm_interpretation_replication_tpu.__main__ import main

        with pytest.raises(SystemExit) as exc:
            main(["obs", "report", "--trace", log])
        assert exc.value.code == 0
        assert "decode" in capsys.readouterr().out


class TestEnginePhaseCoverage:
    def test_score_prompts_phases_cover_the_call(self):
        """The tiny-engine acceptance proxy for the bench criterion: the
        instrumented phases must attribute the large majority of a
        scoring call's wall-clock (the bench bar on real hardware is
        >= 90%; the CPU harness asserts a conservative 70% — span
        machinery and test-host noise weigh more at millisecond
        scales)."""
        eng, _, _ = _tiny_engine()
        prompts = ["Is a tweet a publication? Answer: Yes",
                   "Is soup a beverage?", "The quick brown fox"] * 2
        eng.score_prompts(prompts)        # warm: compiles outside the claim
        obs.enable()
        t0 = time.perf_counter()
        rows = eng.score_prompts(prompts)
        wall = time.perf_counter() - t0
        totals = obs.phase_totals()
        assert all(r["success"] for r in rows)
        assert {"host_tokenize", "prefill", "dispatch",
                "d2h_fetch"} <= set(totals)
        assert "decode" in totals         # completions decode by default
        coverage = sum(totals.values()) / wall
        assert coverage >= 0.7, (coverage, totals)
        # spans carry the bucket/batch tags the phases table groups by
        prefills = [s for s in obs.get_tracer().spans()
                    if s["phase"] == "prefill"]
        assert prefills and all(
            s["args"]["bucket"] > 0 and s["args"]["batch"] > 0
            for s in prefills)

    def test_fused_two_leg_call_tags_phases_by_leg(self):
        from llm_interpretation_replication_tpu.runtime.engine import LegSpec

        eng, _, _ = _tiny_engine()
        pairs = [("Is a tweet a publication?", (" Answer Yes or No.",
                                                " Confidence 0-100:"))] * 3
        legs = [LegSpec("binary"),
                LegSpec("confidence", with_confidence=True,
                        max_new_tokens=10)]
        obs.enable()
        out = eng.score_prefixed(pairs, legs=legs)
        assert len(out) == 2 and all(len(rows) == 3 for rows in out)
        by_leg = obs.phase_totals(by_leg=True)
        assert set(by_leg["extend_prefill"]) == {"binary", "confidence"}
        assert set(by_leg["d2h_fetch"]) >= {"binary", "confidence"}
        # traced run changed nothing numerically vs an untraced one
        obs.disable()
        out2 = eng.score_prefixed(pairs, legs=legs)
        assert out2[0][0]["relative_prob"] == out[0][0]["relative_prob"]

    def test_traced_results_identical_to_untraced(self):
        eng, _, _ = _tiny_engine()
        prompts = ["Is a tweet a publication?", "Is soup a beverage?"]
        plain = eng.score_prompts(prompts)
        obs.enable(sync=True)             # sync mode must not change rows
        traced = eng.score_prompts(prompts)
        for a, b in zip(plain, traced):
            assert a == b


class TestServeRequestSpans:
    def test_replay_parity_with_trace_ids_in_output(self):
        """Serve request-span parity: with tracing armed, every answered
        row carries its trace_id AND row parity with the offline path
        still holds (rows_equal ignores the measurement-only key)."""
        from llm_interpretation_replication_tpu.serve.replay import replay

        eng, _, _ = _tiny_engine(batch_size=4)
        prompts = ["Is a tweet a publication?", "Is soup a beverage?",
                   "Is a burrito a sandwich?", "The quick brown fox"]
        obs.enable()
        report = replay(eng, prompts)     # require_parity raises on skew
        assert report["mismatched_rows"] == 0
        assert all(row["trace_id"].startswith("sv-")
                   for row in report["serve_rows"])
        # the request lifecycle spans exist and correlate by trace id
        spans = obs.get_tracer().spans()
        phases = {s["phase"] for s in spans}
        assert {"serve_queue_wait", "serve_engine",
                "serve_respond"} <= phases
        waited = {s["trace_id"] for s in spans
                  if s["phase"] == "serve_queue_wait"}
        answered = {row["trace_id"] for row in report["serve_rows"]}
        assert answered <= waited

    def test_untraced_serve_rows_carry_no_trace_id(self):
        from llm_interpretation_replication_tpu.serve.replay import replay

        eng, _, _ = _tiny_engine(batch_size=4)
        report = replay(eng, ["Is a tweet a publication?",
                              "Is soup a beverage?"])
        assert report["mismatched_rows"] == 0
        assert all("trace_id" not in row for row in report["serve_rows"])


class TestStrictModeTracing:
    def test_traced_strict_sweep_has_zero_blocked_transfers(self):
        """The tentpole's strict contract: tracing (including the opt-in
        sync-at-close mode) performs no unsanctioned device->host
        transfer, so a strict-mode sweep with tracing on stays
        blocked_transfers == 0."""
        from llm_interpretation_replication_tpu.runtime import strict
        from llm_interpretation_replication_tpu.runtime.engine import LegSpec

        eng, _, _ = _tiny_engine()
        pairs = [("Is a tweet a publication?",
                  (" Answer Yes or No.",))] * 3
        obs.enable(sync=True)
        strict.activate(sentry=False)
        try:
            snap = telemetry.counters()
            out = eng.score_prefixed(pairs, legs=[LegSpec("binary")])
            assert all(r["success"] for r in out[0])
            delta = telemetry.counters_since(snap)
            assert delta.get(strict.BLOCKED_COUNTER, 0) == 0
        finally:
            strict.deactivate()


class TestOverheadSmoke:
    def test_traced_tiny_sweep_within_tolerance(self):
        """Overhead contract proxy: a traced warm scoring pass must stay
        close to the untraced one.  The bench acceptance bar is <= 2% on
        real hardware; at tiny-model CPU scales span bookkeeping is a
        visible fraction of the microsecond-scale batches, so the test
        bound is deliberately loose (1.6x + 150 ms) and exists to catch
        an accidentally quadratic or blocking tracer, not to certify the
        2% number."""
        eng, _, _ = _tiny_engine()
        prompts = ["Is a tweet a publication?", "Is soup a beverage?",
                   "The quick brown fox jumps"] * 4
        eng.score_prompts(prompts)                 # compile
        t0 = time.perf_counter()
        eng.score_prompts(prompts)
        untraced = time.perf_counter() - t0
        obs.enable()
        eng.score_prompts(prompts)                 # traced warm-up
        t0 = time.perf_counter()
        eng.score_prompts(prompts)
        traced = time.perf_counter() - t0
        assert traced <= untraced * 1.6 + 0.15, (traced, untraced)


def test_bench_full_study_secondary_keeps_instrumentation():
    """Satellite lineage: the sweep-full companion used to be a child
    re-exec that had to inherit --trace / --profile / --metrics with
    child-specific artifact paths.  ISSUE-12 moved it IN-PROCESS
    (subprocess deleted — verified engine teardown replaced the
    isolation), which makes trace/metrics inheritance automatic (one
    process, one armed tracer/metrics stream); the one artifact that
    still needs a child-specific path is the windowed profiler capture
    dir — pin that, and pin that the old re-exec never comes back
    silently."""
    import os

    bench_src = open(os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench.py")).read()
    assert "import subprocess" not in bench_src
    secondary = bench_src[bench_src.index("def _full_study_secondary"):]
    secondary = secondary[:secondary.index("\ndef ")]
    # profiled parent => the in-process leg captures into its own subdir
    assert 'os.path.join(args.profile, "sweep-full")' in secondary
    # a traced/metered parent stays traced/metered in-process: the leg
    # must NOT disarm or re-arm the obs layer on its own
    assert "obs_mod.enable" not in secondary
    assert "enable_jsonl" not in secondary
