"""Pallas flash-attention kernel vs dense XLA attention (interpret mode on
the CPU test mesh; the same kernel compiles for real on TPU)."""

import numpy as np
import pytest

import jax.numpy as jnp

from llm_interpretation_replication_tpu.ops.attention import (
    _dense_attention,
    attention,
    flash_attention,
    grouped_attention,
)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("dtype", [np.float32])
def test_flash_matches_dense(causal, dtype):
    rng = np.random.default_rng(0)
    B, N, S, D = 2, 3, 256, 64
    q = rng.standard_normal((B, N, S, D)).astype(dtype)
    k = rng.standard_normal((B, N, S, D)).astype(dtype)
    v = rng.standard_normal((B, N, S, D)).astype(dtype)
    lengths = np.array([S, S - 70], np.int32)
    out = flash_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), lengths,
        causal=causal, block_q=128, block_k=128, interpret=True,
    )
    expected = _dense_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), jnp.asarray(lengths), causal
    )
    valid = (np.arange(S)[None, :] < lengths[:, None])[:, None, :, None]
    np.testing.assert_allclose(
        np.asarray(out) * valid, np.asarray(expected) * valid, atol=2e-5, rtol=1e-4
    )


def test_flash_small_seq_block_clamp():
    rng = np.random.default_rng(1)
    B, N, S, D = 1, 2, 64, 32
    q, k, v = (rng.standard_normal((B, N, S, D)).astype(np.float32) for _ in range(3))
    lengths = np.array([50], np.int32)
    out = flash_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), lengths,
                          causal=True, interpret=True)
    expected = _dense_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                                jnp.asarray(lengths), True)
    valid = (np.arange(S)[None, :] < lengths[:, None])[:, None, :, None]
    np.testing.assert_allclose(np.asarray(out) * valid, np.asarray(expected) * valid,
                               atol=2e-5, rtol=1e-4)


def test_indivisible_seq_raises():
    q = jnp.zeros((1, 1, 100, 32))
    with pytest.raises(ValueError):
        flash_attention(q, q, q, np.array([100]), block_q=64, block_k=64)


def test_pick_block():
    from llm_interpretation_replication_tpu.ops.attention import pick_block

    assert pick_block(512, 128) == 128
    assert pick_block(448, 128) == 64    # 448 = 7·64 — the sweep's hot bucket
    assert pick_block(320, 128) == 64
    assert pick_block(192, 128) == 64
    assert pick_block(64, 128) == 64
    assert pick_block(100, 128) is None  # no power-of-two divisor ≥ 8


def test_flash_non_pow2_bucket_matches_dense():
    """Regression: buckets like 448 are not 128-multiples; blocks must shrink
    to a divisor instead of raising (runtime/batching.DEFAULT_BUCKETS)."""
    rng = np.random.default_rng(2)
    B, N, S, D = 2, 2, 448, 32
    q, k, v = (rng.standard_normal((B, N, S, D)).astype(np.float32) for _ in range(3))
    lengths = np.array([430, 448], np.int32)
    out = flash_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), lengths,
                          causal=True, interpret=True)
    expected = _dense_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                                jnp.asarray(lengths), True)
    valid = (np.arange(S)[None, :] < lengths[:, None])[:, None, :, None]
    np.testing.assert_allclose(np.asarray(out) * valid, np.asarray(expected) * valid,
                               atol=2e-5, rtol=1e-4)


def test_decoder_flash_config_matches_xla():
    """attention_impl='flash' must not change decoder outputs (dense dispatch
    on CPU; the Pallas kernel itself is parity-tested above)."""
    import dataclasses

    import torch
    from transformers import GPTNeoXConfig, GPTNeoXForCausalLM

    from llm_interpretation_replication_tpu.models import config as mcfg
    from llm_interpretation_replication_tpu.models import convert as mconvert
    from llm_interpretation_replication_tpu.models import decoder

    hf_config = GPTNeoXConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64, rotary_pct=0.25,
        max_position_embeddings=64,
    )
    torch.manual_seed(9)
    model = GPTNeoXForCausalLM(hf_config).eval()
    fam, cfg = mcfg.from_hf_config(hf_config)
    params = mconvert.convert(
        fam, mconvert.getter_from_torch_state_dict(model.state_dict()), cfg,
        dtype=jnp.float32,
    )
    rng = np.random.default_rng(7)
    ids = rng.integers(3, 128, size=(2, 12)).astype(np.int32)
    mask = np.ones_like(ids)
    mask[1, 9:] = 0
    base = decoder.forward(params, cfg, jnp.asarray(ids), jnp.asarray(mask))
    flash_cfg = dataclasses.replace(cfg, attention_impl="flash")
    flashed = decoder.forward(params, flash_cfg, jnp.asarray(ids), jnp.asarray(mask))
    valid = mask.astype(bool)
    np.testing.assert_allclose(
        np.asarray(flashed)[valid], np.asarray(base)[valid], atol=2e-4, rtol=1e-4
    )


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("n_heads,n_kv", [(6, 1), (8, 4), (4, 4)])
def test_grouped_matches_dense(causal, n_heads, n_kv):
    """Grouped single-pass kernel (heads flattened into the row axis, K/V
    unrepeated) vs dense attention with repeated K/V.  block_rows=32 with
    S=48 forces row blocks that straddle head boundaries AND pad the tail."""
    rng = np.random.default_rng(4)
    B, S, D = 2, 48, 16
    q = rng.standard_normal((B, n_heads, S, D)).astype(np.float32)
    k = rng.standard_normal((B, n_kv, S, D)).astype(np.float32)
    v = rng.standard_normal((B, n_kv, S, D)).astype(np.float32)
    lengths = np.array([S, S - 17], np.int32)
    out = grouped_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), lengths,
        causal=causal, block_rows=32, interpret=True,
    )
    reps = n_heads // n_kv
    expected = _dense_attention(
        jnp.asarray(q),
        jnp.asarray(np.repeat(k, reps, axis=1)),
        jnp.asarray(np.repeat(v, reps, axis=1)),
        jnp.asarray(lengths), causal,
    )
    valid = (np.arange(S)[None, :] < lengths[:, None])[:, None, :, None]
    np.testing.assert_allclose(
        np.asarray(out) * valid, np.asarray(expected) * valid, atol=2e-5, rtol=1e-4
    )


def test_fully_masked_rows_return_zero():
    """Length-0 padded batch rows must come back as zeros on EVERY backend —
    NEG_INF is finite, so without an explicit guard a fully-masked row
    softmaxes to uniform 1/S and returns the mean of V (matching the
    ring/Ulysses zero-row semantics)."""
    rng = np.random.default_rng(6)
    B, N, S, D = 2, 4, 64, 16
    q = rng.standard_normal((B, N, S, D)).astype(np.float32)
    k = rng.standard_normal((B, N, S, D)).astype(np.float32)
    v = rng.standard_normal((B, N, S, D)).astype(np.float32)
    lengths = np.array([S, 0], np.int32)                     # row 1 fully padded

    dense = _dense_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                             jnp.asarray(lengths), True)
    np.testing.assert_array_equal(np.asarray(dense)[1], 0.0)
    assert np.abs(np.asarray(dense)[0]).sum() > 0            # live row untouched

    flash = flash_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                            lengths, causal=True, interpret=True)
    np.testing.assert_array_equal(np.asarray(flash)[1], 0.0)

    grouped = grouped_attention(jnp.asarray(q), jnp.asarray(k[:, :1]),
                                jnp.asarray(v[:, :1]), lengths,
                                causal=True, block_rows=32, interpret=True)
    np.testing.assert_array_equal(np.asarray(grouped)[1], 0.0)


def test_attention_dispatch_accepts_grouped_kv():
    """The dispatcher takes unrepeated [B, G, S, D] K/V on every backend; on
    the dense path it must repeat to full heads itself."""
    rng = np.random.default_rng(5)
    B, N, G, S, D = 2, 8, 2, 32, 8
    q = jnp.asarray(rng.standard_normal((B, N, S, D)).astype(np.float32))
    k = rng.standard_normal((B, G, S, D)).astype(np.float32)
    v = rng.standard_normal((B, G, S, D)).astype(np.float32)
    lengths = jnp.asarray([S, S - 5], jnp.int32)
    got = attention(q, jnp.asarray(k), jnp.asarray(v), lengths, causal=True)
    expected = _dense_attention(
        q, jnp.asarray(np.repeat(k, N // G, axis=1)),
        jnp.asarray(np.repeat(v, N // G, axis=1)), lengths, True,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("n_heads,n_kv", [(6, 1), (8, 4), (4, 4)])
def test_causal_grouped_matches_dense(causal, n_heads, n_kv):
    """Causal block-skipping kernel (layout-native [B,S,N,D], dynamic k-block
    trip counts, mask only on boundary blocks) vs dense attention.  block_k=16
    with S=48 exercises clean blocks, boundary blocks, and skipped blocks;
    ragged lengths exercise the length bound inside a clean region."""
    from llm_interpretation_replication_tpu.ops.attention import (
        causal_grouped_attention,
    )

    rng = np.random.default_rng(8)
    B, S, D = 2, 48, 16
    q = rng.standard_normal((B, S, n_heads, D)).astype(np.float32)
    k = rng.standard_normal((B, S, n_kv, D)).astype(np.float32)
    v = rng.standard_normal((B, S, n_kv, D)).astype(np.float32)
    lengths = np.array([S, S - 17], np.int32)
    out = causal_grouped_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), lengths,
        causal=causal, block_k=16, interpret=True,
    )
    reps = n_heads // n_kv
    qh = np.swapaxes(q, 1, 2)
    expected = _dense_attention(
        jnp.asarray(qh),
        jnp.asarray(np.repeat(np.swapaxes(k, 1, 2), reps, axis=1)),
        jnp.asarray(np.repeat(np.swapaxes(v, 1, 2), reps, axis=1)),
        jnp.asarray(lengths), causal,
    )
    expected = np.swapaxes(np.asarray(expected), 1, 2)
    valid = (np.arange(S)[None, :] < lengths[:, None])[:, :, None, None]
    np.testing.assert_allclose(
        np.asarray(out) * valid, expected * valid, atol=2e-5, rtol=1e-4
    )


def test_causal_grouped_padded_seq_and_zero_rows():
    """S not a block_k multiple pads K/V inside the wrapper (pad cols must be
    masked as boundary blocks); length-0 rows come back all-zero."""
    from llm_interpretation_replication_tpu.ops.attention import (
        causal_grouped_attention,
    )

    rng = np.random.default_rng(9)
    B, S, N, D = 2, 40, 4, 16                            # 40 % 16 != 0
    q = rng.standard_normal((B, S, N, D)).astype(np.float32)
    k = rng.standard_normal((B, S, 1, D)).astype(np.float32)
    v = rng.standard_normal((B, S, 1, D)).astype(np.float32)
    lengths = np.array([S - 3, 0], np.int32)
    out = causal_grouped_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), lengths,
        causal=True, block_k=16, interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(out)[1], 0.0)
    expected = _dense_attention(
        jnp.asarray(np.swapaxes(q, 1, 2)),
        jnp.asarray(np.repeat(np.swapaxes(k, 1, 2), N, axis=1)),
        jnp.asarray(np.repeat(np.swapaxes(v, 1, 2), N, axis=1)),
        jnp.asarray(lengths), True,
    )
    expected = np.swapaxes(np.asarray(expected), 1, 2)
    valid = (np.arange(S)[None, :] < lengths[:, None])[:, :, None, None]
    np.testing.assert_allclose(
        np.asarray(out) * valid, expected * valid, atol=2e-5, rtol=1e-4
    )


def test_pick_block_pos():
    from llm_interpretation_replication_tpu.ops.attention import pick_block_pos

    assert pick_block_pos(432, 71) == 8        # Falcon MQA: 568 rows
    # nq >= 4 preferred so the causal skip stays alive (one giant block would
    # make every k-tile a boundary tile)
    assert pick_block_pos(432, 1) == 72        # MHA: 6 query blocks
    assert pick_block_pos(448, 4) == 112       # 448 rows, 4 query blocks
    assert pick_block_pos(48, 3) == 8          # 24 rows, 6 blocks
    assert pick_block_pos(8, 3) == 8           # fallback: no divisor leaves 4
    assert pick_block_pos(7, 3) is None        # no sublane-aligned block


def test_attention_bsnd_dispatch_matches_dense():
    """The layout-native dispatcher must agree with dense on every forced
    backend (causal kernel in interpret mode; dense via transpose)."""
    from llm_interpretation_replication_tpu.ops.attention import attention_bsnd

    rng = np.random.default_rng(10)
    B, S, N, G, D = 2, 64, 8, 2, 16
    q = jnp.asarray(rng.standard_normal((B, S, N, D)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((B, S, G, D)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((B, S, G, D)).astype(np.float32))
    lengths = np.array([S, S - 11], np.int32)
    via_causal = attention_bsnd(q, k, v, lengths, causal=True,
                                force="causal", interpret=True)
    via_dense = attention_bsnd(q, k, v, lengths, causal=True, force="dense")
    valid = (np.arange(S)[None, :] < lengths[:, None])[:, :, None, None]
    np.testing.assert_allclose(
        np.asarray(via_causal) * valid, np.asarray(via_dense) * valid,
        atol=2e-5, rtol=1e-4,
    )


def test_decoder_flash_mqa_matches_xla():
    """attention_impl='flash' on an MQA decoder (num_kv_heads=1) routes
    unrepeated K/V through the dispatcher — outputs must match the XLA path."""
    import dataclasses

    from llm_interpretation_replication_tpu.models.config import DecoderConfig
    from llm_interpretation_replication_tpu.models import decoder

    from helpers import random_decoder_params

    cfg = DecoderConfig(
        vocab_size=96, hidden_size=32, num_layers=2, num_heads=4,
        num_kv_heads=1, intermediate_size=64, position_embedding="rotary",
        max_position_embeddings=64,
    )
    params = random_decoder_params(cfg, seed=3)
    rng = np.random.default_rng(11)
    ids = rng.integers(3, 96, size=(2, 14)).astype(np.int32)
    mask = np.ones_like(ids)
    mask[0, 10:] = 0
    base = decoder.forward(params, cfg, jnp.asarray(ids), jnp.asarray(mask))
    flash_cfg = dataclasses.replace(cfg, attention_impl="flash")
    flashed = decoder.forward(params, flash_cfg, jnp.asarray(ids), jnp.asarray(mask))
    valid = mask.astype(bool)
    np.testing.assert_allclose(
        np.asarray(flashed)[valid], np.asarray(base)[valid], atol=2e-4, rtol=1e-4
    )


def test_flash_config_rejects_alibi():
    from llm_interpretation_replication_tpu.models.config import DecoderConfig

    with pytest.raises(ValueError):
        DecoderConfig(
            vocab_size=10, hidden_size=8, num_layers=1, num_heads=2,
            position_embedding="alibi", attention_impl="flash",
        )


# ---------------------------------------------------------------------------
# W8A8 int8 quantization (ops/quant.py) — the TPU answer to the reference's
# bitsandbytes load_in_8bit path (run_base_vs_instruct_100q.py:414-451).
# ---------------------------------------------------------------------------

class TestQuant:
    def test_int8_matmul_close_to_fp(self):
        from llm_interpretation_replication_tpu.ops import quant

        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((4, 8, 64)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((64, 32)) * 0.05, jnp.float32)
        q, s = quant.quantize_weight(w)
        assert q.dtype == jnp.int8 and s.shape == (32,)
        ref = np.asarray(x @ w)
        got = np.asarray(quant.int8_matmul(x, q, s))
        rel = np.abs(got - ref).max() / np.abs(ref).max()
        assert rel < 0.02, rel

    def test_quantize_weight_stacked_layers(self):
        from llm_interpretation_replication_tpu.ops import quant

        rng = np.random.default_rng(1)
        w = jnp.asarray(rng.standard_normal((3, 16, 8)), jnp.float32)  # [L, K, N]
        q, s = quant.quantize_weight(w)
        assert q.shape == (3, 16, 8) and s.shape == (3, 8)
        deq = np.asarray(q, np.float32) * np.asarray(s)[:, None, :]
        np.testing.assert_allclose(deq, np.asarray(w), atol=np.abs(w).max() / 127)

    def test_linear_dispatch(self):
        from llm_interpretation_replication_tpu.ops import quant

        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.standard_normal((2, 16)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((16, 4)) * 0.1, jnp.float32)
        plain = quant.linear({"w": w}, "w", x)
        np.testing.assert_allclose(np.asarray(plain), np.asarray(x @ w), rtol=1e-6)
        qw, s = quant.quantize_weight(w)
        quantized = quant.linear({"w": qw, "w_qscale": s}, "w", x)
        assert np.abs(np.asarray(quantized) - np.asarray(x @ w)).max() < 0.05

    def test_quantized_decoder_matches_fp32(self):
        """End-to-end: quantized tiny decoder logits track fp32 closely."""
        from llm_interpretation_replication_tpu.models.config import DecoderConfig
        from llm_interpretation_replication_tpu.models.decoder import forward_last_logits
        from llm_interpretation_replication_tpu.ops import quant

        from helpers import random_decoder_params

        cfg = DecoderConfig(
            vocab_size=128, hidden_size=32, num_layers=2, num_heads=4,
            intermediate_size=64, position_embedding="rotary",
            tie_word_embeddings=True, max_position_embeddings=64,
        )
        rng = np.random.default_rng(3)
        params = random_decoder_params(cfg, seed=3)
        ids = jnp.asarray(rng.integers(1, cfg.vocab_size, (2, 24)), jnp.int32)
        mask = jnp.ones((2, 24), jnp.int32)
        ref = np.asarray(forward_last_logits(params, cfg, ids, mask))
        qp = quant.quantize_decoder_params(params)
        got = np.asarray(forward_last_logits(qp, cfg, ids, mask))
        corr = np.corrcoef(ref.ravel(), got.ravel())[0, 1]
        assert corr > 0.999, corr

    def test_quantized_greedy_decode_matches_fp32(self):
        """The decode path (_attn_decode / _block_decode two-block attention)
        must also apply the dequant scales — greedy tokens should match fp32
        on a tiny model."""
        from llm_interpretation_replication_tpu.models.config import DecoderConfig
        from llm_interpretation_replication_tpu.models.decoder import greedy_decode
        from llm_interpretation_replication_tpu.ops import quant

        from helpers import random_decoder_params

        cfg = DecoderConfig(
            vocab_size=128, hidden_size=32, num_layers=2, num_heads=4,
            intermediate_size=64, position_embedding="rotary",
            tie_word_embeddings=True, max_position_embeddings=64,
        )
        rng = np.random.default_rng(5)
        params = random_decoder_params(cfg, seed=5)
        ids = jnp.asarray(rng.integers(1, cfg.vocab_size, (2, 12)), jnp.int32)
        mask = jnp.ones((2, 12), jnp.int32)
        toks_fp, scores_fp = greedy_decode(params, cfg, ids, mask, num_steps=5)
        qp = quant.quantize_decoder_params(params)
        toks_q, scores_q = greedy_decode(qp, cfg, ids, mask, num_steps=5)
        np.testing.assert_array_equal(np.asarray(toks_q), np.asarray(toks_fp))
        corr = np.corrcoef(
            np.asarray(scores_fp, np.float64).ravel(),
            np.asarray(scores_q, np.float64).ravel(),
        )[0, 1]
        assert corr > 0.999, corr

    def test_quantize_decoder_params_gated_mlp(self):
        from llm_interpretation_replication_tpu.ops import quant

        rng = np.random.default_rng(4)
        layers = {
            "attn": {"wq": jnp.asarray(rng.standard_normal((2, 8, 8)), jnp.float32)},
            "mlp": {
                "wg": jnp.asarray(rng.standard_normal((2, 8, 16)), jnp.float32),
                "wi": jnp.asarray(rng.standard_normal((2, 8, 16)), jnp.float32),
                "wo": jnp.asarray(rng.standard_normal((2, 16, 8)), jnp.float32),
                "bo": jnp.zeros((2, 8), jnp.float32),
            },
        }
        out = quant.quantize_decoder_params({"layers": layers})
        for grp, key in (("attn", "wq"), ("mlp", "wg"), ("mlp", "wi"), ("mlp", "wo")):
            assert out["layers"][grp][key].dtype == jnp.int8
            assert key + "_qscale" in out["layers"][grp]
        assert out["layers"]["mlp"]["bo"].dtype == jnp.float32  # biases untouched


# ---------------------------------------------------------------------------
# Mixture-of-Experts with expert parallelism (ops/moe.py) — beyond-reference
# capability (SURVEY.md §2.7: EP absent upstream).
# ---------------------------------------------------------------------------

class TestMoE:
    H, F, E, K = 16, 32, 8, 2

    def _params_and_tokens(self, n_tokens=32, seed=0):
        import jax

        from llm_interpretation_replication_tpu.ops import moe

        params = moe.init_moe_params(jax.random.PRNGKey(0), self.H, self.F, self.E)
        rng = np.random.default_rng(seed)
        x = jnp.asarray(rng.standard_normal((n_tokens, self.H)), jnp.float32)
        return params, x

    def test_dense_matches_per_token_loop(self):
        import jax

        from llm_interpretation_replication_tpu.ops import moe

        params, x = self._params_and_tokens()
        out, aux = moe.moe_mlp_dense(params, x, top_k=self.K)
        gates, idx, _ = moe.route(params, x, self.K)
        expect = np.zeros(x.shape, np.float32)
        for t in range(x.shape[0]):
            for k in range(self.K):
                e = int(idx[t, k])
                wi = np.asarray(params["wi"][e])
                wo = np.asarray(params["wo"][e])
                y = np.asarray(jax.nn.gelu(np.asarray(x[t]) @ wi)) @ wo
                expect[t] += float(gates[t, k]) * y
        np.testing.assert_allclose(np.asarray(out), expect, atol=1e-5)
        assert np.isfinite(float(aux))

    def test_route_renormalizes_topk(self):
        from llm_interpretation_replication_tpu.ops import moe

        params, x = self._params_and_tokens()
        gates, idx, probs = moe.route(params, x, self.K)
        np.testing.assert_allclose(np.asarray(gates).sum(-1), 1.0, rtol=1e-5)
        assert np.asarray(probs).shape == (x.shape[0], self.E)
        # distinct experts per token
        assert (np.asarray(idx)[:, 0] != np.asarray(idx)[:, 1]).all()

    def test_sharded_matches_dense(self, eight_cpu_devices):
        from llm_interpretation_replication_tpu.ops import moe
        from llm_interpretation_replication_tpu.parallel import make_mesh

        params, x = self._params_and_tokens()
        out_d, aux_d = moe.moe_mlp_dense(params, x, top_k=self.K)
        mesh = make_mesh(data=4, model=2)
        out_s, aux_s = moe.moe_mlp_sharded(
            params, x, mesh, axis_name="data", top_k=self.K, capacity_factor=8.0
        )
        np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_d), atol=1e-5)
        np.testing.assert_allclose(float(aux_s), float(aux_d), rtol=1e-6)

    def test_capacity_drops_overflow(self, eight_cpu_devices):
        """capacity_factor→tiny forces token dropping: output stays finite and
        differs from the uncapped result (documents GShard overflow)."""
        from llm_interpretation_replication_tpu.ops import moe
        from llm_interpretation_replication_tpu.parallel import make_mesh

        params, x = self._params_and_tokens()
        mesh = make_mesh(data=4, model=2)
        out_tiny, _ = moe.moe_mlp_sharded(
            params, x, mesh, axis_name="data", top_k=self.K, capacity_factor=0.25
        )
        out_full, _ = moe.moe_mlp_sharded(
            params, x, mesh, axis_name="data", top_k=self.K, capacity_factor=8.0
        )
        assert np.isfinite(np.asarray(out_tiny)).all()
        assert np.abs(np.asarray(out_tiny) - np.asarray(out_full)).max() > 1e-6

    def test_grad_through_sharded(self, eight_cpu_devices):
        import jax

        from llm_interpretation_replication_tpu.ops import moe
        from llm_interpretation_replication_tpu.parallel import make_mesh

        params, x = self._params_and_tokens()
        mesh = make_mesh(data=4, model=2)

        def loss(p):
            y, aux = moe.moe_mlp_sharded(
                p, x, mesh, axis_name="data", top_k=self.K, capacity_factor=8.0
            )
            return (y ** 2).sum() + 0.01 * aux

        g = jax.grad(loss)(params)
        for name, v in g.items():
            arr = np.asarray(v)
            assert np.isfinite(arr).all() and np.abs(arr).max() > 0, name

    def test_indivisible_experts_raise(self, eight_cpu_devices):
        from llm_interpretation_replication_tpu.ops import moe
        from llm_interpretation_replication_tpu.parallel import make_mesh

        import jax

        params = moe.init_moe_params(jax.random.PRNGKey(0), self.H, self.F, 6)
        mesh = make_mesh(data=4, model=2)
        x = jnp.zeros((8, self.H), jnp.float32)
        with pytest.raises(ValueError, match="divisible"):
            moe.moe_mlp_sharded(params, x, mesh, axis_name="data")


def test_attention_impl_auto_resolution():
    """'auto' keeps dense at sweep lengths, flips to the Pallas kernel past
    auto_flash_seq, and never flips for ALiBi / sliding-window configs."""
    import dataclasses

    from llm_interpretation_replication_tpu.models.config import DecoderConfig

    cfg = DecoderConfig(
        vocab_size=96, hidden_size=32, num_layers=1, num_heads=4,
        intermediate_size=64, position_embedding="rotary",
        max_position_embeddings=8192, attention_impl="auto",
    )
    assert not cfg.use_flash_attention(432)      # sweep bucket: dense wins
    assert cfg.use_flash_attention(2048)         # dense S^2 scores would OOM
    alibi = dataclasses.replace(cfg, position_embedding="alibi")
    assert not alibi.use_flash_attention(2048)   # kernel can't do ALiBi
    sw = dataclasses.replace(cfg, sliding_window=256)
    assert not sw.use_flash_attention(2048)
    flash = dataclasses.replace(cfg, attention_impl="flash")
    assert flash.use_flash_attention(16)         # explicit flash: always
    with pytest.raises(ValueError, match="attention_impl"):
        DecoderConfig(vocab_size=8, hidden_size=8, num_layers=1, num_heads=1,
                      attention_impl="bogus")


def test_decoder_auto_impl_matches_xla_past_threshold():
    """attention_impl='auto' past the threshold routes through the dispatcher
    (dense fallback on CPU) and must not change decoder outputs."""
    import dataclasses

    from helpers import random_decoder_params

    from llm_interpretation_replication_tpu.models import decoder
    from llm_interpretation_replication_tpu.models.config import DecoderConfig

    cfg = DecoderConfig(
        vocab_size=96, hidden_size=32, num_layers=2, num_heads=4,
        num_kv_heads=1, intermediate_size=64, position_embedding="rotary",
        max_position_embeddings=64, attention_impl="auto", auto_flash_seq=8,
    )
    params = random_decoder_params(cfg, seed=6)
    rng = np.random.default_rng(12)
    ids = rng.integers(3, 96, size=(2, 16)).astype(np.int32)  # 16 > threshold
    mask = np.ones_like(ids)
    mask[1, 12:] = 0
    auto = decoder.forward(params, cfg, jnp.asarray(ids), jnp.asarray(mask))
    base_cfg = dataclasses.replace(cfg, attention_impl="xla")
    base = decoder.forward(params, base_cfg, jnp.asarray(ids), jnp.asarray(mask))
    valid = mask.astype(bool)
    np.testing.assert_allclose(
        np.asarray(auto)[valid], np.asarray(base)[valid], atol=2e-4, rtol=1e-4
    )


def test_greedy_decode_flash_matches_xla():
    """attention_impl='flash' must also cover greedy_decode's cached prompt
    forward (dense dispatch on CPU validates the plumbing): tokens identical,
    scores equal within dispatch tolerance."""
    import dataclasses

    from helpers import random_decoder_params

    from llm_interpretation_replication_tpu.models import decoder
    from llm_interpretation_replication_tpu.models.config import DecoderConfig

    cfg = DecoderConfig(
        vocab_size=96, hidden_size=32, num_layers=2, num_heads=4,
        num_kv_heads=1, intermediate_size=64, position_embedding="rotary",
        max_position_embeddings=64,
    )
    params = random_decoder_params(cfg, seed=8)
    rng = np.random.default_rng(13)
    ids = rng.integers(3, 96, size=(2, 12)).astype(np.int32)
    mask = np.ones_like(ids)
    mask[1, 9:] = 0
    tok_b, sc_b = decoder.greedy_decode(params, cfg, jnp.asarray(ids),
                                        jnp.asarray(mask), num_steps=4)
    flash_cfg = dataclasses.replace(cfg, attention_impl="flash")
    tok_f, sc_f = decoder.greedy_decode(params, flash_cfg, jnp.asarray(ids),
                                        jnp.asarray(mask), num_steps=4)
    np.testing.assert_array_equal(np.asarray(tok_f), np.asarray(tok_b))
    np.testing.assert_allclose(np.asarray(sc_f), np.asarray(sc_b),
                               atol=2e-4, rtol=1e-4)
