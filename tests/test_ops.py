"""Pallas flash-attention kernel vs dense XLA attention (interpret mode on
the CPU test mesh; the same kernel compiles for real on TPU)."""

import numpy as np
import pytest

import jax.numpy as jnp

from llm_interpretation_replication_tpu.ops.attention import (
    _dense_attention,
    flash_attention,
)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("dtype", [np.float32])
def test_flash_matches_dense(causal, dtype):
    rng = np.random.default_rng(0)
    B, N, S, D = 2, 3, 256, 64
    q = rng.standard_normal((B, N, S, D)).astype(dtype)
    k = rng.standard_normal((B, N, S, D)).astype(dtype)
    v = rng.standard_normal((B, N, S, D)).astype(dtype)
    lengths = np.array([S, S - 70], np.int32)
    out = flash_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), lengths,
        causal=causal, block_q=128, block_k=128, interpret=True,
    )
    expected = _dense_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), jnp.asarray(lengths), causal
    )
    valid = (np.arange(S)[None, :] < lengths[:, None])[:, None, :, None]
    np.testing.assert_allclose(
        np.asarray(out) * valid, np.asarray(expected) * valid, atol=2e-5, rtol=1e-4
    )


def test_flash_small_seq_block_clamp():
    rng = np.random.default_rng(1)
    B, N, S, D = 1, 2, 64, 32
    q, k, v = (rng.standard_normal((B, N, S, D)).astype(np.float32) for _ in range(3))
    lengths = np.array([50], np.int32)
    out = flash_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), lengths,
                          causal=True, interpret=True)
    expected = _dense_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                                jnp.asarray(lengths), True)
    valid = (np.arange(S)[None, :] < lengths[:, None])[:, None, :, None]
    np.testing.assert_allclose(np.asarray(out) * valid, np.asarray(expected) * valid,
                               atol=2e-5, rtol=1e-4)


def test_indivisible_seq_raises():
    q = jnp.zeros((1, 1, 100, 32))
    with pytest.raises(ValueError):
        flash_attention(q, q, q, np.array([100]), block_q=64, block_k=64)


def test_decoder_flash_config_matches_xla():
    """attention_impl='flash' must not change decoder outputs (dense dispatch
    on CPU; the Pallas kernel itself is parity-tested above)."""
    import dataclasses

    import torch
    from transformers import GPTNeoXConfig, GPTNeoXForCausalLM

    from llm_interpretation_replication_tpu.models import config as mcfg
    from llm_interpretation_replication_tpu.models import convert as mconvert
    from llm_interpretation_replication_tpu.models import decoder

    hf_config = GPTNeoXConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64, rotary_pct=0.25,
        max_position_embeddings=64,
    )
    torch.manual_seed(9)
    model = GPTNeoXForCausalLM(hf_config).eval()
    fam, cfg = mcfg.from_hf_config(hf_config)
    params = mconvert.convert(
        fam, mconvert.getter_from_torch_state_dict(model.state_dict()), cfg,
        dtype=jnp.float32,
    )
    rng = np.random.default_rng(7)
    ids = rng.integers(3, 128, size=(2, 12)).astype(np.int32)
    mask = np.ones_like(ids)
    mask[1, 9:] = 0
    base = decoder.forward(params, cfg, jnp.asarray(ids), jnp.asarray(mask))
    flash_cfg = dataclasses.replace(cfg, attention_impl="flash")
    flashed = decoder.forward(params, flash_cfg, jnp.asarray(ids), jnp.asarray(mask))
    valid = mask.astype(bool)
    np.testing.assert_allclose(
        np.asarray(flashed)[valid], np.asarray(base)[valid], atol=2e-4, rtol=1e-4
    )


def test_flash_config_rejects_alibi():
    from llm_interpretation_replication_tpu.models.config import DecoderConfig

    with pytest.raises(ValueError):
        DecoderConfig(
            vocab_size=10, hidden_size=8, num_layers=1, num_heads=2,
            position_embedding="alibi", attention_impl="flash",
        )
