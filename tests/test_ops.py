"""Pallas flash-attention kernel vs dense XLA attention (interpret mode on
the CPU test mesh; the same kernel compiles for real on TPU)."""

import numpy as np
import pytest

import jax.numpy as jnp

from llm_interpretation_replication_tpu.ops.attention import (
    _dense_attention,
    flash_attention,
)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("dtype", [np.float32])
def test_flash_matches_dense(causal, dtype):
    rng = np.random.default_rng(0)
    B, N, S, D = 2, 3, 256, 64
    q = rng.standard_normal((B, N, S, D)).astype(dtype)
    k = rng.standard_normal((B, N, S, D)).astype(dtype)
    v = rng.standard_normal((B, N, S, D)).astype(dtype)
    lengths = np.array([S, S - 70], np.int32)
    out = flash_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), lengths,
        causal=causal, block_q=128, block_k=128, interpret=True,
    )
    expected = _dense_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), jnp.asarray(lengths), causal
    )
    valid = (np.arange(S)[None, :] < lengths[:, None])[:, None, :, None]
    np.testing.assert_allclose(
        np.asarray(out) * valid, np.asarray(expected) * valid, atol=2e-5, rtol=1e-4
    )


def test_flash_small_seq_block_clamp():
    rng = np.random.default_rng(1)
    B, N, S, D = 1, 2, 64, 32
    q, k, v = (rng.standard_normal((B, N, S, D)).astype(np.float32) for _ in range(3))
    lengths = np.array([50], np.int32)
    out = flash_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), lengths,
                          causal=True, interpret=True)
    expected = _dense_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                                jnp.asarray(lengths), True)
    valid = (np.arange(S)[None, :] < lengths[:, None])[:, None, :, None]
    np.testing.assert_allclose(np.asarray(out) * valid, np.asarray(expected) * valid,
                               atol=2e-5, rtol=1e-4)


def test_indivisible_seq_raises():
    q = jnp.zeros((1, 1, 100, 32))
    with pytest.raises(ValueError):
        flash_attention(q, q, q, np.array([100]), block_q=64, block_k=64)
