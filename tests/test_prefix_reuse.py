"""Prefix-KV reuse layer (runtime/engine.score_prefixed) + the machinery
around it: fused-vs-unfused equivalence over identical token streams, the
prefix cache pool's lifetime accounting under OOM re-bucketing, the
generation-plan cache keying, the host prefetcher, suffix bucketing, and
the env-gated persistent compilation cache."""

import os

import numpy as np
import pytest

from helpers import build_test_tokenizer
from test_runtime import _tiny_engine

from llm_interpretation_replication_tpu.runtime import batching
from llm_interpretation_replication_tpu.runtime.engine import LegSpec
from llm_interpretation_replication_tpu.utils import telemetry

BIN_SUFFIX = " Answer only 'Yes' or 'No'."
CONF_SUFFIX = " How confident are you, 0-100?"

#: fields the fused path must reproduce EXACTLY: position-0 logits come
#: out of the suffix-extension prefill bit-identical to the full-prompt
#: prefill (masked pad slots contribute exact zeros to the joint softmax)
EXACT_FIELDS = ("first_token_yes_prob", "first_token_no_prob",
                "first_token_relative_prob", "completion", "success",
                "scan_found")
#: fields read from the scored look-ahead DECODE, whose cache is laid out
#: prefix-bucket + suffix-bucket instead of one full-length bucket — the
#: same masked key set reduces in a different slot order, so these agree
#: to reduction-order noise (last-ulp), not bit-for-bit
SCAN_FIELDS = ("yes_prob", "no_prob", "relative_prob", "odds_ratio")


def _pairs(prefixes, confidence=True):
    sufs = (BIN_SUFFIX, CONF_SUFFIX) if confidence else (BIN_SUFFIX,)
    return [(p, sufs) for p in prefixes]


def _token_streams(tok, pairs):
    """The unfused comparison prompts: the SAME token ids the fused path
    consumes, concatenated per leg."""
    pe, se = batching.encode_prefix_pairs(tok, pairs)
    return [[p + s for p, s in zip(pe, se[li])] for li in range(len(se))]


class TestFusedEquivalence:
    def test_two_leg_fused_matches_unfused_rows(self):
        """The acceptance contract: fused prefix+suffix scoring returns the
        same yes/no logprob rows and confidence rows as the unfused
        two-leg path over identical token streams — position-0 /
        completion / first-token fields bit-identical, scored-decode
        fields to reduction-order noise."""
        eng, _, tok = _tiny_engine(batch_size=4)
        prefixes = [f"Is thing {i} a stuff?" for i in range(6)]
        pairs = _pairs(prefixes)
        legs = [LegSpec("binary"),
                LegSpec("confidence", with_confidence=True,
                        max_new_tokens=10)]
        fused = eng.score_prefixed(pairs, targets=("Yes", "No"), legs=legs)
        bin_ids, conf_ids = _token_streams(tok, pairs)
        unfused = [
            eng.score_prompts(bin_ids, targets=("Yes", "No")),
            eng.score_prompts(conf_ids, targets=("Yes", "No"),
                              with_confidence=True, max_new_tokens=10),
        ]
        assert [len(r) for r in fused] == [6, 6]
        for leg_f, leg_u in zip(fused, unfused):
            for a, b in zip(leg_f, leg_u):
                for f in EXACT_FIELDS:
                    assert a[f] == b[f], f
                for f in SCAN_FIELDS:
                    np.testing.assert_allclose(a[f], b[f], rtol=2e-5,
                                               atol=1e-9, err_msg=f)
        for a, b in zip(fused[1], unfused[1]):
            np.testing.assert_allclose(a["weighted_confidence"],
                                       b["weighted_confidence"],
                                       rtol=1e-4, atol=1e-6)

    def test_score_prompts_accepts_pairs(self):
        """A (prefix, suffix) 2-tuple routes score_prompts through the
        fused single-leg path; rows match scoring the concatenated token
        stream."""
        eng, _, tok = _tiny_engine(batch_size=4)
        prefixes = [f"prompt {i} about soup" for i in range(3)]
        rows_pair = eng.score_prompts([(p, BIN_SUFFIX) for p in prefixes])
        (bin_ids,) = _token_streams(tok, _pairs(prefixes, confidence=False))
        rows_flat = eng.score_prompts(bin_ids)
        for a, b in zip(rows_pair, rows_flat):
            for f in EXACT_FIELDS:
                assert a[f] == b[f], f
        # single leg: nothing to reuse, so misses only
        assert eng.last_prefix_pool.hits == 0
        assert eng.last_prefix_pool.misses == 3

    def test_per_row_targets_and_counters(self):
        """Mixed per-row target pairs flow through the fused path, and the
        prefix-hit counter records one hit per real row per extra leg."""
        eng, _, _ = _tiny_engine(batch_size=4)
        prefixes = [f"Is item {i} a thing?" for i in range(5)]
        targets = [("Yes", "No") if i % 2 else ("No", "Yes")
                   for i in range(5)]
        telemetry.clear_counters()
        fused = eng.score_prefixed(_pairs(prefixes), targets=targets,
                                   legs=[LegSpec(), LegSpec()])
        assert len(fused[0]) == len(fused[1]) == 5
        pool = eng.last_prefix_pool
        assert pool.consistent
        assert pool.misses == 5 and pool.hits == 5
        assert telemetry.counter("prefix_hit") == 5
        assert telemetry.counter("prefix_miss") == 5
        # swapped targets really swap the probabilities
        flat = eng.score_prompts([(p, BIN_SUFFIX) for p in prefixes],
                                 targets=targets)
        for a, b in zip(fused[0], flat):
            assert a["first_token_yes_prob"] == b["first_token_yes_prob"]

    def test_empty_and_mismatched_legs(self):
        eng, _, _ = _tiny_engine(batch_size=2)
        assert eng.score_prefixed([], legs=[LegSpec(), LegSpec()]) == [[], []]
        with pytest.raises(ValueError, match="legs"):
            eng.score_prefixed([("p", (BIN_SUFFIX,))],
                               legs=[LegSpec(), LegSpec()])


class TestGenerationPlanCache:
    def test_cap_keys_separate_plans(self):
        """Satellite: the confidence leg's max_new_tokens cap is part of
        the plan cache key — the binary (50) and confidence (10) legs hold
        two live plans side by side instead of evicting each other."""
        eng, _, _ = _tiny_engine(batch_size=2)
        eng._plan_cache.clear()
        p_bin = eng._gen_plan()          # engine default cap (50)
        p_conf = eng._gen_plan(10)       # confidence cap
        assert p_bin == (10, 50) and p_conf == (10, 10)  # legacy unpack
        assert p_bin.cache_key != p_conf.cache_key
        assert p_bin.cache_key[-1] == 50 and p_conf.cache_key[-1] == 10
        assert len(eng._plan_cache) == 2
        # re-resolving either cap returns the SAME cached plan object
        assert eng._gen_plan() is p_bin
        assert eng._gen_plan(10) is p_conf
        assert len(eng._plan_cache) == 2
        # chunk schedule covers the total in scan-step chunks
        assert sum(p_bin.chunks) == 50 and p_bin.chunks[0] == 10
        assert p_conf.chunks == (10,)


class TestSuffixBuckets:
    def test_menu_and_rounding(self):
        assert batching.suffix_bucket_for(1) == 8
        assert batching.suffix_bucket_for(8) == 8
        assert batching.suffix_bucket_for(9) == 16
        assert batching.suffix_bucket_for(64) == 64
        assert batching.suffix_bucket_for(65) == 128   # rounds up, no raise
        assert batching.suffix_bucket_for(130) == 192


class TestEncodePrefixPairs:
    def test_memoizes_and_passes_through_ids(self):
        tok = build_test_tokenizer()
        pairs = [("alpha one", (BIN_SUFFIX, CONF_SUFFIX)),
                 ("beta two", (BIN_SUFFIX, CONF_SUFFIX)),
                 ([5, 6, 7], ([8], [9, 10]))]
        pe, se = batching.encode_prefix_pairs(tok, pairs)
        assert len(pe) == 3 and len(se) == 2
        assert pe[2] == [5, 6, 7]
        assert se[0][2] == [8] and se[1][2] == [9, 10]
        # shared suffix text encodes identically across rows
        assert se[0][0] == se[0][1]
        # suffixes tokenize WITHOUT special tokens, prefixes with defaults
        assert se[0][0] == list(
            tok([BIN_SUFFIX], add_special_tokens=False)["input_ids"][0])

    def test_encode_prompts_mixed(self):
        tok = build_test_tokenizer()
        enc = batching.encode_prompts(tok, ["soup", [1, 2, 3]])
        assert enc[1] == [1, 2, 3]
        assert enc[0] == list(tok(["soup"])["input_ids"][0])


class TestHostPrefetcher:
    def test_order_and_counters(self):
        telemetry.clear_counters()
        out = list(batching.HostPrefetcher(range(7), lambda i: i * i))
        assert out == [i * i for i in range(7)]
        assert telemetry.counter("host_overlap_chunks") == 7
        assert "host_overlap_idle_ms" in telemetry.counters()

    def test_worker_exception_reraises_in_consumer(self):
        def fn(i):
            if i == 2:
                raise ValueError("boom at 2")
            return i

        it = iter(batching.HostPrefetcher(range(5), fn))
        assert next(it) == 0 and next(it) == 1
        with pytest.raises(ValueError, match="boom at 2"):
            next(it)

    def test_overlap_actually_runs_ahead(self):
        """While the consumer sits on item N, the worker should already
        have produced item N+1 (depth-1 double buffering)."""
        import time

        produced = []

        def fn(i):
            produced.append(i)
            return i

        it = iter(batching.HostPrefetcher(range(3), fn))
        assert next(it) == 0
        deadline = time.monotonic() + 2.0
        while len(produced) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(produced) >= 2  # item 1 tokenized before it was asked for


class TestCompileCacheEnv:
    def test_env_gate(self, tmp_path, monkeypatch):
        from llm_interpretation_replication_tpu.runtime.loader import (
            enable_compile_cache,
        )

        import jax

        prev = jax.config.jax_compilation_cache_dir
        try:
            # env path wins over the caller's default
            monkeypatch.setenv("LLM_INTERP_COMPILE_CACHE", str(tmp_path))
            assert enable_compile_cache("/ignored") == str(tmp_path)
            assert jax.config.jax_compilation_cache_dir == str(tmp_path)
            # off-switch beats any default
            monkeypatch.setenv("LLM_INTERP_COMPILE_CACHE", "0")
            assert enable_compile_cache(str(tmp_path)) is None
            # unset env: caller's path is used; no path -> no-op
            monkeypatch.delenv("LLM_INTERP_COMPILE_CACHE")
            assert enable_compile_cache(None) is None
            assert enable_compile_cache(str(tmp_path)) == str(tmp_path)
        finally:
            jax.config.update("jax_compilation_cache_dir", prev)


class TestWarmup:
    def test_warmup_compiles_and_records_counters(self):
        eng, _, _ = _tiny_engine(batch_size=2)
        telemetry.clear_counters()
        report = eng.warmup(
            prompt_lengths=[10, 20], suffix_length=6,
            legs=[LegSpec("binary"),
                  LegSpec("confidence", with_confidence=True,
                          max_new_tokens=10)],
            compile_hit_secs=1e9,  # tiny CPU compiles always classify hit
        )
        assert [r["bucket"] for r in report] == [32]  # both lengths, 1 bucket
        assert all(r["cache_hit"] for r in report)
        assert telemetry.counter("compile_cache_hit") == 1
        # both legs' plans registered under their own cap keys
        caps = {k[-1] for k in eng._plan_cache}
        assert {None, 10} <= caps


@pytest.mark.faults
class TestPrefixPoolFaults:
    def test_oom_mid_suffix_leaves_pool_consistent(self, monkeypatch):
        """An OOM raised by a suffix-extension launch re-buckets the batch
        (PR-1 ladder); the failed attempt's prefix cache entry must be
        released exactly once — never orphaned past the sweep, never
        double-freed — and the retried rows still land correct rows."""
        import dataclasses as dc

        from llm_interpretation_replication_tpu.models import decoder as dmod
        from llm_interpretation_replication_tpu.utils.testing import (
            injected_oom_error,
        )

        eng, _, _ = _tiny_engine(batch_size=4)
        eng.ecfg = dc.replace(eng.ecfg, oom_backoff=True, oom_batch_floor=1,
                              oom_batch_ladder=())
        prefixes = [f"Is thing {i} a stuff?" for i in range(6)]
        clean = eng.score_prefixed(_pairs(prefixes),
                                   legs=[LegSpec(), LegSpec()])

        real_extend = dmod.extend_prefill
        state = {"calls": 0}

        def failing_extend(*a, **kw):
            state["calls"] += 1
            if state["calls"] == 2:  # mid-suffix: leg 2 of the first batch
                raise injected_oom_error()
            return real_extend(*a, **kw)

        monkeypatch.setattr(dmod, "extend_prefill", failing_extend)
        fused = eng.score_prefixed(_pairs(prefixes),
                                   legs=[LegSpec(), LegSpec()])
        pool = eng.last_prefix_pool
        assert pool.consistent, (pool.acquired, pool.released, pool.leaked)
        assert pool.live_bytes == 0 and not pool.live
        assert pool.acquired == pool.released > 1  # the retry re-acquired
        assert any(e["kind"] == "engine_oom_backoff"
                   for e in eng.fault_events)
        for leg_c, leg_f in zip(clean, fused):
            for a, b in zip(leg_c, leg_f):
                assert b["success"]
                np.testing.assert_allclose(a["relative_prob"],
                                           b["relative_prob"], rtol=2e-5)

    def test_double_release_raises(self):
        from llm_interpretation_replication_tpu.runtime.engine import (
            PrefixCachePool,
        )

        pool = PrefixCachePool()
        entry = pool.acquire(128, 4)
        pool.release(entry)
        with pytest.raises(RuntimeError, match="released twice"):
            pool.release(entry)
        assert pool.consistent

    def test_abandoned_entry_counts_as_leak(self):
        from llm_interpretation_replication_tpu.runtime.engine import (
            PrefixCachePool,
        )

        pool = PrefixCachePool()
        pool.acquire(128, 4)
        pool.close()
        assert pool.leaked == 1 and not pool.consistent
        assert pool.live_bytes == 0


class TestSweep100qPairs:
    def test_format_prompt_parts_rejoin(self):
        from llm_interpretation_replication_tpu.scoring.prompts import (
            format_prompt,
            format_prompt_parts,
        )

        q = 'Is a "tweet" a "publication"?'
        for base in (True, False):
            for name in ("org/falcon-7b", "org/Baichuan-13B-Chat"):
                pre, suf = format_prompt_parts(q, base, name)
                assert pre + suf == format_prompt(q, base, name)
        # base-model prefix is the SHARED few-shot preamble
        pre, _ = format_prompt_parts(q, True)
        from llm_interpretation_replication_tpu.scoring.prompts import (
            FEW_SHOT_PREFIX,
        )

        assert pre == FEW_SHOT_PREFIX
