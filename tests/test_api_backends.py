"""API backend tests driven entirely through FakeTransport."""

import json
import math

import numpy as np
import pytest

from llm_interpretation_replication_tpu.api_backends import (
    AnthropicClient,
    CostTracker,
    FakeTransport,
    GeminiClient,
    OpenAIClient,
    ResponseCache,
    build_openai_batch_request,
    evaluate_claude,
    evaluate_gemini_binary,
    evaluate_gemini_confidence,
    evaluate_gpt_binary,
    evaluate_gpt_confidence,
    evaluate_normal_baseline,
    evaluate_random_baseline,
    first_token_target_probs,
    is_reasoning_model,
)
from llm_interpretation_replication_tpu.api_backends.transport import TransportError
from llm_interpretation_replication_tpu.utils.retry import RetryPolicy


def fast_retry():
    return RetryPolicy(retry_on=(TransportError,), max_retries=3,
                       initial_delay=0.0, sleep=lambda s: None)


def chat_response(text, top_logprobs=None, usage=None):
    content = []
    if top_logprobs is not None:
        content = [
            {"token": text.split()[0] if text else "", "top_logprobs": top_logprobs}
        ]
    return {
        "choices": [
            {
                "message": {"content": text},
                "logprobs": {"content": content} if content else None,
            }
        ],
        "usage": usage or {"prompt_tokens": 100, "completion_tokens": 5},
    }


class TestOpenAIClient:
    def test_chat_completion_params(self):
        ft = FakeTransport()
        seen = {}

        def responder(call):
            seen.update(call["json"])
            return 200, chat_response("Yes")

        ft.add("POST", "/chat/completions", responder)
        client = OpenAIClient("k", transport=ft, retry_policy=fast_retry())
        client.chat_completion("gpt-4.1-2025-04-14", [{"role": "user", "content": "q"}])
        assert seen["temperature"] == 0.0
        assert seen["logprobs"] is True
        assert seen["top_logprobs"] == 20
        assert seen["max_tokens"] == 500

    def test_reasoning_model_params(self):
        ft = FakeTransport()
        seen = {}
        ft.add("POST", "/chat/completions", lambda c: (seen.update(c["json"]), (200, chat_response("Yes")))[1])
        client = OpenAIClient("k", transport=ft, retry_policy=fast_retry())
        client.chat_completion("gpt-5", [{"role": "user", "content": "q"}])
        assert seen["max_completion_tokens"] == 2000
        assert "logprobs" not in seen
        assert is_reasoning_model("o3-2025-04-16")
        assert not is_reasoning_model("gpt-4.1-mini-2025-04-14")

    def test_retry_on_429_then_success(self):
        ft = FakeTransport()
        attempts = {"n": 0}

        def responder(call):
            attempts["n"] += 1
            if attempts["n"] < 3:
                raise TransportError(429, "rate limited", retryable=True)
            return 200, chat_response("ok")

        ft.add("POST", "/chat/completions", responder)
        client = OpenAIClient("k", transport=ft, retry_policy=fast_retry())
        resp = client.chat_completion("gpt-4o-2024-11-20", [{"role": "user", "content": "q"}])
        assert attempts["n"] == 3
        assert resp["choices"][0]["message"]["content"] == "ok"

    def test_non_retryable_raises_immediately(self):
        ft = FakeTransport()
        ft.add("POST", "/chat/completions",
               lambda c: (_ for _ in ()).throw(TransportError(401, "bad key", retryable=False)))
        client = OpenAIClient("k", transport=ft, retry_policy=fast_retry())
        with pytest.raises(RuntimeError):
            client.chat_completion("gpt-4o-2024-11-20", [{"role": "user", "content": "q"}])
        assert len(ft.calls) == 1

    def test_batch_pipeline(self):
        ft = FakeTransport()
        polls = {"n": 0}
        ft.add("POST", "/files", lambda c: (200, {"id": "file-1"}))
        ft.add("POST", "/batches", lambda c: (200, {"id": "batch-1", "status": "validating"}))

        def poll(call):
            polls["n"] += 1
            status = "completed" if polls["n"] >= 2 else "in_progress"
            return 200, {"id": "batch-1", "status": status, "output_file_id": "file-2"}

        ft.add("GET", "/batches/batch-1", poll)
        out_lines = [{"custom_id": "a", "response": {"body": chat_response("Yes")}}]
        ft.add("GET", "/files/file-2/content",
               lambda c: (200, "\n".join(json.dumps(l) for l in out_lines).encode()))
        client = OpenAIClient("k", transport=ft, retry_policy=fast_retry())
        reqs = [build_openai_batch_request("a", "gpt-4.1-2025-04-14",
                                           [{"role": "user", "content": "q"}])]
        results = client.run_batch(reqs, poll_interval=0, sleep=lambda s: None)
        assert results[0]["custom_id"] == "a"
        # the uploaded multipart body contains the request JSONL
        upload = [c for c in ft.calls if "/files" in c["url"] and c["method"] == "POST"][0]
        assert b"custom_id" in upload["data"]

    def test_batch_terminal_failure(self):
        ft = FakeTransport()
        ft.add("GET", "/batches/batch-x", lambda c: (200, {"id": "batch-x", "status": "failed"}))
        client = OpenAIClient("k", transport=ft, retry_policy=fast_retry())
        with pytest.raises(RuntimeError, match="terminal state"):
            client.wait_for_batch("batch-x", poll_interval=0, sleep=lambda s: None)


class TestAnthropicClient:
    def _client(self, handlers):
        ft = FakeTransport()
        for h in handlers:
            ft.add(*h)
        return AnthropicClient("k", transport=ft, retry_policy=fast_retry()), ft

    def test_message_and_text(self):
        client, ft = self._client([
            ("POST", "/messages", lambda c: (200, {
                "content": [{"type": "text", "text": "Not Covered"}]
            })),
        ])
        msg = client.create_message("claude-opus-4-1-20250805",
                                    [{"role": "user", "content": "q"}])
        assert client.text_of(msg) == "Not Covered"
        sent = ft.calls[0]["headers"]
        assert sent["x-api-key"] == "k"
        assert "anthropic-version" in sent

    def test_approximate_logprobs_counts(self):
        # reference quirk: first matching target in target order wins, so
        # "Not Covered" counts toward "Covered" with targets (Covered, Not)
        replies = iter(["Covered", "Covered", "Not Covered", "Not sure thing", "weird"])
        client, _ = self._client([
            ("POST", "/messages", lambda c: (200, {
                "content": [{"type": "text", "text": next(replies)}]
            })),
        ])
        probs, texts = client.approximate_logprobs(
            "claude-opus-4-1-20250805", [{"role": "user", "content": "q"}],
            ["Covered", "Not"], n_samples=5,
        )
        assert probs["Covered"] == pytest.approx(3 / 5)
        assert probs["Not"] == pytest.approx(1 / 5)

    def test_approximate_logprobs_uniform_fallback(self):
        client, _ = self._client([
            ("POST", "/messages", lambda c: (200, {
                "content": [{"type": "text", "text": "no target here"}]
            })),
        ])
        probs, _ = client.approximate_logprobs(
            "claude-opus-4-1-20250805", [{"role": "user", "content": "q"}],
            ["Covered", "Nope"], n_samples=3,
        )
        assert probs == {"Covered": 0.5, "Nope": 0.5}

    def test_batch_size_cap(self):
        client, _ = self._client([])
        with pytest.raises(ValueError):
            client.create_batch([{} for _ in range(10_001)])

    def test_batch_poll_and_results(self):
        polls = {"n": 0}

        def poll(call):
            polls["n"] += 1
            status = "ended" if polls["n"] >= 2 else "in_progress"
            return 200, {"id": "b1", "processing_status": status}

        lines = [{"custom_id": "x", "result": {"type": "succeeded"}}]
        client, _ = self._client([
            ("POST", "/messages/batches", lambda c: (200, {"id": "b1", "processing_status": "in_progress"})),
            ("GET", "/messages/batches/b1/results",
             lambda c: (200, "\n".join(json.dumps(l) for l in lines).encode())),
            ("GET", "/messages/batches/b1", poll),
        ])
        results = client.run_batches([{"custom_id": "x", "params": {}}],
                                     poll_interval=0, sleep=lambda s: None)
        assert results[0]["custom_id"] == "x"


class TestGeminiClient:
    def _response(self, text, top=None):
        cand = {"content": {"parts": [{"text": text}]}}
        if top is not None:
            cand["logprobsResult"] = {
                "topCandidates": [
                    {"candidates": [{"token": t, "logProbability": lp} for t, lp in pos]}
                    for pos in top
                ]
            }
        return {"candidates": [cand]}

    def test_generate_content_safety_and_logprobs(self):
        ft = FakeTransport()
        seen = {}
        ft.add("POST", ":generateContent",
               lambda c: (seen.update(c["json"]), (200, self._response("85")))[1])
        client = GeminiClient("k", transport=ft, retry_policy=fast_retry())
        resp = client.generate_content("gemini-2.5-pro", "q", response_logprobs=True)
        assert seen["generationConfig"]["responseLogprobs"] is True
        assert seen["generationConfig"]["logprobs"] == 19
        assert "maxOutputTokens" not in seen["generationConfig"]  # bug dodge
        assert all(s["threshold"] == "BLOCK_NONE" for s in seen["safetySettings"])
        assert client.text_of(resp) == "85"

    def test_top_candidates_extraction(self):
        client = GeminiClient("k", transport=FakeTransport(), retry_policy=fast_retry())
        resp = self._response("85", top=[[("85", math.log(0.9)), ("90", math.log(0.1))]])
        positions = client.top_candidates_of(resp)
        assert positions[0][0] == ("85", pytest.approx(math.log(0.9)))

    def test_generate_many_threads(self):
        ft = FakeTransport()
        ft.add("POST", ":generateContent", lambda c: (200, self._response("ok")))
        client = GeminiClient("k", transport=ft, retry_policy=fast_retry())
        out = client.generate_many("gemini-2.0-flash", [f"p{i}" for i in range(10)],
                                   max_workers=4)
        assert len(out) == 10

    def _batch_client(self, states, results=None):
        """Fake batch endpoints: submit returns batches/b1; each poll pops the
        next JOB_STATE_*; success carries inlined responses."""
        ft = FakeTransport()
        submitted = {}
        ft.add("POST", ":batchGenerateContent",
               lambda c: (submitted.update(c["json"]), (200, {"name": "batches/b1"}))[1])
        it = iter(states)

        def poll(_c):
            state = next(it)
            body = {"name": "batches/b1", "metadata": {"state": state}}
            if state == "JOB_STATE_SUCCEEDED" and results is not None:
                body["response"] = {"inlinedResponses": {"inlinedResponses": [
                    {"response": self._response(t)} for t in results
                ]}}
            return 200, body

        ft.add("GET", "batches/b1", poll)
        client = GeminiClient("k", transport=ft, retry_policy=fast_retry())
        return client, ft, submitted

    def test_batch_lifecycle(self):
        """Submit -> PENDING -> RUNNING -> SUCCEEDED with inlined results
        (perturb_prompts_gemini_batch.py:236-347)."""
        client, ft, submitted = self._batch_client(
            ["JOB_STATE_PENDING", "JOB_STATE_RUNNING", "JOB_STATE_SUCCEEDED"],
            results=["yes", "no"],
        )
        name = client.create_batch("gemini-2.5-pro", ["p1", "p2"],
                                   response_logprobs=True)
        assert name == "batches/b1"
        reqs = submitted["batch"]["inputConfig"]["requests"]["requests"]
        assert len(reqs) == 2
        assert reqs[0]["request"]["generationConfig"]["logprobs"] == 19
        naps = []
        batch = client.wait_for_batch(name, poll_interval=30, sleep_fn=naps.append)
        assert naps == [30, 30]  # slept between the 3 polls, 30 s apart
        out = client.batch_responses(batch)
        assert [client.text_of(r) for r in out] == ["yes", "no"]

    def test_batch_failure_state_raises(self):
        client, _, _ = self._batch_client(["JOB_STATE_FAILED"])
        with pytest.raises(RuntimeError, match="JOB_STATE_FAILED"):
            client.wait_for_batch("batches/b1", sleep_fn=lambda _s: None)

    def test_wait_timeout_uses_wall_clock_not_sleep_sum(self):
        """max_wait is enforced against a monotonic clock: get_batch latency
        and retry backoffs count toward the budget, not just the sleeps
        (summing poll intervals let real elapsed time overshoot 24h)."""
        client, _, _ = self._batch_client(["JOB_STATE_RUNNING"] * 100)
        now = [0.0]

        def clock():
            return now[0]

        def slow_sleep(s):
            now[0] += s + 45.0          # each poll round-trip costs 45 s extra

        with pytest.raises(TimeoutError, match="after 150s"):
            client.wait_for_batch("batches/b1", poll_interval=30,
                                  max_wait=140.0, sleep_fn=slow_sleep,
                                  clock_fn=clock)

    def test_openai_anthropic_wait_also_wall_clock(self):
        """The sibling OpenAI/Anthropic poll loops share the monotonic-clock
        timeout semantics (the defect was fixed in all three copies)."""
        import json as _json

        from llm_interpretation_replication_tpu.api_backends.anthropic_client import (
            AnthropicClient,
        )
        from llm_interpretation_replication_tpu.api_backends.openai_client import (
            OpenAIClient,
        )

        class Poll:
            def __init__(self, body):
                self.body = _json.dumps(body).encode()

            def request(self, method, url, headers, *payload):
                return 200, self.body

        for client, kwargs in [
            (OpenAIClient("k", transport=Poll({"id": "b", "status": "in_progress"})),
             {}),
            (AnthropicClient("k", transport=Poll(
                {"id": "b", "processing_status": "in_progress"})), {}),
        ]:
            now = [0.0]
            with pytest.raises(TimeoutError):
                client.wait_for_batch(
                    "b", poll_interval=30, timeout=100.0,
                    sleep=lambda s: now.__setitem__(0, now[0] + s + 45.0),
                    clock=lambda: now[0], **kwargs)

    def test_run_batch_resumes_from_saved_id(self, tmp_path):
        """A saved batch id re-attaches (NO second submit) and is cleared on
        success (reference save/load/clear_batch_id :349-381)."""
        from llm_interpretation_replication_tpu.api_backends.gemini_client import (
            load_batch_id, save_batch_id,
        )

        resume = str(tmp_path / "ckpt" / "batch_id.txt")
        save_batch_id(resume, "batches/b1")
        assert load_batch_id(resume) == "batches/b1"
        client, ft, _ = self._batch_client(["JOB_STATE_SUCCEEDED"], results=["ok"])
        out = client.run_batch("gemini-2.5-pro", ["p"], resume_file=resume,
                               sleep_fn=lambda _s: None)
        assert [client.text_of(r) for r in out] == ["ok"]
        assert not any(":batchGenerateContent" in c["url"] for c in ft.calls)
        assert load_batch_id(resume) is None  # cleared after success


class TestBatchRepair:
    def test_extract_text_from_response_string(self):
        from llm_interpretation_replication_tpu.api_backends import (
            extract_text_from_response_string,
        )

        raw = "candidates=[Candidate(content=Content(parts=[Part(text='85')]))]"
        assert extract_text_from_response_string(raw) == "85"
        assert extract_text_from_response_string("no text field here") == ""
        # Python repr switches to double quotes around apostrophes; escaped
        # quotes inside the literal must survive un-truncated
        assert extract_text_from_response_string(
            'Part(text="It\'s likely")') == "It's likely"
        assert extract_text_from_response_string(
            "Part(text='a \\'quoted\\' word')") == "a 'quoted' word"

    def test_repair_batch_responses(self, tmp_path):
        import json

        from llm_interpretation_replication_tpu.api_backends import (
            repair_batch_responses,
        )

        req = tmp_path / "requests.jsonl"
        resp = tmp_path / "responses.jsonl"
        out = tmp_path / "fixed.jsonl"
        req.write_text(
            "\n".join(json.dumps({"custom_id": f"q{i}", "request": {}}) for i in range(2)) + "\n"
        )
        # corrupted rows: the text field holds a stringified response object,
        # custom_ids lost; third row has no matching request
        def corrupt(text):
            return {"response": {"candidates": [{"content": {"parts": [{
                "text": f"Candidate(content=Content(parts=[Part(text='{text}')]))"
            }]}}]}}

        resp.write_text("\n".join(
            json.dumps(r) for r in (corrupt("Yes"), corrupt("72"), {"response": {}})
        ) + "\n")
        n = repair_batch_responses(str(req), str(resp), str(out))
        assert n == 3
        rows = [json.loads(l) for l in out.read_text().splitlines()]
        assert [r["custom_id"] for r in rows] == ["q0", "q1", "result_2"]
        texts = [r["response"]["candidates"][0]["content"]["parts"][0]["text"]
                 for r in rows]
        assert texts == ["Yes", "72", ""]


class TestEvaluators:
    def test_gpt_binary_relative_prob(self):
        ft = FakeTransport()
        top = [{"token": "Yes", "logprob": math.log(0.7)},
               {"token": "No", "logprob": math.log(0.2)}]
        ft.add("POST", "/chat/completions", lambda c: (200, chat_response("Yes", top)))
        client = OpenAIClient("k", transport=ft, retry_policy=fast_retry())
        res = evaluate_gpt_binary(client, "gpt-4.1-2025-04-14", "Is a tent a building?")
        assert res["yes_prob"] == pytest.approx(0.7)
        assert res["relative_prob"] == pytest.approx(0.7 / 0.9)

    def test_gpt_binary_targets_missing_from_top(self):
        ft = FakeTransport()
        top = [{"token": "Maybe", "logprob": math.log(0.9)}]
        ft.add("POST", "/chat/completions", lambda c: (200, chat_response("Maybe", top)))
        client = OpenAIClient("k", transport=ft, retry_policy=fast_retry())
        res = evaluate_gpt_binary(client, "gpt-4.1-2025-04-14", "q?")
        assert res["relative_prob"] == 0.5  # both zero -> 0.5 fallback

    def test_gpt_confidence_weighted(self):
        ft = FakeTransport()
        top = [{"token": "85", "logprob": math.log(0.8)},
               {"token": "90", "logprob": math.log(0.2)}]
        ft.add("POST", "/chat/completions", lambda c: (200, chat_response("85", top)))
        client = OpenAIClient("k", transport=ft, retry_policy=fast_retry())
        res = evaluate_gpt_confidence(client, "gpt-4.1-2025-04-14", "q?")
        assert res["confidence"] == 85
        assert res["weighted_confidence"] == pytest.approx(85 * 0.8 + 90 * 0.2)

    def test_gemini_evaluators(self):
        ft = FakeTransport()
        resp = {
            "candidates": [{
                "content": {"parts": [{"text": "Yes"}]},
                "logprobsResult": {"topCandidates": [
                    {"candidates": [
                        {"token": "Yes", "logProbability": math.log(0.6)},
                        {"token": "No", "logProbability": math.log(0.3)},
                    ]}
                ]},
            }]
        }
        ft.add("POST", ":generateContent", lambda c: (200, resp))
        client = GeminiClient("k", transport=ft, retry_policy=fast_retry())
        out = evaluate_gemini_binary(client, "gemini-2.5-pro", "q?")
        assert out["relative_prob"] == pytest.approx(0.6 / 0.9)
        conf = evaluate_gemini_confidence(client, "gemini-2.5-pro", "q?")
        assert conf["response"] == "Yes"

    def test_claude_evaluator_no_logprobs(self):
        ft = FakeTransport()
        texts = iter(["Yes", "85"])
        ft.add("POST", "/messages", lambda c: (200, {
            "content": [{"type": "text", "text": next(texts)}]
        }))
        client = AnthropicClient("k", transport=ft, retry_policy=fast_retry())
        res = evaluate_claude(client, "claude-opus-4-1-20250805", "q?")
        assert res["response"] == "Yes"
        assert res["confidence"] == 85

    def test_baselines_seeded(self):
        rng = np.random.default_rng(42)
        r1 = evaluate_random_baseline(rng)
        assert r1["response"] in ("Yes", "No")
        assert 0 <= r1["confidence"] <= 100
        n = evaluate_normal_baseline(0.619, 0.167, np.random.default_rng(42))
        assert 0.0 <= n["relative_prob"] <= 1.0

    def test_first_token_target_probs(self):
        top = [{"token": "Covered", "logprob": math.log(0.5)},
               {"token": "Not", "logprob": math.log(0.4)}]
        p1, p2 = first_token_target_probs(top, ("Covered", "Not"))
        assert (p1, p2) == (pytest.approx(0.5), pytest.approx(0.4))


class TestCacheAndCost:
    def test_cache_partial_reruns(self, tmp_path):
        path = str(tmp_path / "api_cache.json")
        cache = ResponseCache(path)
        q = "Is a screenshot a photograph?" + "x" * 200
        cache.put(q, {"gpt_response": "Yes", "gpt_yes_prob": 0.7, "gpt_no_prob": 0.2,
                      "gpt_relative_prob": 0.78, "gpt_confidence": 80,
                      "gpt_weighted_confidence": 79.5})
        missing = cache.missing_evaluators(q)
        assert "gpt" not in missing
        assert set(missing) == {"gemini", "claude", "random"}
        # reload from disk; key is first-100-chars so long questions collide correctly
        cache2 = ResponseCache(path)
        assert cache2.get(q[:100] + "DIFFERENT TAIL") is not None
        assert not cache2.is_complete(q)

    def test_cost_tracking_and_extrapolation(self):
        tracker = CostTracker(pricing={"m": {"input": 2.0, "output": 8.0}})
        tracker.record("m", 1_000_000, 500_000)
        assert tracker.cost("m") == pytest.approx(2.0 + 4.0)
        assert tracker.extrapolate("m", processed=100, total=1000) == pytest.approx(60.0)
        tracker.record_response("m", {"usage": {"prompt_tokens": 10, "completion_tokens": 2}})
        assert tracker.usage["m"]["requests"] == 2
        assert tracker.total_cost() > 6.0


class TestApiPerturbationSweep:
    """Study-1 batch orchestration (perturb_prompts.py:190-667) through
    FakeTransport: request pairing, chunked submit, extraction, resume,
    reasoning-model modes."""

    def _scenarios(self):
        return [{
            "original_main": "Scenario text one.",
            "response_format": "Answer 'Covered' or 'Not'.",
            "target_tokens": ["Covered", "Not"],
            "confidence_format": "Confidence 0-100?",
            "rephrasings": ["Rephrase A.", "Rephrase B."],
        }]

    def _client(self):
        import math

        from llm_interpretation_replication_tpu.api_backends.openai_client import (
            OpenAIClient,
        )

        ft = FakeTransport()
        uploads = {}

        def upload(call):
            fid = f"file-{len(uploads)}"
            # multipart body carries the JSONL; stash per file id
            uploads[fid] = call["data"]
            return 200, {"id": fid}

        ft.add("POST", "/files", upload)
        ft.add("POST", "/batches", lambda c: (200, {
            "id": "batch-1", "status": "validating",
            "input_file_id": c["json"]["input_file_id"],
        }))

        def poll(_c):
            # completed immediately; results derived from the uploaded JSONL
            fid = next(iter(uploads))
            return 200, {"id": "batch-1", "status": "completed",
                         "output_file_id": f"out-{fid}"}

        ft.add("GET", "/batches/batch-1", poll)

        def download(call):
            import json as _json

            fid = call["url"].rsplit("/files/out-", 1)[1].split("/content")[0]
            lines = []
            for line in uploads[fid].decode(errors="ignore").splitlines():
                line = line.strip()
                if not line.startswith("{"):
                    continue
                req = _json.loads(line)
                content = req["body"]["messages"][0]["content"]
                if "Confidence" in content:
                    body = {"choices": [{"message": {"content": "85"}, "logprobs": {
                        "content": [{"top_logprobs": [
                            {"token": "85", "logprob": math.log(0.6)},
                            {"token": "90", "logprob": math.log(0.2)},
                        ]}]}}],
                        "usage": {"prompt_tokens": 9, "completion_tokens": 2}}
                else:
                    body = {"choices": [{"message": {"content": "Covered"}, "logprobs": {
                        "content": [{"top_logprobs": [
                            {"token": "Covered", "logprob": math.log(0.7)},
                            {"token": "Not", "logprob": math.log(0.2)},
                        ]}]}}],
                        "usage": {"prompt_tokens": 9, "completion_tokens": 1}}
                lines.append(_json.dumps({
                    "custom_id": req["custom_id"], "response": {"body": body},
                }))
            return 200, "\n".join(lines).encode()

        ft.add("GET", "/content", download)
        return OpenAIClient("k", transport=ft, retry_policy=fast_retry()), ft

    def test_full_sweep_schema_extraction_and_resume(self, tmp_path):
        from llm_interpretation_replication_tpu.api_backends.cost import CostTracker
        from llm_interpretation_replication_tpu.sweeps.api_perturbation import (
            run_api_perturbation_sweep,
        )
        from llm_interpretation_replication_tpu.sweeps.writers import (
            PERTURBATION_COLUMNS,
        )

        client, ft = self._client()
        out = str(tmp_path / "results.xlsx")
        cost = CostTracker(pricing={"gpt-4.1": {"input": 2.0, "output": 8.0}})
        df = run_api_perturbation_sweep(
            client, ["gpt-4.1"], self._scenarios(), out,
            sleep=lambda _s: None, cost_tracker=cost,
        )
        assert list(df.columns) == PERTURBATION_COLUMNS
        assert len(df) == 2                       # 2 rephrasings
        assert df["Token_1_Prob"].iloc[0] == pytest.approx(0.7)
        assert df["Token_2_Prob"].iloc[0] == pytest.approx(0.2)
        assert df["Odds_Ratio"].iloc[0] == pytest.approx(0.7 / 0.2)
        assert df["Confidence Value"].iloc[0] == 85
        # weighted = (85*0.6 + 90*0.2) / 0.8
        assert df["Weighted Confidence"].iloc[0] == pytest.approx(
            (85 * 0.6 + 90 * 0.2) / 0.8)
        assert cost.total_cost() > 0

        # resume: everything processed -> no new uploads
        uploads_before = sum(1 for c in ft.calls if c["url"].endswith("/files"))
        df2 = run_api_perturbation_sweep(
            client, ["gpt-4.1"], self._scenarios(), out, sleep=lambda _s: None,
        )
        uploads_after = sum(1 for c in ft.calls if c["url"].endswith("/files"))
        assert uploads_after == uploads_before
        assert len(df2) == 2

    def test_reasoning_model_confidence_only(self, tmp_path):
        from llm_interpretation_replication_tpu.sweeps.api_perturbation import (
            create_batch_requests, extract_results_from_batch, group_batch_results,
            run_api_perturbation_sweep,
        )

        requests, mapping = create_batch_requests("gpt-5", self._scenarios())
        # skip_reasoning_logprobs default: confidence leg only
        assert len(requests) == 2
        assert all("max_completion_tokens" in r["body"] for r in requests)
        assert all(m["format_type"] == "confidence" for m in mapping.values())

        client, _ = self._client()
        out = str(tmp_path / "r.xlsx")
        df = run_api_perturbation_sweep(
            client, ["gpt-5"], self._scenarios(), out, sleep=lambda _s: None,
        )
        assert (df["Model Response"] == "N/A (skipped for reasoning model)").all()
        assert (df["Token_1_Prob"] == 0).all()
        assert (df["Confidence Value"] == 85).all()
        assert (df["Log Probabilities"] == "N/A for reasoning models").all()

    def test_half_failed_pair_left_out_for_resume(self):
        """Binary succeeded but confidence errored: the pair must NOT be
        written (a null-confidence row would be skipped forever by
        triple-based resume) — mirroring the Claude leg's retry-on-resume
        semantics."""
        from llm_interpretation_replication_tpu.sweeps.api_perturbation import (
            create_batch_requests, extract_results_from_batch, group_batch_results,
        )

        _, mapping = create_batch_requests("gpt-4.1", self._scenarios(),
                                           max_rephrasings=2)
        raw = []
        for cid, info in mapping.items():
            if info["format_type"] == "confidence" and info["rephrase_idx"] == 0:
                raw.append({"custom_id": cid, "error": {"message": "boom"},
                            "response": None})
                continue
            raw.append({"custom_id": cid, "response": {"body": {
                "choices": [{"message": {"content": "Covered"},
                             "logprobs": {"content": []}}]}}})
        rows = extract_results_from_batch(group_batch_results(raw, mapping),
                                          "gpt-4.1")
        assert len(rows) == 1                       # only the complete pair
        assert rows[0]["Rephrased Main Part"] == "Rephrase B."

        # reasoning frequency mode (skip_reasoning_logprobs=False) has the
        # same failure shape: successful binary runs + errored confidence
        # must not be written either
        _, rmap = create_batch_requests("o3", self._scenarios(),
                                        skip_reasoning_logprobs=False,
                                        max_rephrasings=1)
        rraw = [{"custom_id": cid, "response": {"body": {
                    "choices": [{"message": {"content": "Covered"}}]}}}
                for cid, info in rmap.items() if info["format_type"] == "binary"]
        rrows = extract_results_from_batch(group_batch_results(rraw, rmap), "o3",
                                           skip_reasoning_logprobs=False)
        assert rrows == []

    def test_reasoning_model_frequency_runs(self):
        from llm_interpretation_replication_tpu.sweeps.api_perturbation import (
            REASONING_MODEL_RUNS, create_batch_requests, extract_results_from_batch,
            group_batch_results,
        )

        requests, mapping = create_batch_requests(
            "o3", self._scenarios(), skip_reasoning_logprobs=False,
            max_rephrasings=1,
        )
        binary = [m for m in mapping.values() if m["format_type"] == "binary"]
        assert len(binary) == REASONING_MODEL_RUNS
        # 7 of 10 runs say Covered, 3 say Not -> frequency probabilities
        raw = []
        for cid, info in mapping.items():
            if info["format_type"] == "binary":
                text = "Covered" if info["run_idx"] < 7 else "Not"
            else:
                text = "60"
            raw.append({"custom_id": cid, "response": {"body": {
                "choices": [{"message": {"content": text}}]}}})
        rows = extract_results_from_batch(
            group_batch_results(raw, mapping), "o3", skip_reasoning_logprobs=False,
        )
        assert rows[0]["Token_1_Prob"] == pytest.approx(0.7)
        assert rows[0]["Token_2_Prob"] == pytest.approx(0.3)
        assert rows[0]["Model Response"] == "Covered"          # modal
        assert rows[0]["Weighted Confidence"] == 60


class TestClaudePerturbationSweep:
    """Confidence-only Message-Batches sweep (perturb_prompts_claude_batch.py)."""

    def _scenarios(self):
        return [{
            "original_main": "Scenario text one.",
            "response_format": "Answer 'Covered' or 'Not'.",
            "target_tokens": ["Covered", "Not"],
            "confidence_format": "Confidence 0-100?",
            "rephrasings": ["Rephrase A.", "Rephrase B."],
        }]

    def _client(self):
        import json as _json

        ft = FakeTransport()
        submitted = {}

        def create(call):
            submitted["requests"] = call["json"]["requests"]
            return 200, {"id": "mb-1", "processing_status": "in_progress"}

        ft.add("POST", "/messages/batches", create)
        ft.add("GET", "/messages/batches/mb-1/results", lambda c: (200, "\n".join(
            _json.dumps({
                "custom_id": r["custom_id"],
                "result": {"type": "succeeded", "message": {
                    "content": [{"type": "text", "text": "Confidence: 85"}]}},
            }) for r in submitted["requests"]
        ).encode()))
        ft.add("GET", "/messages/batches/mb-1",
               lambda c: (200, {"id": "mb-1", "processing_status": "ended"}))
        return AnthropicClient("k", transport=ft, retry_policy=fast_retry()), ft

    def test_sweep_matches_reference_workbook_schema(self, tmp_path):
        import os

        from llm_interpretation_replication_tpu.sweeps.api_perturbation import (
            CLAUDE_PERTURBATION_COLUMNS, run_claude_perturbation_sweep,
        )

        client, ft = self._client()
        out = str(tmp_path / "claude.xlsx")
        df = run_claude_perturbation_sweep(
            client, "claude-opus-4-1-20250805", self._scenarios(), out,
            sleep=lambda _s: None,
        )
        assert list(df.columns) == CLAUDE_PERTURBATION_COLUMNS
        ref_wb = "/root/reference/results/claude_opus_batch_perturbation_results.xlsx"
        if os.path.exists(ref_wb):
            from llm_interpretation_replication_tpu.utils.xlsx import read_xlsx

            # byte-identical column order to the study's recorded workbook
            assert list(read_xlsx(ref_wb).columns) == CLAUDE_PERTURBATION_COLUMNS
        assert len(df) == 2
        assert (df["Confidence Value"] == 85).all()
        assert (df["Weighted Confidence"] == 85).all()
        assert (df["Odds_Ratio"] == 0.0).all()
        assert (df["Model Response"] == "N/A (Confidence-only mode)").all()
        sent = ft.calls[0]["json"]["requests"][0]["params"]
        assert sent["temperature"] == 1.0 and sent["max_tokens"] == 500

        # resume: all pairs in the workbook -> no new batch submitted
        n_creates = sum(1 for c in ft.calls
                        if c["url"].endswith("/messages/batches") and c["method"] == "POST")
        run_claude_perturbation_sweep(
            client, "claude-opus-4-1-20250805", self._scenarios(), out,
            sleep=lambda _s: None,
        )
        n_creates2 = sum(1 for c in ft.calls
                         if c["url"].endswith("/messages/batches") and c["method"] == "POST")
        assert n_creates2 == n_creates


class TestGeminiPerturbationSweep:
    def test_threaded_sweep_with_checkpoints_and_resume(self, tmp_path):
        import math

        from llm_interpretation_replication_tpu.sweeps.api_perturbation import (
            run_gemini_perturbation_sweep,
        )
        from llm_interpretation_replication_tpu.sweeps.writers import (
            PERTURBATION_COLUMNS,
        )

        scenarios = [{
            "original_main": "Scenario text one.",
            "response_format": "Answer 'Covered' or 'Not'.",
            "target_tokens": ["Covered", "Not"],
            "confidence_format": "Confidence 0-100?",
            "rephrasings": [f"Rephrase {i}." for i in range(5)],
        }]
        ft = FakeTransport()

        def respond(call):
            content = call["json"]["contents"][0]["parts"][0]["text"]
            if "Confidence" in content:
                return 200, {"candidates": [{
                    "content": {"parts": [{"text": "85"}]},
                    "logprobsResult": {"topCandidates": [
                        {"candidates": [{"token": "8", "logProbability": math.log(0.6)},
                                        {"token": "9", "logProbability": math.log(0.3)}]},
                        {"candidates": [{"token": "5", "logProbability": math.log(0.9)}]},
                    ]},
                }]}
            return 200, {"candidates": [{
                "content": {"parts": [{"text": "Covered"}]},
                "logprobsResult": {"topCandidates": [
                    {"candidates": [{"token": "Covered", "logProbability": math.log(0.7)},
                                    {"token": "Not", "logProbability": math.log(0.2)}]},
                ]},
            }]}

        ft.add("POST", ":generateContent", respond)
        client = GeminiClient("k", transport=ft, retry_policy=fast_retry())
        out = str(tmp_path / "gemini.xlsx")
        df = run_gemini_perturbation_sweep(
            client, "gemini-2.5-pro", scenarios, out,
            max_workers=3, checkpoint_every=2,
        )
        assert list(df.columns) == PERTURBATION_COLUMNS
        assert len(df) == 5
        assert df["Token_1_Prob"].iloc[0] == pytest.approx(0.7)
        assert df["Token_2_Prob"].iloc[0] == pytest.approx(0.2)
        assert df["Confidence Value"].iloc[0] == 85
        from llm_interpretation_replication_tpu.scoring.confidence import (
            weighted_confidence_digits,
        )

        expected_wc = weighted_confidence_digits([
            [("8", math.log(0.6)), ("9", math.log(0.3))],
            [("5", math.log(0.9))],
        ])
        assert expected_wc is not None
        assert df["Weighted Confidence"].iloc[0] == pytest.approx(expected_wc)
        calls_before = len(ft.calls)
        df2 = run_gemini_perturbation_sweep(
            client, "gemini-2.5-pro", scenarios, out, max_workers=3,
        )
        assert len(ft.calls) == calls_before     # resume: no new API calls
        assert len(df2) == 5
        # a different model re-evaluates
        run_gemini_perturbation_sweep(client, "gemini-2.0-flash", scenarios, out,
                                      max_workers=2)
        assert len(ft.calls) > calls_before


class TestGptPerturbationSweep:
    """Serial GPT sync sweep (perturb_prompts_gpt.py:86-233): blank-line
    prompt join, first-token top-20 scan, single-token weighted confidence,
    checkpointed workbook append with resume-by-triple (the discipline the
    Claude/Gemini sync legs share)."""

    def _scenarios(self, n=5):
        return [{
            "original_main": "Scenario text one.",
            "response_format": "Answer 'Covered' or 'Not'.",
            "target_tokens": ["Covered", "Not"],
            "confidence_format": "Confidence 0-100?",
            "rephrasings": [f"Rephrase {i}." for i in range(n)],
        }]

    def _client(self):
        ft = FakeTransport()

        def respond(call):
            content = call["json"]["messages"][0]["content"]
            if "Confidence" in content:
                return 200, {"choices": [{
                    "message": {"content": "85"},
                    "logprobs": {"content": [
                        {"token": "8", "top_logprobs": [
                            {"token": "8", "logprob": math.log(0.6)},
                            {"token": "9", "logprob": math.log(0.3)},
                        ]},
                        {"token": "5", "top_logprobs": [
                            {"token": "5", "logprob": math.log(0.9)},
                        ]},
                    ]},
                }], "usage": {"prompt_tokens": 50, "completion_tokens": 2}}
            return 200, {"choices": [{
                "message": {"content": "Covered"},
                "logprobs": {"content": [
                    {"token": "Covered", "top_logprobs": [
                        {"token": "Covered", "logprob": math.log(0.7)},
                        {"token": "Not", "logprob": math.log(0.2)},
                    ]},
                ]},
            }], "usage": {"prompt_tokens": 50, "completion_tokens": 1}}

        ft.add("POST", "/chat/completions", respond)
        return OpenAIClient("k", transport=ft, retry_policy=fast_retry()), ft

    def test_serial_sweep_checkpoints_and_resume(self, tmp_path):
        from llm_interpretation_replication_tpu.sweeps.api_perturbation import (
            run_gpt_perturbation_sweep,
        )
        from llm_interpretation_replication_tpu.sweeps.writers import (
            PERTURBATION_COLUMNS,
        )

        client, ft = self._client()
        out = str(tmp_path / "gpt.xlsx")
        slept = []
        df = run_gpt_perturbation_sweep(
            client, "gpt-4-0125-preview", self._scenarios(), out,
            checkpoint_every=2, sleep=slept.append,
        )
        assert list(df.columns) == PERTURBATION_COLUMNS
        assert len(df) == 5
        assert df["Token_1_Prob"].iloc[0] == pytest.approx(0.7)
        assert df["Token_2_Prob"].iloc[0] == pytest.approx(0.2)
        assert df["Odds_Ratio"].iloc[0] == pytest.approx(0.7 / 0.2)
        assert df["Confidence Value"].iloc[0] == 85
        # reference weighted confidence: single-token positions (:47-85)
        from llm_interpretation_replication_tpu.scoring.confidence import (
            weighted_confidence_single_tokens,
        )

        expected = weighted_confidence_single_tokens([
            [("8", math.log(0.6)), ("9", math.log(0.3))],
            [("5", math.log(0.9))],
        ])
        assert df["Weighted Confidence"].iloc[0] == pytest.approx(expected)
        # blank-line prompt join (perturb_prompts_gpt.py:156-157)
        first = ft.calls[0]["json"]["messages"][0]["content"]
        assert first == "Rephrase 0.\n\nAnswer 'Covered' or 'Not'."
        # reference rate-limit sleep between pairs (:190)
        assert slept == [0.5] * 5

        # resume: same model re-run makes NO new API calls
        calls_before = len(ft.calls)
        df2 = run_gpt_perturbation_sweep(
            client, "gpt-4-0125-preview", self._scenarios(), out,
            sleep=lambda _s: None,
        )
        assert len(ft.calls) == calls_before
        assert len(df2) == 5
        # a different model re-evaluates into the same workbook
        run_gpt_perturbation_sweep(client, "gpt-4o", self._scenarios(), out,
                                   sleep=lambda _s: None)
        assert len(ft.calls) > calls_before

    def test_reasoning_model_rejected(self, tmp_path):
        """o*/gpt-5* return no logprobs on the sync API — the sweep must
        refuse instead of checkpointing Token_i_Prob=0 garbage (the batch
        pipeline has the reasoning-model modes, perturb_prompts.py:46-48)."""
        from llm_interpretation_replication_tpu.sweeps.api_perturbation import (
            run_gpt_perturbation_sweep,
        )

        client, _ = self._client()
        with pytest.raises(ValueError, match="reasoning model"):
            run_gpt_perturbation_sweep(
                client, "o3", self._scenarios(1), str(tmp_path / "gpt.xlsx"),
                sleep=lambda _s: None,
            )

    def test_all_failures_raise(self, tmp_path):
        from llm_interpretation_replication_tpu.sweeps.api_perturbation import (
            run_gpt_perturbation_sweep,
        )

        ft = FakeTransport()
        ft.add("POST", "/chat/completions", lambda c: (500, {"error": "boom"}))
        client = OpenAIClient("k", transport=ft, retry_policy=fast_retry())
        with pytest.raises(RuntimeError, match="every evaluation failed"):
            run_gpt_perturbation_sweep(
                client, "gpt-4-0125-preview", self._scenarios(2),
                str(tmp_path / "gpt.xlsx"), sleep=lambda _s: None,
            )
