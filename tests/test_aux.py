"""Auxiliary-subsystem tests: telemetry, profiling, demographics (SURVEY.md
§5 rows previously only smoke/drive-tested)."""

import os

import numpy as np
import pytest

REF = "/root/reference"
DEMO = [f"{REF}/data/demographic_data.csv", f"{REF}/data/demographic_data_part_2.csv"]


class TestTelemetry:
    def test_memory_usage_string(self):
        from llm_interpretation_replication_tpu.utils.telemetry import get_memory_usage

        s = get_memory_usage()
        assert "RAM" in s and "GB" in s  # reference format: RAM/disk telemetry

    def test_device_memory_summary_no_crash(self):
        from llm_interpretation_replication_tpu.utils.telemetry import (
            device_memory_summary,
        )

        out = device_memory_summary()
        assert out is None or isinstance(out, str)

    def test_clear_host_memory(self):
        from llm_interpretation_replication_tpu.utils.telemetry import clear_host_memory

        clear_host_memory()  # triple-gc path (reference clear_memory)


class TestProfiling:
    def test_trace_writes_profile(self, tmp_path):
        import jax.numpy as jnp

        from llm_interpretation_replication_tpu.utils.profiling import trace

        with trace(str(tmp_path), enabled=True):
            jnp.ones((8, 8)) @ jnp.ones((8, 8))
        found = any("trace" in f or f.endswith(".pb") or f.endswith(".json.gz")
                    for _, _, fs in os.walk(tmp_path) for f in fs)
        assert found, "jax.profiler trace produced no artifacts"

    def test_top_device_ops_finds_the_matmul(self, tmp_path):
        """The headless op-profile reader (no TensorBoard in the image):
        tracing a jit'd matmul must surface dot_general among the top ops —
        the workflow that located the round-3 decode relayout loop."""
        import jax
        import jax.numpy as jnp

        from llm_interpretation_replication_tpu.utils.profiling import (
            top_device_ops,
            trace,
        )

        f = jax.jit(lambda x: (x @ x.T).sum())
        x = jnp.ones((256, 256))
        f(x).block_until_ready()                    # compile outside the trace
        with trace(str(tmp_path), enabled=True):
            f(x).block_until_ready()
        top = top_device_ops(str(tmp_path), top_n=10)
        assert top, "no device ops parsed from the trace"
        assert any("dot" in name for name, _ in top), top
        assert all(ms >= 0 for _, ms in top)

    def test_trace_disabled_noop(self, tmp_path):
        from llm_interpretation_replication_tpu.utils.profiling import trace

        with trace(str(tmp_path / "off"), enabled=False):
            pass
        assert not os.path.exists(tmp_path / "off")

    def test_annotate(self):
        import jax.numpy as jnp

        from llm_interpretation_replication_tpu.utils.profiling import annotate

        with annotate("step"):
            x = jnp.arange(4).sum()
        assert int(x) == 6


@pytest.mark.skipif(not os.path.exists(DEMO[0]), reason="reference data not mounted")
class TestDemographicsRealData:
    def test_recruited_count_matches_paper(self):
        """Paper: 1,003 recruited via Prolific (main.tex:341-349).  The raw
        exports hold 1,009 submissions incl. returned/timed-out rows."""
        from llm_interpretation_replication_tpu.survey.demographics import (
            load_demographics,
        )

        df = load_demographics(DEMO)
        assert len(df) == 1009
        approved = df[df["Status"] == "APPROVED"]
        assert 990 <= len(approved) <= 1009

    def test_categorical_and_age_summaries(self):
        from llm_interpretation_replication_tpu.survey.demographics import (
            load_demographics,
            summarize_age,
            summarize_categorical,
        )

        df = load_demographics(DEMO)
        sex = summarize_categorical(df, "Sex")
        assert set(sex["Sex"]) >= {"Male", "Female"}
        assert sex["count"].sum() == len(df)
        assert abs(sex["percent"].sum() - 100.0) < 1e-9
        age = summarize_age(df)
        assert 18 <= age["median"] <= 80 and age["n"] > 900

    def test_latex_table_renders(self):
        from llm_interpretation_replication_tpu.survey.demographics import (
            demographics_latex_table,
            load_demographics,
        )

        df = load_demographics(DEMO)
        tex = demographics_latex_table(df, ["Sex", "Employment status"])
        assert tex.startswith("\\begin{tabular}") and tex.endswith("\\end{tabular}")
        assert "\\textbf{Sex}" in tex and "Male" in tex


class TestDistributedBootstrap:
    def test_noop_outside_cluster(self, monkeypatch):
        """Single-host: no coordinator env vars -> returns False, no init
        attempt (the CLI calls this unconditionally on the TPU path)."""
        from llm_interpretation_replication_tpu.parallel import (
            initialize_distributed,
        )

        for var in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES", "JAX_PROCESS_ID"):
            monkeypatch.delenv(var, raising=False)
        assert initialize_distributed() is False
