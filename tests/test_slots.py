"""Decode-then-repack slot-level continuous batching (ISSUE 14,
``-m slots``, tier-1).

Pins the slot allocator's contracts (runtime/slots.py, PARITY.md
"Decode-then-repack"):

- **repack-on == repack-off row parity on all three consumers** — the
  ``_Phase2Pool`` legs (confidence + binary), the packed autoregressive
  demo decode, and the serve scheduler's slot-admission path: tokens,
  parses, verdicts and position-0 fields identical, multi-chunk score
  fields within the chunked-prefill fp32 class; the legacy whole-flush
  schedule stays reachable via ``slot_repack=False`` /
  ``SchedulerConfig.slot_admission=False``.
- **occupancy gain is measured, not asserted**: a synthetic
  staggered-retirement run shows the ``occupancy`` block's slot-idle
  fraction STRICTLY lower with repack than the whole-flush
  counterfactual, with refills actually recorded.
- **retirement is repack-invariant** (satellite): a row's
  ``first_int_stable`` retirement step and parse are identical whether
  it decodes in a fresh batch, a refilled slot, or the legacy flush.
- Satellites: K-head persistence beside snapshots (load-or-redistill
  key), ``slot_*`` telemetry in the PR-12 labeled convention +
  Prometheus export, bench-diff ``occupancy`` alignment with slot-idle
  as a lower-is-better row, refill-model plan pricing.
"""

import dataclasses
import itertools
import json
import os

import numpy as np
import pytest

from test_runtime import _tiny_engine

from llm_interpretation_replication_tpu.runtime import engine as emod
from llm_interpretation_replication_tpu.runtime import slots as slots_mod
from llm_interpretation_replication_tpu.runtime.engine import ScoringEngine
from llm_interpretation_replication_tpu.scoring.confidence import (
    extract_first_int,
)
from llm_interpretation_replication_tpu.utils import telemetry

pytestmark = pytest.mark.slots

EXACT_FIELDS = ("first_token_yes_prob", "first_token_no_prob",
                "first_token_relative_prob")
PROB_FIELDS = ("yes_prob", "no_prob", "relative_prob")

CONF_PROMPTS = [f"How confident are you about rule {i}, 0-100?"
                for i in range(16)]
BIN_PROMPTS = [f"Is item {i} a vehicle? Answer Yes or No."
               for i in range(12)]


def _clone(eng, tok, **kw):
    return ScoringEngine(eng.family, eng.cfg, eng.params, tok,
                         engine_config=dataclasses.replace(eng.ecfg, **kw))


@pytest.fixture(scope="module")
def tiny():
    eng, _, tok = _tiny_engine(batch_size=8)
    return eng, tok


class TestPoolParity:
    def test_confidence_leg_repack_matches_whole_flush(self, tiny):
        """Acceptance: repack-on vs repack-off on the confidence pool —
        weighted confidence, first-int parse, completion and position-0
        fields identical; scan fields within the chunked class."""
        eng, tok = tiny
        telemetry.clear_counters()
        rows_r = _clone(eng, tok).score_prompts(
            CONF_PROMPTS, with_confidence=True, max_new_tokens=10)
        c = telemetry.counters()
        assert c.get("slot_rows", 0) >= len(CONF_PROMPTS)
        # satellite: labeled twin rides the PR-12 convention from day one
        assert c.get("slot_rows|leg=confidence,workload=engine", 0) >= \
            len(CONF_PROMPTS)
        rows_f = _clone(eng, tok, slot_repack=False).score_prompts(
            CONF_PROMPTS, with_confidence=True, max_new_tokens=10)
        for a, b in zip(rows_r, rows_f):
            assert a["success"] and b["success"]
            assert a["weighted_confidence"] == b["weighted_confidence"]
            assert a["completion"] == b["completion"]
            assert extract_first_int(a["completion"]) == \
                extract_first_int(b["completion"])
            for f in EXACT_FIELDS:
                assert a[f] == b[f], f
            for f in PROB_FIELDS:
                np.testing.assert_allclose(a[f], b[f], rtol=2e-5,
                                           atol=1e-9, err_msg=f)

    def test_binary_leg_repack_matches_whole_flush(self, tiny):
        """The binary undecided-row pool through the ring: verdicts and
        position-0 fields identical, scan probabilities within the
        chunked class (the ring decodes 5+5 chunks with per-row early
        exit; the legacy flush decodes one async 10-step chunk)."""
        eng, tok = tiny
        telemetry.clear_counters()
        rows_r = _clone(eng, tok, decode_completions=False).score_prompts(
            BIN_PROMPTS)
        assert telemetry.counter(
            "slot_rows|leg=binary,workload=engine") > 0
        rows_f = _clone(eng, tok, decode_completions=False,
                        slot_repack=False).score_prompts(BIN_PROMPTS)
        for a, b in zip(rows_r, rows_f):
            assert a["success"] and b["success"]
            assert a["scan_found"] == b["scan_found"]
            for f in EXACT_FIELDS:
                assert a[f] == b[f], f
            for f in PROB_FIELDS + ("odds_ratio",):
                np.testing.assert_allclose(a[f], b[f], rtol=2e-5,
                                           atol=1e-9, err_msg=f)

    def test_ring_composition_never_changes_a_row(self, tiny):
        """Ring capacity (pool target) changes batch composition and
        refill timing — emitted confidence rows must not move (the
        pooled-confidence bit-reproducibility rule, re-pinned on the
        ring)."""
        eng, tok = tiny
        small = _clone(eng, tok, phase2_pool_target=4).score_prompts(
            CONF_PROMPTS[:9], with_confidence=True, max_new_tokens=10)
        big = _clone(eng, tok, batch_size=16).score_prompts(
            CONF_PROMPTS[:9], with_confidence=True, max_new_tokens=10)
        for a, b in zip(small, big):
            assert a["weighted_confidence"] == b["weighted_confidence"]
            assert a["completion"] == b["completion"]


class TestStaggeredOccupancy:
    def _staggered(self, eng, tok, repack: bool):
        """Score with a deterministic staggered retirement cadence and
        a 4-lane ring; returns (rows, occupancy block, counters)."""
        counter = itertools.count()
        orig = emod._Phase2Pool._conf_retired_at
        emod._Phase2Pool._conf_retired_at = \
            lambda self, toks, k: next(counter) % 4 == 0
        telemetry.clear_counters()
        try:
            e = _clone(eng, tok, batch_size=16, phase2_pool_target=4,
                       slot_repack=repack)
            rows = e.score_prompts(CONF_PROMPTS, with_confidence=True,
                                   max_new_tokens=10)
            occ = e.occupancy_report()
        finally:
            emod._Phase2Pool._conf_retired_at = orig
        return rows, occ, telemetry.counters()

    def test_staggered_retirement_idle_fraction_strictly_lower(self, tiny):
        """Acceptance: the synthetic staggered-retirement case — rows
        retire at different steps, vacated lanes REFILL mid-decode, and
        the occupancy block shows slot-idle fraction strictly lower
        with repack than the whole-flush counterfactual."""
        eng, tok = tiny
        rows, occ, c = self._staggered(eng, tok, repack=True)
        assert all(r["success"] for r in rows)
        assert occ is not None and occ["rows"] == len(CONF_PROMPTS)
        assert c.get("slot_refills", 0) > 0, "no lane ever refilled"
        assert occ["refills"] > 0
        assert occ["slot_idle_frac"] is not None
        assert occ["slot_idle_frac_no_repack"] is not None
        assert occ["slot_idle_frac"] < occ["slot_idle_frac_no_repack"]
        # the legacy counters keep firing under repack (same semantics)
        assert c.get("conf_steps_saved", 0) > 0
        assert c.get("completion_cache_bytes_freed", 0) > 0
        assert c.get("pooled_conf_retired_rows", 0) > 0

    def test_legacy_path_reachable_and_ring_counters_silent(self, tiny):
        """Acceptance: ``slot_repack=False`` keeps the whole-flush
        schedule — no slot_* counters fire, no occupancy block."""
        eng, tok = tiny
        rows, occ, c = self._staggered(eng, tok, repack=False)
        assert all(r["success"] for r in rows)
        assert occ is None
        assert c.get("slot_rows", 0) == 0
        assert c.get("slot_refills", 0) == 0


class TestRetirementUnderRepack:
    """Satellite: a row's retirement step and parse are a pure function
    of its own tokens — identical in a fresh batch, a refilled slot, and
    the legacy flush path (append-proof style, test_pooled_conf.py)."""

    def test_retire_step_identical_across_paths(self, tiny):
        eng, tok = tiny
        seen = {}
        orig = emod._Phase2Pool._conf_retired_at

        def spy(self, toks, k):
            out = orig(self, toks, k)
            if out:
                key = tuple(int(t) for t in np.asarray(toks[:k]))
                seen.setdefault(key, k)
                assert seen[key] == k     # same prefix -> same r*
            return out

        emod._Phase2Pool._conf_retired_at = spy
        try:
            for cfg_kw in ({"slot_repack": True},
                           {"slot_repack": True, "phase2_pool_target": 4},
                           {"slot_repack": False}):
                _clone(eng, tok, **cfg_kw).score_prompts(
                    CONF_PROMPTS[:9], with_confidence=True,
                    max_new_tokens=10)
        finally:
            emod._Phase2Pool._conf_retired_at = orig

    def test_parse_identical_fresh_vs_refilled_vs_flush(self, tiny):
        """End-to-end: per-row parses emitted by a fresh ring (capacity
        >= rows, no refills), a refilled ring (capacity 4 — rows 5+ run
        in refilled lanes), and the legacy flush are identical."""
        eng, tok = tiny
        fresh = _clone(eng, tok, batch_size=16).score_prompts(
            CONF_PROMPTS, with_confidence=True, max_new_tokens=10)
        refilled = _clone(eng, tok, batch_size=16,
                          phase2_pool_target=4).score_prompts(
            CONF_PROMPTS, with_confidence=True, max_new_tokens=10)
        flush = _clone(eng, tok, batch_size=16,
                       slot_repack=False).score_prompts(
            CONF_PROMPTS, with_confidence=True, max_new_tokens=10)
        for a, b, c in zip(fresh, refilled, flush):
            pa = extract_first_int(a["completion"])
            assert pa == extract_first_int(b["completion"])
            assert pa == extract_first_int(c["completion"])
            assert a["weighted_confidence"] == b["weighted_confidence"] \
                == c["weighted_confidence"]
            assert a["completion"] == b["completion"] == c["completion"]


class TestPackedDemos:
    def test_autoregressive_demos_repack_parity(self, tiny):
        """Packed consumer: decode-then-repack autoregressive demos are
        identical texts whether slots refill mid-decode or run
        whole-flush; the last question of each pack stays demo-free."""
        from llm_interpretation_replication_tpu.scoring import packed

        eng, tok = tiny
        qs = [f"Q{i}: is a tent a dwelling? Answer Yes or No."
              for i in range(8)]
        telemetry.clear_counters()
        e_on = _clone(eng, tok, phase2_pool_target=2,
                      buckets=(32, 64, 128, 256))
        packs_on, demos_on = packed.autoregressive_demos(
            e_on, qs, packing=4, max_demo_tokens=6)
        c = telemetry.counters()
        assert c.get("slot_rows|leg=packed,workload=packed", 0) > 0
        packs_off, demos_off = packed.autoregressive_demos(
            _clone(eng, tok, phase2_pool_target=2,
                   buckets=(32, 64, 128, 256)), qs, packing=4,
            max_demo_tokens=6, repack=False)
        assert demos_on == demos_off
        assert packs_on == packs_off
        assert len(demos_on) == 8
        assert demos_on[3] is None and demos_on[7] is None
        assert all(d is not None for d in demos_on[:3])
        # the packs feed score_packed directly (build_packs layout)
        rows = e_on.score_packed(packs_on, targets=("Yes", "No"))
        assert len(rows) == 8 and all(r["success"] for r in rows)

    def test_demo_decode_occupancy_recorded(self, tiny):
        eng, tok = tiny
        e = _clone(eng, tok, phase2_pool_target=2,
                   buckets=(32, 64, 128, 256))
        e.packed_autoregressive_demos(
            [f"Q{i}?" for i in range(6)], packing=3, max_demo_tokens=4)
        occ = e.occupancy_report()
        assert occ is not None and occ["rows"] >= 4


class TestServeSlotAdmission:
    def _scheduler(self, eng, tok, slot_admission, max_batch=2):
        from llm_interpretation_replication_tpu.serve import (
            Scheduler,
            SchedulerConfig,
        )

        engine = _clone(eng, tok, decode_completions=False)
        return engine, Scheduler(engine, SchedulerConfig(
            max_batch=max_batch, max_wait_s=0.01,
            slot_admission=slot_admission))

    def test_mid_decode_admission_and_parity(self, tiny):
        """Acceptance (serve consumer): requests queued beyond the first
        micro-batch are admitted into vacated slots MID-DECODE
        (serve_slot_admitted fires) and every answered row matches the
        whole-flush scheduler's within the documented class."""
        from llm_interpretation_replication_tpu.serve import ScoreRequest

        eng, tok = tiny
        telemetry.clear_counters()
        engine, sched = self._scheduler(eng, tok, slot_admission=True)
        futures = [sched.submit(ScoreRequest(prompt=p))
                   for p in BIN_PROMPTS]     # queued BEFORE the loop runs
        with sched:
            rows = [f.result(timeout=300) for f in futures]
        assert telemetry.counter("serve_slot_admitted") > 0
        assert telemetry.counter(
            "slot_admitted|leg=binary,workload=serve") > 0
        _, sched_off = self._scheduler(eng, tok, slot_admission=False)
        futures = [sched_off.submit(ScoreRequest(prompt=p))
                   for p in BIN_PROMPTS]
        with sched_off:
            rows_off = [f.result(timeout=300) for f in futures]
        for a, b in zip(rows, rows_off):
            assert a["success"] and b["success"]
            assert a["scan_found"] == b["scan_found"]
            for f in PROB_FIELDS:
                np.testing.assert_allclose(a[f], b[f], rtol=2e-5,
                                           atol=1e-9, err_msg=f)

    def test_slotted_matches_offline_scoring(self, tiny):
        """Served slotted rows vs offline ``score_prompts`` on the same
        engine configuration (the replay-harness comparison, at the
        ring's documented tolerance class)."""
        from llm_interpretation_replication_tpu.serve import ScoreRequest

        eng, tok = tiny
        engine, sched = self._scheduler(eng, tok, slot_admission=True,
                                        max_batch=4)
        futures = [sched.submit(ScoreRequest(prompt=p))
                   for p in BIN_PROMPTS[:8]]
        with sched:
            rows = [f.result(timeout=300) for f in futures]
        offline = _clone(eng, tok, decode_completions=False).score_prompts(
            BIN_PROMPTS[:8])
        for a, b in zip(rows, offline):
            assert a["scan_found"] == b["scan_found"]
            for f in PROB_FIELDS + EXACT_FIELDS:
                np.testing.assert_allclose(a[f], b[f], rtol=2e-5,
                                           atol=1e-9, err_msg=f)

    def test_slot_admission_default_on_with_escape_hatch(self):
        """Satellite (ISSUE 20): slot admission is the serve DEFAULT now
        that the replay harness pinned its parity; ``--no-slot-admission``
        is the escape hatch on every serving entry point."""
        from llm_interpretation_replication_tpu.serve import (
            SchedulerConfig,
        )

        assert SchedulerConfig().slot_admission is True
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        for rel in ("bench.py",
                    os.path.join("llm_interpretation_replication_tpu",
                                 "__main__.py")):
            src = open(os.path.join(repo_root, rel),
                       encoding="utf-8").read()
            assert '"--no-slot-admission"' in src, rel
        cli = open(os.path.join(
            repo_root, "llm_interpretation_replication_tpu", "serve",
            "cli.py"), encoding="utf-8").read()
        assert 'getattr(args, "no_slot_admission", False)' in cli

    def test_confidence_requests_keep_coalescer_path(self, tiny):
        """Eligibility guard: confidence requests never route slotted
        (their replay contract is the pooled-confidence one), even with
        the knob on."""
        from llm_interpretation_replication_tpu.serve import ScoreRequest

        eng, tok = tiny
        telemetry.clear_counters()
        engine, sched = self._scheduler(eng, tok, slot_admission=True)
        with sched:
            row = sched.submit(ScoreRequest(
                prompt=CONF_PROMPTS[0], with_confidence=True,
                max_new_tokens=10)).result(timeout=300)
        assert row["success"] and "weighted_confidence" in row
        assert telemetry.counter("serve_slot_admitted") == 0


class TestKHeadPersistence:
    """Satellite: distilled K-heads persist beside snapshots keyed on
    (snapshot fingerprint, decode_k); load-or-redistill on construction."""

    def _snapshot_dir(self, tmp_path, seed=b"weights-v1"):
        d = tmp_path / "snap"
        d.mkdir(exist_ok=True)
        (d / "config.json").write_text(json.dumps({"model_type": "test"}))
        (d / "model.safetensors").write_bytes(seed)
        return str(d)

    def test_round_trip_and_key_misses(self, tmp_path, tiny):
        from llm_interpretation_replication_tpu.models import decoder as dmod
        from llm_interpretation_replication_tpu.runtime import loader

        eng, _ = tiny
        path = self._snapshot_dir(tmp_path)
        head = dmod.init_k_head(eng.cfg, k=3, seed=7)
        out = loader.save_k_head(path, head, decode_k=3)
        assert os.path.basename(out) == loader.K_HEAD_FILENAME
        loaded = loader.load_k_head(path, decode_k=3)
        assert loaded is not None
        np.testing.assert_allclose(np.asarray(loaded["w"], np.float32),
                                   np.asarray(head["w"], np.float32),
                                   rtol=1e-6)
        # decode_k mismatch -> miss (re-distill)
        assert loader.load_k_head(path, decode_k=4) is None
        # weight change moves the fingerprint -> miss
        with open(os.path.join(path, "model.safetensors"), "wb") as f:
            f.write(b"weights-v2-longer")
        assert loader.load_k_head(path, decode_k=3) is None

    def test_attach_on_construction(self, tmp_path, tiny):
        from llm_interpretation_replication_tpu.models import decoder as dmod
        from llm_interpretation_replication_tpu.runtime import loader

        eng, tok = tiny
        path = self._snapshot_dir(tmp_path)
        e = _clone(eng, tok, decode_k=3)
        assert not loader.attach_k_head(e, path)      # nothing saved yet
        assert e.k_head is None
        loader.save_k_head(path, dmod.init_k_head(e.cfg, k=3), decode_k=3)
        telemetry.clear_counters()
        assert loader.attach_k_head(e, path)
        assert e.k_head is not None
        assert telemetry.counter("k_head_loaded") == 1
        # decode_k=1 engines never touch the file
        assert not loader.attach_k_head(_clone(eng, tok), path)

    def test_torn_file_is_a_miss(self, tmp_path):
        from llm_interpretation_replication_tpu.runtime import loader

        path = self._snapshot_dir(tmp_path)
        with open(os.path.join(path, loader.K_HEAD_FILENAME), "wb") as f:
            f.write(b"not an npz")
        assert loader.load_k_head(path, decode_k=3) is None


class TestTelemetryAndExport:
    def test_slot_counters_export_as_labeled_prometheus_series(self, tiny):
        """Satellite: slot_* counters ride the ``name|k=v`` convention,
        so the exporter emits ONE family with {leg, workload} label sets
        — no second migration needed."""
        from llm_interpretation_replication_tpu.obs import (
            metrics as obs_metrics,
        )

        eng, tok = tiny
        telemetry.clear_counters()
        _clone(eng, tok).score_prompts(CONF_PROMPTS[:6],
                                       with_confidence=True,
                                       max_new_tokens=10)
        obs_metrics.get_registry().sample()
        text = obs_metrics.prometheus_text()
        labeled = [l for l in text.splitlines()
                   if l.startswith("llm_interp_slot_rows{")]
        assert any('leg="confidence"' in l and 'workload="engine"' in l
                   for l in labeled), text[:2000]


class TestBenchDiffOccupancy:
    def _rec(self, idle, before=0.5, refills=3, stalls=0):
        return {"metric": "rows/sec x", "value": 10.0, "unit": "rows/sec",
                "occupancy": {"capacity": 320, "rows": 100,
                              "slot_steps": 1000, "live_steps": 800,
                              "slot_idle_frac": idle,
                              "slot_idle_frac_no_repack": before,
                              "refills": refills, "repacks": 5,
                              "compactions": 1, "repack_stalls": stalls}}

    def test_occupancy_rows_flatten_and_regress(self):
        """Satellite: the occupancy block aligns across records with
        slot-idle fraction as a LOWER-is-better verdict row."""
        from llm_interpretation_replication_tpu.obs import benchdiff

        flat = benchdiff.flatten_metrics(self._rec(0.2))
        assert flat["slot idle fraction [idle-frac]"]["value"] == 0.2
        assert "slot idle fraction (no-repack counterfactual)" in flat
        diff = benchdiff.diff_records(
            [dict(self._rec(0.2), label="r1"),
             dict(self._rec(0.4), label="r2")], threshold_pct=5.0)
        row = next(r for r in diff["metrics"]
                   if r["key"] == "slot idle fraction [idle-frac]")
        assert row["verdict"] == "REGRESSION"       # idle GREW = worse
        diff2 = benchdiff.diff_records(
            [dict(self._rec(0.4), label="r1"),
             dict(self._rec(0.2), label="r2")], threshold_pct=5.0)
        row2 = next(r for r in diff2["metrics"]
                    if r["key"] == "slot idle fraction [idle-frac]")
        assert row2["verdict"] == "improved"

    def test_nested_secondary_occupancy_flattens(self):
        from llm_interpretation_replication_tpu.obs import benchdiff

        rec = {"metric": "prompts/sec y", "value": 5.0,
               "unit": "prompts/sec",
               "secondary": [dict(self._rec(0.3),
                                  metric="full-study rows/sec",
                                  unit="rows/sec")]}
        flat = benchdiff.flatten_metrics(rec)
        assert flat["slot idle fraction [idle-frac]"]["value"] == 0.3


class TestPlanRefillModel:
    def test_refill_pricing_is_cheaper_and_opt_in(self):
        """The refill model prices the confidence pool below the
        all-or-nothing flush accumulation (capacity-shaped residency),
        and the default keeps every legacy pin byte-identical."""
        from llm_interpretation_replication_tpu.models.config import (
            BENCH_GEOMETRIES,
            DecoderConfig,
        )
        from llm_interpretation_replication_tpu.runtime import plan

        f7 = DecoderConfig(**BENCH_GEOMETRIES["falcon-7b"])
        legacy = plan.pooled_confidence_extra_bytes(f7, 320, 256,
                                                   kv_dtype="int8")
        refill = plan.slot_refill_pool_bytes(f7, 320, 320, 256,
                                             kv_dtype="int8")
        assert refill < legacy
        base = plan.full_study_need_terms(
            f7, plan.weight_bytes(f7, "int8"), "xla", 320, 256,
            kv_dtype="int8", prefill_chunk=128, pooled_confidence=True)
        repack = plan.full_study_need_terms(
            f7, plan.weight_bytes(f7, "int8"), "xla", 320, 256,
            kv_dtype="int8", prefill_chunk=128, pooled_confidence=True,
            slot_repack=True)
        assert base["conf_pool"] == plan.pooled_confidence_extra_bytes(
            f7, 320, 256, kv_dtype="int8")       # default untouched
        assert repack["conf_pool"] == refill
        assert sum(repack.values()) < sum(base.values())

    def test_search_threads_slot_repack(self):
        from llm_interpretation_replication_tpu.models.config import (
            BENCH_GEOMETRIES,
            DecoderConfig,
        )
        from llm_interpretation_replication_tpu.runtime import plan_search

        f7 = DecoderConfig(**BENCH_GEOMETRIES["falcon-7b"])
        ranked_r = plan_search.search_plans(
            f7, "int8", n_devices=1, workload="full", slot_repack=True)
        ranked_l = plan_search.search_plans(
            f7, "int8", n_devices=1, workload="full")
        fits_r = sum(1 for c in ranked_r if c.fits)
        fits_l = sum(1 for c in ranked_l if c.fits)
        assert fits_r >= fits_l       # cheaper pool can only admit more


class TestRingUnit:
    def test_occupancy_counterfactual_math(self):
        s = slots_mod.OccupancyStats(capacity=4)
        s.capacity_steps, s.live_steps = 100, 80
        s.row_steps = [10, 5, 5, 10, 3, 3, 3, 3]
        assert s.idle_fraction() == pytest.approx(0.2)
        # flushes: [10,5,5,10] dur 10 -> idle 10; [3,3,3,3] dur 3 -> 0
        assert s.no_repack_idle_fraction() == pytest.approx(10 / 52)
        merged = slots_mod.merge_occupancy(
            [s, slots_mod.OccupancyStats(capacity=2)])
        assert merged.rows == s.rows and merged.capacity == 4

    def test_strict_mode_clean(self, tiny):
        """Strict-mode transfer guard holds through the ring (every
        chunk fetch happens inside the sanctioned consume scope)."""
        from llm_interpretation_replication_tpu.runtime import strict

        eng, tok = tiny
        e = _clone(eng, tok, kv_dtype="int8")
        strict.activate()
        try:
            snap = telemetry.counters()
            rows = e.score_prompts(CONF_PROMPTS[:6], with_confidence=True,
                                   max_new_tokens=10)
            delta = telemetry.counters_since(snap)
            assert delta.get(strict.BLOCKED_COUNTER, 0) == 0
            assert delta.get("slot_rows", 0) >= 6
            assert all(r["success"] for r in rows)
        finally:
            strict.deactivate()


class TestBenchIntegration:
    def test_bench_sweep_full_occupancy_block_end_to_end(self, tmp_path):
        """The whole bench wiring, executed: a tiny --mode sweep-full run
        with a 4-lane pool lands the ``occupancy`` block (slot-idle
        fraction + whole-flush counterfactual + refill/repack counts)
        and the slot counters in the record's context."""
        import argparse

        import jax
        import jax.numpy as jnp

        import bench
        from llm_interpretation_replication_tpu.models.config import (
            DecoderConfig,
        )
        from test_kdecode import TINY

        scenarios = [{
            "original_main": "Is soup a beverage?",
            "response_format": "Answer only 'Yes' or 'No'.",
            "confidence_format": "How confident are you (0-100)?",
            "target_tokens": ["Yes", "No"],
            "rephrasings": [f"Is soup number {i} a beverage?"
                            for i in range(6)],
        }]
        corpus = tmp_path / "perturbations.json"
        corpus.write_text(json.dumps(scenarios))
        cfg = DecoderConfig(**dict(
            TINY, parallel_residual=True, qkv_bias=True, out_bias=True,
            mlp_bias=True))
        params = bench.init_params(cfg, jax.random.PRNGKey(0),
                                   jnp.float32)
        args = argparse.Namespace(
            model="tiny", quant="none", sweep_batch=8, sweep_rows=0,
            sweep_repeats=1, pool_target=4, pipeline_depth=2,
            checkpoint_every=100, sweep_out=str(tmp_path / "out.xlsx"),
            decided_frac=0.9, perturbations=str(corpus),
            mode="sweep-full", warmup=False, fuse_prefix=True,
            eos_mode="none", eos_brackets=False, decode_k=1)
        rps, rate, _ = bench.run_sweep_full_mode(args, cfg, params)
        assert rps > 0 and np.isfinite(rps)
        record = bench._full_study_record(args, rps, rate)
        occ = record["occupancy"]
        assert occ["rows"] == 6 and occ["capacity"] == 4
        assert occ["slot_idle_frac"] is not None
        assert occ["slot_idle_frac_no_repack"] is not None
        assert record["context"]["slot_repack"] is True
        assert record["context"]["slot_rows"] == 6
        json.dumps(record)      # record-serializable
        # bench-diff aligns the executed record's occupancy rows
        from llm_interpretation_replication_tpu.obs import benchdiff

        flat = benchdiff.flatten_metrics(record)
        assert "slot idle fraction [idle-frac]" in flat

    def test_bench_source_wires_slot_repack(self):
        """Source pins (the child-forwarding test style): the flag
        exists, both sweep engines receive it, the full-study secondary
        child inherits it, and plan search prices with the refill model
        when it is on."""
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        src = open(os.path.join(repo_root, "bench.py"),
                   encoding="utf-8").read()
        assert '"--slot-repack"' in src
        assert src.count('slot_repack=getattr(args, "slot_repack", True)'
                         ) >= 4
        assert 'child.slot_repack = getattr(args, "slot_repack", True)' \
            in src
        assert 'slot_repack=getattr(child, "slot_repack", True)' in src
        cli_src = open(os.path.join(
            repo_root, "llm_interpretation_replication_tpu",
            "__main__.py"), encoding="utf-8").read()
        assert '"--slot-repack"' in cli_src
        assert 'slot_repack=getattr(args, "slot_repack", True)' in cli_src


class TestKVSlabHandoff:
    """Cross-replica KV handoff (ISSUE 20, PARITY.md "Cross-replica KV
    handoff"): a prefill-specialist engine exports its undecided rows'
    prompt caches as host KVSlabs; a DIFFERENT engine imports them into
    its slot ring and decodes to retirement.  The round trip moves
    bytes, not values — decode-leg rows are bit-identical to the
    exporter decoding its own cache (bf16), within the int8 class when
    the slab carries quantized codes + scales."""

    def _merge(self, results, slabs, decoded):
        """Map decode-side rows (flat feed order) back onto the
        exporter's prompt indices via the slab metas."""
        merged = list(results)
        i = 0
        for slab in slabs:
            for m in slab.metas:
                merged[m["orig"]] = decoded[i]
                i += 1
        return merged

    def test_bf16_round_trip_bit_identical_under_strict(self, tiny):
        """Acceptance: export -> host slab -> import on a FRESH engine,
        with strict mode active end to end (``blocked_transfers == 0``)
        — the merged rows are BIT-identical to offline score_prompts,
        and the export/import telemetry balances."""
        from llm_interpretation_replication_tpu.runtime import strict

        eng, tok = tiny
        exporter = _clone(eng, tok, decode_completions=False)
        importer = _clone(eng, tok, decode_completions=False)
        telemetry.clear_counters()
        strict.activate()
        try:
            results, slabs = exporter.export_kv_slab(BIN_PROMPTS)
            assert slabs, "no undecided rows ever shipped"
            assert all(s.rows() > 0 and s.nbytes() > 0 for s in slabs)
            assert all(s.k_scale is None and s.v_scale is None
                       for s in slabs)          # bf16: no scale planes
            decoded = importer.decode_kv_slabs(slabs)
        finally:
            strict.deactivate()
        c = telemetry.counters()
        assert c.get(strict.BLOCKED_COUNTER, 0) == 0
        n = sum(s.rows() for s in slabs)
        assert len(decoded) == n
        assert c.get("slot_slab_export_rows", 0) == n
        assert c.get("slot_slab_import_rows", 0) == n
        assert c.get("slab_export_bytes", 0) > 0
        merged = self._merge(results, slabs, decoded)
        ref = _clone(eng, tok, decode_completions=False).score_prompts(
            BIN_PROMPTS)
        for a, b in zip(merged, ref):
            assert a is not None and a["success"] and b["success"]
            assert a["scan_found"] == b["scan_found"]
            for f in EXACT_FIELDS + PROB_FIELDS + ("odds_ratio",):
                assert a[f] == b[f], f

    def test_int8_slab_carries_scales_within_class(self, tiny):
        """int8 KV: the slab ships quantized codes AND the per-row scale
        planes; imported decode stays within the documented int8 class
        (|delta relative_prob| <= 0.05) of offline int8 scoring."""
        eng, tok = tiny
        exporter = _clone(eng, tok, decode_completions=False,
                          kv_dtype="int8")
        results, slabs = exporter.export_kv_slab(BIN_PROMPTS)
        assert slabs
        assert all(s.k_scale is not None and s.v_scale is not None
                   for s in slabs)
        importer = _clone(eng, tok, decode_completions=False,
                          kv_dtype="int8")
        decoded = importer.decode_kv_slabs(slabs)
        merged = self._merge(results, slabs, decoded)
        ref = _clone(eng, tok, decode_completions=False,
                     kv_dtype="int8").score_prompts(BIN_PROMPTS)
        for a, b in zip(merged, ref):
            assert a is not None and a["success"] and b["success"]
            assert abs(a["relative_prob"] - b["relative_prob"]) <= 0.05

    def test_admit_fn_feeds_slabs_mid_decode(self, tiny):
        """The decode replica's mid-decode admission hook: slabs that
        arrive while earlier slabs are still decoding refill the ring
        via ``admit_fn`` (not a fresh drain), and no row is orphaned —
        the fleet handoff-queue shape of serve/scheduler.submit_slab."""
        eng, tok = tiny
        exporter = _clone(eng, tok, decode_completions=False)
        results, slabs = exporter.export_kv_slab(BIN_PROMPTS)
        assert len(slabs) >= 2, "need >= 2 prefill batches for the hook"
        rest = list(slabs[1:])

        def admit():
            return [rest.pop(0)] if rest else []

        importer = _clone(eng, tok, decode_completions=False)
        decoded = importer.decode_kv_slabs(slabs[:1], admit_fn=admit)
        assert not rest                    # every queued slab admitted
        assert len(decoded) == sum(s.rows() for s in slabs)
        merged = self._merge(results, slabs, decoded)
        ref = _clone(eng, tok, decode_completions=False).score_prompts(
            BIN_PROMPTS)
        for a, b in zip(merged, ref):
            assert a is not None
            for f in EXACT_FIELDS:
                assert a[f] == b[f], f


class TestPackedStageExtend:
    def test_extend_stages_bit_parity_vs_reprefill(self, tiny):
        """Satellite (ISSUE 20): packed autoregressive demo stages grow
        the pack by EXTENDING the previous stage's cache
        (``extend_prefill``) instead of re-prefilling from scratch —
        demos and packs bit-identical to the re-prefill path, with the
        ``slot_stage_extends`` counter proving the reuse actually ran."""
        eng, tok = tiny
        qs = [f"Is item {i} a vehicle?" for i in range(6)]
        telemetry.clear_counters()
        e_ext = _clone(eng, tok, phase2_pool_target=2,
                       buckets=(32, 64, 128, 256))
        packs_ext, demos_ext = e_ext.packed_autoregressive_demos(
            qs, packing=3, max_demo_tokens=4)
        assert telemetry.counter("slot_stage_extends") > 0
        e_leg = _clone(eng, tok, phase2_pool_target=2,
                       buckets=(32, 64, 128, 256))
        packs_leg, demos_leg = e_leg.packed_autoregressive_demos(
            qs, packing=3, max_demo_tokens=4, extend_stages=False)
        assert demos_ext == demos_leg
        assert packs_ext == packs_leg


class TestMixedSlotLengths:
    def test_longer_newcomer_pads_live_lanes_up(self, tiny):
        """Review regression: a pending group whose cache slot axis is
        WIDER than the ring's current slot length must pad the live
        lanes up (not crash the concat) — the slotted-serve mixed-bucket
        and grown-pack scenarios.  Tokens must match the same rows
        decoded in unmixed rings (padding slots are inert)."""
        import jax.numpy as jnp

        from llm_interpretation_replication_tpu.runtime import (
            batching as bmod,
        )

        eng, tok = tiny
        e = _clone(eng, tok)
        eos = getattr(tok, "eos_token_id", None)

        def group(prompts, pad_to):
            encoded = bmod.encode_prompts(tok, prompts)
            batch = next(bmod.batches_for_prompts(
                encoded, len(prompts), (32,),
                pad_id=tok.pad_token_id or 0))
            last, cache = e._prefill(jnp.asarray(batch.token_ids),
                                     jnp.asarray(batch.attention_mask),
                                     batch.bucket_len)
            lens = jnp.sum(jnp.asarray(batch.attention_mask), axis=-1)
            cache = slots_mod._pad_cache_to(cache, pad_to)
            metas = [{"orig": int(i)} for i in batch.indices]
            return cache, last, lens, np.zeros((len(prompts), 2),
                                               np.int32), metas

        def run(groups, steps=6):
            got = {}

            def emit(rows):
                for r in rows:
                    got[r.meta["orig"] + r.meta.get("base", 0)] = \
                        r.toks[: r.decoded].copy()

            ring = slots_mod.SlotRing(
                e, steps=steps, eos_id=eos, capacity=3, leg="binary",
                workload="test",
                retire=lambda row: row.decoded
                if row.decoded >= steps else -1,
                emit=emit, with_scores=False,
                pad_slice=lambda n: n)
            for base, g in enumerate(groups):
                cache, last, lens, ids, metas = g
                for m in metas:
                    m["base"] = base * 10
                ring.feed(cache, last, lens, ids, metas)
            ring.drain()
            return got

        narrow = group(["Is a kayak a boat?", "Is tea a soup?"], 40)
        wide = group(["Is rain weather now?", "Is a shed a house?"], 56)
        mixed = run([narrow, wide])
        # the same rows through single-length rings (the reference)
        solo_n = run([group(["Is a kayak a boat?", "Is tea a soup?"], 40)])
        solo_w = run([group(["Is rain weather now?",
                             "Is a shed a house?"], 56)])
        assert len(mixed) == 4
        for k in (0, 1):
            np.testing.assert_array_equal(mixed[k], solo_n[k])
        for k in (10, 11):
            np.testing.assert_array_equal(mixed[k], solo_w[k - 10])
