"""Live run-health layer (obs/metrics.py, obs/flight.py,
obs/benchdiff.py): Prometheus exposition correctness, the /metrics +
/healthz endpoint, the JSONL metrics log, the one-code-path heartbeat,
the stall watchdog (no-false-positive guard + trip semantics), the
flight recorder (including the injected-FaultyEngine-OOM dump), the
typed serve_rejected_* split, and the ``obs bench-diff`` trajectory
analyzer against the checked-in BENCH records.

Tier-1 (``-m obsmetrics``).  The metrics registry and flight recorder
are process-global singletons; every test runs against reset state
(autouse fixture) and unique telemetry names where global counters
cannot be reset safely.
"""

import json
import os
import urllib.error
import urllib.request

import pytest

from llm_interpretation_replication_tpu.obs import benchdiff, flight, metrics
from llm_interpretation_replication_tpu.utils import telemetry

pytestmark = pytest.mark.obsmetrics

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_state():
    flight.get_recorder().wait()
    flight.disable()
    metrics.get_registry().reset()
    yield
    flight.get_recorder().wait()
    flight.disable()
    metrics.get_registry().reset()


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------

class TestPrometheusExposition:
    def test_counters_typed_as_counters(self):
        telemetry.record_counter("texpo_hits", 3)
        text = metrics.prometheus_text()
        lines = text.splitlines()
        assert "# TYPE llm_interp_texpo_hits counter" in lines
        assert "llm_interp_texpo_hits 3" in lines

    def test_gauges_typed_as_gauges_with_label_escaping(self):
        reg = metrics.MetricsRegistry()
        reg.set_gauge("texpo_gauge", 1.5,
                      labels={"model": 'fal"con\\7b\nx'})
        text = reg.prometheus_text()
        assert "# TYPE llm_interp_texpo_gauge gauge" in text
        # backslash, double quote, and newline all escaped per the
        # exposition format — a model path can contain any of them
        assert ('llm_interp_texpo_gauge{model="fal\\"con\\\\7b\\nx"} 1.5'
                in text)

    def test_ring_percentiles_export_as_summary(self):
        for v in (1.0, 2.0, 3.0, 4.0, 100.0):
            telemetry.record_sample("texpo_ring_ms", v)
        text = metrics.prometheus_text()
        assert "# TYPE llm_interp_texpo_ring_ms summary" in text
        assert 'llm_interp_texpo_ring_ms{quantile="0.5"} 3' in text
        assert 'llm_interp_texpo_ring_ms{quantile="0.99"} 100' in text
        assert "llm_interp_texpo_ring_ms_count 5" in text
        assert "llm_interp_texpo_ring_ms_retained 5" in text

    def test_empty_ring_yields_no_bogus_series(self):
        # never-recorded ring: no series at all (a fabricated 0-quantile
        # would read as "p99 latency is zero" on a dashboard)
        assert "texpo_never_recorded" not in metrics.prometheus_text()

    def test_metric_names_sanitized(self):
        telemetry.record_counter("texpo.weird-name/x", 1)
        text = metrics.prometheus_text()
        assert "llm_interp_texpo_weird_name_x 1" in text
        assert "texpo.weird-name/x" not in text

    def test_name_helpers(self):
        assert metrics.sanitize_metric_name("a.b-c/d") == "a_b_c_d"
        assert metrics.sanitize_metric_name("9lead") == "_9lead"
        assert metrics.escape_label_value('a"b\\c\nd') == 'a\\"b\\\\c\\nd'


# ---------------------------------------------------------------------------
# Registry sampling + JSONL metrics log
# ---------------------------------------------------------------------------

class TestRegistrySampling:
    def test_sample_records_typed_series_and_since_enable_deltas(self):
        telemetry.record_counter("tsamp_ctr", 5)
        reg = metrics.MetricsRegistry()      # baselines AFTER the 5
        telemetry.record_counter("tsamp_ctr", 2)
        telemetry.record_sample("tsamp_ring", 7.0)
        doc = reg.sample()
        assert doc["counters"]["tsamp_ctr"] == 7          # raw monotone
        assert doc["counters_delta"]["tsamp_ctr"] == 2    # counters_since
        assert doc["rings"]["tsamp_ring"]["p50"] == 7.0
        assert doc["rings"]["tsamp_ring"]["total"] == 1   # truncation block
        assert reg.series_type("tsamp_ctr") == "counter"
        assert reg.series_type("tsamp_ring_p50") == "gauge"
        assert [v for _, v in reg.series("tsamp_ctr")] == [7]

    def test_jsonl_stream_appends_one_valid_line_per_sample(self, tmp_path):
        reg = metrics.MetricsRegistry()
        path = str(tmp_path / "metrics.jsonl")
        reg.enable_jsonl(path)
        telemetry.record_counter("tjsonl_ctr", 1)
        reg.sample()
        reg.sample()
        reg.disable_jsonl()
        lines = [json.loads(line) for line in
                 open(path).read().strip().splitlines()]
        assert len(lines) == 2
        for doc in lines:
            assert {"t", "uptime_s", "counters", "counters_delta",
                    "rings", "gauges"} <= set(doc)
        assert lines[-1]["counters"]["tjsonl_ctr"] == 1


class TestMetricsServer:
    def test_metrics_and_healthz_endpoints(self):
        telemetry.record_counter("tsrv_ctr", 1)
        reg = metrics.MetricsRegistry()
        health = {"queue_depth": 3}
        with metrics.MetricsServer(reg, 0, host="127.0.0.1",
                                   healthz_fn=lambda: health) as srv:
            url = f"http://127.0.0.1:{srv.port}"
            resp = urllib.request.urlopen(url + "/metrics")
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith(
                "text/plain; version=0.0.4")
            assert "llm_interp_tsrv_ctr" in resp.read().decode()
            doc = json.loads(urllib.request.urlopen(
                url + "/healthz").read())
            assert doc["status"] == "ok"
            assert doc["queue_depth"] == 3
            assert doc["uptime_s"] >= 0
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(url + "/nope")
            assert exc.value.code == 404

    def test_healthz_degrades_instead_of_500(self):
        reg = metrics.MetricsRegistry()

        def broken():
            raise RuntimeError("scheduler introspection failed")

        with metrics.MetricsServer(reg, 0, host="127.0.0.1",
                                   healthz_fn=broken) as srv:
            doc = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz").read())
        assert doc["status"] == "degraded"
        assert "introspection" in doc["error"]


# ---------------------------------------------------------------------------
# Heartbeat: one code path -> log line + gauges (+ watchdog beat)
# ---------------------------------------------------------------------------

class TestHeartbeat:
    def test_line_format_and_gauges_from_one_call(self):
        lines = []
        out = metrics.heartbeat("falcon-7b", 40, 100, 2.0,
                                log=lines.append)
        assert lines == [out]
        # the exact PR-6 stderr contract, unchanged
        assert out == ("[heartbeat] falcon-7b: 40/100 rows "
                       "| 20.00 rows/s | ETA 3s")
        text = metrics.prometheus_text()
        assert ('llm_interp_sweep_progress_rows{label="falcon-7b"} 40'
                in text)
        assert ('llm_interp_sweep_rows_per_s{label="falcon-7b"} 20'
                in text)

    def test_heartbeat_beats_the_active_watchdog(self):
        wd = flight.StallWatchdog(label="hb")
        flight._set_active_watchdog(wd)
        try:
            for i in range(3):
                metrics.heartbeat("m", i + 1, 10, 1.0 + i)
            assert wd._last_beat is not None
            assert len(wd._intervals) == 2
        finally:
            flight._clear_active_watchdog(wd)

    def test_sweep_shell_routes_progress_through_the_registry(self, tmp_path):
        """Satellite: the perturbation sweep's [heartbeat] lines and the
        metrics gauges come from ONE code path — running the shell
        updates the registry without any stderr scraping."""
        from llm_interpretation_replication_tpu.sweeps import (
            run_model_perturbation_sweep,
        )

        from test_faults import _scenarios
        from test_sweeps import FakeEngine

        logged = []
        df = run_model_perturbation_sweep(
            FakeEngine("fake/hb-7b"), "fake/hb-7b", _scenarios(),
            str(tmp_path / "out.xlsx"), confidence=False, score_chunk=4,
            log=logged.append)
        assert len(df) == 12
        beats = [l for l in logged if l.startswith("[heartbeat]")]
        assert len(beats) == 3            # one per 4-row chunk
        assert beats[-1].startswith("[heartbeat] fake/hb-7b: 12/12 rows")
        text = metrics.prometheus_text()
        assert ('llm_interp_sweep_progress_rows{label="fake/hb-7b"} 12'
                in text)
        assert ('llm_interp_sweep_progress_total{label="fake/hb-7b"} 12'
                in text)


# ---------------------------------------------------------------------------
# Stall watchdog
# ---------------------------------------------------------------------------

class TestStallWatchdog:
    def _fed(self, intervals, **kw):
        clk = {"t": 0.0}
        wd = flight.StallWatchdog(label="wd-test",
                                  clock=lambda: clk["t"], **kw)
        wd.beat(0)
        for i, dt in enumerate(intervals):
            clk["t"] += dt
            wd.beat(i + 1)
        return wd, clk

    def test_no_false_positive_on_slow_but_progressing_sweep(self):
        """A sweep whose chunks take 10s each — slow, irregular, but
        progressing — must never trip a watchdog calibrated to its own
        trailing median."""
        wd, clk = self._fed([8.0, 12.0, 10.0, 9.0, 11.0], floor_s=1.0)
        snap = len(telemetry.fault_events("watchdog_stall"))
        for idle in (5.0, 15.0, 35.0):    # all below 4 x median(10) = 40
            assert wd.check(now=clk["t"] + idle) is False
        assert wd.trips == 0
        assert len(telemetry.fault_events("watchdog_stall")) == snap

    def test_startup_compile_time_never_trips(self):
        # fewer than min_beats intervals: no median, no threshold, no trip
        wd, clk = self._fed([2.0], floor_s=0.1)
        assert wd.threshold_s() is None
        assert wd.check(now=clk["t"] + 9999.0) is False

    def test_trip_warns_once_records_fault_and_resets_on_beat(self, capsys):
        wd, clk = self._fed([1.0, 1.0, 1.0, 1.0], floor_s=1.0)
        snap = len(telemetry.fault_events("watchdog_stall"))
        assert wd.check(now=clk["t"] + 10.0) is True     # > 4 x 1s
        assert wd.check(now=clk["t"] + 20.0) is False    # once per stall
        events = telemetry.fault_events("watchdog_stall")[snap:]
        assert len(events) == 1 and events[0]["label"] == "wd-test"
        assert events[0]["threshold_s"] == 4.0
        assert "no progress" in capsys.readouterr().err
        clk["t"] += 30.0
        wd.beat(99)                                      # progress resumed
        clk["t"] += 1.0
        wd.beat(100)
        assert wd.check(now=clk["t"] + 0.5) is False
        assert wd.trips == 1

    def test_floor_absorbs_fast_test_scale_chunks(self):
        # millisecond chunks: threshold is the floor, not 4 x 1ms
        wd, clk = self._fed([0.001] * 5, floor_s=5.0)
        assert wd.threshold_s() == 5.0
        assert wd.check(now=clk["t"] + 1.0) is False


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------

class TestFlightRecorder:
    def test_dump_on_injected_faulty_engine_oom(self, tmp_path):
        """Satellite acceptance: an injected FaultyEngine OOM that
        engages the engine's back-off ladder leaves a flightrec-*.json
        triage artifact with the trigger event, counters, and rings."""
        import dataclasses as dc

        from llm_interpretation_replication_tpu.utils.testing import (
            Fault,
            FaultyEngine,
        )

        from test_runtime import _tiny_engine

        eng, _, _ = _tiny_engine(batch_size=4)
        eng.ecfg = dc.replace(eng.ecfg, oom_backoff=True,
                              oom_batch_ladder=(2,), oom_batch_floor=1)
        flight.enable(str(tmp_path))
        faulty = FaultyEngine(eng, [Fault("oom", at_batch=1)])
        rows = faulty.score_prompts(
            [f"Is item {i} a vehicle?" for i in range(6)])
        assert len(rows) == 6 and all(r["success"] for r in rows)
        flight.get_recorder().wait()      # dumps write on a worker thread
        dumps = sorted(tmp_path.glob("flightrec-engine_oom_backoff-*.json"))
        assert len(dumps) == 1
        doc = json.loads(dumps[0].read_text())
        assert doc["reason"] == "engine_oom_backoff"
        assert doc["trigger"]["new_batch"] == 2
        assert doc["fault_events"][-1]["kind"] == "engine_oom_backoff"
        assert "counters" in doc and "rings" in doc and "memory" in doc

    def test_preempted_sweep_leaves_artifact_next_to_workbook(self, tmp_path):
        """The sweep SIGTERM shell hook: a preempted perturbation sweep
        dumps a flight record into the workbook's directory before the
        Preempted exit propagates."""
        from llm_interpretation_replication_tpu.runtime.faults import (
            Preempted,
        )
        from llm_interpretation_replication_tpu.sweeps import (
            run_model_perturbation_sweep,
        )
        from llm_interpretation_replication_tpu.utils.testing import (
            Fault,
            FaultyEngine,
        )

        from test_faults import _scenarios
        from test_sweeps import FakeEngine

        # call 3 = chunk 2's binary leg: chunk 1 finished (and emitted
        # its heartbeat frame) before the preemption lands
        faulty = FaultyEngine(FakeEngine("fake/pre-7b"),
                              [Fault("preempt", at_call=3)])
        with pytest.raises(Preempted):
            run_model_perturbation_sweep(
                faulty, "fake/pre-7b", _scenarios(),
                str(tmp_path / "out.xlsx"), confidence=False,
                score_chunk=4, log=lambda *a, **k: None)
        flight.get_recorder().wait()
        dumps = sorted(tmp_path.glob("flightrec-preempted-*.json"))
        assert len(dumps) == 1
        doc = json.loads(dumps[0].read_text())
        assert doc["trigger"]["kind"] == "preempted"
        # the heartbeat frames captured before the preemption ride along
        assert any(f["kind"] == "heartbeat" for f in doc["frames"])

    def test_transient_exhaustion_is_a_trigger(self, tmp_path):
        from llm_interpretation_replication_tpu.runtime.faults import (
            TransientError,
            retry_transient,
        )
        from llm_interpretation_replication_tpu.utils.retry import (
            RetryPolicy,
        )

        flight.enable(str(tmp_path))

        def always():
            raise TransientError("injected transient")

        fast = RetryPolicy(max_retries=2, initial_delay=0.001,
                           max_delay=0.002)
        with pytest.raises(TransientError):
            retry_transient(always, fast, label="texh")()
        events = telemetry.fault_events("transient_exhausted")
        assert events and events[-1]["label"] == "texh"
        assert events[-1]["retries"] == 2
        flight.get_recorder().wait()
        assert sorted(tmp_path.glob("flightrec-transient_exhausted-*.json"))

    def test_cooldown_rate_limits_dump_storms(self, tmp_path):
        rec = flight.FlightRecorder(cooldown_s=60.0)
        rec.enable(str(tmp_path))
        try:
            assert rec.dump("watchdog_stall") is not None
            assert rec.dump("watchdog_stall") is None       # cooldown
            assert rec.dump("preempted") is not None        # per-kind
        finally:
            rec.disable()
        assert len(list(tmp_path.glob("flightrec-*.json"))) == 2

    def test_disarmed_recorder_is_inert(self, tmp_path):
        rec = flight.FlightRecorder()
        assert rec.dump("preempted") is None
        rec.note("heartbeat", done=1)
        assert rec._frames == []


# ---------------------------------------------------------------------------
# Measurement-only contract (acceptance): metering changes nothing
# ---------------------------------------------------------------------------

class TestMeteredStrictParity:
    def test_traced_metered_strict_sweep_rows_identical_and_clean(
            self, tmp_path):
        """Acceptance: a strict-mode traced+metered scoring pass reports
        blocked_transfers == 0 and returns BIT-IDENTICAL rows vs the
        metrics-off run — the whole layer is measurement-only."""
        from llm_interpretation_replication_tpu import obs
        from llm_interpretation_replication_tpu.runtime import strict

        from test_runtime import _tiny_engine

        eng, _, _ = _tiny_engine()
        prompts = ["Is a tweet a publication?", "Is soup a beverage?",
                   "The quick brown fox"] * 2
        plain = eng.score_prompts(prompts)           # metrics off
        reg = metrics.get_registry()
        reg.enable_jsonl(str(tmp_path / "m.jsonl"))
        flight.enable(str(tmp_path))
        obs.enable()
        strict.activate(sentry=False)
        try:
            snap = telemetry.counters()
            metered = eng.score_prompts(prompts)
            metrics.heartbeat("parity", len(metered), len(metered), 1.0)
            reg.sample()
            delta = telemetry.counters_since(snap)
            assert delta.get(strict.BLOCKED_COUNTER, 0) == 0
        finally:
            strict.deactivate()
            obs.disable()
            obs.get_tracer().reset()
        for a, b in zip(plain, metered):
            assert a == b
        # the metrics log captured the run without touching it
        lines = (tmp_path / "m.jsonl").read_text().strip().splitlines()
        assert lines and json.loads(lines[-1])["counters"]


# ---------------------------------------------------------------------------
# Typed serve rejection split
# ---------------------------------------------------------------------------

class TestServeRejectionSplit:
    def test_submit_after_close_counts_serve_rejected_closed(self):
        from llm_interpretation_replication_tpu.serve.request import (
            SchedulerClosed,
            ScoreRequest,
        )
        from llm_interpretation_replication_tpu.serve.scheduler import (
            Scheduler,
        )

        sched = Scheduler(engine=object())
        sched.close()
        snap = telemetry.counters()
        with pytest.raises(SchedulerClosed):
            sched.submit(ScoreRequest(prompt="Is soup a beverage?"))
        delta = telemetry.counters_since(snap)
        assert delta.get("serve_rejected_closed") == 1
        # the split is complete: full/deadline/closed are distinct names
        assert delta.get("serve_rejected_full") is None
        assert delta.get("serve_rejected_deadline") is None


# ---------------------------------------------------------------------------
# obs bench-diff
# ---------------------------------------------------------------------------

class TestBenchDiff:
    R04 = os.path.join(REPO_ROOT, "BENCH_r04.json")
    R05 = os.path.join(REPO_ROOT, "BENCH_r05.json")

    def test_reproduces_the_known_r04_r05_delta(self, capsys):
        """Acceptance: the checked-in records diff to the known 91.89 ->
        120.15 p/s headline improvement, exit 0 (no regression)."""
        assert benchdiff.main([self.R04, self.R05]) == 0
        out = capsys.readouterr().out
        assert "91.89" in out and "120.15" in out
        assert "+30.75%" in out
        assert "improved" in out
        assert "0 regression(s)" in out

    def test_reversed_order_flags_the_regression_and_exits_1(self, capsys):
        assert benchdiff.main([self.R05, self.R04]) == 1
        out = capsys.readouterr().out
        assert "REGRESSION" in out
        assert "1 regression(s)" in out
        assert benchdiff.main([self.R05, self.R04, "--no-fail"]) == 0

    def test_threshold_is_configurable(self):
        # at a 30% threshold the 23.5% drop is tolerated
        assert benchdiff.main([self.R05, self.R04,
                               "--threshold", "30"]) == 0

    def test_json_format_aligns_secondary_metrics_by_stable_key(
            self, capsys):
        assert benchdiff.main([self.R04, self.R05, "--format",
                               "json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["labels"] == ["r04", "r05"]
        rows = {r["key"]: r for r in doc["metrics"]}
        head = rows["headline"]
        assert head["values"] == [91.89, 120.15]
        assert head["delta_pct"] == pytest.approx(30.75, abs=0.01)
        # the 430-token parity/single rows align despite free-text drift
        assert "parity@430tok [prompts/sec]" in rows
        assert "single@430tok [prompts/sec]" in rows
        # r05's full-study row has no r04 counterpart: new, not dropped
        fs = rows["full-study [rows/sec]"]
        assert fs["verdict"] == "new" and fs["values"][0] is None

    def test_three_round_trajectory(self, capsys):
        r03 = os.path.join(REPO_ROOT, "BENCH_r03.json")
        assert benchdiff.main([r03, self.R04, self.R05]) == 0
        out = capsys.readouterr().out
        assert "r03 -> r04 -> r05" in out

    def test_phases_and_context_blocks_align(self, tmp_path, capsys):
        a = {"metric": "m", "value": 100.0, "unit": "rows/sec",
             "phases": {"per_phase": {"decode": {"seconds": 10.0,
                                                 "ms_per_row": 1.0}},
                        "total_s": 10.0},
             "context": {"kv_dtype": "bf16", "prefill_chunks": 3}}
        b = {"metric": "m", "value": 101.0, "unit": "rows/sec",
             "phases": {"per_phase": {"decode": {"seconds": 30.0,
                                                 "ms_per_row": 3.0}},
                        "total_s": 30.0},
             "context": {"kv_dtype": "int8", "prefill_chunks": 3}}
        pa, pb = tmp_path / "BENCH_x01.json", tmp_path / "BENCH_x02.json"
        pa.write_text(json.dumps(a))
        pb.write_text(json.dumps(b))
        assert benchdiff.main([str(pa), str(pb)]) == 1   # 3x ms/row
        out = capsys.readouterr().out
        assert "phase:decode" in out and "REGRESSION" in out
        assert "context:kv_dtype" in out        # changed context surfaces
        assert "prefill_chunks" not in out      # unchanged context is noise

    def test_mixed_bracket_and_packed_rows_never_cross_compare(
            self, tmp_path, capsys):
        """ISSUE-10 satellite: eos_mode and the packing factor fold into
        the workload alignment key — an EOS-typical bracket row (faster by
        construction) must never align against a no-EOS row and read as an
        'improvement', and a packed questions/sec row must never align
        with an isolated row.  A record WITHOUT the new blocks aligns with
        one that has them: the new rows report 'new', the shared rows
        diff normally."""
        old = {"metric": ("full-study rows/sec/chip (... no-EOS worst "
                          "case)"), "value": 30.0, "unit": "rows/sec"}
        new = {"metric": ("full-study rows/sec/chip (... no-EOS worst "
                          "case)"), "value": 31.0, "unit": "rows/sec",
               "brackets": [
                   {"eos_mode": "no-eos", "value": 31.0,
                    "unit": "rows/sec",
                    "metric": "full-study rows/sec/chip (no-eos decode "
                              "bracket)"},
                   {"eos_mode": "eos-typical", "value": 95.0,
                    "unit": "rows/sec",
                    "metric": "full-study rows/sec/chip (eos-typical "
                              "decode bracket)"},
               ],
               "packed": {"metric": "questions/sec/chip (packed batch "
                                    "prompting secondary, Q=4 ...)",
                          "value": 140.0, "unit": "questions/sec"}}
        # the full-study CHILD secondary carries its own nested brackets
        # (the bench child-extras forwarding) — flattened like top-level
        new["secondary"] = [{
            "metric": "full-study rows/sec/chip (child secondary)",
            "value": 31.0, "unit": "rows/sec",
            "brackets": [{"eos_mode": "eos-typical", "value": 96.0,
                          "unit": "rows/sec",
                          "metric": "full-study rows/sec/chip "
                                    "(eos-typical decode bracket) #child"}],
        }]
        pa, pb = tmp_path / "BENCH_y01.json", tmp_path / "BENCH_y02.json"
        pa.write_text(json.dumps(old))
        pb.write_text(json.dumps(new))
        assert benchdiff.main([str(pa), str(pb), "--format", "json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        rows = {r["key"]: r for r in doc["metrics"]}
        # distinct keys per bracket / packing — no cross-comparison
        assert "full-study@eos-typical [rows/sec]" in rows
        assert "packed@q4 [questions/sec]" in rows
        # the bracket/packed rows are NEW vs the bracket-less record, and
        # the 95-vs-30 bracket span never registers as a delta
        assert rows["full-study@eos-typical [rows/sec]"]["verdict"] == "new"
        assert rows["packed@q4 [questions/sec]"]["verdict"] == "new"
        assert rows["headline"]["values"] == [30.0, 31.0]
        # the child's NESTED bracket row surfaced too (disambiguated key)
        assert any(k.startswith("full-study@eos-typical") and k !=
                   "full-study@eos-typical [rows/sec]" for k in rows)

    def test_headline_keys_fold_the_workload_shape(self, tmp_path, capsys):
        """An --eos-mode typical headline (faster by construction) must
        never produce a verdict against a no-EOS headline — the shape
        tags fold into the otherwise-positional headline key."""
        a = {"metric": "full-study rows/sec/chip (no-EOS worst case)",
             "value": 30.0, "unit": "rows/sec"}
        b = {"metric": "full-study rows/sec/chip (EOS-typical decode "
                       "bracket)", "value": 95.0, "unit": "rows/sec"}
        pa, pb = tmp_path / "BENCH_w01.json", tmp_path / "BENCH_w02.json"
        pa.write_text(json.dumps(a))
        pb.write_text(json.dumps(b))
        assert benchdiff.main([str(pa), str(pb), "--format", "json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        rows = {r["key"]: r for r in doc["metrics"]}
        assert rows["headline"]["verdict"] == "gone"
        assert rows["headline@eos-typical"]["verdict"] == "new"

    def test_mixed_brackets_catch_same_bracket_regressions(
            self, tmp_path, capsys):
        """Same-bracket rows still diff: an EOS-typical drop between two
        bracketed records is a real regression."""
        def rec(no_eos, eos_typical):
            return {"metric": "full-study rows/sec/chip (no-EOS)",
                    "value": no_eos, "unit": "rows/sec",
                    "brackets": [
                        {"eos_mode": "eos-typical", "value": eos_typical,
                         "unit": "rows/sec",
                         "metric": "full-study rows/sec/chip (eos-typical "
                                   "decode bracket)"}]}
        pa, pb = tmp_path / "BENCH_z01.json", tmp_path / "BENCH_z02.json"
        pa.write_text(json.dumps(rec(30.0, 95.0)))
        pb.write_text(json.dumps(rec(30.0, 60.0)))
        assert benchdiff.main([str(pa), str(pb)]) == 1
        out = capsys.readouterr().out
        assert "eos-typical" in out and "REGRESSION" in out

    def test_rejects_non_records(self, tmp_path, capsys):
        bad = tmp_path / "nope.json"
        bad.write_text('{"no": "value"}')
        assert benchdiff.main([str(bad), self.R05]) == 2
        assert "not a bench record" in capsys.readouterr().err

    def test_cli_routes_obs_bench_diff_before_argparse(self, capsys):
        from llm_interpretation_replication_tpu.__main__ import main

        with pytest.raises(SystemExit) as exc:
            main(["obs", "bench-diff", self.R04, self.R05])
        assert exc.value.code == 0
        assert "120.15" in capsys.readouterr().out


class TestBenchDiffPoolRoster:
    """serve_load_pool is an ALIGNED block (ISSUE 20): rosters key by
    role composition, so the disaggregated-vs-symmetric knee comparison
    lands as adjacent verdict rows across rounds."""

    def _rec(self, name, roles=None, sat=20.0, p99=50.0, n=2):
        entry = {"name": name, "replicas": [{} for _ in range(n)],
                 "serve_load": {"saturation_rows_per_s": sat,
                                "rates": [{"latency_ms": {"p99": p99}}]}}
        if roles:
            entry["roles"] = roles
        return {"metric": "rows/sec x", "value": 1.0, "unit": "rows/sec",
                "serve_load_pool": {"replicas": n,
                                    "configurations": [entry]}}

    def test_block_is_aligned_not_informational(self):
        assert "serve_load_pool" in benchdiff.ALIGNED_BLOCKS
        assert "serve_load_pool" not in benchdiff.INFORMATIONAL_BLOCKS

    def test_roles_roster_tags_by_composition_not_spelling(self):
        """The tag sorts roles (prefill first), so flag spelling order
        never splits a series across rounds."""
        flat = benchdiff.flatten_metrics(self._rec(
            "roles-decode:1,prefill:1",
            roles={"decode": 1, "prefill": 1}))
        key = "pool[prefill:1,decode:1] saturation [rows/sec]"
        assert flat[key]["value"] == 20.0
        assert flat["pool[prefill:1,decode:1] p99@top [ms]"][
            "value"] == 50.0
        assert flat["pool[prefill:1,decode:1] replicas"]["value"] == 2

    def test_symmetric_roster_tags_by_replica_count(self):
        flat = benchdiff.flatten_metrics(self._rec("single-model-x2"))
        assert "pool[symmetric-x2] saturation [rows/sec]" in flat
        flat3 = benchdiff.flatten_metrics(self._rec("single-model-x3",
                                                    n=3))
        assert "pool[symmetric-x3] saturation [rows/sec]" in flat3

    def test_knee_drop_is_a_regression_row(self):
        roles = {"prefill": 1, "decode": 1}
        diff = benchdiff.diff_records(
            [dict(self._rec("roles-a", roles=roles, sat=20.0, p99=50.0),
                  label="r1"),
             dict(self._rec("roles-a", roles=roles, sat=10.0, p99=30.0),
                  label="r2")], threshold_pct=5.0)
        row = next(r for r in diff["metrics"] if r["key"] ==
                   "pool[prefill:1,decode:1] saturation [rows/sec]")
        assert row["verdict"] == "REGRESSION"      # knee fell = worse
        row99 = next(r for r in diff["metrics"] if r["key"] ==
                     "pool[prefill:1,decode:1] p99@top [ms]")
        assert row99["verdict"] == "improved"      # latency fell = better
