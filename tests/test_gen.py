"""Generator tests, incl. the golden diff against the reference's shipped
perturbations_irrelevant.json (reference data mounted read-only)."""

import json
import os

import pytest

from llm_interpretation_replication_tpu.config import (
    irrelevant_scenarios,
    irrelevant_statements,
    legal_scenarios,
)
from llm_interpretation_replication_tpu.gen import (
    generate_perturbations,
    parse_numbered_rephrasings,
)
from llm_interpretation_replication_tpu.gen.rephrase import (
    generate_rephrasings,
    load_perturbations,
    save_perturbations,
)

REF_DATA = "/root/reference/data/perturbations_irrelevant.json"


class TestIrrelevantPerturber:
    @pytest.mark.skipif(not os.path.exists(REF_DATA), reason="reference not mounted")
    def test_golden_exact_reproduction(self):
        ref = json.load(open(REF_DATA))
        ours = generate_perturbations(irrelevant_scenarios(), irrelevant_statements())
        assert len(ours) == len(ref) == 5
        total = 0
        for o, r in zip(ours, ref):
            assert o["scenario_name"] == r["scenario_name"]
            assert o["perturbations_with_irrelevant"] == r["perturbations_with_irrelevant"]
            total += len(o["perturbations_with_irrelevant"])
        assert total == 3400

    def test_counts_by_scenario(self):
        ours = generate_perturbations(irrelevant_scenarios(), irrelevant_statements())
        counts = [len(s["perturbations_with_irrelevant"]) for s in ours]
        assert counts == [400, 400, 600, 1000, 1000]


class TestRephrasings:
    def test_parse_numbered_list(self):
        text = (
            "Here are 20 variations:\n"
            "1. First rephrasing?\n"
            "2. Second rephrasing\n"
            "   that continues on another line?\n"
            "3 Third without dot?\n"
            "\n"
            "4. Fourth?\n"
        )
        got = parse_numbered_rephrasings(text)
        assert got == [
            "First rephrasing?",
            "Second rephrasing that continues on another line?",
            "Third without dot?",
            "Fourth?",
        ]

    def test_generate_with_fake_backend(self):
        scenarios = legal_scenarios()[:1]
        calls = {"n": 0}

        def fake_complete(prompt):
            calls["n"] += 1
            assert scenarios[0]["original_main"][:40] in prompt
            return "\n".join(f"{i}. Variation {calls['n']}-{i}?" for i in range(1, 21))

        records = generate_rephrasings(
            scenarios, fake_complete, sessions_per_scenario=3, target_per_scenario=50
        )
        assert len(records) == 1
        assert len(records[0]["rephrasings"]) == 50
        assert records[0]["target_tokens"] == list(scenarios[0]["target_tokens"])

    def test_save_load_identity_verification(self, tmp_path):
        scenarios = legal_scenarios()
        records = [
            {
                "original_main": s["original_main"],
                "response_format": s["response_format"],
                "target_tokens": list(s["target_tokens"]),
                "confidence_format": s["confidence_format"],
                "rephrasings": ["a?", "b?"],
            }
            for s in scenarios
        ]
        path = str(tmp_path / "perturbations.json")
        save_perturbations(records, path)
        back = load_perturbations(path, expected_scenarios=scenarios)
        assert back[0]["rephrasings"] == ["a?", "b?"]
        # tampered scenario text must fail verification
        records[0]["original_main"] = "different"
        save_perturbations(records, path)
        with pytest.raises(ValueError):
            load_perturbations(path, expected_scenarios=scenarios)


def test_readable_dump_golden_vs_reference():
    """The human-readable companion dump is byte-identical to the reference's
    recorded perturbations_irrelevant_readable.txt (timestamp injected)."""
    import os

    ref_path = "/root/reference/data/perturbations_irrelevant_readable.txt"
    if not os.path.exists(ref_path):
        import pytest

        pytest.skip("reference not mounted")
    from llm_interpretation_replication_tpu.config import (
        irrelevant_scenarios,
        irrelevant_statements,
    )
    from llm_interpretation_replication_tpu.gen.irrelevant import (
        generate_perturbations,
        readable_dump,
    )

    perturbed = generate_perturbations(irrelevant_scenarios(),
                                       irrelevant_statements())
    ours = readable_dump(perturbed, generated_at="2025-11-09 14:23:48")
    ref = open(ref_path, encoding="utf-8").read()
    assert ours == ref
