"""graftlint static-analysis gate + strict-mode runtime guards.

Six layers, all tier-1 (``-m lint``):

1. **Rule self-tests** — synthetic fixtures proving every rule
   (G01-G08) fires on its target pattern and stays quiet on the blessed
   idiom next to it.  This is what guarantees the repo gate below has
   teeth: a violation introduced into the tree is, by construction of
   these fixtures, a pattern the analyzer flags.
2. **Interprocedural fixtures** (PR 15) — the module call graph
   propagates device-region membership into helpers reachable from
   jit/launch roots, pinned BOTH directions: the new engine flags the
   helper-called-from-jit ``.item()`` the PR-3 per-function engine
   provably missed (``interprocedural=False`` re-runs the old engine).
3. **Baseline machinery** — fingerprint matching survives line drift,
   stale entries surface, rotten entries (fingerprint matching no line
   of the file on disk) fail the gate, suppression comments work.
4. **`lint contracts`** — the cross-artifact layer exits zero on the
   checked-in tree and nonzero on every seeded drift class (counter
   dropped from the README table, marker unregistered, record block
   unaligned in bench-diff, forwardable flag dropped from the child
   block) — the machine-checked successor of the hand-written
   source-pin tests, one seeded-drift teeth check kept per class.
5. **Concurrency layer** (PR 18) — the whole-tree thread model
   (``lint/threads.py``): fixture self-tests for G09 (guarded-by), G10
   (lock-order cycles, incl. a deliberate two-lock deadlock fixture),
   G11 (blocking under a contended lock), thread-root propagation
   through the call graph, PLUS the real-tree gate: zero G09-G11
   findings over the package, the global lock-order graph asserted
   cycle-free, and functional regression tests for the races the
   PR-18 triage sweep fixed (each cross-referenced to its fingerprint).
6. **The repo gate + strict mode** — the analyzer runs over the actual
   package (plus bench.py) against the checked-in ``lint_baseline.json``
   and must exit clean (pinned in-process AND as the `python -m … lint`
   subprocess the tier-1 driver fast-fails on), and a real 2-batch fused
   two-leg sweep runs under ``LLM_INTERP_STRICT`` semantics with
   ``blocked_transfers == 0`` and a flat warm-repeat
   ``recompile_events`` count.
"""

import json
import os
import shutil
import subprocess
import sys
import textwrap

import pytest

from llm_interpretation_replication_tpu.lint import (
    apply_baseline,
    build_model,
    collect_thread_findings,
    default_paths,
    default_rules,
    lint_paths,
    lint_source,
    load_baseline,
    model_from_paths,
    rotten_entries,
    save_baseline,
)
from llm_interpretation_replication_tpu.lint.cli import main as lint_main
from llm_interpretation_replication_tpu.lint.cli import repo_root
from llm_interpretation_replication_tpu.lint.contracts import (
    PKG_NAME,
    main as contracts_main,
)
from llm_interpretation_replication_tpu.utils import telemetry

REPO_ROOT = repo_root()

pytestmark = pytest.mark.lint


def run(path, source):
    return lint_source(path, textwrap.dedent(source), default_rules())


def rules_of(findings):
    return sorted(f.rule for f in findings)


# ---------------------------------------------------------------------------
# G01 host-sync
# ---------------------------------------------------------------------------

class TestG01HostSync:
    def test_item_in_jit_region(self):
        findings = run("ops/kernels.py", """
            import jax

            @jax.jit
            def f(x):
                return x.item()
        """)
        assert rules_of(findings) == ["G01"]
        assert ".item()" in findings[0].message

    def test_item_in_hot_module_outside_jit(self):
        findings = run("models/decoder.py", "def f(x):\n    return x.item()\n")
        assert rules_of(findings) == ["G01"]

    def test_item_in_cold_module_ok(self):
        assert run("stats/bootstrap.py",
                   "def f(x):\n    return x.item()\n") == []

    def test_np_asarray_in_jit(self):
        findings = run("ops/kernels.py", """
            import functools, jax
            import numpy as np

            @functools.partial(jax.jit, static_argnames=("k",))
            def f(x, k):
                return np.asarray(x)
        """)
        assert rules_of(findings) == ["G01"]

    def test_float_on_traced_param_in_jit(self):
        findings = run("ops/kernels.py", """
            import jax

            @jax.jit
            def f(x):
                return float(x) + 1.0
        """)
        assert rules_of(findings) == ["G01"]

    def test_float_on_static_param_ok(self):
        assert run("ops/kernels.py", """
            import functools, jax

            @functools.partial(jax.jit, static_argnames=("cfg",))
            def f(x, cfg):
                rd = int(cfg.rotary_pct * 64)
                return x * rd
        """) == []

    def test_shape_derived_local_ok(self):
        # `t = xb.shape[0]` is Python-static under trace: int(t * k) is fine
        assert run("ops/kernels.py", """
            import jax

            @jax.jit
            def f(xb, k):
                t = xb.shape[0]
                cap = max(1, int(0.5 * t))
                return xb[:cap]
        """) == []

    def test_launch_closure_fetch_flagged_consume_ok(self):
        findings = run("runtime/engine.py", """
            import numpy as np
            import jax.numpy as jnp

            def pipeline(batches):
                def launch(batch):
                    out = jnp.sum(batch.ids)
                    return np.asarray(out)      # device fetch in launch: BAD

                def consume(batch, out):
                    return np.asarray(out)      # sanctioned fetch point

                return launch, consume
        """)
        assert rules_of(findings) == ["G01"]
        assert findings[0].message.count("consume")


# ---------------------------------------------------------------------------
# G02 traced control flow
# ---------------------------------------------------------------------------

class TestG02TracedControlFlow:
    def test_if_on_traced_param(self):
        findings = run("m.py", """
            import jax

            @jax.jit
            def f(x):
                if x > 0:
                    return x
                return -x
        """)
        assert rules_of(findings) == ["G02"]

    def test_while_on_traced_local(self):
        findings = run("m.py", """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def f(x):
                s = jnp.sum(x)
                while s > 0:
                    s = s - 1
                return s
        """)
        assert "G02" in rules_of(findings)

    def test_static_argname_ok(self):
        assert run("m.py", """
            import functools, jax

            @functools.partial(jax.jit, static_argnames=("causal",))
            def f(x, causal):
                if causal:
                    return x
                return -x
        """) == []

    def test_is_none_and_isinstance_ok(self):
        assert run("m.py", """
            import jax

            @jax.jit
            def f(x, mask):
                if mask is None:
                    return x
                if isinstance(x, tuple):
                    return x[0]
                return x
        """) == []

    def test_shape_comparison_ok(self):
        assert run("m.py", """
            import jax

            @jax.jit
            def f(x):
                b = x.shape[0]
                if b % 2:
                    raise ValueError("odd batch")
                return x
        """) == []

    def test_plain_function_ok(self):
        assert run("m.py", "def f(x):\n    if x > 0:\n        return x\n    return -x\n") == []


# ---------------------------------------------------------------------------
# G03 PRNG key reuse
# ---------------------------------------------------------------------------

class TestG03KeyReuse:
    def test_double_consumption(self):
        findings = run("m.py", """
            import jax

            def init(hidden):
                key = jax.random.PRNGKey(0)
                a = jax.random.normal(key, (hidden,))
                b = jax.random.normal(key, (hidden,))
                return a, b
        """)
        assert rules_of(findings) == ["G03"]
        assert "'key'" in findings[0].message

    def test_split_is_clean(self):
        assert run("m.py", """
            import jax

            def init(hidden):
                key = jax.random.PRNGKey(0)
                ka, kb = jax.random.split(key)
                a = jax.random.normal(ka, (hidden,))
                b = jax.random.normal(kb, (hidden,))
                return a, b
        """) == []

    def test_fold_in_derives_not_consumes(self):
        assert run("m.py", """
            import jax

            def init(hidden):
                key = jax.random.PRNGKey(0)
                heads = jax.random.split(key, 4)
                extra = jax.random.fold_in(key, 99)
                return heads, jax.random.normal(extra, (hidden,))
        """) == []

    def test_loop_reuse(self):
        findings = run("m.py", """
            import jax

            def draws(n):
                key = jax.random.PRNGKey(0)
                out = []
                for i in range(n):
                    out.append(jax.random.uniform(key, (3,)))
                return out
        """)
        assert rules_of(findings) == ["G03"]
        assert "IDENTICAL" in findings[0].message

    def test_rebind_in_loop_ok(self):
        assert run("m.py", """
            import jax

            def draws(n):
                key = jax.random.PRNGKey(0)
                out = []
                for i in range(n):
                    key, sub = jax.random.split(key)
                    out.append(jax.random.uniform(sub, (3,)))
                return out
        """) == []

    def test_module_level_scan(self):
        findings = run("m.py", """
            import jax

            KEY = jax.random.PRNGKey(0)
            A = jax.random.normal(KEY, (4,))
            B = jax.random.normal(KEY, (4,))
        """)
        assert rules_of(findings) == ["G03"]


# ---------------------------------------------------------------------------
# G04 jit-boundary hygiene
# ---------------------------------------------------------------------------

class TestG04JitBoundary:
    def test_mutable_default(self):
        findings = run("m.py", """
            import jax

            @jax.jit
            def f(x, buckets=[]):
                return x
        """)
        assert "G04" in rules_of(findings)
        assert "mutable default" in " ".join(f.message for f in findings)

    def test_jit_on_method_self(self):
        findings = run("m.py", """
            import jax

            class Engine:
                @jax.jit
                def step(self, x):
                    return x
        """)
        assert "G04" in rules_of(findings)

    def test_jit_of_bound_attribute(self):
        findings = run("m.py", """
            import jax

            def build(engine):
                return jax.jit(engine.step)
        """)
        assert rules_of(findings) == ["G04"]

    def test_bare_jit_over_shape_param(self):
        findings = run("m.py", """
            import jax

            @jax.jit
            def prefill(x, cache_len):
                return x[:cache_len]
        """)
        assert "G04" in rules_of(findings)
        assert "cache_len" in " ".join(f.message for f in findings)

    def test_static_shape_param_ok(self):
        assert run("m.py", """
            import functools, jax

            @functools.partial(jax.jit, static_argnames=("cache_len",))
            def prefill(x, cache_len):
                return x[:cache_len]
        """) == []

    def test_jit_of_local_function_ok(self):
        assert run("m.py", """
            import jax

            def build(params):
                def step(x):
                    return x @ params
                return jax.jit(step)
        """) == []


# ---------------------------------------------------------------------------
# G05 broad except
# ---------------------------------------------------------------------------

class TestG05BroadExcept:
    SWALLOW = """
        def f():
            try:
                g()
            except Exception:
                return None
    """

    def test_swallow_in_fault_scope(self):
        findings = run("runtime/thing.py", self.SWALLOW)
        assert rules_of(findings) == ["G05"]

    def test_serve_package_in_fault_scope(self):
        """serve/ sits between device errors and the split/re-queue
        ladder, so G05 applies there from day one."""
        findings = run("serve/scheduler.py", self.SWALLOW)
        assert rules_of(findings) == ["G05"]

    def test_serve_load_in_g05_scope(self):
        """Satellite (ISSUE 11): the load harness drives scheduler
        launches and relays their failures, so a swallowed broad except
        there would hide a device error inside the measurement — G05
        applies to serve/load.py like the rest of serve/ (its deliberate
        result-relay catches carry disable annotations)."""
        findings = run("serve/load.py", self.SWALLOW)
        assert rules_of(findings) == ["G05"]

    def test_serve_pool_in_g05_scope(self):
        """Satellite (ISSUE 12): the EnginePool router/relay sits
        between replica engine errors and each request's future, and
        its unload path runs the verified engine teardown — a swallowed
        broad except there would drop a request silently or hide a
        teardown failure.  G05 has teeth on serve/pool.py (its vendor
        result-relay catch carries a disable annotation)."""
        findings = run("serve/pool.py", self.SWALLOW)
        assert rules_of(findings) == ["G05"]

    def test_runtime_engine_teardown_in_g05_scope(self):
        """The teardown path (ScoringEngine.close / EngineClosed) lives
        in runtime/ — already fault scope; pin it so a refactor moving
        close() out of scope cannot silently shed the gate."""
        findings = run("runtime/engine.py", self.SWALLOW)
        assert rules_of(findings) == ["G05"]

    def test_runtime_slots_in_g05_scope(self):
        """Satellite (ISSUE 14): the slot allocator's repack/refill loop
        sits directly on the decode hot path — a swallowed broad except
        there would drop pending rows silently or hide a device error
        from the OOM ladder.  G05 has teeth on runtime/slots.py."""
        findings = run("runtime/slots.py", self.SWALLOW)
        assert rules_of(findings) == ["G05"]

    def test_out_of_scope_module_ok(self):
        assert run("viz/figures.py", self.SWALLOW) == []

    def test_reraise_ok(self):
        assert run("runtime/thing.py", """
            def f():
                try:
                    g()
                except Exception:
                    cleanup()
                    raise
        """) == []

    def test_typed_except_ok(self):
        assert run("runtime/thing.py", """
            def f():
                try:
                    g()
                except (ValueError, OSError):
                    return None
        """) == []

    def test_bare_except_flagged(self):
        findings = run("sweeps/s.py", """
            def f():
                try:
                    g()
                except:
                    pass
        """)
        assert rules_of(findings) == ["G05"]

    def test_suppression_comment(self):
        assert run("runtime/thing.py", """
            def f():
                try:
                    g()
                # graftlint: disable=G05 deliberate keep-alive
                except Exception:
                    return None
        """) == []

    def test_trailing_suppression_comment(self):
        assert run("runtime/thing.py", """
            def f():
                try:
                    g()
                except Exception:  # graftlint: disable=G05 keep-alive
                    return None
        """) == []

    def test_tuple_except_containing_broad_flagged(self):
        findings = run("runtime/thing.py", """
            def f():
                try:
                    g()
                except (Exception, OSError):
                    return None
        """)
        assert rules_of(findings) == ["G05"]

    def test_tuple_of_typed_excepts_ok(self):
        assert run("runtime/thing.py", """
            def f():
                try:
                    g()
                except (ValueError, OSError):
                    return None
        """) == []

    def test_trailing_suppression_does_not_bleed_to_next_line(self):
        # the same-line disable must not exempt the NEXT statement's
        # violation
        findings = run("models/decoder.py", """
            def f(x):
                y = x  # graftlint: disable=G01 unrelated trailing comment
                return x.item()
        """)
        assert rules_of(findings) == ["G01"]

    def test_suppression_is_rule_specific(self):
        findings = run("runtime/thing.py", """
            def f():
                try:
                    g()
                except Exception:  # graftlint: disable=G01 wrong rule
                    return None
        """)
        assert rules_of(findings) == ["G05"]


# ---------------------------------------------------------------------------
# Interprocedural device regions (PR 15 — the call-graph layer)
# ---------------------------------------------------------------------------

class TestInterprocedural:
    HELPER_FROM_JIT = """
        import jax

        def helper(x):
            return x.item()

        @jax.jit
        def f(x):
            return helper(x)
    """

    def test_helper_called_from_jit_item_flagged(self):
        """THE acceptance fixture: a jit region calls a same-module
        helper containing ``.item()`` — G01 fires inside the helper, and
        the message names the root and hop count so the finding is
        explainable."""
        findings = run("m.py", self.HELPER_FROM_JIT)
        assert rules_of(findings) == ["G01"]
        assert findings[0].line == 5  # inside helper, not at the call
        assert "reachable from jit region 'f'" in findings[0].message
        assert "1 call hop" in findings[0].message

    def test_pr3_engine_provably_missed_it(self):
        """The other direction of the acceptance pin: the per-function
        PR-3 engine (``interprocedural=False``) does NOT flag the same
        fixture — the call-graph layer is what catches it."""
        findings = lint_source("m.py", textwrap.dedent(self.HELPER_FROM_JIT),
                               default_rules(), interprocedural=False)
        assert findings == []

    def test_two_hop_call_chain(self):
        findings = run("m.py", """
            import jax
            import numpy as np

            def inner(y):
                return np.asarray(y)

            def outer(y):
                return inner(y)

            @jax.jit
            def f(x):
                return outer(x)
        """)
        assert rules_of(findings) == ["G01"]
        assert "2 call hops" in findings[0].message

    def test_alias_import_jit_resolves(self):
        """``from jax import jit as fastjit`` still roots the graph —
        alias resolution is part of the layer-1 contract."""
        findings = run("m.py", """
            from jax import jit as fastjit

            def helper(x):
                return x.item()

            @fastjit
            def f(x):
                return helper(x)
        """)
        assert rules_of(findings) == ["G01"]

    def test_module_level_rebind_resolves(self):
        findings = run("m.py", """
            import jax

            def _impl(x):
                return x.item()

            score = _impl

            @jax.jit
            def f(x):
                return score(x)
        """)
        assert rules_of(findings) == ["G01"]

    def test_self_method_call_resolves(self):
        findings = run("m.py", """
            import jax

            class Engine:
                def _gather(self, x):
                    return x.item()

                @jax.jit
                def step(self, x):
                    return self._gather(x)
        """)
        assert "G01" in rules_of(findings)  # the helper, via the graph
        # (G04 also fires on jit-over-self — independent, pre-existing)

    def test_recursion_terminates_and_depth_bound_caps(self):
        """The propagation fixpoint terminates on recursion, and a chain
        deeper than INTERPROCEDURAL_DEPTH hops is (deliberately) out of
        reach — the bound keeps findings explainable."""
        from llm_interpretation_replication_tpu.lint.visitor import (
            INTERPROCEDURAL_DEPTH,
        )

        assert run("m.py", """
            import jax

            def rec(x, n):
                if n == 0:
                    return x
                return rec(x, n - 1)

            @jax.jit
            def f(x):
                return rec(x, 3)
        """) == []  # n is a host int; x never .item()'d — just terminate
        deep = "import jax\n\n"
        last = INTERPROCEDURAL_DEPTH + 1
        deep += f"def h{last}(x):\n    return x.item()\n\n"
        for i in range(last - 1, 0, -1):
            deep += f"def h{i}(x):\n    return h{i + 1}(x)\n\n"
        deep += "@jax.jit\ndef f(x):\n    return h1(x)\n"
        assert run("m.py", deep) == []

    def test_host_only_helper_params_not_flooded(self):
        """A reached helper only treats SEEDED params (those receiving
        traced-looking args at device call sites) as traced — a host
        counter param must not trip G02 in every reached helper."""
        assert run("m.py", """
            import jax

            def helper(x, n):
                for i in range(n):
                    pass
                return x * 2

            @jax.jit
            def f(x):
                return helper(x, 4)
        """) == []

    def test_launch_closure_helper_fetch_flagged(self):
        """The launch-pipeline root propagates too: a helper called from
        a hot module's launch closure may not materialize device values."""
        src = """
            import numpy as np
            import jax.numpy as jnp

            def fetch_rows(out):
                return np.asarray(out)

            def pipeline(batches):
                def launch(batch):
                    out = jnp.sum(batch.ids)
                    return fetch_rows(out)

                def consume(batch, out):
                    return np.asarray(out)

                return launch, consume
        """
        findings = run("runtime/engine.py", src)
        assert rules_of(findings) == ["G01"]
        assert "launch closure" in findings[0].message
        assert lint_source("runtime/engine.py", textwrap.dedent(src),
                           default_rules(), interprocedural=False) == []


# ---------------------------------------------------------------------------
# G06 telemetry discipline
# ---------------------------------------------------------------------------

class TestG06TelemetryDiscipline:
    def test_concatenated_name_flagged(self):
        findings = run("utils/m.py", """
            from .telemetry import record_counter

            def f(kind):
                record_counter("slot_" + kind)
        """)
        assert rules_of(findings) == ["G06"]

    def test_fstring_dynamic_base_flagged(self):
        findings = run("utils/m.py", """
            from .telemetry import record_counter

            def f(kind):
                record_counter(f"slot_{kind}")
        """)
        assert rules_of(findings) == ["G06"]

    def test_labeled_fstring_with_literal_keys_ok(self):
        assert run("utils/m.py", """
            from .telemetry import record_counter

            def f(leg):
                record_counter(f"k_steps_saved|leg={leg}", 3)
        """) == []

    def test_dynamic_label_key_flagged(self):
        findings = run("utils/m.py", """
            from .telemetry import record_counter

            def f(k):
                record_counter(f"slot_rows|{k}=x", 1)
        """)
        assert rules_of(findings) == ["G06"]

    def test_malformed_label_section_flagged(self):
        findings = run("utils/m.py", """
            from .telemetry import record_counter

            def f():
                record_counter("slot_rows|leg confidence", 1)
        """)
        assert rules_of(findings) == ["G06"]

    def test_chokepoint_forwarded_param_ok(self):
        """The slots/scheduler idiom: a wrapper forwards its own name
        param — its CALLERS are the checked surface (and `lint
        contracts` enumerates names through the chokepoint)."""
        assert run("runtime/slots.py", """
            from ..utils.telemetry import record_counter

            def slot_counter(name, value, leg, workload):
                record_counter(f"{name}|leg={leg},workload={workload}",
                               value)
        """) == []

    def test_module_constant_ok(self):
        assert run("runtime/strict.py", """
            from ..utils.telemetry import record_counter

            RECOMPILE_COUNTER = "recompile_events"

            def f():
                record_counter(RECOMPILE_COUNTER)
        """) == []

    def test_unresolvable_name_flagged(self):
        findings = run("utils/m.py", """
            from .telemetry import record_counter

            def f():
                name = make_name()
                record_counter(name)
        """)
        assert rules_of(findings) == ["G06"]

    def test_unregistered_fault_kind_flagged(self):
        """A literal record_fault kind outside FAULT_KINDS forks an
        event stream no flight trigger or listener matches."""
        findings = run("serve/m.py", """
            from ..utils.telemetry import record_fault

            def f(rid):
                record_fault("pool_replica_crashd", replica=rid)
        """)
        assert rules_of(findings) == ["G06"]
        assert "FAULT_KINDS" in findings[0].message

    def test_registered_fault_kind_ok(self):
        assert run("serve/m.py", """
            from ..utils.telemetry import record_fault

            def f(rid, wedged):
                record_fault("pool_replica_wedged" if wedged
                             else "pool_replica_crash", replica=rid)
        """) == []

    def test_dynamic_fault_kind_out_of_scope(self):
        """Forwarded/dynamic kinds are the chokepoint idiom — the
        registry check only bites on literals."""
        assert run("serve/m.py", """
            from ..utils.telemetry import record_fault

            def f(kind, rid):
                record_fault(kind, replica=rid)
        """) == []

    def test_fault_listener_kind_sets_stay_registered(self):
        """Listeners match on event['kind'] (add_fault_listener takes no
        kind filter), so the consumer-side literal sets must be subsets
        of the same registry G06 holds producers to — a trigger kind
        outside FAULT_KINDS could never fire."""
        from llm_interpretation_replication_tpu.obs import flight
        from llm_interpretation_replication_tpu.utils import telemetry

        assert set(flight.TRIGGER_KINDS) <= telemetry.FAULT_KINDS

    def test_ifexp_of_literals_ok(self):
        assert run("utils/m.py", """
            from .telemetry import record_counter

            def f(ok):
                record_counter("cache_hit" if ok else "cache_miss")
        """) == []


# ---------------------------------------------------------------------------
# G07 cache scale awareness
# ---------------------------------------------------------------------------

class TestG07CacheScaleAwareness:
    def test_direct_reshape_on_cache_k_flagged(self):
        findings = run("runtime/m.py", """
            import jax.numpy as jnp

            def f(cache):
                return jnp.reshape(cache.k, (2, -1))
        """)
        assert rules_of(findings) == ["G07"]
        assert "cache_kv_map" in findings[0].message

    def test_concat_inside_list_arg_flagged(self):
        findings = run("serve/m.py", """
            import jax.numpy as jnp

            def f(cache, other):
                return jnp.concatenate([cache.k, other.v], axis=1)
        """)
        assert rules_of(findings) == ["G07"]

    def test_ops_helpers_exempt(self):
        assert run("ops/quant.py", """
            import jax.numpy as jnp

            def f(cache):
                return jnp.reshape(cache.k, (2, -1))
        """) == []

    def test_decoder_owner_module_exempt(self):
        """models/decoder.py OWNS the layout (cache_kv_map and the
        append/fold sites live there) — exempt by construction."""
        assert run("models/decoder.py", """
            import jax.numpy as jnp

            def cache_kv_map(cache, fn):
                return fn(cache.k)
        """) == []

    def test_metadata_access_ok(self):
        assert run("runtime/m.py", """
            import jax.numpy as jnp

            def f(cache):
                return jnp.zeros(cache.k.shape, cache.k.dtype)
        """) == []

    def test_non_cache_base_ok(self):
        assert run("runtime/m.py", """
            import jax.numpy as jnp

            def f(x):
                return jnp.reshape(x.k, (2, -1))
        """) == []


# ---------------------------------------------------------------------------
# G08 span hygiene
# ---------------------------------------------------------------------------

class TestG08SpanHygiene:
    def test_unmanaged_span_flagged(self):
        findings = run("runtime/m.py", """
            from ..obs import tracer

            def f():
                s = tracer.span("x", phase="decode")
                s.close()
        """)
        assert rules_of(findings) == ["G08"]

    def test_with_managed_span_ok(self):
        assert run("runtime/m.py", """
            from ..obs import tracer

            def f():
                with tracer.span("x", phase="decode"):
                    pass
        """) == []

    def test_enter_context_managed_ok(self):
        assert run("runtime/m.py", """
            def f(stack, obs):
                stack.enter_context(obs.span("x", phase="decode"))
        """) == []

    def test_unknown_phase_flagged(self):
        findings = run("runtime/m.py", """
            from ..obs import tracer

            def f():
                with tracer.span("x", phase="warmup_zap"):
                    pass
        """)
        assert rules_of(findings) == ["G08"]
        assert "KNOWN_PHASES" in findings[0].message

    def test_computed_phase_flagged(self):
        findings = run("runtime/m.py", """
            from ..obs import tracer

            def f(p):
                with tracer.span("x", phase=p):
                    pass
        """)
        assert rules_of(findings) == ["G08"]

    def test_every_known_phase_passes(self):
        from llm_interpretation_replication_tpu.obs.tracer import (
            KNOWN_PHASES,
        )

        for phase in sorted(KNOWN_PHASES):
            assert run("runtime/m.py", f"""
                from ..obs import tracer

                def f():
                    with tracer.span("x", phase="{phase}"):
                        pass
            """) == [], phase


# ---------------------------------------------------------------------------
# Baseline machinery
# ---------------------------------------------------------------------------

class TestBaseline:
    def _findings(self, line_pad=0):
        src = "\n" * line_pad + textwrap.dedent("""
            def f():
                try:
                    g()
                except Exception:
                    return None
        """)
        return lint_source("runtime/thing.py", src, default_rules())

    def test_roundtrip_and_line_drift(self, tmp_path):
        findings = self._findings()
        path = str(tmp_path / "baseline.json")
        save_baseline(findings, path,
                      {findings[0].fingerprint: "known keep-alive"})
        entries = load_baseline(path)
        assert entries[0]["rationale"] == "known keep-alive"
        # the same violation 7 lines lower still matches (fingerprint is
        # line-independent)
        drifted = self._findings(line_pad=7)
        new, stale, matched = apply_baseline(drifted, entries)
        assert new == [] and stale == [] and matched == 1

    def test_stale_entry_surfaces(self, tmp_path):
        findings = self._findings()
        path = str(tmp_path / "baseline.json")
        save_baseline(findings, path)
        new, stale, matched = apply_baseline([], load_baseline(path))
        assert matched == 0 and len(stale) == 1

    def test_entry_absorbs_once(self, tmp_path):
        findings = self._findings()
        path = str(tmp_path / "baseline.json")
        save_baseline(findings, path)
        twice = findings + findings
        new, stale, matched = apply_baseline(twice, load_baseline(path))
        assert matched == 1 and len(new) == 1

    def test_diff_and_write_baseline_conflict(self):
        """`--diff --write-baseline` would rewrite the baseline from a
        changed-files subset, silently dropping every entry for
        untouched files — refused outright."""
        assert lint_main(["--diff", "--write-baseline"]) == 2

    def test_rot_missing_file(self, tmp_path):
        """An entry whose file is gone is rot regardless of what the
        current run linted — the scope-independent check the ``--diff``
        mode relies on."""
        entries = [{"rule": "G05", "path": "runtime/gone.py",
                    "code": "except Exception:", "rationale": "x"}]
        assert rotten_entries(entries, str(tmp_path)) == entries

    def test_rot_fingerprint_no_longer_in_file(self, tmp_path):
        d = tmp_path / "runtime"
        d.mkdir()
        (d / "x.py").write_text("def f():\n    return g()\n")
        entries = [{"rule": "G05", "path": "runtime/x.py",
                    "code": "except Exception:", "rationale": "x"}]
        assert rotten_entries(entries, str(tmp_path)) == entries

    def test_line_drift_is_not_rot(self, tmp_path):
        d = tmp_path / "runtime"
        d.mkdir()
        (d / "x.py").write_text(
            "\n" * 9 + "def f():\n    try:\n        g()\n"
            "    except Exception:\n        return None\n")
        entries = [{"rule": "G05", "path": "runtime/x.py",
                    "code": "except Exception:", "rationale": "x"}]
        assert rotten_entries(entries, str(tmp_path)) == []

    def test_checked_in_baseline_has_no_rot(self):
        from llm_interpretation_replication_tpu.lint.cli import (
            default_baseline_path,
        )

        entries = load_baseline(default_baseline_path())
        assert rotten_entries(entries, REPO_ROOT) == []

    def test_cli_gate_exit_codes(self, tmp_path):
        bad = tmp_path / "runtime"
        bad.mkdir()
        (bad / "x.py").write_text(
            "def f():\n    try:\n        g()\n    except Exception:\n"
            "        return None\n")
        empty_baseline = tmp_path / "b.json"
        assert lint_main([str(bad), "--baseline", str(empty_baseline)]) == 1
        # --write-baseline grandfathers it; the gate then passes
        assert lint_main([str(bad), "--baseline", str(empty_baseline),
                          "--write-baseline"]) == 0
        assert lint_main([str(bad), "--baseline", str(empty_baseline)]) == 0
        # fixing the code turns the entry stale — the ratchet FAILS the
        # gate until the entry is deleted (it would otherwise re-shield
        # the next violation with the same fingerprint)
        (bad / "x.py").write_text("def f():\n    return g()\n")
        assert lint_main([str(bad), "--baseline", str(empty_baseline)]) == 1
        assert lint_main([str(bad), "--baseline", str(empty_baseline),
                          "--write-baseline"]) == 0
        assert lint_main([str(bad), "--baseline", str(empty_baseline)]) == 0


# ---------------------------------------------------------------------------
# lint contracts — the cross-artifact layer (PR 15)
# ---------------------------------------------------------------------------

def _write_tree(root, files):
    for rel, text in files.items():
        path = root / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(text))


class TestContractsCleanTree:
    def test_checked_in_tree_is_clean(self):
        """THE gate: code, README tables, pyproject registry, bench-diff
        classification, and the child contract agree on the real tree."""
        assert contracts_main([]) == 0

    def test_json_format(self, capsys):
        assert contracts_main(["--format", "json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc == {"drift": []}

    def test_unknown_only_kind_exits_2(self):
        assert contracts_main(["--only", "nonsense"]) == 2


class TestContractsTeeth:
    """One seeded-drift teeth check per contract class — the pins kept
    from the hand-written source-pin era, now proving the CHECKER fails
    rather than re-pinning artifact contents by hand."""

    def test_counter_dropped_from_readme_table(self, tmp_path, capsys):
        _write_tree(tmp_path, {
            "README.md": """
                ### Telemetry counters

                | Counter | Meaning |
                |---|---|
                | `real_counter` | documented and recorded |
            """,
            f"{PKG_NAME}/mod.py": """
                from .utils.telemetry import record_counter

                def f():
                    record_counter("real_counter")
                    record_counter("ghost_counter")
            """,
        })
        assert contracts_main(["--root", str(tmp_path),
                               "--only", "counter-table"]) == 1
        out = capsys.readouterr().out
        assert "ghost_counter" in out and "missing" in out

    def test_documented_counter_never_recorded(self, tmp_path, capsys):
        _write_tree(tmp_path, {
            "README.md": """
                ### Telemetry counters

                | Counter | Meaning |
                |---|---|
                | `never_recorded` | a row readers wait on forever |
            """,
            f"{PKG_NAME}/mod.py": "x = 1\n",
        })
        assert contracts_main(["--root", str(tmp_path),
                               "--only", "counter-table"]) == 1
        assert "never_recorded" in capsys.readouterr().out

    def test_label_value_param_is_not_a_wrapper(self, tmp_path):
        """A helper whose param only interpolates a LABEL VALUE
        (``f"k_steps_saved|leg={leg}"``) is NOT a name-forwarding
        chokepoint — its call-site argument strings must not register as
        counter names."""
        _write_tree(tmp_path, {
            "README.md": """
                ### Telemetry counters

                | Counter | Meaning |
                |---|---|
                | `k_steps_saved` | the only real counter |
            """,
            f"{PKG_NAME}/mod.py": """
                from .utils.telemetry import record_counter

                def bump(leg):
                    record_counter(f"k_steps_saved|leg={leg}")

                def run():
                    bump("decode")
            """,
        })
        assert contracts_main(["--root", str(tmp_path),
                               "--only", "counter-table"]) == 0

    def test_docstring_mention_is_not_a_read(self, tmp_path, capsys):
        """A docstring mentioning an ALIGNED block's name does not count
        as benchdiff reading it."""
        diff_py = self._copy_bench_tree(tmp_path)
        text = diff_py.read_text()
        diff_py.write_text(text.replace(
            'ALIGNED_BLOCKS = ("secondary",',
            'ALIGNED_BLOCKS = ("phantom_block", "secondary",', 1)
            + '\n\ndef _doc_only():\n    """mentions phantom_block."""\n')
        assert contracts_main(["--root", str(tmp_path),
                               "--only", "record-blocks"]) == 1
        assert "phantom_block" in capsys.readouterr().out

    def test_labeled_and_wildcard_rows_resolve(self, tmp_path):
        """The real table's spellings: `a` / `b` pairs, `slot_*` wildcard
        rows, and labeled-twin `name\\|k=…` cells all match their code
        counters — no false drift."""
        _write_tree(tmp_path, {
            "README.md": """
                ### Telemetry counters

                | Counter | Meaning |
                |---|---|
                | `hit` / `miss` | a pair row |
                | `slot_*` | wildcard family |
                | `k_steps_saved` | labeled twins `k_steps_saved\\|leg=…` |
            """,
            f"{PKG_NAME}/mod.py": """
                from .utils.telemetry import record_counter

                def f(leg):
                    record_counter("hit")
                    record_counter("miss")
                    record_counter("slot_rows|leg=binary")
                    record_counter(f"k_steps_saved|leg={leg}")
            """,
        })
        assert contracts_main(["--root", str(tmp_path),
                               "--only", "counter-table"]) == 0

    # NOTE: the marker-usage scan greps tests/ source text, so the seeded
    # fixtures below assemble "pytest.mark.<name>" at runtime — spelling
    # it literally HERE would make this file itself the drift.
    _MARK = "pytest." + "mark."

    def test_marker_unregistered(self, tmp_path, capsys):
        _write_tree(tmp_path, {
            "pyproject.toml": """
                [tool.pytest.ini_options]
                markers = [
                    "registered: a real marker",
                ]
            """,
            "tests/test_x.py": f"""
                import pytest

                pytestmark = {self._MARK}ghostmark

                @{self._MARK}registered
                def test_y():
                    pass
            """,
        })
        assert contracts_main(["--root", str(tmp_path),
                               "--only", "markers"]) == 1
        assert "ghostmark" in capsys.readouterr().out

    def test_marker_registered_but_unused(self, tmp_path, capsys):
        _write_tree(tmp_path, {
            "pyproject.toml": """
                [tool.pytest.ini_options]
                markers = [
                    "registered: a real marker",
                    "deadmark: nothing uses this",
                ]
            """,
            "tests/test_x.py": f"""
                import pytest

                pytestmark = {self._MARK}registered
            """,
        })
        assert contracts_main(["--root", str(tmp_path),
                               "--only", "markers"]) == 1
        assert "deadmark" in capsys.readouterr().out

    def test_slow_selector_mark_is_exempt(self, tmp_path):
        """``slow`` is the tier-1 gate's exclusion selector (`-m 'not
        slow'`): registered-but-unused must NOT drift — the registration
        documents the gate convention."""
        _write_tree(tmp_path, {
            "pyproject.toml": """
                [tool.pytest.ini_options]
                markers = [
                    "slow: excluded from the tier-1 gate",
                ]
            """,
            "tests/test_x.py": "def test_y():\n    pass\n",
        })
        assert contracts_main(["--root", str(tmp_path),
                               "--only", "markers"]) == 0

    # -- record-blocks + child-flags teeth run against COPIES of the real
    # artifacts, so the seeded drift is exactly one edit away from the
    # checked-in truth --------------------------------------------------

    def _copy_bench_tree(self, tmp_path):
        shutil.copy(os.path.join(REPO_ROOT, "bench.py"),
                    tmp_path / "bench.py")
        obs = tmp_path / PKG_NAME / "obs"
        obs.mkdir(parents=True)
        shutil.copy(os.path.join(REPO_ROOT, PKG_NAME, "obs",
                                 "benchdiff.py"), obs / "benchdiff.py")
        return obs / "benchdiff.py"

    def test_record_block_unaligned_in_benchdiff(self, tmp_path, capsys):
        diff_py = self._copy_bench_tree(tmp_path)
        assert contracts_main(["--root", str(tmp_path),
                               "--only", "record-blocks"]) == 0
        capsys.readouterr()
        text = diff_py.read_text()
        assert '"occupancy",' in text
        diff_py.write_text(text.replace('"occupancy",', "", 1))
        assert contracts_main(["--root", str(tmp_path),
                               "--only", "record-blocks"]) == 1
        assert "occupancy" in capsys.readouterr().out

    def test_aligned_block_no_longer_read(self, tmp_path, capsys):
        """The other direction: benchdiff CLAIMS to align a block it
        never reads."""
        diff_py = self._copy_bench_tree(tmp_path)
        text = diff_py.read_text()
        diff_py.write_text(text.replace(
            'ALIGNED_BLOCKS = ("secondary",',
            'ALIGNED_BLOCKS = ("phantom_block", "secondary",', 1))
        assert contracts_main(["--root", str(tmp_path),
                               "--only", "record-blocks"]) == 1
        assert "phantom_block" in capsys.readouterr().out

    def test_child_override_undeclared(self, tmp_path, capsys):
        self._copy_bench_tree(tmp_path)
        bench = tmp_path / "bench.py"
        text = bench.read_text()
        assert '"mode", "sweep_repeats", "kv_dtype",' in text
        bench.write_text(text.replace(
            '"mode", "sweep_repeats", "kv_dtype",',
            '"mode", "sweep_repeats",', 1))
        assert contracts_main(["--root", str(tmp_path),
                               "--only", "child-flags"]) == 1
        assert "child.kv_dtype" in capsys.readouterr().out

    def test_forwardable_flag_dropped_from_child_block(self, tmp_path,
                                                       capsys):
        """The acceptance drift class: a flag DECLARED forwardable that
        the child block never assigns."""
        self._copy_bench_tree(tmp_path)
        bench = tmp_path / "bench.py"
        text = bench.read_text()
        bench.write_text(text.replace(
            '"mode", "sweep_repeats",',
            '"mode", "sweep_repeats", "ghost_flag",', 1))
        assert contracts_main(["--root", str(tmp_path),
                               "--only", "child-flags"]) == 1
        assert "ghost_flag" in capsys.readouterr().out

    def test_phase_dropped_from_readme_table(self, tmp_path, capsys):
        _write_tree(tmp_path, {
            "README.md": """
                ### Span / phase names (obs/)

                | Phase | Where the time goes |
                |---|---|
                | `decode` | decode chunks |
            """,
            f"{PKG_NAME}/obs/tracer.py": """
                KNOWN_PHASES = frozenset({"decode", "ghost_phase"})
            """,
        })
        assert contracts_main(["--root", str(tmp_path),
                               "--only", "phase-table"]) == 1
        assert "ghost_phase" in capsys.readouterr().out

    def test_uncited_calibration_coefficient_fails(self, tmp_path, capsys):
        """ROADMAP item 4 satellite: a NEW pinned cost-model literal
        without an ``# anchor: BENCH_rNN`` / ``# prior:`` citation fails
        the gate — an uncited number is one nobody can ever refit."""
        _write_tree(tmp_path, {
            f"{PKG_NAME}/runtime/plan.py": """
                RESERVE_BYTES = 3 << 28  # anchor: BENCH_r05
            """,
            f"{PKG_NAME}/runtime/plan_search.py": """
                #: ceiling solved from the r05 saturation pair
                #: anchor: BENCH_r05
                ROWS_CEILING = 169.5
                NEW_GUESS = 0.25
            """,
        })
        assert contracts_main(["--root", str(tmp_path),
                               "--only", "calibration"]) == 1
        out = capsys.readouterr().out
        assert "NEW_GUESS" in out and "ROWS_CEILING" not in out

    def test_cited_coefficients_and_menus_pass(self, tmp_path):
        """Both citation spellings pass (trailing or in the comment
        block above), and tuple menus — enumerated search axes, not
        calibrated coefficients — need no citation."""
        _write_tree(tmp_path, {
            f"{PKG_NAME}/runtime/plan.py": """
                HBM_BYTES_V5E = 16 << 30  # prior: v5e device spec
            """,
            f"{PKG_NAME}/runtime/plan_search.py": """
                #: anchor: BENCH_r05
                ROWS_CEILING = 169.5
                K_ACCEPT_PRIOR = 0.9  # prior: K-Forcing regime guess
                DEFAULT_DECODE_KS = (1, 2, 4, 8)
            """,
        })
        assert contracts_main(["--root", str(tmp_path),
                               "--only", "calibration"]) == 0

    def test_bare_prior_without_rationale_fails(self, tmp_path, capsys):
        """``# prior:`` with no rationale text is not a citation — the
        recalibration story is the point."""
        _write_tree(tmp_path, {
            f"{PKG_NAME}/runtime/plan.py": """
                RESERVE_BYTES = 3 << 28  # prior:
            """,
            f"{PKG_NAME}/runtime/plan_search.py": "",
        })
        assert contracts_main(["--root", str(tmp_path),
                               "--only", "calibration"]) == 1
        assert "RESERVE_BYTES" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# Tier-1 gate wiring: the subprocess entry points the driver fast-fails on
# ---------------------------------------------------------------------------

class TestTier1GateSubprocess:
    def _run(self, *argv):
        return subprocess.run(
            [sys.executable, "-m", PKG_NAME, *argv],
            capture_output=True, text=True, cwd=REPO_ROOT, timeout=300)

    def test_lint_gate_exits_zero(self):
        proc = self._run("lint")
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_contracts_gate_exits_zero(self):
        proc = self._run("lint", "contracts")
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_diff_modes_exit_zero(self):
        """--diff (both layers) must pass on the checked-in tree — the
        cheap-CI path a pre-pytest hook runs."""
        proc = self._run("lint", "--diff")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        proc = self._run("lint", "contracts", "--diff")
        assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# The repo gate
# ---------------------------------------------------------------------------

class TestRepoGate:
    def test_repo_is_clean_vs_checked_in_baseline(self):
        """THE gate: the analyzer over the real tree + lint_baseline.json
        must report zero new findings.  A PR introducing any fixture-class
        violation (the self-tests above) fails here."""
        assert lint_main([]) == 0

    def test_checked_in_baseline_is_small_and_justified(self):
        from llm_interpretation_replication_tpu.lint.cli import (
            default_baseline_path,
        )

        entries = load_baseline(default_baseline_path())
        assert len(entries) <= 10
        for e in entries:
            assert e["rationale"].strip(), f"no rationale: {e}"
            assert "TODO" not in e["rationale"]

    def test_default_paths_cover_package_and_bench(self):
        paths = default_paths()
        assert any(p.endswith("llm_interpretation_replication_tpu")
                   for p in paths)
        assert any(p.endswith("bench.py") for p in paths)

    def test_default_paths_cover_serve_package(self):
        """serve/ lives inside the scanned package dir, so the repo gate
        lints it on every run — asserted via the gate's own file walker."""
        from llm_interpretation_replication_tpu.lint.cli import (
            iter_python_files,
        )

        pkg = next(p for p in default_paths()
                   if p.endswith("llm_interpretation_replication_tpu"))
        assert os.path.isdir(os.path.join(pkg, "serve"))
        scanned = [f.replace(os.sep, "/") for f in iter_python_files([pkg])]
        assert any("/serve/scheduler.py" in f for f in scanned)
        assert any("/serve/queue.py" in f for f in scanned)
        # ISSUE-11: the load harness joins the same gate
        assert any("/serve/load.py" in f for f in scanned)
        # ISSUE-12: the EnginePool joins the same gate
        assert any("/serve/pool.py" in f for f in scanned)

    def test_serve_package_lint_clean_without_baseline(self):
        """Satellite: serve/ ships lint-clean from day one — zero
        findings even with NO baseline, and no lint_baseline.json entry
        grandfathers anything under serve/."""
        from llm_interpretation_replication_tpu.lint.cli import (
            default_baseline_path,
        )

        pkg = next(p for p in default_paths()
                   if p.endswith("llm_interpretation_replication_tpu"))
        # the load harness (ISSUE 11) and the EnginePool (ISSUE 12) are
        # part of the zero-baseline pin — assert they exist so this gate
        # cannot green-light their removal
        assert os.path.exists(os.path.join(pkg, "serve", "load.py"))
        assert os.path.exists(os.path.join(pkg, "serve", "pool.py"))
        assert lint_paths([os.path.join(pkg, "serve")]) == []
        entries = load_baseline(default_baseline_path())
        assert not [e for e in entries if e.get("path", "").startswith(
            "llm_interpretation_replication_tpu/serve/")]

    def test_default_paths_cover_obs_package(self):
        """obs/ lives inside the scanned package dir, so the repo gate
        lints it on every run — asserted via the gate's own file walker
        (the serve/ gate's pattern)."""
        from llm_interpretation_replication_tpu.lint.cli import (
            iter_python_files,
        )

        pkg = next(p for p in default_paths()
                   if p.endswith("llm_interpretation_replication_tpu"))
        assert os.path.isdir(os.path.join(pkg, "obs"))
        scanned = [f.replace(os.sep, "/") for f in iter_python_files([pkg])]
        assert any("/obs/tracer.py" in f for f in scanned)
        assert any("/obs/report.py" in f for f in scanned)
        assert any("/obs/profiler.py" in f for f in scanned)
        # ISSUE-9: the run-health layer's modules join the same gate
        assert any("/obs/metrics.py" in f for f in scanned)
        assert any("/obs/flight.py" in f for f in scanned)
        assert any("/obs/benchdiff.py" in f for f in scanned)

    def test_obs_package_lint_clean_without_baseline(self):
        """Satellite (ISSUE 6): obs/ ships lint-clean from day one — zero
        findings even with NO baseline (G01-G08; its best-effort catches
        carry disable annotations), and no lint_baseline.json entry
        grandfathers anything under obs/."""
        from llm_interpretation_replication_tpu.lint.cli import (
            default_baseline_path,
        )

        pkg = next(p for p in default_paths()
                   if p.endswith("llm_interpretation_replication_tpu"))
        assert lint_paths([os.path.join(pkg, "obs")]) == []
        entries = load_baseline(default_baseline_path())
        assert not [e for e in entries if e.get("path", "").startswith(
            "llm_interpretation_replication_tpu/obs/")]

    def test_obs_is_in_g05_fault_scope(self):
        """obs/ spans wrap the engine's launch/consume callbacks, so a
        broad except that swallows there hides a device error inside the
        instrumentation — G05 applies (the teeth behind the gate above)."""
        findings = run("obs/tracer.py", """
            def close_span(rec):
                try:
                    rec.close()
                except Exception:
                    pass
        """)
        assert rules_of(findings) == ["G05"]

    def test_obs_metrics_and_flight_are_in_g05_fault_scope(self):
        """ISSUE-9 satellite: the run-health modules sit on the fault
        path (the flight recorder runs INSIDE fault handling), so a
        swallowing broad except there is exactly the bug G05 exists to
        catch — fires for the new modules like any runtime/ file."""
        for path in ("obs/metrics.py", "obs/flight.py",
                     "obs/benchdiff.py"):
            findings = run(path, """
                def sample_tick(reg):
                    try:
                        reg.sample()
                    except Exception:
                        pass
            """)
            assert rules_of(findings) == ["G05"], path

    def test_hot_modules_are_scanned_by_the_gate(self):
        """Consolidated scan pin (PR 15): every module a past PR named in
        its per-issue walker pin sits inside the default-paths walk — one
        list instead of six hand-maintained copies that drifted one PR at
        a time (the same rot class `lint contracts` machine-checks for
        the doc/config artifacts)."""
        from llm_interpretation_replication_tpu.lint.cli import (
            iter_python_files,
        )

        pkg = next(p for p in default_paths()
                   if p.endswith("llm_interpretation_replication_tpu"))
        scanned = [f.replace(os.sep, "/") for f in iter_python_files([pkg])]
        for mod in ("/models/decoder.py", "/models/config.py",
                    "/runtime/engine.py", "/runtime/plan.py",
                    "/runtime/plan_search.py", "/runtime/slots.py",
                    "/runtime/loader.py", "/runtime/faults.py",
                    "/scoring/packed.py", "/scoring/confidence.py",
                    "/serve/request.py", "/serve/coalescer.py",
                    "/serve/scheduler.py", "/serve/queue.py",
                    "/serve/load.py", "/serve/pool.py",
                    "/obs/tracer.py", "/obs/metrics.py",
                    "/obs/flight.py", "/obs/benchdiff.py",
                    "/ops/quant.py", "/ops/attention.py",
                    "/lint/contracts.py"):
            assert any(mod in f for f in scanned), mod

    def test_touched_modules_carry_no_baseline_entries(self):
        """Consolidated zero-baseline pin: the union of every module a
        past PR declared ships-lint-clean still carries no
        ``lint_baseline.json`` entry (the rot check guards entry
        validity; this guards the no-new-grandfathering promise)."""
        from llm_interpretation_replication_tpu.lint.cli import (
            default_baseline_path,
        )

        touched = ("ops/quant.py", "ops/attention.py", "models/decoder.py",
                   "models/config.py", "runtime/plan.py",
                   "runtime/engine.py", "runtime/faults.py",
                   "runtime/plan_search.py", "runtime/slots.py",
                   "runtime/loader.py", "scoring/packed.py",
                   "scoring/confidence.py", "scoring/prompts.py",
                   "serve/request.py", "serve/coalescer.py",
                   "serve/scheduler.py", "serve/queue.py",
                   "serve/config.py", "parallel/mesh.py",
                   "stats/correlations.py", "sweeps/perturbation.py",
                   "obs/benchdiff.py", "config/__init__.py",
                   "llm_interpretation_replication_tpu/__main__.py",
                   "bench.py")
        entries = load_baseline(default_baseline_path())
        offenders = [e for e in entries
                     if e.get("path", "").endswith(touched)]
        assert not offenders, offenders

    def test_scoring_package_is_in_g05_scope(self):
        """Satellite (ISSUE 10): scoring/ joined the G05 fault scope when
        packed anchor scoring landed there (scoring/packed.py feeds the
        engine's launch path) — a swallowing broad except in the packed
        encoder would hide a device error inside prompt assembly."""
        for path in ("scoring/packed.py", "scoring/confidence.py"):
            findings = run(path, """
                def encode(tok, packs):
                    try:
                        return tok(packs)
                    except Exception:
                        return None
            """)
            assert rules_of(findings) == ["G05"], path

    def test_scoring_package_lint_clean_without_baseline(self):
        """Satellite (ISSUE 10): scoring/ (incl. the new packed module)
        ships lint-clean — zero findings with NO baseline, and no
        lint_baseline.json entry grandfathers anything under scoring/."""
        from llm_interpretation_replication_tpu.lint.cli import (
            default_baseline_path,
        )

        pkg = next(p for p in default_paths()
                   if p.endswith("llm_interpretation_replication_tpu"))
        assert lint_paths([os.path.join(pkg, "scoring")]) == []
        entries = load_baseline(default_baseline_path())
        assert not [e for e in entries if e.get("path", "").startswith(
            "llm_interpretation_replication_tpu/scoring/")]

    def test_plan_search_is_in_g05_scope(self):
        """Satellite (ISSUE 8): the plan search sits between the budget
        model and the engine factory — a broad except swallowing there
        turns a mis-priced candidate into a silent wrong operating point,
        so G05 applies to runtime/plan_search.py like every other runtime
        module (the default-paths walker already scans it; this is the
        teeth check)."""
        findings = run("runtime/plan_search.py", """
            def pick(candidates):
                try:
                    return candidates[0]
                except Exception:
                    return None
        """)
        assert rules_of(findings) == ["G05"]

    def test_kdecode_verify_path_is_in_g05_scope(self):
        """Satellite (ISSUE 13): the K-decode verify/propose path lives
        in models/ and runtime/ — both fault scope — so a broad except
        swallowing around a verify pass would hide the device error the
        reject-fallback ladder must classify.  Teeth check for the two
        modules the K path runs through."""
        for path in ("models/decoder.py", "runtime/engine.py"):
            findings = run(path, """
                def verify(block):
                    try:
                        return block.accept()
                    except Exception:
                        return None
            """)
            assert rules_of(findings) == ["G05"], path

    def test_gate_would_catch_an_injected_violation(self, tmp_path):
        """End-to-end teeth check: copy one real hot-path file, inject a
        G01 `.item()` into it, and confirm the same entry point that the
        gate test runs reports it."""
        victim = tmp_path / "models"
        victim.mkdir()
        src = os.path.join(os.path.dirname(default_paths()[0]),
                           "llm_interpretation_replication_tpu", "models",
                           "decoder.py")
        text = open(src).read()
        text += ("\n\ndef _injected(x):\n"
                 "    return x.item()\n")
        (victim / "decoder.py").write_text(text)
        findings = lint_paths([str(victim)], root=str(tmp_path))
        injected = [f for f in findings if f.rule == "G01"]
        assert injected and injected[0].path == "models/decoder.py"


# ---------------------------------------------------------------------------
# Layer 3: the whole-tree concurrency analysis (lint/threads.py, PR 18)
# ---------------------------------------------------------------------------

def _texts(files):
    return {p: textwrap.dedent(s) for p, s in files.items()}


def _thread_findings(files):
    return collect_thread_findings(_texts(files))


#: a worker class whose state is reached from two thread roots (the
#: spawned poll loop + any API caller) with ONE access site left
#: unguarded — the canonical G09 target.  The guarded twin next to it
#: is the blessed idiom the rule must stay quiet on.
_G09_RACE = {
    "pkg/w.py": textwrap.dedent("""
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0
                self._thread = threading.Thread(
                    target=self._loop, name="w-loop", daemon=True)
                self._thread.start()

            def _loop(self):
                while True:
                    with self._lock:
                        self._n += 1

            def bump(self):
                self._n += 1
    """),
}


class TestG09GuardedBy:
    def test_unguarded_write_with_guarded_siblings_fires(self):
        findings = _thread_findings(_G09_RACE)
        assert rules_of(findings) == ["G09"]
        f = findings[0]
        assert f.path == "pkg/w.py"
        assert "Worker._n" in f.message
        # the message names the guard the other sites hold and the
        # competing roots — the fix is legible from the finding alone
        assert "Worker._lock" in f.message
        assert "w-loop" in f.message or "API caller" in f.message

    def test_consistently_guarded_state_is_quiet(self):
        files = {"pkg/w.py": _G09_RACE["pkg/w.py"].replace(
            "    def bump(self):\n        self._n += 1",
            "    def bump(self):\n"
            "        with self._lock:\n"
            "            self._n += 1")}
        assert _thread_findings(files) == []

    def test_single_thread_state_is_quiet(self):
        """State reached from ONE root (no spawn -> only the implicit
        API root) is thread-confined; unguarded writes are fine."""
        files = {"pkg/w.py": """
            class Counter:
                def __init__(self):
                    self._n = 0

                def bump(self):
                    self._n += 1
        """}
        assert _thread_findings(files) == []

    def test_never_locked_rmw_fires(self):
        """Two roots, no lock anywhere: a += on the shared counter is a
        non-atomic read-modify-write — G09 even with no guard to infer."""
        files = {"pkg/w.py": """
            import threading

            class Worker:
                def __init__(self):
                    self._n = 0
                    self._thread = threading.Thread(target=self._loop)

                def _loop(self):
                    self._n += 1

                def bump(self):
                    self._n += 1
        """}
        findings = _thread_findings(files)
        assert rules_of(findings) == ["G09", "G09"]
        assert "read-modify-write" in findings[0].message

    def test_never_locked_plain_rebind_is_quiet(self):
        """An atomic rebind (``self._flag = True``) on never-locked
        shared state is the blessed stop-flag idiom — not a G09."""
        files = {"pkg/w.py": """
            import threading

            class Worker:
                def __init__(self):
                    self._flag = False
                    self._thread = threading.Thread(target=self._loop)

                def _loop(self):
                    while not self._flag:
                        pass

                def stop(self):
                    self._flag = True
        """}
        assert _thread_findings(files) == []

    def test_init_writes_are_exempt(self):
        """__init__ runs before the object escapes to other threads —
        its unguarded stores never count as racing accesses (the fixture
        above would otherwise flag every constructor)."""
        files = {"pkg/w.py": _G09_RACE["pkg/w.py"].replace(
            "    def bump(self):\n        self._n += 1",
            "    def snapshot(self):\n"
            "        with self._lock:\n"
            "            return self._n")}
        assert _thread_findings(files) == []

    def test_suppression_comment_clears_the_finding(self):
        files = {"pkg/w.py": _G09_RACE["pkg/w.py"].replace(
            "    def bump(self):\n        self._n += 1",
            "    def bump(self):\n"
            "        # graftlint: disable=G09 approximate stat\n"
            "        self._n += 1")}
        assert _thread_findings(files) == []


#: two locks taken in OPPOSITE orders from two public methods — the
#: deliberate deadlock fixture the satellite list names.
_G10_CYCLE = {
    "pkg/d.py": textwrap.dedent("""
        import threading

        class Pair:
            def __init__(self):
                self._la = threading.Lock()
                self._lb = threading.Lock()

            def ab(self):
                with self._la:
                    with self._lb:
                        pass

            def ba(self):
                with self._lb:
                    with self._la:
                        pass
    """),
}


class TestG10LockOrder:
    def test_two_lock_cycle_fires(self):
        findings = _thread_findings(_G10_CYCLE)
        assert "G10" in rules_of(findings)
        f = next(f for f in findings if f.rule == "G10")
        assert "Pair._la" in f.message and "Pair._lb" in f.message
        # both conflicting acquisition sites are cited in the chain
        assert "d.py:" in f.message

    def test_consistent_order_is_quiet(self):
        files = {"pkg/d.py": _G10_CYCLE["pkg/d.py"].replace(
            "        with self._lb:\n            with self._la:",
            "        with self._la:\n            with self._lb:")}
        assert _thread_findings(files) == []

    def test_cycle_spanning_a_call_edge_fires(self):
        """The ordering graph is interprocedural: holding A while
        CALLING a function that acquires B mints the A->B edge even
        with no lexically nested with-block."""
        files = {"pkg/d.py": """
            import threading

            _LA = threading.Lock()
            _LB = threading.Lock()

            def _grab_b():
                with _LB:
                    pass

            def ab():
                with _LA:
                    _grab_b()

            def ba():
                with _LB:
                    with _LA:
                        pass
        """}
        findings = _thread_findings(files)
        assert "G10" in rules_of(findings)

    def test_nonreentrant_self_reacquisition_fires(self):
        files = {"pkg/d.py": """
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()

                def outer(self):
                    with self._lock:
                        self.inner()

                def inner(self):
                    with self._lock:
                        pass
        """}
        findings = _thread_findings(files)
        assert "G10" in rules_of(findings)
        assert "re-acquires" in findings[0].message

    def test_rlock_self_reacquisition_is_quiet(self):
        files = {"pkg/d.py": """
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.RLock()

                def outer(self):
                    with self._lock:
                        self.inner()

                def inner(self):
                    with self._lock:
                        pass
        """}
        assert _thread_findings(files) == []

    def test_lock_cycles_api_reports_the_scc(self):
        model = build_model(_texts(_G10_CYCLE))
        cycles = model.lock_cycles()
        assert len(cycles) == 1
        assert sorted(cycles[0]) == ["pkg.d:Pair._la", "pkg.d:Pair._lb"]


#: a scheduler-shaped fixture: the lock is contended (poll loop + API
#: callers) and the API method sleeps while holding it.
_G11_SLEEP = {
    "pkg/s.py": textwrap.dedent("""
        import threading
        import time

        class Sched:
            def __init__(self):
                self._lock = threading.Lock()
                self._thread = threading.Thread(target=self._loop)

            def _loop(self):
                with self._lock:
                    pass

            def tick(self):
                with self._lock:
                    time.sleep(0.5)
    """),
}


class TestG11BlockingUnderLock:
    def test_sleep_under_contended_lock_fires(self):
        findings = _thread_findings(_G11_SLEEP)
        assert rules_of(findings) == ["G11"]
        assert "time.sleep" in findings[0].message
        assert "Sched._lock" in findings[0].message

    def test_sleep_outside_the_lock_is_quiet(self):
        files = {"pkg/s.py": _G11_SLEEP["pkg/s.py"].replace(
            "        with self._lock:\n            time.sleep(0.5)",
            "        with self._lock:\n            pass\n"
            "        time.sleep(0.5)")}
        assert _thread_findings(files) == []

    def test_uncontended_lock_is_quiet(self):
        """One root only (no spawned loop): nobody queues behind the
        sleeper, so the hold is harmless — G11 requires contention."""
        files = {"pkg/s.py": """
            import threading
            import time

            class Sched:
                def __init__(self):
                    self._lock = threading.Lock()

                def tick(self):
                    with self._lock:
                        time.sleep(0.5)
        """}
        assert _thread_findings(files) == []

    def test_timeout_zero_result_is_exempt(self):
        """``fut.result(timeout=0)`` / ``.exception(timeout=0)`` return
        immediately — the pool's reap-under-lock idiom must stay legal."""
        files = {"pkg/s.py": _G11_SLEEP["pkg/s.py"].replace(
            "            time.sleep(0.5)",
            "            self.fut.result(timeout=0)")}
        assert _thread_findings(files) == []

    def test_condition_wait_on_held_lock_is_exempt(self):
        """``cond.wait`` RELEASES the lock it rides — the queue's
        pop-with-timeout idiom (serve/queue.py) is not a hold-and-block."""
        files = {"pkg/s.py": """
            import threading

            class Q:
                def __init__(self):
                    self._cond = threading.Condition()
                    self._thread = threading.Thread(target=self._loop)

                def _loop(self):
                    with self._cond:
                        pass

                def pop(self):
                    with self._cond:
                        self._cond.wait(timeout=0.05)
        """}
        assert _thread_findings(files) == []

    def test_transitive_blocking_through_a_helper_fires(self):
        files = {"pkg/s.py": _G11_SLEEP["pkg/s.py"].replace(
            "            time.sleep(0.5)",
            "            self._flush()")
            + "\n    def _flush(self):\n        time.sleep(0.5)\n"}
        findings = _thread_findings(files)
        # two findings, both actionable: the caller's hold-and-call (with
        # the via-chain naming the helper) and the helper's own sleep
        # under the entry-held lock
        assert set(rules_of(findings)) == {"G11"}
        assert any("via" in f.message and "_flush" in f.message
                   for f in findings)

    def test_suppressing_the_source_clears_transitive_findings(self):
        """An inline G11 suppression at the blocking site declares it
        non-blocking for the MODEL: callers' transitive findings clear
        with it (one written rationale, not one per caller)."""
        files = {"pkg/s.py": _G11_SLEEP["pkg/s.py"].replace(
            "            time.sleep(0.5)",
            "            # graftlint: disable=G11 bounded 1ms debounce\n"
            "            time.sleep(0.5)")}
        assert _thread_findings(files) == []


class TestThreadRoots:
    """The thread-model inference pack (mirrors TestInterprocedural):
    every spawn idiom in the tree mints a root, and membership
    propagates through resolved call edges."""

    def test_thread_target_and_name_label(self):
        model = build_model(_texts(_G09_RACE))
        roots = model.roots_of("pkg.w", "Worker._loop")
        assert roots == {"pkg.w:Worker._loop"}
        # the Thread(name=...) literal becomes the human label findings
        # print
        assert model.root_labels["pkg.w:Worker._loop"] == "thread 'w-loop'"

    def test_public_method_gets_the_api_root(self):
        model = build_model(_texts(_G09_RACE))
        assert "<api>" in model.roots_of("pkg.w", "Worker.bump")

    def test_roots_propagate_through_calls(self):
        files = {"pkg/w.py": """
            import threading

            def _spawn():
                threading.Thread(target=_loop).start()

            def _loop():
                _helper()

            def _helper():
                _leaf()

            def _leaf():
                pass
        """}
        model = build_model(_texts(files))
        assert "pkg.w:_loop" in model.roots_of("pkg.w", "_leaf")

    def test_executor_submit_is_a_root(self):
        files = {"pkg/w.py": """
            from concurrent.futures import ThreadPoolExecutor

            class Pool:
                def __init__(self):
                    self._ex = ThreadPoolExecutor(4)

                def kick(self):
                    self._ex.submit(self._work)

                def _work(self):
                    pass
        """}
        model = build_model(_texts(files))
        assert any("Pool._work" in r
                   for r in model.roots_of("pkg.w", "Pool._work"))

    def test_timer_callback_is_a_root(self):
        files = {"pkg/w.py": """
            import threading

            class Debounce:
                def arm(self):
                    threading.Timer(0.5, self._fire).start()

                def _fire(self):
                    pass
        """}
        model = build_model(_texts(files))
        assert any("Debounce._fire" in r
                   for r in model.roots_of("pkg.w", "Debounce._fire"))

    def test_http_handler_method_is_a_root(self):
        files = {"pkg/w.py": """
            from http.server import BaseHTTPRequestHandler

            class Handler(BaseHTTPRequestHandler):
                def do_GET(self):
                    pass
        """}
        model = build_model(_texts(files))
        assert any("Handler.do_GET" in r
                   for r in model.roots_of("pkg.w", "Handler.do_GET"))

    def test_private_uncalled_function_has_no_roots(self):
        files = {"pkg/w.py": """
            def _never_called():
                pass
        """}
        model = build_model(_texts(files))
        assert model.roots_of("pkg.w", "_never_called") == set()

    def test_spawn_target_enters_with_no_locks_held(self):
        """A new thread starts with an empty lock set even when every
        in-tree SPAWN site holds a lock — the spawned frame is fresh
        (this is what kept entry-held inference from fabricating
        reversed lock-order edges on the supervisor's rebuild workers)."""
        files = {"pkg/w.py": """
            import threading

            class W:
                def __init__(self):
                    self._lock = threading.Lock()

                def kick(self):
                    with self._lock:
                        threading.Thread(target=self._loop).start()

                def _loop(self):
                    with self._lock:
                        pass
        """}
        # were the spawn site's held set credited to _loop, this would
        # be a G10 self-reacquisition on a non-reentrant lock
        assert _thread_findings(files) == []


class TestThreadRepoGate:
    """The acceptance criteria: the thread layer over the REAL tree."""

    def test_real_tree_has_zero_concurrency_findings(self):
        """Zero unsuppressed G09/G10/G11 over the package + bench.py —
        the PR-18 triage sweep fixed the real races instead of
        baselining them, and this pin keeps it that way."""
        offenders = [f for f in lint_paths(default_paths())
                     if f.rule in ("G09", "G10", "G11")]
        assert offenders == [], [
            (f.rule, f.path, f.line, f.message) for f in offenders]

    def test_real_lock_order_graph_is_cycle_free(self):
        """THE deadlock gate: the global lock-acquisition ordering graph
        across serve/, obs/, runtime/, utils/ has no cycle."""
        model = model_from_paths(default_paths())
        assert model.lock_cycles() == []

    def test_lock_order_graph_pins_the_fleet_ordering(self):
        """The load-bearing ordering contract, pinned: the pool lock is
        always OUTER to the telemetry counter lock (every pool path that
        bumps counters), and no edge points back into the pool lock."""
        model = model_from_paths(default_paths())
        pool = PKG_NAME + ".serve.pool:EnginePool._lock"
        counters = PKG_NAME + ".utils.telemetry:_COUNTERS_LOCK"
        assert (pool, counters) in model.lock_edges
        assert not [e for e in model.lock_edges if e[1] == pool]

    def test_all_lock_using_modules_are_modeled(self):
        """Coverage: every module that creates a threading primitive is
        inside the model's lock registry — the layer sees the whole
        fleet, not a hand-picked subset."""
        model = model_from_paths(default_paths())
        modeled = {key.split(":", 1)[0] for key in model.lock_kinds}
        expected = {
            PKG_NAME + "." + m for m in (
                "api_backends.cost", "obs.flight", "obs.metrics",
                "obs.tracer", "serve.pool", "serve.queue",
                "serve.request", "serve.scheduler", "serve.supervisor",
                "utils.logging", "utils.retry", "utils.telemetry",
            )}
        missing = expected - modeled
        assert not missing, sorted(missing)
        # serve/load.py + sweeps/api_perturbation.py use function-LOCAL
        # locks (no shared attribute to register) but are still parsed
        # into the model like every other module
        for mod in ("serve.load", "sweeps.api_perturbation"):
            assert PKG_NAME + "." + mod in model.modules

    def test_gate_would_catch_an_injected_race(self, tmp_path):
        """End-to-end teeth: copy the REAL telemetry module, bolt on an
        unguarded mutation of its lock-guarded registry plus a thread
        that calls it, and the same ``lint_paths`` entry point the gate
        runs reports the G09."""
        pkg_dir = os.path.join(REPO_ROOT, PKG_NAME)
        text = open(os.path.join(pkg_dir, "utils", "telemetry.py")).read()
        text += ("\n\ndef bump_unguarded():\n"
                 "    _FAULT_EVENTS.append({'kind': 'transient_retry'})\n")
        _write_tree(tmp_path, {
            "pkg/utils/telemetry.py": "",
            "pkg/driver.py": """
                import threading

                from .utils.telemetry import bump_unguarded

                def _loop():
                    bump_unguarded()

                def start():
                    threading.Thread(target=_loop).start()
            """,
        })
        (tmp_path / "pkg" / "utils" / "telemetry.py").write_text(text)
        findings = lint_paths([str(tmp_path / "pkg")], root=str(tmp_path))
        injected = [f for f in findings if f.rule == "G09"
                    and "_FAULT_EVENTS" in f.message]
        assert injected, [(f.rule, f.path, f.message) for f in findings]


class TestConcurrencyRegressions:
    """Functional twins of the races the PR-18 triage sweep fixed —
    each cross-referenced to the fingerprint the analyzer reported
    before the fix (the injected-race teeth test above proves the
    analyzer still catches the pattern class)."""

    def test_fault_registry_is_atomic_under_contention(self):
        """G09 utils/telemetry.py `_FAULT_EVENTS.append(event)` + the
        listener check-then-append: N threads recording concurrently
        lose no events, and a listener registered from racing threads
        delivers each event exactly once."""
        telemetry.clear_fault_events()
        hits = []
        listener = hits.append
        n_threads, per_thread = 8, 50
        import threading as _threading

        def work():
            telemetry.add_fault_listener(listener)
            for _ in range(per_thread):
                telemetry.record_fault("transient_retry", src="test")

        threads = [_threading.Thread(target=work) for _ in range(n_threads)]
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            events = telemetry.fault_events("transient_retry")
            assert len(events) == n_threads * per_thread
            # idempotent registration survived the race: no event was
            # double-delivered (listener list holds ONE copy)
            assert len(hits) == n_threads * per_thread
        finally:
            telemetry.remove_fault_listener(listener)
            telemetry.clear_fault_events()

    def test_cost_tracker_tallies_are_exact_under_contention(self):
        """G09 api_backends/cost.py `CostTracker.usage`: the per-model
        += tallies are read-modify-write shared by every RemoteReplica
        worker — totals must be exact, not approximately right."""
        from llm_interpretation_replication_tpu.api_backends.cost import (
            CostTracker,
        )
        import threading as _threading

        tracker = CostTracker(pricing={})
        n_threads, per_thread = 8, 200

        def work():
            for _ in range(per_thread):
                tracker.record("m", 3, 5)

        threads = [_threading.Thread(target=work) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        u = tracker.usage["m"]
        assert u["requests"] == n_threads * per_thread
        assert u["input_tokens"] == 3 * n_threads * per_thread
        assert u["output_tokens"] == 5 * n_threads * per_thread

    def test_session_logger_close_does_not_race_log(self, tmp_path):
        """G09 utils/logging.py `self._file = None`: close() now takes
        the same lock as log(), so a writer mid-line can never hit a
        closed file object."""
        from llm_interpretation_replication_tpu.utils.logging import (
            SessionLogger,
        )
        import io
        import threading as _threading

        logger = SessionLogger(log_file=str(tmp_path / "s.log"),
                               stream=io.StringIO())
        stop = _threading.Event()
        errors = []

        def writer():
            while not stop.is_set():
                try:
                    logger.log("tick")
                except ValueError as err:  # "I/O operation on closed file"
                    errors.append(err)
                    return

        t = _threading.Thread(target=writer)
        t.start()
        logger.close()
        stop.set()
        t.join(timeout=5)
        assert errors == []


# ---------------------------------------------------------------------------
# Strict mode (runtime/strict.py) — the runtime complement
# ---------------------------------------------------------------------------

class TestStrictMode:
    @pytest.fixture(autouse=True)
    def _clean(self):
        from llm_interpretation_replication_tpu.runtime import strict

        strict.deactivate()
        yield
        strict.deactivate()

    def test_env_gate(self, monkeypatch):
        from llm_interpretation_replication_tpu.runtime import strict

        monkeypatch.delenv(strict.STRICT_ENV, raising=False)
        assert not strict.activate_from_env()
        monkeypatch.setenv(strict.STRICT_ENV, "0")
        assert not strict.activate_from_env()
        monkeypatch.setenv(strict.STRICT_ENV, "1")
        assert strict.activate_from_env()
        assert strict.strict_enabled()

    def test_contexts_are_noops_when_inactive(self):
        import numpy as np
        import jax.numpy as jnp

        from llm_interpretation_replication_tpu.runtime import strict

        snap = telemetry.counters()
        with strict.scoring_guard("t"), strict.device_region("t"):
            with strict.sanctioned_fetch():
                jnp.sin(np.ones((2,)))  # implicit h2d: fine when inactive
        assert telemetry.counters_since(snap).get(
            strict.BLOCKED_COUNTER, 0) == 0

    def test_device_region_blocks_and_counts(self):
        import numpy as np
        import jax.numpy as jnp

        from llm_interpretation_replication_tpu.runtime import strict

        strict.activate(sentry=False)
        snap = telemetry.counters()
        with pytest.raises(Exception, match="[Dd]isallowed"):
            with strict.device_region("test"):
                jnp.sin(np.ones((4,)))  # implicit host->device transfer
        assert telemetry.counters_since(snap)[strict.BLOCKED_COUNTER] == 1
        assert telemetry.fault_events("blocked_transfer")

    def test_recompile_sentry_counts_fresh_compiles_only(self):
        import jax
        import jax.numpy as jnp

        from llm_interpretation_replication_tpu.runtime import strict

        strict.activate()

        @jax.jit
        def probe(x):
            return x * 3.0 + 1.0

        snap = telemetry.counters()
        probe(jnp.ones((5,))).block_until_ready()
        cold = telemetry.counters_since(snap).get(strict.RECOMPILE_COUNTER, 0)
        assert cold >= 1
        assert strict.sentry_programs()
        snap = telemetry.counters()
        probe(jnp.ones((5,))).block_until_ready()  # warm: cached executable
        assert telemetry.counters_since(snap).get(
            strict.RECOMPILE_COUNTER, 0) == 0

    def test_activate_upgrades_guards_only_to_sentry(self):
        from llm_interpretation_replication_tpu.runtime import strict

        strict.activate(sentry=False)
        assert strict.strict_enabled() and strict.sentry_programs() == []
        strict.activate()  # bench/CLI arming later in the same process
        import jax
        import jax.numpy as jnp

        @jax.jit
        def upgrade_probe(x):
            return x - 7.0

        snap = telemetry.counters()
        upgrade_probe(jnp.ones((3,))).block_until_ready()
        assert telemetry.counters_since(snap).get(
            strict.RECOMPILE_COUNTER, 0) >= 1

    def test_strict_report_shape(self):
        from llm_interpretation_replication_tpu.runtime import strict

        strict.activate(sentry=False)
        rep = strict.strict_report()
        assert rep["enabled"] is True
        # "samples" is the optional ring-truncation visibility block
        # (ISSUE-6 satellite): present only when sample rings recorded
        assert set(rep) - {"samples"} == {
            "enabled", strict.RECOMPILE_COUNTER, strict.BLOCKED_COUNTER}
        for ring in rep.get("samples", {}).values():
            assert set(ring) == {"total", "retained", "cap"}
            assert ring["total"] >= ring["retained"]


class TestStrictFusedSweep:
    """Acceptance: a 2-batch fused two-leg sweep runs under strict mode
    with blocked_transfers == 0 and a flat warm-repeat recompile count."""

    def test_two_chunk_fused_sweep_clean_and_warm_stable(self):
        from test_runtime import _tiny_engine

        from llm_interpretation_replication_tpu.runtime import strict
        from llm_interpretation_replication_tpu.runtime.engine import LegSpec

        eng, _, _ = _tiny_engine(batch_size=4)
        # 8 rows at batch 4 -> two pipelined batches ("2-chunk")
        pairs = [
            (f"Scenario {i}: the contract covers vehicles.",
             ("Answer Yes or No.", "Give a confidence from 0 to 100."))
            for i in range(8)
        ]
        legs = [LegSpec("binary"),
                LegSpec("confidence", with_confidence=True,
                        max_new_tokens=10)]
        strict.activate()
        try:
            snap = telemetry.counters()
            cold = eng.score_prefixed(pairs, targets=("Yes", "No"),
                                      legs=legs)
            d_cold = telemetry.counters_since(snap)
            assert d_cold.get(strict.BLOCKED_COUNTER, 0) == 0
            assert len(cold) == 2 and len(cold[0]) == 8
            assert eng.last_prefix_pool.consistent

            snap = telemetry.counters()
            warm = eng.score_prefixed(pairs, targets=("Yes", "No"),
                                      legs=legs)
            d_warm = telemetry.counters_since(snap)
            assert d_warm.get(strict.BLOCKED_COUNTER, 0) == 0
            # warm repeat must not recompile: plan keys + bucketed shapes
            # are stable, so a nonzero delta is a cache-key leak
            assert d_warm.get(strict.RECOMPILE_COUNTER, 0) == 0
            for a, b in zip(cold[0], warm[0]):
                assert a["relative_prob"] == pytest.approx(
                    b["relative_prob"], abs=1e-9)
        finally:
            strict.deactivate()
