"""graftlint static-analysis gate + strict-mode runtime guards.

Three layers, all tier-1 (``-m lint``):

1. **Rule self-tests** — synthetic fixtures proving every rule
   (G01/G02/G03/G04/G05) fires on its target pattern and stays quiet on
   the blessed idiom next to it.  This is what guarantees the repo gate
   below has teeth: a violation introduced into the tree is, by
   construction of these fixtures, a pattern the analyzer flags.
2. **Baseline machinery** — fingerprint matching survives line drift,
   stale entries surface, suppression comments work.
3. **The repo gate + strict mode** — the analyzer runs over the actual
   package (plus bench.py) against the checked-in ``lint_baseline.json``
   and must exit clean, and a real 2-batch fused two-leg sweep runs under
   ``LLM_INTERP_STRICT`` semantics with ``blocked_transfers == 0`` and a
   flat warm-repeat ``recompile_events`` count.
"""

import json
import os
import textwrap

import pytest

from llm_interpretation_replication_tpu.lint import (
    apply_baseline,
    default_paths,
    default_rules,
    lint_paths,
    lint_source,
    load_baseline,
    save_baseline,
)
from llm_interpretation_replication_tpu.lint.cli import main as lint_main
from llm_interpretation_replication_tpu.utils import telemetry

pytestmark = pytest.mark.lint


def run(path, source):
    return lint_source(path, textwrap.dedent(source), default_rules())


def rules_of(findings):
    return sorted(f.rule for f in findings)


# ---------------------------------------------------------------------------
# G01 host-sync
# ---------------------------------------------------------------------------

class TestG01HostSync:
    def test_item_in_jit_region(self):
        findings = run("ops/kernels.py", """
            import jax

            @jax.jit
            def f(x):
                return x.item()
        """)
        assert rules_of(findings) == ["G01"]
        assert ".item()" in findings[0].message

    def test_item_in_hot_module_outside_jit(self):
        findings = run("models/decoder.py", "def f(x):\n    return x.item()\n")
        assert rules_of(findings) == ["G01"]

    def test_item_in_cold_module_ok(self):
        assert run("stats/bootstrap.py",
                   "def f(x):\n    return x.item()\n") == []

    def test_np_asarray_in_jit(self):
        findings = run("ops/kernels.py", """
            import functools, jax
            import numpy as np

            @functools.partial(jax.jit, static_argnames=("k",))
            def f(x, k):
                return np.asarray(x)
        """)
        assert rules_of(findings) == ["G01"]

    def test_float_on_traced_param_in_jit(self):
        findings = run("ops/kernels.py", """
            import jax

            @jax.jit
            def f(x):
                return float(x) + 1.0
        """)
        assert rules_of(findings) == ["G01"]

    def test_float_on_static_param_ok(self):
        assert run("ops/kernels.py", """
            import functools, jax

            @functools.partial(jax.jit, static_argnames=("cfg",))
            def f(x, cfg):
                rd = int(cfg.rotary_pct * 64)
                return x * rd
        """) == []

    def test_shape_derived_local_ok(self):
        # `t = xb.shape[0]` is Python-static under trace: int(t * k) is fine
        assert run("ops/kernels.py", """
            import jax

            @jax.jit
            def f(xb, k):
                t = xb.shape[0]
                cap = max(1, int(0.5 * t))
                return xb[:cap]
        """) == []

    def test_launch_closure_fetch_flagged_consume_ok(self):
        findings = run("runtime/engine.py", """
            import numpy as np
            import jax.numpy as jnp

            def pipeline(batches):
                def launch(batch):
                    out = jnp.sum(batch.ids)
                    return np.asarray(out)      # device fetch in launch: BAD

                def consume(batch, out):
                    return np.asarray(out)      # sanctioned fetch point

                return launch, consume
        """)
        assert rules_of(findings) == ["G01"]
        assert findings[0].message.count("consume")


# ---------------------------------------------------------------------------
# G02 traced control flow
# ---------------------------------------------------------------------------

class TestG02TracedControlFlow:
    def test_if_on_traced_param(self):
        findings = run("m.py", """
            import jax

            @jax.jit
            def f(x):
                if x > 0:
                    return x
                return -x
        """)
        assert rules_of(findings) == ["G02"]

    def test_while_on_traced_local(self):
        findings = run("m.py", """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def f(x):
                s = jnp.sum(x)
                while s > 0:
                    s = s - 1
                return s
        """)
        assert "G02" in rules_of(findings)

    def test_static_argname_ok(self):
        assert run("m.py", """
            import functools, jax

            @functools.partial(jax.jit, static_argnames=("causal",))
            def f(x, causal):
                if causal:
                    return x
                return -x
        """) == []

    def test_is_none_and_isinstance_ok(self):
        assert run("m.py", """
            import jax

            @jax.jit
            def f(x, mask):
                if mask is None:
                    return x
                if isinstance(x, tuple):
                    return x[0]
                return x
        """) == []

    def test_shape_comparison_ok(self):
        assert run("m.py", """
            import jax

            @jax.jit
            def f(x):
                b = x.shape[0]
                if b % 2:
                    raise ValueError("odd batch")
                return x
        """) == []

    def test_plain_function_ok(self):
        assert run("m.py", "def f(x):\n    if x > 0:\n        return x\n    return -x\n") == []


# ---------------------------------------------------------------------------
# G03 PRNG key reuse
# ---------------------------------------------------------------------------

class TestG03KeyReuse:
    def test_double_consumption(self):
        findings = run("m.py", """
            import jax

            def init(hidden):
                key = jax.random.PRNGKey(0)
                a = jax.random.normal(key, (hidden,))
                b = jax.random.normal(key, (hidden,))
                return a, b
        """)
        assert rules_of(findings) == ["G03"]
        assert "'key'" in findings[0].message

    def test_split_is_clean(self):
        assert run("m.py", """
            import jax

            def init(hidden):
                key = jax.random.PRNGKey(0)
                ka, kb = jax.random.split(key)
                a = jax.random.normal(ka, (hidden,))
                b = jax.random.normal(kb, (hidden,))
                return a, b
        """) == []

    def test_fold_in_derives_not_consumes(self):
        assert run("m.py", """
            import jax

            def init(hidden):
                key = jax.random.PRNGKey(0)
                heads = jax.random.split(key, 4)
                extra = jax.random.fold_in(key, 99)
                return heads, jax.random.normal(extra, (hidden,))
        """) == []

    def test_loop_reuse(self):
        findings = run("m.py", """
            import jax

            def draws(n):
                key = jax.random.PRNGKey(0)
                out = []
                for i in range(n):
                    out.append(jax.random.uniform(key, (3,)))
                return out
        """)
        assert rules_of(findings) == ["G03"]
        assert "IDENTICAL" in findings[0].message

    def test_rebind_in_loop_ok(self):
        assert run("m.py", """
            import jax

            def draws(n):
                key = jax.random.PRNGKey(0)
                out = []
                for i in range(n):
                    key, sub = jax.random.split(key)
                    out.append(jax.random.uniform(sub, (3,)))
                return out
        """) == []

    def test_module_level_scan(self):
        findings = run("m.py", """
            import jax

            KEY = jax.random.PRNGKey(0)
            A = jax.random.normal(KEY, (4,))
            B = jax.random.normal(KEY, (4,))
        """)
        assert rules_of(findings) == ["G03"]


# ---------------------------------------------------------------------------
# G04 jit-boundary hygiene
# ---------------------------------------------------------------------------

class TestG04JitBoundary:
    def test_mutable_default(self):
        findings = run("m.py", """
            import jax

            @jax.jit
            def f(x, buckets=[]):
                return x
        """)
        assert "G04" in rules_of(findings)
        assert "mutable default" in " ".join(f.message for f in findings)

    def test_jit_on_method_self(self):
        findings = run("m.py", """
            import jax

            class Engine:
                @jax.jit
                def step(self, x):
                    return x
        """)
        assert "G04" in rules_of(findings)

    def test_jit_of_bound_attribute(self):
        findings = run("m.py", """
            import jax

            def build(engine):
                return jax.jit(engine.step)
        """)
        assert rules_of(findings) == ["G04"]

    def test_bare_jit_over_shape_param(self):
        findings = run("m.py", """
            import jax

            @jax.jit
            def prefill(x, cache_len):
                return x[:cache_len]
        """)
        assert "G04" in rules_of(findings)
        assert "cache_len" in " ".join(f.message for f in findings)

    def test_static_shape_param_ok(self):
        assert run("m.py", """
            import functools, jax

            @functools.partial(jax.jit, static_argnames=("cache_len",))
            def prefill(x, cache_len):
                return x[:cache_len]
        """) == []

    def test_jit_of_local_function_ok(self):
        assert run("m.py", """
            import jax

            def build(params):
                def step(x):
                    return x @ params
                return jax.jit(step)
        """) == []


# ---------------------------------------------------------------------------
# G05 broad except
# ---------------------------------------------------------------------------

class TestG05BroadExcept:
    SWALLOW = """
        def f():
            try:
                g()
            except Exception:
                return None
    """

    def test_swallow_in_fault_scope(self):
        findings = run("runtime/thing.py", self.SWALLOW)
        assert rules_of(findings) == ["G05"]

    def test_serve_package_in_fault_scope(self):
        """serve/ sits between device errors and the split/re-queue
        ladder, so G05 applies there from day one."""
        findings = run("serve/scheduler.py", self.SWALLOW)
        assert rules_of(findings) == ["G05"]

    def test_serve_load_in_g05_scope(self):
        """Satellite (ISSUE 11): the load harness drives scheduler
        launches and relays their failures, so a swallowed broad except
        there would hide a device error inside the measurement — G05
        applies to serve/load.py like the rest of serve/ (its deliberate
        result-relay catches carry disable annotations)."""
        findings = run("serve/load.py", self.SWALLOW)
        assert rules_of(findings) == ["G05"]

    def test_serve_pool_in_g05_scope(self):
        """Satellite (ISSUE 12): the EnginePool router/relay sits
        between replica engine errors and each request's future, and
        its unload path runs the verified engine teardown — a swallowed
        broad except there would drop a request silently or hide a
        teardown failure.  G05 has teeth on serve/pool.py (its vendor
        result-relay catch carries a disable annotation)."""
        findings = run("serve/pool.py", self.SWALLOW)
        assert rules_of(findings) == ["G05"]

    def test_runtime_engine_teardown_in_g05_scope(self):
        """The teardown path (ScoringEngine.close / EngineClosed) lives
        in runtime/ — already fault scope; pin it so a refactor moving
        close() out of scope cannot silently shed the gate."""
        findings = run("runtime/engine.py", self.SWALLOW)
        assert rules_of(findings) == ["G05"]

    def test_runtime_slots_in_g05_scope(self):
        """Satellite (ISSUE 14): the slot allocator's repack/refill loop
        sits directly on the decode hot path — a swallowed broad except
        there would drop pending rows silently or hide a device error
        from the OOM ladder.  G05 has teeth on runtime/slots.py."""
        findings = run("runtime/slots.py", self.SWALLOW)
        assert rules_of(findings) == ["G05"]

    def test_out_of_scope_module_ok(self):
        assert run("viz/figures.py", self.SWALLOW) == []

    def test_reraise_ok(self):
        assert run("runtime/thing.py", """
            def f():
                try:
                    g()
                except Exception:
                    cleanup()
                    raise
        """) == []

    def test_typed_except_ok(self):
        assert run("runtime/thing.py", """
            def f():
                try:
                    g()
                except (ValueError, OSError):
                    return None
        """) == []

    def test_bare_except_flagged(self):
        findings = run("sweeps/s.py", """
            def f():
                try:
                    g()
                except:
                    pass
        """)
        assert rules_of(findings) == ["G05"]

    def test_suppression_comment(self):
        assert run("runtime/thing.py", """
            def f():
                try:
                    g()
                # graftlint: disable=G05 deliberate keep-alive
                except Exception:
                    return None
        """) == []

    def test_trailing_suppression_comment(self):
        assert run("runtime/thing.py", """
            def f():
                try:
                    g()
                except Exception:  # graftlint: disable=G05 keep-alive
                    return None
        """) == []

    def test_tuple_except_containing_broad_flagged(self):
        findings = run("runtime/thing.py", """
            def f():
                try:
                    g()
                except (Exception, OSError):
                    return None
        """)
        assert rules_of(findings) == ["G05"]

    def test_tuple_of_typed_excepts_ok(self):
        assert run("runtime/thing.py", """
            def f():
                try:
                    g()
                except (ValueError, OSError):
                    return None
        """) == []

    def test_trailing_suppression_does_not_bleed_to_next_line(self):
        # the same-line disable must not exempt the NEXT statement's
        # violation
        findings = run("models/decoder.py", """
            def f(x):
                y = x  # graftlint: disable=G01 unrelated trailing comment
                return x.item()
        """)
        assert rules_of(findings) == ["G01"]

    def test_suppression_is_rule_specific(self):
        findings = run("runtime/thing.py", """
            def f():
                try:
                    g()
                except Exception:  # graftlint: disable=G01 wrong rule
                    return None
        """)
        assert rules_of(findings) == ["G05"]


# ---------------------------------------------------------------------------
# Baseline machinery
# ---------------------------------------------------------------------------

class TestBaseline:
    def _findings(self, line_pad=0):
        src = "\n" * line_pad + textwrap.dedent("""
            def f():
                try:
                    g()
                except Exception:
                    return None
        """)
        return lint_source("runtime/thing.py", src, default_rules())

    def test_roundtrip_and_line_drift(self, tmp_path):
        findings = self._findings()
        path = str(tmp_path / "baseline.json")
        save_baseline(findings, path,
                      {findings[0].fingerprint: "known keep-alive"})
        entries = load_baseline(path)
        assert entries[0]["rationale"] == "known keep-alive"
        # the same violation 7 lines lower still matches (fingerprint is
        # line-independent)
        drifted = self._findings(line_pad=7)
        new, stale, matched = apply_baseline(drifted, entries)
        assert new == [] and stale == [] and matched == 1

    def test_stale_entry_surfaces(self, tmp_path):
        findings = self._findings()
        path = str(tmp_path / "baseline.json")
        save_baseline(findings, path)
        new, stale, matched = apply_baseline([], load_baseline(path))
        assert matched == 0 and len(stale) == 1

    def test_entry_absorbs_once(self, tmp_path):
        findings = self._findings()
        path = str(tmp_path / "baseline.json")
        save_baseline(findings, path)
        twice = findings + findings
        new, stale, matched = apply_baseline(twice, load_baseline(path))
        assert matched == 1 and len(new) == 1

    def test_cli_gate_exit_codes(self, tmp_path):
        bad = tmp_path / "runtime"
        bad.mkdir()
        (bad / "x.py").write_text(
            "def f():\n    try:\n        g()\n    except Exception:\n"
            "        return None\n")
        empty_baseline = tmp_path / "b.json"
        assert lint_main([str(bad), "--baseline", str(empty_baseline)]) == 1
        # --write-baseline grandfathers it; the gate then passes
        assert lint_main([str(bad), "--baseline", str(empty_baseline),
                          "--write-baseline"]) == 0
        assert lint_main([str(bad), "--baseline", str(empty_baseline)]) == 0
        # fixing the code turns the entry stale — the ratchet FAILS the
        # gate until the entry is deleted (it would otherwise re-shield
        # the next violation with the same fingerprint)
        (bad / "x.py").write_text("def f():\n    return g()\n")
        assert lint_main([str(bad), "--baseline", str(empty_baseline)]) == 1
        assert lint_main([str(bad), "--baseline", str(empty_baseline),
                          "--write-baseline"]) == 0
        assert lint_main([str(bad), "--baseline", str(empty_baseline)]) == 0


# ---------------------------------------------------------------------------
# The repo gate
# ---------------------------------------------------------------------------

class TestRepoGate:
    def test_repo_is_clean_vs_checked_in_baseline(self):
        """THE gate: the analyzer over the real tree + lint_baseline.json
        must report zero new findings.  A PR introducing any fixture-class
        violation (the self-tests above) fails here."""
        assert lint_main([]) == 0

    def test_checked_in_baseline_is_small_and_justified(self):
        from llm_interpretation_replication_tpu.lint.cli import (
            default_baseline_path,
        )

        entries = load_baseline(default_baseline_path())
        assert len(entries) <= 10
        for e in entries:
            assert e["rationale"].strip(), f"no rationale: {e}"
            assert "TODO" not in e["rationale"]

    def test_default_paths_cover_package_and_bench(self):
        paths = default_paths()
        assert any(p.endswith("llm_interpretation_replication_tpu")
                   for p in paths)
        assert any(p.endswith("bench.py") for p in paths)

    def test_default_paths_cover_serve_package(self):
        """serve/ lives inside the scanned package dir, so the repo gate
        lints it on every run — asserted via the gate's own file walker."""
        from llm_interpretation_replication_tpu.lint.cli import (
            iter_python_files,
        )

        pkg = next(p for p in default_paths()
                   if p.endswith("llm_interpretation_replication_tpu"))
        assert os.path.isdir(os.path.join(pkg, "serve"))
        scanned = [f.replace(os.sep, "/") for f in iter_python_files([pkg])]
        assert any("/serve/scheduler.py" in f for f in scanned)
        assert any("/serve/queue.py" in f for f in scanned)
        # ISSUE-11: the load harness joins the same gate
        assert any("/serve/load.py" in f for f in scanned)
        # ISSUE-12: the EnginePool joins the same gate
        assert any("/serve/pool.py" in f for f in scanned)

    def test_serve_package_lint_clean_without_baseline(self):
        """Satellite: serve/ ships lint-clean from day one — zero
        findings even with NO baseline, and no lint_baseline.json entry
        grandfathers anything under serve/."""
        from llm_interpretation_replication_tpu.lint.cli import (
            default_baseline_path,
        )

        pkg = next(p for p in default_paths()
                   if p.endswith("llm_interpretation_replication_tpu"))
        # the load harness (ISSUE 11) and the EnginePool (ISSUE 12) are
        # part of the zero-baseline pin — assert they exist so this gate
        # cannot green-light their removal
        assert os.path.exists(os.path.join(pkg, "serve", "load.py"))
        assert os.path.exists(os.path.join(pkg, "serve", "pool.py"))
        assert lint_paths([os.path.join(pkg, "serve")]) == []
        entries = load_baseline(default_baseline_path())
        assert not [e for e in entries if e.get("path", "").startswith(
            "llm_interpretation_replication_tpu/serve/")]

    def test_default_paths_cover_obs_package(self):
        """obs/ lives inside the scanned package dir, so the repo gate
        lints it on every run — asserted via the gate's own file walker
        (the serve/ gate's pattern)."""
        from llm_interpretation_replication_tpu.lint.cli import (
            iter_python_files,
        )

        pkg = next(p for p in default_paths()
                   if p.endswith("llm_interpretation_replication_tpu"))
        assert os.path.isdir(os.path.join(pkg, "obs"))
        scanned = [f.replace(os.sep, "/") for f in iter_python_files([pkg])]
        assert any("/obs/tracer.py" in f for f in scanned)
        assert any("/obs/report.py" in f for f in scanned)
        assert any("/obs/profiler.py" in f for f in scanned)
        # ISSUE-9: the run-health layer's modules join the same gate
        assert any("/obs/metrics.py" in f for f in scanned)
        assert any("/obs/flight.py" in f for f in scanned)
        assert any("/obs/benchdiff.py" in f for f in scanned)

    def test_obs_package_lint_clean_without_baseline(self):
        """Satellite (ISSUE 6): obs/ ships lint-clean from day one — zero
        findings even with NO baseline (G01-G05; its best-effort catches
        carry disable annotations), and no lint_baseline.json entry
        grandfathers anything under obs/."""
        from llm_interpretation_replication_tpu.lint.cli import (
            default_baseline_path,
        )

        pkg = next(p for p in default_paths()
                   if p.endswith("llm_interpretation_replication_tpu"))
        assert lint_paths([os.path.join(pkg, "obs")]) == []
        entries = load_baseline(default_baseline_path())
        assert not [e for e in entries if e.get("path", "").startswith(
            "llm_interpretation_replication_tpu/obs/")]

    def test_obs_is_in_g05_fault_scope(self):
        """obs/ spans wrap the engine's launch/consume callbacks, so a
        broad except that swallows there hides a device error inside the
        instrumentation — G05 applies (the teeth behind the gate above)."""
        findings = run("obs/tracer.py", """
            def close_span(rec):
                try:
                    rec.close()
                except Exception:
                    pass
        """)
        assert rules_of(findings) == ["G05"]

    def test_obs_metrics_and_flight_are_in_g05_fault_scope(self):
        """ISSUE-9 satellite: the run-health modules sit on the fault
        path (the flight recorder runs INSIDE fault handling), so a
        swallowing broad except there is exactly the bug G05 exists to
        catch — fires for the new modules like any runtime/ file."""
        for path in ("obs/metrics.py", "obs/flight.py",
                     "obs/benchdiff.py"):
            findings = run(path, """
                def sample_tick(reg):
                    try:
                        reg.sample()
                    except Exception:
                        pass
            """)
            assert rules_of(findings) == ["G05"], path

    def test_kvcache_touched_modules_carry_no_baseline_entries(self):
        """Satellite (ISSUE 5): the int8-KV-cache / chunked-prefill change
        ships lint-clean — zero new ``lint_baseline.json`` entries for the
        modules it touches in ops/, models/, and runtime/ (the repo gate
        above already proves zero NEW findings; this pins that none were
        grandfathered instead)."""
        from llm_interpretation_replication_tpu.lint.cli import (
            default_baseline_path,
        )

        touched = ("ops/quant.py", "ops/attention.py", "models/decoder.py",
                   "models/config.py", "runtime/plan.py",
                   "runtime/engine.py", "runtime/faults.py",
                   "sweeps/perturbation.py")
        entries = load_baseline(default_baseline_path())
        offenders = [e for e in entries
                     if e.get("path", "").endswith(touched)]
        assert not offenders, offenders

    def test_slots_walker_covers_and_zero_baseline(self):
        """Satellite (ISSUE 14): runtime/slots.py is inside the scanned
        package dir (the gate's own walker proves it), ships lint-clean
        with NO baseline, and the decode-then-repack change adds zero
        ``lint_baseline.json`` entries for any module it touches."""
        from llm_interpretation_replication_tpu.lint.cli import (
            default_baseline_path,
            iter_python_files,
        )

        pkg = next(p for p in default_paths()
                   if p.endswith("llm_interpretation_replication_tpu"))
        assert os.path.exists(os.path.join(pkg, "runtime", "slots.py"))
        scanned = [f.replace(os.sep, "/") for f in iter_python_files([pkg])]
        assert any("/runtime/slots.py" in f for f in scanned)
        assert lint_paths([os.path.join(pkg, "runtime", "slots.py")]) == []
        touched = ("runtime/slots.py", "runtime/engine.py",
                   "runtime/plan.py", "runtime/plan_search.py",
                   "runtime/loader.py", "serve/scheduler.py",
                   "serve/queue.py", "serve/config.py",
                   "scoring/packed.py", "obs/benchdiff.py",
                   "config/__init__.py",
                   "llm_interpretation_replication_tpu/__main__.py",
                   "bench.py")
        entries = load_baseline(default_baseline_path())
        offenders = [e for e in entries
                     if e.get("path", "").endswith(touched)]
        assert not offenders, offenders

    def test_pooled_conf_touched_modules_carry_no_baseline_entries(self):
        """Satellite (ISSUE 7): the pooled-confidence-decode change ships
        lint-clean — zero new ``lint_baseline.json`` entries for every
        module it touches (engine pool + gate, plan term, confidence
        stability predicate, CLI/config plumbing, bench)."""
        from llm_interpretation_replication_tpu.lint.cli import (
            default_baseline_path,
        )

        touched = ("runtime/engine.py", "runtime/plan.py",
                   "scoring/confidence.py", "config/__init__.py",
                   "llm_interpretation_replication_tpu/__main__.py",
                   "bench.py")
        entries = load_baseline(default_baseline_path())
        offenders = [e for e in entries
                     if e.get("path", "").endswith(touched)]
        assert not offenders, offenders

    def test_scoring_package_is_in_g05_scope(self):
        """Satellite (ISSUE 10): scoring/ joined the G05 fault scope when
        packed anchor scoring landed there (scoring/packed.py feeds the
        engine's launch path) — a swallowing broad except in the packed
        encoder would hide a device error inside prompt assembly."""
        for path in ("scoring/packed.py", "scoring/confidence.py"):
            findings = run(path, """
                def encode(tok, packs):
                    try:
                        return tok(packs)
                    except Exception:
                        return None
            """)
            assert rules_of(findings) == ["G05"], path

    def test_scoring_package_lint_clean_without_baseline(self):
        """Satellite (ISSUE 10): scoring/ (incl. the new packed module)
        ships lint-clean — zero findings with NO baseline, and no
        lint_baseline.json entry grandfathers anything under scoring/."""
        from llm_interpretation_replication_tpu.lint.cli import (
            default_baseline_path,
        )

        pkg = next(p for p in default_paths()
                   if p.endswith("llm_interpretation_replication_tpu"))
        assert lint_paths([os.path.join(pkg, "scoring")]) == []
        entries = load_baseline(default_baseline_path())
        assert not [e for e in entries if e.get("path", "").startswith(
            "llm_interpretation_replication_tpu/scoring/")]

    def test_packed_module_is_scanned_by_the_gate(self):
        from llm_interpretation_replication_tpu.lint.cli import (
            iter_python_files,
        )

        pkg = next(p for p in default_paths()
                   if p.endswith("llm_interpretation_replication_tpu"))
        scanned = [f.replace(os.sep, "/") for f in iter_python_files([pkg])]
        assert any("/scoring/packed.py" in f for f in scanned)

    def test_packed_touched_modules_carry_no_baseline_entries(self):
        """Satellite (ISSUE 10): the packed-batching / EOS-bracket change
        ships lint-clean — zero new ``lint_baseline.json`` entries for
        every module it touches (packed scoring + engine anchor path,
        decoder anchor logits, sweep shell, plan/plan_search packing
        terms, benchdiff keys, CLI plumbing, bench)."""
        from llm_interpretation_replication_tpu.lint.cli import (
            default_baseline_path,
        )

        touched = ("scoring/packed.py", "scoring/prompts.py",
                   "runtime/engine.py", "runtime/plan.py",
                   "runtime/plan_search.py", "models/decoder.py",
                   "sweeps/perturbation.py", "obs/benchdiff.py",
                   "config/__init__.py",
                   "llm_interpretation_replication_tpu/__main__.py",
                   "bench.py")
        entries = load_baseline(default_baseline_path())
        offenders = [e for e in entries
                     if e.get("path", "").endswith(touched)]
        assert not offenders, offenders

    def test_plan_search_is_in_g05_scope(self):
        """Satellite (ISSUE 8): the plan search sits between the budget
        model and the engine factory — a broad except swallowing there
        turns a mis-priced candidate into a silent wrong operating point,
        so G05 applies to runtime/plan_search.py like every other runtime
        module (the default-paths walker already scans it; this is the
        teeth check)."""
        findings = run("runtime/plan_search.py", """
            def pick(candidates):
                try:
                    return candidates[0]
                except Exception:
                    return None
        """)
        assert rules_of(findings) == ["G05"]

    def test_plan_search_module_is_scanned_by_the_gate(self):
        from llm_interpretation_replication_tpu.lint.cli import (
            iter_python_files,
        )

        pkg = next(p for p in default_paths()
                   if p.endswith("llm_interpretation_replication_tpu"))
        scanned = [f.replace(os.sep, "/") for f in iter_python_files([pkg])]
        assert any("/runtime/plan_search.py" in f for f in scanned)

    def test_plan_search_touched_modules_carry_no_baseline_entries(self):
        """Satellite (ISSUE 8): the auto-parallel-search change ships
        lint-clean — zero new ``lint_baseline.json`` entries for every
        module it touches (search + budget helpers, mesh enumeration,
        stats comparison, CLI/config plumbing, sweeps logging, bench)."""
        from llm_interpretation_replication_tpu.lint.cli import (
            default_baseline_path,
        )

        touched = ("runtime/plan_search.py", "runtime/plan.py",
                   "parallel/mesh.py", "models/config.py",
                   "stats/correlations.py", "sweeps/perturbation.py",
                   "config/__init__.py",
                   "llm_interpretation_replication_tpu/__main__.py",
                   "bench.py")
        entries = load_baseline(default_baseline_path())
        offenders = [e for e in entries
                     if e.get("path", "").endswith(touched)]
        assert not offenders, offenders

    def test_kdecode_verify_path_is_in_g05_scope(self):
        """Satellite (ISSUE 13): the K-decode verify/propose path lives
        in models/ and runtime/ — both fault scope — so a broad except
        swallowing around a verify pass would hide the device error the
        reject-fallback ladder must classify.  Teeth check for the two
        modules the K path runs through."""
        for path in ("models/decoder.py", "runtime/engine.py"):
            findings = run(path, """
                def verify(block):
                    try:
                        return block.accept()
                    except Exception:
                        return None
            """)
            assert rules_of(findings) == ["G05"], path

    def test_kdecode_touched_modules_are_scanned_by_the_gate(self):
        """Satellite (ISSUE 13): every package module the K-decode change
        touches sits inside the default-paths walker, so the repo gate
        lints the new code on every run."""
        from llm_interpretation_replication_tpu.lint.cli import (
            iter_python_files,
        )

        pkg = next(p for p in default_paths()
                   if p.endswith("llm_interpretation_replication_tpu"))
        scanned = [f.replace(os.sep, "/") for f in iter_python_files([pkg])]
        for mod in ("/models/decoder.py", "/runtime/engine.py",
                    "/runtime/plan.py", "/runtime/plan_search.py",
                    "/serve/request.py", "/serve/coalescer.py",
                    "/serve/scheduler.py", "/obs/benchdiff.py"):
            assert any(mod in f for f in scanned), mod

    def test_kdecode_touched_modules_carry_no_baseline_entries(self):
        """Satellite (ISSUE 13): the joint K-token decode change ships
        lint-clean — zero new ``lint_baseline.json`` entries for every
        module it touches (decoder K-head/verify program, engine K-chunk
        driver, plan/plan_search K axis, serve request/coalescer/
        scheduler key plumbing, benchdiff K tags, CLI/config plumbing,
        bench)."""
        from llm_interpretation_replication_tpu.lint.cli import (
            default_baseline_path,
        )

        touched = ("models/decoder.py", "runtime/engine.py",
                   "runtime/plan.py", "runtime/plan_search.py",
                   "serve/request.py", "serve/coalescer.py",
                   "serve/scheduler.py", "obs/benchdiff.py",
                   "config/__init__.py",
                   "llm_interpretation_replication_tpu/__main__.py",
                   "bench.py")
        entries = load_baseline(default_baseline_path())
        offenders = [e for e in entries
                     if e.get("path", "").endswith(touched)]
        assert not offenders, offenders

    def test_gate_would_catch_an_injected_violation(self, tmp_path):
        """End-to-end teeth check: copy one real hot-path file, inject a
        G01 `.item()` into it, and confirm the same entry point that the
        gate test runs reports it."""
        victim = tmp_path / "models"
        victim.mkdir()
        src = os.path.join(os.path.dirname(default_paths()[0]),
                           "llm_interpretation_replication_tpu", "models",
                           "decoder.py")
        text = open(src).read()
        text += ("\n\ndef _injected(x):\n"
                 "    return x.item()\n")
        (victim / "decoder.py").write_text(text)
        findings = lint_paths([str(victim)], root=str(tmp_path))
        injected = [f for f in findings if f.rule == "G01"]
        assert injected and injected[0].path == "models/decoder.py"


# ---------------------------------------------------------------------------
# Strict mode (runtime/strict.py) — the runtime complement
# ---------------------------------------------------------------------------

class TestStrictMode:
    @pytest.fixture(autouse=True)
    def _clean(self):
        from llm_interpretation_replication_tpu.runtime import strict

        strict.deactivate()
        yield
        strict.deactivate()

    def test_env_gate(self, monkeypatch):
        from llm_interpretation_replication_tpu.runtime import strict

        monkeypatch.delenv(strict.STRICT_ENV, raising=False)
        assert not strict.activate_from_env()
        monkeypatch.setenv(strict.STRICT_ENV, "0")
        assert not strict.activate_from_env()
        monkeypatch.setenv(strict.STRICT_ENV, "1")
        assert strict.activate_from_env()
        assert strict.strict_enabled()

    def test_contexts_are_noops_when_inactive(self):
        import numpy as np
        import jax.numpy as jnp

        from llm_interpretation_replication_tpu.runtime import strict

        snap = telemetry.counters()
        with strict.scoring_guard("t"), strict.device_region("t"):
            with strict.sanctioned_fetch():
                jnp.sin(np.ones((2,)))  # implicit h2d: fine when inactive
        assert telemetry.counters_since(snap).get(
            strict.BLOCKED_COUNTER, 0) == 0

    def test_device_region_blocks_and_counts(self):
        import numpy as np
        import jax.numpy as jnp

        from llm_interpretation_replication_tpu.runtime import strict

        strict.activate(sentry=False)
        snap = telemetry.counters()
        with pytest.raises(Exception, match="[Dd]isallowed"):
            with strict.device_region("test"):
                jnp.sin(np.ones((4,)))  # implicit host->device transfer
        assert telemetry.counters_since(snap)[strict.BLOCKED_COUNTER] == 1
        assert telemetry.fault_events("blocked_transfer")

    def test_recompile_sentry_counts_fresh_compiles_only(self):
        import jax
        import jax.numpy as jnp

        from llm_interpretation_replication_tpu.runtime import strict

        strict.activate()

        @jax.jit
        def probe(x):
            return x * 3.0 + 1.0

        snap = telemetry.counters()
        probe(jnp.ones((5,))).block_until_ready()
        cold = telemetry.counters_since(snap).get(strict.RECOMPILE_COUNTER, 0)
        assert cold >= 1
        assert strict.sentry_programs()
        snap = telemetry.counters()
        probe(jnp.ones((5,))).block_until_ready()  # warm: cached executable
        assert telemetry.counters_since(snap).get(
            strict.RECOMPILE_COUNTER, 0) == 0

    def test_activate_upgrades_guards_only_to_sentry(self):
        from llm_interpretation_replication_tpu.runtime import strict

        strict.activate(sentry=False)
        assert strict.strict_enabled() and strict.sentry_programs() == []
        strict.activate()  # bench/CLI arming later in the same process
        import jax
        import jax.numpy as jnp

        @jax.jit
        def upgrade_probe(x):
            return x - 7.0

        snap = telemetry.counters()
        upgrade_probe(jnp.ones((3,))).block_until_ready()
        assert telemetry.counters_since(snap).get(
            strict.RECOMPILE_COUNTER, 0) >= 1

    def test_strict_report_shape(self):
        from llm_interpretation_replication_tpu.runtime import strict

        strict.activate(sentry=False)
        rep = strict.strict_report()
        assert rep["enabled"] is True
        # "samples" is the optional ring-truncation visibility block
        # (ISSUE-6 satellite): present only when sample rings recorded
        assert set(rep) - {"samples"} == {
            "enabled", strict.RECOMPILE_COUNTER, strict.BLOCKED_COUNTER}
        for ring in rep.get("samples", {}).values():
            assert set(ring) == {"total", "retained", "cap"}
            assert ring["total"] >= ring["retained"]


class TestStrictFusedSweep:
    """Acceptance: a 2-batch fused two-leg sweep runs under strict mode
    with blocked_transfers == 0 and a flat warm-repeat recompile count."""

    def test_two_chunk_fused_sweep_clean_and_warm_stable(self):
        from test_runtime import _tiny_engine

        from llm_interpretation_replication_tpu.runtime import strict
        from llm_interpretation_replication_tpu.runtime.engine import LegSpec

        eng, _, _ = _tiny_engine(batch_size=4)
        # 8 rows at batch 4 -> two pipelined batches ("2-chunk")
        pairs = [
            (f"Scenario {i}: the contract covers vehicles.",
             ("Answer Yes or No.", "Give a confidence from 0 to 100."))
            for i in range(8)
        ]
        legs = [LegSpec("binary"),
                LegSpec("confidence", with_confidence=True,
                        max_new_tokens=10)]
        strict.activate()
        try:
            snap = telemetry.counters()
            cold = eng.score_prefixed(pairs, targets=("Yes", "No"),
                                      legs=legs)
            d_cold = telemetry.counters_since(snap)
            assert d_cold.get(strict.BLOCKED_COUNTER, 0) == 0
            assert len(cold) == 2 and len(cold[0]) == 8
            assert eng.last_prefix_pool.consistent

            snap = telemetry.counters()
            warm = eng.score_prefixed(pairs, targets=("Yes", "No"),
                                      legs=legs)
            d_warm = telemetry.counters_since(snap)
            assert d_warm.get(strict.BLOCKED_COUNTER, 0) == 0
            # warm repeat must not recompile: plan keys + bucketed shapes
            # are stable, so a nonzero delta is a cache-key leak
            assert d_warm.get(strict.RECOMPILE_COUNTER, 0) == 0
            for a, b in zip(cold[0], warm[0]):
                assert a["relative_prob"] == pytest.approx(
                    b["relative_prob"], abs=1e-9)
        finally:
            strict.deactivate()
