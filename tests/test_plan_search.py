"""Auto-parallel plan search (ISSUE 8, ``-m plansearch``, tier-1).

Pins the three contracts of the strategy search:

- **the cost model cannot drift**: every coefficient is a literal anchored
  to a measured BENCH/MULTICHIP number (120.15 p/s at batch 320, 112.0 at
  256, 31.64 full-study rows/s at 224), and the predicted rates at those
  operating points are pinned here to the measured values — the PR-5
  anchor discipline applied to the estimator.
- **the budget filter reuses plan.py, sharded per mesh axis**: the
  per-device need is the exact resolve_full_sweep_plan term sum at
  dp=tp=1 (byte-pinned), weights divide across tp, batch-leading terms
  across dp, and falcon's MQA single kv head is NOT credited with a tp
  division its replicated cache cannot deliver.
- **the search reproduces the hand-picked operating points**: batch 320
  for the binary sweep (the BENCH_r05 headline), int8 KV at batch >= 320
  for the full-study contract (the PR-5 prediction), and a chosen
  8-device plan that beats the hand-picked MULTICHIP_r05 dp4xtp2 mesh —
  with every rejection carrying an auditable reason.
"""

import json
import os
import shutil
import subprocess
import sys

import pytest

from llm_interpretation_replication_tpu.models.config import (
    DecoderConfig,
    FALCON_7B_GEOMETRY,
    SMALL_1B_GEOMETRY,
)
from llm_interpretation_replication_tpu.parallel.mesh import (
    enumerate_mesh_shapes,
)
from llm_interpretation_replication_tpu.runtime import plan as plan_mod
from llm_interpretation_replication_tpu.runtime import plan_search as ps

pytestmark = pytest.mark.plansearch

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _falcon():
    return DecoderConfig(**FALCON_7B_GEOMETRY)


# ---------------------------------------------------------------------------
# Cost-model coefficient + prediction anchors
# ---------------------------------------------------------------------------

class TestCostModelAnchors:
    def test_coefficients_are_pinned(self):
        """The calibrated literals: a change here is a re-calibration and
        must cite a new measured anchor (module docstring)."""
        assert ps.ROWS_CEILING == 169.5
        assert ps.BATCH_HALF_SAT == 131.4
        assert ps.FULL_STUDY_WORK == 3.38
        assert ps.TP_COMM_PENALTY == 0.07
        assert ps.INT8_KV_PENALTY == 0.02
        assert ps.CHUNK_PENALTY == 0.01
        assert ps.CALIBRATION_PARAMS == 6_921_420_800
        assert ps.BINARY_SWEEP_HEADROOM_BYTES == 7 << 28

    def test_calibration_params_match_the_falcon_geometry(self):
        assert plan_mod.param_count(_falcon()) == ps.CALIBRATION_PARAMS

    def test_predicted_binary_anchors(self):
        """The BENCH_r05 pair the saturating curve was solved from."""
        f7 = _falcon()
        assert ps.predicted_rows_per_s(
            f7, 1, 1, 320, workload="binary") == pytest.approx(120.15,
                                                               abs=0.5)
        assert ps.predicted_rows_per_s(
            f7, 1, 1, 256, workload="binary") == pytest.approx(112.0,
                                                               abs=0.5)

    def test_predicted_full_study_anchor(self):
        """31.64 measured rows/s at the bf16-KV batch-224 operating
        point (BENCH_r05 full-study secondary)."""
        assert ps.predicted_rows_per_s(
            _falcon(), 1, 1, 224, workload="full") == pytest.approx(
                31.64, abs=0.5)

    def test_predicted_ordering_int8_chunk_batch320_beats_bf16_224(self):
        """THE ISSUE-8 ordering: the PR-5 operating point must out-rank
        the r5 hand-picked one even after the int8/chunk penalties."""
        f7 = _falcon()
        new = ps.predicted_rows_per_s(f7, 1, 1, 320, kv_dtype="int8",
                                      prefill_chunk=128, workload="full")
        old = ps.predicted_rows_per_s(f7, 1, 1, 224, workload="full")
        assert new > old

    def test_tp_penalty_and_dp_scaling(self):
        """dp multiplies device rate at fixed per-device batch; tp costs
        the collective penalty at the same global batch."""
        f7 = _falcon()
        one = ps.predicted_rows_per_s(f7, 1, 1, 64, workload="binary")
        four = ps.predicted_rows_per_s(f7, 4, 1, 256, workload="binary")
        assert four == pytest.approx(4 * one, rel=1e-9)
        tp2 = ps.predicted_rows_per_s(f7, 4, 2, 256, workload="binary")
        assert tp2 == pytest.approx(four / 1.07, rel=1e-9)

    def test_chunk_penalty_scales_with_replay_count(self):
        """The replay tax is per extra chunk: chunk 64 at the 256-token
        bucket pays 3 replays, chunk 128 pays 1, and a chunk covering the
        whole bucket is monolithic prefill (no penalty) — so chunk 64 can
        never tie chunk 128 and win on an arbitrary tie-break."""
        f7 = _falcon()
        base = ps.predicted_rows_per_s(f7, 1, 1, 320, workload="full")
        c128 = ps.predicted_rows_per_s(f7, 1, 1, 320, prefill_chunk=128,
                                       workload="full")
        c64 = ps.predicted_rows_per_s(f7, 1, 1, 320, prefill_chunk=64,
                                      workload="full")
        assert c128 == pytest.approx(base * 0.99, rel=1e-9)
        assert c64 == pytest.approx(base * 0.97, rel=1e-9)
        assert ps.predicted_rows_per_s(
            f7, 1, 1, 320, prefill_chunk=256,
            workload="full") == pytest.approx(base, rel=1e-9)

    def test_small_geometry_scales_by_params(self):
        small = DecoderConfig(**SMALL_1B_GEOMETRY)
        ratio = (ps.predicted_rows_per_s(small, 1, 1, 320)
                 / ps.predicted_rows_per_s(_falcon(), 1, 1, 320))
        assert ratio == pytest.approx(
            ps.CALIBRATION_PARAMS / plan_mod.param_count(small), rel=1e-9)


# ---------------------------------------------------------------------------
# Sharded byte predictions (the plan.py reuse contract)
# ---------------------------------------------------------------------------

class TestShardedNeedBytes:
    def _terms(self, b=320, kv="int8", chunk=128):
        f7 = _falcon()
        wb = plan_mod.weight_bytes(f7, "int8")
        return f7, plan_mod.full_study_need_terms(
            f7, wb, "xla", b, 256, kv_dtype=kv, prefill_chunk=chunk,
            pooled_confidence=True)

    def test_dp1_tp1_matches_resolve_full_sweep_plan_sum(self):
        """At dp=tp=pp=1 the sharded need IS resolve_full_sweep_plan's
        need(b) — the search and the single-chip planner can never
        disagree about the unsharded live set."""
        f7, terms = self._terms()
        assert ps.sharded_need_bytes(terms, f7, 1, 1, 1) \
            == sum(terms.values())
        # the documented ISSUE-7 fit: 13.4 GiB of 15.0 at batch 320
        assert sum(terms.values()) / 2**30 == pytest.approx(13.4, abs=0.1)

    def test_weights_divide_across_tp_and_pp(self):
        f7, terms = self._terms()
        tp2 = ps.sharded_need_bytes(terms, f7, 1, 2, 1)
        assert tp2 < sum(terms.values())
        # falcon heads (71) don't divide tp=2, and MQA kv (1 head) never
        # divides: ONLY the weights term shrinks
        expected = (terms["weights"] // 2 + terms["attn"] + terms["act"]
                    + terms["completions"] + terms["conf_pool"])
        assert tp2 == expected

    def test_batch_terms_divide_across_dp_kv_not_across_tp_for_mqa(self):
        f7, terms = self._terms()
        dp2 = ps.sharded_need_bytes(terms, f7, 2, 1, 1)
        expected = (terms["weights"] + terms["attn"] // 2
                    + terms["act"] // 2 + terms["completions"] // 2
                    + terms["conf_pool"] // 2)
        assert dp2 == expected

    def test_kv_divides_across_tp_when_heads_divide(self):
        """A GQA geometry (4 kv heads) DOES earn the tp division on its
        cache terms — the MQA exception is per-geometry, not global."""
        gqa = DecoderConfig(
            vocab_size=1024, hidden_size=256, num_layers=4, num_heads=8,
            num_kv_heads=4, intermediate_size=1024,
            position_embedding="rotary", max_position_embeddings=512)
        wb = plan_mod.weight_bytes(gqa, "int8")
        terms = plan_mod.full_study_need_terms(
            gqa, wb, "xla", 32, 96, pooled_confidence=True)
        tp2 = ps.sharded_need_bytes(terms, gqa, 1, 2, 1)
        expected = (terms["weights"] // 2 + terms["attn"] // 2
                    + terms["act"] + terms["completions"] // 2
                    + terms["conf_pool"] // 2)
        assert tp2 == expected


# ---------------------------------------------------------------------------
# Mesh enumeration (parallel/mesh.py)
# ---------------------------------------------------------------------------

class TestMeshEnumeration:
    def test_eight_device_shapes(self):
        shapes = enumerate_mesh_shapes(8, max_pipe=2)
        assert (8, 1, 1) in shapes and (4, 1, 2) in shapes
        assert (4, 2, 1) in shapes and (2, 2, 2) in shapes
        for d, p, m in shapes:
            assert d * p * m == 8

    def test_data_major_order_and_bounds(self):
        shapes = enumerate_mesh_shapes(8, max_model=2, max_pipe=1)
        assert shapes[0] == (8, 1, 1)
        assert all(m <= 2 and p == 1 for _, p, m in shapes)

    def test_invalid_device_count(self):
        with pytest.raises(ValueError):
            enumerate_mesh_shapes(0)


# ---------------------------------------------------------------------------
# The search: hand-picked operating points reproduced
# ---------------------------------------------------------------------------

class TestSearch:
    def test_binary_single_chip_reproduces_batch_320(self):
        """The BENCH_r05 headline: hand-picked batch 320 (120.15 p/s
        measured; 352/384 ResourceExhaust).  The search must land there
        from the model alone, and must reject 352."""
        ranked = ps.search_plans(_falcon(), "int8", 1, workload="binary")
        best = ps.chosen_plan(ranked)
        assert best is not None and best.batch == 320
        assert best.predicted_rows_per_s == pytest.approx(120.15, abs=0.5)
        rejected_352 = [c for c in ranked
                        if c.batch == 352 and not c.fits]
        assert rejected_352 and "over budget" in rejected_352[0].reason
        # the binary need terms are not kv-dtype-aware, so the kv axis
        # collapses to bf16 (int8 twins would be dominated duplicates)
        assert {c.kv_dtype for c in ranked} == {"bf16"}

    def test_full_study_single_chip_needs_int8_past_224(self):
        """The PR-5 prediction: bf16 KV cannot carry the full-study
        contract past the 224 cliff; the chosen plan runs int8 KV at
        batch >= 320, and the int8+chunk-128 batch-320 candidate fits."""
        ranked = ps.search_plans(_falcon(), "int8", 1, workload="full")
        best = ps.chosen_plan(ranked)
        assert best is not None
        assert best.kv_dtype == "int8" and best.batch >= 320
        pr5 = [c for c in ranked
               if c.batch == 320 and c.kv_dtype == "int8"
               and c.prefill_chunk == 128 and c.pool_target == 0]
        assert pr5 and pr5[0].fits
        bf16_320 = [c for c in ranked
                    if c.batch == 320 and c.kv_dtype == "bf16"
                    and c.prefill_chunk == 128 and c.pool_target == 0]
        assert bf16_320 and not bf16_320[0].fits

    def test_full_study_bf16_224_boundary(self):
        """The measured bf16 boundary: 224 fits (momentarily without the
        pooled-confidence term — the r5 contract), 256 does not."""
        f7 = _falcon()
        wb = plan_mod.weight_bytes(f7, "int8")
        budget = (plan_mod.HBM_BYTES_V5E - plan_mod.RESERVE_BYTES
                  - plan_mod.THRASH_HEADROOM_BYTES)
        for b, fits in ((224, True), (256, False)):
            terms = plan_mod.full_study_need_terms(
                f7, wb, "xla", b, 256, kv_dtype="bf16",
                pooled_confidence=False)
            assert (ps.sharded_need_bytes(terms, f7, 1, 1, 1)
                    <= budget) is fits

    def test_reject_reasons_are_auditable(self):
        ranked = ps.search_plans(_falcon(), "int8", 8, workload="full",
                                 max_pipe=2)
        reasons = {c.reason for c in ranked if not c.fits}
        assert any("pipe axis unsupported" in r for r in reasons)
        # falcon's 71 heads divide no tp degree > 1
        assert any("num_heads 71 not divisible" in r for r in reasons)
        assert any("not sublane-aligned" in r for r in reasons)
        # over-budget rejections appear where the budget actually binds:
        # the single-chip space (8-way dp shards every batch term)
        single = ps.search_plans(_falcon(), "int8", 1, workload="full")
        assert any("over budget" in c.reason for c in single
                   if not c.fits)

    def test_fit_reasons_use_the_unified_budget_audit_spelling(self):
        """ISSUE-8 satellite: search fit reasons, rejections, and
        resolve_full_sweep_plan all route through plan.budget_audit /
        budget_reject, so the JSON block and stderr can never disagree."""
        ranked = ps.search_plans(_falcon(), "int8", 1, workload="full")
        best = ps.chosen_plan(ranked)
        budget = (plan_mod.HBM_BYTES_V5E - plan_mod.RESERVE_BYTES
                  - plan_mod.THRASH_HEADROOM_BYTES)
        assert plan_mod.budget_audit(best.need_bytes, budget) in best.reason
        reject = next(c for c in ranked
                      if not c.fits and "over budget" in c.reason)
        assert plan_mod.budget_reject(reject.need_bytes, budget) \
            in reject.reason
        # and the single-chip planner's reason carries the same fragment
        resolved = plan_mod.resolve_full_sweep_plan(
            _falcon(), "int8", 320, 256, pipeline_depth=2,
            kv_dtype="int8", prefill_chunk=128, pooled_confidence=True)
        assert " GiB of " in resolved.reason

    def test_ranking_prefers_simpler_config_on_ties(self):
        """bf16 out-ranks int8 and chunk 0 out-ranks chunked at the same
        predicted rate class; rejected candidates always sort last."""
        ranked = ps.search_plans(_falcon(), "int8", 1, workload="full")
        fits = [c.fits for c in ranked]
        assert fits == sorted(fits, reverse=True)
        preds = [c.predicted_rows_per_s for c in ranked if c.fits]
        assert preds == sorted(preds, reverse=True)

    def test_workload_validation(self):
        with pytest.raises(ValueError):
            ps.search_plans(_falcon(), "int8", 1, workload="train")

    def test_chunks_covering_the_bucket_are_not_enumerated(self):
        """chunk >= seq is monolithic prefill (zero replays, identical
        bound): enumerating it would pad the runner-up table with no-op
        duplicates of the chunk-0 rows."""
        ranked = ps.search_plans(_falcon(), "int8", 1, workload="full")
        assert all(c.prefill_chunk < 256 for c in ranked)

    def test_flash_pricing_uses_the_workspace_not_dense_scores(self):
        """A flash run budgets the fp32 output workspace, not the dense
        [B, H, S, S] score tensor the kernel never materializes — at the
        sweep bucket the dense tensor is the larger term, so flash must
        admit batches dense rejects."""
        f7 = _falcon()
        wb = plan_mod.weight_bytes(f7, "int8")
        dense = ps.binary_need_terms(f7, wb, 384, 256,
                                     attention_impl="xla")
        flash = ps.binary_need_terms(f7, wb, 384, 256,
                                     attention_impl="flash")
        assert flash["attn"] == plan_mod.flash_workspace_bytes(f7, 384,
                                                               256)
        assert flash["attn"] < dense["attn"]
        full_flash = plan_mod.full_study_need_terms(
            f7, wb, "flash", 320, 256, kv_dtype="int8",
            pooled_confidence=True)
        assert full_flash["attn"] == plan_mod.flash_workspace_bytes(
            f7, 320, 256)

    def test_binary_pipeline_depth_moves_the_budget(self):
        """The depth the caller passes must reach the binary terms — a
        depth-8 sweep pins twice the in-flight logits of depth 4."""
        f7 = _falcon()
        wb = plan_mod.weight_bytes(f7, "int8")
        d4 = ps.binary_need_terms(f7, wb, 320, 256, pipeline_depth=4)
        d8 = ps.binary_need_terms(f7, wb, 320, 256, pipeline_depth=8)
        logits = 320 * f7.vocab_size * 4
        assert d8["completions"] - d4["completions"] == 4 * logits


# ---------------------------------------------------------------------------
# Record + table
# ---------------------------------------------------------------------------

class TestRecord:
    def test_plan_search_record_structure(self):
        ranked = ps.search_plans(_falcon(), "int8", 1, workload="full")
        rec = ps.plan_search_record(ranked, top=5)
        assert rec["chosen"]["fits"] is True
        assert rec["chosen"]["predicted_rows_per_s"] > 0
        assert len(rec["runners_up"]) == 5
        assert rec["n_candidates"] == len(ranked)
        assert rec["n_fit"] + rec["n_rejected"] == rec["n_candidates"]
        for row in rec["runners_up"]:
            assert row["fits"] and row["reason"]
        for row in rec["rejected_sample"]:
            assert not row["fits"] and row["reason"]
        json.dumps(rec)  # the block must be JSON-able as recorded

    def test_format_table_lists_chosen_and_reasons(self):
        ranked = ps.search_plans(_falcon(), "int8", 1, workload="binary")
        table = ps.format_candidate_table(ranked, top=3)
        assert "chosen" in table and "fits:" in table
        assert f"{len(ranked)} candidates" in table


# ---------------------------------------------------------------------------
# Dryrun: the virtual 8-device mesh vs the hand-picked MULTICHIP points
# ---------------------------------------------------------------------------

class TestDryrun:
    def test_dryrun_rejects_device_counts_without_the_hand_mesh(self):
        """Any count dp4xtp2 does not factorize must fail with a clear
        message, not a misleading missing-candidate assertion."""
        with pytest.raises(ValueError, match="factorizes exactly 8"):
            ps.run_dryrun(n_devices=16, exec_leg=False)

    def test_dryrun_beats_hand_picked_mesh(self, eight_cpu_devices,
                                           capsys):
        result = ps.run_dryrun(n_devices=8, exec_leg=False)
        assert result["chosen"]["predicted_rows_per_s"] \
            >= result["hand_picked"]["predicted_rows_per_s"]
        assert result["hand_picked"]["mesh"] == ps.HAND_PICKED_MULTICHIP
        err = capsys.readouterr().err
        assert "plan search dryrun OK" in err

    def test_dryrun_exec_leg_runs_the_chosen_mesh(self, eight_cpu_devices,
                                                  capsys):
        """The chosen plan is proven runnable, not just priced: a tiny
        sharded engine scores with single-device parity on the chosen
        mesh shape."""
        result = ps.run_dryrun(n_devices=8, exec_leg=True)
        assert result["exec"]["parity"] is True
        assert result["exec"]["mesh"]["data"] \
            * result["exec"]["mesh"]["model"] <= 8
        assert "exec parity checked" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# CLI + bench wiring
# ---------------------------------------------------------------------------
# Packed batch prompting (ISSUE 10): coefficient pins + the acceptance
# ordering — packed predicted questions/s beats the isolated prediction at
# equal device budget.
# ---------------------------------------------------------------------------

class TestPackedWorkload:
    def test_packed_coefficients_are_pinned(self):
        """The packed cost-model literals: question/scaffold/demo token
        counts measured through the sweep tokenizer on the real corpus,
        and the no-decode gain solved from the r01-r04 single-vs-parity
        steady-state anchors (38.15 / 36.9)."""
        assert ps.PACKED_QUESTION_TOKENS == 104.0
        assert ps.PACKED_SHARED_TOKENS == 16.0
        assert ps.PACKED_DEMO_TOKENS == 12.0
        assert ps.PACKED_NO_DECODE_GAIN == 1.034
        assert ps.DEFAULT_PACKINGS == (1, 2, 4, 8)
        assert ps.PACKED_SWEEP_HEADROOM_BYTES == 1 << 28

    def test_packed_seq_tokens(self):
        assert ps.packed_seq_tokens(1) == 132
        assert ps.packed_seq_tokens(4) == 480

    def test_packed_beats_isolated_at_equal_budget(self):
        """THE ISSUE-10 acceptance ordering: the chosen packed plan's
        predicted questions/s beats the chosen isolated (binary) plan's
        predicted prompts/s on the same 16 GiB device."""
        f7 = _falcon()
        binary = ps.chosen_plan(ps.search_plans(f7, "int8", 1,
                                                workload="binary"))
        packed = ps.chosen_plan(ps.search_plans(f7, "int8", 1,
                                                workload="packed"))
        assert binary is not None and packed is not None
        assert packed.packing > 1
        assert (packed.predicted_rows_per_s
                > binary.predicted_rows_per_s), (packed, binary)

    def test_packed_q1_pays_the_demo_overhead(self):
        """Q=1 packing is strictly worse than isolated scoring at the
        same batch: the demonstration-continuation tokens buy nothing
        when no later question shares the row — the model must price the
        overhead, not assume packing is free."""
        f7 = _falcon()
        q1 = ps.predicted_rows_per_s(f7, 1, 1, 320, workload="packed",
                                     packing=1)
        iso = ps.predicted_rows_per_s(f7, 1, 1, 320, workload="binary")
        assert q1 < iso

    def test_packed_question_batch_saturates(self):
        """Packed rows saturate the device at the QUESTION batch: Q=4 at
        80 rows predicts like 320 questions, not 80 — modulo the
        no-decode gain and token ratio."""
        f7 = _falcon()
        q4 = ps.predicted_rows_per_s(f7, 1, 1, 80, workload="packed",
                                     packing=4)
        iso320 = ps.predicted_rows_per_s(f7, 1, 1, 320, workload="binary")
        ratio = ((ps.PACKED_SHARED_TOKENS + ps.PACKED_QUESTION_TOKENS)
                 / (ps.PACKED_SHARED_TOKENS / 4 + ps.PACKED_QUESTION_TOKENS
                    + ps.PACKED_DEMO_TOKENS))
        assert q4 == pytest.approx(
            iso320 * ps.PACKED_NO_DECODE_GAIN * ratio, rel=1e-9)

    def test_packed_need_terms_budget_large_q_out(self):
        """The packed attention transient grows quadratically in the row
        length, so the budget filter — not a hand rule — prices out large
        packings at big row batches."""
        f7 = _falcon()
        ranked = ps.search_plans(f7, "int8", 1, workload="packed")
        big = [c for c in ranked if c.packing == 8 and c.batch >= 256]
        assert big and all(not c.fits for c in big)
        # and every reject carries the budget_reject audit spelling
        assert all("over budget" in c.reason for c in big)

    def test_packed_record_carries_packing(self):
        f7 = _falcon()
        rec = ps.plan_search_record(
            ps.search_plans(f7, "int8", 1, workload="packed"))
        assert rec["chosen"]["packing"] > 1
        assert all("packing" in r for r in rec["runners_up"])

    def test_packed_need_terms_shape(self):
        """plan.packed_need_terms mirrors the binary keys so
        sharded_need_bytes prices both workloads, and the anchor-logit
        transient rides the batch-leading 'completions' slot."""
        f7 = _falcon()
        wb = plan_mod.weight_bytes(f7, "int8")
        terms = plan_mod.packed_need_terms(f7, wb, "xla", 96,
                                           ps.packed_seq_tokens(4), 4,
                                           pipeline_depth=4)
        assert set(terms) == {"weights", "attn", "act", "completions"}
        assert terms["completions"] == 4 * 96 * 4 * f7.vocab_size * 4
        assert terms["attn"] == plan_mod.dense_attention_bytes(
            f7, 96, ps.packed_seq_tokens(4))

    def test_cli_accepts_packed_workload(self, capsys):
        rc = ps.main(["search", "--model", "falcon-7b", "--devices", "1",
                      "--workload", "packed", "--format", "json"])
        assert rc == 0
        rec = json.loads(capsys.readouterr().out)
        assert rec["chosen"]["packing"] > 1


class TestCli:
    def test_search_json_output(self, capsys):
        rc = ps.main(["search", "--model", "falcon-7b", "--devices", "1",
                      "--workload", "binary", "--format", "json"])
        assert rc == 0
        rec = json.loads(capsys.readouterr().out)
        assert rec["chosen"]["batch"] == 320

    def test_search_table_output(self, capsys):
        assert ps.main(["search", "--workload", "full"]) == 0
        assert "chosen" in capsys.readouterr().out

    def test_plan_search_reaches_the_full_study_secondary(self):
        """The PR-5 forwarding discipline, ISSUE-12 shape: a
        --plan-search parent must not run its in-process full-study
        secondary at the fixed operating point — the secondary searches
        its OWN full-study workload (the parent's binary-workload
        choice does not transfer across workloads)."""
        bench_src = open(os.path.join(REPO_ROOT, "bench.py")).read()
        secondary = bench_src[bench_src.index("def _full_study_secondary"):]
        secondary = secondary[:secondary.index("\ndef ")]
        assert 'getattr(args, "plan_search", False)' in secondary
        assert 'workload="full"' in secondary

    def test_bench_records_the_plan_search_block(self):
        """Every sweep record attaches the runner-up table: the sweep and
        sweep-packed branches directly, the sweep-full headline AND the
        in-process full-study secondary through the shared record
        builder (_full_study_record)."""
        bench_src = open(os.path.join(REPO_ROOT, "bench.py")).read()
        assert bench_src.count(
            'record["plan_search"] = args.plan_search_report') == 2
        builder = bench_src[bench_src.index("def _full_study_record"):]
        builder = builder[:builder.index("\ndef ")]
        assert 'record["plan_search"] = a.plan_search_report' in builder
        # both full-study consumers go through the shared builder
        assert bench_src.count("_full_study_record(") >= 3


class TestEngineFactoryWiring:
    def test_searched_run_config_rewrites_the_flags(self, tmp_path,
                                                    eight_cpu_devices,
                                                    capsys):
        """The CLI --plan-search path: the factory helper reads a
        snapshot's config.json (no weights), searches the visible
        devices, and rewrites RunConfig (+ builds the dp x tp mesh) to
        the chosen plan."""
        from llm_interpretation_replication_tpu.__main__ import (
            _searched_run_config,
        )
        from llm_interpretation_replication_tpu.config import RunConfig

        snap = tmp_path / "snap"
        snap.mkdir()
        (snap / "config.json").write_text(json.dumps({
            "model_type": "falcon", "vocab_size": 1024,
            "hidden_size": 256, "num_hidden_layers": 4,
            "num_attention_heads": 8, "ffn_hidden_size": 1024,
            "multi_query": True, "parallel_attn": True, "bias": False,
        }))
        rc0 = RunConfig(device="cpu", quant="int8", plan_search=True)
        rc, mesh, note = _searched_run_config(rc0, str(snap), None)
        assert note and "plan search chose" in note
        assert rc.batch_size > 0 and rc.batch_size % 32 == 0
        assert rc.kv_dtype in ("bf16", "int8")
        assert mesh is not None and mesh.shape["data"] >= 1
        assert mesh.shape["data"] * mesh.shape["model"] == 8
        assert "plan search" in capsys.readouterr().err

    def test_unpriceable_geometry_falls_back_to_flags(self, tmp_path,
                                                      capsys):
        from llm_interpretation_replication_tpu.__main__ import (
            _searched_run_config,
        )
        from llm_interpretation_replication_tpu.config import RunConfig

        snap = tmp_path / "snap"
        snap.mkdir()
        (snap / "config.json").write_text(json.dumps({
            "model_type": "not-a-family"}))
        rc0 = RunConfig(device="cpu", plan_search=True, batch_size=16)
        rc, mesh, note = _searched_run_config(rc0, str(snap), None)
        assert rc is rc0 and mesh is None and note is None
        assert "plan search skipped" in capsys.readouterr().err


class TestBenchIntegration:
    def test_bench_main_applies_the_chosen_plan(self, monkeypatch,
                                                capsys):
        """bench.py --mode sweep-full --plan-search end to end through
        main(): the chosen candidate overrides the operating-point args,
        the record carries the plan_search block, and the context block
        names the SAME kv/chunk the search chose (the fit-decision
        unification contract).  Weights init and the sweep itself are
        stubbed — this pins the planning control flow, not throughput."""
        import numpy as np

        import bench

        monkeypatch.setattr(
            bench, "init_params",
            lambda cfg, key, dtype, quant=False: {
                "final_ln": {"scale": np.zeros(4)}})
        seen = {}

        def fake_sweep_full(args, cfg, params):
            seen["args"] = args
            return 12.34, 0.9, None

        monkeypatch.setattr(bench, "run_sweep_full_mode", fake_sweep_full)
        monkeypatch.setattr(sys, "argv", [
            "bench.py", "--mode", "sweep-full", "--plan-search",
            "--sweep-repeats", "1"])
        from llm_interpretation_replication_tpu import obs

        try:
            bench.main()
        finally:
            obs.disable()  # bench arms phases-by-default in sweep modes
        out = capsys.readouterr().out.strip().splitlines()[-1]
        record = json.loads(out)
        chosen = record["plan_search"]["chosen"]
        args = seen["args"]
        assert args.sweep_batch == chosen["batch"]
        assert args.kv_dtype == chosen["kv_dtype"] == "int8"
        assert args.prefill_chunk == chosen["prefill_chunk"]
        assert args.fit_decision == chosen["reason"]
        assert record["context"]["kv_dtype"] == chosen["kv_dtype"]
        assert record["context"]["planner"] == chosen["reason"]
        assert record["plan_search"]["runners_up"]
        assert record["plan_search"]["n_rejected"] > 0


# ---------------------------------------------------------------------------
# Console entry point (ROADMAP item 5): the installed-script path
# ---------------------------------------------------------------------------

def _console_cmd():
    """The ``llm-interp-tpu`` console script if installed; otherwise the
    exact shim setuptools generates for the [project.scripts] spec in
    pyproject.toml — resolving the spec catches a typo'd module/attr the
    same way a fresh ``pip install`` would."""
    exe = shutil.which("llm-interp-tpu")
    if exe:
        return [exe]
    with open(os.path.join(REPO_ROOT, "pyproject.toml"),
              encoding="utf-8") as f:
        pyproject = f.read()
    try:  # tomllib is 3.11+; the regex reads the same key on 3.10
        import tomllib

        target = tomllib.loads(pyproject)["project"]["scripts"][
            "llm-interp-tpu"]
    except ModuleNotFoundError:
        import re

        match = re.search(r'^llm-interp-tpu\s*=\s*"([^"]+)"', pyproject,
                          re.MULTILINE)
        assert match, "no [project.scripts] llm-interp-tpu entry"
        target = match.group(1)
    module, _, attr = target.partition(":")
    shim = (f"import sys; from {module} import {attr} as m; "
            f"sys.exit(m())")
    return [sys.executable, "-c", shim]


class TestConsoleEntryPoint:
    def _run(self, *argv, timeout=300):
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=REPO_ROOT
                   + os.pathsep + os.environ.get("PYTHONPATH", ""))
        env.pop("XLA_FLAGS", None)  # the dryrun sets its own device count
        return subprocess.run(_console_cmd() + list(argv), cwd=REPO_ROOT,
                              env=env, capture_output=True, text=True,
                              timeout=timeout)

    def test_help_runs(self):
        proc = self._run("--help")
        assert proc.returncode == 0, proc.stderr
        assert "run-perturbation" in proc.stdout
        assert "plan" in proc.stdout

    def test_plan_search_dryrun_runs(self):
        """The ISSUE-8 acceptance leg through the console script: the
        dryrun's prediction comparison on the virtual 8-device mesh
        (--no-exec keeps the tier-1 gate off the compile path; the exec
        leg is covered in-process above)."""
        proc = self._run("plan", "search", "--dryrun", "--no-exec")
        assert proc.returncode == 0, proc.stderr
        assert "plan search dryrun OK" in proc.stderr


class TestRolePricing:
    """Role-specialist operating points for the disaggregated fleet
    (ISSUE 20): replica_plan(role=...) re-ranks the slice's fitting
    candidates by the role_rate_factor-adjusted rate."""

    def test_role_coefficients_are_pinned(self):
        """Provenance pins: both priors carry their anchor comments in
        source (the lint contracts convention) and these exact values —
        recalibrate them only against a roles bench record."""
        from llm_interpretation_replication_tpu.runtime import plan_search

        assert plan_search.PREFILL_PHASE_SHARE == 0.72
        assert plan_search.DECODE_REFILL_GAIN == 1.08

    def test_role_rate_factor_shapes(self):
        from llm_interpretation_replication_tpu.runtime.plan_search import (
            DECODE_REFILL_GAIN,
            PREFILL_PHASE_SHARE,
            k_decode_speedup,
            role_rate_factor,
        )

        assert role_rate_factor(None) == 1.0
        # prefill specialist: the symmetric rate divided by the prefill
        # phase share (no chunking: no replays to charge)
        assert role_rate_factor("prefill") == pytest.approx(
            1.0 / PREFILL_PHASE_SHARE)
        # chunk replays charge ABSOLUTELY against the prefill-only row:
        # chunked candidates separate harder than under symmetric pricing
        chunked = role_rate_factor("prefill", prefill_chunk=64, seq=256)
        assert chunked < role_rate_factor("prefill")
        # decode specialist: only the decode share, slot-refill gain on
        # pooled candidates, full K-decode speedup
        base = 1.0 / (1.0 - PREFILL_PHASE_SHARE)
        assert role_rate_factor("decode") == pytest.approx(base)
        assert role_rate_factor("decode", pool_target=320) == \
            pytest.approx(base * DECODE_REFILL_GAIN)
        assert role_rate_factor("decode", pool_target=320, decode_k=2) \
            == pytest.approx(base * DECODE_REFILL_GAIN
                             * k_decode_speedup(2))
        with pytest.raises(ValueError):
            role_rate_factor("draft")

    def test_replica_plan_prices_roles_with_reason_tag(self):
        from llm_interpretation_replication_tpu.models.config import (
            BENCH_GEOMETRIES,
            DecoderConfig,
        )
        from llm_interpretation_replication_tpu.runtime.plan_search import (
            replica_plan,
        )

        cfg = DecoderConfig(**BENCH_GEOMETRIES["falcon-7b"])
        sym = replica_plan(cfg, "int8", 1, workload="binary")
        pre = replica_plan(cfg, "int8", 1, workload="binary",
                           role="prefill")
        dec = replica_plan(cfg, "int8", 1, workload="binary",
                           role="decode")
        assert sym is not None and pre is not None and dec is not None
        assert "[role=" not in sym.reason
        assert "[role=prefill x" in pre.reason
        assert "[role=decode x" in dec.reason
        # specialists price ABOVE the symmetric estimate (each runs only
        # its share of the row)
        assert pre.predicted_rows_per_s > sym.predicted_rows_per_s
        assert dec.predicted_rows_per_s > sym.predicted_rows_per_s
