"""Int8 KV cache + chunked prefill (ISSUE 5, ``-m kvcache``, tier-1).

Pins the three contracts of the kv-dtype layer:

- **bf16 stays bit-parity**: the default engine's cache layout and every
  chunked-vs-monolithic prefill comparison reproduce the monolithic bf16
  path (exact position-0 fields; scored fields to reduction-order noise),
  so the fused-vs-unfused and serve `--replay` parity contracts are
  untouched.
- **int8 KV is tolerance-parity**: quantize/dequant round-trips within the
  per-head-scale error bound, prompt-forward logits stay bit-identical
  (quantization touches STORAGE only), and full scoring rows agree with
  the bf16 engine within the tolerance documented in PARITY.md
  (|Δ relative_prob| <= 0.05 on this harness).
- **the budget model predicts, never discovers**: the calibrated v5e
  anchor points (w8a8 192/432 fits; bf16 flash 64 fits / 128+ OOM; the
  full-study 224 boundary) cannot drift, and the kv-dtype-aware +
  chunked-prefill terms put the full-study sweep back at batch >= 320
  under int8 KV.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from test_runtime import _tiny_engine

from llm_interpretation_replication_tpu.models.config import DecoderConfig
from llm_interpretation_replication_tpu.models import decoder as dmod
from llm_interpretation_replication_tpu.ops import quant
from llm_interpretation_replication_tpu.runtime.engine import (
    EngineConfig,
    LegSpec,
    ScoringEngine,
)
from llm_interpretation_replication_tpu.utils import telemetry

pytestmark = pytest.mark.kvcache

#: Documented int8-KV tolerance (PARITY.md "Int8 KV cache"): scored-decode
#: probability fields of an int8-KV engine vs the bf16 engine.  The prompt
#: forward always runs on exact projections, so monolithic position-0
#: fields are bit-identical; only decode / suffix-extension reads pass
#: through dequantized values.
INT8_KV_ATOL = 0.05

EXACT_FIELDS = ("first_token_yes_prob", "first_token_no_prob",
                "first_token_relative_prob")
PROB_FIELDS = ("yes_prob", "no_prob", "relative_prob")


def _clone_engine(eng, tok, **ecfg_kw):
    """A second engine over the SAME params/tokenizer with engine-config
    overrides — kv_dtype lands on the decoder config at construction."""
    return ScoringEngine(
        eng.family, eng.cfg, eng.params, tok,
        engine_config=dataclasses.replace(eng.ecfg, **ecfg_kw))


def _tiny_cfg(**kw):
    base = dict(vocab_size=128, hidden_size=32, num_layers=2, num_heads=4,
                num_kv_heads=2, intermediate_size=64,
                position_embedding="rotary", qkv_bias=False, out_bias=False,
                mlp_bias=False)
    base.update(kw)
    return DecoderConfig(**base)


def _prompt_batch(cfg, batch=3, seq=24, lens=(24, 13, 7), seed=0):
    rng = np.random.default_rng(seed)
    ids = jnp.asarray(
        rng.integers(1, cfg.vocab_size - 1, size=(batch, seq)).astype(np.int32))
    mask = jnp.asarray(
        (np.arange(seq)[None, :] < np.asarray(lens)[:, None]).astype(np.int32))
    return ids, mask


# ---------------------------------------------------------------------------
# Quantize/dequant round-trip (ops/quant.py)
# ---------------------------------------------------------------------------

class TestQuantRoundTrip:
    def test_round_trip_within_per_head_scale_bound(self):
        rng = np.random.default_rng(3)
        # cache-shaped block with wildly different per-(slot, head) ranges,
        # the case per-TENSOR scales would butcher
        x = rng.standard_normal((2, 3, 8, 2, 16)).astype(np.float32)
        x *= (10.0 ** rng.integers(-3, 3, size=(2, 3, 8, 2, 1)))
        q, scale = quant.quantize_kv(jnp.asarray(x))
        assert q.dtype == jnp.int8 and q.shape == x.shape
        assert scale.shape == x.shape[:-1]
        deq = np.asarray(quant.dequantize_kv(q, scale))
        # symmetric int8: round-trip error is at most half a code step,
        # i.e. scale/2 per element — PER HEAD, independent of other heads
        bound = np.asarray(scale)[..., None] * 0.5 + 1e-12
        assert np.all(np.abs(deq - x) <= bound)

    def test_zero_block_is_exact_and_finite(self):
        q, scale = quant.quantize_kv(jnp.zeros((1, 2, 4, 1, 8)))
        assert np.all(np.asarray(q) == 0)
        assert np.all(np.isfinite(np.asarray(scale)))
        assert np.all(np.asarray(quant.dequantize_kv(q, scale)) == 0)

    def test_codes_cover_the_full_range(self):
        x = jnp.asarray(np.linspace(-1, 1, 64, dtype=np.float32)
                        .reshape(1, 1, 4, 1, 16))
        q, _ = quant.quantize_kv(x)
        assert int(jnp.max(jnp.abs(q))) == 127  # absmax maps to full scale


# ---------------------------------------------------------------------------
# Chunked-vs-monolithic prefill equivalence at bf16 (models/decoder.py)
# ---------------------------------------------------------------------------

class TestChunkedPrefill:
    """Chunk boundaries must be invisible at bf16: same last-token logits,
    same greedy decode continuation, same per-step scores.  Masked key
    slots contribute exact zeros to the joint softmax, so the chunked
    replay agrees to reduction-order noise."""

    def _run(self, chunk):
        cfg = _tiny_cfg()
        from helpers import random_decoder_params

        params = random_decoder_params(cfg)
        ids, mask = _prompt_batch(cfg)
        if chunk is None:
            last, cache = dmod.prefill(params, cfg, ids, mask,
                                       cache_len=ids.shape[1])
        else:
            last, cache, n = dmod.chunked_prefill(params, cfg, ids, mask,
                                                  chunk)
        lengths = jnp.sum(mask, axis=-1)
        toks, scores, _, _, _ = dmod.decode_steps(
            params, cfg, cache, last, lengths, jnp.int32(0), 5, None,
            with_scores=True)
        return np.asarray(last), np.asarray(toks), np.asarray(scores)

    @pytest.mark.parametrize("chunk", [8, 9, 16])
    def test_chunk_sizes_match_monolithic(self, chunk):
        last_m, toks_m, sc_m = self._run(None)
        last_c, toks_c, sc_c = self._run(chunk)
        np.testing.assert_allclose(last_c, last_m, rtol=2e-5, atol=1e-6)
        np.testing.assert_array_equal(toks_c, toks_m)
        np.testing.assert_allclose(sc_c, sc_m, rtol=2e-5, atol=1e-6)

    def test_chunk_count_and_degenerate_chunk(self):
        cfg = _tiny_cfg()
        from helpers import random_decoder_params

        params = random_decoder_params(cfg)
        ids, mask = _prompt_batch(cfg)
        _, _, n = dmod.chunked_prefill(params, cfg, ids, mask, 8)
        assert n == 3                       # 24 tokens / 8-token chunks
        # chunk >= S degenerates to one ordinary prefill
        last_m, cache_m = dmod.prefill(params, cfg, ids, mask, cache_len=24)
        last_1, cache_1, n1 = dmod.chunked_prefill(params, cfg, ids, mask, 64)
        assert n1 == 1
        np.testing.assert_array_equal(np.asarray(last_1), np.asarray(last_m))

    def test_mismatched_cache_dtype_raises(self):
        """extend_prefill must refuse a bf16 cache under an int8 config (and
        vice versa) — a silent concat would corrupt every later read."""
        cfg = _tiny_cfg()
        from helpers import random_decoder_params

        params = random_decoder_params(cfg)
        ids, mask = _prompt_batch(cfg)
        _, cache = dmod.prefill(params, cfg, ids, mask, cache_len=24)
        cfg8 = dataclasses.replace(cfg, kv_cache_dtype="int8")
        with pytest.raises(ValueError, match="kv_cache_dtype"):
            dmod.extend_prefill(params, cfg8, cache, ids[:, :4], mask[:, :4],
                                jnp.sum(mask, axis=-1))


# ---------------------------------------------------------------------------
# Engine-level: chunked prefill rows == monolithic rows (bf16)
# ---------------------------------------------------------------------------

class TestEngineChunkedPrefill:
    def test_rows_match_and_counter_fires(self):
        eng, _, tok = _tiny_engine(batch_size=4)
        chunked = _clone_engine(eng, tok, prefill_chunk=16)
        prompts = [f"Is thing number {i} a kind of stuff?" for i in range(6)]
        base_rows = eng.score_prompts(prompts)
        telemetry.clear_counters()
        rows = chunked.score_prompts(prompts)
        assert telemetry.counter("prefill_chunks") >= 2
        for a, b in zip(rows, base_rows):
            for f in EXACT_FIELDS:
                assert a[f] == b[f], f
            for f in PROB_FIELDS:
                np.testing.assert_allclose(a[f], b[f], rtol=2e-5, atol=1e-9,
                                           err_msg=f)
            assert a["completion"] == b["completion"]

    def test_fused_two_leg_path_matches_under_chunking(self):
        """score_prefixed with a chunked prefix prefill reproduces the
        unchunked fused rows — the chunk replays through the SAME
        suffix-extension machinery the legs use."""
        eng, _, tok = _tiny_engine(batch_size=4)
        chunked = _clone_engine(eng, tok, prefill_chunk=16)
        pairs = [(f"Scenario {i}: the bylaw covers bicycles in the park.",
                  (" Answer Yes or No.", " How confident, 0-100?"))
                 for i in range(5)]
        legs = [LegSpec("binary"),
                LegSpec("confidence", with_confidence=True,
                        max_new_tokens=10)]
        base = eng.score_prefixed(pairs, legs=legs)
        rows = chunked.score_prefixed(pairs, legs=legs)
        assert chunked.last_prefix_pool.consistent
        for leg_a, leg_b in zip(rows, base):
            for a, b in zip(leg_a, leg_b):
                for f in EXACT_FIELDS:
                    assert a[f] == b[f], f
                for f in PROB_FIELDS:
                    np.testing.assert_allclose(a[f], b[f], rtol=2e-5,
                                               atol=1e-9, err_msg=f)


# ---------------------------------------------------------------------------
# Int8 KV parity (tolerance-based — the documented operating point)
# ---------------------------------------------------------------------------

class TestInt8KVParity:
    def test_prompt_forward_bit_identical_storage_only(self):
        """Quantization must touch STORAGE only: the monolithic prefill's
        last-token logits come from exact projections and stay
        bit-identical; the cache itself is int8 + per-head scales."""
        cfg = _tiny_cfg()
        from helpers import random_decoder_params

        params = random_decoder_params(cfg)
        ids, mask = _prompt_batch(cfg)
        last, cache = dmod.prefill(params, cfg, ids, mask, cache_len=24)
        cfg8 = dataclasses.replace(cfg, kv_cache_dtype="int8")
        last8, cache8 = dmod.prefill(params, cfg8, ids, mask, cache_len=24)
        np.testing.assert_array_equal(np.asarray(last8), np.asarray(last))
        assert cache8.k.dtype == jnp.int8
        assert cache8.k_scale.shape == cache8.k.shape[:-1]
        assert cache.k_scale is None

    def test_rows_within_documented_tolerance(self):
        eng, _, tok = _tiny_engine(batch_size=4)
        eng8 = _clone_engine(eng, tok, kv_dtype="int8")
        assert eng8.cfg.kv_cache_dtype == "int8"
        assert eng.cfg.kv_cache_dtype == "bf16"   # source engine untouched
        prompts = [f"Is item {i} considered a vehicle?" for i in range(6)]
        telemetry.clear_counters()
        rows_bf16 = eng.score_prompts(prompts)
        rows_int8 = eng8.score_prompts(prompts)
        assert telemetry.counter("kv_cache_bytes_saved") > 0
        for a, b in zip(rows_int8, rows_bf16):
            # monolithic prefill: position-0 fields are exact
            for f in EXACT_FIELDS:
                assert a[f] == b[f], f
            # scored-decode fields: within the documented tolerance
            for f in PROB_FIELDS:
                assert abs(a[f] - b[f]) <= INT8_KV_ATOL, (f, a[f], b[f])
            assert a["success"] and b["success"]

    def test_fused_legs_within_tolerance_and_pool_consistent(self):
        eng, _, tok = _tiny_engine(batch_size=4)
        eng8 = _clone_engine(eng, tok, kv_dtype="int8", prefill_chunk=16)
        pairs = [(f"Clause {i} talks about animals kept as pets.",
                  (" Answer Yes or No.", " How confident, 0-100?"))
                 for i in range(5)]
        legs = [LegSpec("binary"),
                LegSpec("confidence", with_confidence=True,
                        max_new_tokens=10)]
        base = eng.score_prefixed(pairs, legs=legs)
        rows = eng8.score_prefixed(pairs, legs=legs)
        assert eng8.last_prefix_pool.consistent
        for leg_a, leg_b in zip(rows, base):
            for a, b in zip(leg_a, leg_b):
                for f in PROB_FIELDS:
                    assert abs(a[f] - b[f]) <= INT8_KV_ATOL, (f, a[f], b[f])

    def test_pooled_phase2_path_handles_int8(self):
        """The cross-batch phase-2 pool (gather, blank padding, concat,
        pooled decode) must carry the scale arrays: no-completions
        no-confidence scoring on an int8 engine completes with rows in
        tolerance."""
        eng, _, tok = _tiny_engine(batch_size=4)
        bf = _clone_engine(eng, tok, decode_completions=False)
        i8 = _clone_engine(eng, tok, decode_completions=False,
                           kv_dtype="int8")
        prompts = [f"Is object {i} a beverage or not?" for i in range(9)]
        rows_bf = bf.score_prompts(prompts)
        rows_i8 = i8.score_prompts(prompts)
        for a, b in zip(rows_i8, rows_bf):
            assert a["success"]
            for f in PROB_FIELDS:
                assert abs(a[f] - b[f]) <= INT8_KV_ATOL, (f, a[f], b[f])

    def test_bad_kv_dtype_rejected(self):
        eng, _, tok = _tiny_engine(batch_size=2)
        with pytest.raises(ValueError, match="kv_dtype"):
            _clone_engine(eng, tok, kv_dtype="fp8")
        with pytest.raises(ValueError, match="kv_cache_dtype"):
            _tiny_cfg(kv_cache_dtype="int4")


# ---------------------------------------------------------------------------
# Strict mode: chunked-prefill sweep keeps blocked_transfers == 0
# ---------------------------------------------------------------------------

class TestStrictChunkedSweep:
    def test_chunked_sweep_no_blocked_transfers(self):
        """Acceptance: the chunked-prefill launch loop is pure device work
        (no host fetch between chunks), so a sweep under the strict-mode
        transfer guard holds ``blocked_transfers == 0``."""
        from llm_interpretation_replication_tpu.runtime import strict

        eng, _, tok = _tiny_engine(batch_size=4)
        chunked = _clone_engine(eng, tok, prefill_chunk=16, kv_dtype="int8")
        prompts = [f"Does rule {i} apply to boats?" for i in range(8)]
        strict.activate()
        try:
            snap = telemetry.counters()
            rows = chunked.score_prompts(prompts)
            delta = telemetry.counters_since(snap)
            assert delta.get(strict.BLOCKED_COUNTER, 0) == 0
            assert delta.get("prefill_chunks", 0) >= 2
            assert len(rows) == 8 and all(r["success"] for r in rows)
        finally:
            strict.deactivate()


# ---------------------------------------------------------------------------
# Budget-model anchor regression (runtime/plan.py — satellite b)
# ---------------------------------------------------------------------------

def _falcon7b():
    return DecoderConfig(
        vocab_size=65024, hidden_size=4544, num_layers=32, num_heads=71,
        num_kv_heads=1, intermediate_size=18176, parallel_residual=True,
        shared_layernorm=True, qkv_bias=False, out_bias=False,
        mlp_bias=False, position_embedding="rotary",
        tie_word_embeddings=True, max_position_embeddings=2048,
    )


class TestBudgetModelAnchors:
    """The documented v5e anchor points, pinned so estimator changes can't
    silently drift the operating point (each line is a measured fact from
    BASELINE/PARITY rounds 3-5 or the ISSUE-5 target)."""

    def test_w8a8_headline_fits(self):
        from llm_interpretation_replication_tpu.runtime import (
            resolve_scoring_plan,
        )

        p = resolve_scoring_plan(_falcon7b(), "int8", 192, 432)
        assert p.fits_dense and p.attention_impl == "xla" and p.batch == 192

    def test_bf16_flash_64_fits_128_ooms(self):
        from llm_interpretation_replication_tpu.runtime import (
            resolve_scoring_plan,
        )

        p64 = resolve_scoring_plan(_falcon7b(), "none", 64, 432)
        assert not p64.fits_dense and p64.attention_impl == "flash"
        assert p64.batch == 64
        p128 = resolve_scoring_plan(_falcon7b(), "none", 128, 432)
        assert p128.attention_impl == "flash" and p128.batch == 64

    def test_full_study_224_boundary_bf16(self):
        from llm_interpretation_replication_tpu.runtime.plan import (
            resolve_full_sweep_plan,
        )

        f7 = _falcon7b()
        for req in (256, 240):
            assert resolve_full_sweep_plan(
                f7, "int8", req, 256, pipeline_depth=2).batch == 224
        assert resolve_full_sweep_plan(
            f7, "int8", 224, 256, pipeline_depth=2).batch == 224
        assert resolve_full_sweep_plan(
            f7, "int8", 192, 256, pipeline_depth=2).batch == 192

    def test_int8_kv_plus_chunked_prefill_fits_at_320(self):
        """THE ISSUE-5 acceptance anchor: kv-dtype-aware cache bytes + the
        chunked-prefill activation bound predict a full-study fit at
        batch >= 320 — each lever alone lands at 288, only both together
        clear the 320 point."""
        from llm_interpretation_replication_tpu.runtime.plan import (
            resolve_full_sweep_plan,
        )

        f7 = _falcon7b()
        both = resolve_full_sweep_plan(f7, "int8", 320, 256,
                                       pipeline_depth=2, kv_dtype="int8",
                                       prefill_chunk=128)
        assert both.batch == 320
        assert "int8" in both.reason
        assert resolve_full_sweep_plan(
            f7, "int8", 384, 256, pipeline_depth=2, kv_dtype="int8",
            prefill_chunk=128).batch >= 320
        only_kv = resolve_full_sweep_plan(f7, "int8", 320, 256,
                                          pipeline_depth=2,
                                          kv_dtype="int8")
        assert only_kv.batch == 288
        only_chunk = resolve_full_sweep_plan(f7, "int8", 320, 256,
                                             pipeline_depth=2,
                                             prefill_chunk=128)
        assert only_chunk.batch == 288

    def test_kv_cache_bytes_dtype_aware(self):
        from llm_interpretation_replication_tpu.runtime.plan import (
            kv_cache_bytes,
        )

        f7 = _falcon7b()
        bf16 = kv_cache_bytes(f7, 320, 256, "bf16")
        int8 = kv_cache_bytes(f7, 320, 256, "int8")
        # 1 B codes + 4 B per-head scale over head_dim 64 -> 1.0625 B/elem
        assert int8 / bf16 == pytest.approx((1 + 4 / 64) / 2)
        with pytest.raises(ValueError):
            kv_cache_bytes(f7, 1, 1, "fp8")

    def test_pool_len_menu_quantization(self):
        """The pool-length menus live in plan.py (the engine aliases
        them) so the budget model prices the exact quantized shapes the
        engine pools.  The binary pool keeps the coarse r4 menu (one key
        coalesces 257-512-token buckets — finer entries would fragment
        its flushes); the confidence pool's 320/384 entries keep the
        fused leg's prefix+suffix cache lengths off the 512 entry."""
        from llm_interpretation_replication_tpu.runtime import engine as em
        from llm_interpretation_replication_tpu.runtime.plan import (
            conf_pool_len_for,
            pool_len_for,
        )

        assert em._pool_len is pool_len_for
        assert em._conf_pool_len is conf_pool_len_for
        # binary: unchanged r4 quantization
        assert [pool_len_for(x) for x in (64, 256, 272, 432)] \
            == [256, 256, 512, 512]
        # confidence: finer, for the every-row pool
        assert [conf_pool_len_for(x) for x in (64, 256, 272, 320, 384,
                                               432)] \
            == [256, 256, 320, 320, 384, 512]

    def test_pooled_confidence_cache_term_anchor(self):
        """Satellite (ISSUE 7): the pooled-confidence cache term is
        PINNED so the estimator can't drift (the PR-5 anchor-pin
        pattern): 2x (source slices + flush concat) of target rows at
        pool_len(seq + suffix) + score_steps slots, dtype-aware."""
        from llm_interpretation_replication_tpu.runtime.plan import (
            pooled_confidence_extra_bytes,
        )

        f7 = _falcon7b()
        # 320 rows, 256-token sweep bucket -> pool len 320 (+64 suffix),
        # +10 decode slots: exact byte pins, bf16 and int8
        assert pooled_confidence_extra_bytes(f7, 320, 256) == 1730150400
        assert pooled_confidence_extra_bytes(
            f7, 320, 256, kv_dtype="int8") == 919142400
        with pytest.raises(ValueError):
            pooled_confidence_extra_bytes(f7, 320, 256, kv_dtype="fp8")

    def test_full_study_fit_survives_the_pooled_confidence_term(self):
        """THE ISSUE-7 planner acceptance: with the pooled-confidence
        pool budgeted on top of the completion caches, the int8-KV +
        chunk-128 full-study prediction still lands at batch >= 320, and
        the fit-decision string names the pool so BENCH_r06 is
        self-describing."""
        from llm_interpretation_replication_tpu.runtime.plan import (
            resolve_full_sweep_plan,
        )

        f7 = _falcon7b()
        p = resolve_full_sweep_plan(f7, "int8", 320, 256, pipeline_depth=2,
                                    kv_dtype="int8", prefill_chunk=128,
                                    pooled_confidence=True)
        assert p.batch == 320
        assert "pooled-conf pool" in p.reason
        # bf16 KV cannot carry the pool at sweep batches — the planner
        # says so instead of OOMing on hardware
        bf = resolve_full_sweep_plan(f7, "int8", 320, 256, pipeline_depth=2,
                                     pooled_confidence=True)
        assert bf.batch == 192
        assert "pooled-conf pool" in bf.reason


# ---------------------------------------------------------------------------
# Serve replay parity with chunked prefill (bf16 contract untouched)
# ---------------------------------------------------------------------------

class TestServeReplayChunked:
    def test_replay_rows_identical_under_chunked_prefill(self):
        """The serve scheduler coalesces requests back onto the engine's
        own bucketed shapes; with chunked prefill on (bf16 KV) replay
        parity must stay row-identical — require_parity raises on skew."""
        from llm_interpretation_replication_tpu.serve.replay import replay

        eng, _, tok = _tiny_engine(batch_size=4)
        chunked = _clone_engine(eng, tok, prefill_chunk=16)
        prompts = [f"Is gadget {i} an appliance?" for i in range(6)]
        report = replay(chunked, prompts)   # raises ServeError on mismatch
        assert report["mismatched_rows"] == 0
        assert report["serve_rows_per_s"] > 0
