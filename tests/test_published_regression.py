"""Golden regression vs the paper's published Table 3/4 numbers (SURVEY.md §6).

Feeds the REFERENCE's real result artifacts (its finished 100-question
closed-source evaluation CSV + the raw survey exports) through THIS
framework's statistics pipeline and requires the paper's numbers back:
MAE, bootstrap CIs, baseline differences, significance calls, correlations.
This pins the whole downstream stack — question matching, error definition,
bootstrap seeds, baselines — to the published results.
"""

import json
import os

import numpy as np
import pandas as pd
import pytest

REF = "/root/reference"
RESULTS_CSV = f"{REF}/results/closed_source_evaluation/closed_source_evaluation_results.csv"
COMPARISONS_JSON = f"{REF}/results/closed_source_evaluation/human_comparisons.json"
SURVEY1 = f"{REF}/data/word_meaning_survey_results.csv"
SURVEY2 = f"{REF}/data/word_meaning_survey_results_part_2.csv"

pytestmark = pytest.mark.skipif(
    not os.path.exists(RESULTS_CSV), reason="reference artifacts not mounted"
)


@pytest.fixture(scope="module")
def comparison():
    from llm_interpretation_replication_tpu.analysis.closed_source_eval import (
        compare_with_human_data,
    )
    from llm_interpretation_replication_tpu.analysis.questions import (
        load_human_survey_means,
    )

    df = pd.read_csv(RESULTS_CSV)
    human_means = load_human_survey_means(SURVEY1, SURVEY2)
    human_std = float(np.std(list(human_means.values())))
    return compare_with_human_data(df, human_means, human_std=human_std,
                                   n_bootstrap=10_000, seed=42)


@pytest.fixture(scope="module")
def reference():
    with open(COMPARISONS_JSON) as f:
        return json.load(f)


def test_human_survey_stats_match_reference_exactly():
    from llm_interpretation_replication_tpu.analysis.questions import (
        load_human_survey_means,
    )

    means, full = load_human_survey_means(SURVEY1, SURVEY2, return_full=True)
    with open(COMPARISONS_JSON) as f:
        ref = json.load(f)["human_statistics"]
    vals = np.array(list(means.values()))
    assert len(means) == 101
    assert float(vals.mean()) == pytest.approx(ref["overall_mean"], abs=1e-12)
    assert float(vals.std()) == pytest.approx(ref["overall_std"], abs=1e-12)
    assert sum(len(v) for v in full.values()) == ref["total_responses"]


def test_table3_mae_per_model(comparison, reference):
    """Paper Table 3 (main.tex:375-395): GPT-4.1 0.197, Claude 0.229,
    Gemini 0.241 — exact to the reference's recorded MAE (deterministic
    given identical question matching and error definition)."""
    for ours, theirs in (("GPT", "gpt"), ("Gemini", "gemini"), ("Claude", "claude")):
        got = comparison["mae"][ours]
        want = reference["models"][theirs]
        assert got["n"] == want["n_matched"] == 100
        assert got["mae"] == pytest.approx(want["mae"], abs=1e-9), ours
    # paper-rounded values
    assert round(comparison["mae"]["GPT"]["mae"], 3) == 0.197
    assert round(comparison["mae"]["Claude"]["mae"], 3) == 0.229
    assert round(comparison["mae"]["Gemini"]["mae"], 3) == 0.241


def test_table3_per_question_errors(comparison, reference):
    """Per-question |model - human| vectors match the artifact elementwise
    (order-independent: compared as sorted multisets)."""
    for ours, theirs in (("GPT", "gpt"), ("Gemini", "gemini"), ("Claude", "claude")):
        got = np.sort(np.asarray(comparison["errors"][ours]))
        want = np.sort(np.asarray(reference["models"][theirs]["mae_values"]))
        np.testing.assert_allclose(got, want, atol=1e-9)


def test_table3_baselines(comparison, reference):
    """Equanimity (always-50) and the N(mu,sigma) baseline — whose draws
    replay the reference's legacy np.random.seed(43) stream — are both
    bit-exact.  Paper values 0.175 and 0.172."""
    eq = comparison["mae"]["Equanimity"]
    want_eq = reference["baselines"]["always_50"]
    assert eq["mae"] == pytest.approx(want_eq["mae"], abs=1e-12)
    assert eq["ci_lower"] == pytest.approx(want_eq["mae_ci_lower"], abs=1e-12)
    assert eq["ci_upper"] == pytest.approx(want_eq["mae_ci_upper"], abs=1e-12)
    normal = comparison["mae"]["Normal"]
    want_n = reference["baselines"]["normal_human"]
    assert normal["mae"] == pytest.approx(want_n["mae"], abs=1e-12)
    assert normal["ci_lower"] == pytest.approx(want_n["mae_ci_lower"], abs=1e-12)
    assert round(eq["mae"], 3) == 0.175 and round(normal["mae"], 3) == 0.172


def test_table3_bootstrap_cis(comparison, reference):
    """10k-resample MAE CIs are bit-exact: same scipy bootstrap, same
    default_rng(42) stream."""
    for ours, theirs in (("GPT", "gpt"), ("Gemini", "gemini"), ("Claude", "claude")):
        got = comparison["mae"][ours]
        want = reference["models"][theirs]
        assert got["ci_lower"] == pytest.approx(want["mae_ci_lower"], abs=1e-12)
        assert got["ci_upper"] == pytest.approx(want["mae_ci_upper"], abs=1e-12)


def test_table4_differences_and_significance(comparison, reference):
    """Paper Table 4 (main.tex:396-417): MAE differences vs BOTH baselines,
    their bootstrap CIs, and the two-sided p-values are bit-exact (identical
    resampling algorithm and default_rng(42) stream), reproducing the
    significance calls — GPT ns, Claude **, Gemini ***."""
    for ours, theirs, sig in (("GPT", "gpt", "ns"), ("Claude", "claude", "**"),
                              ("Gemini", "gemini", "***")):
        for base_key, want_key in (("Equanimity", "vs_always_50"),
                                   ("Normal", "vs_normal_human")):
            got = comparison["differences"][ours][base_key]
            want = reference["models"][theirs][want_key]
            assert got["diff"] == pytest.approx(want["mae_diff"], abs=1e-12)
            assert got["ci_lower"] == pytest.approx(want["mae_diff_ci_lower"], abs=1e-12)
            assert got["ci_upper"] == pytest.approx(want["mae_diff_ci_upper"], abs=1e-12)
            assert got["p_value"] == pytest.approx(want["p_value"], abs=1e-12)
        p = comparison["differences"][ours]["Equanimity"]["p_value"]
        if sig == "ns":
            assert p > 0.05
        elif sig == "**":
            assert p < 0.05
        else:
            assert p < 0.01


def test_correlations_vs_humans(comparison, reference):
    """Pearson correlation of each model's predictions with the human means
    (deterministic): GPT 0.665, Gemini 0.591, Claude 0.530."""
    for ours, theirs in (("GPT", "gpt"), ("Gemini", "gemini"), ("Claude", "claude")):
        got = comparison["mae"][ours]
        want = reference["models"][theirs]
        assert got["correlation"] == pytest.approx(want["correlation"], abs=1e-9)
        assert got["p_value"] == pytest.approx(want["p_value"], rel=1e-6)
