"""Golden regression vs the paper's published Table 3/4 numbers (SURVEY.md §6).

Feeds the REFERENCE's real result artifacts (its finished 100-question
closed-source evaluation CSV + the raw survey exports) through THIS
framework's statistics pipeline and requires the paper's numbers back:
MAE, bootstrap CIs, baseline differences, significance calls, correlations.
This pins the whole downstream stack — question matching, error definition,
bootstrap seeds, baselines — to the published results.
"""

import json
import os

import numpy as np
import pandas as pd
import pytest

REF = "/root/reference"
RESULTS_CSV = f"{REF}/results/closed_source_evaluation/closed_source_evaluation_results.csv"
COMPARISONS_JSON = f"{REF}/results/closed_source_evaluation/human_comparisons.json"
SURVEY1 = f"{REF}/data/word_meaning_survey_results.csv"
SURVEY2 = f"{REF}/data/word_meaning_survey_results_part_2.csv"

pytestmark = pytest.mark.skipif(
    not os.path.exists(RESULTS_CSV), reason="reference artifacts not mounted"
)


@pytest.fixture(scope="module")
def comparison():
    from llm_interpretation_replication_tpu.analysis.closed_source_eval import (
        compare_with_human_data,
    )
    from llm_interpretation_replication_tpu.analysis.questions import (
        load_human_survey_means,
    )

    df = pd.read_csv(RESULTS_CSV)
    human_means = load_human_survey_means(SURVEY1, SURVEY2)
    human_std = float(np.std(list(human_means.values())))
    return compare_with_human_data(df, human_means, human_std=human_std,
                                   n_bootstrap=10_000, seed=42)


@pytest.fixture(scope="module")
def reference():
    with open(COMPARISONS_JSON) as f:
        return json.load(f)


def test_human_survey_stats_match_reference_exactly():
    from llm_interpretation_replication_tpu.analysis.questions import (
        load_human_survey_means,
    )

    means, full = load_human_survey_means(SURVEY1, SURVEY2, return_full=True)
    with open(COMPARISONS_JSON) as f:
        ref = json.load(f)["human_statistics"]
    vals = np.array(list(means.values()))
    assert len(means) == 101
    assert float(vals.mean()) == pytest.approx(ref["overall_mean"], abs=1e-12)
    assert float(vals.std()) == pytest.approx(ref["overall_std"], abs=1e-12)
    assert sum(len(v) for v in full.values()) == ref["total_responses"]


def test_table3_mae_per_model(comparison, reference):
    """Paper Table 3 (main.tex:375-395): GPT-4.1 0.197, Claude 0.229,
    Gemini 0.241 — exact to the reference's recorded MAE (deterministic
    given identical question matching and error definition)."""
    for ours, theirs in (("GPT", "gpt"), ("Gemini", "gemini"), ("Claude", "claude")):
        got = comparison["mae"][ours]
        want = reference["models"][theirs]
        assert got["n"] == want["n_matched"] == 100
        assert got["mae"] == pytest.approx(want["mae"], abs=1e-9), ours
    # paper-rounded values
    assert round(comparison["mae"]["GPT"]["mae"], 3) == 0.197
    assert round(comparison["mae"]["Claude"]["mae"], 3) == 0.229
    assert round(comparison["mae"]["Gemini"]["mae"], 3) == 0.241


def test_table3_per_question_errors(comparison, reference):
    """Per-question |model - human| vectors match the artifact elementwise
    (order-independent: compared as sorted multisets)."""
    for ours, theirs in (("GPT", "gpt"), ("Gemini", "gemini"), ("Claude", "claude")):
        got = np.sort(np.asarray(comparison["errors"][ours]))
        want = np.sort(np.asarray(reference["models"][theirs]["mae_values"]))
        np.testing.assert_allclose(got, want, atol=1e-9)


def test_table3_baselines(comparison, reference):
    """Equanimity (always-50) and the N(mu,sigma) baseline — whose draws
    replay the reference's legacy np.random.seed(43) stream — are both
    bit-exact.  Paper values 0.175 and 0.172."""
    eq = comparison["mae"]["Equanimity"]
    want_eq = reference["baselines"]["always_50"]
    assert eq["mae"] == pytest.approx(want_eq["mae"], abs=1e-12)
    assert eq["ci_lower"] == pytest.approx(want_eq["mae_ci_lower"], abs=1e-12)
    assert eq["ci_upper"] == pytest.approx(want_eq["mae_ci_upper"], abs=1e-12)
    normal = comparison["mae"]["Normal"]
    want_n = reference["baselines"]["normal_human"]
    assert normal["mae"] == pytest.approx(want_n["mae"], abs=1e-12)
    assert normal["ci_lower"] == pytest.approx(want_n["mae_ci_lower"], abs=1e-12)
    assert round(eq["mae"], 3) == 0.175 and round(normal["mae"], 3) == 0.172


def test_table3_bootstrap_cis(comparison, reference):
    """10k-resample MAE CIs are bit-exact: same scipy bootstrap, same
    default_rng(42) stream."""
    for ours, theirs in (("GPT", "gpt"), ("Gemini", "gemini"), ("Claude", "claude")):
        got = comparison["mae"][ours]
        want = reference["models"][theirs]
        assert got["ci_lower"] == pytest.approx(want["mae_ci_lower"], abs=1e-12)
        assert got["ci_upper"] == pytest.approx(want["mae_ci_upper"], abs=1e-12)


def test_table4_differences_and_significance(comparison, reference):
    """Paper Table 4 (main.tex:396-417): MAE differences vs BOTH baselines,
    their bootstrap CIs, and the two-sided p-values are bit-exact (identical
    resampling algorithm and default_rng(42) stream), reproducing the
    significance calls — GPT ns, Claude **, Gemini ***."""
    for ours, theirs, sig in (("GPT", "gpt", "ns"), ("Claude", "claude", "**"),
                              ("Gemini", "gemini", "***")):
        for base_key, want_key in (("Equanimity", "vs_always_50"),
                                   ("Normal", "vs_normal_human")):
            got = comparison["differences"][ours][base_key]
            want = reference["models"][theirs][want_key]
            assert got["diff"] == pytest.approx(want["mae_diff"], abs=1e-12)
            assert got["ci_lower"] == pytest.approx(want["mae_diff_ci_lower"], abs=1e-12)
            assert got["ci_upper"] == pytest.approx(want["mae_diff_ci_upper"], abs=1e-12)
            assert got["p_value"] == pytest.approx(want["p_value"], abs=1e-12)
        p = comparison["differences"][ours]["Equanimity"]["p_value"]
        if sig == "ns":
            assert p > 0.05
        elif sig == "**":
            assert p < 0.05
        else:
            assert p < 0.01


def test_correlations_vs_humans(comparison, reference):
    """Pearson correlation of each model's predictions with the human means
    (deterministic): GPT 0.665, Gemini 0.591, Claude 0.530."""
    for ours, theirs in (("GPT", "gpt"), ("Gemini", "gemini"), ("Claude", "claude")):
        got = comparison["mae"][ours]
        want = reference["models"][theirs]
        assert got["correlation"] == pytest.approx(want["correlation"], abs=1e-9)
        assert got["p_value"] == pytest.approx(want["p_value"], rel=1e-6)


# ---------------------------------------------------------------------------
# Perturbation-study regression (paper Appendix B; SURVEY.md §6 row 3) —
# the reference's REAL Claude/Gemini 10k-perturbation workbooks through our
# dependency-free xlsx reader + statistics engine vs its recorded analysis
# CSVs (results/{claude,gemini}_analysis/*.csv).
# ---------------------------------------------------------------------------

PERTURBATIONS_JSON = f"{REF}/data/perturbations.json"
WORKBOOKS = {
    "claude": f"{REF}/results/claude_opus_batch_perturbation_results.xlsx",
    "gemini": f"{REF}/results/gemini_perturbation_results.xlsx",
}


@pytest.mark.parametrize("model,paper_width", [("claude", 72.8), ("gemini", 78.0)])
def test_perturbation_confidence_stats_match_recorded_analysis(model, paper_width):
    """Per-scenario confidence statistics (mean/std/extremes/percentiles/CI
    width/favor counts) and KS/AD normality tests reproduce the reference's
    recorded analysis to float precision; scenario numbering follows
    perturbations.json order (the analyzers' convention).  The mean 95%
    interval width rounds to the paper's Appendix B value (Claude 72.8,
    Gemini 78.0)."""
    if not (os.path.exists(WORKBOOKS[model]) and os.path.exists(PERTURBATIONS_JSON)):
        pytest.skip("perturbation artifacts not mounted")
    from llm_interpretation_replication_tpu.stats.normality import normality_tests
    from llm_interpretation_replication_tpu.utils.xlsx import read_xlsx

    df = read_xlsx(WORKBOOKS[model])
    summary = pd.read_csv(f"{REF}/results/{model}_analysis/summary_statistics.csv")
    normality = pd.read_csv(f"{REF}/results/{model}_analysis/normality_tests.csv")
    scenarios = json.load(open(PERTURBATIONS_JSON))
    widths = []
    for i, scenario in enumerate(scenarios):
        sub = df[df["Original Main Part"] == scenario["original_main"]]
        assert len(sub), f"scenario {i + 1} missing from workbook"
        vals = pd.to_numeric(sub["Confidence Value"], errors="coerce").dropna()
        row = summary[summary["Prompt Number"] == i + 1].iloc[0]
        assert int(len(vals)) == int(row["Sample Size"])
        assert float(vals.mean()) == pytest.approx(row["Mean Confidence"], abs=1e-9)
        assert float(vals.std()) == pytest.approx(row["Std Dev"], abs=1e-9)
        assert float(vals.min()) == pytest.approx(row["Min"], abs=1e-9)
        assert float(vals.max()) == pytest.approx(row["Max"], abs=1e-9)
        p_lo, p_hi = np.percentile(vals, [2.5, 97.5])
        assert p_lo == pytest.approx(row["2.5th Percentile"], abs=1e-9)
        assert p_hi == pytest.approx(row["97.5th Percentile"], abs=1e-9)
        width = p_hi - p_lo
        assert width == pytest.approx(row["95% Interval Width"], abs=1e-9)
        widths.append(width)
        assert int((vals > 50).sum()) == int(row["Favors First Token (>50)"])
        assert int((vals < 50).sum()) == int(row["Favors Second Token (<50)"])
        assert int((vals == 50).sum()) == int(row["Neutral (=50)"])

        nrow = normality[normality["Prompt"] == i + 1].iloc[0]
        nt = normality_tests(vals.to_numpy())
        assert nt["mean"] == pytest.approx(nrow["Distribution Mean"], abs=1e-9)
        assert nt["std"] == pytest.approx(nrow["Distribution Std Dev"], abs=1e-9)
        assert nt["ks_stat"] == pytest.approx(nrow["KS Statistic"], abs=1e-9)
        assert nt["ks_p"] == pytest.approx(nrow["KS p-value"], rel=1e-6, abs=1e-200)
        assert nt["ad_stat"] == pytest.approx(nrow["AD Statistic"], abs=1e-9)
        # Dual-pin of the AD critical value (PARITY.md §6): the recorded
        # analysis came from a legacy-table scipy; the installed scipy may
        # use the revised 1.17 table.  Detect the active era empirically and
        # compare each side BIT-EXACTLY against its matching table — no
        # loose tolerance.  An unknown era (future scipy revision) fails
        # loudly so the new table gets added to AD_NORM_TABLES.
        from llm_interpretation_replication_tpu.stats.normality import (
            active_ad_table_version,
            ad_critical_values,
            ad_pvalue_from_bands,
        )

        version = active_ad_table_version()
        assert version in ("legacy", "scipy117"), version
        n = len(vals)
        legacy_crit = ad_critical_values(n, "legacy")
        active_crit = ad_critical_values(n, version)
        assert nrow["AD Critical Value (5%)"] == legacy_crit[2]
        assert nt["ad_crit_5pct"] == active_crit[2]
        # the recorded banded p-value re-derives exactly from the legacy
        # table; ours from the active table
        assert nrow["AD p-value"] == ad_pvalue_from_bands(
            nrow["AD Statistic"], legacy_crit)
        assert nt["ad_p"] == ad_pvalue_from_bands(nt["ad_stat"], active_crit)
        assert nt["ks_normal"] == bool(nrow["KS Normal (p>0.05)"])
        assert (nt["ad_stat"] < nrow["AD Critical Value (5%)"]) == bool(
            nrow["AD Normal (stat<crit)"])
        assert nt["ad_normal"] == bool(nt["ad_stat"] < active_crit[2])
    assert round(float(np.mean(widths)), 1) == paper_width


def test_similarity_metrics_match_recorded_workbook():
    """Rephrasing-similarity validation (calculate_prompt_similarity.py) —
    our in-package TF-IDF, rank_bm25-clone BM25, and native-C Levenshtein
    reproduce the reference's recorded similarity workbook bit-exactly.
    TF-IDF/BM25 are corpus-dependent, so the comparison runs at full corpus
    (original + 2000 rephrasings); BM25 checks a 100-row slice of the
    symmetrized row to keep the O(n^2) matrix out of the test."""
    if not os.path.exists(f"{REF}/results/prompt_similarity/original_vs_rephrasings_similarity.xlsx"):
        pytest.skip("similarity workbook not mounted")
    from llm_interpretation_replication_tpu.stats import similarity as sim
    from llm_interpretation_replication_tpu.utils.xlsx import read_xlsx

    wb = read_xlsx(f"{REF}/results/prompt_similarity/original_vs_rephrasings_similarity.xlsx")
    sub = wb[wb["prompt_index"] == 0]
    texts = [sub["original_main"].iloc[0]] + sub["rephrasing"].tolist()

    tfidf = sim.tfidf_cosine_matrix(texts)[0, 1:]
    np.testing.assert_allclose(
        tfidf, sub["tfidf_cosine_similarity"].to_numpy(), atol=1e-12
    )

    tok = [t.lower().split() for t in texts]
    bm = sim.BM25Okapi(tok)

    def norm_row(j):
        s = bm.get_scores(tok[j])
        return s / (s.max() if s.max() > 0 else 1.0)

    row0 = norm_row(0)
    k = 100
    ours = np.array([(row0[j] + norm_row(j)[0]) / 2 for j in range(1, k + 1)])
    np.testing.assert_allclose(
        ours, sub["bm25_similarity"].to_numpy()[:k], atol=1e-12
    )

    lev = np.array([
        sim.normalized_levenshtein_similarity(texts[0], t) for t in texts[1:k + 1]
    ])
    np.testing.assert_allclose(
        lev, sub["levenshtein_similarity"].to_numpy()[:k], atol=1e-12
    )


def test_appendix_inter_model_correlations():
    """Online-appendix inter-LLM correlation table (main_online_appendix.tex:
    517-533): mean rho 0.051, median 0.045, sigma 0.220 over the 28
    non-degenerate model pairs of the word-meaning sweep CSV (models with
    all-NaN overlap drop out of the 45 raw pairs).  Point statistics are
    deterministic; bootstrap CIs agree with the published intervals to
    resampling noise."""
    if not os.path.exists(f"{REF}/data/instruct_model_comparison_results.csv"):
        pytest.skip("instruct sweep CSV not mounted")
    from llm_interpretation_replication_tpu.stats.correlations import (
        correlation_summary_bootstrap,
        pairwise_correlations,
        pivot_model_values,
    )

    df = pd.read_csv(f"{REF}/data/instruct_model_comparison_results.csv")
    pivot = pivot_model_values(df)
    pairs = pairwise_correlations(pivot)
    r = pairs["pearson_r"].dropna()
    assert len(r) == 28
    assert round(float(r.mean()), 3) == 0.051
    assert round(float(np.median(r)), 3) == 0.045
    assert round(float(np.std(r)), 3) == 0.220
    summary = correlation_summary_bootstrap(pivot, n_bootstrap=1000, seed=42)
    assert summary["n_pairs"] == 28
    lo, hi = summary["mean_ci"]
    assert lo == pytest.approx(-0.015, abs=0.01) and hi == pytest.approx(0.126, abs=0.01)


def test_irrelevant_perturbation_summary_matches_recorded():
    """Irrelevant-insertion study (paper Appendix C): the reference's raw
    results workbook through our consistency_statistics reproduces every
    recorded row of its summary.csv (consistency, pooled and perturbed-only
    confidence stats, CIs, sample counts) to float precision."""
    from llm_interpretation_replication_tpu.analysis.irrelevant_eval import (
        consistency_statistics,
    )
    from llm_interpretation_replication_tpu.utils.xlsx import read_xlsx

    wb_path = f"{REF}/results/irrelevant_perturbations/results_analysis.xlsx"
    if not os.path.exists(wb_path):
        pytest.skip("irrelevant-perturbation workbook not mounted")
    wb = read_xlsx(wb_path)
    ref = pd.read_csv(f"{REF}/results/irrelevant_perturbations/summary.csv")
    is_orig = wb["is_original"].astype(str).str.lower().isin(("true", "1", "1.0"))
    frame = pd.DataFrame({
        "model": wb["model"],
        "scenario_name": wb["scenario"],
        "perturbation_id": np.where(is_orig, "original", wb["perturbation_id"]),
        "response": wb["response"],
        "confidence": wb["confidence"],
    })
    stats = consistency_statistics(frame)
    assert len(stats) == len(ref)
    merged = 0
    for _, want in ref.iterrows():
        got = stats[(stats["model"] == want["model"])
                    & (stats["scenario_name"] == want["scenario"])].iloc[0]
        for ours, theirs in (
            ("consistency", "consistency"),
            ("original_confidence", "original_confidence"),
            ("mean_all_confidence", "mean_all_confidence"),
            ("std_all_confidence", "std_all_confidence"),
            ("median_all_confidence", "median_all_confidence"),
            ("ci_lower_95", "ci_lower_95"),
            ("ci_upper_95", "ci_upper_95"),
            ("mean_perturbed_confidence", "mean_perturbed_confidence"),
            ("std_perturbed_confidence", "std_perturbed_confidence"),
        ):
            assert got[ours] == pytest.approx(want[theirs], abs=1e-9), (
                want["scenario"], want["model"], ours)
        assert int(got["n_samples"]) == int(want["n_samples"])
        assert int(got["num_perturbations"]) == int(want["num_perturbations"])
        assert got["original_response"] == want["original_response"]
        merged += 1
    assert merged == len(ref) == 15          # 5 scenarios x 3 models


def test_combined_analysis_per_prompt_stats():
    """Three-model combiner (combine_model_confidence_analysis.py) vs the
    recorded combined_analysis/per_prompt_statistics.csv: per-prompt mean and
    (ddof=1) std for the two models whose raw workbooks survive in the mount
    (Claude Opus 4, Gemini 2.0) match to float precision.  The GPT-4.1
    workbook was stripped (.MISSING_LARGE_BLOBS) so its column is untestable."""
    from llm_interpretation_replication_tpu.analysis.combined_confidence import (
        ModelConfidenceAnalyzer,
    )
    from llm_interpretation_replication_tpu.utils.xlsx import read_xlsx

    per_prompt = f"{REF}/results/combined_analysis/per_prompt_statistics.csv"
    if not os.path.exists(per_prompt):
        pytest.skip("combined-analysis artifacts not mounted")
    # default constructor args = the production path (the reference combiner
    # reads 'Confidence Value' unconditionally)
    analyzer = ModelConfidenceAnalyzer({
        "Claude Opus 4": read_xlsx(f"{REF}/results/claude_opus_batch_perturbation_results.xlsx"),
        "Gemini 2.0": read_xlsx(f"{REF}/results/gemini_perturbation_results.xlsx"),
    })
    stats = analyzer.summary_stats()
    ref = pd.read_csv(per_prompt)
    checked = 0
    for _, want in ref.iterrows():
        prefix = str(want["Original Prompt"])[:40]
        for model in ("Claude Opus 4", "Gemini 2.0"):
            got = stats[stats["scenario"].astype(str).str.startswith(prefix)
                        & (stats["model"] == model)]
            assert len(got) == 1
            assert got["mean"].iloc[0] == pytest.approx(want[f"{model} Mean"], abs=1e-9)
            assert got["std"].iloc[0] == pytest.approx(want[f"{model} Std"], abs=1e-9)
            checked += 1
    assert checked == 10          # 5 prompts x 2 surviving models


# ---------------------------------------------------------------------------
# verify-replication: the one-command parity harness (round-4 verdict item 3)
# ---------------------------------------------------------------------------

class TestVerifyReplication:
    def test_verdict_logic(self):
        from llm_interpretation_replication_tpu.analysis.replication import (
            _check,
            significance_category,
        )

        # point inside published CI
        assert _check("t", "m", 0.2, (0.1, 0.3), 0.25)["verdict"] == "PASS"
        # CIs overlap even though points differ
        assert _check("t", "m", 0.2, (0.1, 0.3), 0.35,
                      (0.28, 0.4))["verdict"] == "PASS"
        # disjoint CIs fail
        assert _check("t", "m", 0.2, (0.1, 0.3), 0.5,
                      (0.4, 0.6))["verdict"] == "FAIL"
        # point-only targets need printed-precision equality
        assert _check("t", "m", 0.051, None, 0.0512)["verdict"] == "PASS"
        assert _check("t", "m", 0.051, None, 0.057)["verdict"] == "FAIL"
        # missing value fails
        assert _check("t", "m", 0.2, (0.1, 0.3), None)["verdict"] == "FAIL"
        # stars follow the PRINTED p (Claude vs Equanimity: p=0.0098 -> 0.010)
        assert significance_category(0.0098) == "**"
        assert significance_category(0.0022) == "***"
        assert significance_category(0.2416) == "ns"
        assert significance_category(0.07) == "*"

    def test_all_pass_on_reference_artifacts(self):
        """The full verifier on the reference's recorded artifacts: every
        runnable check PASSES, Table 5 SKIPs (raw reference CSV unpublished
        - .MISSING_LARGE_BLOBS), nothing FAILS."""
        from llm_interpretation_replication_tpu.analysis.replication import (
            format_report,
            verify_replication,
        )

        result = verify_replication(
            reference_root=REF, n_bootstrap=10_000,
            cross_prompt_bootstrap=100,
        )
        assert result["ok"], format_report(result)
        assert result["n_fail"] == 0
        assert result["n_skip"] == 3          # the three Table-5 families
        assert result["n_pass"] == 17
        report = format_report(result)
        assert "REPLICATION OK" in report
        assert report.count("[PASS]") == 17

    def test_table5_skip_without_results(self):
        from llm_interpretation_replication_tpu.analysis.replication import (
            check_table5,
        )

        rows = check_table5(None, "s1.csv", "s2.csv")
        assert [r["verdict"] for r in rows] == ["SKIP"] * 3
        assert all("snapshots" in r["detail"] for r in rows)

    def test_table5_pass_path_on_engineered_sweep(self, tmp_path):
        """PASS path for the Table-5 check: a synthetic run-100q results CSV
        whose per-question error distributions are ENGINEERED to land every
        family's base/instruct MAE, paired-bootstrap diff CI, and printed
        significance category inside the published Table 5 values
        (main.tex:432-446) — Falcon +0.135*** (constant positive diffs),
        StableLM -0.030 ns (small mean, wide spread), RedPajama +0.122*
        (borderline p via 16 high-mean questions with +/-0.268 spread).
        Exercises the verdict logic end-to-end on data shaped like a real
        sweep output, which the reference never published."""
        from llm_interpretation_replication_tpu.analysis.replication import (
            check_table5,
        )
        from llm_interpretation_replication_tpu.survey import (
            apply_exclusion_criteria,
            human_responses_by_question,
            load_and_clean_survey_data,
        )
        from llm_interpretation_replication_tpu.survey.pipeline import (
            extract_question_text,
        )

        df, cols = load_and_clean_survey_data([SURVEY1, SURVEY2])
        df, _ = apply_exclusion_criteria(df, cols)
        human = human_responses_by_question(df, cols)
        texts = extract_question_text([SURVEY1, SURVEY2])
        means = {c: human[c]["mean"] / 100.0 for c in human}
        ordered = sorted(means, key=lambda c: means[c])

        def rel(h, err):
            # place the prediction err away from the human mean, inside [0,1]
            return h + err if h + err <= 1.0 else h - err

        rows = []

        def add(model, columns, errors):
            for col, err in zip(columns, errors):
                r = rel(means[col], err)
                assert 0.0 <= r <= 1.0, (model, col, means[col], err)
                rows.append({"prompt": texts[col], "model": model,
                             "relative_prob": r})

        # Falcon: constant errors -> diff +0.135 exactly, p=0 -> ***
        add("tiiuae/falcon-7b", ordered, [0.333] * len(ordered))
        add("tiiuae/falcon-7b-instruct", ordered, [0.468] * len(ordered))
        # StableLM: 50 questions, instruct errors 0.339 +/- 0.15 -> ns
        fifty = ordered[:50]
        add("stabilityai/stablelm-base-alpha-7b", fifty, [0.369] * 50)
        add("stabilityai/stablelm-tuned-alpha-7b", fifty,
            [0.339 + (0.15 if i % 2 else -0.15) for i in range(50)])
        # RedPajama: 16 highest-mean questions, +/-0.268 spread -> p ~ 0.06 *
        high = [c for c in ordered if means[c] >= 0.75][-16:]
        assert len(high) == 16
        add("togethercomputer/RedPajama-INCITE-7B-Base", high, [0.313] * 16)
        add("togethercomputer/RedPajama-INCITE-7B-Instruct", high,
            [0.437 + (0.268 if i % 2 else -0.268) for i in range(16)])

        csv = tmp_path / "base_vs_instruct_100q_results.csv"
        pd.DataFrame(rows).to_csv(csv, index=False)
        verdicts = check_table5(str(csv), SURVEY1, SURVEY2)
        assert len(verdicts) == 9          # 3 families x (base, instruct, diff)
        for v in verdicts:
            assert v["verdict"] == "PASS", v
