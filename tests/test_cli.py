"""CLI smoke tests (no-model commands run end-to-end; model commands are
covered via the engine-factory path in test_sweeps)."""

import json
import os

import numpy as np
import pandas as pd
import pytest

from llm_interpretation_replication_tpu.__main__ import main
from llm_interpretation_replication_tpu.analysis.questions import (
    extract_survey2_questions,
    load_ordinary_meaning_questions,
)
from llm_interpretation_replication_tpu.utils.profiling import ThroughputMeter


def test_generate_irrelevant_cli(tmp_path, capsys):
    out = str(tmp_path / "perturbations_irrelevant.json")
    main(["generate-irrelevant", "--output", out])
    data = json.load(open(out))
    assert sum(len(s["perturbations_with_irrelevant"]) for s in data) == 3400
    assert "3400 perturbations" in capsys.readouterr().out


def test_analyze_100q_cli(tmp_path, capsys):
    rng = np.random.default_rng(0)
    rows = []
    for i in range(30):
        rows.append({"model_family": "Fam", "base_or_instruct": "base",
                     "prompt": f"q{i}", "relative_prob": rng.uniform(0.2, 0.4)})
        rows.append({"model_family": "Fam", "base_or_instruct": "instruct",
                     "prompt": f"q{i}", "relative_prob": rng.uniform(0.6, 0.8)})
    csv = str(tmp_path / "r.csv")
    pd.DataFrame(rows).to_csv(csv, index=False)
    main(["analyze-100q", "--results", csv])
    out = capsys.readouterr().out
    assert "mean_diff" in out
    # --latex emits paper Table 5, which needs the human survey means; the old
    # survey-less mapping printed NaN MAE columns and is gone
    with pytest.raises(SystemExit, match="analyze-mae-100q"):
        main(["analyze-100q", "--results", csv, "--latex"])


REF_MODEL_COMPARISON = "/root/reference/data/model_comparison_results.csv"
REF_INSTRUCT_COMBINED = (
    "/root/reference/data/instruct_model_comparison_results_combined.csv"
)


@pytest.mark.skipif(not os.path.exists(REF_INSTRUCT_COMBINED),
                    reason="reference not mounted")
def test_model_comparison_cli_writes_artifacts(tmp_path, capsys):
    """model-comparison on the real 8-model sweep reproduces the appendix
    inter-LLM correlation (mean rho = 0.051, main_online_appendix.tex:517-533)
    and writes the reference's artifact set."""
    out = str(tmp_path / "mc")
    main(["model-comparison", "--results", REF_INSTRUCT_COMBINED,
          "--output-dir", out, "--bootstrap", "100"])
    printed = capsys.readouterr().out
    assert "mean correlation 0.051" in printed
    assert os.path.exists(os.path.join(out, "pairwise_correlations.csv"))
    assert os.path.exists(os.path.join(out, "correlation_summary.json"))
    assert os.path.exists(os.path.join(out, "correlation_heatmap.png"))
    assert os.path.exists(os.path.join(out, "correlation_distribution.png"))
    summary = json.load(open(os.path.join(out, "correlation_summary.json")))
    assert abs(summary["summary"]["mean"] - 0.051) < 0.005


@pytest.mark.skipif(not os.path.exists(REF_INSTRUCT_COMBINED),
                    reason="reference not mounted")
def test_cross_kappa_cli(tmp_path, capsys):
    out_json = str(tmp_path / "kappa.json")
    main(["cross-kappa", "--results", REF_INSTRUCT_COMBINED,
          "--bootstrap", "50", "--output-json", out_json])
    printed = capsys.readouterr().out
    assert "mean_kappa" in printed
    data = json.load(open(out_json))
    assert np.isfinite(data["mean_kappa"])
    assert data["n_pairs"] >= 28  # 8 models -> 28 pairs minimum


REF1_SURVEY = "/root/reference/data/word_meaning_survey_results.csv"
REF2_SURVEY = "/root/reference/data/word_meaning_survey_results_part_2.csv"


@pytest.mark.skipif(not os.path.exists(REF_INSTRUCT_COMBINED),
                    reason="reference not mounted")
def test_analyze_3way_cli(tmp_path, capsys):
    """3-way comparison on real data: correlations CSV + validity audit +
    bias warnings + best-model scatter (analyze_base_vs_instruct_vs_human.py)."""
    out = str(tmp_path / "3way")
    main(["analyze-3way", "--llm-csv", REF_INSTRUCT_COMBINED,
          "--survey1-csv", REF1_SURVEY, "--survey2-csv", REF2_SURVEY,
          "--output-dir", out])
    printed = capsys.readouterr().out
    assert "Loaded human data for 100 questions" in printed
    assert "invalid responses" in printed
    assert "WARNING: tends to answer" in printed
    corr = pd.read_csv(os.path.join(out, "model_human_correlations.csv"))
    assert {"model", "pearson_r", "spearman_r", "mae"} <= set(corr.columns)
    assert len(corr) >= 8
    # sorted by pearson descending
    valid = corr["pearson_r"].dropna()
    assert (valid.diff().dropna() <= 1e-12).all()
    assert os.path.exists(os.path.join(out, "human_vs_model_comparison.png"))


@pytest.mark.skipif(not os.path.exists(REF_MODEL_COMPARISON),
                    reason="reference not mounted")
def test_analyze_family_differences_cli(tmp_path, capsys):
    """Respondent-bootstrap agreement + family diffs on real data: the MAE
    direction must agree with Table 5 (Falcon worse, StableLM better)."""
    out = str(tmp_path / "fam")
    main(["analyze-family-differences", "--llm-csv", REF_MODEL_COMPARISON,
          "--survey1-csv", REF1_SURVEY, "--survey2-csv", REF2_SURVEY,
          "--output-dir", out, "--bootstrap", "60"])
    printed = capsys.readouterr().out
    assert "PER-FAMILY BASE vs INSTRUCT DIFFERENCES" in printed
    agreement = json.load(
        open(os.path.join(out, "llm_human_agreement_bootstrap.json")))
    by_model = {r["model"]: r for r in agreement["model_results"]}
    falcon_b = by_model["tiiuae/falcon-7b"]
    falcon_i = by_model["tiiuae/falcon-7b-instruct"]
    assert falcon_i["mae_mean"] > falcon_b["mae_mean"]          # Table 5 sign
    assert abs(falcon_b["mae_mean"] - 0.213) < 0.02             # near MAE val
    report = open(os.path.join(out, "family_differences.txt")).read()
    assert "SUMMARY TABLE" in report and "Falcon" in report
    # reuse path: --agreement-json skips the bootstrap
    main(["analyze-family-differences",
          "--agreement-json",
          os.path.join(out, "llm_human_agreement_bootstrap.json"),
          "--output-dir", str(tmp_path / "fam2")])
    assert "StableLM" in capsys.readouterr().out


@pytest.mark.skipif(not os.path.exists(REF1_SURVEY),
                    reason="reference not mounted")
def test_ground_truth_figure_cli(tmp_path, capsys):
    out = str(tmp_path / "gt")
    main(["ground-truth-figure", "--survey1-csv", REF1_SURVEY,
          "--survey2-csv", REF2_SURVEY, "--output-dir", out])
    printed = capsys.readouterr().out
    assert "Loaded 100 human ground truth values" in printed
    assert "Mean: 0.610" in printed                             # real-data pin
    assert os.path.exists(os.path.join(out, "ground_truth_distribution.png"))
    assert os.path.exists(
        os.path.join(out, "ground_truth_distribution_simple.png"))


def test_power_analysis_cli(tmp_path, capsys):
    out = str(tmp_path / "power")
    main(["power-analysis", "--output-dir", out, "--simulations", "500"])
    printed = capsys.readouterr().out
    assert "recommendation (80% power)" in printed
    assert "GPT" in printed and "Claude" in printed
    tex = open(os.path.join(out, "power_analysis_report.tex")).read()
    assert "\\begin{tabular}" in tex


@pytest.mark.skipif(not os.path.exists(REF_MODEL_COMPARISON),
                    reason="reference not mounted")
def test_analyze_mae_100q_cli_reproduces_reference(tmp_path, capsys):
    """Table 5 machinery on the REAL reference inputs reproduces the numbers
    analyze_base_vs_instruct_mae_100q.py prints on the same data (MAE values
    exact; CI edges differ only by RNG stream, pinned in test_survey)."""
    tex = str(tmp_path / "table5.tex")
    js = str(tmp_path / "families.json")
    main([
        "analyze-mae-100q",
        "--results", REF_MODEL_COMPARISON,
        "--survey1-csv", "/root/reference/data/word_meaning_survey_results.csv",
        "--survey2-csv", "/root/reference/data/word_meaning_survey_results_part_2.csv",
        "--output-tex", tex, "--output-json", js,
    ])
    out = capsys.readouterr().out
    assert "Respondents after exclusions: 884" in out
    assert "Falcon: base 0.213 -> instruct 0.286  diff +0.073" in out
    assert "StableLM: base 0.246 -> instruct 0.211  diff -0.035" in out
    assert "RedPajama: base 0.137 -> instruct 0.135" in out
    assert "Pythia-Dolly: base 0.183 -> instruct 0.379  diff +0.196" in out
    assert "Mistral: excluded" in out
    assert "Overall: base 0.188 -> instruct 0.241  diff +0.053" in out
    table = open(tex).read()
    assert "Falcon & 0.213 & 0.286 & +0.073***" in table
    families = json.load(open(js))["families"]
    assert families["_overall"]["p_value"] < 0.001


def test_similarity_cli(tmp_path, capsys):
    from llm_interpretation_replication_tpu.config import legal_scenarios

    records = [
        {
            "original_main": s["original_main"],
            "response_format": s["response_format"],
            "target_tokens": list(s["target_tokens"]),
            "confidence_format": s["confidence_format"],
            "rephrasings": [s["original_main"][:60] + " rephrased?"] * 3,
        }
        for s in legal_scenarios()
    ]
    path = str(tmp_path / "perturbations.json")
    json.dump(records, open(path, "w"))
    main(["similarity", "--perturbations", path,
          "--output-dir", str(tmp_path / "sim"), "--max-rephrasings", "3"])
    assert os.path.exists(tmp_path / "sim" / "original_vs_rephrasings_similarity.xlsx")


def test_similarity_cli_embeddings_leg(tmp_path, capsys, monkeypatch):
    """--embeddings drives the sentence-transformer leg
    (calculate_prompt_similarity.py:98-207) end-to-end from the CLI: with a
    loadable model the embedding_cosine_similarity column appears in the
    per-scenario CSV and the summary; when the loader degrades (package or
    model unavailable — the reference's gate) the run succeeds without it."""
    import numpy as np
    import pandas as pd

    from llm_interpretation_replication_tpu import __main__ as cli
    from llm_interpretation_replication_tpu.config import legal_scenarios

    records = [
        {
            "original_main": s["original_main"],
            "response_format": s["response_format"],
            "target_tokens": list(s["target_tokens"]),
            "confidence_format": s["confidence_format"],
            "rephrasings": [s["original_main"][:60] + " rephrased?"] * 2,
        }
        for s in legal_scenarios()
    ]
    path = str(tmp_path / "perturbations.json")
    json.dump(records, open(path, "w"))

    class StubModel:
        """Deterministic stand-in for SentenceTransformer.encode."""

        def encode(self, texts):
            rng = np.random.default_rng(7)
            basis = rng.standard_normal((8, 16))
            return np.stack([basis[len(t) % 8] + 0.01 * (i % 3)
                             for i, t in enumerate(texts)])

    import importlib

    simrep = importlib.import_module(
        "llm_interpretation_replication_tpu.analysis.similarity_report")
    monkeypatch.setattr(simrep, "load_embedding_model",
                        lambda name, log=print: StubModel())
    main(["similarity", "--perturbations", path,
          "--output-dir", str(tmp_path / "emb"), "--embeddings"])
    csv = pd.read_csv(tmp_path / "emb" / "scenario_1_original_vs_rephrasings.csv")
    assert "embedding_cosine_similarity" in csv.columns
    assert csv["embedding_cosine_similarity"].notna().all()
    out = capsys.readouterr().out
    assert "embedding_cosine_similarity" in out

    # degraded path: loader returns None (package/model unavailable)
    monkeypatch.setattr(simrep, "load_embedding_model",
                        lambda name, log=print: None)
    main(["similarity", "--perturbations", path,
          "--output-dir", str(tmp_path / "noemb"), "--embeddings"])
    csv2 = pd.read_csv(tmp_path / "noemb" / "scenario_1_original_vs_rephrasings.csv")
    assert ("embedding_cosine_similarity" not in csv2.columns
            or csv2["embedding_cosine_similarity"].isna().all())
    # the real loader itself degrades cleanly in this zero-egress image
    monkeypatch.undo()
    msgs = []
    model = simrep.load_embedding_model("all-MiniLM-L6-v2", log=msgs.append)
    assert model is None or hasattr(model, "encode")
    if model is None:
        assert any("Warning" in m for m in msgs)


REF1 = "/root/reference/data/word_meaning_survey_results.csv"
REF2 = "/root/reference/data/word_meaning_survey_results_part_2.csv"
REF_INSTRUCT = "/root/reference/data/instruct_model_comparison_results.csv"


@pytest.mark.skipif(not os.path.exists(REF2), reason="reference not mounted")
def test_question_loaders_on_real_data():
    questions, mapping = extract_survey2_questions(REF2)
    assert len(questions) >= 50
    assert all(not c.endswith("_8") for c in mapping.values())
    all_questions = load_ordinary_meaning_questions(REF_INSTRUCT, REF2)
    assert len(all_questions) == 100
    assert len(set(all_questions)) == 100


def test_throughput_meter():
    t = {"now": 0.0}
    meter = ThroughputMeter(n_chips=4, clock=lambda: t["now"])
    t["now"] = 2.0
    meter.add(100, tokens=50_000)
    snap = meter.snapshot()
    assert snap["prompts_per_sec"] == 50.0
    assert snap["prompts_per_sec_per_chip"] == 12.5
    assert snap["tokens_per_sec_per_chip"] == 6250.0


@pytest.mark.skipif(not os.path.exists(REF2), reason="reference not mounted")
def test_run_closed_source_cli_short_circuit(tmp_path, capsys):
    """run-closed-source with a finished results CSV short-circuits to report
    generation — no API keys needed (the reference main()'s saved-results
    path, evaluate_closed_source_models.py:1919-1926)."""
    import numpy as np
    import pandas as pd

    from llm_interpretation_replication_tpu.analysis.closed_source_eval import (
        RESULT_COLUMNS,
    )

    out = tmp_path / "cseval"
    out.mkdir()
    rng = np.random.default_rng(0)
    df = pd.DataFrame({c: rng.uniform(size=4) for c in RESULT_COLUMNS})
    df["question"] = [f"q{i}?" for i in range(4)]
    df.to_csv(out / "closed_source_evaluation_results.csv", index=False)
    main([
        "run-closed-source",
        "--questions-csv", REF_INSTRUCT,
        "--survey2-csv", REF2,
        "--survey1-csv", REF1,
        "--output-dir", str(out),
        "--yes",
    ])
    assert (out / "correlations.json").exists()
    assert (out / "mae_results_tables.tex").exists()


@pytest.mark.skipif(not os.path.exists(REF2), reason="reference not mounted")
def test_analyze_survey_cli_real_data(tmp_path, capsys):
    """analyze-survey end-to-end on the real exports: report + JSON with the
    paper's exclusion counts and the published cross-prompt point estimates."""
    out = tmp_path / "survey"
    main([
        "analyze-survey",
        "--survey1-csv", "/root/reference/data/word_meaning_survey_results.csv",
        "--survey2-csv", REF2,
        "--llm-csv", "/root/reference/data/instruct_model_comparison_results_combined.csv",
        "--output-dir", str(out),
        "--bootstrap", "50", "--cross-prompt-bootstrap", "3",
    ])
    results = json.loads((out / "results.json").read_text())
    assert results["exclusions"]["attention_failed"] == 115
    assert results["exclusions"]["identical_excluded"] == 9
    assert round(results["human_cross_prompt"]["mean_correlation"], 3) == 0.285
    assert round(results["llm_cross_prompt"]["mean_correlation"], 3) == 0.052
    assert results["meta_correlation"]["n_matched_items"] > 50
    report = (out / "report.txt").read_text()
    assert "Final sample size: 884" in report


@pytest.mark.skipif(not os.path.exists(REF2), reason="reference not mounted")
def test_demographics_table_cli(tmp_path, capsys):
    out = tmp_path / "demo.tex"
    main([
        "demographics-table",
        "--csv", "/root/reference/data/demographic_data.csv",
        "--csv", "/root/reference/data/demographic_data_part_2.csv",
        "--output", str(out),
    ])
    tex = out.read_text()
    assert tex.startswith("\\begin{tabular}") and "\\textbf{Sex}" in tex


@pytest.mark.skipif(
    not os.path.exists("/root/reference/results/claude_opus_batch_perturbation_results.xlsx"),
    reason="reference not mounted")
def test_analyze_combined_cli(tmp_path, capsys):
    out = tmp_path / "combined"
    main([
        "analyze-combined",
        "--workbook", "Claude=/root/reference/results/claude_opus_batch_perturbation_results.xlsx",
        "--workbook", "Gemini=/root/reference/results/gemini_perturbation_results.xlsx",
        "--output-dir", str(out),
    ])
    assert (out / "combined_confidence_stats.csv").exists()
    assert (out / "cross_model_correlations.csv").exists()
    assert "Claude" in capsys.readouterr().out


def test_api_keyed_commands_require_env(monkeypatch, tmp_path):
    """Every API-keyed command exits loudly (not silently) without its key."""
    for var in ("ANTHROPIC_API_KEY", "OPENAI_API_KEY", "GEMINI_API_KEY"):
        monkeypatch.delenv(var, raising=False)
    pert = tmp_path / "p.json"
    pert.write_text("[]")
    for argv in (
        ["generate-rephrasings"],
        ["run-api-perturbation", "--perturbations", str(pert), "--model", "gpt-4.1"],
        ["run-claude-perturbation", "--perturbations", str(pert)],
        ["run-gemini-perturbation", "--perturbations", str(pert)],
        ["run-irrelevant", "--perturbations", str(pert), "--force-rerun"],
    ):
        with pytest.raises(SystemExit, match="API_KEY not set"):
            main(argv)


class TestRunIrrelevantCli:
    """run-irrelevant end-to-end against FakeTransport — the Appendix C study
    leg (evaluate_irrelevant_perturbations.py:942-1297 as a subcommand)."""

    def _fixture(self, tmp_path, monkeypatch):
        from llm_interpretation_replication_tpu.api_backends import (
            anthropic_client, gemini_client, openai_client,
        )
        from llm_interpretation_replication_tpu.api_backends.transport import (
            FakeTransport,
        )
        from llm_interpretation_replication_tpu.gen.irrelevant import (
            generate_perturbations, save_perturbations,
        )

        scenarios = generate_perturbations(
            [{"original_main": "Main text one. Second sentence.",
              "scenario_name": "S1",
              "response_format": "Answer 'Covered' or 'Not Covered'.",
              "target_tokens": ["Covered", "Not"],
              "confidence_format": "How confident are you, 0-100?"}],
            ["Fact A.", "Fact B."],
        )
        pert_path = tmp_path / "p.json"
        save_perturbations(scenarios, str(pert_path))

        ft = FakeTransport()

        def openai_handler(call):
            content = call["json"]["messages"][0]["content"]
            text = "85" if "confident" in content else "Covered"
            return 200, {"choices": [{"message": {"content": text}}]}

        def claude_handler(call):
            content = call["json"]["messages"][0]["content"]
            text = "70" if "confident" in content else "Covered"
            return 200, {"content": [{"type": "text", "text": text}]}

        def gemini_handler(call):
            content = call["json"]["contents"][0]["parts"][0]["text"]
            text = "60" if "confident" in content else "Not Covered"
            return 200, {"candidates": [{"content": {"parts": [{"text": text}]}}]}

        ft.add("POST", "/chat/completions", openai_handler)
        ft.add("POST", "/messages", claude_handler)
        ft.add("POST", ":generateContent", gemini_handler)
        for mod in (openai_client, anthropic_client, gemini_client):
            monkeypatch.setattr(mod, "UrllibTransport", lambda: ft)
        for var in ("OPENAI_API_KEY", "ANTHROPIC_API_KEY", "GEMINI_API_KEY"):
            monkeypatch.setenv(var, "test-key")
        return pert_path, ft

    def test_full_flow_resume_and_plot_modes(self, tmp_path, monkeypatch, capsys):
        import time

        pert_path, ft = self._fixture(tmp_path, monkeypatch)
        monkeypatch.setattr(time, "sleep", lambda _s: None)  # no pacing in tests
        out = tmp_path / "irr"
        argv = ["run-irrelevant", "--perturbations", str(pert_path),
                "--output-dir", str(out), "--test-mode", "--limit", "9"]
        main(argv)
        for name in ("raw_results.csv", "summary.csv", "results_analysis.xlsx",
                     "analysis.json", "summary_report.txt",
                     "detailed_prompts.txt",
                     "three_model_stacked_visualization.png"):
            assert (out / name).exists(), name

        df = pd.read_csv(out / "raw_results.csv")
        # limit 9 split 3/3/3: per model the original + first 2 perturbations
        assert len(df) == 9
        assert set(df["model"]) == {"gpt", "claude", "gemini"}
        assert (df.groupby("model").size() == 3).all()
        # vendor quirks rode through: temperature 0.7 everywhere, Gemini with
        # safety BLOCK_NONE and maxOutputTokens UNSET (the truncation dodge)
        gemini_calls = [c for c in ft.calls if ":generateContent" in c["url"]]
        assert gemini_calls
        for c in gemini_calls:
            assert c["json"]["generationConfig"]["temperature"] == 0.7
            assert "maxOutputTokens" not in c["json"]["generationConfig"]
            assert c["json"]["safetySettings"]
        openai_calls = [c for c in ft.calls if "/chat/completions" in c["url"]]
        assert all(c["json"]["temperature"] == 0.7 for c in openai_calls)
        # each vendor got ITS OWN model name (regression: a shared late-bound
        # closure once sent the last vendor's spec to every client)
        assert all(c["json"]["model"] == "gpt-4.1-2025-04-14" for c in openai_calls)
        claude_calls = [c for c in ft.calls if "/messages" in c["url"]]
        assert all(c["json"]["model"] == "claude-opus-4-1-20250805"
                   for c in claude_calls)
        assert all("gemini-2.5-pro" in c["url"] for c in gemini_calls)
        summary = pd.read_csv(out / "summary.csv")
        assert list(summary.columns) == [
            "scenario", "model", "consistency", "original_confidence",
            "mean_all_confidence", "std_all_confidence",
            "median_all_confidence", "ci_lower_95", "ci_upper_95", "n_samples",
            "mean_perturbed_confidence", "std_perturbed_confidence",
            "original_response", "num_perturbations", "num_total_samples",
        ]
        # every model answered consistently with its own original
        assert (summary["consistency"] == 1.0).all()

        # 2nd invocation: --load-existing default short-circuits, NO new calls
        calls_before = len(ft.calls)
        main(argv)
        assert len(ft.calls) == calls_before
        assert "force-rerun" in capsys.readouterr().out

        # --force-rerun resumes via the triple set: only the evaluations the
        # limit previously cut off are sent (4 remaining per model x 2 legs)
        main(argv + ["--force-rerun", "--full-mode"])
        assert len(pd.read_csv(out / "raw_results.csv")) == 21  # 7 per model
        assert len(ft.calls) == calls_before + 3 * 4 * 2
        # original rows reloaded from the resume CSV carry NaN statements —
        # they must not leak into the prompts report as 'nan'
        assert "nan" not in (out / "detailed_prompts.txt").read_text()

        # --regenerate-plots touches no data, rebuilds the figure
        (out / "three_model_stacked_visualization.png").unlink()
        calls_before = len(ft.calls)
        main(["run-irrelevant", "--output-dir", str(out), "--regenerate-plots"])
        assert (out / "three_model_stacked_visualization.png").exists()
        assert len(ft.calls) == calls_before

        # --no-resume clears state: a fresh run re-evaluates everything
        main(argv + ["--force-rerun", "--no-resume"])
        assert len(pd.read_csv(out / "raw_results.csv")) == 9


@pytest.mark.skipif(not os.path.exists(REF2), reason="reference not mounted")
def test_extract_survey2_cli(tmp_path, capsys):
    out = str(tmp_path / "q2.txt")
    main(["extract-survey2-questions", "--survey-csv", REF2, "--output", out])
    printed = capsys.readouterr().out
    lines = open(out, encoding="utf-8").read().strip().splitlines()
    assert len(lines) >= 50
    assert all(q.endswith("?") for q in lines)
    assert "wrote" in printed
    # golden: byte-exact against the reference's committed extractor output
    ref_txt = "/root/reference/data/question_list_part_2.txt"
    if os.path.exists(ref_txt):
        ref = open(ref_txt, encoding="utf-8").read().strip().splitlines()
        assert lines == ref

    # --ascii-quotes produces the straight-quote form the reference sweep
    # actually ran (compare_instruct_models_survey2.py:298-355 hardcodes a
    # straight-quote transcription of the extractor output)
    out2 = str(tmp_path / "q2_ascii.txt")
    main(["extract-survey2-questions", "--survey-csv", REF2,
          "--output", out2, "--ascii-quotes"])
    ascii_lines = open(out2, encoding="utf-8").read().strip().splitlines()
    assert len(ascii_lines) == len(lines)
    assert not any(ch in q for q in ascii_lines for ch in "“”‘’")
    assert 'Is "biodegradable plastic" an "organic material"?' in ascii_lines


def test_sample_statements_cli(tmp_path, capsys):
    out = str(tmp_path / "sample.tex")
    main(["sample-statements", "--output", out])
    tex = open(out).read()
    assert tex.startswith("\\begin{enumerate}")
    assert tex.count("\\item") == 50
    # seeded: identical to the byte-exact golden the viz test pins
    ref = "/root/reference/results/irrelevant_statements_sample.tex"
    if os.path.exists(ref):
        assert tex.strip() == open(ref).read().strip()


def test_repair_batch_cli(tmp_path, capsys):
    reqs = tmp_path / "reqs.jsonl"
    reqs.write_text("\n".join(
        json.dumps({"custom_id": f"id-{i}", "request": {}}) for i in range(2)
    ))
    # real corruption shape: the text field holds a stringified response
    # object (see test_api_backends.py's repair tests)
    corrupted = tmp_path / "bad.jsonl"
    corrupted.write_text("\n".join(json.dumps(
        {"response": {"candidates": [{"content": {"parts": [{
            "text": f"Candidate(content=Content(parts=[Part(text='Answer {i}')]))"
        }]}}]}}
    ) for i in range(2)))
    out = tmp_path / "fixed.jsonl"
    main(["repair-batch", "--requests", str(reqs), "--responses", str(corrupted),
          "--output", str(out)])
    rows = [json.loads(l) for l in open(out).read().splitlines()]
    assert len(rows) == 2
    assert rows[0]["custom_id"] == "id-0"
    texts = [r["response"]["candidates"][0]["content"]["parts"][0]["text"]
             for r in rows]
    assert texts == ["Answer 0", "Answer 1"]   # extraction actually recovered
    assert "repaired 2 rows" in capsys.readouterr().out


@pytest.mark.skipif(not os.path.exists(REF2), reason="reference not mounted")
def test_analyze_agreement_cli_real_data(tmp_path, capsys):
    """analyze-agreement end-to-end on the real CSVs: both reference JSON
    shapes written (llm_human_agreement_analysis.json +
    llm_human_agreement_bootstrap.json), ranking printed."""
    out = tmp_path / "agreement"
    main([
        "analyze-agreement",
        "--llm-csv", REF_INSTRUCT,
        "--base-csv", "/root/reference/data/model_comparison_results.csv",
        "--survey-csv", REF1,
        "--output-dir", str(out),
        "--bootstrap", "120",
    ])
    printed = capsys.readouterr().out
    assert "Loaded human average ratings for 50 questions" in printed
    assert "p = " in printed
    point = json.loads((out / "llm_human_agreement_analysis.json").read_text())
    assert point["analysis_type"] == "llm_human_agreement"
    assert len(point["question_variance"]) == 50
    assert "detailed" not in point            # print-only detail not serialized
    boot = json.loads((out / "llm_human_agreement_bootstrap.json").read_text())
    assert boot["analysis_type"] == "llm_human_agreement_bootstrap_questions"
    assert {"mae", "mse", "mape"} <= set(boot["overall_comparison"]["metrics"])
