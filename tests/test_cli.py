"""CLI smoke tests (no-model commands run end-to-end; model commands are
covered via the engine-factory path in test_sweeps)."""

import json
import os

import numpy as np
import pandas as pd
import pytest

from llm_interpretation_replication_tpu.__main__ import main
from llm_interpretation_replication_tpu.analysis.questions import (
    extract_survey2_questions,
    load_ordinary_meaning_questions,
)
from llm_interpretation_replication_tpu.utils.profiling import ThroughputMeter


def test_generate_irrelevant_cli(tmp_path, capsys):
    out = str(tmp_path / "perturbations_irrelevant.json")
    main(["generate-irrelevant", "--output", out])
    data = json.load(open(out))
    assert sum(len(s["perturbations_with_irrelevant"]) for s in data) == 3400
    assert "3400 perturbations" in capsys.readouterr().out


def test_analyze_100q_cli(tmp_path, capsys):
    rng = np.random.default_rng(0)
    rows = []
    for i in range(30):
        rows.append({"model_family": "Fam", "base_or_instruct": "base",
                     "prompt": f"q{i}", "relative_prob": rng.uniform(0.2, 0.4)})
        rows.append({"model_family": "Fam", "base_or_instruct": "instruct",
                     "prompt": f"q{i}", "relative_prob": rng.uniform(0.6, 0.8)})
    csv = str(tmp_path / "r.csv")
    pd.DataFrame(rows).to_csv(csv, index=False)
    main(["analyze-100q", "--results", csv, "--latex"])
    out = capsys.readouterr().out
    assert "mean_diff" in out
    assert "\\begin{tabular}" in out


def test_similarity_cli(tmp_path, capsys):
    from llm_interpretation_replication_tpu.config import legal_scenarios

    records = [
        {
            "original_main": s["original_main"],
            "response_format": s["response_format"],
            "target_tokens": list(s["target_tokens"]),
            "confidence_format": s["confidence_format"],
            "rephrasings": [s["original_main"][:60] + " rephrased?"] * 3,
        }
        for s in legal_scenarios()
    ]
    path = str(tmp_path / "perturbations.json")
    json.dump(records, open(path, "w"))
    main(["similarity", "--perturbations", path,
          "--output-dir", str(tmp_path / "sim"), "--max-rephrasings", "3"])
    assert os.path.exists(tmp_path / "sim" / "original_vs_rephrasings_similarity.xlsx")


REF1 = "/root/reference/data/word_meaning_survey_results.csv"
REF2 = "/root/reference/data/word_meaning_survey_results_part_2.csv"
REF_INSTRUCT = "/root/reference/data/instruct_model_comparison_results.csv"


@pytest.mark.skipif(not os.path.exists(REF2), reason="reference not mounted")
def test_question_loaders_on_real_data():
    questions, mapping = extract_survey2_questions(REF2)
    assert len(questions) >= 50
    assert all(not c.endswith("_8") for c in mapping.values())
    all_questions = load_ordinary_meaning_questions(REF_INSTRUCT, REF2)
    assert len(all_questions) == 100
    assert len(set(all_questions)) == 100


def test_throughput_meter():
    t = {"now": 0.0}
    meter = ThroughputMeter(n_chips=4, clock=lambda: t["now"])
    t["now"] = 2.0
    meter.add(100, tokens=50_000)
    snap = meter.snapshot()
    assert snap["prompts_per_sec"] == 50.0
    assert snap["prompts_per_sec_per_chip"] == 12.5
    assert snap["tokens_per_sec_per_chip"] == 6250.0


@pytest.mark.skipif(not os.path.exists(REF2), reason="reference not mounted")
def test_run_closed_source_cli_short_circuit(tmp_path, capsys):
    """run-closed-source with a finished results CSV short-circuits to report
    generation — no API keys needed (the reference main()'s saved-results
    path, evaluate_closed_source_models.py:1919-1926)."""
    import numpy as np
    import pandas as pd

    from llm_interpretation_replication_tpu.analysis.closed_source_eval import (
        RESULT_COLUMNS,
    )

    out = tmp_path / "cseval"
    out.mkdir()
    rng = np.random.default_rng(0)
    df = pd.DataFrame({c: rng.uniform(size=4) for c in RESULT_COLUMNS})
    df["question"] = [f"q{i}?" for i in range(4)]
    df.to_csv(out / "closed_source_evaluation_results.csv", index=False)
    main([
        "run-closed-source",
        "--questions-csv", REF_INSTRUCT,
        "--survey2-csv", REF2,
        "--survey1-csv", REF1,
        "--output-dir", str(out),
        "--yes",
    ])
    assert (out / "correlations.json").exists()
    assert (out / "mae_results_tables.tex").exists()


@pytest.mark.skipif(not os.path.exists(REF2), reason="reference not mounted")
def test_analyze_survey_cli_real_data(tmp_path, capsys):
    """analyze-survey end-to-end on the real exports: report + JSON with the
    paper's exclusion counts and the published cross-prompt point estimates."""
    out = tmp_path / "survey"
    main([
        "analyze-survey",
        "--survey1-csv", "/root/reference/data/word_meaning_survey_results.csv",
        "--survey2-csv", REF2,
        "--llm-csv", "/root/reference/data/instruct_model_comparison_results_combined.csv",
        "--output-dir", str(out),
        "--bootstrap", "50", "--cross-prompt-bootstrap", "3",
    ])
    results = json.loads((out / "results.json").read_text())
    assert results["exclusions"]["attention_failed"] == 115
    assert results["exclusions"]["identical_excluded"] == 9
    assert round(results["human_cross_prompt"]["mean_correlation"], 3) == 0.285
    assert round(results["llm_cross_prompt"]["mean_correlation"], 3) == 0.052
    assert results["meta_correlation"]["n_matched_items"] > 50
    report = (out / "report.txt").read_text()
    assert "Final sample size: 884" in report


@pytest.mark.skipif(not os.path.exists(REF2), reason="reference not mounted")
def test_demographics_table_cli(tmp_path, capsys):
    out = tmp_path / "demo.tex"
    main([
        "demographics-table",
        "--csv", "/root/reference/data/demographic_data.csv",
        "--csv", "/root/reference/data/demographic_data_part_2.csv",
        "--output", str(out),
    ])
    tex = out.read_text()
    assert tex.startswith("\\begin{tabular}") and "\\textbf{Sex}" in tex


@pytest.mark.skipif(
    not os.path.exists("/root/reference/results/claude_opus_batch_perturbation_results.xlsx"),
    reason="reference not mounted")
def test_analyze_combined_cli(tmp_path, capsys):
    out = tmp_path / "combined"
    main([
        "analyze-combined",
        "--workbook", "Claude=/root/reference/results/claude_opus_batch_perturbation_results.xlsx",
        "--workbook", "Gemini=/root/reference/results/gemini_perturbation_results.xlsx",
        "--output-dir", str(out),
    ])
    assert (out / "combined_confidence_stats.csv").exists()
    assert (out / "cross_model_correlations.csv").exists()
    assert "Claude" in capsys.readouterr().out


def test_api_keyed_commands_require_env(monkeypatch, tmp_path):
    """Every API-keyed command exits loudly (not silently) without its key."""
    for var in ("ANTHROPIC_API_KEY", "OPENAI_API_KEY", "GEMINI_API_KEY"):
        monkeypatch.delenv(var, raising=False)
    pert = tmp_path / "p.json"
    pert.write_text("[]")
    for argv in (
        ["generate-rephrasings"],
        ["run-api-perturbation", "--perturbations", str(pert), "--model", "gpt-4.1"],
        ["run-claude-perturbation", "--perturbations", str(pert)],
        ["run-gemini-perturbation", "--perturbations", str(pert)],
    ):
        with pytest.raises(SystemExit, match="API_KEY not set"):
            main(argv)
