"""Full-chain integration: HF snapshot on disk -> CLI sweep -> workbook ->
CLI analysis.  Exercises exactly the user path (loader + tokenizer + engine +
bucketing + writers + xlsx + statistics) with a tiny random model on the CPU
mesh — the glue the per-layer unit tests can't see."""

import json
import os
import sys

import numpy as np
import pandas as pd
import pytest

torch = pytest.importorskip("torch")

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from helpers import build_test_tokenizer  # noqa: E402

from llm_interpretation_replication_tpu.__main__ import main  # noqa: E402
from llm_interpretation_replication_tpu.config import legal_scenarios  # noqa: E402
from llm_interpretation_replication_tpu.utils.xlsx import read_xlsx  # noqa: E402


@pytest.fixture(scope="module")
def snapshot(tmp_path_factory):
    from transformers import GPTNeoXConfig, GPTNeoXForCausalLM

    snap = tmp_path_factory.mktemp("snap")
    config = GPTNeoXConfig(
        vocab_size=300, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=1024,
    )
    torch.manual_seed(7)
    GPTNeoXForCausalLM(config).eval().save_pretrained(snap, safe_serialization=True)
    build_test_tokenizer(300).save_pretrained(snap)
    return str(snap)


def test_perturbation_sweep_to_analysis_cli(snapshot, tmp_path, capsys):
    """run-perturbation on the real 5 scenarios (2 tiny rephrasings each)
    through a disk snapshot, then analyze-perturbations over the produced
    workbook — both via the CLI."""
    scenarios = legal_scenarios()
    pert = []
    for s in scenarios:
        pert.append({
            **s,
            "rephrasings": [f"Variant one of: {s['original_main'][:80]}",
                            f"Variant two of: {s['original_main'][:80]}"],
        })
    pert_path = tmp_path / "perturbations.json"
    pert_path.write_text(json.dumps(pert))
    out = tmp_path / "run"
    main([
        "run-perturbation", "--device", "cpu", "--dtype", "float32",
        "--model", snapshot, "--perturbations", str(pert_path),
        "--batch-size", "4", "--output-dir", str(out),
    ])
    wb_path = out / "perturbation_results.xlsx"
    assert wb_path.exists()
    df = read_xlsx(str(wb_path))
    assert len(df) == 10                       # 5 scenarios x 2 rephrasings
    probs = pd.to_numeric(df["Token_1_Prob"], errors="coerce")
    assert probs.notna().all() and ((probs >= 0) & (probs <= 1)).all()
    assert set(df["Original Main Part"]) == {s["original_main"] for s in scenarios}

    analysis_out = tmp_path / "analysis"
    main([
        "analyze-perturbations", "--workbook", str(wb_path),
        "--output-dir", str(analysis_out), "--simulations", "2000",
    ])
    produced = [f for _, _, fs in os.walk(analysis_out) for f in fs]
    assert any(f.endswith("tables.tex") for f in produced)


def test_100q_sweep_cli_roundtrip(snapshot, tmp_path, capsys):
    """run-100q with the snapshot standing in for every roster model, then
    analyze-100q over the results CSV."""
    from llm_interpretation_replication_tpu.sweeps import base_vs_instruct_100q as sweep_mod

    import shutil

    out = tmp_path / "run100"
    # distinct paths: the sweep checkpoints completed models BY NAME (the
    # reference's semantics), so base==instruct would skip the second leg
    instruct_snap = str(tmp_path / "snap_instruct")
    shutil.copytree(snapshot, instruct_snap)
    pairs = [{"base": snapshot, "instruct": instruct_snap, "family": "tiny"}]
    orig = sweep_mod.model_pairs_100q
    sweep_mod.model_pairs_100q = lambda: pairs
    try:
        main([
            "run-100q", "--device", "cpu", "--dtype", "float32",
            "--batch-size", "8", "--output-dir", str(out),
            "--checkpoint-dir", str(tmp_path / "ckpt"),
        ])
    finally:
        sweep_mod.model_pairs_100q = orig
    csv = out / "base_vs_instruct_100q_results.csv"
    assert csv.exists()
    df = pd.read_csv(csv)
    assert set(df["base_or_instruct"]) == {"base", "instruct"}
    assert len(df) == 200                      # 100 questions x 2 legs
    main(["analyze-100q", "--results", str(csv)])
    assert "tiny" in capsys.readouterr().out
