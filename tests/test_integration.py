"""Full-chain integration: HF snapshot on disk -> CLI sweep -> workbook ->
CLI analysis.  Exercises exactly the user path (loader + tokenizer + engine +
bucketing + writers + xlsx + statistics) with a tiny random model on the CPU
mesh — the glue the per-layer unit tests can't see."""

import json
import os
import sys

import numpy as np
import pandas as pd
import pytest

torch = pytest.importorskip("torch")

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from helpers import build_test_tokenizer  # noqa: E402

from llm_interpretation_replication_tpu.__main__ import main  # noqa: E402
from llm_interpretation_replication_tpu.config import legal_scenarios  # noqa: E402
from llm_interpretation_replication_tpu.utils.xlsx import read_xlsx  # noqa: E402


@pytest.fixture(scope="module")
def snapshot(tmp_path_factory):
    from transformers import GPTNeoXConfig, GPTNeoXForCausalLM

    snap = tmp_path_factory.mktemp("snap")
    config = GPTNeoXConfig(
        vocab_size=300, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=1024,
    )
    torch.manual_seed(7)
    GPTNeoXForCausalLM(config).eval().save_pretrained(snap, safe_serialization=True)
    build_test_tokenizer(300).save_pretrained(snap)
    return str(snap)


def test_perturbation_sweep_to_analysis_cli(snapshot, tmp_path, capsys):
    """run-perturbation on the real 5 scenarios (2 tiny rephrasings each)
    through a disk snapshot, then analyze-perturbations over the produced
    workbook — both via the CLI."""
    scenarios = legal_scenarios()
    pert = []
    for s in scenarios:
        pert.append({
            **s,
            "rephrasings": [f"Variant one of: {s['original_main'][:80]}",
                            f"Variant two of: {s['original_main'][:80]}"],
        })
    pert_path = tmp_path / "perturbations.json"
    pert_path.write_text(json.dumps(pert))
    out = tmp_path / "run"
    main([
        "run-perturbation", "--device", "cpu", "--dtype", "float32",
        "--model", snapshot, "--perturbations", str(pert_path),
        "--batch-size", "4", "--output-dir", str(out),
    ])
    wb_path = out / "perturbation_results.xlsx"
    assert wb_path.exists()
    df = read_xlsx(str(wb_path))
    assert len(df) == 10                       # 5 scenarios x 2 rephrasings
    probs = pd.to_numeric(df["Token_1_Prob"], errors="coerce")
    assert probs.notna().all() and ((probs >= 0) & (probs <= 1)).all()
    assert set(df["Original Main Part"]) == {s["original_main"] for s in scenarios}

    analysis_out = tmp_path / "analysis"
    main([
        "analyze-perturbations", "--workbook", str(wb_path),
        "--output-dir", str(analysis_out), "--simulations", "2000",
    ])
    produced = [f for _, _, fs in os.walk(analysis_out) for f in fs]
    assert any(f.endswith("tables.tex") for f in produced)


def test_100q_sweep_cli_roundtrip(snapshot, tmp_path, capsys):
    """run-100q with the snapshot standing in for every roster model, then
    analyze-100q over the results CSV."""
    from llm_interpretation_replication_tpu.sweeps import base_vs_instruct_100q as sweep_mod

    import shutil

    out = tmp_path / "run100"
    # distinct paths: the sweep checkpoints completed models BY NAME (the
    # reference's semantics), so base==instruct would skip the second leg
    instruct_snap = str(tmp_path / "snap_instruct")
    shutil.copytree(snapshot, instruct_snap)
    pairs = [{"base": snapshot, "instruct": instruct_snap, "family": "tiny"}]
    orig = sweep_mod.model_pairs_100q
    sweep_mod.model_pairs_100q = lambda: pairs
    try:
        main([
            "run-100q", "--device", "cpu", "--dtype", "float32",
            "--batch-size", "8", "--output-dir", str(out),
            "--checkpoint-dir", str(tmp_path / "ckpt"),
        ])
    finally:
        sweep_mod.model_pairs_100q = orig
    csv = out / "base_vs_instruct_100q_results.csv"
    assert csv.exists()
    df = pd.read_csv(csv)
    assert set(df["base_or_instruct"]) == {"base", "instruct"}
    assert len(df) == 200                      # 100 questions x 2 legs
    main(["analyze-100q", "--results", str(csv)])
    assert "tiny" in capsys.readouterr().out


def test_instruct_sweep_cli_roundtrip(snapshot, tmp_path, capsys):
    """run-instruct-sweep with two snapshot stand-ins for the 9-model roster,
    asserting the CSV byte-matches the writers contract
    (INSTRUCT_COMPARISON_COLUMNS), then model-comparison over the result —
    the full appendix inter-LLM-correlation chain via the CLI."""
    import shutil

    from llm_interpretation_replication_tpu.sweeps import instruct_sweep as sweep_mod
    from llm_interpretation_replication_tpu.sweeps.writers import (
        INSTRUCT_COMPARISON_COLUMNS,
    )

    out = tmp_path / "run_instruct"
    snap2 = str(tmp_path / "snap_b")
    shutil.copytree(snapshot, snap2)
    orig = sweep_mod.instruct_sweep_models
    sweep_mod.instruct_sweep_models = lambda: [snapshot, snap2]
    try:
        main([
            "run-instruct-sweep", "--device", "cpu", "--dtype", "float32",
            "--batch-size", "8", "--output-dir", str(out),
            "--checkpoint-dir", str(tmp_path / "ckpt_instr"),
        ])
    finally:
        sweep_mod.instruct_sweep_models = orig
    csv = out / "instruct_model_comparison_results.csv"
    assert csv.exists()
    df = pd.read_csv(csv)
    assert list(df.columns) == INSTRUCT_COMPARISON_COLUMNS
    assert len(df) == 200                      # 100 questions x 2 models
    rel = pd.to_numeric(df["relative_prob"], errors="coerce")
    assert rel.notna().all() and ((rel >= 0) & (rel <= 1)).all()

    mc_out = tmp_path / "mc"
    main(["model-comparison", "--results", str(csv),
          "--output-dir", str(mc_out), "--bootstrap", "50", "--no-figures"])
    assert (mc_out / "pairwise_correlations.csv").exists()
    assert "model pairs" in capsys.readouterr().out


@pytest.mark.skipif(
    not (os.path.exists("/root/reference/data/word_meaning_survey_results_part_2.csv")
         and os.path.exists("/root/reference/data/word_meaning_survey_results.csv")),
    reason="reference not mounted",
)
def test_survey2_instruct_sweep_chain(snapshot, tmp_path, capsys):
    """The survey-2 leg end-to-end via the CLI, the reference's
    compare_instruct_models_survey2.py flow: extract-survey2-questions on the
    real Qualtrics export -> run-instruct-sweep --questions-file -> the
    survey-2 results CSV with the §2.8 schema (one row per question x model,
    filename instruct_model_comparison_results_survey2.csv, ibid.:543-546)."""
    from llm_interpretation_replication_tpu.sweeps import instruct_sweep as sweep_mod
    from llm_interpretation_replication_tpu.sweeps.writers import (
        INSTRUCT_COMPARISON_COLUMNS,
    )

    ref2 = "/root/reference/data/word_meaning_survey_results_part_2.csv"
    qfile = str(tmp_path / "question_list_part_2_actual.txt")
    main(["extract-survey2-questions", "--survey-csv", ref2,
          "--output", qfile, "--ascii-quotes"])
    questions = open(qfile, encoding="utf-8").read().strip().splitlines()
    assert len(questions) == 50          # the reference's survey-2 prompt count

    out = tmp_path / "run_survey2"
    csv = out / "instruct_model_comparison_results_survey2.csv"
    orig = sweep_mod.instruct_sweep_models
    sweep_mod.instruct_sweep_models = lambda: [snapshot]
    try:
        main([
            "run-instruct-sweep", "--device", "cpu", "--dtype", "float32",
            "--batch-size", "8", "--output-dir", str(out),
            "--checkpoint-dir", str(tmp_path / "ckpt_s2"),
            "--questions-file", qfile, "--results-csv", str(csv),
        ])
    finally:
        sweep_mod.instruct_sweep_models = orig
    printed = capsys.readouterr().out
    assert "50 questions" in printed
    df = pd.read_csv(csv)
    assert list(df.columns) == INSTRUCT_COMPARISON_COLUMNS
    assert len(df) == 50                 # 50 questions x 1 model
    assert set(df["prompt"]) == set(questions)
    rel = pd.to_numeric(df["relative_prob"], errors="coerce")
    assert rel.notna().all() and ((rel >= 0) & (rel <= 1)).all()
    # the survey-2 checkpoint is derived from the CSV basename, so it can
    # coexist with the 50q sweep's checkpoint in one output dir
    assert (out / "instruct_model_comparison_results_survey2_checkpoint.json").exists()

    # chain end: the sweep CSV feeds the consolidated survey pipeline (the
    # reference concatenated its survey-2 run into the combined CSV that
    # survey_analysis_consolidated.py consumes)
    survey_out = tmp_path / "survey2_analysis"
    main([
        "analyze-survey",
        "--survey1-csv", "/root/reference/data/word_meaning_survey_results.csv",
        "--survey2-csv", ref2,
        "--llm-csv", str(csv),
        "--output-dir", str(survey_out),
        "--bootstrap", "20", "--cross-prompt-bootstrap", "2",
    ])
    results = json.loads((survey_out / "results.json").read_text())
    assert results, "analyze-survey produced no results on the survey-2 sweep"


def test_api_perturbation_cli_full_batch_lifecycle(tmp_path, monkeypatch, capsys):
    """run-api-perturbation via the CLI against a faked OpenAI Batch service
    (upload -> create -> poll -> download), on the real 5 legal scenarios:
    the produced workbook must match the PERTURBATION_COLUMNS contract."""
    import math

    from llm_interpretation_replication_tpu.api_backends import (
        openai_client as oc_mod,
    )
    from llm_interpretation_replication_tpu.api_backends.transport import (
        FakeTransport,
    )
    from llm_interpretation_replication_tpu.sweeps.writers import (
        PERTURBATION_COLUMNS,
    )

    scenarios = legal_scenarios()
    pert = [
        {**s, "rephrasings": [f"V1: {s['original_main'][:60]}",
                              f"V2: {s['original_main'][:60]}"]}
        for s in scenarios
    ]
    pert_path = tmp_path / "perturbations.json"
    pert_path.write_text(json.dumps(pert))

    ft = FakeTransport()
    uploads = {}

    def upload(call):
        fid = f"file-{len(uploads)}"
        uploads[fid] = call["data"]
        return 200, {"id": fid}

    ft.add("POST", "/files", upload)
    ft.add("POST", "/batches", lambda c: (200, {
        "id": f"batch-{c['json']['input_file_id']}", "status": "validating",
        "input_file_id": c["json"]["input_file_id"],
    }))

    def poll(call):
        fid = call["url"].rsplit("/batches/batch-", 1)[1]
        return 200, {"id": f"batch-{fid}", "status": "completed",
                     "output_file_id": f"out-{fid}"}

    ft.add("GET", "/batches/", poll)

    def download(call):
        fid = call["url"].rsplit("/files/out-", 1)[1].split("/content")[0]
        lines = []
        for line in uploads[fid].decode(errors="ignore").splitlines():
            line = line.strip()
            if not line.startswith("{"):
                continue
            req = json.loads(line)
            content = req["body"]["messages"][0]["content"]
            scenario = next(s for s in scenarios
                            if s["confidence_format"] in content
                            or s["response_format"] in content)
            t1, t2 = scenario["target_tokens"]
            if scenario["confidence_format"] in content:
                body = {"choices": [{"message": {"content": "70"},
                                     "logprobs": {"content": [{"top_logprobs": [
                                         {"token": "70", "logprob": math.log(0.5)},
                                     ]}]}}],
                        "usage": {"prompt_tokens": 5, "completion_tokens": 1}}
            else:
                body = {"choices": [{"message": {"content": t1},
                                     "logprobs": {"content": [{"top_logprobs": [
                                         {"token": t1, "logprob": math.log(0.6)},
                                         {"token": t2, "logprob": math.log(0.3)},
                                     ]}]}}],
                        "usage": {"prompt_tokens": 5, "completion_tokens": 1}}
            lines.append(json.dumps({
                "custom_id": req["custom_id"], "response": {"body": body},
            }))
        return 200, "\n".join(lines).encode()

    ft.add("GET", "/content", download)
    monkeypatch.setattr(oc_mod, "UrllibTransport", lambda: ft)
    monkeypatch.setenv("OPENAI_API_KEY", "test-key")

    out = tmp_path / "api_results.xlsx"
    main(["run-api-perturbation", "--perturbations", str(pert_path),
          "--model", "gpt-4.1", "--output", str(out)])
    assert "gpt-4.1" in capsys.readouterr().out
    df = read_xlsx(str(out))
    assert list(df.columns) == PERTURBATION_COLUMNS
    assert len(df) == 10                       # 5 scenarios x 2 rephrasings
    t1 = pd.to_numeric(df["Token_1_Prob"], errors="coerce")
    assert t1.notna().all() and (t1 > 0).all()


def test_claude_perturbation_cli_batch_lifecycle(tmp_path, monkeypatch, capsys):
    """run-claude-perturbation via the CLI against a faked Message-Batches
    service (create -> poll -> results), real 5 scenarios."""
    from llm_interpretation_replication_tpu.api_backends import (
        anthropic_client as ac_mod,
    )
    from llm_interpretation_replication_tpu.api_backends.transport import (
        FakeTransport,
    )
    from llm_interpretation_replication_tpu.sweeps.api_perturbation import (
        CLAUDE_PERTURBATION_COLUMNS,
    )

    scenarios = legal_scenarios()
    pert = [
        {**s, "rephrasings": [f"V1: {s['original_main'][:60]}",
                              f"V2: {s['original_main'][:60]}"]}
        for s in scenarios
    ]
    pert_path = tmp_path / "perturbations.json"
    pert_path.write_text(json.dumps(pert))

    ft = FakeTransport()
    submitted = {}

    def create(call):
        submitted["requests"] = call["json"]["requests"]
        return 200, {"id": "b1", "processing_status": "in_progress"}

    def results(_call):
        lines = []
        for req in submitted["requests"]:
            lines.append(json.dumps({
                "custom_id": req["custom_id"],
                "result": {"type": "succeeded", "message": {
                    "content": [{"type": "text", "text": "65"}]}},
            }))
        return 200, "\n".join(lines).encode()

    ft.add("POST", "/messages/batches", create)
    ft.add("GET", "/messages/batches/b1/results", results)
    ft.add("GET", "/messages/batches/b1",
           lambda c: (200, {"id": "b1", "processing_status": "ended"}))
    monkeypatch.setattr(ac_mod, "UrllibTransport", lambda: ft)
    monkeypatch.setenv("ANTHROPIC_API_KEY", "test-key")

    out = tmp_path / "claude_results.xlsx"
    main(["run-claude-perturbation", "--perturbations", str(pert_path),
          "--output", str(out)])
    df = read_xlsx(str(out))
    assert list(df.columns) == CLAUDE_PERTURBATION_COLUMNS
    assert len(df) == 10
    conf = pd.to_numeric(df["Confidence Value"], errors="coerce")
    assert (conf == 65).all()


def test_gemini_perturbation_cli_threaded_sync(tmp_path, monkeypatch, capsys):
    """run-gemini-perturbation via the CLI against a faked sync API with
    logprobs — binary + confidence legs per rephrasing, threaded."""
    import math

    from llm_interpretation_replication_tpu.api_backends import (
        gemini_client as gc_mod,
    )
    from llm_interpretation_replication_tpu.api_backends.transport import (
        FakeTransport,
    )
    from llm_interpretation_replication_tpu.sweeps.writers import (
        PERTURBATION_COLUMNS,
    )

    scenarios = legal_scenarios()
    pert = [
        {**s, "rephrasings": [f"V1: {s['original_main'][:60]}"]}
        for s in scenarios
    ]
    pert_path = tmp_path / "perturbations.json"
    pert_path.write_text(json.dumps(pert))

    ft = FakeTransport()

    def handler(call):
        content = call["json"]["contents"][0]["parts"][0]["text"]
        scenario = next(s for s in scenarios
                        if s["confidence_format"] in content
                        or s["response_format"] in content)
        t1 = scenario["target_tokens"][0]
        text = "55" if scenario["confidence_format"] in content else t1
        return 200, {"candidates": [{
            "content": {"parts": [{"text": text}]},
            "logprobsResult": {"topCandidates": [{"candidates": [
                {"token": text, "logProbability": math.log(0.8)},
            ]}]},
        }]}

    ft.add("POST", ":generateContent", handler)
    monkeypatch.setattr(gc_mod, "UrllibTransport", lambda: ft)
    monkeypatch.setenv("GEMINI_API_KEY", "test-key")

    out = tmp_path / "gemini_results.xlsx"
    main(["run-gemini-perturbation", "--perturbations", str(pert_path),
          "--output", str(out), "--threads", "2"])
    df = read_xlsx(str(out))
    assert list(df.columns) == PERTURBATION_COLUMNS
    assert len(df) == 5
    t1 = pd.to_numeric(df["Token_1_Prob"], errors="coerce")
    assert t1.notna().all() and (t1 > 0.7).all()


@pytest.mark.skipif(
    not os.path.exists("/root/reference/data/word_meaning_survey_results.csv"),
    reason="reference not mounted")
def test_closed_source_cli_full_evaluation(tmp_path, monkeypatch, capsys):
    """run-closed-source end-to-end via the CLI against all three faked
    vendor APIs on the real 100-question inputs: cache, per-vendor
    evaluators, baselines, MAE tables, figures."""
    import math
    import time

    from llm_interpretation_replication_tpu.api_backends import (
        anthropic_client as ac_mod,
        gemini_client as gc_mod,
        openai_client as oc_mod,
    )
    from llm_interpretation_replication_tpu.api_backends.transport import (
        FakeTransport,
    )

    ft = FakeTransport()

    def openai_handler(call):
        content = call["json"]["messages"][0]["content"]
        conf = "confident" in content or "0 and 100" in content
        text = "80" if conf else "Yes"
        top = ([{"token": "80", "logprob": math.log(0.6)},
                {"token": "90", "logprob": math.log(0.2)}] if conf else
               [{"token": "Yes", "logprob": math.log(0.7)},
                {"token": "No", "logprob": math.log(0.2)}])
        return 200, {"choices": [{"message": {"content": text},
                                  "logprobs": {"content": [{"top_logprobs": top}]}}]}

    def gemini_handler(call):
        content = call["json"]["contents"][0]["parts"][0]["text"]
        conf = "confident" in content or "0 and 100" in content
        text = "70" if conf else "No"
        cands = [{"token": text, "logProbability": math.log(0.8)}]
        return 200, {"candidates": [{
            "content": {"parts": [{"text": text}]},
            "logprobsResult": {"topCandidates": [{"candidates": cands}]},
        }]}

    def claude_handler(call):
        content = call["json"]["messages"][0]["content"]
        conf = "confident" in content or "0 and 100" in content
        return 200, {"content": [{"type": "text",
                                  "text": "60" if conf else "Yes"}]}

    ft.add("POST", "/chat/completions", openai_handler)
    ft.add("POST", ":generateContent", gemini_handler)
    ft.add("POST", "/messages", claude_handler)
    for mod in (oc_mod, gc_mod, ac_mod):
        monkeypatch.setattr(mod, "UrllibTransport", lambda: ft)
    for var in ("OPENAI_API_KEY", "GEMINI_API_KEY", "ANTHROPIC_API_KEY"):
        monkeypatch.setenv(var, "test-key")
    monkeypatch.setattr(time, "sleep", lambda _s: None)

    out = tmp_path / "closed"
    main([
        "run-closed-source",
        "--questions-csv", "/root/reference/data/instruct_model_comparison_results.csv",
        "--survey2-csv", "/root/reference/data/word_meaning_survey_results_part_2.csv",
        "--survey1-csv", "/root/reference/data/word_meaning_survey_results.csv",
        "--output-dir", str(out), "--yes",
    ])
    df = pd.read_csv(out / "closed_source_evaluation_results.csv")
    assert len(df) == 100
    assert {"gpt_relative_prob", "gemini_relative_prob", "claude_response",
            "random_relative_prob"} <= set(df.columns)
    assert df["gpt_relative_prob"].between(0, 1).all()
    assert (out / "api_cache.json").exists()
    assert (out / "mae_results_tables.tex").exists()
    # re-run short-circuits to the saved CSV (no new API calls)
    calls_before = len(ft.calls)
    main([
        "run-closed-source",
        "--questions-csv", "/root/reference/data/instruct_model_comparison_results.csv",
        "--survey2-csv", "/root/reference/data/word_meaning_survey_results_part_2.csv",
        "--survey1-csv", "/root/reference/data/word_meaning_survey_results.csv",
        "--output-dir", str(out), "--yes",
    ])
    assert len(ft.calls) == calls_before


@pytest.mark.skipif(
    not os.path.exists("/root/reference/data/word_meaning_survey_results.csv"),
    reason="reference not mounted",
)
def test_verify_replication_snapshots_dress_rehearsal(snapshot, tmp_path, capsys):
    """The snapshot-mode chain end-to-end: ``verify-replication --snapshots``
    drives run_snapshot_sweep (a REAL run-100q through the engine from tiny
    disk checkpoints) -> check_table5 -> PASS/FAIL verdict rows — the one
    chain (analysis/replication.py run_snapshot_sweep -> check_table5) that
    recorded-artifact mode never executes, so the day real 7B snapshots
    appear the command works first try (main.tex:432-446)."""
    import shutil

    from llm_interpretation_replication_tpu.sweeps import (
        base_vs_instruct_100q as sweep_mod,
    )

    from llm_interpretation_replication_tpu.survey import mae_100q

    instruct_snap = str(tmp_path / "snap_instruct")
    shutil.copytree(snapshot, instruct_snap)
    # one Table-5 family so check_table5 finds it by name — both the sweep
    # roster AND the Table-5 family map must name these snapshots (with real
    # checkpoints both key on the same HF ids, e.g. tiiuae/falcon-7b); the
    # other two families report FAIL/no-computed-value, which
    # (deterministically) makes the verifier exit nonzero regardless of how
    # the random-weight MAEs land
    pairs = [{"base": snapshot, "instruct": instruct_snap, "family": "Falcon"}]
    out = tmp_path / "verify_out"
    orig = sweep_mod.model_pairs_100q
    orig_fams = mae_100q.MODEL_FAMILIES
    sweep_mod.model_pairs_100q = lambda: pairs
    mae_100q.MODEL_FAMILIES = {
        "Falcon": {"base": snapshot, "instruct": instruct_snap}}
    try:
        with pytest.raises(SystemExit):
            main([
                "verify-replication", "--device", "cpu", "--dtype", "float32",
                "--batch-size", "8", "--snapshots", str(tmp_path),
                "--output-dir", str(out),
                "--bootstrap", "500", "--cross-prompt-bootstrap", "30",
                "--output-json", str(out / "verdicts.json"),
            ])
    finally:
        sweep_mod.model_pairs_100q = orig
        mae_100q.MODEL_FAMILIES = orig_fams

    # the snapshot sweep really ran: 100 questions x 2 legs through the engine
    csv = out / "base_vs_instruct_100q_results.csv"
    assert csv.exists()
    df = pd.read_csv(csv)
    assert len(df) == 200
    assert set(df["base_or_instruct"]) == {"base", "instruct"}

    # ...and its output reached the Table-5 judge: Falcon rows carry real
    # computed numbers with verdicts, not SKIPs
    verdicts = json.load(open(out / "verdicts.json"))
    t5 = {c["metric"]: c for c in verdicts["checks"] if c["table"] == "Table 5"}
    for metric in ("Falcon base MAE", "Falcon instruct MAE", "Falcon diff"):
        row = t5[metric]
        assert row["verdict"] in ("PASS", "FAIL")
        assert row["computed"] is not None and np.isfinite(row["computed"])
    assert t5["Falcon diff"]["computed_ci"] is not None
    # families absent from the sweep stay judged (FAIL), never silently SKIP
    assert t5["StableLM base->instruct"]["verdict"] == "FAIL"
    report = capsys.readouterr().out
    assert "Table 5" in report and "Falcon base MAE" in report
