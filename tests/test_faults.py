"""Fault-injection matrix for the runtime fault-tolerance layer.

The north-star sweeps run for hours on shared/preemptible TPU slices where
co-tenant RESOURCE_EXHAUSTED and SIGTERM preemption are routine, so every
recovery path in ``runtime/faults.py`` is pinned here against a tiny CPU
model and the deterministic fake engine, via the ``utils.testing``
fault-injection harness (:class:`FaultyEngine`):

- OOM at batch launch / mid-chunk → the engine re-buckets the failed batch
  down the ladder and completes without losing or duplicating a row
- SIGTERM mid-sweep → the PreemptionGuard flushes checkpoint state and the
  resumed sweep loses at most the in-flight chunk / model
- transient RPC error → retried in place with backoff, then success
- NaN logits → rows still land, the event is recorded in telemetry

All tests are CPU-only and fast; the ``faults`` marker keeps them
selectable (``-m faults``) and they run inside the tier-1 ``-m 'not slow'``
PR gate.
"""

import os
import signal
import threading
import time

import numpy as np
import pytest

from llm_interpretation_replication_tpu.runtime import batching
from llm_interpretation_replication_tpu.runtime.faults import (
    MEASURED_SWEEP_LADDER,
    Preempted,
    PreemptionGuard,
    TransientError,
    is_oom,
    is_transient,
    next_batch_down,
    oom_detail,
    retry_transient,
)
from llm_interpretation_replication_tpu.sweeps import (
    run_instruct_sweep,
    run_model_perturbation_sweep,
    run_sweep,
)
from llm_interpretation_replication_tpu.utils import telemetry
from llm_interpretation_replication_tpu.utils.retry import RetryPolicy
from llm_interpretation_replication_tpu.utils.testing import (
    Fault,
    FaultyEngine,
    injected_oom_error,
)

from test_sweeps import FakeEngine

pytestmark = pytest.mark.faults

#: retry policy with sub-millisecond sleeps so the matrix stays fast
FAST_RETRY = RetryPolicy(max_retries=3, initial_delay=0.001, max_delay=0.002)


@pytest.fixture(autouse=True)
def _clean_fault_log():
    telemetry.clear_fault_events()
    yield
    telemetry.clear_fault_events()


def _scenarios(n_scenarios=2, rephrasings=6):
    return [
        {
            "original_main": f"Is thing {s} a stuff?",
            "response_format": "Answer only 'Yes' or 'No'.",
            "confidence_format": "How confident are you (0-100)?",
            "target_tokens": ["Yes", "No"],
            "rephrasings": [f"Is thing {s} variant {i} a stuff?"
                            for i in range(rephrasings)],
        }
        for s in range(n_scenarios)
    ]


def _row_keys(df):
    return list(zip(df["Model"], df["Original Main Part"],
                    df["Rephrased Main Part"]))


# ---------------------------------------------------------------------------
# Classification + ladder unit behavior
# ---------------------------------------------------------------------------

class TestClassification:
    def test_is_oom_matches_every_spelling(self):
        for s in ("RESOURCE_EXHAUSTED: TPU backend error",
                  "jax.errors.JaxRuntimeError: ResourceExhausted",
                  "Resource exhausted: Out of memory allocating 1 bytes"):
            assert is_oom(RuntimeError(s)), s
        assert not is_oom(ValueError("shape mismatch"))
        assert is_oom(injected_oom_error())

    def test_oom_detail_truncates_and_flattens(self):
        err = RuntimeError("RESOURCE_EXHAUSTED:\n  " + "x" * 400)
        detail = oom_detail(err)
        assert len(detail) <= 163 and detail.endswith("...")
        assert "\n" not in detail

    def test_is_transient_excludes_oom_and_bugs(self):
        assert is_transient(TransientError("injected"))
        assert is_transient(ConnectionError("reset"))
        assert is_transient(RuntimeError("UNAVAILABLE: channel dropped"))
        assert not is_transient(injected_oom_error())
        assert not is_transient(ValueError("shape mismatch"))

    def test_next_batch_down_walks_measured_ladder(self):
        assert next_batch_down(384, MEASURED_SWEEP_LADDER, floor=256) == 320
        assert next_batch_down(352, MEASURED_SWEEP_LADDER, floor=256) == 320
        assert next_batch_down(320, MEASURED_SWEEP_LADDER, floor=256) == 256
        assert next_batch_down(256, MEASURED_SWEEP_LADDER, floor=256) is None

    def test_next_batch_down_halves_without_ladder(self):
        assert next_batch_down(8) == 4
        assert next_batch_down(4, floor=3) == 3
        assert next_batch_down(1) is None

    def test_next_batch_down_floor_zero_never_yields_batch_zero(self):
        # LLM_INTERP_OOM_FLOOR=0 ("no floor") clamps to 1: batch 0 is
        # unlaunchable and would crash mid-OOM-recovery
        assert next_batch_down(2, floor=0) == 1
        assert next_batch_down(1, floor=0) is None

    def test_env_knobs(self, monkeypatch):
        from llm_interpretation_replication_tpu.runtime import faults

        monkeypatch.setenv("LLM_INTERP_OOM_BACKOFF", "0")
        monkeypatch.setenv("LLM_INTERP_OOM_FLOOR", "16")
        monkeypatch.setenv("LLM_INTERP_OOM_LADDER", "320,256")
        assert faults.default_engine_backoff() is False
        assert faults.default_engine_floor() == 16
        assert faults.default_engine_ladder() == (320, 256)


# ---------------------------------------------------------------------------
# Transient retry
# ---------------------------------------------------------------------------

class TestRetryTransient:
    def test_retry_then_success(self):
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise TransientError("injected hiccup")
            return "ok"

        assert retry_transient(flaky, FAST_RETRY, label="t")() == "ok"
        assert calls["n"] == 3
        assert len(telemetry.fault_events("transient_retry")) == 2

    def test_non_transient_propagates_immediately(self):
        calls = {"n": 0}

        def bug():
            calls["n"] += 1
            raise ValueError("shape mismatch")

        with pytest.raises(ValueError):
            retry_transient(bug, FAST_RETRY)()
        assert calls["n"] == 1

    def test_exhausted_retries_record_only_actual_retries(self):
        def always():
            raise TransientError("injected hiccup")

        with pytest.raises(TransientError):
            retry_transient(always, FAST_RETRY)()
        # the final, propagating failure is not a retry and must not be
        # logged as one — the audit trail counts what actually happened
        events = telemetry.fault_events("transient_retry")
        assert len(events) == FAST_RETRY.max_retries

    def test_oom_is_never_retried_in_place(self):
        calls = {"n": 0}

        def oom():
            calls["n"] += 1
            raise injected_oom_error()

        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            retry_transient(oom, FAST_RETRY)()
        assert calls["n"] == 1  # the batch ladder owns OOM, not the retry


# ---------------------------------------------------------------------------
# Preemption guard
# ---------------------------------------------------------------------------

class TestPreemptionGuard:
    def test_sigterm_flushes_then_exits_with_143(self):
        before = signal.getsignal(signal.SIGTERM)
        flushed = []
        with pytest.raises(Preempted) as excinfo:
            with PreemptionGuard(lambda: flushed.append(1), label="t"):
                os.kill(os.getpid(), signal.SIGTERM)
                time.sleep(1)  # handler raises out of here at the latest
        assert flushed == [1]
        assert excinfo.value.code == 128 + signal.SIGTERM
        assert signal.getsignal(signal.SIGTERM) is before  # restored
        assert telemetry.fault_events("preempted")

    def test_sigint_raises_keyboardinterrupt(self):
        flushed = []
        with pytest.raises(KeyboardInterrupt):
            with PreemptionGuard(lambda: flushed.append(1)):
                os.kill(os.getpid(), signal.SIGINT)
                time.sleep(1)
        assert flushed == [1]

    def test_failing_flush_does_not_block_the_next(self, capsys):
        order = []

        def bad():
            order.append("bad")
            raise OSError("disk full")

        guard = PreemptionGuard(bad, lambda: order.append("good"))
        guard.flush(reason="test")
        assert order == ["bad", "good"]
        assert "flush failed" in capsys.readouterr().err

    def test_non_main_thread_degrades_to_noop(self):
        result = {}

        def worker():
            with PreemptionGuard(lambda: None) as guard:
                result["active"] = guard.active

        t = threading.Thread(target=worker)
        t.start()
        t.join()
        assert result["active"] is False


# ---------------------------------------------------------------------------
# Engine back-off mechanics (no model needed)
# ---------------------------------------------------------------------------

class _PadTok:
    pad_token_id = 0


def _bare_engine(**ecfg_kw):
    from llm_interpretation_replication_tpu.runtime.engine import (
        EngineConfig,
        ScoringEngine,
    )

    ecfg = EngineConfig(batch_size=4, buckets=(8, 16), **ecfg_kw)
    return ScoringEngine(None, None, None, _PadTok(), engine_config=ecfg)


class TestEngineBackoffMechanics:
    ENCODED = [[1] * 5 for _ in range(8)]

    def _batches(self, eng):
        return list(batching.batches_for_prompts(
            self.ENCODED, eng.ecfg.batch_size, eng.ecfg.buckets, pad_id=0,
            length_sorted=True))

    def test_rebatch_remaps_indices_exactly_once(self):
        batches = self._batches(_bare_engine())
        original = sorted(int(i) for i in batches[0].indices if i >= 0)
        subs = batching.rebatch(batches[0], self.ENCODED, 2, buckets=(8, 16))
        covered = sorted(int(i) for b in subs for i in b.indices if i >= 0)
        assert covered == original        # no row lost, none duplicated
        assert all(b.token_ids.shape[0] == 2 for b in subs)

    @pytest.mark.parametrize("fail_side", ["launch", "consume"])
    def test_oom_steps_down_and_covers_every_row(self, fail_side):
        eng = _bare_engine(oom_backoff=True, oom_batch_floor=1)
        state = {"launches": 0, "failed": False}
        consumed = []

        def launch(batch):
            state["launches"] += 1
            if fail_side == "launch" and not state["failed"]:
                state["failed"] = True
                raise injected_oom_error()
            return batch

        def consume(batch, out):
            if fail_side == "consume" and not state["failed"]:
                state["failed"] = True
                raise injected_oom_error()
            consumed.extend(int(i) for i in batch.indices if i >= 0)

        eng._run_pipelined(self._batches(eng), launch, consume,
                           rebatch=eng._oom_rebatch(self.ENCODED))
        assert sorted(consumed) == list(range(8))
        assert state["launches"] > 2      # the failed batch relaunched smaller
        assert [e["kind"] for e in eng.fault_events] == ["engine_oom_backoff"]
        assert telemetry.fault_events("engine_oom_backoff")

    def test_oom_at_floor_propagates(self):
        eng = _bare_engine(oom_backoff=True, oom_batch_floor=4)

        def launch(batch):
            raise injected_oom_error()

        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            eng._run_pipelined(self._batches(eng), launch, lambda b, o: None,
                               rebatch=eng._oom_rebatch(self.ENCODED))

    def test_backoff_disabled_propagates(self):
        eng = _bare_engine(oom_backoff=False)
        assert eng._oom_rebatch(self.ENCODED) is None

    def test_non_oom_errors_propagate(self):
        eng = _bare_engine(oom_backoff=True, oom_batch_floor=1)

        def launch(batch):
            raise ValueError("shape mismatch")

        with pytest.raises(ValueError):
            eng._run_pipelined(self._batches(eng), launch, lambda b, o: None,
                               rebatch=eng._oom_rebatch(self.ENCODED))

    def test_faulty_engine_hook_detaches_after_each_call(self):
        """Discarding a FaultyEngine must leave the wrapped engine clean:
        the batch hook shadows ``_run_pipelined`` only for the duration of
        the wrapper's own calls, so a stale unfired ``at_batch`` fault can
        never ambush a later direct use of the engine."""
        eng = _bare_engine(oom_backoff=True, oom_batch_floor=1)
        faulty = FaultyEngine(eng, [Fault("oom", at_batch=5)])  # never fires
        assert "_run_pipelined" not in eng.__dict__
        with faulty._batch_hook():
            assert "_run_pipelined" in eng.__dict__
        assert "_run_pipelined" not in eng.__dict__

    def test_marked_pool_oom_bypasses_rebatch(self):
        """An OOM flagged ``_no_rebatch`` (a phase-2 pooled decode spanning
        rows from many batches) must propagate: stepping down the batch
        that triggered the pool flush cannot shrink the pooled program."""
        eng = _bare_engine(oom_backoff=True, oom_batch_floor=1)

        def consume(batch, out):
            err = injected_oom_error()
            err._no_rebatch = True
            raise err

        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            eng._run_pipelined(self._batches(eng), lambda b: b, consume,
                               rebatch=eng._oom_rebatch(self.ENCODED))


# ---------------------------------------------------------------------------
# Int8 KV cache + chunked prefill fault rows (ISSUE 5): OOM mid-chunked-
# prefill must release PrefixCachePool entries before the ladder retry (no
# double-free, no orphan), and int8-KV sweeps re-bucket down the SAME
# measured ladder as bf16
# ---------------------------------------------------------------------------


class TestChunkedPrefillFaults:
    def _fused_engine(self, **ecfg_kw):
        import dataclasses as dc

        from test_runtime import _tiny_engine

        from llm_interpretation_replication_tpu.runtime.engine import (
            ScoringEngine,
        )

        eng, _, tok = _tiny_engine(batch_size=4)
        ecfg = dc.replace(eng.ecfg, oom_backoff=True, oom_batch_floor=1,
                          **ecfg_kw)
        return ScoringEngine(eng.family, eng.cfg, eng.params, tok,
                             engine_config=ecfg)

    def _pairs(self, n=6):
        return [(f"Is thing number {i} considered a kind of stuff?",
                 (" Answer Yes or No.", " How confident, 0-100?"))
                for i in range(n)]

    def _legs(self):
        from llm_interpretation_replication_tpu.runtime.engine import LegSpec

        return [LegSpec("binary"),
                LegSpec("confidence", with_confidence=True,
                        max_new_tokens=10)]

    @pytest.mark.parametrize("fail_call", [1, 2])
    def test_oom_mid_chunked_prefill_releases_pool_before_retry(
            self, monkeypatch, fail_call):
        """A fused batch with chunked prefix prefill calls extend_prefill
        for the chunk replay FIRST (before the pool entry exists) and for
        each suffix leg AFTER acquire.  An injected OOM at either point
        must re-bucket down the ladder with the entry released exactly
        once: retried sub-batches acquire fresh entries, nothing is
        orphaned or double-freed, and every row completes."""
        from llm_interpretation_replication_tpu.models import decoder as dmod

        eng = self._fused_engine(prefill_chunk=16, kv_dtype="int8")
        real = dmod.extend_prefill
        state = {"calls": 0}

        def failing(*a, **kw):
            state["calls"] += 1
            if state["calls"] == fail_call:
                raise injected_oom_error()
            return real(*a, **kw)

        # chunked_prefill and the engine's suffix legs both resolve
        # extend_prefill off the decoder module at call time
        monkeypatch.setattr(dmod, "extend_prefill", failing)
        outs = eng.score_prefixed(self._pairs(), legs=self._legs())
        pool = eng.last_prefix_pool
        assert pool.consistent, (pool.acquired, pool.released, pool.leaked)
        assert pool.leaked == 0
        assert len(outs) == 2
        assert all(r["success"] for rows in outs for r in rows)
        assert [e["kind"] for e in eng.fault_events] == ["engine_oom_backoff"]
        assert eng.fault_events[0]["new_batch"] < eng.fault_events[0]["batch"]

    def test_int8_kv_sweep_rebuckets_down_measured_ladder(self):
        """An int8-KV engine walks the SAME back-off machinery as bf16: an
        injected device OOM at the first batch launch re-buckets the rows
        at the configured ladder step and the sweep completes with every
        row scored (none lost, none duplicated)."""
        eng = self._fused_engine(kv_dtype="int8", oom_batch_ladder=(2,))
        faulty = FaultyEngine(eng, [Fault("oom", at_batch=1)])
        prompts = [f"Is item {i} a vehicle of some sort?" for i in range(6)]
        rows = faulty.score_prompts(prompts)
        assert len(rows) == 6 and all(r["success"] for r in rows)
        assert faulty.injected == [{"kind": "oom", "at_call": 0,
                                    "at_batch": 1}]
        events = telemetry.fault_events("engine_oom_backoff")
        assert events and events[0]["new_batch"] == 2
        # int8 KV held through the retry: the re-bucketed batches still
        # produced a quantized cache (bytes-saved telemetry is monotone)
        assert telemetry.counter("kv_cache_bytes_saved") > 0


# ---------------------------------------------------------------------------
# Perturbation sweep fault matrix (fake engine: 2 scenarios x 6 rephrasings,
# score_chunk=4 -> 3 chunks, confidence off -> 2 engine calls per chunk)
# ---------------------------------------------------------------------------

def _run_perturbation(tmp_path, engine, name="fake/model-7b", **kw):
    kw.setdefault("checkpoint_every", 100)
    kw.setdefault("confidence", False)
    kw.setdefault("score_chunk", 4)
    kw.setdefault("retry_policy", FAST_RETRY)
    return run_model_perturbation_sweep(
        engine, name, _scenarios(), str(tmp_path / "out.xlsx"), **kw)


class TestPerturbationFaultMatrix:
    def test_transient_error_retries_then_succeeds(self, tmp_path):
        faulty = FaultyEngine(FakeEngine("fake/model-7b"),
                              [Fault("transient", at_call=1)])
        df = _run_perturbation(tmp_path, faulty)
        clean = _run_perturbation(tmp_path / "clean", FakeEngine("fake/model-7b"))
        assert len(df) == 12
        assert sorted(_row_keys(df)) == sorted(_row_keys(clean))
        np.testing.assert_allclose(
            df.sort_values("Rephrased Main Part")["Token_1_Prob"].values,
            clean.sort_values("Rephrased Main Part")["Token_1_Prob"].values)
        assert faulty.injected == [{"kind": "transient", "at_call": 1,
                                    "at_batch": 0}]
        assert telemetry.fault_events("transient_retry")

    def test_nan_logits_recorded_not_silent(self, tmp_path):
        # call 2 is chunk 1's first_token leg: its 4 rows go NaN
        faulty = FaultyEngine(FakeEngine("fake/model-7b"),
                              [Fault("nan", at_call=2)])
        df = _run_perturbation(tmp_path, faulty)
        assert len(df) == 12
        assert len(set(_row_keys(df))) == 12
        assert int(np.isnan(df["Token_1_Prob"].astype(float)).sum()) == 4
        events = telemetry.fault_events("nan_logits")
        assert len(events) == 1 and events[0]["rows"] == 4

    def test_sigterm_mid_sweep_resumes_losing_at_most_one_chunk(self, tmp_path):
        """Acceptance: a 10k-style sweep interrupted by injected SIGTERM
        resumes losing <= the in-flight score_chunk."""
        from llm_interpretation_replication_tpu.sweeps.perturbation import (
            load_existing_rows,
        )

        # call 3 = chunk 2's binary leg: chunk 1 done (4 rows pending,
        # checkpoint_every=100 so unflushed), chunk 2 in flight
        faulty = FaultyEngine(FakeEngine("fake/model-7b"),
                              [Fault("preempt", at_call=3)])
        with pytest.raises(Preempted):
            _run_perturbation(tmp_path, faulty)
        # the guard flushed the pending rows inside the grace window
        rows, keys = load_existing_rows(str(tmp_path / "out.xlsx"))
        assert len(rows) == 4             # every completed chunk, no more
        assert telemetry.fault_events("preempted")

        # resume: only the 2 unfinished chunks are rescored, and the final
        # workbook carries every (model, scenario, rephrasing) exactly once
        clean = FakeEngine("fake/model-7b")
        resumed = FaultyEngine(clean, [])
        df = _run_perturbation(tmp_path, resumed)
        assert resumed.calls == 4         # 2 chunks x (binary + first_token)
        assert len(df) == 12
        assert len(set(_row_keys(df))) == 12
        assert not os.path.exists(str(tmp_path / "out.xlsx") + ".rows.jsonl")

    def test_torn_sidelog_line_is_skipped_on_resume(self, tmp_path):
        """A hard kill mid-append can leave a torn trailing JSONL line;
        resume must skip it (re-scoring its chunk) instead of crashing."""
        from llm_interpretation_replication_tpu.sweeps.perturbation import (
            load_existing_rows,
        )

        out = tmp_path / "out.xlsx"
        sidelog = str(out) + ".rows.jsonl"
        good = {"Model": "m", "Original Main Part": "o",
                "Rephrased Main Part": "r", "Token_1_Prob": 0.5}
        with open(sidelog, "w") as f:
            f.write(__import__("json").dumps(good) + "\n")
            f.write('{"Model": "m", "Original Main Part": "o", "Reph')
        rows, keys = load_existing_rows(str(out))
        assert len(rows) == 1
        assert keys == {("m", "o", "r")}


# ---------------------------------------------------------------------------
# Perturbation sweep on the real tiny engine: injected device OOM at batch
# granularity steps the batch down inside the engine and the sweep completes
# with every row intact (acceptance criterion)
# ---------------------------------------------------------------------------

class TestPerturbationEngineOOM:
    @pytest.mark.parametrize("at_batch", [1, 2])  # launch + mid-chunk
    def test_injected_oom_completes_at_stepped_down_batch(self, tmp_path,
                                                          at_batch):
        import dataclasses as dc

        from test_runtime import _tiny_engine

        eng, _, _ = _tiny_engine(batch_size=4)
        eng.ecfg = dc.replace(eng.ecfg, oom_backoff=True, oom_batch_floor=1,
                              oom_batch_ladder=())
        faulty = FaultyEngine(eng, [Fault("oom", at_batch=at_batch)])
        df = _run_perturbation(tmp_path, faulty, score_chunk=12)

        clean_eng, _, _ = _tiny_engine(batch_size=4)
        clean = _run_perturbation(tmp_path / "clean", clean_eng)

        # no row lost, none duplicated, values identical to the clean run
        assert len(df) == 12
        assert sorted(_row_keys(df)) == sorted(_row_keys(clean))
        merged = df.merge(clean, on="Rephrased Main Part", suffixes=("", "_c"))
        np.testing.assert_allclose(merged["Token_1_Prob"].astype(float),
                                   merged["Token_1_Prob_c"].astype(float),
                                   atol=1e-5)
        # the degraded batch is on the audit trail
        assert any(e["kind"] == "engine_oom_backoff" for e in eng.fault_events)
        event = telemetry.fault_events("engine_oom_backoff")[0]
        assert event["new_batch"] < event["batch"]
        assert faulty.injected == [{"kind": "oom", "at_call": 0,
                                    "at_batch": at_batch}]


# ---------------------------------------------------------------------------
# Serve-path fault matrix: the continuous-batching scheduler (serve/) over
# the real tiny engine, injected through FaultyEngine.serve_scheduler —
# OOM splits re-enter the QUEUE down the PR-1 ladder (never the engine's
# in-place retry), transients retry in place, floor OOMs fail TYPED.
# ---------------------------------------------------------------------------


class TestServeSchedulerFaults:
    def _serve(self, faulty, prompts, config=None):
        from llm_interpretation_replication_tpu.serve import (
            ScoreRequest,
            SchedulerConfig,
        )

        cfg = config or SchedulerConfig(max_wait_s=0.01,
                                        retry_policy=FAST_RETRY)
        with faulty.serve_scheduler(cfg) as sched:
            futures = [sched.submit(ScoreRequest(prompt=p))
                       for p in prompts]
            return [f.result(timeout=300) for f in futures]

    def test_micro_batch_oom_mid_queue_splits_and_completes(self):
        """A micro-batch whose device launch OOMs mid-queue is split down
        the ladder and re-queued at a stepped-down engine batch; every
        request still resolves, values match a clean run."""
        from test_runtime import _tiny_engine

        eng, _, _ = _tiny_engine(batch_size=4)
        prompts = [f"Is thing {i} a stuff?" for i in range(6)]
        clean = eng.score_prompts(prompts)
        faulty = FaultyEngine(eng, [Fault("oom", at_call=1)])
        snap = telemetry.counters()
        rows = self._serve(faulty, prompts)
        delta = telemetry.counters_since(snap)
        assert all(r["success"] for r in rows)
        assert faulty.calls >= 2                 # the split re-launched
        assert delta["serve_oom_splits"] >= 1
        events = telemetry.fault_events("serve_oom_split")
        assert events and events[0]["new_batch"] < events[0]["batch"]
        np.testing.assert_allclose(
            [r["relative_prob"] for r in rows],
            [r["relative_prob"] for r in clean], rtol=2e-5)

    def test_transient_retried_in_place_on_serve_path(self):
        from test_runtime import _tiny_engine

        eng, _, _ = _tiny_engine(batch_size=4)
        prompts = [f"Is item {i} a thing?" for i in range(3)]
        faulty = FaultyEngine(eng, [Fault("transient", at_call=1)])
        rows = self._serve(faulty, prompts)
        assert all(r["success"] for r in rows)
        assert faulty.calls == 2                 # one retry, in place
        assert telemetry.fault_events("transient_retry")

    def test_oom_at_floor_fails_requests_with_the_original_error(self):
        """At the ladder floor the scheduler stops splitting: every
        request in the micro-batch gets the ORIGINAL device error on its
        future — a typed answer, not a hang or a silent drop."""
        from llm_interpretation_replication_tpu.serve import (
            ScoreRequest,
            SchedulerConfig,
        )
        from test_runtime import _tiny_engine

        eng, _, _ = _tiny_engine(batch_size=4)
        faulty = FaultyEngine(eng, [Fault("oom", at_call=1)])
        snap = telemetry.counters()
        cfg = SchedulerConfig(max_wait_s=0.01, oom_floor=4,
                              retry_policy=FAST_RETRY)
        with faulty.serve_scheduler(cfg) as sched:
            futures = [sched.submit(ScoreRequest(prompt=f"q{i}"))
                       for i in range(4)]
            errs = [f.exception(timeout=300) for f in futures]
        assert all(is_oom(e) for e in errs)
        assert telemetry.counters_since(snap)["serve_failed"] == 4

    def test_split_for_requeue_walks_the_ladder(self):
        from llm_interpretation_replication_tpu.runtime.faults import (
            split_for_requeue,
        )

        assert split_for_requeue(10, 8) == (4, (4, 4, 2))
        assert split_for_requeue(4, 384, ladder=MEASURED_SWEEP_LADDER,
                                 floor=256) == (320, (4,))
        assert split_for_requeue(4, 1) is None            # at the floor
        assert split_for_requeue(4, 8, floor=8) is None


# ---------------------------------------------------------------------------
# Instruct sweep fault matrix
# ---------------------------------------------------------------------------

MODELS = ["fake/gamma-7b-instruct", "fake/delta-7b-chat", "fake/eps-7b-chat"]
QUESTIONS = [f'Is a "thing{i}" a "stuff{i}"?' for i in range(5)]


class TestInstructSweepFaults:
    def test_transient_error_retries_then_succeeds(self, tmp_path):
        engines = {}

        def factory(name):
            faults = ([Fault("transient", at_call=1)]
                      if name == MODELS[0] else [])
            engines[name] = FaultyEngine(FakeEngine(name), faults)
            return engines[name]

        df = run_instruct_sweep(
            factory, prompts=QUESTIONS, models=MODELS,
            checkpoint_path=str(tmp_path / "ck.json"),
            results_csv=str(tmp_path / "out.csv"),
            retry_policy=FAST_RETRY,
        )
        assert len(df) == len(MODELS) * len(QUESTIONS)
        # retried in place, not burned as MODEL_ERROR rows
        assert not df["model_output"].str.startswith("MODEL_ERROR").any()
        assert not df["yes_prob"].isna().any()
        assert engines[MODELS[0]].calls == 2
        assert telemetry.fault_events("transient_retry")

    def test_sigterm_mid_sweep_resumes_losing_one_model(self, tmp_path):
        def faulty_factory(name):
            faults = [Fault("preempt", at_call=1)] if name == MODELS[1] else []
            return FaultyEngine(FakeEngine(name), faults)

        ck = str(tmp_path / "ck.json")
        csv = str(tmp_path / "out.csv")
        with pytest.raises(Preempted):
            run_instruct_sweep(faulty_factory, prompts=QUESTIONS,
                               models=MODELS, checkpoint_path=ck,
                               results_csv=csv, retry_policy=FAST_RETRY)

        # the guard checkpointed the completed model before exiting
        factory_calls = []

        def factory(name):
            factory_calls.append(name)
            return FakeEngine(name)

        df = run_instruct_sweep(factory, prompts=QUESTIONS, models=MODELS,
                                checkpoint_path=ck, results_csv=csv)
        assert factory_calls == MODELS[1:]   # model 0 survived the SIGTERM
        assert len(df) == len(MODELS) * len(QUESTIONS)
        assert len(df.drop_duplicates(["model", "prompt"])) == len(df)


# ---------------------------------------------------------------------------
# 100q sweep fault matrix
# ---------------------------------------------------------------------------

PAIRS_100Q = [
    {"base": "fake/alpha-7b", "instruct": "fake/alpha-7b-instruct",
     "family": "Alpha"},
    {"base": "fake/beta-7b", "instruct": "fake/beta-7b-chat",
     "family": "Beta"},
]


class Test100qSweepFaults:
    def test_sigterm_mid_sweep_never_duplicates_rows(self, tmp_path):
        """Unlike the sibling sweeps, the 100q checkpoint keeps rows and the
        completion marker as SEPARATE state; the save_checkpoint filter must
        hold the invariant — rows exactly for completed models — no matter
        where in the loop the preemption flush fires, or the resumed sweep
        re-scores a model whose rows are already checkpointed and the CSV
        carries them twice."""
        import json as jsonlib

        names = [m for p in PAIRS_100Q for m in (p["base"], p["instruct"])]

        def faulty_factory(name):
            faults_ = [Fault("preempt", at_call=1)] if name == names[2] else []
            return FaultyEngine(FakeEngine(name), faults_)

        ck = str(tmp_path / "ck.json")
        csv = str(tmp_path / "out.csv")
        with pytest.raises(Preempted):
            run_sweep(faulty_factory, model_pairs=PAIRS_100Q,
                      prompts=QUESTIONS, checkpoint_path=ck, results_csv=csv)

        with open(ck) as f:
            state = jsonlib.load(f)
        assert state["completed_models"] == sorted(names[:2])
        # the invariant: checkpointed rows belong exactly to completed models
        assert {r["model"] for r in state["results"]} == set(names[:2])
        assert len(state["results"]) == 2 * len(QUESTIONS)

        df = run_sweep(lambda name: FakeEngine(name), model_pairs=PAIRS_100Q,
                       prompts=QUESTIONS, checkpoint_path=ck, results_csv=csv)
        assert len(df) == len(names) * len(QUESTIONS)
        assert len(df.drop_duplicates(["model", "prompt"])) == len(df)
        assert sorted(set(df["model"])) == sorted(names)
